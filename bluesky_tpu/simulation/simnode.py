"""The networked sim worker: Simulation wrapped in a network Node
(parity: bluesky/simulation/qtgl/simulation.py:204-287 event surface +
network/node.py loop).

Event surface (same tokens as the reference): STACKCMD, STEP, BATCH, QUIT,
GETSIMSTATE.  State changes are reported to the server via STATECHANGE so
the BATCH farm can schedule the next scenario piece on this worker when it
finishes (server.py:234-247 semantics).

OPT BATCH pieces (differentiable workloads, bluesky_tpu/diff/): a piece
whose scenario runs the OPT stack command blocks this loop for the
optimization's duration — the server's busy-PING budget
(hb_busy_multiplier) covers it exactly like a long first compile — then
sends its OPTRESULT upstream on this node's event socket and HOLDs, so
the piece's ``completed`` record follows the journaled ``opt_result``
on the FIFO pair.  The server never packs OPT pieces into world-batches
(the optimizer multi-starts on the world axis internally; see
network/server.py _piece_solo_reason).
"""
from .. import settings
from ..network import node as netnode
from ..network import detached
from .sim import Simulation, INIT, HOLD, OP, END
from .screenio import ScreenIO


def _make_simnode_class(base):
    class _SimNode(base):
        def __init__(self, event_port=None, stream_port=None, node_id=None,
                     **simkw):
            # watchdog knobs ride to the Node base, not the Simulation
            nodekw = {k: simkw.pop(k) for k in
                      ("watchdog_warn", "watchdog_kill") if k in simkw}
            super().__init__(
                event_port=event_port or settings.wevent_port,
                stream_port=stream_port or settings.wstream_port,
                node_id=node_id, **nodekw)
            self.sim = Simulation(**simkw)
            self.sim.scr = ScreenIO(self.sim, self)
            self.sim.node = self
            # Packed multi-world BATCH (simulation/worlds.py): the
            # server may dispatch a world-batch of compatible pieces as
            # ONE assignment; while it runs, step() drives the runner
            # instead of the main sim.  Construction kwargs are kept so
            # every world sim shares the worker's nmax bucket.
            self.worlds = None
            self._world_simkw = dict(simkw)
            # broker HA (network/ha.py): the solo BATCH piece currently
            # running, kept so a re-REGISTER after broker failover can
            # report it and the new leader ADOPTS it in place instead
            # of requeueing.  Packs are not reported (their per-world
            # completions already journaled; the rest requeues after
            # the adoption grace).
            self._batch_piece = None
            # Subsystems constructed before the swap hold the headless
            # Screen; repoint them at the streaming ScreenIO
            self.sim.areas.scr = self.sim.scr
            # BATCH stack command: upload the multi-SCEN scenario to
            # the server for farm-out (simulation.py:195-202)
            self.sim.batch = self.batch
            self.prev_state = self.sim.state_flag

        def batch(self, fname):
            ok, msg = self.sim.stack.openfile(fname)
            if not ok:
                return False, msg
            scentime = self.sim.stack.scentime
            scencmd = self.sim.stack.scencmd
            self.sim.stack.scentime, self.sim.stack.scencmd = [], []
            self.send_event(b"BATCH", {"scentime": scentime,
                                       "scencmd": scencmd})
            return True, "BATCH uploaded to the server"

        def close(self):
            self.sim.scr.close()      # deregister stream timers
            super().close()

        # ------------------------------------------------------ preemption
        def on_preempt_signal(self, signum):
            # SIGTERM from the scheduler: don't die mid-chunk — raise
            # the flag and let step() drain + checkpoint at the edge
            self.sim.request_preempt()

        def _preempt_shutdown(self):
            """Preemption-safe exit: the current chunk has drained
            (sim.step returns at chunk edges), so write the final
            checksummed checkpoint, tell the server (PREEMPTED — the
            in-flight BATCH piece is requeued WITHOUT a circuit-breaker
            strike; STATECHANGE -1 follows from the run() teardown)
            and leave cleanly."""
            sim = self.sim
            path, err = sim.handle_preempt()
            info = {"simt": sim.simt, "ntraf": sim.traf.ntraf}
            if path:
                info["checkpoint"] = path
            if err:
                info["error"] = err
            self.send_event(b"PREEMPTED", info)
            self._batch_piece = None
            sim.stop()
            self.quit()

        # ------------------------------------------------------ multi-world
        def _start_worlds(self, worlds_payload):
            """A packed BATCH assignment: run the worlds through the
            joint-dispatch WorldBatch runner.  Per-world completion is
            reported upstream as ``BATCHWORLD`` events the server
            journals per piece (exactly-once demux); per-world echo
            output streams with a ``[wNN]`` prefix."""
            from .worlds import WorldBatch
            self.sim.reset()
            pieces = [(p["scentime"], p["scencmd"])
                      for p in worlds_payload]
            self._batch_piece = None   # packs are not adoption-reported
            self.worlds = WorldBatch(
                pieces, simkw=self._world_simkw,
                host_tag=self.node_id.hex()[:8],
                on_world_done=lambda w, status, info=None:
                    self.send_event(b"BATCHWORLD",
                                    dict({"world": w, "status": status},
                                         **(info or {}))),
                on_echo=lambda w, text:
                    self.sim.scr.echo(f"[w{w:02d}] {text}"))
            self.prev_state = OP
            self.send_event(b"STATECHANGE", OP)

        def _finish_worlds(self):
            self.worlds = None
            self.prev_state = HOLD
            self.send_event(b"STATECHANGE", HOLD)

        def _preempt_worlds(self):
            """Preemption mid-pack: checkpoint every active world (one
            tagged file each), tell the server which worlds were
            already done (only the unfinished pieces requeue) and
            leave cleanly."""
            self.sim.preempt_requested = False
            info = self.worlds.handle_preempt()
            self.send_event(b"PREEMPTED", info)
            self.worlds = None
            self.sim.stop()
            self.quit()

        # --------------------------------------------------------- heartbeat
        def register_payload(self):
            """REGISTER payload: the in-flight solo BATCH piece, keyed
            by content (network/journal.py piece_key) — what lets the
            post-failover leader adopt this worker's running piece
            instead of requeueing a second copy (server._ha_adopt)."""
            if self._batch_piece is None:
                return None
            from ..network.journal import BatchJournal
            sim = self.sim
            return {"inflight": {
                "key": BatchJournal.piece_key(self._batch_piece),
                "simt": float(sim.simt_planned),
                "chunks": int(sim._step_count)}}

        def heartbeat_payload(self, stamp):
            """Progress piggybacked on the PONG reply: sim-time and
            chunks done let the server's straggler detector tell a
            stalled piece (fresh heartbeats, flat progress) from a
            long device chunk or first compile (no heartbeats at all —
            this loop is blocked, and the busy-PING budget applies)."""
            sim = self.sim
            if self.worlds is not None:
                # packed piece: aggregate progress — the slowest active
                # world's clock advances monotonically while the pack
                # runs, which is exactly the advance signal the
                # straggler detector needs
                info = dict({"stamp": stamp}, **self.worlds.progress())
                obs = self.worlds.obs_delta()
                if obs:
                    info["obs"] = obs
                # worst-case scan summary across the pack's worlds
                # (peaks max, minima min) — host dicts only, no device
                # reads, same contract as the single-sim branch below
                scans = [s._scan_last for s in self.worlds.sims
                         if s._scan_last is not None]
                if scans:
                    from ..obs import scanstats as _ss
                    info["scan"] = _ss.merge_summaries(scans)
                return info
            # "ff" gates the server's RATE-based hedging: sim-s/wall-s
            # is only comparable across workers running full speed — a
            # wall-clock-paced piece reports ~dtmult by design, which
            # must not read as "far below the fleet median".
            # planned clock: a device read here would block the event
            # loop on the in-flight pipelined chunk, turning "busy" into
            # "silent" for the server's straggler detector
            info = {"stamp": stamp, "simt": sim.simt_planned,
                    "chunks": sim._step_count,
                    "state": sim.state_flag, "ntraf": sim.traf.ntraf,
                    "ff": bool(sim.ffmode)}
            # mesh-epoch health rides the heartbeat so HEALTH can show
            # the fleet's shard state without a round-trip per worker
            if sim.shard_mode != "off" or sim.mesh_epoch > 0:
                info["mesh"] = sim.mesh_health()
            # in-scan telemetry summary (newest drained chunk): a host
            # dict stamped at the chunk edge — reading the device here
            # would block the loop exactly like the planned-clock note
            if sim.cfg.scanstats and sim._scan_last is not None:
                info["scan"] = sim._scan_last
            # SDC fingerprint chain summary: host ints stamped at each
            # drained chunk edge — same no-device-read contract; the
            # server records it per piece for hedge/vote comparison
            fp = sim.fp_summary()
            if fp is not None:
                info["fp"] = fp
            # fleet telemetry: ship the metric increments since the
            # last heartbeat; the server merges them into its fleet
            # registry (METRICS DUMP shows the aggregate)
            obs = sim.obs.delta()
            if obs:
                info["obs"] = obs
            return info

        # ------------------------------------------------------------ events
        def event(self, name, data, sender_route):
            sim = self.sim
            if name == b"STACKCMD":
                cmd = data["cmd"] if isinstance(data, dict) else str(data)
                # Reply route = REVERSED accumulated sender tail (see
                # network/server.py routing note); comma-joined hex so
                # the stack's plain-string sender survives multi-hop.
                sender = ",".join(f.hex() for f in reversed(sender_route)) \
                    if sender_route else ""
                sim.stack.stack(cmd, sender)
            elif name == b"STEP":
                # lockstep: advance exactly dtmult seconds of sim time
                # (possibly several quantized chunks), then ack
                sim.op()
                t_target = sim.simt_planned + sim.dtmult
                while sim.state_flag == OP \
                        and sim.simt_planned < t_target - 1e-9:
                    nsteps = max(1, int(round(
                        (t_target - sim.simt_planned) / sim.simdt)))
                    sim.step(max_chunk=nsteps)
                sim.pause()
                self.send_event(b"STEP", None,
                                list(reversed(sender_route)) or None)
            elif name == b"BATCH":
                if isinstance(data, dict) and data.get("worlds"):
                    self._start_worlds(data["worlds"])
                else:
                    sim.reset()
                    self._batch_piece = (data["scentime"],
                                         data["scencmd"])
                    sim.stack.set_scendata(data["scentime"],
                                           data["scencmd"])
                    sim.op()
            elif name == b"BATCHCANCEL":
                # the server hedged this piece and the other copy won:
                # ack FIRST (the FIFO event pair is how the server
                # tells a cancel ack from a duplicate completion), then
                # abandon the piece — the reset's STATECHANGE makes
                # this worker available again
                self.send_event(b"BATCHCANCELLED", None)
                self._batch_piece = None
                if self.worlds is not None:
                    self.worlds = None
                    self.prev_state = sim.state_flag
                    self.send_event(b"STATECHANGE", HOLD)
                sim.reset()
            elif name == b"BATCHREJECTED":
                d = data or {}
                sim.scr.echo(
                    f"BATCH rejected by the server: queue "
                    f"{d.get('queue_depth', '?')}/{d.get('limit', '?')} "
                    f"full — retry in {d.get('retry_after', '?')} s")
            elif name == b"HEALTH":
                # reply to the stack HEALTH command's server query
                txt = data.get("text") if isinstance(data, dict) \
                    else str(data)
                sim.scr.echo(txt or "no health data")
            elif name == b"WORLDS":
                # reply to the stack WORLDS command's server query/set
                txt = data.get("text") if isinstance(data, dict) \
                    else str(data)
                sim.scr.echo(txt or "no worlds data")
            elif name == b"MITIGATE":
                # reply to the stack MITIGATE command's server query/set
                txt = data.get("text") if isinstance(data, dict) \
                    else str(data)
                sim.scr.echo(txt or "no mitigation data")
            elif name == b"SDC":
                # reply to the stack SDC command's server query/set
                txt = data.get("text") if isinstance(data, dict) \
                    else str(data)
                sim.scr.echo(txt or "no sdc data")
            elif name == b"HA":
                # reply to the stack HA STATUS command's server query
                txt = data.get("text") if isinstance(data, dict) \
                    else str(data)
                sim.scr.echo(txt or "no ha data")
            elif name == b"METRICS":
                # reply to METRICS DUMP's server query: broker + fleet
                # registries rendered server-side
                txt = data.get("text") if isinstance(data, dict) \
                    else str(data)
                sim.scr.echo(txt or "no metrics data")
            elif name == b"TRACE":
                # reply to TRACE DUMP's server-side ring dump
                d = data if isinstance(data, dict) else {}
                sim.scr.echo(
                    f"server trace: {d.get('path') or 'ring empty'}"
                    if d.get("enabled")
                    else "server trace: recorder disabled")
            elif name == b"GETSIMSTATE":
                self.send_event(b"SIMSTATE", {
                    "state": sim.state_flag, "simt": sim.simt_planned,
                    "simdt": sim.simdt, "ntraf": sim.traf.ntraf},
                    list(reversed(sender_route)) or None)
            elif name == b"QUIT":
                sim.stop()
                self.quit()

        # -------------------------------------------------------------- step
        def step(self):
            import time as _time
            sim = self.sim
            sim.scr.update()
            if self.worlds is not None:
                running = self.worlds.step()
                if sim.preempt_requested and self.running:
                    self._preempt_worlds()
                    return
                if not running:
                    self._finish_worlds()
                return
            alive = sim.step()
            # mesh-epoch transitions (device-group loss + recovery)
            # queued by sim._handle_mesh_lost — tell the server so it
            # journals the mesh_lost/resharded audit pair (or requeues
            # the piece PREEMPTED-style when recovery failed)
            while sim.mesh_events:
                self.send_event(b"MESHLOST", sim.mesh_events.pop(0))
            if sim.preempt_requested and self.running:
                self._preempt_shutdown()
                return
            if sim.state_flag != OP:
                _time.sleep(0.02)   # idle pacing (~50 Hz stack polling)
            if sim.state_flag != self.prev_state:
                was_op = self.prev_state == OP
                self.prev_state = sim.state_flag
                if was_op and sim.state_flag != OP:
                    self._batch_piece = None   # piece left flight
                    # completion fingerprint: SDCFP rides the FIFO
                    # event pair ahead of the STATECHANGE, so the
                    # server can journal/compare it against the piece
                    # this worker still has in flight (the OPTRESULT
                    # ordering contract)
                    fp = sim.fp_summary()
                    if fp is not None:
                        self.send_event(b"SDCFP", fp)
                self.send_event(b"STATECHANGE", sim.state_flag)
            if not alive or sim.state_flag == END:
                self.quit()

    return _SimNode


SimNode = _make_simnode_class(netnode.Node)
DetachedSimNode = _make_simnode_class(detached.Node)
