"""bluesky_tpu — a TPU-native air-traffic-simulation framework.

A ground-up redesign of the capabilities of BlueSky (the open ATM simulator,
reference: /root/reference) for TPU hardware: the N-aircraft simulation state
is a padded struct-of-arrays JAX pytree advanced by a jitted, `lax.scan`-
wrapped step function; the O(N^2) conflict detection and MVP resolution are
batched all-pairs kernels; geodesy/atmosphere primitives are jitted ops; the
aircraft axis shards over a `jax.sharding.Mesh` for large N, and Monte-Carlo
ensembles vmap over a replica axis.

Package layout:
  ops/        pure jitted math: geodesy, atmosphere, conflict detection
              (dense / lax-tiled / Pallas), MVP/Eby/Swarm/SSD resolvers,
              legacy+BADA performance kernels
  core/       simulation state pytree, traffic facade, kinematics,
              autopilot, pilot arbitration, ASAS coordinator, perf,
              wind, noise, routes, trails, conditionals, metrics, step
  parallel/   device-mesh sharding of the aircraft axis, ensemble axis
  stack/      the text-command stack (the universal user/API surface)
  simulation/ the fixed-dt simulation loop + node, streams, snapshots
  network/    zmq server/client/node fabric, GuiClient, telnet bridge
  plugins/    plugin system + the nine shipped plugins
  models/     OpenAP / BADA / BS coefficient databases, fwparser
  ui/         SVG radar renderer
  utils/      datalog, areafilter, plotter, profiler, timers
"""

__version__ = "0.1.0"
