"""bluesky_tpu — a TPU-native air-traffic-simulation framework.

A ground-up redesign of the capabilities of BlueSky (the open ATM simulator,
reference: /root/reference) for TPU hardware: the N-aircraft simulation state
is a padded struct-of-arrays JAX pytree advanced by a jitted, `lax.scan`-
wrapped step function; the O(N^2) conflict detection and MVP resolution are
batched all-pairs kernels; geodesy/atmosphere primitives are jitted ops; the
aircraft axis shards over a `jax.sharding.Mesh` for large N, and Monte-Carlo
ensembles vmap over a replica axis.

Package layout:
  ops/        pure jitted math: geodesy, atmosphere, conflict detection,
              conflict resolution kernels (jnp + Pallas variants)
  core/       simulation state pytree, traffic facade, kinematics, autopilot,
              pilot arbitration, performance model, step function
  parallel/   device-mesh sharding of the aircraft axis, ensemble axis
  stack/      the text-command stack (the universal user/API surface)
  simulation/ the fixed-dt simulation loop + node
  network/    zmq server/client/node process fabric
  models/     aircraft performance coefficient tables
  utils/      datalog, areafilter, timers, misc parsing
"""

__version__ = "0.1.0"
