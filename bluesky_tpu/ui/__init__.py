"""UI layer: headless radar rendering + the GUI client data mirror.

The reference ships a Qt-OpenGL radar (ui/qtgl/, ~3k LoC of GL state)
and a legacy pygame screen.  This framework is headless-first: the
equivalent surface is (a) the GuiClient-compatible ACDATA/ROUTEDATA
streams (simulation/screenio.py), (b) the client-side nodeData mirror
(network/guiclient.py), and (c) an SVG radar renderer (ui/radar.py)
that draws the same picture the RadarWidget draws — aircraft symbols
with labels, trails, area shapes, the selected route — into a file any
browser displays.  SCREENSHOT renders it sim-side.
"""
