"""UI layer: headless radar rendering + the GUI client data mirror.

The reference ships a Qt-OpenGL radar (ui/qtgl/, ~3k LoC of GL state)
and a legacy pygame screen.  This framework is headless-first: the
equivalent surface is (a) the GuiClient-compatible ACDATA/ROUTEDATA
streams (simulation/screenio.py), (b) the client-side nodeData mirror
(network/guiclient.py), and (c) an SVG radar renderer (ui/radar.py)
that draws the same picture the RadarWidget draws — aircraft symbols
with labels, trails, area shapes, the selected route — into a file any
browser displays.  SCREENSHOT renders it sim-side.

Shared frontend logic, usable by any client (reference parity):
- ``radarclick`` — click-to-command-line completion (ui/radarclick.py)
- ``console``    — command-line state/history/IC-autocomplete
  (ui/qtgl/console.py + autocomplete.py, de-Qt-ified)
- ``polytools``  — polygon -> triangle buffers (GLU tessellator replaced
  by pure-NumPy ear clipping)
- ``palette``    — colour registry (exec()-based palette files replaced
  by literal-parsed ones)
"""
