"""SVG radar renderer: the headless stand-in for the Qt RadarWidget.

Draws the same picture ``ui/qtgl/radarwidget.py`` draws from the ACDATA
stream — aircraft chevrons rotated to track with callsign/FL labels,
trail segments, named area shapes (BOX/CIRCLE/POLY/LINE), and the
selected route polyline — as a standalone SVG string/file.

Pure host-side: input is plain dicts/arrays (an ACDATA frame, the
objdata shape registry, a ROUTEDATA frame), so both the sim process
(SCREENSHOT command) and a connected GuiClient (its nodeData mirror)
render through this one code path.
"""
from xml.sax.saxutils import quoteattr, escape as _esc

import numpy as np

W, H = 1000, 800
BG = "#10141c"
COLORS = {
    "ac": "#37c837", "ac_conf": "#e8463c", "label": "#9fd49f",
    "trail": "#2b8cbe", "shape": "#b08d2f", "route": "#b05fd0",
    "grid": "#223",
}


def _extent(acdata, shapes):
    lats, lons = [], []
    if acdata and len(acdata.get("lat", [])):
        lats += list(np.atleast_1d(acdata["lat"]))
        lons += list(np.atleast_1d(acdata["lon"]))
    for _name, (kind, coords) in (shapes or {}).items():
        if coords is None:
            continue
        c = list(coords)
        if kind.upper() == "CIRCLE":
            clat, clon, r_nm = c[:3]
            dlat = r_nm / 60.0
            lats += [clat - dlat, clat + dlat]
            lons += [clon - 2 * dlat, clon + 2 * dlat]
        else:
            lats += c[0::2]
            lons += c[1::2]
    if not lats:
        return (-1.0, 1.0, -1.0, 1.0)
    lat0, lat1 = min(lats), max(lats)
    lon0, lon1 = min(lons), max(lons)
    padlat = max(0.05, 0.08 * (lat1 - lat0))
    padlon = max(0.05, 0.08 * (lon1 - lon0))
    return (lat0 - padlat, lat1 + padlat, lon0 - padlon, lon1 + padlon)


class _Proj:
    def __init__(self, extent):
        self.lat0, self.lat1, self.lon0, self.lon1 = extent

    def xy(self, lat, lon):
        x = (lon - self.lon0) / max(1e-9, self.lon1 - self.lon0) * W
        y = H - (lat - self.lat0) / max(1e-9, self.lat1 - self.lat0) * H
        return x, y


# ------------------------------------------------------------------
# SSD velocity-space discs (the reference RadarWidget's SSD view:
# radarwidget.py:290-302, 593-598 — a per-aircraft disc whose pixels
# are colored by a conflict test against every intruder, selected with
# the SSD stack command).  Here each selected aircraft gets an annular
# polar grid of candidate velocities (the vmin..vmax envelope ring of
# SSD.py:131-141), each cell colored red when flying that velocity
# would intrude within rpz_m inside the lookahead — the same VO
# predicate ops/cr_ssd.py resolves on, sampled host-side in NumPy so
# the overlay works on every CD backend and any fleet size (cost is
# O(intruders-in-ADS-B-range) per selected disc).
# ------------------------------------------------------------------

SSD_R_PX = 46          # disc outer radius on screen [px]
SSD_MAX_DISCS = 16     # drawing cap (ALL/CONFLICTS at large N)
_ADSB_MAX_M = 65.0 * 1852.0     # reference SSD.py:110 adsbmax


def ssd_disc(i, lat, lon, gseast, gsnorth, active, vmin, vmax, rpz_m,
             tlookahead, ntrk=36, nspd=5):
    """Sample ownship ``i``'s solution space: conf [ntrk, nspd] bool.

    Cell (t, s) covers track sector t of the annulus ring s between
    vmin and vmax; True = that candidate velocity conflicts with at
    least one intruder within ADS-B range (the cr_ssd._vo_masks CPA
    predicate, NumPy edition)."""
    from ..ops import hostgeo
    lat = np.asarray(lat, float)
    lon = np.asarray(lon, float)
    mask = np.asarray(active, bool).copy()
    mask[i] = False
    idx = np.flatnonzero(mask)
    trk_c = (np.arange(ntrk) + 0.5) * (360.0 / ntrk)
    spd_c = vmin + (np.arange(nspd) + 0.5) * ((vmax - vmin) / nspd)
    cve = (spd_c[None, :] * np.sin(np.radians(trk_c))[:, None]).ravel()
    cvn = (spd_c[None, :] * np.cos(np.radians(trk_c))[:, None]).ravel()
    if len(idx) == 0:
        return np.zeros((ntrk, nspd), bool)
    qdr, dist_nm = hostgeo.qdrdist(
        np.full(len(idx), lat[i]), np.full(len(idx), lon[i]),
        lat[idx], lon[idx])
    dist = np.asarray(dist_nm, float) * 1852.0
    near = dist < _ADSB_MAX_M
    if not near.any():
        return np.zeros((ntrk, nspd), bool)
    qdr = np.asarray(qdr, float)[near]
    dist = dist[near]
    dx = dist * np.sin(np.radians(qdr))        # ownship -> intruder east
    dy = dist * np.cos(np.radians(qdr))
    ge = np.asarray(gseast, float)[idx][near]
    gn = np.asarray(gsnorth, float)[idx][near]
    # w = v_j - u_candidate (StateBasedCD.py:39-40 convention)
    wve = ge[None, :] - cve[:, None]           # [C, M]
    wvn = gn[None, :] - cvn[:, None]
    dv2 = np.maximum(wve * wve + wvn * wvn, 1e-6)
    tcpa = -(wve * dx[None, :] + wvn * dy[None, :]) / dv2
    dcpa2 = (dx * dx + dy * dy)[None, :] - tcpa * tcpa * dv2
    r2 = rpz_m * rpz_m
    dtin = np.sqrt(np.maximum(0.0, r2 - dcpa2) / dv2)
    conf = (dcpa2 < r2) & (tcpa + dtin > 0.0) \
        & (tcpa - dtin < tlookahead)
    return np.any(conf, axis=1).reshape(ntrk, nspd)


def _ssd_disc_svg(x, y, conf, ve, vn, vmax, acid="", vmin=None):
    """One SSD disc as an SVG group at screen position (x, y)."""
    ntrk, nspd = conf.shape
    r0 = SSD_R_PX * 0.35               # vmin ring radius (fixed fraction)
    if vmin is None:
        vmin = 0.35 * vmax

    def vrad(v):
        """Speed -> radius with the SAME mapping as the annulus cells
        (vmin..vmax onto r0..R), linear from 0 below vmin — so the
        own-velocity vector tip lands in its true speed ring."""
        if v <= vmin:
            return r0 * v / max(vmin, 1.0)
        return r0 + (SSD_R_PX - r0) * min(
            (v - vmin) / max(vmax - vmin, 1.0), 1.15)

    v = float(np.hypot(ve, vn))
    scale = vrad(v) / max(v, 1.0)
    parts = [f'<g class="ssd" data-acid={quoteattr(str(acid))} '
             f'transform="translate({x:.1f},{y:.1f})" opacity="0.75">']

    def pt(ang_deg, r):
        a = np.radians(ang_deg)
        return f"{r * np.sin(a):.1f},{-r * np.cos(a):.1f}"

    step = 360.0 / ntrk
    for t in range(ntrk):
        a0, a1 = t * step, (t + 1) * step
        for s in range(nspd):
            ra = r0 + (SSD_R_PX - r0) * s / nspd
            rb = r0 + (SSD_R_PX - r0) * (s + 1) / nspd
            color = "#b03028" if conf[t, s] else "#1f7a2f"
            parts.append(
                f'<path d="M{pt(a0, ra)} L{pt(a0, rb)} '
                f'A{rb:.1f},{rb:.1f} 0 0 1 {pt(a1, rb)} '
                f'L{pt(a1, ra)} A{ra:.1f},{ra:.1f} 0 0 0 {pt(a0, ra)} Z" '
                f'fill="{color}" stroke="none"/>')
    # envelope rings + own velocity vector (radarwidget draws the
    # ownship speed vector over the disc)
    parts.append(f'<circle r="{SSD_R_PX:.1f}" fill="none" '
                 f'stroke="#889" stroke-width="0.8"/>')
    parts.append(f'<circle r="{r0:.1f}" fill="none" stroke="#889" '
                 f'stroke-width="0.8"/>')
    parts.append(f'<line x1="0" y1="0" x2="{ve * scale:.1f}" '
                 f'y2="{-vn * scale:.1f}" stroke="#fff" '
                 f'stroke-width="1.6"/>')
    parts.append("</g>")
    return "".join(parts)


def render_svg(acdata=None, shapes=None, routedata=None, title="",
               extent=None, ssd=None):
    """SVG text for one radar frame.

    acdata: dict with id/lat/lon/trk/alt (+ optional inconf,
    traillat0..) — the ACDATA schema; shapes: {name: (kind, coords)}
    — the objdata registry; routedata: the ROUTEDATA schema.
    ``extent`` (lat0, lat1, lon0, lon1) fixes the view window (the
    PAN/ZOOM state); default auto-fits the scene.  The extent rides on
    the root element (``data-extent``) so an interactive frontend can
    map clicks back to lat/lon, and each aircraft group carries its
    callsign (``data-acid``) for click-to-command.
    """
    ext = extent if extent is not None else _extent(acdata, shapes)
    proj = _Proj(ext)
    parts = [
        f'<svg xmlns="http://www.w3.org/2000/svg" width="{W}" '
        f'height="{H}" viewBox="0 0 {W} {H}" '
        f'data-extent="{ext[0]:.6f},{ext[1]:.6f},'
        f'{ext[2]:.6f},{ext[3]:.6f}">',
        f'<rect width="{W}" height="{H}" fill="{BG}"/>',
    ]
    # Graticule each whole degree
    for latg in range(int(np.floor(proj.lat0)), int(np.ceil(proj.lat1)) + 1):
        _, y = proj.xy(latg, proj.lon0)
        parts.append(f'<line x1="0" y1="{y:.1f}" x2="{W}" y2="{y:.1f}" '
                     f'stroke="{COLORS["grid"]}" stroke-width="1"/>')
    for long in range(int(np.floor(proj.lon0)), int(np.ceil(proj.lon1)) + 1):
        x, _ = proj.xy(proj.lat0, long)
        parts.append(f'<line x1="{x:.1f}" y1="0" x2="{x:.1f}" y2="{H}" '
                     f'stroke="{COLORS["grid"]}" stroke-width="1"/>')

    # Area shapes
    for name, (kind, coords) in (shapes or {}).items():
        if coords is None:
            continue
        k = kind.upper()
        c = list(coords)
        if k == "CIRCLE":
            x, y = proj.xy(c[0], c[1])
            _, y2 = proj.xy(c[0] + c[2] / 60.0, c[1])
            parts.append(
                f'<circle cx="{x:.1f}" cy="{y:.1f}" r="{abs(y - y2):.1f}" '
                f'fill="none" stroke="{COLORS["shape"]}"/>')
        else:
            pts = " ".join(f"{proj.xy(la, lo)[0]:.1f},"
                           f"{proj.xy(la, lo)[1]:.1f}"
                           for la, lo in zip(c[0::2], c[1::2]))
            closed = "polygon" if k in ("POLY", "BOX") else "polyline"
            parts.append(f'<{closed} points="{pts}" fill="none" '
                         f'stroke="{COLORS["shape"]}"/>')
        la0, lo0 = c[0], c[1]
        x, y = proj.xy(la0, lo0)
        parts.append(f'<text x="{x + 4:.1f}" y="{y - 4:.1f}" '
                     f'fill="{COLORS["shape"]}" font-size="10">'
                     f'{_esc(str(name))}</text>')

    # Selected route
    if routedata and routedata.get("wplat"):
        pts = " ".join(
            f"{proj.xy(la, lo)[0]:.1f},{proj.xy(la, lo)[1]:.1f}"
            for la, lo in zip(routedata["wplat"], routedata["wplon"]))
        parts.append(f'<polyline points="{pts}" fill="none" '
                     f'stroke="{COLORS["route"]}" stroke-dasharray="6 4"/>')
        for la, lo, nm_ in zip(routedata["wplat"], routedata["wplon"],
                               routedata.get("wpname", [])):
            x, y = proj.xy(la, lo)
            parts.append(f'<circle cx="{x:.1f}" cy="{y:.1f}" r="3" '
                         f'fill="{COLORS["route"]}"/>')
            parts.append(f'<text x="{x + 4:.1f}" y="{y + 10:.1f}" '
                         f'fill="{COLORS["route"]}" font-size="9">'
                         f'{_esc(str(nm_))}</text>')

    # SSD velocity-space discs (under the chevrons)
    for d in (ssd or []):
        x, y = proj.xy(d["lat"], d["lon"])
        parts.append(_ssd_disc_svg(x, y, d["conf"], d["ve"], d["vn"],
                                   d["vmax"], d.get("acid", ""),
                                   vmin=d.get("vmin")))

    if acdata:
        # Trails
        t0 = np.atleast_1d(acdata.get("traillat0", []))
        if len(t0):
            for la0, lo0, la1, lo1 in zip(
                    t0, np.atleast_1d(acdata["traillon0"]),
                    np.atleast_1d(acdata["traillat1"]),
                    np.atleast_1d(acdata["traillon1"])):
                x0, y0 = proj.xy(la0, lo0)
                x1, y1 = proj.xy(la1, lo1)
                parts.append(
                    f'<line x1="{x0:.1f}" y1="{y0:.1f}" x2="{x1:.1f}" '
                    f'y2="{y1:.1f}" stroke="{COLORS["trail"]}"/>')
        # Aircraft chevrons + labels
        ids = acdata.get("id", [])
        lat = np.atleast_1d(acdata.get("lat", []))
        lon = np.atleast_1d(acdata.get("lon", []))
        trk = np.atleast_1d(acdata.get("trk", np.zeros(len(lat))))
        alt = np.atleast_1d(acdata.get("alt", np.zeros(len(lat))))
        inconf = np.atleast_1d(acdata.get("inconf",
                                          np.zeros(len(lat), bool)))
        # CPA lines: in-conflict aircraft projected along track to the
        # closest-point-of-approach time (reference radarwidget.py:754
        # — lat1, lon1 = qdrpos(lat, lon, trk, tcpa*gs/nm))
        tcpa = np.atleast_1d(acdata.get("tcpamax", []))
        gs = np.atleast_1d(acdata.get("gs", []))
        if len(tcpa) == len(lat) and len(gs) == len(lat):
            from ..ops import hostgeo
            for i in np.flatnonzero(np.asarray(inconf[:len(lat)],
                                               bool)):
                d_nm = max(0.0, float(tcpa[i]) * float(gs[i]) / 1852.0)
                la1, lo1 = hostgeo.qdrpos(float(lat[i]), float(lon[i]),
                                          float(trk[i]), d_nm)
                x0, y0 = proj.xy(lat[i], lon[i])
                x1, y1 = proj.xy(la1, lo1)
                parts.append(
                    f'<line x1="{x0:.1f}" y1="{y0:.1f}" x2="{x1:.1f}" '
                    f'y2="{y1:.1f}" stroke="{COLORS["ac_conf"]}" '
                    f'stroke-width="1" stroke-dasharray="3 3"/>')
        for i in range(len(lat)):
            x, y = proj.xy(lat[i], lon[i])
            color = COLORS["ac_conf"] if (len(inconf) > i
                                          and inconf[i]) \
                else COLORS["ac"]
            label = str(ids[i]) if i < len(ids) else ""
            parts.append(
                f'<g transform="translate({x:.1f},{y:.1f}) '
                f'rotate({float(trk[i]):.0f})" '
                f'data-acid={quoteattr(label)}>'
                f'<path d="M0,-6 L4,6 L0,3 L-4,6 Z" fill="{color}"/>'
                f'<circle r="8" fill="transparent"/></g>')
            fl = int(round(float(alt[i]) / 0.3048 / 100.0))
            parts.append(f'<text x="{x + 6:.1f}" y="{y:.1f}" '
                         f'fill="{COLORS["label"]}" font-size="10">'
                         f'{_esc(label)} FL{fl:03d}</text>')

    if title:
        parts.append(f'<text x="10" y="20" fill="#ccc" font-size="13">'
                     f'{_esc(str(title))}</text>')
    parts.append("</svg>")
    return "\n".join(parts)


def render_sim(sim, fname=None):
    """Render the current state of an embedded Simulation (the
    SCREENSHOT command path): builds an ACDATA-shaped frame from the
    state arrays + the screen's shape registry + the selected route."""
    traf = sim.traf
    st = traf.state.ac
    active = np.asarray(st.active)
    idx = np.flatnonzero(active)
    acdata = {
        "id": [traf.ids[i] for i in idx],
        "lat": np.asarray(st.lat)[idx],
        "lon": np.asarray(st.lon)[idx],
        "trk": np.asarray(st.trk)[idx],
        "alt": np.asarray(st.alt)[idx],
        "gs": np.asarray(st.gs)[idx],
        "inconf": np.asarray(traf.state.asas.inconf)[idx],
        "tcpamax": np.asarray(traf.state.asas.tcpamax)[idx],
        "traillat0": traf.trails.lat0, "traillon0": traf.trails.lon0,
        "traillat1": traf.trails.lat1, "traillon1": traf.trails.lon1,
    }
    routedata = None
    acid = getattr(sim.scr, "route_acid", "")
    if acid:
        i = traf.id2idx(acid)
        if isinstance(i, int) and i >= 0:
            r = sim.routes.route(i)
            routedata = {"wplat": list(r.lat), "wplon": list(r.lon),
                         "wpname": list(r.name)}
    # Honor the PAN/ZOOM display state once the user has set it (the
    # reference RadarWidget's pan/zoom); before any PAN/ZOOM command
    # the view auto-fits the scene.
    extent = None
    if getattr(sim.scr, "user_view", False):
        lat0, lat1, lon0, lon1 = sim.scr.getviewbounds()
        # widen lon by the aspect ratio so degrees stay ~square
        c = (lon0 + lon1) / 2.0
        half = (lon1 - lon0) / 2.0 * (W / H)
        extent = (lat0, lat1, c - half, c + half)
    else:
        # Sync the auto-fitted view into the display state, so the
        # FIRST user ZOOM/PAN continues smoothly from what is on
        # screen instead of jumping to the (0,0) default center.
        a = _extent(acdata, sim.scr.objdata)
        sim.scr.ctrlat = (a[0] + a[1]) / 2.0
        sim.scr.ctrlon = (a[2] + a[3]) / 2.0
        sim.scr.scrzoom = 1.0 / max((a[1] - a[0]) / 2.0, 1e-6)
    svg = render_svg(acdata, sim.scr.objdata, routedata,
                     title=f"simt {sim.simt:.1f} s — "
                           f"{len(idx)} aircraft",
                     extent=extent, ssd=compute_ssd_discs(sim))
    if fname:
        with open(fname, "w") as f:
            f.write(svg)
    return svg


def compute_ssd_discs_acdata(acdata, ssd_all, ssd_conflicts, ssd_ownship,
                             vmin=None, vmax=None, rpz_m=None,
                             tlookahead=None):
    """SSD disc data from an ACDATA-shaped mirror (the GuiClient path:
    the reference's GL client computes its discs from the same streamed
    arrays, radarwidget.py:728-765).  ASAS parameters come from the
    stream itself (ACDATA carries vmin/vmax/asasrpz/asasdtlook, so a
    server-side ZONER/DTLOOK change is mirrored — unlike the reference
    client's hard-coded display constants); explicit arguments override,
    and AsasConfig defaults back an old producer without the fields."""
    if not (ssd_all or ssd_conflicts or ssd_ownship):
        return None
    lat = np.atleast_1d(acdata.get("lat", []))
    if not len(lat):
        return None
    from ..core.asas import AsasConfig
    _c = AsasConfig()
    vmin = acdata.get("vmin", _c.vmin) if vmin is None else vmin
    vmax = acdata.get("vmax", _c.vmax) if vmax is None else vmax
    rpz_m = acdata.get("asasrpz", _c.rpz_m) if rpz_m is None else rpz_m
    tlookahead = acdata.get("asasdtlook", _c.dtlookahead) \
        if tlookahead is None else tlookahead
    lon = np.atleast_1d(acdata["lon"])
    trk = np.radians(np.atleast_1d(acdata.get("trk",
                                              np.zeros(len(lat)))))
    gs = np.atleast_1d(acdata.get("gs", np.zeros(len(lat))))
    gse, gsn = gs * np.sin(trk), gs * np.cos(trk)
    ids = list(acdata.get("id", []))
    inconf = np.atleast_1d(acdata.get("inconf", np.zeros(len(lat), bool)))
    active = np.ones(len(lat), bool)
    if ssd_all:
        sel = list(range(len(lat)))
    else:
        sel = []
        if ssd_conflicts:
            sel += list(np.flatnonzero(
                np.asarray(inconf[:len(lat)], bool)))
        sel += [i for i, a in enumerate(ids)
                if a in ssd_ownship and i not in sel]
    sel = sel[:SSD_MAX_DISCS]
    if not sel:
        return None
    return [{
        "lat": float(lat[i]), "lon": float(lon[i]),
        "conf": ssd_disc(int(i), lat, lon, gse, gsn, active,
                         vmin, vmax, rpz_m, tlookahead),
        "ve": float(gse[i]), "vn": float(gsn[i]),
        "vmin": vmin, "vmax": vmax,
        "acid": ids[i] if i < len(ids) else "",
    } for i in sel]


def compute_ssd_discs(sim):
    """SSD disc data for the aircraft selected by the SSD command
    (scr.ssd_all / ssd_conflicts / ssd_ownship — reference
    radarwidget.py:751-765 selssd logic), capped at SSD_MAX_DISCS."""
    scr = sim.scr
    if not (getattr(scr, "ssd_all", False)
            or getattr(scr, "ssd_conflicts", False)
            or getattr(scr, "ssd_ownship", None)):
        return None
    traf = sim.traf
    st = traf.state.ac
    active = np.asarray(st.active)
    if scr.ssd_all:
        sel = list(np.flatnonzero(active))
    else:
        # conflicts and named ownships COMBINE (reference
        # radarwidget.py:751-762 sets selssd for either condition)
        sel = []
        if scr.ssd_conflicts:
            sel += list(np.flatnonzero(
                active & np.asarray(traf.state.asas.inconf)))
        sel += [i for i in (traf.id2idx(a)
                            for a in sorted(scr.ssd_ownship))
                if isinstance(i, (int, np.integer)) and i >= 0
                and i not in sel]
    sel = sel[:SSD_MAX_DISCS]
    if not sel:
        return None
    c = sim.cfg.asas
    lat, lon = np.asarray(st.lat), np.asarray(st.lon)
    gse, gsn = np.asarray(st.gseast), np.asarray(st.gsnorth)
    return [{
        "lat": float(lat[i]), "lon": float(lon[i]),
        "conf": ssd_disc(int(i), lat, lon, gse, gsn, active,
                         c.vmin, c.vmax, c.rpz_m, c.dtlookahead),
        "ve": float(gse[i]), "vn": float(gsn[i]),
        "vmin": c.vmin, "vmax": c.vmax,
        "acid": traf.ids[int(i)],
    } for i in sel]


# --------------------------------------------------------------------------
# Navigation display: the reference's per-aircraft heading-up ND
# (ui/qtgl/nd.py:55-282) as an SVG — ownship chevron, the +-60 deg
# wedge with compass ticks, three intermediate range arcs, GS/TAS
# readout, surrounding traffic with relative-altitude tags, and the
# ownship route — selected with the SHOWND stack command.
# --------------------------------------------------------------------------

ND_W = ND_H = 400


def render_nd(sim, acid=None, range_nm=40.0):
    """SVG navigation display for one aircraft (default: SHOWND's) —
    rendered from live Simulation state."""
    acid = acid or getattr(sim.scr, "nd_acid", None)
    traf = sim.traf
    i = traf.id2idx(acid) if acid else -1
    if not isinstance(i, (int, np.integer)) or i < 0:
        return _render_nd_data(acid, None, None, None, range_nm)
    st = traf.state.ac
    own = dict(lat=float(st.lat[i]), lon=float(st.lon[i]),
               trk=float(st.trk[i]), gs=float(st.gs[i]),
               tas=float(st.tas[i]), alt=float(st.alt[i]))
    active = np.asarray(st.active).copy()
    active[i] = False
    idx = np.flatnonzero(active)
    traffic = dict(
        id=[traf.ids[j] for j in idx],
        lat=np.asarray(st.lat)[idx], lon=np.asarray(st.lon)[idx],
        alt=np.asarray(st.alt)[idx],
        inconf=np.asarray(traf.state.asas.inconf)[idx])
    route = None
    if getattr(sim.scr, "route_acid", "") == acid:
        r = sim.routes.route(i)
        route = (list(r.lat), list(r.lon))
    return _render_nd_data(acid, own, traffic, route, range_nm)


def render_nd_acdata(nd, acid=None, range_nm=40.0):
    """ND from a GuiClient nodeData mirror (the networked-client path —
    the reference ND draws from the same streamed buffers,
    ui/qtgl/nd.py consuming the radarwidget's ACDATA state)."""
    acid = acid or getattr(nd, "nd_acid", None)
    ac = nd.acdata or {}
    ids = list(ac.get("id", []))
    if not acid or acid not in ids:
        return _render_nd_data(acid, None, None, None, range_nm)
    i = ids.index(acid)
    lat = np.atleast_1d(ac["lat"])
    lon = np.atleast_1d(ac["lon"])
    trk = np.atleast_1d(ac.get("trk", np.zeros(len(lat))))
    gs = np.atleast_1d(ac.get("gs", np.zeros(len(lat))))
    tas = np.atleast_1d(ac.get("tas", gs))
    alt = np.atleast_1d(ac.get("alt", np.zeros(len(lat))))
    inconf = np.atleast_1d(ac.get("inconf", np.zeros(len(lat), bool)))
    own = dict(lat=float(lat[i]), lon=float(lon[i]), trk=float(trk[i]),
               gs=float(gs[i]), tas=float(tas[i]), alt=float(alt[i]))
    keep = [j for j in range(len(lat)) if j != i]
    traffic = dict(id=[ids[j] for j in keep],
                   lat=lat[keep], lon=lon[keep], alt=alt[keep],
                   inconf=np.asarray(inconf)[keep])
    route = None
    rd = getattr(nd, "routedata", None) or {}
    if rd.get("wplat") and rd.get("acid", acid) == acid:
        route = (list(rd["wplat"]), list(rd["wplon"]))
    return _render_nd_data(acid, own, traffic, route, range_nm)


def _render_nd_data(acid, own, traffic, route, range_nm=40.0):
    """The ND picture from plain data (shared by the embedded and
    client paths).  ``own``: dict lat/lon/trk/gs/tas/alt; ``traffic``:
    dict of arrays id/lat/lon/alt/inconf (ownship already excluded);
    ``route``: (lats, lons) or None."""
    from ..ops import hostgeo
    cx, cy = ND_W / 2.0, ND_H * 0.78
    unit = (ND_H * 0.62) / 1.4          # 1.4 ND units = display range
    parts = [
        f'<svg xmlns="http://www.w3.org/2000/svg" width="{ND_W}" '
        f'height="{ND_H}" viewBox="0 0 {ND_W} {ND_H}">',
        f'<rect width="{ND_W}" height="{ND_H}" fill="#000"/>',
    ]
    if own is None:
        parts.append('<text x="20" y="30" fill="#888" font-size="13">'
                     'ND: no aircraft selected (SHOWND acid)</text>'
                     '</svg>')
        return "\n".join(parts)

    olat, olon = own["lat"], own["lon"]
    otrk = own["trk"]
    ogs, otas = own["gs"], own["tas"]
    oalt = own["alt"]

    def arc(rad_units, lo=-60, hi=60, color="#ccc"):
        pts = []
        for a in range(lo, hi + 1, 2):
            r = rad_units * unit
            pts.append(f"{cx + r * np.sin(np.radians(a)):.1f},"
                       f"{cy - r * np.cos(np.radians(a)):.1f}")
        return (f'<polyline points="{" ".join(pts)}" fill="none" '
                f'stroke="{color}"/>')

    # wedge edge + intermediate range arcs (nd.py:99-113)
    parts.append(arc(1.4))
    for k in (1, 2, 3):
        parts.append(arc(k * 0.35, color="#444"))
    # compass ticks every 5 deg, heading labels every 30 (nd.py:124-152)
    for a in range(-60, 61, 5):
        hdg = (otrk + a) % 360.0
        big = abs(round(hdg)) % 30 < 2.5
        r0, r1 = 1.4 * unit, (1.46 if big else 1.42) * unit
        sa, ca = np.sin(np.radians(a)), np.cos(np.radians(a))
        parts.append(f'<line x1="{cx + r0 * sa:.1f}" '
                     f'y1="{cy - r0 * ca:.1f}" x2="{cx + r1 * sa:.1f}" '
                     f'y2="{cy - r1 * ca:.1f}" stroke="#ccc"/>')
        if big:
            parts.append(
                f'<text x="{cx + 1.52 * unit * sa:.1f}" '
                f'y="{cy - 1.5 * unit * ca:.1f}" fill="#ccc" '
                f'font-size="11" text-anchor="middle">'
                f'{int(round(hdg / 10.0)) % 36:02d}</text>')
    # GS/TAS readout (nd.py:158-159) + range note
    parts.append(f'<text x="8" y="16" fill="#ccc" font-size="11">GS'
                 f'<tspan fill="#3c3" dx="4">{ogs * 1.94384:.0f}'
                 f'</tspan>  TAS<tspan fill="#3c3" dx="4">'
                 f'{otas * 1.94384:.0f}</tspan></text>')
    parts.append(f'<text x="{ND_W - 8}" y="16" fill="#888" '
                 f'font-size="11" text-anchor="end">{_esc(str(acid))} '
                 f'rng {range_nm:.0f} nm</text>')

    def to_xy(lat, lon):
        qdr, dist = hostgeo.qdrdist(olat, olon, float(lat), float(lon))
        rel = np.radians(float(qdr) - otrk)
        r = float(dist) / range_nm * 1.4 * unit
        return cx + r * np.sin(rel), cy - r * np.cos(rel), float(dist)

    # ownship route, heading-up (the reference copies the route buffers)
    if route is not None:
        pts = []
        for la, lo in zip(*route):
            x, y, d = to_xy(la, lo)
            if d < range_nm * 1.6:
                pts.append(f"{x:.1f},{y:.1f}")
        if pts:
            parts.append(f'<polyline points="{" ".join(pts)}" '
                         f'fill="none" stroke="{COLORS["route"]}" '
                         f'stroke-dasharray="5 4"/>')

    # surrounding traffic (diamonds + relative altitude, TCAS-style)
    t_ids = traffic["id"] if traffic else []
    t_inconf = np.atleast_1d(traffic["inconf"]) if traffic else []
    for j in range(len(t_ids)):
        x, y, d = to_xy(traffic["lat"][j], traffic["lon"][j])
        if d > range_nm * 1.5:
            continue
        color = COLORS["ac_conf"] if (len(t_inconf) > j
                                      and t_inconf[j]) else "#fff"
        parts.append(f'<path d="M{x:.1f},{y - 5:.1f} l5,5 l-5,5 '
                     f'l-5,-5 Z" fill="none" stroke="{color}"/>')
        dalt_fl = (float(traffic["alt"][j]) - oalt) / 0.3048 / 100.0
        parts.append(f'<text x="{x + 7:.1f}" y="{y + 4:.1f}" '
                     f'fill="{color}" font-size="9">'
                     f'{_esc(str(t_ids[j]))} '
                     f'{"+" if dalt_fl >= 0 else "-"}'
                     f'{abs(dalt_fl):03.0f}</text>')

    # ownship symbol (nd.py:155 vown), fixed heading-up at the focus
    s = unit * 0.09
    parts.append(
        f'<g transform="translate({cx},{cy})" stroke="#ff0" fill="none">'
        f'<line x1="0" y1="0" x2="0" y2="{1.33 * s:.1f}"/>'
        f'<line x1="{-0.72 * s:.1f}" y1="{0.33 * s:.1f}" '
        f'x2="{0.72 * s:.1f}" y2="{0.33 * s:.1f}"/>'
        f'<line x1="{-0.24 * s:.1f}" y1="{1.11 * s:.1f}" '
        f'x2="{0.24 * s:.1f}" y2="{1.11 * s:.1f}"/></g>')
    parts.append("</svg>")
    return "\n".join(parts)


def render_plots(sim, width=640, row_h=160):
    """SVG chart sheet for the live PLOT registry — the headless
    analogue of the reference's matplotlib InfoWindow plot tabs
    (ui/qtgl/infowindow.py:34-109): one panel per PLOT command, drawn
    from the plotter's buffered series."""
    plots = [p for p in getattr(sim.plotter, "plots", [])
             if len(p.series[0]) >= 2]
    h = max(1, len(plots)) * row_h
    parts = [
        f'<svg xmlns="http://www.w3.org/2000/svg" width="{width}" '
        f'height="{h}" viewBox="0 0 {width} {h}">',
        f'<rect width="{width}" height="{h}" fill="{BG}"/>',
    ]
    if not plots:
        parts.append('<text x="16" y="28" fill="#888" font-size="12">'
                     'no plots — use e.g. PLOT simt ac.tas[0] 1'
                     '</text></svg>')
        return "\n".join(parts)
    m = 36                                   # panel margin

    def as_curve(samples):
        """Robust per-sample scalarization: unindexed PLOT variables
        buffer a (possibly ragged) vector per sample — chart the mean."""
        return np.array([float(np.mean(np.asarray(v, float)))
                         if np.size(v) else np.nan for v in samples])

    for k, p in enumerate(plots):
        xs = as_curve(p.series[0])
        ys = as_curve(p.series[1])
        keep = np.isfinite(xs) & np.isfinite(ys)
        xs, ys = xs[keep], ys[keep]
        y0 = k * row_h
        if len(xs) < 2:
            continue
        # more than ~2 samples per pixel is invisible: stride-downsample
        # so an hours-long fast-time run cannot bloat the sheet
        stride = max(1, len(xs) // (2 * (width - 2 * m)))
        xs, ys = xs[::stride], ys[::stride]
        x_lo, x_hi = float(xs.min()), float(xs.max())
        y_lo, y_hi = float(ys.min()), float(ys.max())
        xs_n = (xs - x_lo) / max(x_hi - x_lo, 1e-9)
        ys_n = (ys - y_lo) / max(y_hi - y_lo, 1e-9)
        px = m + xs_n * (width - 2 * m)
        py = y0 + row_h - m - ys_n * (row_h - 2 * m)
        pts = " ".join(f"{x:.1f},{y:.1f}" for x, y in zip(px, py))
        color = quoteattr(str(p.color or "#3c3"))
        parts += [
            f'<rect x="{m}" y="{y0 + m}" width="{width - 2 * m}" '
            f'height="{row_h - 2 * m}" fill="none" stroke="#334"/>',
            f'<polyline points="{pts}" fill="none" stroke={color} '
            f'stroke-width="1.5"/>',
            f'<text x="{m}" y="{y0 + m - 6}" fill="#9fd49f" '
            f'font-size="11">fig {p.fig}: '
            f'{_esc(p.y.varname)} vs {_esc(p.x.varname)}</text>',
            f'<text x="{m}" y="{y0 + row_h - m + 14}" fill="#678" '
            f'font-size="9">{x_lo:.4g}</text>',
            f'<text x="{width - m}" y="{y0 + row_h - m + 14}" '
            f'fill="#678" font-size="9" text-anchor="end">'
            f'{x_hi:.4g}</text>',
            f'<text x="{m - 4}" y="{y0 + row_h - m}" fill="#678" '
            f'font-size="9" text-anchor="end">{y_lo:.4g}</text>',
            f'<text x="{m - 4}" y="{y0 + m + 10}" fill="#678" '
            f'font-size="9" text-anchor="end">{y_hi:.4g}</text>',
        ]
    parts.append("</svg>")
    return "\n".join(parts)
