"""SVG radar renderer: the headless stand-in for the Qt RadarWidget.

Draws the same picture ``ui/qtgl/radarwidget.py`` draws from the ACDATA
stream — aircraft chevrons rotated to track with callsign/FL labels,
trail segments, named area shapes (BOX/CIRCLE/POLY/LINE), and the
selected route polyline — as a standalone SVG string/file.

Pure host-side: input is plain dicts/arrays (an ACDATA frame, the
objdata shape registry, a ROUTEDATA frame), so both the sim process
(SCREENSHOT command) and a connected GuiClient (its nodeData mirror)
render through this one code path.
"""
from xml.sax.saxutils import quoteattr, escape as _esc

import numpy as np

W, H = 1000, 800
BG = "#10141c"
COLORS = {
    "ac": "#37c837", "ac_conf": "#e8463c", "label": "#9fd49f",
    "trail": "#2b8cbe", "shape": "#b08d2f", "route": "#b05fd0",
    "grid": "#223",
}


def _extent(acdata, shapes):
    lats, lons = [], []
    if acdata and len(acdata.get("lat", [])):
        lats += list(np.atleast_1d(acdata["lat"]))
        lons += list(np.atleast_1d(acdata["lon"]))
    for _name, (kind, coords) in (shapes or {}).items():
        if coords is None:
            continue
        c = list(coords)
        if kind.upper() == "CIRCLE":
            clat, clon, r_nm = c[:3]
            dlat = r_nm / 60.0
            lats += [clat - dlat, clat + dlat]
            lons += [clon - 2 * dlat, clon + 2 * dlat]
        else:
            lats += c[0::2]
            lons += c[1::2]
    if not lats:
        return (-1.0, 1.0, -1.0, 1.0)
    lat0, lat1 = min(lats), max(lats)
    lon0, lon1 = min(lons), max(lons)
    padlat = max(0.05, 0.08 * (lat1 - lat0))
    padlon = max(0.05, 0.08 * (lon1 - lon0))
    return (lat0 - padlat, lat1 + padlat, lon0 - padlon, lon1 + padlon)


class _Proj:
    def __init__(self, extent):
        self.lat0, self.lat1, self.lon0, self.lon1 = extent

    def xy(self, lat, lon):
        x = (lon - self.lon0) / max(1e-9, self.lon1 - self.lon0) * W
        y = H - (lat - self.lat0) / max(1e-9, self.lat1 - self.lat0) * H
        return x, y


def render_svg(acdata=None, shapes=None, routedata=None, title="",
               extent=None):
    """SVG text for one radar frame.

    acdata: dict with id/lat/lon/trk/alt (+ optional inconf,
    traillat0..) — the ACDATA schema; shapes: {name: (kind, coords)}
    — the objdata registry; routedata: the ROUTEDATA schema.
    ``extent`` (lat0, lat1, lon0, lon1) fixes the view window (the
    PAN/ZOOM state); default auto-fits the scene.  The extent rides on
    the root element (``data-extent``) so an interactive frontend can
    map clicks back to lat/lon, and each aircraft group carries its
    callsign (``data-acid``) for click-to-command.
    """
    ext = extent if extent is not None else _extent(acdata, shapes)
    proj = _Proj(ext)
    parts = [
        f'<svg xmlns="http://www.w3.org/2000/svg" width="{W}" '
        f'height="{H}" viewBox="0 0 {W} {H}" '
        f'data-extent="{ext[0]:.6f},{ext[1]:.6f},'
        f'{ext[2]:.6f},{ext[3]:.6f}">',
        f'<rect width="{W}" height="{H}" fill="{BG}"/>',
    ]
    # Graticule each whole degree
    for latg in range(int(np.floor(proj.lat0)), int(np.ceil(proj.lat1)) + 1):
        _, y = proj.xy(latg, proj.lon0)
        parts.append(f'<line x1="0" y1="{y:.1f}" x2="{W}" y2="{y:.1f}" '
                     f'stroke="{COLORS["grid"]}" stroke-width="1"/>')
    for long in range(int(np.floor(proj.lon0)), int(np.ceil(proj.lon1)) + 1):
        x, _ = proj.xy(proj.lat0, long)
        parts.append(f'<line x1="{x:.1f}" y1="0" x2="{x:.1f}" y2="{H}" '
                     f'stroke="{COLORS["grid"]}" stroke-width="1"/>')

    # Area shapes
    for name, (kind, coords) in (shapes or {}).items():
        if coords is None:
            continue
        k = kind.upper()
        c = list(coords)
        if k == "CIRCLE":
            x, y = proj.xy(c[0], c[1])
            _, y2 = proj.xy(c[0] + c[2] / 60.0, c[1])
            parts.append(
                f'<circle cx="{x:.1f}" cy="{y:.1f}" r="{abs(y - y2):.1f}" '
                f'fill="none" stroke="{COLORS["shape"]}"/>')
        else:
            pts = " ".join(f"{proj.xy(la, lo)[0]:.1f},"
                           f"{proj.xy(la, lo)[1]:.1f}"
                           for la, lo in zip(c[0::2], c[1::2]))
            closed = "polygon" if k in ("POLY", "BOX") else "polyline"
            parts.append(f'<{closed} points="{pts}" fill="none" '
                         f'stroke="{COLORS["shape"]}"/>')
        la0, lo0 = c[0], c[1]
        x, y = proj.xy(la0, lo0)
        parts.append(f'<text x="{x + 4:.1f}" y="{y - 4:.1f}" '
                     f'fill="{COLORS["shape"]}" font-size="10">'
                     f'{_esc(str(name))}</text>')

    # Selected route
    if routedata and routedata.get("wplat"):
        pts = " ".join(
            f"{proj.xy(la, lo)[0]:.1f},{proj.xy(la, lo)[1]:.1f}"
            for la, lo in zip(routedata["wplat"], routedata["wplon"]))
        parts.append(f'<polyline points="{pts}" fill="none" '
                     f'stroke="{COLORS["route"]}" stroke-dasharray="6 4"/>')
        for la, lo, nm_ in zip(routedata["wplat"], routedata["wplon"],
                               routedata.get("wpname", [])):
            x, y = proj.xy(la, lo)
            parts.append(f'<circle cx="{x:.1f}" cy="{y:.1f}" r="3" '
                         f'fill="{COLORS["route"]}"/>')
            parts.append(f'<text x="{x + 4:.1f}" y="{y + 10:.1f}" '
                         f'fill="{COLORS["route"]}" font-size="9">'
                         f'{_esc(str(nm_))}</text>')

    if acdata:
        # Trails
        t0 = np.atleast_1d(acdata.get("traillat0", []))
        if len(t0):
            for la0, lo0, la1, lo1 in zip(
                    t0, np.atleast_1d(acdata["traillon0"]),
                    np.atleast_1d(acdata["traillat1"]),
                    np.atleast_1d(acdata["traillon1"])):
                x0, y0 = proj.xy(la0, lo0)
                x1, y1 = proj.xy(la1, lo1)
                parts.append(
                    f'<line x1="{x0:.1f}" y1="{y0:.1f}" x2="{x1:.1f}" '
                    f'y2="{y1:.1f}" stroke="{COLORS["trail"]}"/>')
        # Aircraft chevrons + labels
        ids = acdata.get("id", [])
        lat = np.atleast_1d(acdata.get("lat", []))
        lon = np.atleast_1d(acdata.get("lon", []))
        trk = np.atleast_1d(acdata.get("trk", np.zeros(len(lat))))
        alt = np.atleast_1d(acdata.get("alt", np.zeros(len(lat))))
        inconf = np.atleast_1d(acdata.get("inconf",
                                          np.zeros(len(lat), bool)))
        # CPA lines: in-conflict aircraft projected along track to the
        # closest-point-of-approach time (reference radarwidget.py:754
        # — lat1, lon1 = qdrpos(lat, lon, trk, tcpa*gs/nm))
        tcpa = np.atleast_1d(acdata.get("tcpamax", []))
        gs = np.atleast_1d(acdata.get("gs", []))
        if len(tcpa) == len(lat) and len(gs) == len(lat):
            from ..ops import hostgeo
            for i in np.flatnonzero(np.asarray(inconf[:len(lat)],
                                               bool)):
                d_nm = max(0.0, float(tcpa[i]) * float(gs[i]) / 1852.0)
                la1, lo1 = hostgeo.qdrpos(float(lat[i]), float(lon[i]),
                                          float(trk[i]), d_nm)
                x0, y0 = proj.xy(lat[i], lon[i])
                x1, y1 = proj.xy(la1, lo1)
                parts.append(
                    f'<line x1="{x0:.1f}" y1="{y0:.1f}" x2="{x1:.1f}" '
                    f'y2="{y1:.1f}" stroke="{COLORS["ac_conf"]}" '
                    f'stroke-width="1" stroke-dasharray="3 3"/>')
        for i in range(len(lat)):
            x, y = proj.xy(lat[i], lon[i])
            color = COLORS["ac_conf"] if (len(inconf) > i
                                          and inconf[i]) \
                else COLORS["ac"]
            label = str(ids[i]) if i < len(ids) else ""
            parts.append(
                f'<g transform="translate({x:.1f},{y:.1f}) '
                f'rotate({float(trk[i]):.0f})" '
                f'data-acid={quoteattr(label)}>'
                f'<path d="M0,-6 L4,6 L0,3 L-4,6 Z" fill="{color}"/>'
                f'<circle r="8" fill="transparent"/></g>')
            fl = int(round(float(alt[i]) / 0.3048 / 100.0))
            parts.append(f'<text x="{x + 6:.1f}" y="{y:.1f}" '
                         f'fill="{COLORS["label"]}" font-size="10">'
                         f'{_esc(label)} FL{fl:03d}</text>')

    if title:
        parts.append(f'<text x="10" y="20" fill="#ccc" font-size="13">'
                     f'{_esc(str(title))}</text>')
    parts.append("</svg>")
    return "\n".join(parts)


def render_sim(sim, fname=None):
    """Render the current state of an embedded Simulation (the
    SCREENSHOT command path): builds an ACDATA-shaped frame from the
    state arrays + the screen's shape registry + the selected route."""
    traf = sim.traf
    st = traf.state.ac
    active = np.asarray(st.active)
    idx = np.flatnonzero(active)
    acdata = {
        "id": [traf.ids[i] for i in idx],
        "lat": np.asarray(st.lat)[idx],
        "lon": np.asarray(st.lon)[idx],
        "trk": np.asarray(st.trk)[idx],
        "alt": np.asarray(st.alt)[idx],
        "gs": np.asarray(st.gs)[idx],
        "inconf": np.asarray(traf.state.asas.inconf)[idx],
        "tcpamax": np.asarray(traf.state.asas.tcpamax)[idx],
        "traillat0": traf.trails.lat0, "traillon0": traf.trails.lon0,
        "traillat1": traf.trails.lat1, "traillon1": traf.trails.lon1,
    }
    routedata = None
    acid = getattr(sim.scr, "route_acid", "")
    if acid:
        i = traf.id2idx(acid)
        if isinstance(i, int) and i >= 0:
            r = sim.routes.route(i)
            routedata = {"wplat": list(r.lat), "wplon": list(r.lon),
                         "wpname": list(r.name)}
    # Honor the PAN/ZOOM display state once the user has set it (the
    # reference RadarWidget's pan/zoom); before any PAN/ZOOM command
    # the view auto-fits the scene.
    extent = None
    if getattr(sim.scr, "user_view", False):
        lat0, lat1, lon0, lon1 = sim.scr.getviewbounds()
        # widen lon by the aspect ratio so degrees stay ~square
        c = (lon0 + lon1) / 2.0
        half = (lon1 - lon0) / 2.0 * (W / H)
        extent = (lat0, lat1, c - half, c + half)
    else:
        # Sync the auto-fitted view into the display state, so the
        # FIRST user ZOOM/PAN continues smoothly from what is on
        # screen instead of jumping to the (0,0) default center.
        a = _extent(acdata, sim.scr.objdata)
        sim.scr.ctrlat = (a[0] + a[1]) / 2.0
        sim.scr.ctrlon = (a[2] + a[3]) / 2.0
        sim.scr.scrzoom = 1.0 / max((a[1] - a[0]) / 2.0, 1e-6)
    svg = render_svg(acdata, sim.scr.objdata, routedata,
                     title=f"simt {sim.simt:.1f} s — "
                           f"{len(idx)} aircraft",
                     extent=extent)
    if fname:
        with open(fname, "w") as f:
            f.write(svg)
    return svg


# --------------------------------------------------------------------------
# Navigation display: the reference's per-aircraft heading-up ND
# (ui/qtgl/nd.py:55-282) as an SVG — ownship chevron, the +-60 deg
# wedge with compass ticks, three intermediate range arcs, GS/TAS
# readout, surrounding traffic with relative-altitude tags, and the
# ownship route — selected with the SHOWND stack command.
# --------------------------------------------------------------------------

ND_W = ND_H = 400


def render_nd(sim, acid=None, range_nm=40.0):
    """SVG navigation display for one aircraft (default: SHOWND's)."""
    from ..ops import hostgeo
    acid = acid or getattr(sim.scr, "nd_acid", None)
    traf = sim.traf
    i = traf.id2idx(acid) if acid else -1
    cx, cy = ND_W / 2.0, ND_H * 0.78
    unit = (ND_H * 0.62) / 1.4          # 1.4 ND units = display range
    parts = [
        f'<svg xmlns="http://www.w3.org/2000/svg" width="{ND_W}" '
        f'height="{ND_H}" viewBox="0 0 {ND_W} {ND_H}">',
        f'<rect width="{ND_W}" height="{ND_H}" fill="#000"/>',
    ]
    if not isinstance(i, (int, np.integer)) or i < 0:
        parts.append('<text x="20" y="30" fill="#888" font-size="13">'
                     'ND: no aircraft selected (SHOWND acid)</text>'
                     '</svg>')
        return "\n".join(parts)

    st = traf.state.ac
    olat, olon = float(st.lat[i]), float(st.lon[i])
    otrk = float(st.trk[i])
    ogs, otas = float(st.gs[i]), float(st.tas[i])
    oalt = float(st.alt[i])

    def arc(rad_units, lo=-60, hi=60, color="#ccc"):
        pts = []
        for a in range(lo, hi + 1, 2):
            r = rad_units * unit
            pts.append(f"{cx + r * np.sin(np.radians(a)):.1f},"
                       f"{cy - r * np.cos(np.radians(a)):.1f}")
        return (f'<polyline points="{" ".join(pts)}" fill="none" '
                f'stroke="{color}"/>')

    # wedge edge + intermediate range arcs (nd.py:99-113)
    parts.append(arc(1.4))
    for k in (1, 2, 3):
        parts.append(arc(k * 0.35, color="#444"))
    # compass ticks every 5 deg, heading labels every 30 (nd.py:124-152)
    for a in range(-60, 61, 5):
        hdg = (otrk + a) % 360.0
        big = abs(round(hdg)) % 30 < 2.5
        r0, r1 = 1.4 * unit, (1.46 if big else 1.42) * unit
        sa, ca = np.sin(np.radians(a)), np.cos(np.radians(a))
        parts.append(f'<line x1="{cx + r0 * sa:.1f}" '
                     f'y1="{cy - r0 * ca:.1f}" x2="{cx + r1 * sa:.1f}" '
                     f'y2="{cy - r1 * ca:.1f}" stroke="#ccc"/>')
        if big:
            parts.append(
                f'<text x="{cx + 1.52 * unit * sa:.1f}" '
                f'y="{cy - 1.5 * unit * ca:.1f}" fill="#ccc" '
                f'font-size="11" text-anchor="middle">'
                f'{int(round(hdg / 10.0)) % 36:02d}</text>')
    # GS/TAS readout (nd.py:158-159) + range note
    parts.append(f'<text x="8" y="16" fill="#ccc" font-size="11">GS'
                 f'<tspan fill="#3c3" dx="4">{ogs * 1.94384:.0f}'
                 f'</tspan>  TAS<tspan fill="#3c3" dx="4">'
                 f'{otas * 1.94384:.0f}</tspan></text>')
    parts.append(f'<text x="{ND_W - 8}" y="16" fill="#888" '
                 f'font-size="11" text-anchor="end">{_esc(str(acid))} '
                 f'rng {range_nm:.0f} nm</text>')

    def to_xy(lat, lon):
        qdr, dist = hostgeo.qdrdist(olat, olon, float(lat), float(lon))
        rel = np.radians(float(qdr) - otrk)
        r = float(dist) / range_nm * 1.4 * unit
        return cx + r * np.sin(rel), cy - r * np.cos(rel), float(dist)

    # ownship route, heading-up (the reference copies the route buffers)
    acid_r = getattr(sim.scr, "route_acid", "")
    if acid_r == acid:
        r = sim.routes.route(i)
        pts = []
        for la, lo in zip(r.lat, r.lon):
            x, y, d = to_xy(la, lo)
            if d < range_nm * 1.6:
                pts.append(f"{x:.1f},{y:.1f}")
        if pts:
            parts.append(f'<polyline points="{" ".join(pts)}" '
                         f'fill="none" stroke="{COLORS["route"]}" '
                         f'stroke-dasharray="5 4"/>')

    # surrounding traffic (diamonds + relative altitude, TCAS-style)
    active = np.asarray(st.active)
    inconf = np.asarray(traf.state.asas.inconf)
    for j in np.flatnonzero(active):
        if j == i:
            continue
        x, y, d = to_xy(st.lat[j], st.lon[j])
        if d > range_nm * 1.5:
            continue
        color = COLORS["ac_conf"] if inconf[j] else "#fff"
        parts.append(f'<path d="M{x:.1f},{y - 5:.1f} l5,5 l-5,5 '
                     f'l-5,-5 Z" fill="none" stroke="{color}"/>')
        dalt_fl = (float(st.alt[j]) - oalt) / 0.3048 / 100.0
        parts.append(f'<text x="{x + 7:.1f}" y="{y + 4:.1f}" '
                     f'fill="{color}" font-size="9">'
                     f'{_esc(str(traf.ids[j]))} '
                     f'{"+" if dalt_fl >= 0 else "-"}'
                     f'{abs(dalt_fl):03.0f}</text>')

    # ownship symbol (nd.py:155 vown), fixed heading-up at the focus
    s = unit * 0.09
    parts.append(
        f'<g transform="translate({cx},{cy})" stroke="#ff0" fill="none">'
        f'<line x1="0" y1="0" x2="0" y2="{1.33 * s:.1f}"/>'
        f'<line x1="{-0.72 * s:.1f}" y1="{0.33 * s:.1f}" '
        f'x2="{0.72 * s:.1f}" y2="{0.33 * s:.1f}"/>'
        f'<line x1="{-0.24 * s:.1f}" y1="{1.11 * s:.1f}" '
        f'x2="{0.24 * s:.1f}" y2="{1.11 * s:.1f}"/></g>')
    parts.append("</svg>")
    return "\n".join(parts)


def render_plots(sim, width=640, row_h=160):
    """SVG chart sheet for the live PLOT registry — the headless
    analogue of the reference's matplotlib InfoWindow plot tabs
    (ui/qtgl/infowindow.py:34-109): one panel per PLOT command, drawn
    from the plotter's buffered series."""
    plots = [p for p in getattr(sim.plotter, "plots", [])
             if len(p.series[0]) >= 2]
    h = max(1, len(plots)) * row_h
    parts = [
        f'<svg xmlns="http://www.w3.org/2000/svg" width="{width}" '
        f'height="{h}" viewBox="0 0 {width} {h}">',
        f'<rect width="{width}" height="{h}" fill="{BG}"/>',
    ]
    if not plots:
        parts.append('<text x="16" y="28" fill="#888" font-size="12">'
                     'no plots — use e.g. PLOT simt ac.tas[0] 1'
                     '</text></svg>')
        return "\n".join(parts)
    m = 36                                   # panel margin

    def as_curve(samples):
        """Robust per-sample scalarization: unindexed PLOT variables
        buffer a (possibly ragged) vector per sample — chart the mean."""
        return np.array([float(np.mean(np.asarray(v, float)))
                         if np.size(v) else np.nan for v in samples])

    for k, p in enumerate(plots):
        xs = as_curve(p.series[0])
        ys = as_curve(p.series[1])
        keep = np.isfinite(xs) & np.isfinite(ys)
        xs, ys = xs[keep], ys[keep]
        y0 = k * row_h
        if len(xs) < 2:
            continue
        # more than ~2 samples per pixel is invisible: stride-downsample
        # so an hours-long fast-time run cannot bloat the sheet
        stride = max(1, len(xs) // (2 * (width - 2 * m)))
        xs, ys = xs[::stride], ys[::stride]
        x_lo, x_hi = float(xs.min()), float(xs.max())
        y_lo, y_hi = float(ys.min()), float(ys.max())
        xs_n = (xs - x_lo) / max(x_hi - x_lo, 1e-9)
        ys_n = (ys - y_lo) / max(y_hi - y_lo, 1e-9)
        px = m + xs_n * (width - 2 * m)
        py = y0 + row_h - m - ys_n * (row_h - 2 * m)
        pts = " ".join(f"{x:.1f},{y:.1f}" for x, y in zip(px, py))
        color = quoteattr(str(p.color or "#3c3"))
        parts += [
            f'<rect x="{m}" y="{y0 + m}" width="{width - 2 * m}" '
            f'height="{row_h - 2 * m}" fill="none" stroke="#334"/>',
            f'<polyline points="{pts}" fill="none" stroke={color} '
            f'stroke-width="1.5"/>',
            f'<text x="{m}" y="{y0 + m - 6}" fill="#9fd49f" '
            f'font-size="11">fig {p.fig}: '
            f'{_esc(p.y.varname)} vs {_esc(p.x.varname)}</text>',
            f'<text x="{m}" y="{y0 + row_h - m + 14}" fill="#678" '
            f'font-size="9">{x_lo:.4g}</text>',
            f'<text x="{width - m}" y="{y0 + row_h - m + 14}" '
            f'fill="#678" font-size="9" text-anchor="end">'
            f'{x_hi:.4g}</text>',
            f'<text x="{m - 4}" y="{y0 + row_h - m}" fill="#678" '
            f'font-size="9" text-anchor="end">{y_lo:.4g}</text>',
            f'<text x="{m - 4}" y="{y0 + m + 10}" fill="#678" '
            f'font-size="9" text-anchor="end">{y_hi:.4g}</text>',
        ]
    parts.append("</svg>")
    return "\n".join(parts)
