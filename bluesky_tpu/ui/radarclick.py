"""Radar-click to command-line completion (reference ui/radarclick.py:10-191).

Translates a click at (lat, lon) on the radar into text appended to the
current command line — the nearest aircraft id, the clicked position, a
heading from the current reference point, the nearest airport, or the
nearest waypoint in the subject aircraft's route — driven by a per-command
click-argument signature table.  When the clicked argument completes the
command, the full line is returned for the stack.

Redesign notes: the reference reads the ``bs.traf``/``bs.navdb`` singletons
and the stack's module-level synonym dict; here everything is passed in via
the owning ``Simulation`` (no globals), and the nearest-point searches are
NumPy argmin over the flat-earth metric like the reference's
``tools.misc.findnearest``.
"""
import math

import numpy as np

#: Which argument positions are clickable, per command
#: (reference radarclick.py:16-59; "-" = not clickable, "..." = the
#: one-but-last repeats, e.g. polygon vertices).
CLICKCMD = {
    "": "acid,-",
    "ADDWPT": "acid,latlon,-,-,wpinroute,-",
    "AFTER": "acid,wpinroute,-",
    "AT": "acid,wpinroute,-",
    "ALT": "acid,-",
    "AREA": "latlon,-,latlon",
    "ASAS": "acid,-",
    "BOX": "-,latlon,-,latlon",
    "CIRCLE": "-,latlon,-,dist",
    "CRE": "-,-,latlon,-,hdg,-,-",
    "DEFWPT": "-,latlon,-",
    "DEL": "acid,-",
    "DELWPT": "acid,wpinroute,-",
    "DELRTE": "acid,-",
    "DEST": "acid,apt",
    "DIRECT": "acid,wpinroute",
    "DIST": "latlon,-,latlon",
    "DUMPRTE": "acid",
    "ENG": "acid,-",
    "GETWIND": "latlon,-",
    "HDG": "acid,hdg",
    "LINE": "-,latlon,-,latlon",
    "LISTRTE": "acid,-",
    "LNAV": "acid,-",
    "MOVE": "acid,latlon,-,-,hdg",
    "NAVDISP": "acid",
    "NOM": "acid",
    "ND": "acid",
    "ORIG": "acid,apt",
    "PAN": "latlon",
    "POLY": "-,latlon,...",
    "POLYALT": "-,-,-,latlon,...",
    "POLYGON": "-,latlon,...",
    "POLYLINE": "-,latlon,...",
    "POS": "acid",
    "SSD": "acid,...",
    "SPD": "acid,-",
    "TRAIL": "acid,-",
    "VNAV": "acid,-",
    "VS": "acid,-",
    "WIND": "latlon,-",
    "WINDGFS": "latlon,-,latlon,-",
}


def findnearest(lat, lon, latarr, lonarr):
    """Index of the nearest point, flat-earth metric (reference
    tools/misc.py findnearest); -1 when the arrays are empty."""
    latarr = np.asarray(latarr, float)
    lonarr = np.asarray(lonarr, float)
    if latarr.size == 0:
        return -1
    d2 = (latarr - lat) ** 2 \
        + (np.cos(np.radians(lat)) * (lonarr - lon)) ** 2
    return int(np.argmin(d2))


def _live(sim):
    """(ids, lats, lons) of live aircraft with their slots."""
    slots = [s for s, i in enumerate(sim.traf.ids) if i is not None]
    ids = [sim.traf.ids[s] for s in slots]
    lat = np.asarray(sim.traf.state.ac.lat)[slots]
    lon = np.asarray(sim.traf.state.ac.lon)[slots]
    return slots, ids, lat, lon


def radarclick(cmdline, lat, lon, sim):
    """Process a click at (lat, lon) given the current command line.

    Returns ``(tostack, todisplay)``: text to send to the stack (when the
    click completes the command) and text to append to the visible command
    line ('\\n' = clear).  Mirrors reference radarclick.py:60-191.
    """
    todisplay = ""
    tostack = ""

    # Tokenize the way the stack does (commas AND spaces, reference
    # tools/misc.cmdsplit): a clicked "lat,lon " insertion counts as TWO
    # arguments, so multi-click commands (BOX/AREA/LINE/CRE...) advance
    # to the right click-argument.
    from ..stack.argparser import cmdsplit
    parts = cmdsplit(cmdline)
    cmd = parts[0].upper() if parts else ""
    args = parts[1:]
    numargs = len(args)

    slots, ids, aclat, aclon = _live(sim)

    # Double click on an aircraft label: POS command (radarclick.py:77-80)
    if numargs == 0 and cmd in ids:
        return "POS " + cmd, "\n"

    cmd = sim.stack.synonyms.get(cmd, cmd)
    lookup = CLICKCMD.get(cmd)
    if not lookup:
        return "", ""

    if cmdline and cmdline[-1] not in (" ", ","):
        todisplay = " "

    clickargs = lookup.lower().split(",")
    totargs = len(clickargs)
    curarg = numargs
    if clickargs[-1] == "...":        # repeating vertex argument
        totargs = 999
        curarg = min(curarg, len(clickargs) - 2)
    if curarg >= totargs:
        return "", ""
    clicktype = clickargs[curarg]

    if clicktype == "acid":
        idx = findnearest(lat, lon, aclat, aclon)
        if idx >= 0:
            todisplay += ids[idx] + " "

    elif clicktype == "latlon":
        todisplay += f"{round(lat, 6)},{round(lon, 6)} "

    elif clicktype == "dist":
        from ..ops import geo
        try:
            latref, lonref = float(args[1]), float(args[2])
        except (IndexError, ValueError):
            return "", ""
        d = float(geo.kwikdist(latref, lonref, lat, lon))
        todisplay += str(round(d, 6))

    elif clicktype == "apt":
        navdb = getattr(sim, "navdb", None)
        if navdb is None or len(navdb.aptid) == 0:
            return "", ""
        idx = findnearest(lat, lon, navdb.aptlat, navdb.aptlon)
        if idx >= 0:
            todisplay += navdb.aptid[idx] + " "

    elif clicktype == "wpinroute":
        if not args or args[0].upper() not in ids:
            return "", ""
        slot = sim.traf.id2idx(args[0])
        r = sim.routes.route(slot)
        if r.nwp == 0:
            return "", ""
        iwp = findnearest(lat, lon, r.lat, r.lon)
        if iwp >= 0:
            todisplay += r.name[iwp] + " "

    elif clicktype == "hdg":
        # Heading from a command-specific reference point
        # (radarclick.py:155-183)
        try:
            if cmd == "CRE":
                reflat, reflon = float(args[2]), float(args[3])
            elif cmd == "MOVE":
                reflat, reflon = float(args[1]), float(args[2])
            else:
                if not args or args[0].upper() not in ids:
                    return "", ""
                slot = sim.traf.id2idx(args[0])
                ac = sim.traf.state.ac
                reflat = float(np.asarray(ac.lat)[slot])
                reflon = float(np.asarray(ac.lon)[slot])
        except (IndexError, ValueError):
            return "", ""
        dy = lat - reflat
        dx = (lon - reflon) * math.cos(math.radians(reflat))
        hdg = math.degrees(math.atan2(dx, dy)) % 360.0
        todisplay += str(int(hdg)) + " "

    # Last argument clicked: complete the command (radarclick.py:186-189)
    if curarg + 1 >= totargs:
        tostack = cmdline + todisplay
        todisplay += "\n"
    return tostack, todisplay
