"""Headless console: command-line editing state, history, autocomplete.

The reference console is a Qt widget (ui/qtgl/console.py:49-184) with the
command-line/history/autocomplete logic interleaved with Qt key events;
here that logic is a plain object driving any frontend (the text client in
``__main__``, tests, or a future GUI), and the IC/BATCH scenario-filename
autocompletion (ui/qtgl/autocomplete.py:20-56) cycles through matches the
same way.
"""
import glob
import os
from typing import Callable, List, Optional


def iglob(pattern):
    """Case-insensitive glob (reference autocomplete.py:11-15)."""
    def either(c):
        return f"[{c.lower()}{c.upper()}]" if c.isalpha() else c
    return sorted(glob.glob("".join(map(either, pattern))))


class Autocomplete:
    """IC/BATCH scenario filename completion, cycling through matches."""

    def __init__(self, scenario_path: str = "scenario"):
        self.scenario_path = scenario_path
        self._previous = ""

    def reset(self):
        self._previous = ""

    def complete(self, cmdline: str):
        """(newcmd, displaytext): completed line + candidates hint
        (reference autocomplete.py:23-56)."""
        parts = cmdline.upper().split()
        if not parts or parts[0] not in ("IC", "BATCH"):
            return cmdline, ""
        g = self.scenario_path
        if not g.endswith(os.sep):
            g += os.sep
        striplen = len(g)
        if len(parts) == 2 and not self._previous:
            g += parts[1].strip()
        elif self._previous:
            g = self._previous
        self._previous = g
        files = iglob(g + "*")
        if not files:
            return cmdline, ""
        if len(files) == 1:
            return f"{parts[0]} {files[0][striplen:]}", ""
        # Common prefix + candidate list
        prefix = os.path.commonprefix(files)
        display = ", ".join(f[striplen:] for f in files[:20])
        return f"{parts[0]} {prefix[striplen:]}", display


class Console:
    """Command-line state machine (reference console.py:49-184).

    ``stack_fn`` receives completed command lines; ``echo_fn`` (optional)
    receives display text (autocomplete candidate lists).
    """

    def __init__(self, stack_fn: Callable[[str], None],
                 echo_fn: Optional[Callable[[str], None]] = None,
                 scenario_path: str = "scenario"):
        self.stack_fn = stack_fn
        self.echo_fn = echo_fn or (lambda _t: None)
        self.command_line = ""
        self.command_history: List[str] = []
        self.history_pos = 0
        self.command_mem = ""
        self.autocomplete = Autocomplete(scenario_path)

    # ------------------------------------------------------------ editing
    def set_cmdline(self, text: str):
        """Replace the command line; any edit invalidates the cached
        autocomplete glob (Tab must match the text now on the line)."""
        self.command_line = text
        self.autocomplete.reset()

    def append_cmdline(self, text: str):
        """Append text (radarclick output); '\\n' submits/clears
        (reference console.py:100-101 + mainwindow radarclick wiring)."""
        if text.endswith("\n"):
            self.command_line = ""
        else:
            self.command_line += text
        self.autocomplete.reset()     # line changed: stale glob invalid

    def stack(self, text: Optional[str] = None):
        """Submit a command line (reference console.py:82-92)."""
        text = self.command_line if text is None else text
        if not text.strip():
            return
        self.command_history.append(text)
        self.stack_fn(text)
        self.command_line = ""
        self.history_pos = 0
        self.autocomplete.reset()

    # ----------------------------------------------------------- keys
    def key_enter(self):
        self.stack()

    def key_up(self):
        """History back (reference console.py:140-146)."""
        if self.history_pos == 0:
            self.command_mem = self.command_line
        if len(self.command_history) >= self.history_pos + 1:
            self.history_pos += 1
            self.command_line = self.command_history[-self.history_pos]
            self.autocomplete.reset()

    def key_down(self):
        """History forward (reference console.py:148-156)."""
        if self.history_pos > 0:
            self.history_pos -= 1
            self.command_line = self.command_mem if self.history_pos == 0 \
                else self.command_history[-self.history_pos]
            self.autocomplete.reset()

    def key_tab(self):
        """Filename autocomplete for IC/BATCH (reference console.py:158+)."""
        if self.command_line:
            newcmd, display = self.autocomplete.complete(self.command_line)
            self.command_line = newcmd
            if display:
                self.echo_fn(display)

    def key_backspace(self):
        self.command_line = self.command_line[:-1]
        self.autocomplete.reset()

    def key_char(self, ch: str):
        self.command_line += ch
        self.autocomplete.reset()
