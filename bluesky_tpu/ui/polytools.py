"""Polygon triangulation for filled shapes (reference ui/polytools.py).

The reference tessellates polygons with OpenGL GLU's tessellator
(polytools.py:16-26) into a triangle vertex buffer for the GL fill pass.
This framework draws headless (SVG/streams) but keeps the same capability
— a contour set to triangle buffer — with a pure-NumPy ear-clipping
triangulator instead of GLU, so filled AREA/POLY shapes can be rendered
by any backend (and tested without a GL context).

API mirrors the reference ``PolygonSet``: ``addContour`` accumulates
contours of the current polygon, ``bufsize``/``vbuf`` expose the triangle
buffer (flat [x0,y0, x1,y1, ...] like the GLU vertex callback produced).
Holes (nested contours) are not supported — the reference's use sites
(areafilter shapes, coastline fills) pass simple contours.
"""
from typing import List

import numpy as np


def _signed_area(pts):
    x, y = pts[:, 0], pts[:, 1]
    return 0.5 * float(np.sum(x * np.roll(y, -1) - np.roll(x, -1) * y))


def _any_point_in_tri(pts, a, b, c, eps=1e-12):
    """True if ANY of pts [k,2] lies inside/on triangle (a,b,c) —
    vectorized so the ear test is O(n) NumPy, not O(n) Python."""
    if len(pts) == 0:
        return False

    def cross(o, u, v):
        return (u[0] - o[0]) * (v[:, 1] - o[1]) \
            - (u[1] - o[1]) * (v[:, 0] - o[0])

    d1 = cross(a, b, pts)
    d2 = cross(b, c, pts)
    d3 = cross(c, a, pts)
    # Callers only test strictly convex CCW ears, so inside/on-edge is
    # "no edge sees the point on its right": all three cross products
    # non-negative (within eps).  A mixed-sign point is strictly outside
    # and must NOT veto the ear (collinear-vertex polygons would
    # otherwise bail early with a partial triangle buffer).
    inside = (d1 >= -eps) & (d2 >= -eps) & (d3 >= -eps)
    return bool(np.any(inside))


def earclip(contour) -> List[float]:
    """Triangulate a simple polygon; returns flat [x,y]*3 per triangle.

    contour: iterable of (x, y) or flat [x0, y0, x1, y1, ...].
    """
    pts = np.asarray(contour, float)
    if pts.ndim == 1:
        pts = pts.reshape(-1, 2)
    # Drop consecutive duplicates (incl. a closing repeat of the start)
    keep = np.ones(len(pts), bool)
    keep[1:] = np.any(pts[1:] != pts[:-1], axis=1)
    pts = pts[keep]
    if len(pts) > 1 and np.all(pts[0] == pts[-1]):
        pts = pts[:-1]
    n = len(pts)
    if n < 3:
        return []
    if _signed_area(pts) < 0.0:          # enforce CCW winding
        pts = pts[::-1]

    idx = list(range(n))
    tris: List[float] = []
    guard = 0
    while len(idx) > 3 and guard < 2 * n * n:
        guard += 1
        ear_found = False
        for k in range(len(idx)):
            i0, i1, i2 = (idx[k - 1], idx[k], idx[(k + 1) % len(idx)])
            a, b, c = pts[i0], pts[i1], pts[i2]
            # Convex corner?
            if (b[0] - a[0]) * (c[1] - a[1]) \
                    - (b[1] - a[1]) * (c[0] - a[0]) <= 0.0:
                continue
            # No other active vertex inside the candidate ear
            others = pts[[j for j in idx if j not in (i0, i1, i2)]]
            if _any_point_in_tri(others, a, b, c):
                continue
            tris.extend([*a, *b, *c])
            del idx[k]
            ear_found = True
            break
        if not ear_found:     # degenerate (self-intersecting) remainder
            break
    if len(idx) == 3:
        a, b, c = pts[idx[0]], pts[idx[1]], pts[idx[2]]
        tris.extend([*a, *b, *c])
    return tris


class PolygonSet:
    """Contour collection -> triangle vertex buffer (reference
    polytools.py:6-121, GLU tessellator replaced by ear clipping)."""

    def __init__(self):
        self.vbuf: List[float] = []

    def bufsize(self) -> int:
        return len(self.vbuf)

    def addContour(self, contour):
        """Triangulate one closed contour into the buffer."""
        self.vbuf.extend(earclip(contour))

    # The reference's begin/end/beginContour/endContour manage GLU
    # tessellator state; with ear clipping they are no-ops kept for
    # call-site compatibility.
    def begin(self):
        pass

    def end(self):
        pass

    def beginContour(self):
        pass

    def endContour(self):
        pass
