"""Colour palette registry (reference ui/palette.py).

The reference ``exec()``s an arbitrary Python palette file into module
globals (palette.py:8-15) — arbitrary code execution for a colour table.
Here a palette file is plain ``name = (r, g, b)`` lines parsed with
``ast.literal_eval`` (data, not code), and defaults are registered
per-module via ``set_default_colours`` exactly like the reference
(palette.py:18-30) so every colour consumer declares what it needs.
"""
import ast
import os
from typing import Dict, Tuple

Colour = Tuple[int, int, int]

_colours: Dict[str, Colour] = {}


def set_default_colours(**kwargs):
    """Register default colour values; the loaded palette wins
    (reference palette.py:18-30)."""
    for key, value in kwargs.items():
        _colours.setdefault(key, tuple(value))


def get(name: str, default: Colour = (255, 255, 255)) -> Colour:
    return _colours.get(name, default)


def __getattr__(name: str):
    # palette.aircraft etc., mirroring the reference's module-global style
    if name.startswith("_"):
        raise AttributeError(name)
    try:
        return _colours[name]
    except KeyError:
        raise AttributeError(f"no colour {name!r} in palette") from None


def load(pfile: str) -> bool:
    """Load ``name = (r, g, b)`` assignments from a palette file."""
    if not os.path.isfile(pfile):
        return False
    with open(pfile) as f:
        for line in f:
            line = line.split("#", 1)[0].strip()
            if not line or "=" not in line:
                continue
            key, _, val = line.partition("=")
            try:
                rgb = ast.literal_eval(val.strip())
            except (ValueError, SyntaxError):
                continue
            if (isinstance(rgb, tuple) and len(rgb) == 3
                    and all(isinstance(c, int) for c in rgb)):
                _colours[key.strip()] = rgb
    return True


# Default radar colours (reference data/graphics/palettes/bluesky-default)
set_default_colours(
    aircraft=(0, 255, 0),
    conflict=(255, 160, 0),
    route=(255, 0, 255),
    trails=(0, 255, 255),
    aptlabel=(220, 250, 255),
    wptlabel=(220, 250, 255),
    polys=(0, 0, 255),
    previewpoly=(0, 204, 255),
    coastlines=(85, 85, 115),
    background=(0, 0, 0),
)
