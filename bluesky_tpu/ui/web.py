"""Live browser frontend: the reference's radar-view UX, headlessly.

The reference's flagship user experience is a live Qt-OpenGL radar
window (``bluesky/ui/qtgl/radarwidget.py:115-1031``) with a command line
(``mainwindow.py:93-399``).  This module serves the same picture to a
web browser instead of a GL context: a tiny stdlib HTTP server streams
the existing SVG radar frames (``ui/radar.py`` — the same renderer the
SCREENSHOT command uses) over Server-Sent Events at a few Hz, and a
command box posts stack commands back, so a user can *watch* moving
traffic and fly the sim from any browser with zero dependencies.

Two backends plug in behind one ``WebUI`` facade:
  * an embedded :class:`~bluesky_tpu.simulation.sim.Simulation`
    (``python -m bluesky_tpu --web``), rendered from live state;
  * a connected :class:`~bluesky_tpu.network.guiclient.GuiClient`,
    rendered from its ACDATA/ROUTEDATA nodeData mirror — the same
    client path the reference GUI consumes (screenio.py:18-21 streams).

Threading: the HTTP server runs daemon threads, but host-side Traffic
state (the ids list, routes, array replacement between chunks) is only
consistent on the sim thread.  ``SimBackend.pump()`` therefore renders
the frame *on the sim thread* between chunks and caches it; server
threads serve the cached frame, so they never read sim state mid-
mutation and N connected viewers cost one render, not N.  Stack
commands are queued to the owner loop the same way.  When no loop is
pumping (tests, ad-hoc embedding) ``frame()`` falls back to rendering
directly, which is safe only because nothing else is stepping the sim.
"""
import json
import queue
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

_PAGE = """<!DOCTYPE html>
<html><head><title>bluesky_tpu radar</title><style>
 body { background:#10141c; color:#9fd49f; font-family:monospace;
        margin:0; display:flex; flex-direction:column; height:100vh; }
 #radar { flex:1; display:flex; align-items:center;
          justify-content:center; overflow:hidden; cursor:crosshair; }
 #radar svg { max-width:100%; max-height:100%; }
 #bar { display:flex; padding:6px; background:#181e2a; }
 #cmd { flex:1; background:#0c0f16; color:#d0e8d0; border:1px solid
        #334; font-family:monospace; padding:4px 8px; }
 #echo { height:9em; overflow-y:auto; background:#0c0f16;
         padding:4px 8px; font-size:12px; white-space:pre-wrap; }
 #info { padding:2px 8px; color:#678; font-size:12px; }
 #nd { position:fixed; top:8px; right:8px; width:280px; height:280px;
       display:none; border:1px solid #334; background:#000; }
 #nd svg { width:100%; height:100%; }
</style></head><body>
 <div id="radar">connecting&hellip;</div>
 <div id="nd"></div>
 <div id="info"></div>
 <div id="bar"><input id="cmd" autofocus placeholder="stack command
 (CRE KL204 B744 52 4 90 FL200 250 / OP / FF 60 ...) &mdash; click the
 map to fill position/aircraft args, drag to pan, wheel to zoom"/></div>
 <div id="echo"></div>
<script>
 const radar = document.getElementById('radar');
 const info = document.getElementById('info');
 const echo = document.getElementById('echo');
 const cmd = document.getElementById('cmd');
 const nd = document.getElementById('nd');
 const es = new EventSource('/events');
 es.onmessage = ev => {
   const d = JSON.parse(ev.data);
   if (d.svg) radar.innerHTML = d.svg;
   if (d.info) info.textContent = d.info;
   if (d.nd) { nd.innerHTML = d.nd; nd.style.display = 'block'; }
   else nd.style.display = 'none';
 };
 function pushEcho(line, t) {
   echo.textContent = '> ' + line + '\\n' + (t || '') + '\\n'
     + echo.textContent;
 }
 async function sendCmd(line) {
   const r = await fetch('/cmd', {method:'POST', body: line});
   pushEcho(line, await r.text());
 }
 const hist = []; let hidx = -1;
 cmd.addEventListener('keydown', async ev => {
   if (ev.key === 'Enter' && cmd.value.trim()) {
     const line = cmd.value.trim(); hist.unshift(line); hidx = -1;
     cmd.value = '';
     await sendCmd(line);
   } else if (ev.key === 'ArrowUp') {
     hidx = Math.min(hidx + 1, hist.length - 1);
     if (hidx >= 0) cmd.value = hist[hidx];
   } else if (ev.key === 'ArrowDown') {
     hidx = Math.max(hidx - 1, -1);
     cmd.value = hidx >= 0 ? hist[hidx] : '';
   } else if (ev.key === 'Tab') {
     ev.preventDefault();              // command/filename completion
     const r = await fetch('/complete', {method:'POST', body: cmd.value});
     const out = await r.json();
     if (out.line) cmd.value = out.line;
     if (out.hint) pushEcho('?', out.hint);
   }
 });

 // ---- radar interaction: click-to-command, drag-pan, wheel-zoom ----
 function svgEl() { return radar.querySelector('svg'); }
 function extent() {
   const s = svgEl(); if (!s) return null;
   const e = (s.dataset.extent || '').split(',').map(Number);
   return e.length === 4 && e.every(isFinite) ? e : null;
 }
 function toLatLon(ev) {
   const s = svgEl(); const e = extent();
   if (!s || !e) return null;
   const r = s.getBoundingClientRect();
   const fx = (ev.clientX - r.left) / r.width;
   const fy = (ev.clientY - r.top) / r.height;
   return [e[1] - fy * (e[1] - e[0]), e[2] + fx * (e[3] - e[2])];
 }
 let drag = null;
 radar.addEventListener('mousedown', ev => {
   drag = {x: ev.clientX, y: ev.clientY, moved: false};
 });
 radar.addEventListener('mousemove', ev => {
   if (drag && Math.abs(ev.clientX - drag.x)
             + Math.abs(ev.clientY - drag.y) > 6) drag.moved = true;
 });
 radar.addEventListener('mouseup', async ev => {
   const d = drag; drag = null;
   const s = svgEl(); const e = extent();
   if (!s || !e) return;
   const r = s.getBoundingClientRect();
   if (d && d.moved) {           // drag -> PAN the view center
     const clat = (e[0] + e[1]) / 2
       + (ev.clientY - d.y) / r.height * (e[1] - e[0]);
     const clon = (e[2] + e[3]) / 2
       - (ev.clientX - d.x) / r.width * (e[3] - e[2]);
     await sendCmd('PAN ' + clat.toFixed(4) + ',' + clon.toFixed(4));
     return;
   }
   const ll = toLatLon(ev); if (!ll) return;
   const resp = await fetch('/click', {method:'POST',
     body: JSON.stringify({line: cmd.value, lat: ll[0], lon: ll[1]})});
   const out = await resp.json();
   if (out.tostack) pushEcho(out.tostack, out.echo);
   const td = out.todisplay || '';
   // a trailing newline means the command completed (it already ran
   // server-side): clear the line instead of leaving stale text
   if (td.endsWith('\\n')) cmd.value = '';
   else cmd.value += td;
   cmd.focus();
 });
 let wheelTimer = null, wheelDir = 0;
 radar.addEventListener('wheel', ev => {
   ev.preventDefault();
   wheelDir = ev.deltaY < 0 ? 1 : -1;   // one ZOOM per gesture window
   if (wheelTimer) return;
   wheelTimer = setTimeout(() => {
     wheelTimer = null;
     sendCmd(wheelDir > 0 ? 'ZOOM IN' : 'ZOOM OUT');
   }, 200);
 }, {passive: false});
</script></body></html>
"""


def _complete_line(line, stack=None, fileac=None):
    """Shared Tab-completion: {"line": completed, "hint": candidates}.

    First word incomplete -> command-name completion against the stack
    dictionary (when available); IC/BATCH -> scenario filename cycling
    via ui/console.Autocomplete.  ``fileac`` carries the caller's
    Autocomplete instance so repeated Tab presses CYCLE (its _previous
    glob state must survive between requests — a fresh instance per
    request would re-complete the same common prefix forever)."""
    from . import console
    words = line.split()
    # filename completion only while the filename is being typed; a
    # line that already has a filename + further args passes through
    if words and words[0].upper() in ("IC", "BATCH") and len(words) <= 2:
        from .. import settings
        ac = fileac if fileac is not None \
            else console.Autocomplete(settings.scenario_path)
        newline, hint = ac.complete(line)
        return {"line": newline, "hint": hint}
    if stack is not None and line and " " not in line:
        frag = line.upper()
        # snapshot: the sim thread may register/remove plugin commands
        # concurrently (stack.append_commands/remove_commands)
        names = sorted(n for n in list(stack.cmddict)
                       if n.startswith(frag))
        if not names:
            return {"line": line, "hint": ""}
        if len(names) == 1:
            return {"line": names[0] + " ", "hint": ""}
        import os
        prefix = os.path.commonprefix(names)
        return {"line": prefix, "hint": ", ".join(names[:20])}
    return {"line": line, "hint": ""}


_FILEAC_INIT_LOCK = threading.Lock()


def _backend_complete(backend, line, stack=None):
    """Per-backend completion holding ONE Autocomplete across requests
    (reset when the typed line is not the one we last emitted, so a
    fresh user edit restarts the cycle — reference autocomplete.py
    semantics).  complete() runs on ThreadingHTTPServer handler
    threads, so the shared cycling state is lock-guarded; like the
    reference console there is ONE completion context per backend —
    two browsers Tab-completing different lines at once take turns
    resetting it, which is harmless (each reset just restarts that
    line's cycle)."""
    from . import console
    from .. import settings
    with _FILEAC_INIT_LOCK:
        lock = getattr(backend, "_fileac_lock", None)
        if lock is None:
            lock = backend._fileac_lock = threading.Lock()
    with lock:
        ac = getattr(backend, "_fileac", None)
        if ac is None:
            ac = console.Autocomplete(settings.scenario_path)
            backend._fileac = ac
            backend._fileac_last = None
        if line != backend._fileac_last:
            ac.reset()
        res = _complete_line(line, stack, fileac=ac)
        backend._fileac_last = res["line"]
        return res


class SimBackend:
    """Frame/command adapter over an embedded Simulation."""

    def __init__(self, sim):
        self.sim = sim
        self._pending = queue.Queue()
        self._frame = None               # (svg, info) cached by pump()
        self._nd = None                  # ND svg when SHOWND active
        self._plots = None               # plot sheet when PLOTs exist
        self.render_period = 0.25        # cache refresh cap (s)
        self._last_render = 0.0
        self._last_request = 0.0         # last frame() call (viewer pull)

    def _render(self):
        from . import radar
        svg = radar.render_sim(self.sim)
        # per-aircraft navigation display when SHOWND selected one
        self._nd = radar.render_nd(self.sim) \
            if getattr(self.sim.scr, "nd_acid", None) else None
        # live plot sheet (the InfoWindow analogue), only when plots run
        self._plots = radar.render_plots(self.sim) \
            if getattr(self.sim.plotter, "plots", None) else None
        return svg, (f"simt {float(self.sim.simt):8.1f} s   "
                     f"ntraf {self.sim.traf.ntraf}   "
                     f"state {self.sim.state_flag}")

    def nd_frame(self):
        return self._nd

    def frame(self):
        """Latest frame; served from the sim-thread cache when a loop is
        pumping, rendered in place otherwise (idle sim only)."""
        self._last_request = time.monotonic()
        cached = self._frame
        return cached if cached is not None else self._render()

    def command(self, line):
        """Queue a stack command; executed by the sim loop via pump()."""
        return self._submit("cmd", line, "(queued)")

    def click(self, line, lat, lon):
        """Radar click -> command completion (ui/radarclick.py), run on
        the sim thread like any command (it reads live traffic state)."""
        return self._submit("click", (line, lat, lon),
                            {"tostack": "", "todisplay": "", "echo": ""})

    def _submit(self, kind, payload, timeout_result):
        done = queue.Queue()
        self._pending.put((kind, payload, done))
        try:
            return done.get(timeout=5.0)
        except queue.Empty:
            return timeout_result

    def _run_cmd(self, line):
        self.sim.scr.echobuf.clear()
        self.sim.stack.stack(line)
        self.sim.stack.process()
        return "\n".join(self.sim.scr.echobuf)

    def complete(self, line):
        """Tab completion: command names from the live dictionary,
        IC/BATCH scenario filenames through the console's Autocomplete
        engine (ui/console.py — the reference console's Tab behavior).
        Reads stable dicts/the filesystem plus the lock-guarded
        completion-cycle state, so it is safe off the sim thread."""
        return _backend_complete(self, line, self.sim.stack)

    def pump(self):
        """Run queued commands and refresh the frame cache — called on
        the sim thread between chunks, the only place state is stable."""
        from . import radarclick
        ran_cmd = False
        while True:
            try:
                kind, payload, done = self._pending.get_nowait()
            except queue.Empty:
                break
            if kind == "cmd":
                done.put(self._run_cmd(payload))
            else:                           # radar click
                line, lat, lon = payload
                tostack, todisplay = radarclick.radarclick(
                    line, lat, lon, self.sim)
                out = {"tostack": tostack, "todisplay": todisplay,
                       "echo": ""}
                if tostack:
                    out["echo"] = self._run_cmd(tostack)
                done.put(out)
            ran_cmd = True
        now = time.monotonic()
        # Refresh at most at render_period and only while a viewer is
        # actually pulling frames (no browser connected -> the sim
        # thread pays nothing); always refresh right after a command —
        # the user who just typed CRE expects to see it.
        wanted = self._frame is None \
            or now - self._last_request < 3.0 * max(self.render_period, 1.0)
        if ran_cmd or (wanted
                       and now - self._last_render >= self.render_period):
            self._last_render = now
            try:
                self._frame = self._render()
            except Exception:
                pass     # keep the last good frame; a render bug must
                         # not take down the sim loop it rides on


class ClientBackend:
    """Frame/command adapter over a connected GuiClient.

    Threading: ZMQ sockets are not thread-safe, so ONLY the thread
    calling ``pump()`` may touch the client socket.  HTTP threads queue
    commands here exactly like SimBackend; ``pump()`` (the attach
    loop's thread) executes them and drains the streams.  When nothing
    is pumping (ad-hoc embedding/tests) ``command()`` falls back to
    running inline, which is safe only single-threaded."""

    #: gesture/flow commands that succeed silently — don't hold the
    #: pump thread waiting for an ECHO that never comes
    _SILENT = {"PAN", "ZOOM", "OP", "HOLD", "PAUSE", "FF", "DTMULT"}

    def __init__(self, client, pumped=False):
        """``pumped=True`` declares up front that a pump loop will own
        the socket (run_web --attach), closing the startup window where
        an early HTTP command could race the loop on the ZMQ socket."""
        self.client = client
        self._pending = queue.Queue()
        self._pumping = pumped
        self._frame = None               # cached by pump()
        self._nd = None                  # ND cache (when SHOWND active)
        self.render_period = 0.25
        self._last_render = 0.0

    def _render(self):
        svg = self.client.render_svg()
        nd = self.client.get_nodedata()
        n = len(nd.acdata.get("id", [])) if nd.acdata else 0
        return svg, f"ntraf {n}   node {self.client.act or '-'}"

    def frame(self):
        """Serve the pump-thread frame cache (nodeData mutates on the
        pump thread mid-receive; rendering there keeps reads
        consistent).  Inline render only when nothing is pumping."""
        cached = self._frame
        if cached is not None:
            return cached
        return self._render()

    def command(self, line):
        if not self._pumping:
            return self._run_cmd(line)
        done = queue.Queue()
        self._pending.put((line, done))
        try:
            return done.get(timeout=8.0)
        except queue.Empty:
            return "(queued)"

    def _run_cmd(self, line):
        """Execute on the socket-owning thread only."""
        nd = self.client.get_nodedata()
        n0 = len(nd.echo_text)
        self.client.stack(line)
        # ECHO rides the event socket; the node replies between scan
        # chunks, which can lag while a chunk computes/compiles.  Known
        # no-echo gestures only get a token wait so drag-pan/zoom stay
        # snappy; anything else waits long enough to catch its reply.
        word = line.split()[0].upper() if line.split() else ""
        wait = 0.2 if word in self._SILENT else 2.5
        deadline = time.monotonic() + wait
        while time.monotonic() < deadline and len(nd.echo_text) == n0:
            self.client.receive(20)
        return "\n".join(nd.echo_text[n0:])

    def click(self, line, lat, lon):
        """Client mode has no live Simulation for the full radarclick
        logic; insert the clicked position (the most common argument)."""
        return {"tostack": "", "echo": "",
                "todisplay": f"{lat:.4f},{lon:.4f} "}

    def complete(self, line):
        return _backend_complete(self, line)   # filename completion only

    def nd_frame(self):
        """Client-side ND: served from the pump-thread cache like
        frame() (nodeData mutates on the pump thread); inline render
        only when nothing is pumping."""
        if self._pumping:
            return self._nd
        return self._render_nd()

    def _render_nd(self):
        from . import radar
        nd = self.client.get_nodedata()
        if not getattr(nd, "nd_acid", None):
            return None
        return radar.render_nd_acdata(nd)

    def pump(self):
        self._pumping = True
        ran = False
        while True:
            try:
                line, done = self._pending.get_nowait()
            except queue.Empty:
                break
            try:
                done.put(self._run_cmd(line))
            except Exception as exc:  # surface, don't kill the loop
                done.put(f"command failed: {exc}")
            ran = True
        self.client.receive()
        now = time.monotonic()
        if ran or self._frame is None \
                or now - self._last_render >= self.render_period:
            self._last_render = now
            try:
                self._frame = self._render()
            except Exception:
                pass                 # keep the last good frame
            try:
                self._nd = self._render_nd()
            except Exception:
                self._nd = None      # never show a silently-stale ND


class WebUI:
    """The HTTP/SSE server; ``start()`` returns immediately (daemon)."""

    def __init__(self, backend, host="127.0.0.1", port=8080, fps=4.0):
        self.backend = backend
        self.host, self.port = host, port
        self.period = 1.0 / max(fps, 0.1)
        self.httpd = None
        ui = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):       # silence request spam
                pass

            def _send(self, code, ctype, body):
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path in ("/", "/index.html"):
                    self._send(200, "text/html; charset=utf-8",
                               _PAGE.encode())
                elif self.path == "/frame.svg":
                    svg, _ = ui.backend.frame()
                    self._send(200, "image/svg+xml", svg.encode())
                elif self.path == "/nd.svg":
                    nd = ui.backend.nd_frame()
                    if nd:
                        self._send(200, "image/svg+xml", nd.encode())
                    else:
                        self._send(404, "text/plain",
                                   b"no ND selected (SHOWND acid)")
                elif self.path == "/plots.svg":
                    pl = getattr(ui.backend, "_plots", None)
                    if pl:
                        self._send(200, "image/svg+xml", pl.encode())
                    else:
                        self._send(404, "text/plain",
                                   b"no plots (PLOT x,y,dt)")
                elif self.path == "/events":
                    self.send_response(200)
                    self.send_header("Content-Type", "text/event-stream")
                    self.send_header("Cache-Control", "no-cache")
                    self.end_headers()
                    try:
                        while True:
                            svg, inf = ui.backend.frame()
                            d = {"svg": svg, "info": inf}
                            nd = ui.backend.nd_frame()
                            if nd:
                                d["nd"] = nd
                            payload = json.dumps(d)
                            self.wfile.write(
                                f"data: {payload}\n\n".encode())
                            self.wfile.flush()
                            time.sleep(ui.period)
                    except (BrokenPipeError, ConnectionResetError,
                            OSError):
                        return               # browser went away
                else:
                    self._send(404, "text/plain", b"not found")

            def do_POST(self):
                if self.path == "/cmd":
                    n = int(self.headers.get("Content-Length", 0))
                    line = self.rfile.read(n).decode().strip()
                    out = ui.backend.command(line)
                    self._send(200, "text/plain; charset=utf-8",
                               (out or "").encode())
                elif self.path == "/complete":
                    n = int(self.headers.get("Content-Length", 0))
                    line = self.rfile.read(n).decode()
                    try:
                        out = ui.backend.complete(line)
                    except Exception as exc:  # completion must not 500
                        out = {"line": line, "hint": f"error: {exc}"}
                    self._send(200, "application/json",
                               json.dumps(out).encode())
                elif self.path == "/click":
                    n = int(self.headers.get("Content-Length", 0))
                    try:
                        req = json.loads(self.rfile.read(n).decode())
                        out = ui.backend.click(
                            str(req.get("line", "")),
                            float(req["lat"]), float(req["lon"]))
                    except (ValueError, KeyError, TypeError,
                            AttributeError) as exc:
                        out = {"tostack": "", "todisplay": "",
                               "echo": f"click error: {exc}"}
                    self._send(200, "application/json",
                               json.dumps(out).encode())
                else:
                    self._send(404, "text/plain", b"not found")

        self._handler = Handler

    def start(self):
        self.httpd = ThreadingHTTPServer((self.host, self.port),
                                         self._handler)
        self.port = self.httpd.server_address[1]      # resolve port 0
        t = threading.Thread(target=self.httpd.serve_forever, daemon=True)
        t.start()
        return self

    def stop(self):
        if self.httpd:
            self.httpd.shutdown()
            self.httpd.server_close()
            self.httpd = None


def serve_sim(sim, host="127.0.0.1", port=8080, fps=4.0, run=True):
    """Serve an embedded sim and (optionally) drive its loop forever.

    The loop advances the sim (wall-clock paced unless the stack said
    FF/DTMULT) and pumps queued browser commands between chunks — the
    web equivalent of the reference's Qt event loop around the sim
    timer (``ui/qtgl/mainwindow.py``)."""
    backend = SimBackend(sim)
    backend.pump()       # seed the frame cache before any server thread
    ui = WebUI(backend, host=host, port=port, fps=fps).start()
    print(f"bluesky_tpu web UI on http://{ui.host}:{ui.port}/")
    if not run:
        return ui
    from ..simulation.sim import OP
    try:
        while True:
            backend.pump()
            if not sim.step():               # END
                break
            if sim.state_flag != OP:         # INIT/HOLD: idle politely
                time.sleep(0.05)
    except KeyboardInterrupt:
        pass
    finally:
        ui.stop()
    return ui
