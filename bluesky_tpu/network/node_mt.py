"""Multithreaded sim node: network I/O on a dedicated thread.

Parity with the reference ``bluesky/network/node_mt.py:9-96``: the
TCP-facing sockets (DEALER events, PUB streams) live in an ``IOThread``
that shuttles frames to/from the sim thread over inproc PAIR sockets.
The sim loop therefore never blocks on the broker — a stalled or slow
server cannot stall a device step chunk, and outbound streams are
buffered by the thread while a chunk runs.

The wire format is identical to :class:`~bluesky_tpu.network.node.Node`
(source-routed multipart events, name-prefixed PUB streams), so an
``MTNode`` is a drop-in replacement wherever a ``Node`` subclass is
used; only the socket plumbing differs.  Like the reference, this
flavor is optional — the default single-threaded node is simpler and
the jitted step's host share is tiny — but long host-side event
handlers (scenario loads, BATCH fan-in) benefit.
"""
import threading

import zmq

from ..utils.timer import Timer
from .common import DEFAULT_PORTS
from .node import Node, split_envelope
from .npcodec import packb, unpackb

_QUIT = b"__IOQUIT__"


class IOThread(threading.Thread):
    """The I/O loop (reference node_mt.py IOThread.run:10-42): poll the
    TCP sockets and the inproc back-ends, forwarding frames both ways
    until the quit sentinel arrives from the sim side."""

    def __init__(self, endpoints, identity, inproc_event, inproc_stream):
        super().__init__(daemon=True)
        self.endpoints = endpoints
        self.identity = identity
        self.inproc = (inproc_event, inproc_stream)

    def run(self):
        ctx = zmq.Context.instance()
        fe_event = ctx.socket(zmq.DEALER)
        fe_event.setsockopt(zmq.IDENTITY, self.identity)
        fe_event.setsockopt(zmq.LINGER, 500)
        fe_stream = ctx.socket(zmq.PUB)
        fe_stream.setsockopt(zmq.LINGER, 0)
        be_event = ctx.socket(zmq.PAIR)
        be_stream = ctx.socket(zmq.PAIR)
        fe_event.connect(self.endpoints[0])
        fe_stream.connect(self.endpoints[1])
        be_event.connect(self.inproc[0])
        be_stream.connect(self.inproc[1])

        poller = zmq.Poller()
        poller.register(fe_event, zmq.POLLIN)
        poller.register(be_event, zmq.POLLIN)
        poller.register(be_stream, zmq.POLLIN)
        try:
            while True:
                socks = dict(poller.poll(None))
                if socks.get(fe_event) == zmq.POLLIN:
                    be_event.send_multipart(fe_event.recv_multipart())
                if socks.get(be_event) == zmq.POLLIN:
                    msg = be_event.recv_multipart()
                    if msg[0] == _QUIT:
                        break
                    fe_event.send_multipart(msg)
                if socks.get(be_stream) == zmq.POLLIN:
                    fe_stream.send_multipart(be_stream.recv_multipart())
        except zmq.ZMQError:
            pass                        # context terminated
        finally:
            fe_event.close()
            fe_stream.close()
            be_event.close()
            be_stream.close()


class MTNode(Node):
    """Node whose TCP sockets live in an :class:`IOThread`."""

    def __init__(self, event_port: int = DEFAULT_PORTS["wevent"],
                 stream_port: int = DEFAULT_PORTS["wstream"],
                 host: str = "127.0.0.1", node_id: bytes = None):
        super().__init__(event_port=event_port, stream_port=stream_port,
                         host=host, node_id=node_id)
        # Replace the direct TCP sockets with inproc bridges; the thread
        # owns the network side.
        self.event_io.close()
        self.stream_out.close()
        ctx = zmq.Context.instance()
        ep_event = f"inproc://mtnode-event-{self.node_id.hex()}"
        ep_stream = f"inproc://mtnode-stream-{self.node_id.hex()}"
        self.event_io = ctx.socket(zmq.PAIR)
        self.event_io.bind(ep_event)
        self.stream_out = ctx.socket(zmq.PAIR)
        self.stream_out.bind(ep_stream)
        self.io_thread = IOThread(self._endpoints, self.node_id,
                                  ep_event, ep_stream)

    # ------------------------------------------------------------ lifecycle
    def connect(self):
        # A PAIR send with no connected peer blocks forever; if the
        # IOThread dies on startup (bad endpoint, context teardown) the
        # REGISTER send would hang the sim thread.  Bound only this send
        # — steady-state sends keep the blocking-backpressure contract
        # (the thread buffers; a stalled broker must not crash the loop).
        self.io_thread.start()
        self.event_io.setsockopt(zmq.SNDTIMEO, 2000)
        try:
            self.send_event(b"REGISTER", None)
        except zmq.Again:
            alive = self.io_thread.is_alive()
            raise RuntimeError(
                "MTNode I/O thread %s — REGISTER send timed out"
                % ("is not consuming" if alive else "died on startup"))
        finally:
            self.event_io.setsockopt(zmq.SNDTIMEO, -1)

    def close(self):
        # stop the I/O thread first, then tear down the inproc pair;
        # bound the _QUIT send the same way as REGISTER (a dead thread
        # must not hang teardown).
        self.event_io.setsockopt(zmq.SNDTIMEO, 2000)
        try:
            self.event_io.send_multipart([_QUIT])
            self.io_thread.join(timeout=2.0)
        except zmq.ZMQError:
            pass
        self.event_io.close()
        self.stream_out.close()

    # ------------------------------------------------------------------ I/O
    def send_stream(self, name: bytes, data):
        # PAIR to the thread (which PUBlishes); same frame format
        self.stream_out.send_multipart([name + self.node_id, packb(data)])

    def run(self):
        """Blocking loop, identical contract to Node.run — the poll on
        the inproc PAIR returns instantly whether or not the broker is
        reachable, which is the point of the threaded flavor."""
        self.running = True
        self.connect()
        self._watchdog_start()
        try:
            while self.running:
                self._watchdog_beat()
                self.process_events(timeout_ms=1)
                self.step()
                Timer.update_timers()
        finally:
            self._watchdog_stop()   # see Node.run: must not outlive loop
        self.send_event(b"STATECHANGE", -1)
        self.close()
