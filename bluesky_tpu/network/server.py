"""Broker + worker manager (parity: bluesky/network/server.py:26-317).

Four sockets: client-facing ROUTER (events) + XPUB (streams), worker-facing
ROUTER (events) + XSUB (streams).  Streams pass through XSUB->XPUB;
subscription messages flow back XPUB->XSUB.  Events are source-routed
multipart ``[*route, name, payload]`` (see node.split_envelope): on each
forward the server pops the first route frame as the next-hop destination
and appends the arrival sender id to the tail, so the frames a receiver
sees are exactly the return route for its reply.  ``b'*'`` fans out to all
workers.

Server-directed events (empty route): REGISTER, ADDNODES, BATCH, QUIT,
STATECHANGE.  BATCH splits a multi-SCEN scenario and farms the pieces out
to idle workers, spawning more (up to max_nnodes) as needed — the
reference's scenario-ensemble parallelism (§2.10), which on TPU pairs with
the device-side ensemble axis in parallel/sharding.py.
"""
import os
import subprocess
import sys
import threading

import zmq

from .common import DEFAULT_PORTS, make_id
from .discovery import Discovery
from .node import split_envelope
from .npcodec import packb, unpackb


def split_scenarios(scentime, scencmd):
    """Split a scenario command list into per-SCEN chunks
    (parity: server.py:26-32)."""
    starts = [i for i, cmd in enumerate(scencmd)
              if cmd.strip().upper().startswith("SCEN")]
    if not starts:
        return [(list(scentime), list(scencmd))] if scencmd else []
    # commands before the first SCEN are global setup: prepend to each piece
    pre_t, pre_c = scentime[:starts[0]], scencmd[:starts[0]]
    bounds = starts + [len(scencmd)]
    return [(pre_t + scentime[a:b], pre_c + scencmd[a:b])
            for a, b in zip(bounds[:-1], bounds[1:])]


class Server(threading.Thread):
    """Runs the broker loop in a thread (reference: Server(Thread))."""

    def __init__(self, headless=False, discoverable=False,
                 ports=None, max_nnodes=None, spawn_workers=True):
        super().__init__(daemon=True)
        self.server_id = make_id()
        self.headless = headless
        self.ports = dict(DEFAULT_PORTS, **(ports or {}))
        self.max_nnodes = max_nnodes or min(os.cpu_count() or 1, 8)
        self.spawn_workers = spawn_workers
        self.running = False
        self._stop_requested = False
        self.clients = []                  # connected client ids
        self.workers = {}                  # worker_id -> state int
        self.avail_workers = []            # idle worker ids (for BATCH)
        self.scenarios = []                # pending BATCH pieces
        self.processes = []                # spawned worker Popen handles
        self._pending_spawns = 0           # spawned but not yet REGISTERed
        self.discovery = Discovery(self.server_id, is_client=False,
                                   port=self.ports["discovery"]) \
            if discoverable else None
        ctx = zmq.Context.instance()
        self.fe_event = ctx.socket(zmq.ROUTER)
        self.fe_stream = ctx.socket(zmq.XPUB)
        self.be_event = ctx.socket(zmq.ROUTER)
        self.be_stream = ctx.socket(zmq.XSUB)
        # event sockets get a short linger so final QUIT/NODESCHANGED sends
        # flush before close; stream sockets can drop in-flight data
        self.fe_event.setsockopt(zmq.LINGER, 500)
        self.be_event.setsockopt(zmq.LINGER, 500)
        self.fe_stream.setsockopt(zmq.LINGER, 0)
        self.be_stream.setsockopt(zmq.LINGER, 0)

    # ----------------------------------------------------------- lifecycle
    def addnodes(self, count=1):
        """Spawn sim worker processes (parity: server.py:62-67)."""
        if not self.spawn_workers:
            return
        for _ in range(count):
            self._pending_spawns += 1
            self.processes.append(subprocess.Popen(
                [sys.executable, "-m", "bluesky_tpu", "--sim",
                 "--event-port", str(self.ports["wevent"]),
                 "--stream-port", str(self.ports["wstream"])]))

    def stop(self):
        self._stop_requested = True
        self.running = False

    # ------------------------------------------------------------- routing
    def _forward(self, sender, route, name, payload):
        """Pop next hop, append sender to the return tail, send."""
        if route and route[0] == b"*":
            # Fan out to every endpoint except the sender (stack.py's
            # b'*' semantics, server.py:302-307): workers AND clients.
            for wid in self.workers:
                if wid != sender:
                    self.be_event.send_multipart(
                        [wid, sender, name, payload])
            for cid in self.clients:
                if cid != sender:
                    self.fe_event.send_multipart(
                        [cid, sender, name, payload])
            return
        dest = route[0]
        tail = list(route[1:]) + [sender]
        sock = self.be_event if dest in self.workers else self.fe_event
        sock.send_multipart([dest] + tail + [name, payload])

    def _nodeschanged(self):
        data = packb({"host_id": self.server_id,
                      "nodes": list(self.workers)})
        for cid in self.clients:
            self.fe_event.send_multipart([cid, b"NODESCHANGED", data])

    def _handle_server_event(self, sock, sender, name, payload):
        from_worker = sock is self.be_event
        if name == b"REGISTER":
            if from_worker:
                self.workers[sender] = 0
                self._pending_spawns = max(0, self._pending_spawns - 1)
                self.avail_workers.append(sender)
                self._send_pending_scenario()
                self._nodeschanged()
            else:
                self.clients.append(sender)
            sock.send_multipart(
                [sender, b"REGISTER",
                 packb({"host_id": self.server_id,
                        "nodes": list(self.workers)})])
        elif name == b"ADDNODES":
            count = unpackb(payload) if payload else 1
            self.addnodes(int(count or 1))
        elif name == b"STATECHANGE":
            state = unpackb(payload)
            if state == -1:
                self.workers.pop(sender, None)
                if sender in self.avail_workers:
                    self.avail_workers.remove(sender)
                self._nodeschanged()
                # keep the batch draining if pieces are still queued
                if self.scenarios:
                    headroom = self.max_nnodes - len(self.workers) \
                        - self._pending_spawns
                    self.addnodes(max(0, min(len(self.scenarios),
                                             headroom)))
            else:
                self.workers[sender] = state
                # worker dropped out of OP -> available for the next piece;
                # busy workers must not receive BATCH pieces
                # (parity: server.py:234-247)
                if state < 2:
                    if sender not in self.avail_workers:
                        self.avail_workers.append(sender)
                        self._send_pending_scenario()
                elif sender in self.avail_workers:
                    self.avail_workers.remove(sender)
        elif name == b"BATCH":
            data = unpackb(payload)
            self.scenarios.extend(
                split_scenarios(data["scentime"], data["scencmd"]))
            while self.avail_workers and self.scenarios:
                self._send_pending_scenario()
            if self.scenarios:
                headroom = self.max_nnodes - len(self.workers) \
                    - self._pending_spawns
                self.addnodes(max(0, min(len(self.scenarios), headroom)))
        elif name == b"QUIT":
            for wid in self.workers:
                self.be_event.send_multipart([wid, b"QUIT", packb(None)])
            self.running = False
        elif from_worker:
            # unaddressed worker output (e.g. scenario-triggered ECHO with
            # no issuing client): fan out to every connected client
            for cid in self.clients:
                self.fe_event.send_multipart([cid, sender, name, payload])

    def _send_pending_scenario(self):
        if self.avail_workers and self.scenarios:
            wid = self.avail_workers.pop(0)
            scentime, scencmd = self.scenarios.pop(0)
            self.be_event.send_multipart(
                [wid, b"BATCH", packb({"scentime": scentime,
                                       "scencmd": scencmd})])

    # ------------------------------------------------------------ main loop
    def run(self):
        self.fe_event.bind(f"tcp://*:{self.ports['event']}")
        self.fe_stream.bind(f"tcp://*:{self.ports['stream']}")
        self.be_event.bind(f"tcp://*:{self.ports['wevent']}")
        self.be_stream.bind(f"tcp://*:{self.ports['wstream']}")
        poller = zmq.Poller()
        for sock in (self.fe_event, self.fe_stream, self.be_event,
                     self.be_stream):
            poller.register(sock, zmq.POLLIN)
        if self.discovery:
            poller.register(self.discovery.handle, zmq.POLLIN)
        self.running = not self._stop_requested
        if not self.headless:
            self.addnodes(1)
        while self.running:
            events = dict(poller.poll(100))
            if self.be_stream in events:
                self.fe_stream.send_multipart(
                    self.be_stream.recv_multipart())
            if self.fe_stream in events:    # subscription propagation
                self.be_stream.send_multipart(
                    self.fe_stream.recv_multipart())
            if self.discovery and (self.discovery.handle in events
                                   or self.discovery.handle.fileno()
                                   in events):
                kind, _ = self.discovery.recv_reqreply()
                if kind == "req":
                    self.discovery.send_reply(self.ports["event"],
                                              self.ports["stream"])
            for sock in (self.fe_event, self.be_event):
                if sock not in events:
                    continue
                frames = sock.recv_multipart()
                # a malformed message from one peer must not kill the broker
                try:
                    sender, rest = frames[0], frames[1:]
                    route, name, payload = split_envelope(rest)
                    if route:
                        self._forward(sender, route, name, payload)
                    else:
                        self._handle_server_event(sock, sender, name,
                                                  payload)
                except Exception as exc:
                    print(f"server: dropped malformed message: {exc!r}")
        # shutdown: tell workers to quit (covers stop() as well as the
        # client-QUIT path), then wait for them (server.py:311-317)
        for wid in self.workers:
            self.be_event.send_multipart([wid, b"QUIT", packb(None)])
        for proc in self.processes:
            try:
                proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                proc.kill()
        for sock in (self.fe_event, self.fe_stream, self.be_event,
                     self.be_stream):
            sock.close()
        if self.discovery:
            self.discovery.close()
