"""Broker + worker manager (parity: bluesky/network/server.py:26-317).

Four sockets: client-facing ROUTER (events) + XPUB (streams), worker-facing
ROUTER (events) + XSUB (streams).  Streams pass through XSUB->XPUB;
subscription messages flow back XPUB->XSUB.  Events are source-routed
multipart ``[*route, name, payload]`` (see node.split_envelope): on each
forward the server pops the first route frame as the next-hop destination
and appends the arrival sender id to the tail, so the frames a receiver
sees are exactly the return route for its reply.  ``b'*'`` fans out to all
workers.

Server-directed events (empty route): REGISTER, ADDNODES, BATCH, QUIT,
STATECHANGE, PONG.  BATCH splits a multi-SCEN scenario and farms the
pieces out to idle workers, spawning more (up to max_nnodes) as needed —
the reference's scenario-ensemble parallelism (§2.10), which on TPU pairs
with the device-side ensemble axis in parallel/sharding.py.

Hardening beyond the reference:
* **Worker liveness**: spawned workers get their id assigned
  (``--node-id``) so a dead child process maps straight back to its
  registration; external workers are probed with PING/PONG.  A dead
  worker's in-flight BATCH piece is requeued and a replacement is
  spawned — kill -9 a worker mid-batch and the batch still completes.
* **Durable BATCH sweeps** (docs/FAULT_TOLERANCE.md): every piece
  transition (queued/dispatched/completed/crashed/quarantined/
  preempted) is appended to a JSONL write-ahead journal
  (network/journal.py); ``--resume-batch <journal>`` replays it after a
  server crash or preemption to rebuild the queue with exactly-once
  completion semantics.  A ``PREEMPTED`` notice from a draining worker
  requeues its piece without a circuit-breaker strike, and
  ``BATCHQUARANTINE`` reports are replayed to late-joining clients.
* **Overload/straggler serving layer** (docs/FAULT_TOLERANCE.md rows
  #10/#11): workers piggyback per-piece progress (simt, chunks done)
  on their PONG replies; an in-flight piece whose progress stalls past
  ``straggler_timeout`` — or whose rate falls far below the fleet
  median — while heartbeats stay fresh is *hedged*: a second copy goes
  to an idle worker, first completion wins, the loser is cancelled
  (``BATCHCANCEL``), and the journal records ``hedged``/
  ``dup_completed`` so exactly-once survives a crash mid-hedge.
  Admission control bounds the pending queue (``batch_queue_max``,
  over-limit submissions get a structured ``BATCHREJECTED``), dispatch
  is round-robin per submitting client (one heavy client cannot starve
  the rest), the stream path is bounded (SNDHWM + drop counter) so a
  stalled GUI cannot back-pressure the broker, and ``HEALTH`` returns
  the whole picture machine-readably.
* **Server-to-server chaining** (reference server.py:213-225): a server
  started with ``upstream=(host, port)`` registers at another server's
  client port, mirrors that server's node table to its own clients
  (NODESCHANGED merge), and routes events for remote nodes over the
  link.  Multi-hop replies work because reply routes are the REVERSED
  accumulated sender tail (single-hop routes are palindromes, so the
  flat fabric is unaffected).
"""
import collections
import os
import statistics
import subprocess
import sys
import threading
import time

import zmq

from .common import DEFAULT_PORTS, make_id
from .discovery import Discovery
from .node import split_envelope
from .npcodec import packb, unpackb


def split_scenarios(scentime, scencmd):
    """Split a scenario command list into per-SCEN chunks
    (parity: server.py:26-32)."""
    starts = [i for i, cmd in enumerate(scencmd)
              if cmd.strip().upper().startswith("SCEN")]
    if not starts:
        return [(list(scentime), list(scencmd))] if scencmd else []
    # commands before the first SCEN are global setup: prepend to each piece
    pre_t, pre_c = scentime[:starts[0]], scencmd[:starts[0]]
    bounds = starts + [len(scencmd)]
    return [(pre_t + scentime[a:b], pre_c + scencmd[a:b])
            for a, b in zip(bounds[:-1], bounds[1:])]


class FairQueue:
    """Per-client round-robin queue of pending BATCH pieces.

    One flood-submitting client must not starve the others, so pieces
    are held in per-owner sub-queues and ``pop_next`` serves owners in
    rotation.  The *read* surface stays list-like (``len``/``bool``/
    ``iter``/``[i]`` over the flattened drain order) because operators,
    tests and the journal-replay path all inspect the queue like the
    plain list it replaces; mutation goes through ``push``/
    ``push_front``/``extend`` so every piece keeps its owner.
    """

    def __init__(self):
        self._queues = {}                  # owner -> deque of pieces
        self._rr = collections.deque()     # owner service rotation
        # queue-wait bookkeeping (docs/OBSERVABILITY.md): admission
        # stamp per piece object, read off at pop.  Keyed by id() —
        # the same list pair flows from push to dispatch unchanged.
        self._enq_t = {}                   # id(piece) -> monotonic stamp
        self.last_wait_s = None            # wait of the last pop_next

    def _ensure(self, owner):
        q = self._queues.get(owner)
        if q is None:
            q = self._queues[owner] = collections.deque()
            self._rr.append(owner)
        return q

    def push(self, piece, owner=b""):
        self._ensure(owner).append(piece)
        self._enq_t[id(piece)] = time.monotonic()

    def push_front(self, piece, owner=b""):
        """Requeue (crash/preempt/resume): the piece goes back to the
        FRONT of its owner's sub-queue, keeping sweep order."""
        self._ensure(owner).appendleft(piece)
        self._enq_t[id(piece)] = time.monotonic()

    def extend(self, pieces, owner=b""):
        self._ensure(owner).extend(pieces)
        now = time.monotonic()
        for p in pieces:
            self._enq_t[id(p)] = now

    def pop_next(self):
        """``(owner, piece)`` from the next owner in rotation with work
        pending, or ``None``.  The served owner moves to the back."""
        for _ in range(len(self._rr)):
            owner = self._rr[0]
            self._rr.rotate(-1)
            q = self._queues.get(owner)
            if q:
                piece = q.popleft()
                t0 = self._enq_t.pop(id(piece), None)
                self.last_wait_s = (None if t0 is None
                                    else time.monotonic() - t0)
                return owner, piece
        return None

    def depth_by_owner(self):
        return {o: len(q) for o, q in self._queues.items() if q}

    def _flat(self):
        """Flattened round-robin drain order (what pop_next would
        yield), starting from the current rotation head.  Index
        pointers keep this O(total) — observers poll it."""
        qs = {o: list(q) for o, q in self._queues.items() if q}
        order = [o for o in self._rr if o in qs]
        idx = dict.fromkeys(order, 0)
        out = []
        remaining = sum(len(q) for q in qs.values())
        while remaining:
            for o in order:
                i = idx[o]
                if i < len(qs[o]):
                    out.append(qs[o][i])
                    idx[o] = i + 1
                    remaining -= 1
        return out

    def __len__(self):
        return sum(len(q) for q in self._queues.values())

    def __bool__(self):
        return any(self._queues.values())

    def __iter__(self):
        return iter(self._flat())

    def __getitem__(self, i):
        return self._flat()[i]


class WorldPack:
    """A packed world-batch assignment: n compatible BATCH pieces in
    flight on ONE worker, stepped there as a single stacked device
    program (simulation/worlds.py).  The server tracks per-world
    completion (``done``: world index -> status) from the worker's
    ``BATCHWORLD`` events so demux back to the individual pieces is
    exactly-once — a crash mid-pack requeues only the worlds whose
    pieces never completed."""

    def __init__(self, picks):
        self.owners = [o for o, _ in picks]
        self.pieces = [p for _, p in picks]
        self.done = {}                     # world index -> status str

    def __len__(self):
        return len(self.pieces)

    def remaining(self):
        """(world, owner, piece) for every world not yet demuxed."""
        return [(i, self.owners[i], self.pieces[i])
                for i in range(len(self.pieces)) if i not in self.done]


def _obs_counter(name, help=""):
    """Registry-backed broker counter exposed as a plain int attribute:
    reads stay ints (tests/operators compare with ``==``), writes
    (``+= 1``) land in ``self.obs`` so METRICS DUMP, the Prometheus
    export and HEALTH all read ONE source of truth."""
    def fget(self):
        return int(self.obs.counter(name, help=help).value)

    def fset(self, v):
        self.obs.counter(name, help=help)._set(v)
    return property(fget, fset)


class Server(threading.Thread):
    """Runs the broker loop in a thread (reference: Server(Thread))."""

    # broker counters, backed by the server metrics registry
    packed_pieces = _obs_counter(
        "server_packed_pieces", "pieces dispatched inside world-packs")
    world_batches = _obs_counter(
        "server_world_batches", "packed world-batch dispatches sent")
    worlds_refused_spatial = _obs_counter(
        "server_worlds_refused_spatial",
        "spatial-shard pieces kept out of packs")
    worlds_refused_opt = _obs_counter(
        "server_worlds_refused_opt", "OPT/GRAD pieces kept out of packs")
    worlds_failed = _obs_counter(
        "server_worlds_failed", "per-world failure reports")
    hedges_started = _obs_counter(
        "server_hedges_started", "speculative straggler re-dispatches")
    hedges_won_hedge = _obs_counter(
        "server_hedges_won_hedge", "hedge copy finished first")
    hedges_won_primary = _obs_counter(
        "server_hedges_won_primary", "primary recovered and won")
    hedges_cancelled = _obs_counter(
        "server_hedges_cancelled", "hedge losers that acked the cancel")
    dup_completions = _obs_counter(
        "server_dup_completions", "hedge losers that finished anyway")
    rejected_batches = _obs_counter(
        "server_rejected_batches", "BATCHREJECTED admission refusals")
    opt_results = _obs_counter(
        "server_opt_results", "OPTRESULT reports journaled")
    stream_drops = _obs_counter(
        "server_stream_drops", "stream frames dropped at SNDHWM")
    perf_regressions = _obs_counter(
        "server_perf_regressions",
        "serving SLO-watch perf_regression records journaled")
    sdc_suspects = _obs_counter(
        "server_sdc_suspects",
        "fingerprint mismatches journaled (sdc_suspect)")
    sdc_votes = _obs_counter(
        "server_sdc_votes", "2-of-3 re-execution votes resolved")
    sdc_audits = _obs_counter(
        "server_sdc_audits", "shadow audit re-executions dispatched")
    sdc_quarantined_workers = _obs_counter(
        "server_sdc_quarantined_workers",
        "workers quarantined by the SDC fingerprint vote")

    def __init__(self, headless=False, discoverable=False,
                 ports=None, max_nnodes=None, spawn_workers=True,
                 upstream=None, hb_interval=2.0, hb_timeout=30.0,
                 restart_crashed=True, max_piece_crashes=None,
                 journal_path=None, resume_journal=None,
                 straggler_timeout=None, hedge_enabled=None,
                 batch_queue_max=None, world_pack=None,
                 world_batch_max=None, mitigate_enabled=None,
                 sdc_enabled=None, sdc_audit_rate=None,
                 ha_role=None, ha_lease_ttl=None, ha_poll_dt=None,
                 ha_fence_strict=None):
        super().__init__(daemon=True)
        # Observability (ISSUE-11, docs/OBSERVABILITY.md): the broker's
        # own registry (counters above, demux/queue series below), the
        # FLEET registry that heartbeat metric deltas from every worker
        # merge into, and the per-process flight recorder.
        from ..obs.metrics import (DEFAULT_S_BUCKETS, Registry)
        from ..obs.trace import get_recorder
        self.obs = Registry()
        self.fleet = Registry()
        self.recorder = get_recorder()
        self.obs.histogram(
            "server_demux_ms",
            help="world-pack demux (BATCHWORLD/retirement) host ms")
        self.obs.histogram(
            "server_queue_wait_s", buckets=DEFAULT_S_BUCKETS,
            help="piece admission -> dispatch queue wait")
        self.obs.gauge("server_queue_depth",
                       help="pending BATCH pieces")
        self.server_id = make_id()
        self.headless = headless
        self.ports = dict(DEFAULT_PORTS, **(ports or {}))
        self.max_nnodes = max_nnodes or min(os.cpu_count() or 1, 8)
        self.spawn_workers = spawn_workers
        self.running = False
        self._stop_requested = False
        self.clients = []                  # connected client ids
        self.workers = {}                  # worker_id -> state int
        self.avail_workers = []            # idle worker ids (for BATCH)
        self.scenarios = FairQueue()       # pending BATCH pieces,
        #                                    round-robin per client
        self.processes = []                # spawned worker Popen handles
        self._pending_spawns = 0           # spawned but not yet REGISTERed
        # ----- liveness / restart
        self.hb_interval = hb_interval
        self.hb_timeout = hb_timeout
        self.restart_crashed = restart_crashed
        self.spawned = {}                  # worker_id -> Popen
        self.inflight = {}                 # worker_id -> BATCH piece
        self.inflight_owner = {}           # worker_id -> submitting client
        self.inflight_t = {}               # worker_id -> dispatch stamp
        self.last_seen = {}                # worker_id -> monotonic stamp
        self._next_hb = 0.0
        # ----- per-scenario circuit breaker: a piece that loses its
        # worker K consecutive times is poison (NaN bomb, OOM bait,
        # FAULT KILL) — quarantine + report it instead of requeueing it
        # into a crash loop that eats the whole worker pool forever.
        from .. import settings as _settings
        self.max_piece_crashes = max_piece_crashes \
            if max_piece_crashes is not None \
            else getattr(_settings, "batch_max_crashes", 3)
        self.piece_crashes = {}            # piece key -> consecutive losses
        self.quarantined = []              # circuit-broken pieces
        # BATCHQUARANTINE payloads replayed to late-joining clients on
        # REGISTER — capped so a long-lived server does not replay
        # unbounded quarantine history to every reattaching operator
        self.quarantine_reports = collections.deque(
            maxlen=max(1, int(getattr(_settings,
                                      "quarantine_report_cap", 64))))
        # ----- overload / straggler layer (docs/FAULT_TOLERANCE.md
        # rows #10/#11): per-worker progress from heartbeat PONGs,
        # speculative hedges, admission control + drop counters
        self.straggler_timeout = straggler_timeout \
            if straggler_timeout is not None \
            else getattr(_settings, "straggler_timeout", 30.0)
        self.hedge_enabled = hedge_enabled if hedge_enabled is not None \
            else getattr(_settings, "hedge_enabled", True)
        self.hedge_rate_factor = getattr(_settings,
                                         "hedge_rate_factor", 0.2)
        # serving SLO watch (ISSUE-12): journal a perf_regression audit
        # record when an in-flight piece's rolling rate drops below
        # perf_slo_factor x the fleet median (0 = off).  Deliberately
        # separate from hedging: the hedge MITIGATES, the SLO record
        # EXPLAINS — and it fires even with hedging off or no idle
        # worker to hedge onto.
        self.perf_slo_factor = float(getattr(_settings,
                                             "perf_slo_factor", 0.0))
        self._slo_flagged = set()          # (wid, piece key) journaled
        self._slo_recent = collections.deque(maxlen=8)
        self._slo_median = None            # last fleet-median FF rate
        self.batch_queue_max = batch_queue_max \
            if batch_queue_max is not None \
            else getattr(_settings, "batch_queue_max", 4096)
        self.hb_busy_multiplier = getattr(_settings,
                                          "hb_busy_multiplier", 10.0)
        # ----- multi-world packing (docs/PERF_ANALYSIS.md §multi-world):
        # compatible BATCH pieces are packed into world-batches — one
        # worker steps W scenarios per device dispatch — and demuxed
        # back per piece.  WORLDS stack/client command flips at runtime.
        self.world_pack = world_pack if world_pack is not None \
            else bool(getattr(_settings, "world_pack", False))
        self.world_batch_max = world_batch_max \
            if world_batch_max is not None \
            else int(getattr(_settings, "world_batch_max", 8))
        self.packed_pieces = 0             # pieces dispatched inside packs
        self.world_batches = 0             # packed dispatches sent
        self._pack_fill_sum = 0.0          # sum of per-dispatch fill
        self.worlds_refused_spatial = 0    # spatial pieces kept out of packs
        self.worlds_refused_opt = 0        # OPT/GRAD pieces kept out of packs
        self.worlds_failed = 0             # per-world failure reports
        self.worker_progress = {}          # wid -> {simt, chunks, rate,
        #                                    t (last report), advance_t}
        self.hedge_by = {}                 # primary wid -> hedge wid
        self.hedge_of = {}                 # hedge wid -> primary wid
        self._cancel_pending = {}          # cancelled loser wid -> piece
        self.hedges_started = 0
        self.hedges_won_hedge = 0          # hedge copy finished first
        self.hedges_won_primary = 0        # primary recovered and won
        self.hedges_cancelled = 0          # losers that acked the cancel
        self.dup_completions = 0           # losers that finished anyway
        self.rejected_batches = 0          # BATCHREJECTED sent
        self.opt_results = 0               # OPTRESULT reports journaled
        self.stream_drops = 0              # stream frames dropped at HWM
        self.perf_regressions = 0          # SLO-watch records journaled
        self._completion_stamps = collections.deque(maxlen=64)
        # ----- durable BATCH state: append-only JSONL journal (WAL)
        # replayed on restart (--resume-batch).  journal_path=None ->
        # settings-derived default (<log_path>/batch-<serverid>.jsonl,
        # or the resume journal itself so chained resumes keep one
        # file); journal_path="" disables journaling.  The file is only
        # created when the first BATCH record is appended.
        from .journal import BatchJournal
        self.resume_journal = resume_journal or None
        if journal_path is None:
            journal_path = self.resume_journal or os.path.join(
                getattr(_settings, "log_path", "output"),
                f"batch-{self.server_id.hex()}.jsonl")
        self.journal = BatchJournal(
            journal_path,
            fsync=getattr(_settings, "batch_journal_fsync", True)) \
            if journal_path else None
        # ----- broker high availability (network/ha.py, ISSUE-18):
        # warm-standby failover with journal-fenced leadership.  With
        # ha_role=None (and settings.ha_standby unset) every HA branch
        # is inert — no lease records, no wepoch stamping, no HA
        # HEALTH section: bit-identical to a build without HA.
        from . import ha as _ha
        if ha_role is None and bool(getattr(_settings, "ha_standby",
                                            False)):
            ha_role = "standby"
        self.ha_role = ha_role             # None | "leader" | "standby"
        self.ha_lease_ttl = float(
            getattr(_settings, "ha_lease_ttl", 10.0)
            if ha_lease_ttl is None else ha_lease_ttl)
        self.ha_poll_dt = float(
            getattr(_settings, "ha_poll_dt", 1.0)
            if ha_poll_dt is None else ha_poll_dt)
        self.ha_fence_strict = bool(
            getattr(_settings, "ha_fence_strict", True)
            if ha_fence_strict is None else ha_fence_strict)
        if self.ha_role and self.journal is None:
            # the journal IS the shared truth the standby tails — HA
            # without one has nothing to fence or replay
            print("server: HA needs a BATCH journal "
                  "(journal_path='' disables both) — HA disabled")
            self.ha_role = None
        self.ha_epoch = 0                  # lease epoch held/last seen
        self._ha_serving = self.ha_role != "standby"  # dispatch gate
        self._ha_lease_file = _ha.lease_path(self.journal.path) \
            if self.ha_role else None
        self._ha_tail = _ha.JournalTail(self.journal.path) \
            if self.ha_role == "standby" else None
        self._ha_limbo = []                # replayed owed pieces held
        #                                    for adoption during grace
        self._ha_pieces = {}               # content key -> piece (replay)
        self._ha_completed = {}            # content key -> completions
        self._ha_grace_until = 0.0         # adoption window end (mono)
        self._ha_next_renew = 0.0          # leader lease-renew stamp
        self._ha_next_poll = 0.0           # standby poll stamp
        self._ha_stale_since = None        # first sighting of a missing
        #                                    lease file (standby)
        self.ha_takeovers = 0              # leases this server acquired
        #                                    by succession
        self.ha_adoptions = 0              # pieces adopted in place
        self.ha_dedup_cancels = 0          # raced completions cancelled
        # ----- self-healing serving (network/mitigate.py): the policy
        # engine that turns sentinel flags into journaled actions.
        # Disabled (default) it is completely inert — journal and
        # HEALTH output stay bit-identical to a build without it.
        from .mitigate import MitigationEngine
        self.mitigator = MitigationEngine(self,
                                          enabled=mitigate_enabled)
        # ----- silent-data-corruption defense (ISSUE-17,
        # docs/FAULT_TOLERANCE.md §SDC): workers running with
        # SimConfig.fingerprint ship a per-piece state fingerprint on
        # completion (SDCFP precedes the STATECHANGE on the FIFO pair).
        # Redundant executions of the same content — hedge duplicates,
        # sampled shadow audits — must agree bit-for-bit; a mismatch
        # journals an audit-only ``sdc_suspect`` and triggers a third
        # re-execution whose 2-of-3 majority names the deviant worker
        # (``sdc_vote``), which the mitigation engine then quarantines
        # (its own gated ``mitigation`` record).
        self.sdc_enabled = bool(getattr(_settings, "sdc_enabled",
                                        False)) \
            if sdc_enabled is None else bool(sdc_enabled)
        self.sdc_audit_rate = float(
            getattr(_settings, "sdc_audit_rate", 0.0)
            if sdc_audit_rate is None else sdc_audit_rate)
        self._sdc_fps = collections.OrderedDict()  # piece key ->
        #                                            {wid hex: fp word}
        self._sdc_execs = {}               # wid -> {kind, key, piece}
        self._sdc_voted = set()            # keys with a vote placed
        self.sdc_quarantine = set()        # voted-deviant worker ids
        self.sdc_suspects = 0              # sdc_suspect records
        self.sdc_votes = 0                 # sdc_vote records
        self.sdc_audits = 0                # shadow audits dispatched
        self.sdc_quarantined_workers = 0   # workers quarantined
        self._audit_acc = 0.0              # deterministic sampling accum
        # journal growth watch (ISSUE-17 satellite): the WAL of an
        # unbounded sweep must warn before it fills the disk
        self.journal_warn_bytes = int(getattr(_settings,
                                              "journal_warn_bytes",
                                              64 * 1024 * 1024))
        self.obs.gauge("server_journal_bytes",
                       help="BATCH journal (WAL) size on disk")
        # ----- server-to-server chaining
        self.upstream = upstream           # (host, event_port) or None
        self.link = None                   # DEALER to the upstream server
        self.link_id = b""                 # upstream host id (after ack)
        self.remote_nodes = {}             # node_id -> upstream host id
        self.discovery = Discovery(self.server_id, is_client=False,
                                   port=self.ports["discovery"]) \
            if discoverable else None
        ctx = zmq.Context.instance()
        self.fe_event = ctx.socket(zmq.ROUTER)
        self.fe_stream = ctx.socket(zmq.XPUB)
        self.be_event = ctx.socket(zmq.ROUTER)
        self.be_stream = ctx.socket(zmq.XSUB)
        # event sockets get a short linger so final QUIT/NODESCHANGED sends
        # flush before close; stream sockets can drop in-flight data
        self.fe_event.setsockopt(zmq.LINGER, 500)
        self.be_event.setsockopt(zmq.LINGER, 500)
        self.fe_stream.setsockopt(zmq.LINGER, 0)
        self.be_stream.setsockopt(zmq.LINGER, 0)
        # Bounded stream buffering (row #11): SNDHWM caps the per-
        # subscriber queue, and XPUB_NODROP turns an over-HWM send into
        # EAGAIN instead of a silent per-peer drop — the forward loop
        # then drops the frame itself and COUNTS it (stream_drops), so
        # a stalled GUI client costs observable drops, never broker
        # back-pressure or unbounded memory.
        self.fe_stream.setsockopt(
            zmq.SNDHWM, int(getattr(_settings, "stream_sndhwm", 1000)))
        self.fe_stream.setsockopt(zmq.XPUB_NODROP, 1)

    # ----------------------------------------------------------- lifecycle
    def addnodes(self, count=1):
        """Spawn sim worker processes (parity: server.py:62-67).

        The worker id is assigned HERE and passed down (--node-id) so a
        child that dies without a goodbye (kill -9, OOM) maps straight
        back to its registration for requeue + restart."""
        if not self.spawn_workers:
            return
        for _ in range(count):
            self._pending_spawns += 1
            wid = make_id()
            proc = subprocess.Popen(
                [sys.executable, "-m", "bluesky_tpu", "--sim",
                 "--event-port", str(self.ports["wevent"]),
                 "--stream-port", str(self.ports["wstream"]),
                 "--node-id", wid.hex()])
            self.processes.append(proc)
            self.spawned[wid] = proc

    def _spawn_for_backlog(self, count=None):
        """Spawn up to ``count`` workers (default: one per queued BATCH
        piece), capped by the max_nnodes headroom — the ONE place the
        headroom formula lives, so every requeue/replay/reap path
        spawns consistently."""
        headroom = self.max_nnodes - len(self.workers) \
            - self._pending_spawns
        n = max(0, min(len(self.scenarios) if count is None else count,
                       headroom))
        if n > 0:
            self.addnodes(n)

    def stop(self):
        self._stop_requested = True
        self.running = False

    # ------------------------------------------------------------- routing
    def _forward(self, sender, route, name, payload):
        """Pop next hop, append sender to the return tail, send."""
        if route and route[0] == b"*":
            # Fan out to every endpoint except the sender (stack.py's
            # b'*' semantics, server.py:302-307): workers AND clients.
            for wid in self.workers:
                if wid != sender:
                    self.be_event.send_multipart(
                        [wid, sender, name, payload])
            for cid in self.clients:
                if cid != sender:
                    self.fe_event.send_multipart(
                        [cid, sender, name, payload])
            return
        dest = route[0]
        tail = list(route[1:]) + [sender]
        if dest in self.workers:
            sock = self.be_event
        elif self.link is not None and (dest in self.remote_nodes
                                        or dest == self.link_id):
            # chained node: hop over the upstream link (the DEALER's own
            # identity is the implicit sender frame on the other side)
            self.link.send_multipart([dest] + tail + [name, payload])
            return
        else:
            sock = self.fe_event
        sock.send_multipart([dest] + tail + [name, payload])

    # --------------------------------------------------- circuit breaker
    @staticmethod
    def _piece_key(piece):
        scentime, scencmd = piece
        return (tuple(scentime), tuple(scencmd))

    @staticmethod
    def _piece_name(piece):
        if isinstance(piece, WorldPack):
            return (f"worlds[{len(piece.done)}/{len(piece)} done: "
                    + ", ".join(Server._piece_name(p)
                                for p in piece.pieces[:4])
                    + (", ..." if len(piece) > 4 else "") + "]")
        for cmd in piece[1]:
            c = cmd.strip()
            if c.upper().startswith("SCEN"):
                parts = c.split(None, 1)
                return parts[1] if len(parts) > 1 else c
        return f"<{len(piece[1])}-command piece>"

    @staticmethod
    def _piece_spatial(piece):
        """Does this piece request the spatial shard mode?  Spatial
        stripes are a per-world layout property and compose with the
        world axis later, not now — packing refuses such pieces with a
        structured echo (WORLDSREFUSED) and dispatches them solo."""
        return any("SHARD" in c.upper() and "SPATIAL" in c.upper()
                   for c in piece[1])

    @staticmethod
    def _piece_solo_reason(piece):
        """Reason string when a piece must dispatch UNPACKED, or None.

        * ``shard_mode=spatial`` — stripes compose with the world axis
          later, not now;
        * ``opt`` — an OPT piece's result event (``OPTRESULT``) and its
          journal record need the worker's own event socket, which the
          world sims of a packed assignment do not have; the optimizer
          already batches its multi-start particles on the world axis
          INTERNALLY (diff/optimize.py), so packing it again wins
          nothing.
        """
        if Server._piece_spatial(piece):
            return "shard_mode=spatial"
        for c in piece[1]:
            head = c.strip().upper().replace(",", " ").split(None, 1)
            if head and head[0] in ("OPT", "GRAD"):
                return "opt"
        return None

    def _report_clients(self, text, name=b"ECHO", data=None):
        """Fan a server-originated event out to every connected client
        (ECHO payload format matches ScreenIO's)."""
        payload = packb(data if data is not None
                        else {"text": text, "flags": 0})
        for cid in self.clients:
            self.fe_event.send_multipart([cid, name, payload])

    def _drop_hedge_links(self, wid):
        """Dissolve any hedge pairing ``wid`` is part of; returns the
        partner id if the partner is STILL running the piece (so the
        piece is not actually lost), else None."""
        partner = self.hedge_by.pop(wid, None)
        if partner is None:
            partner = self.hedge_of.pop(wid, None)
            self.hedge_by.pop(partner, None)
        else:
            self.hedge_of.pop(partner, None)
        return partner if partner is not None \
            and partner in self.inflight else None

    def _requeue_lost_piece(self, wid):
        """A worker was lost with a BATCH piece in flight: requeue the
        piece — unless it has now taken down a worker
        ``max_piece_crashes`` consecutive times, in which case it is
        circuit-broken: quarantined server-side and reported to every
        client (ECHO + a machine-readable BATCHQUARANTINE event)
        instead of being requeued into an infinite crash loop.

        A lost WORLD-PACK demuxes first: only the worlds whose pieces
        never completed (no ``BATCHWORLD`` ack, no ``completed``
        journal record) are requeued/striked — the finished worlds'
        pieces stay exactly-once done."""
        self._cancel_pending.pop(wid, None)
        self.sdc_quarantine.discard(wid)
        piece = self.inflight.pop(wid, None)
        owner = self.inflight_owner.pop(wid, b"")
        self.inflight_t.pop(wid, None)
        self.worker_progress.pop(wid, None)
        if self._sdc_execs.pop(wid, None) is not None:
            # a vote/audit re-execution lost its worker: the original
            # piece is already complete — neither a requeue nor a
            # circuit-breaker strike (the comparison is simply lost)
            print(f"server: SDC re-execution worker {wid.hex()} lost — "
                  f"comparison abandoned, piece stays complete")
            return
        if piece is None:
            return
        if isinstance(piece, WorldPack):
            lost = piece.remaining()
            print(f"server: packed worker {wid.hex()} lost — "
                  f"{len(piece.done)}/{len(piece)} world(s) were "
                  f"complete, requeueing {len(lost)}")
            # reversed: push_front per piece keeps the original order
            for _i, powner, p in reversed(lost):
                self._piece_failed(p, powner)
            return
        if self._drop_hedge_links(wid) is not None:
            # the hedge partner still runs a copy of this piece: the
            # piece is not lost, so neither a requeue nor a circuit-
            # breaker strike — one crashed half of a hedge must not
            # poison-count content the other half may yet complete
            print(f"server: hedged worker {wid.hex()} lost — partner "
                  f"still running the piece, no requeue")
            return
        self._piece_failed(piece, owner)

    def _piece_failed(self, piece, owner=b""):
        """One circuit-breaker strike against a piece (its worker died
        or its world failed): requeue it, or quarantine it once it has
        struck out ``max_piece_crashes`` consecutive times."""
        key = self._piece_key(piece)
        count = self.piece_crashes.get(key, 0) + 1
        self.piece_crashes[key] = count
        if count >= self.max_piece_crashes:
            self.piece_crashes.pop(key, None)
            self.quarantined.append(piece)
            pname = self._piece_name(piece)
            if self.journal:
                self.journal.quarantined(piece, count)
            msg = (f"BATCH piece '{pname}' quarantined: lost its worker "
                   f"{count} consecutive times (circuit breaker)")
            print(f"server: {msg}")
            data = {"piece": pname, "crashes": count,
                    "scencmd": list(piece[1])}
            self.quarantine_reports.append(data)
            self._report_clients(msg)
            self._report_clients(msg, name=b"BATCHQUARANTINE", data=data)
        else:
            # requeue BEFORE the journal append: the fsync is a real
            # disk wait, and observers polling inflight/scenarios must
            # never see the piece in neither
            self.scenarios.push_front(piece, owner)
            if self.journal:
                self.journal.crashed(piece, count)
        self._sweep_slo(piece)

    def _sweep_slo(self, piece):
        """Drop the SLO watch's bookkeeping for a piece leaving flight
        (completed, requeued or quarantined) so week-long soaks never
        grow ``_slo_flagged``/``_slo_recent`` unboundedly.  Sweeps
        every worker's entry for the piece — a completion/requeue ends
        the flight of ALL its copies (hedge halves included), and a
        re-dispatch re-flags on its own merit."""
        if not self._slo_flagged and not self._slo_recent:
            return
        from .journal import BatchJournal
        key = BatchJournal.piece_key(piece)
        for flag in [f for f in self._slo_flagged if f[1] == key]:
            self._slo_flagged.discard(flag)
        pname = self._piece_name(piece)
        kept = [r for r in self._slo_recent if r.get("piece") != pname]
        if len(kept) != len(self._slo_recent):
            self._slo_recent.clear()
            self._slo_recent.extend(kept)

    def _nodeschanged(self):
        """Notify clients; chained remote nodes are merged in (reference
        server.py:213-225 route-prefixed server table)."""
        data = packb({"host_id": self.server_id,
                      "nodes": list(self.workers)
                      + list(self.remote_nodes)})
        for cid in self.clients:
            self.fe_event.send_multipart([cid, b"NODESCHANGED", data])

    def _handle_server_event(self, sock, sender, name, payload):
        from_worker = sock is self.be_event
        if name == b"REGISTER":
            reg = unpackb(payload) if payload else None
            if from_worker:
                if sender not in self.workers:
                    self.workers[sender] = 0
                    self._pending_spawns = max(0, self._pending_spawns - 1)
                # broker-HA failover reconciliation: a surviving worker
                # re-REGISTERs with its in-flight piece report — fold it
                # BEFORE the availability check (an adopted piece puts
                # the worker in ``inflight``, which keeps it unavailable
                # exactly like any mid-BATCH worker)
                if isinstance(reg, dict):
                    self._ha_adopt(sender, reg.get("inflight"))
                # duplicated/late REGISTER frames (flaky transport) must
                # not double-book the worker: one mid-BATCH (in inflight
                # or state OP) stays unavailable, or piece B would
                # overwrite its in-flight piece A and silently drop A
                if sender not in self.avail_workers \
                        and sender not in self.inflight \
                        and sender not in self.sdc_quarantine \
                        and self.workers[sender] < 2:
                    self.avail_workers.append(sender)
                self._send_pending_scenario()
                self._nodeschanged()
            new_client = False
            if not from_worker and sender not in self.clients:
                # backoff clients re-send REGISTER until acked — every
                # resend must ack, but only the first may register
                self.clients.append(sender)
                new_client = True
            ack = {"host_id": self.server_id,
                   "nodes": list(self.workers)
                   + list(self.remote_nodes),
                   # broker pid: FAULT KILLSERVER's SIGKILL target
                   "pid": os.getpid()}
            if self.ha_role:
                # HA peers learn the lease terms from the ack: epoch
                # presence is what arms a node's failover detector, and
                # the discovery port is where it re-runs arbitration
                ack.update(epoch=int(self.ha_epoch),
                           role="leader" if self._ha_serving
                           else "standby",
                           lease_ttl=float(self.ha_lease_ttl),
                           discovery=self.ports["discovery"])
            sock.send_multipart([sender, b"REGISTER", packb(ack)])
            if new_client:
                # replay circuit-breaker verdicts so a late-joining /
                # reattaching operator still sees what the sweep dropped
                for data in self.quarantine_reports:
                    sock.send_multipart(
                        [sender, b"BATCHQUARANTINE", packb(data)])
        elif name == b"ADDNODES":
            count = unpackb(payload) if payload else 1
            self.addnodes(int(count or 1))
        elif name == b"STATECHANGE":
            state = unpackb(payload)
            if state == -1:
                self.workers.pop(sender, None)
                self.spawned.pop(sender, None)
                self.last_seen.pop(sender, None)
                if sender in self.avail_workers:
                    self.avail_workers.remove(sender)
                # a worker that quit with a piece still running gives it
                # back to the queue — through the circuit breaker: a
                # poison pill that makes its worker abort cleanly loops
                # exactly like one that SIGKILLs it
                self._requeue_lost_piece(sender)
                self._nodeschanged()
                # keep the batch draining if pieces are still queued
                if self.scenarios:
                    self._spawn_for_backlog()
            else:
                self.workers[sender] = state
                # worker dropped out of OP -> available for the next piece;
                # busy workers must not receive BATCH pieces
                # (parity: server.py:234-247)
                if state < 2:
                    if sender in self._sdc_execs:
                        # an SDC vote/audit re-execution retired: its
                        # piece is ALREADY complete — never journal a
                        # second ``completed`` (content-addressed keys
                        # would double-count a repeat-trial sweep);
                        # resolve the fingerprint comparison instead
                        self._finish_sdc_exec(sender)
                        return
                    piece = self.inflight.pop(sender, None)
                    if isinstance(piece, WorldPack):
                        # packed piece retired cleanly: per-world
                        # BATCHWORLD events arrived first (FIFO pair),
                        # so normally nothing remains — but a world the
                        # worker finished without reporting is counted
                        # completed exactly once HERE, never dropped
                        t0 = time.perf_counter()
                        self.inflight_owner.pop(sender, None)
                        self.inflight_t.pop(sender, None)
                        for i, _owner, p in piece.remaining():
                            piece.done[i] = "completed"
                            self.piece_crashes.pop(self._piece_key(p),
                                                   None)
                            if self.journal:
                                self.journal.completed(p, sender,
                                                       world=i)
                        self._completion_stamps.append(time.monotonic())
                        self._observe_demux(t0, kind="pack_retire",
                                            worker=sender.hex())
                    elif piece is not None:   # piece completed cleanly:
                        # reset its consecutive-crash count
                        self.inflight_owner.pop(sender, None)
                        self.inflight_t.pop(sender, None)
                        self.piece_crashes.pop(self._piece_key(piece),
                                               None)
                        self._completion_stamps.append(time.monotonic())
                        if self.journal:    # exactly-once: a resumed
                            # server will never requeue this piece
                            self.journal.completed(piece, sender)
                        self._resolve_hedge_win(sender, piece)
                        self._sweep_slo(piece)
                        self._maybe_sdc_audit(sender, piece)
                    elif sender in self._cancel_pending:
                        # the hedge LOSER finished before its cancel
                        # landed (its BATCHCANCELLED ack would have
                        # arrived first — DEALER/ROUTER pairs are FIFO):
                        # a duplicate completion.  Audit-journal it;
                        # replay does NOT count it as a completion.
                        dup = self._cancel_pending.pop(sender)
                        self.dup_completions += 1
                        if self.journal:
                            self.journal.dup_completed(dup, sender)
                        # redundant-execution voting: the loser ran the
                        # SAME content to completion — its fingerprint
                        # is a free comparison word against the winner's
                        self._sdc_compare(dup, via="hedge_dup")
                    if sender not in self.avail_workers \
                            and sender not in self.sdc_quarantine:
                        self.avail_workers.append(sender)
                        self._send_pending_scenario()
                elif sender in self.avail_workers:
                    self.avail_workers.remove(sender)
        elif name == b"PONG":
            # last_seen already stamped; a SimNode piggybacks progress
            # (simt, chunks done) on the reply — feed the straggler
            # detector so a stall is distinguishable from a long chunk
            data = unpackb(payload) if payload else None
            if isinstance(data, dict) and "simt" in data:
                self._note_progress(sender, data)
        elif name == b"BATCHWORLD" and from_worker:
            # per-world completion report from a packed assignment: the
            # demux leg of exactly-once — journal THAT piece completed
            # (or strike/requeue it on a per-world failure) while the
            # rest of the pack keeps running
            t0 = time.perf_counter()
            pack = self.inflight.get(sender)
            data = unpackb(payload) if payload else None
            if isinstance(pack, WorldPack) and isinstance(data, dict):
                i = int(data.get("world", -1))
                status = str(data.get("status", "completed"))
                if 0 <= i < len(pack) and i not in pack.done:
                    pack.done[i] = status
                    p = pack.pieces[i]
                    if status == "completed":
                        self.piece_crashes.pop(self._piece_key(p), None)
                        self._completion_stamps.append(time.monotonic())
                        if self.journal:
                            self.journal.completed(p, sender, world=i)
                    else:
                        self.worlds_failed += 1
                        self._report_clients(
                            f"world {i} of packed piece on worker "
                            f"{sender.hex()} {status} — piece striked")
                        self._piece_failed(p, pack.owners[i])
                    self._observe_demux(t0, kind="world", world=i,
                                        worker=sender.hex())
        elif name == b"OPTRESULT" and from_worker:
            # Trajectory-optimization result from an OPT BATCH piece
            # (diff/optimize.py via the OPT stack command): journal it
            # against the in-flight piece BEFORE the piece's completion
            # lands (the FIFO pair guarantees OPTRESULT precedes the
            # STATECHANGE out of OP), and fan a machine-readable
            # BATCHOPT report out to the clients.  The journal record
            # is audit data: replay ignores it for the queue math.
            data = unpackb(payload) if payload else None
            piece = self.inflight.get(sender)
            self.opt_results += 1
            if self.journal and piece is not None \
                    and not isinstance(piece, WorldPack):
                self.journal.opt_result(piece, sender, data)
            d = data if isinstance(data, dict) else {}
            msg = (f"OPT result from worker {sender.hex()}: objective "
                   f"{d.get('objective_first', '?')} -> "
                   f"{d.get('objective_last', '?')} in "
                   f"{d.get('iters', '?')} iters, hard LoS "
                   f"{d.get('hard_los_before', '?')} -> "
                   f"{d.get('hard_los_after', '?')}"
                   + (f", guard word {d['bad']}"
                      if d.get("bad", -1) != -1 else ""))
            print(f"server: {msg}")
            self._report_clients(msg)
            self._report_clients(msg, name=b"BATCHOPT", data=data)
        elif name == b"DEVPROF" and from_worker:
            # PROFILE DEVICE on a worker: journal the trace-window dir
            # (audit record; links the sweep's journal to the captured
            # XLA trace for scripts/devprof_report.py)
            data = unpackb(payload) if payload else None
            d = data if isinstance(data, dict) else {}
            if self.journal:
                self.journal.device_profile(sender,
                                            dir=d.get("dir", ""),
                                            chunks=d.get("chunks"))
            self._report_clients(
                f"worker {sender.hex()} device-profiling "
                f"{d.get('chunks', '?')} chunk(s) to {d.get('dir', '?')}")
        elif name == b"WORLDS":
            # WORLDS stack/client command: set the packing knobs
            # (payload dict) and/or read them back HEALTH-style
            data = unpackb(payload) if payload else None
            if isinstance(data, dict):
                if "pack" in data:
                    self.world_pack = bool(data["pack"])
                if "max" in data:
                    self.world_batch_max = max(1, int(data["max"]))
            sock.send_multipart(
                [sender, b"WORLDS", packb(self.worlds_payload())])
        elif name == b"MITIGATE":
            # MITIGATE stack/client command: flip the mitigation
            # engine (payload dict) and/or read its state back
            # HEALTH-style.  Disabling restores every actuator the
            # engine touched (mitigate.set_enabled).
            data = unpackb(payload) if payload else None
            if isinstance(data, dict) and "enabled" in data:
                self.mitigator.set_enabled(data["enabled"])
            sock.send_multipart(
                [sender, b"MITIGATE", packb(self.mitigator.payload())])
        elif name == b"SDCFP" and from_worker:
            # per-piece state fingerprint, shipped just BEFORE the
            # worker's STATECHANGE out of OP (FIFO pair: the piece is
            # still in ``inflight`` when this arrives) — record it for
            # the redundant-execution comparisons
            data = unpackb(payload) if payload else None
            piece = self.inflight.get(sender)
            if piece is None:
                # hedge loser: its piece left inflight when the winner
                # completed, but the cancelled copy still finished and
                # its word is exactly the comparison the dup path needs
                piece = self._cancel_pending.get(sender)
            if isinstance(data, dict) and piece is not None \
                    and not isinstance(piece, WorldPack):
                self._note_sdc_fp(sender, piece, data)
        elif name == b"SDC":
            # SDC stack/client command: flip the defense / set the
            # audit-sampling rate (payload dict) and/or read the state
            # back HEALTH-style
            data = unpackb(payload) if payload else None
            if isinstance(data, dict):
                if "enabled" in data:
                    self.sdc_enabled = bool(data["enabled"])
                if "audit_rate" in data:
                    self.sdc_audit_rate = max(
                        0.0, float(data["audit_rate"] or 0.0))
            sock.send_multipart(
                [sender, b"SDC", packb(self.sdc_payload())])
        elif name == b"HA":
            # HA STATUS stack/client command: broker-HA state readback
            # (role, epoch, lease age, takeover/adoption counters)
            sock.send_multipart(
                [sender, b"HA", packb(self.ha_payload())])
        elif name == b"BATCHCANCELLED" and from_worker:
            # hedge loser acked the cancel (it had NOT completed: a
            # completion would have arrived first on the FIFO pair)
            if self._cancel_pending.pop(sender, None) is not None:
                self.hedges_cancelled += 1
        elif name == b"HEALTH":
            sock.send_multipart(
                [sender, b"HEALTH", packb(self.health_payload())])
        elif name == b"METRICS":
            # METRICS DUMP (stack/commands.py): broker registry + the
            # fleet aggregate merged from worker heartbeat deltas
            sock.send_multipart(
                [sender, b"METRICS", packb(self.metrics_payload())])
        elif name == b"TRACE":
            # TRACE DUMP reached the broker: dump ITS ring too, so the
            # report merger gets the server half of the timeline
            path = self.recorder.dump(reason="manual", proc="server") \
                if self.recorder.enabled and len(self.recorder) else None
            sock.send_multipart(
                [sender, b"TRACE",
                 packb({"path": path,
                        "enabled": bool(self.recorder.enabled),
                        "events": len(self.recorder)})])
        elif name == b"PREEMPTED" and from_worker:
            # a preempted worker drained its chunk, wrote a checkpoint
            # and is exiting: requeue its piece WITHOUT a circuit-
            # breaker strike (preemption is capacity churn, not a piece
            # fault) — the follow-up STATECHANGE(-1) then finds nothing
            # in flight, so no crash is counted either
            data = unpackb(payload) if payload else None
            piece = self.inflight.pop(sender, None)
            owner = self.inflight_owner.pop(sender, b"")
            self.inflight_t.pop(sender, None)
            if isinstance(piece, WorldPack):
                # preemption mid-pack is capacity churn, not a piece
                # fault: requeue ONLY the unfinished worlds' pieces,
                # no circuit-breaker strikes (completed worlds were
                # already journaled by their BATCHWORLD events)
                for i, powner, p in reversed(piece.remaining()):
                    self.scenarios.push_front(p, powner)
                    if self.journal:
                        self.journal.preempted(p, sender, world=i)
                while self.avail_workers and self.scenarios:
                    self._send_pending_scenario()
                piece = None
            if piece is not None and self._drop_hedge_links(sender) \
                    is not None:
                # the hedge partner still runs this piece — a preempted
                # hedge half neither requeues nor re-dispatches
                piece = None
            if piece is not None:
                self.scenarios.push_front(piece, owner)
                if self.journal:
                    self.journal.preempted(piece, sender)
                self._sweep_slo(piece)
                # hand the piece straight to an idle worker if one is
                # available — the preempted worker's own STATECHANGE(-1)
                # only spawns replacements, it does not dispatch
                while self.avail_workers and self.scenarios:
                    self._send_pending_scenario()
            ck = (data or {}).get("checkpoint", "")
            msg = (f"worker {sender.hex()} preempted"
                   + (f" (checkpoint: {ck})" if ck else "")
                   + (" — piece requeued" if piece is not None else ""))
            print(f"server: {msg}")
            self._report_clients(msg)
        elif name == b"MESHLOST" and from_worker:
            # a sharded worker lost a device group mid-piece.  Two
            # shapes: recovered=True — the worker re-formed a survivor
            # mesh, restored its last checksummed snapshot and is STILL
            # running the same piece (audit records only, the piece
            # stays in flight); recovered=False — the worker could not
            # re-form a mesh: requeue WITHOUT a circuit-breaker strike,
            # PREEMPTED-style (device-group loss is capacity churn, not
            # a piece fault)
            data = unpackb(payload) if payload else None
            ev = data if isinstance(data, dict) else {}
            epoch = ev.get("epoch")
            lost = ev.get("lost_groups")
            if ev.get("recovered", True):
                piece = self.inflight.get(sender)
                if self.journal and piece is not None:
                    if isinstance(piece, WorldPack):
                        for i, _powner, p in piece.remaining():
                            self.journal.mesh_lost(p, sender, world=i,
                                                   epoch=epoch,
                                                   lost=lost)
                            self.journal.resharded(
                                p, sender, world=i, epoch=epoch,
                                ndev=ev.get("ndev"),
                                mode=ev.get("mode"))
                    else:
                        self.journal.mesh_lost(piece, sender,
                                               epoch=epoch, lost=lost)
                        self.journal.resharded(piece, sender,
                                               epoch=epoch,
                                               ndev=ev.get("ndev"),
                                               mode=ev.get("mode"))
                if ev.get("degraded") and piece is not None:
                    # mitigation: accept the degraded epoch instead of
                    # requeueing — journaled so the acceptance audits
                    self.mitigator.on_mesh_degraded(sender, piece,
                                                    epoch,
                                                    ev.get("ndev"))
                msg = (f"worker {sender.hex()} mesh epoch {epoch}: "
                       f"lost group(s) {lost}, resharded to "
                       f"{ev.get('ndev')} device(s) "
                       f"({ev.get('mode')})"
                       + (" [degraded]" if ev.get("degraded") else "")
                       + (", restored from snapshot"
                          if ev.get("restored") else "")
                       + " — piece continues")
            else:
                piece = self.inflight.pop(sender, None)
                owner = self.inflight_owner.pop(sender, b"")
                self.inflight_t.pop(sender, None)
                if isinstance(piece, WorldPack):
                    for i, powner, p in reversed(piece.remaining()):
                        self.scenarios.push_front(p, powner)
                        if self.journal:
                            self.journal.mesh_lost(p, sender, world=i,
                                                   epoch=epoch,
                                                   lost=lost)
                    while self.avail_workers and self.scenarios:
                        self._send_pending_scenario()
                    piece = None
                if piece is not None and self._drop_hedge_links(sender) \
                        is not None:
                    piece = None
                if piece is not None:
                    self.scenarios.push_front(piece, owner)
                    if self.journal:
                        self.journal.mesh_lost(piece, sender,
                                               epoch=epoch, lost=lost)
                    self._sweep_slo(piece)
                    while self.avail_workers and self.scenarios:
                        self._send_pending_scenario()
                msg = (f"worker {sender.hex()} mesh lost "
                       f"(epoch {epoch}, group(s) {lost}) — no "
                       f"survivor mesh"
                       + (", piece requeued" if piece is not None
                          else ""))
            print(f"server: {msg}")
            self._report_clients(msg)
        elif name == b"BATCH":
            data = unpackb(payload)
            if self.ha_role and not self._ha_serving:
                # warm standby: NEVER admit work before holding the
                # lease — admission would journal ``queued`` records
                # into a file the live leader still owns
                self.rejected_batches += 1
                sock.send_multipart(
                    [sender, b"BATCHREJECTED",
                     packb({"reason": "standby",
                            "epoch": int(self.ha_epoch)})])
                return
            pieces = split_scenarios(data["scentime"], data["scencmd"])
            # Admission control: a flood of submissions must not grow
            # the pending queue (and its journal) without bound.  The
            # over-limit submitter gets a structured refusal with the
            # queue state and a drain-rate-informed retry hint; the
            # queue and journal stay untouched.
            depth = len(self.scenarios)
            if self.batch_queue_max \
                    and depth + len(pieces) > self.batch_queue_max:
                self.rejected_batches += 1
                sock.send_multipart(
                    [sender, b"BATCHREJECTED",
                     packb({"queue_depth": depth,
                            "limit": self.batch_queue_max,
                            "submitted": len(pieces),
                            "retry_after": self._retry_after(
                                len(pieces))})])
                return
            if self.journal:
                # one flush+fsync for the whole submission — per-piece
                # syncs would stall the poll loop on large sweeps.
                # Synthetic pieces (FAULT LOADSPIKE chaos filler) are
                # marked so replay's exactly-once accounting skips
                # them: a resumed sweep is never owed load-spike noise.
                self.journal.queued_many(
                    pieces, synthetic=bool(data.get("synthetic")))
            self.scenarios.extend(pieces, owner=sender)
            while self.avail_workers and self.scenarios:
                self._send_pending_scenario()
            if self.scenarios:
                self._spawn_for_backlog()
        elif name == b"QUIT":
            for wid in self.workers:
                self.be_event.send_multipart([wid, b"QUIT", packb(None)])
            self.running = False
        elif from_worker:
            # unaddressed worker output (e.g. scenario-triggered ECHO with
            # no issuing client): fan out to every connected client
            for cid in self.clients:
                self.fe_event.send_multipart([cid, sender, name, payload])

    def _send_pending_scenario(self):
        if self.ha_role and not self._ha_serving:
            return                 # standby never dispatches pre-lease
        if not (self.avail_workers and self.scenarios):
            return
        wid = self.avail_workers.pop(0)
        # World packing (WORLDS command / settings.world_pack): fill up
        # to world_batch_max compatible pieces into ONE assignment.
        # Compatibility is per worker-bucket by construction (every
        # world sim shares the worker's nmax); a piece requesting
        # shard_mode=spatial never joins a pack — it dispatches solo
        # with a structured WORLDSREFUSED echo instead of a crash.
        wmax = max(1, int(self.world_batch_max)) if self.world_pack \
            else 1
        if wmax > 1 and self.avail_workers:
            # spread across the idle fleet: pack only the share the
            # OTHER idle workers can't take — packing exists to
            # oversubscribe one device, not to starve idle ones
            share = -(-len(self.scenarios)
                      // (len(self.avail_workers) + 1))
            wmax = max(1, min(wmax, share))
        picks = []
        # pack_fill span (ISSUE-12 satellite): the world-pack fill loop
        # — compatibility checks + fairness-queue pops — was invisible
        # to the PR-11 recorder; a complete event keeps the solo path
        # (wmax == 1) untouched
        t_fill0 = time.perf_counter() \
            if wmax > 1 and self.recorder.enabled else None
        while len(picks) < wmax and self.scenarios:
            owner, piece = self.scenarios.pop_next()
            if self.scenarios.last_wait_s is not None:
                self.obs.get("server_queue_wait_s").observe(
                    self.scenarios.last_wait_s)
            solo_why = self._piece_solo_reason(piece) \
                if self.world_pack and wmax > 1 else None
            if solo_why and picks:
                # pack already filling: refuse the solo-only piece from
                # THIS pack with a structured echo — exactly once,
                # because the piece keeps its fairness turn and takes
                # the worker SOLO (a requeue would let the FairQueue
                # rotation re-refuse it on every pack fill); the
                # pieces already picked go back to their owners' queue
                # heads and pack on the next idle worker.  A solo-only
                # piece popped with the pack still empty just takes
                # the 1-piece solo path below: nothing was refused.
                if solo_why == "shard_mode=spatial":
                    self.worlds_refused_spatial += 1
                else:
                    self.worlds_refused_opt += 1
                pname = self._piece_name(piece)
                why_txt = ("requests shard_mode=spatial — refused from "
                           "the world-batch, dispatching it unpacked "
                           "(world-batching and spatial stripes compose "
                           "later, not now)"
                           if solo_why == "shard_mode=spatial" else
                           "is an OPT/GRAD piece — refused from the "
                           "world-batch, dispatching it unpacked (the "
                           "optimizer multi-starts on the world axis "
                           "internally and its OPTRESULT needs the "
                           "worker's own event socket)")
                msg = f"WORLDS: piece '{pname}' {why_txt}"
                print(f"server: {msg}")
                self._report_clients(msg)
                self._report_clients(
                    msg, name=b"WORLDSREFUSED",
                    data={"piece": pname, "reason": solo_why,
                          "scencmd": list(piece[1])})
                for powner, p in reversed(picks):
                    self.scenarios.push_front(p, powner)
                picks = [(owner, piece)]
                break
            picks.append((owner, piece))
            if solo_why:
                break    # solo-only piece dispatches alone, never packs
        if t_fill0 is not None:
            rec = self.recorder
            rec.complete("pack_fill", rec.wall_us(t_fill0),
                         (time.perf_counter() - t_fill0) * 1e6,
                         cat="server", wmax=wmax, npicks=len(picks),
                         worker=wid.hex())
        self.inflight_t[wid] = time.monotonic()
        prog = self.worker_progress.get(wid)
        if prog is not None:               # straggler clock restarts at
            prog["advance_t"] = self.inflight_t[wid]   # dispatch
        if len(picks) == 1:
            owner, piece = picks[0]
            self.inflight[wid] = piece     # held until the worker leaves OP
            self.inflight_owner[wid] = owner
            if self.journal:
                self.journal.dispatched(piece, wid)
            scentime, scencmd = piece
            self.be_event.send_multipart(
                [wid, b"BATCH", packb({"scentime": scentime,
                                       "scencmd": scencmd})])
            return
        pack = WorldPack(picks)
        self.inflight[wid] = pack
        self.inflight_owner[wid] = b""     # owners tracked per world
        self.packed_pieces += len(pack)
        self.world_batches += 1
        self._pack_fill_sum += len(pack) / wmax
        if self.journal:
            for i, (_owner, p) in enumerate(picks):
                self.journal.dispatched(p, wid, world=i,
                                        pack=len(pack))
        self.be_event.send_multipart(
            [wid, b"BATCH",
             packb({"worlds": [{"scentime": p[0], "scencmd": p[1]}
                               for _o, p in picks]})])

    # -------------------------------------------- broker HA (ISSUE-18)
    def _ha_renew_dt(self):
        """Lease-renew cadence: well inside the ttl (a renewal must
        land several times per lease or a busy poll loop looks dead)."""
        return min(self.ha_poll_dt, max(self.ha_lease_ttl / 3.0, 0.05))

    def _ha_acquire(self):
        """Leader start-up: take the lease.  The epoch is one past the
        highest ever seen (journal lease records OR the lease file), so
        a restarted/promoted leader always fences its predecessor's
        late appends — and the lease record lands in the journal BEFORE
        any sweep record this leader writes."""
        from . import ha as _ha
        tail = _ha.JournalTail(self.journal.path)
        tail.poll()
        lease = _ha.read_lease(self._ha_lease_file) or {}
        seen = max(int(lease.get("epoch", 0) or 0), tail.epoch,
                   self.ha_epoch)
        self.ha_epoch = seen + 1
        self.journal.epoch = self.ha_epoch
        self.journal.lease(leader=self.server_id.hex(),
                           epoch=self.ha_epoch, ttl=self.ha_lease_ttl)
        _ha.write_lease(self._ha_lease_file, self.server_id.hex(),
                        self.ha_epoch, self.ha_lease_ttl)
        self._ha_next_renew = time.monotonic() + self._ha_renew_dt()
        print(f"server: HA leader {self.server_id.hex()} acquired "
              f"lease epoch {self.ha_epoch} "
              f"(ttl {self.ha_lease_ttl:g}s)")

    def _ha_renew(self, now):
        """Refresh the lease file's stamp (the journal record is the
        durable acquisition; renewal is file-only and cheap)."""
        from . import ha as _ha
        _ha.write_lease(self._ha_lease_file, self.server_id.hex(),
                        self.ha_epoch, self.ha_lease_ttl)
        self._ha_next_renew = now + self._ha_renew_dt()

    def _ha_standby_poll(self, now):
        """Standby heartbeat: tail the journal (warm replay state),
        watch the lease, and take over only after the leader has been
        silent for its full promised ttl."""
        from . import ha as _ha
        self._ha_tail.poll()
        lease = _ha.read_lease(self._ha_lease_file)
        if lease is not None:
            ep = int(lease.get("epoch", 0) or 0)
            if ep > self.ha_epoch:
                self.ha_epoch = ep         # track the live leader
        if not _ha.is_stale(lease, default_ttl=self.ha_lease_ttl):
            self._ha_stale_since = None
            return
        if lease is None:
            # no lease file at all: the leader may simply not have
            # started yet — demand a full ttl of OBSERVED absence
            if self._ha_stale_since is None:
                self._ha_stale_since = now
                return
            if now - self._ha_stale_since < self.ha_lease_ttl:
                return
        self._ha_takeover(lease)

    def _ha_takeover(self, stale_lease):
        """The lease went silent: become the leader.  Succession is
        journal-fenced — our own ``lease`` record (epoch N+1) is
        appended FIRST, so everything the deposed leader manages to
        append after it carries a stale ``wepoch`` and replay fences it
        off as audit-only.  Then the whole sweep state carries over
        from a full replay: quarantines, strikes, completions, and an
        owed-pieces limbo that surviving workers' re-REGISTERs adopt
        from during a grace window (leftovers requeue after it)."""
        from . import ha as _ha
        from .journal import BatchJournal
        old = int((stale_lease or {}).get("epoch", 0) or 0)
        self.ha_epoch = max(old, self._ha_tail.epoch,
                            self.ha_epoch) + 1
        self.ha_role = "leader"
        self._ha_serving = True
        self.ha_takeovers += 1
        self._ha_stale_since = None
        self.journal.epoch = self.ha_epoch
        self.journal.lease(leader=self.server_id.hex(),
                           epoch=self.ha_epoch, ttl=self.ha_lease_ttl)
        _ha.write_lease(self._ha_lease_file, self.server_id.hex(),
                        self.ha_epoch, self.ha_lease_ttl)
        now = time.monotonic()
        self._ha_next_renew = now + self._ha_renew_dt()
        try:
            state = BatchJournal.replay(
                self.journal.path,
                fence_strict=self.ha_fence_strict)
        except OSError as e:
            print(f"server: HA takeover replay failed ({e}) — "
                  f"serving with an empty queue")
            state = None
        if state is not None:
            self._ha_fold_state(state)
        self.journal.append("resumed", pending=len(self._ha_limbo),
                            completed=sum(self._ha_completed.values()),
                            quarantined=len(self.quarantined),
                            takeover=True)
        # adoption grace: long enough for every surviving worker to
        # notice the dead socket, re-discover and re-REGISTER.  A
        # worker only declares the server dead after 1.5x ttl of
        # silence, then probes (rate-limited to ttl/4) with a 0.5 s
        # collect window — 3x ttl from takeover covers that worst case
        # with slack; the 2 s floor absorbs scheduler jitter at tiny
        # ttls.
        grace = max(3.0 * self.ha_lease_ttl, 3.0 * self.hb_interval,
                    2.0)
        self._ha_grace_until = now + grace
        msg = (f"HA: standby {self.server_id.hex()} took over as "
               f"leader, epoch {self.ha_epoch} — "
               f"{len(self._ha_limbo)} owed piece(s) awaiting "
               f"adoption ({grace:g}s grace), "
               f"{sum(self._ha_completed.values())} already complete")
        print(f"server: {msg}")
        self._report_clients(msg)

    def _ha_fold_state(self, state):
        """Carry the deposed leader's sweep state over from replay:
        quarantines (with their client-visible reports), crash strikes,
        the owed-pieces multiset (held in LIMBO for worker adoption,
        not requeued yet), per-key completion counts for raced-
        completion dedupe, placed SDC votes, and worker quarantines
        from the mitigation decision history."""
        from .journal import BatchJournal
        for piece in state["quarantined"]:
            self.quarantined.append(piece)
            self.quarantine_reports.append(
                {"piece": self._piece_name(piece),
                 "crashes": state["quarantined_crashes"].get(
                     BatchJournal.piece_key(piece), 0),
                 "scencmd": list(piece[1]), "resumed": True})
        for piece in state["pending"]:
            jkey = BatchJournal.piece_key(piece)
            if jkey in state["crashes"]:
                self.piece_crashes[self._piece_key(piece)] = \
                    state["crashes"][jkey]
        self._ha_limbo = list(state["pending"])
        self._ha_pieces = {}
        for piece in state["pending"] + state["completed"]:
            self._ha_pieces.setdefault(
                BatchJournal.piece_key(piece), piece)
        self._ha_completed = dict(collections.Counter(
            BatchJournal.piece_key(p) for p in state["completed"]))
        for vote in state.get("sdc", {}).get("votes", []):
            if vote.get("key"):
                self._sdc_voted.add(vote["key"])
        for m in state.get("mitigations", []):
            try:
                wid = bytes.fromhex(m.get("target", ""))
            except ValueError:
                continue
            if m.get("action") == "quarantine_worker":
                self.sdc_quarantine.add(wid)
            elif m.get("action") == "release_worker":
                self.sdc_quarantine.discard(wid)

    def _ha_adopt(self, wid, report):
        """Fold one re-REGISTERing worker's in-flight report into the
        post-takeover reconciliation.  A report matching an owed limbo
        copy ADOPTS it: the piece keeps running where it is — no
        requeue, no breaker strike (the PREEMPTED capacity-churn model
        generalized to leadership churn), journaled ``adopted``.  A
        report whose content is already fully counted is a completion
        that raced the failover (or a surviving hedge twin): that copy
        is cancelled, and a completion that still lands dedupes through
        the existing ``dup_completed`` cancel path.  Inert (empty maps)
        unless a takeover populated the limbo."""
        if not isinstance(report, dict):
            return
        key = str(report.get("key") or "")
        if not key or wid in self.inflight:
            return                 # idempotent duplicate re-REGISTER
        if not (self._ha_limbo or self._ha_pieces):
            return
        from .journal import BatchJournal
        for i, piece in enumerate(self._ha_limbo):
            if BatchJournal.piece_key(piece) == key:
                self._ha_limbo.pop(i)
                self.inflight[wid] = piece
                self.inflight_owner[wid] = b""
                self.inflight_t[wid] = time.monotonic()
                self.ha_adoptions += 1
                if self.journal:
                    self.journal.adopted(piece, wid)
                msg = (f"HA: piece '{self._piece_name(piece)}' still "
                       f"running on surviving worker {wid.hex()} — "
                       f"adopted in place, no requeue")
                print(f"server: {msg}")
                self._report_clients(msg)
                return
        piece = self._ha_pieces.get(key)
        if piece is not None and self._ha_completed.get(key, 0) > 0:
            # every owed copy of this content is accounted for: the
            # completion raced the failover — cancel the survivor's
            # redundant copy (a completion beating the cancel lands as
            # an audit-only ``dup_completed``, exactly the hedge-loser
            # path)
            self._cancel_pending[wid] = piece
            self.ha_dedup_cancels += 1
            self.be_event.send_multipart(
                [wid, b"BATCHCANCEL", packb(None)])
            print(f"server: HA: worker {wid.hex()} reports already-"
                  f"counted piece '{self._piece_name(piece)}' — "
                  f"cancelled (raced-completion dedupe)")

    def _ha_release_limbo(self):
        """Adoption grace expired: requeue the owed copies nobody
        adopted (their workers died with the old leader) and kick the
        dispatch loop."""
        pieces, self._ha_limbo = self._ha_limbo, []
        self._ha_grace_until = 0.0
        if not pieces:
            return
        print(f"server: HA adoption grace over — requeueing "
              f"{len(pieces)} unadopted piece(s)")
        self.scenarios.extend(pieces)
        while self.avail_workers and self.scenarios:
            self._send_pending_scenario()
        if self.scenarios and self.spawn_workers:
            self._spawn_for_backlog()

    def ha_payload(self):
        """Machine-readable broker-HA state (the ``HA`` command and the
        HEALTH ``ha`` section), with a human ``text`` rendering — the
        HEALTH-style readback contract."""
        from . import ha as _ha
        if not self.ha_role:
            return {"enabled": False,
                    "text": "HA OFF: single-broker mode (settings."
                            "ha_standby / Server(ha_role=...) runs a "
                            "warm standby)"}
        lease = _ha.read_lease(self._ha_lease_file)
        d = {"enabled": True,
             "role": "leader" if self._ha_serving else "standby",
             "epoch": int(self.ha_epoch),
             "lease_ttl": float(self.ha_lease_ttl),
             "poll_dt": float(self.ha_poll_dt),
             "fence_strict": bool(self.ha_fence_strict),
             "lease_file": self._ha_lease_file,
             "lease_age": round(_ha.lease_age(lease), 3)
             if lease else None,
             "lease_leader": str(lease.get("leader", ""))
             if lease else None,
             "takeovers": self.ha_takeovers,
             "adoptions": self.ha_adoptions,
             "dedup_cancels": self.ha_dedup_cancels,
             "limbo": len(self._ha_limbo)}
        if self._ha_tail is not None:
            d["tail"] = {"records": self._ha_tail.records,
                         "leases": self._ha_tail.leases,
                         "epoch": self._ha_tail.epoch}
        d["text"] = (
            f"HA {d['role'].upper()}: epoch {d['epoch']}, lease ttl "
            f"{d['lease_ttl']:g}s"
            + (f", lease age {d['lease_age']:g}s"
               if d["lease_age"] is not None else ", no lease file")
            + f"; {d['takeovers']} takeover(s), "
              f"{d['adoptions']} adoption(s), "
              f"{d['dedup_cancels']} dedup cancel(s)"
            + (f", {d['limbo']} piece(s) in adoption limbo"
               if d["limbo"] else ""))
        return d

    # ------------------------------------------- stragglers / introspection
    def _note_progress(self, wid, data):
        """Fold a progress heartbeat (PONG payload from a SimNode) into
        the per-worker record: sim-time/chunk counters, the stamp of
        the last *advance*, and an EMA progress rate [sim s / wall s].
        A BATCH dispatch resets the sim (simt drops to 0), so chunk
        count — monotonic per worker process — is the advance signal;
        simt deltas feed the rate."""
        now = time.monotonic()
        # fleet telemetry: heartbeats piggyback the worker's metric
        # increments since its last report; merging deltas commutes,
        # so out-of-order arrivals from W workers aggregate exactly
        obs_delta = data.get("obs")
        if obs_delta:
            self.fleet.merge(obs_delta)
        simt = float(data.get("simt", 0.0))
        chunks = int(data.get("chunks", 0))
        prev = self.worker_progress.get(wid)
        if prev is None:
            self.worker_progress[wid] = {
                "simt": simt, "chunks": chunks, "rate": 0.0,
                "t": now, "advance_t": now,
                "state": data.get("state"),
                "ff": bool(data.get("ff", False)),
                "mesh": data.get("mesh"),
                "scan": data.get("scan"),
                "fp": data.get("fp")}
            return
        dt = now - prev["t"]
        if chunks > prev["chunks"] or simt > prev["simt"] + 1e-9:
            if dt > 1e-6 and simt > prev["simt"]:
                inst = (simt - prev["simt"]) / dt
                prev["rate"] = inst if prev["rate"] <= 0.0 \
                    else 0.5 * prev["rate"] + 0.5 * inst
            prev["advance_t"] = now
        prev.update(simt=simt, chunks=chunks, t=now,
                    state=data.get("state"),
                    ff=bool(data.get("ff", False)),
                    mesh=data.get("mesh", prev.get("mesh")),
                    scan=data.get("scan", prev.get("scan")),
                    fp=data.get("fp", prev.get("fp")))

    def _check_stragglers(self, now):
        """Speculative straggler re-dispatch: an in-flight piece whose
        worker keeps sending progress heartbeats (so it is alive — a
        worker blocked in a long first-compile sends NONE and is left
        to the busy-PING budget) but whose progress has not advanced
        for ``straggler_timeout`` — or whose rate sits far below the
        fleet median — is hedged to an idle worker.  First completion
        wins; the loser is cancelled.

        With ``hedge_enabled`` off but the mitigation engine on, a
        detected straggler is handed to the engine instead: mitigation
        IS the operator typing the hedge, gated by its rate limits,
        backoff and budget (network/mitigate.py)."""
        if not (self.hedge_enabled or self.mitigator.enabled) \
                or self.straggler_timeout <= 0 \
                or not self.avail_workers:
            return
        fresh = 3.0 * self.hb_interval     # report recency window
        # The fleet-median rate is only meaningful across workers
        # running FULL SPEED (fast-forward sweep pieces): a wall-clock
        # paced piece reports ~dtmult sim-s/s by design, and hedging
        # it on "low rate" would burn a second worker on a copy that
        # cannot finish any earlier.  Stall detection (flat progress)
        # still covers non-FF pieces.
        median = self._fresh_ff_median(now)
        for wid, piece in list(self.inflight.items()):
            if not self.avail_workers:
                return
            if isinstance(piece, WorldPack):
                continue                   # packs are not hedged: a
                #                            second copy would duplicate
                #                            W pieces for one straggler
            if wid in self.hedge_by or wid in self.hedge_of:
                continue                   # one hedge per piece
            prog = self.worker_progress.get(wid)
            if prog is None or now - prog["t"] > fresh:
                continue                   # silent, not stalled
            age = now - self.inflight_t.get(wid, now)
            if age <= self.straggler_timeout:
                continue                   # dispatch grace period
            stalled = now - prog["advance_t"] > self.straggler_timeout
            slow = median is not None and prog.get("ff") \
                and prog["rate"] < self.hedge_rate_factor * median
            if stalled or slow:
                why = "stalled" if stalled else \
                    f"rate {prog['rate']:.2f} << median {median:.2f}"
                if self.hedge_enabled:
                    self._dispatch_hedge(wid, piece, why)
                else:
                    self.mitigator.on_straggler(wid, piece, why, now)

    def _fresh_ff_median(self, now):
        """Fleet-median progress rate over fresh fast-forward reports
        (the hedge detector's yardstick, shared by the SLO watch)."""
        fresh = 3.0 * self.hb_interval
        rates = [p["rate"] for w, p in self.worker_progress.items()
                 if w in self.inflight and p["rate"] > 0.0
                 and p.get("ff") and now - p["t"] <= fresh]
        return statistics.median(rates) if len(rates) >= 2 else None

    def _check_perf_slo(self, now):
        """Serving-side SLO watch (ISSUE-12): journal ONE
        ``perf_regression`` audit record per (worker, piece) whose
        rolling FF rate sits below ``perf_slo_factor`` x the fleet
        median.  Pure observation — the piece stays in flight and the
        queue math never sees the record; hedging (if enabled) remains
        the mitigation."""
        if self.perf_slo_factor <= 0.0:
            return
        median = self._fresh_ff_median(now)
        self._slo_median = median
        if median is None:
            return
        fresh = 3.0 * self.hb_interval
        from .journal import BatchJournal
        for wid, piece in list(self.inflight.items()):
            if isinstance(piece, WorldPack):
                continue               # pack rates aggregate W pieces
            prog = self.worker_progress.get(wid)
            if prog is None or now - prog["t"] > fresh \
                    or not prog.get("ff") or prog["rate"] <= 0.0:
                continue
            if now - self.inflight_t.get(wid, now) \
                    <= self.straggler_timeout:
                continue               # dispatch/compile grace period
            if prog["rate"] >= self.perf_slo_factor * median:
                continue
            key = (wid, BatchJournal.piece_key(piece))
            if key in self._slo_flagged:
                continue               # once per (worker, piece)
            self._slo_flagged.add(key)
            self.perf_regressions += 1
            pname = self._piece_name(piece)
            self.recorder.instant("perf_regression", cat="server",
                                  piece=pname, worker=wid.hex(),
                                  rate=round(prog["rate"], 4),
                                  baseline=round(median, 4))
            if self.journal:
                self.journal.perf_regression(
                    piece, wid, rate=prog["rate"], baseline=median,
                    factor=self.perf_slo_factor)
            msg = (f"SLO: piece '{pname}' on worker {wid.hex()} "
                   f"running at {prog['rate']:.2f} sim-s/s vs fleet "
                   f"median {median:.2f} (< {self.perf_slo_factor:g}x)"
                   " — perf_regression journaled")
            print(f"server: {msg}")
            self._report_clients(msg)
            self._slo_recent.append(
                {"worker": wid.hex(), "piece": pname,
                 "rate": round(prog["rate"], 4),
                 "baseline": round(median, 4)})
            # mitigation: escalate a hedge for the flagged piece (the
            # engine gates with rate limit / backoff / budget; inert
            # when disabled)
            self.mitigator.on_perf_regression(wid, piece,
                                              prog["rate"], median,
                                              now)

    def _dispatch_hedge(self, wid, piece, why):
        """Send a second copy of ``wid``'s in-flight piece to an idle
        worker (first completion wins)."""
        hwid = self.avail_workers.pop(0)
        self.inflight[hwid] = piece
        self.inflight_owner[hwid] = self.inflight_owner.get(wid, b"")
        self.inflight_t[hwid] = time.monotonic()
        self.hedge_by[wid] = hwid
        self.hedge_of[hwid] = wid
        self.hedges_started += 1
        self.recorder.instant("hedge", cat="server",
                              piece=self._piece_name(piece),
                              primary=wid.hex(), hedge=hwid.hex(),
                              why=str(why))
        prog = self.worker_progress.get(hwid)
        if prog is not None:
            prog["advance_t"] = self.inflight_t[hwid]
        if self.journal:
            self.journal.hedged(piece, wid, hwid)
        pname = self._piece_name(piece)
        msg = (f"hedging BATCH piece '{pname}': worker {wid.hex()} "
               f"{why} — speculative copy to {hwid.hex()}")
        print(f"server: {msg}")
        self._report_clients(msg)
        scentime, scencmd = piece
        self.be_event.send_multipart(
            [hwid, b"BATCH", packb({"scentime": scentime,
                                    "scencmd": scencmd})])

    def _resolve_hedge_win(self, winner, piece):
        """First completion of a hedged piece wins: count who won and
        cancel the partner's still-running copy (``BATCHCANCEL``; the
        loser acks with ``BATCHCANCELLED``, or its own completion
        arrives first and is journaled as ``dup_completed``)."""
        if winner not in self.hedge_by and winner not in self.hedge_of:
            return
        was_hedge = winner in self.hedge_of
        partner = self._drop_hedge_links(winner)
        if was_hedge:
            self.hedges_won_hedge += 1
        else:
            self.hedges_won_primary += 1
        if partner is None:
            return                         # partner already gone
        self.inflight.pop(partner, None)
        self.inflight_owner.pop(partner, None)
        self.inflight_t.pop(partner, None)
        self._cancel_pending[partner] = piece
        self.be_event.send_multipart(
            [partner, b"BATCHCANCEL", packb(None)])
        print(f"server: hedge resolved — "
              f"{'hedge' if was_hedge else 'primary'} {winner.hex()} "
              f"won '{self._piece_name(piece)}', cancelling "
              f"{partner.hex()}")

    # --------------------------------------------- SDC defense (ISSUE-17)
    def _note_sdc_fp(self, wid, piece, data):
        """Record one execution's completion fingerprint, keyed by the
        piece's CONTENT key — redundant executions of identical content
        (hedge copies, votes, shadow audits) land in the same map and
        must agree bit-for-bit (the device fold is order-sensitive and
        deterministic for a fixed scenario)."""
        if not self.sdc_enabled:
            return
        from .journal import BatchJournal
        key = BatchJournal.piece_key(piece)
        fps = self._sdc_fps.get(key)
        if fps is None:
            fps = self._sdc_fps[key] = {}
            while len(self._sdc_fps) > 256:  # bound week-long sweeps
                self._sdc_fps.popitem(last=False)
        fps[wid.hex()] = str(data.get("fp", ""))
        self.recorder.instant("sdc_fp", cat="server", worker=wid.hex(),
                              key=key, fp=fps[wid.hex()])

    def _sdc_compare(self, piece, via="hedge_dup"):
        """Compare every fingerprint recorded for ``piece``'s content:
        a disagreement journals an audit-only ``sdc_suspect`` and (once
        per key) places the 2-of-3 tie-break re-execution."""
        if not self.sdc_enabled:
            return
        from .journal import BatchJournal
        key = BatchJournal.piece_key(piece)
        fps = self._sdc_fps.get(key) or {}
        words = {f for f in fps.values() if f}
        if len(fps) < 2 or len(words) <= 1:
            return                 # agreement, or nothing to compare
        self.sdc_suspects += 1
        pname = self._piece_name(piece)
        self.recorder.instant("sdc_suspect", cat="server", piece=pname,
                              via=via, fps=dict(fps))
        if self.journal:
            self.journal.sdc_suspect(piece, fps=fps, via=via)
        msg = ("SDC: fingerprint mismatch on piece "
               f"'{pname}' ({via}): "
               + ", ".join(f"{w[:8]}:{f}"
                           for w, f in sorted(fps.items()))
               + " — suspect journaled")
        print(f"server: {msg}")
        self._report_clients(msg)
        if key not in self._sdc_voted:
            self._dispatch_sdc_exec(piece, "vote", key)

    def _dispatch_sdc_exec(self, piece, kind, key):
        """Place a ``vote``/``audit`` re-execution of ``piece`` on an
        idle worker that has NOT already reported a word for this key
        (a repeat on the same worker would overwrite its own entry and
        can never break a tie).  The copy is journaled ``queued`` with
        ``synthetic: true`` — replay must never owe it to a resumed
        sweep — and its completion is intercepted by
        ``_finish_sdc_exec``: it NEVER journals ``completed``
        (content-addressed keys: a second completion would corrupt
        repeat-trial multiset math)."""
        fps = self._sdc_fps.get(key) or {}
        wid = next((w for w in self.avail_workers
                    if w not in self.sdc_quarantine
                    and w.hex() not in fps), None)
        if wid is None:
            print(f"server: SDC {kind} wanted for piece "
                  f"'{self._piece_name(piece)}' but no fresh idle "
                  f"worker — comparison skipped")
            return False
        self.avail_workers.remove(wid)
        self.inflight[wid] = piece
        self.inflight_owner[wid] = b""
        self.inflight_t[wid] = time.monotonic()
        prog = self.worker_progress.get(wid)
        if prog is not None:
            prog["advance_t"] = self.inflight_t[wid]
        self._sdc_execs[wid] = {"kind": kind, "key": key,
                                "piece": piece}
        if kind == "vote":
            self._sdc_voted.add(key)
        else:
            self.sdc_audits += 1
        if self.journal:
            self.journal.queued(piece, synthetic=True)
            self.journal.dispatched(piece, wid)
        pname = self._piece_name(piece)
        self.recorder.instant("sdc_exec", cat="server", kind=kind,
                              worker=wid.hex(), piece=pname)
        msg = (f"SDC: dispatching {kind} re-execution of piece "
               f"'{pname}' to worker {wid.hex()}")
        print(f"server: {msg}")
        self._report_clients(msg)
        scentime, scencmd = piece
        self.be_event.send_multipart(
            [wid, b"BATCH", packb({"scentime": scentime,
                                   "scencmd": scencmd})])
        return True

    def _finish_sdc_exec(self, wid):
        """A vote/audit re-execution left OP: resolve the comparison.
        An audit copy raises the suspect (and the vote) on mismatch; a
        vote resolves 2-of-3 — the out-voted worker is named in the
        ``sdc_vote`` record and handed to the mitigation engine for
        quarantine (its own gated ``mitigation`` record)."""
        info = self._sdc_execs.pop(wid)
        self.inflight.pop(wid, None)
        self.inflight_owner.pop(wid, None)
        self.inflight_t.pop(wid, None)
        kind, key, piece = info["kind"], info["key"], info["piece"]
        fps = dict(self._sdc_fps.get(key) or {})
        if kind == "audit":
            self._sdc_compare(piece, via="audit")
        else:
            self.sdc_votes += 1
            counts = collections.Counter(
                f for f in fps.values() if f)
            top = counts.most_common(1)
            deviants = []
            if top and top[0][1] >= 2:
                maj = top[0][0]
                deviants = sorted(w for w, f in fps.items()
                                  if f != maj)
            deviant = ",".join(deviants)
            pname = self._piece_name(piece)
            self.recorder.instant("sdc_vote", cat="server",
                                  piece=pname, fps=dict(fps),
                                  deviant=deviant)
            if self.journal:
                self.journal.sdc_vote(piece, fps=fps, deviant=deviant)
            msg = (f"SDC: vote on piece '{pname}' resolved: "
                   + ", ".join(f"{w[:8]}:{f}"
                               for w, f in sorted(fps.items()))
                   + (f" — deviant {deviant}" if deviant
                      else " — no majority (all words differ)"))
            print(f"server: {msg}")
            self._report_clients(msg)
            for dhex in deviants:
                try:
                    dwid = bytes.fromhex(dhex)
                except ValueError:
                    continue
                self.mitigator.on_sdc_deviant(
                    dwid, piece,
                    why=f"out-voted 2-of-3 fingerprint vote on "
                        f"'{pname}'")
            self._sdc_fps.pop(key, None)  # verdict reached
        # the exec worker rejoins the pool — unless the vote it just
        # completed named IT the deviant and quarantined it
        if wid not in self.avail_workers \
                and wid not in self.sdc_quarantine \
                and wid not in self.inflight \
                and self.workers.get(wid, 0) < 2:
            self.avail_workers.append(wid)
            self._send_pending_scenario()

    def _maybe_sdc_audit(self, wid, piece):
        """Deterministically sample completed fast-forward pieces for a
        shadow re-execution at ``sdc_audit_rate`` (0 = off): corruption
        that never hits a hedge duplicate still gets caught.  Wall-
        clock paced pieces are skipped — re-running one doubles its
        full wall time for a single comparison word."""
        if not self.sdc_enabled or self.sdc_audit_rate <= 0.0:
            return
        from .journal import BatchJournal
        key = BatchJournal.piece_key(piece)
        if not self._sdc_fps.get(key):
            return     # no fingerprint shipped: nothing to compare to
        prog = self.worker_progress.get(wid)
        if prog is not None and not prog.get("ff"):
            return
        self._audit_acc += min(1.0, self.sdc_audit_rate)
        if self._audit_acc < 1.0:
            return
        self._audit_acc -= 1.0
        self._dispatch_sdc_exec(piece, "audit", key)

    def sdc_payload(self):
        """Machine-readable SDC-defense state (the ``SDC`` command and
        the HEALTH ``sdc`` section), with a human ``text`` rendering —
        the HEALTH-style readback contract."""
        d = {"enabled": bool(self.sdc_enabled),
             "audit_rate": float(self.sdc_audit_rate),
             "suspects": self.sdc_suspects,
             "votes": self.sdc_votes,
             "audits": self.sdc_audits,
             "quarantined_workers": sorted(
                 w.hex() for w in self.sdc_quarantine),
             "tracked_pieces": len(self._sdc_fps),
             "pending_execs": len(self._sdc_execs)}
        d["text"] = (
            f"SDC {'ON' if d['enabled'] else 'OFF'}: "
            f"{d['suspects']} suspect(s), {d['votes']} vote(s), "
            f"{d['audits']} audit(s), "
            f"{len(d['quarantined_workers'])} worker(s) quarantined"
            + (f", audit rate {d['audit_rate']:g}"
               if d["audit_rate"] else "")
            + (" [" + ", ".join(w[:8]
                                for w in d["quarantined_workers"])
               + "]" if d["quarantined_workers"] else ""))
        return d

    def _retry_after(self, n_new):
        """Retry hint for a BATCHREJECTED: time for ``n_new`` slots to
        drain at the recently observed completion rate, else the
        settings default."""
        from .. import settings as _settings
        now = time.monotonic()
        recent = [t for t in self._completion_stamps if now - t < 60.0]
        if len(recent) >= 2 and now - recent[0] > 1e-3:
            rate = len(recent) / (now - recent[0])
            return round(min(max(n_new / rate, 1.0), 600.0), 1)
        return float(getattr(_settings, "batch_retry_after", 5.0))

    def worlds_payload(self):
        """Machine-readable world-batch state (the ``WORLDS`` command):
        packing knobs + packed-dispatch counters, with a human ``text``
        rendering — the HEALTH-style readback contract."""
        avg_fill = self._pack_fill_sum / self.world_batches \
            if self.world_batches else 0.0
        # demux latency comes from the registry histogram (windowed
        # p50/p95, not just a lifetime running mean — ISSUE-11 fix)
        dh = self.obs.get("server_demux_ms")
        d = {"pack": bool(self.world_pack),
             "batch_max": int(self.world_batch_max),
             "world_batches": self.world_batches,
             "packed_pieces": self.packed_pieces,
             "fill_ratio": round(avg_fill, 3),
             "refused_spatial": self.worlds_refused_spatial,
             "refused_opt": self.worlds_refused_opt,
             "opt_results": self.opt_results,
             "worlds_failed": self.worlds_failed,
             "demux_events": dh.count,
             "demux_ms_avg": round(dh.mean, 3),
             "demux_ms_p50": round(dh.percentile(0.5), 3),
             "demux_ms_p95": round(dh.percentile(0.95), 3)}
        d["text"] = (
            f"WORLDS: packing {'ON' if d['pack'] else 'OFF'}, max "
            f"{d['batch_max']} pieces/dispatch; {d['world_batches']} "
            f"world-batch(es) sent carrying {d['packed_pieces']} "
            f"piece(s), fill {d['fill_ratio']:.0%}; "
            f"{d['refused_spatial']} spatial + {d['refused_opt']} "
            f"OPT/GRAD refusal(s), "
            f"{d['worlds_failed']} world failure(s); demux "
            f"{d['demux_events']} event(s), avg {d['demux_ms_avg']:.2f} "
            f"ms, p95 {d['demux_ms_p95']:.2f} ms")
        return d

    def _observe_demux(self, t0, **tags):
        """Book one demux leg: the registry histogram (windowed
        p50/p95) + a demux span on the flight-recorder timeline."""
        now = time.perf_counter()
        self.obs.get("server_demux_ms").observe((now - t0) * 1e3)
        rec = self.recorder
        if rec.enabled:
            rec.complete("demux", rec.wall_us(t0), (now - t0) * 1e6,
                         cat="server", **tags)

    def metrics_payload(self):
        """Machine-readable telemetry (the ``METRICS DUMP`` command):
        the broker's own registry plus the fleet aggregate merged from
        worker heartbeat deltas, with a human ``text`` rendering."""
        self.obs.gauge("server_queue_depth").set(len(self.scenarios))
        d = {"server": self.obs.snapshot(),
             "fleet": self.fleet.snapshot()}
        fl = self.fleet.text()
        d["text"] = ("== server ==\n" + self.obs.text()
                     + ("\n== fleet (aggregated from worker "
                        "heartbeats) ==\n" + fl
                        if len(self.fleet) else ""))
        return d

    def health_payload(self):
        """Machine-readable serving-fabric health (the ``HEALTH``
        command): queue depth and per-client split, per-worker
        in-flight piece age / heartbeat staleness / progress rate,
        hedge + admission + stream-drop counters, plus a human-
        readable ``text`` rendering."""
        now = time.monotonic()
        workers = {}
        for wid, state in self.workers.items():
            w = {"state": state,
                 "hb_age": round(now - self.last_seen.get(wid, now), 3)}
            piece = self.inflight.get(wid)
            if piece is not None:
                w["piece"] = self._piece_name(piece)
                w["piece_age"] = round(
                    now - self.inflight_t.get(wid, now), 3)
                if wid in self.hedge_of:
                    w["hedge"] = "hedge"
                elif wid in self.hedge_by:
                    w["hedge"] = "hedged"
            prog = self.worker_progress.get(wid)
            if prog is not None:
                w["simt"] = round(prog["simt"], 3)
                w["rate"] = round(prog["rate"], 4)
                w["stalled_for"] = round(now - prog["advance_t"], 3)
                if isinstance(prog.get("mesh"), dict):
                    w["mesh"] = prog["mesh"]
                if isinstance(prog.get("scan"), dict):
                    w["scan"] = prog["scan"]
                if isinstance(prog.get("fp"), dict):
                    w["fp"] = prog["fp"]
            if wid in self.sdc_quarantine:
                w["quarantined"] = True
            workers[wid.hex()] = w
        # fleet mesh summary: the most advanced epoch any worker
        # reports (after a loss that is the worker that re-formed)
        mesh = None
        for w in workers.values():
            m = w.get("mesh")
            if isinstance(m, dict) and (
                    mesh is None
                    or m.get("epoch", 0) > mesh.get("epoch", 0)):
                mesh = m
        # fleet scan summary: worst case across workers (peaks max,
        # minima min) — same reduction the worlds pack applies
        from ..obs import scanstats as _scanstats
        scan = _scanstats.merge_summaries(
            [w["scan"] for w in workers.values()
             if isinstance(w.get("scan"), dict)])
        data = {
            "queue_depth": len(self.scenarios),
            "queue_limit": self.batch_queue_max,
            "queue_by_client": {o.hex(): n for o, n in
                                self.scenarios.depth_by_owner().items()},
            "inflight": len(self.inflight),
            "avail_workers": len(self.avail_workers),
            "workers": workers,
            "hedges": {"started": self.hedges_started,
                       "won_by_hedge": self.hedges_won_hedge,
                       "won_by_primary": self.hedges_won_primary,
                       "cancelled": self.hedges_cancelled,
                       "dup_completions": self.dup_completions},
            "rejected_batches": self.rejected_batches,
            "stream_drops": self.stream_drops,
            "quarantined": len(self.quarantined),
            "straggler_timeout": self.straggler_timeout,
            "hedge_enabled": bool(self.hedge_enabled),
            "worlds": {k: v for k, v in self.worlds_payload().items()
                       if k != "text"},
            # serving SLO watch + fleet compile telemetry (ISSUE-12):
            # the fleet counters arrive merged from worker heartbeat
            # obs deltas, so HEALTH shows recompiles fleet-wide
            "perf": {
                "slo_factor": self.perf_slo_factor,
                "regressions": self.perf_regressions,
                "fleet_median_rate": self._slo_median,
                "recent": list(self._slo_recent),
                "fleet_offladder_recompiles": int(getattr(
                    self.fleet.get("devprof_cache_misses_offladder"),
                    "value", 0) or 0),
                "fleet_ladder_warmups": int(getattr(
                    self.fleet.get("devprof_cache_misses_ladder"),
                    "value", 0) or 0),
            },
        }
        if mesh is not None:
            data["mesh"] = mesh
        if scan is not None:
            data["scan"] = scan
        # mitigation section ONLY while the engine is enabled: with
        # mitigate_enabled=0 the HEALTH payload must stay bit-identical
        # to a build without the engine (the audit-only contract)
        if self.mitigator.enabled:
            data["mitigation"] = {
                k: v for k, v in self.mitigator.payload().items()
                if k != "text"}
        # SDC section ONLY while the defense is enabled (same
        # audit-only contract as mitigation: sdc_enabled=0 keeps the
        # payload bit-identical to a build without the defense)
        if self.sdc_enabled:
            data["sdc"] = {k: v for k, v in self.sdc_payload().items()
                           if k != "text"}
        # broker-HA section ONLY while HA is configured (same contract:
        # ha_standby unset keeps HEALTH bit-identical to a build
        # without the subsystem)
        if self.ha_role:
            data["ha"] = {k: v for k, v in self.ha_payload().items()
                          if k != "text"}
        # journal growth watch (ISSUE-17 satellite): size + warn flag
        if self.journal is not None:
            jb = int(self.journal.size_bytes)
            self.obs.gauge("server_journal_bytes").set(jb)
            data["journal"] = {
                "path": self.journal.path, "bytes": jb,
                "warn_bytes": self.journal_warn_bytes,
                "warn": bool(self.journal_warn_bytes
                             and jb >= self.journal_warn_bytes)}
        data["text"] = self._health_text(data)
        return data

    @staticmethod
    def _health_text(d):
        lines = [f"queue: {d['queue_depth']}"
                 + (f"/{d['queue_limit']}" if d['queue_limit'] else "")
                 + f" pending ({len(d['queue_by_client'])} client(s)), "
                 f"{d['inflight']} in flight, "
                 f"{d['avail_workers']} idle worker(s)",
                 "hedges: {started} started, {won_by_hedge} won by "
                 "hedge, {won_by_primary} by primary, {cancelled} "
                 "cancelled, {dup_completions} duplicate "
                 "completion(s)".format(**d["hedges"]),
                 f"admission: {d['rejected_batches']} BATCH submission"
                 f"(s) rejected; stream drops: {d['stream_drops']}; "
                 f"quarantined: {d['quarantined']}"]
        w = d.get("worlds")
        if w:
            lines.append(
                f"worlds: packing {'ON' if w['pack'] else 'OFF'} "
                f"(max {w['batch_max']}), {w['world_batches']} "
                f"batch(es)/{w['packed_pieces']} packed piece(s), "
                f"fill {w['fill_ratio']:.0%}, "
                f"{w['refused_spatial']} spatial + "
                f"{w['refused_opt']} OPT/GRAD refusal(s), "
                f"{w['opt_results']} OPT result(s), "
                f"demux avg {w['demux_ms_avg']:.2f} ms")
        m = d.get("mesh")
        if m:
            lines.append(
                f"mesh: epoch {m.get('epoch', 0)}, "
                f"{m.get('devices', 0)} device(s), "
                f"mode {m.get('mode', 'off')}, last refresh "
                f"{m.get('last_refresh_ms', 0):g} ms"
                + (" [DEGRADED]" if m.get("degraded") else ""))
        sc = d.get("scan")
        if sc:
            ms = sc.get("min_sep_m")
            lines.append(
                f"sim: in-scan conflicts peak {sc.get('conf_peak', 0)}"
                f"/mean {sc.get('conf_mean', 0):g}, LoS peak "
                f"{sc.get('los_peak', 0)}, min sep "
                + (f"{ms:g} m" if ms is not None else "n/a")
                + f", clamp-sat {sc.get('clamp_sat_ratio', 0):.1%}, "
                  f"occ peak {sc.get('occ_peak', 0)}")
        mi = d.get("mitigation")
        if mi:
            b = mi.get("budget", {})
            taken = sum(mi.get("actions", {}).values())
            supp = sum(mi.get("suppressed", {}).values())
            lines.append(
                f"mitigation: ON, {taken} action(s), {supp} "
                "suppressed, budget "
                + (f"{b.get('remaining')}/{b.get('total')} left"
                   if b.get("total") else "unbounded")
                + (", SHEDDING" if mi.get("shed_active") else "")
                + (", REPACKED" if mi.get("repack_active") else ""))
        s = d.get("sdc")
        if s:
            lines.append(
                f"sdc: ON, {s['suspects']} suspect(s), "
                f"{s['votes']} vote(s), {s['audits']} audit(s), "
                f"{len(s['quarantined_workers'])} worker(s) "
                "quarantined"
                + (f", audit rate {s['audit_rate']:g}"
                   if s["audit_rate"] else "")
                + (" [" + ", ".join(w[:8] for w
                                    in s["quarantined_workers"]) + "]"
                   if s["quarantined_workers"] else ""))
        h = d.get("ha")
        if h:
            lines.append(
                f"ha: {h['role'].upper()}, epoch {h['epoch']}, lease "
                f"ttl {h['lease_ttl']:g}s"
                + (f", lease age {h['lease_age']:g}s"
                   if h.get("lease_age") is not None
                   else ", no lease file")
                + f", {h['takeovers']} takeover(s), "
                  f"{h['adoptions']} adoption(s), "
                  f"{h['dedup_cancels']} dedup cancel(s)"
                + (f", {h['limbo']} in limbo" if h.get("limbo")
                   else ""))
        j = d.get("journal")
        if j:
            lines.append(
                f"journal: {j['bytes']} bytes ({j['path']})"
                + (f" — WARNING: past journal_warn_bytes "
                   f"{j['warn_bytes']}" if j["warn"] else ""))
        p = d.get("perf")
        if p:
            med = p.get("fleet_median_rate")
            lines.append(
                "perf: SLO watch "
                + (f"{p['slo_factor']:g}x median"
                   if p["slo_factor"] else "OFF")
                + f", {p['regressions']} regression record(s)"
                + (f", fleet median {med:.2f} sim-s/s"
                   if isinstance(med, (int, float)) else "")
                + f"; compiles fleet-wide: "
                  f"{p['fleet_ladder_warmups']} ladder warm-up(s), "
                  f"{p['fleet_offladder_recompiles']} off-ladder")
        for wid, w in d["workers"].items():
            line = (f"  {wid[:8]}: state {w['state']}, "
                    f"hb {w['hb_age']:.1f}s ago")
            if "piece" in w:
                line += (f", piece '{w['piece']}' "
                         f"{w['piece_age']:.1f}s in flight"
                         + (f" [{w['hedge']}]" if "hedge" in w else ""))
            if "rate" in w:
                line += (f", rate {w['rate']:g} sim-s/s, last advance "
                         f"{w['stalled_for']:.1f}s ago")
            wm = w.get("mesh")
            if isinstance(wm, dict) and wm.get("mode", "off") != "off":
                line += (f", mesh e{wm.get('epoch', 0)} "
                         f"D{wm.get('devices', 0)} {wm.get('mode')}")
            ws = w.get("scan")
            if isinstance(ws, dict) and ws.get("steps"):
                line += (f", scan conf-peak {ws.get('conf_peak', 0)}")
            wf = w.get("fp")
            if isinstance(wf, dict) and wf.get("fp"):
                line += f", fp {wf['fp']}"
            if w.get("quarantined"):
                line += " [SDC-QUARANTINED]"
            lines.append(line)
        return "\n".join(lines)

    def _replay_journal(self):
        """--resume-batch: rebuild the sweep from the journal —
        completed pieces stay done (exactly-once), pieces in flight at
        crash time are requeued, quarantine decisions (and their
        client-visible reports) persist, crash counters carry over so
        a poison pill cannot reset its strikes by killing the server."""
        from .journal import BatchJournal
        try:
            state = BatchJournal.replay(self.resume_journal)
        except OSError as e:
            print(f"server: --resume-batch {self.resume_journal}: {e}")
            return
        for piece in state["quarantined"]:
            self.quarantined.append(piece)
            self.quarantine_reports.append(
                {"piece": self._piece_name(piece),
                 "crashes": state["quarantined_crashes"].get(
                     BatchJournal.piece_key(piece), 0),
                 "scencmd": list(piece[1]), "resumed": True})
        for piece in state["pending"]:
            jkey = BatchJournal.piece_key(piece)
            if jkey in state["crashes"]:
                self.piece_crashes[self._piece_key(piece)] = \
                    state["crashes"][jkey]
        self.scenarios.extend(state["pending"])
        if self.journal:
            self.journal.append("resumed",
                                pending=len(state["pending"]),
                                completed=len(state["completed"]),
                                quarantined=len(state["quarantined"]))
        print(f"server: resumed BATCH journal {self.resume_journal}: "
              f"{len(state['pending'])} piece(s) requeued, "
              f"{len(state['completed'])} already complete, "
              f"{len(state['quarantined'])} quarantined"
              + (f", {state['torn_lines']} torn line(s) skipped"
                 if state["torn_lines"] else ""))
        if self.scenarios and self.spawn_workers:
            self._spawn_for_backlog()

    # ------------------------------------------------- liveness / chaining
    def _reap_dead_workers(self):
        """PING registered workers and bury the dead: a spawned child
        whose process exited, or any worker silent past hb_timeout.
        The dead worker's in-flight piece is requeued and (for crashed
        children) a replacement is spawned."""
        now = time.monotonic()
        dead = []
        for wid in list(self.workers):
            proc = self.spawned.get(wid)
            # A worker mid-BATCH may be stuck in a long device chunk or
            # a first-step JIT compile (minutes at large N) without a
            # chance to pump events — give busy workers
            # hb_busy_multiplier x the silence budget before declaring
            # a pong-based death (process exit stays immediate for
            # spawned children).
            budget = self.hb_timeout * (
                self.hb_busy_multiplier if wid in self.inflight
                or self.workers.get(wid, 0) >= 2 else 1.0)
            if proc is not None and proc.poll() is not None:
                dead.append(wid)           # child exited without goodbye
            elif proc is None and now - self.last_seen.get(wid, now) \
                    > budget:
                dead.append(wid)           # external worker went silent
            else:
                self.be_event.send_multipart([wid, b"PING", packb(now)])
        # Spawned children that died BEFORE ever registering (startup
        # crash: import error, OOM) would otherwise leak their pending-
        # spawn slot and shrink the headroom forever.
        for wid, proc in list(self.spawned.items()):
            if wid not in self.workers and proc.poll() is not None:
                self.spawned.pop(wid, None)
                self._pending_spawns = max(0, self._pending_spawns - 1)
                print(f"server: spawned worker {wid.hex()} died before "
                      f"registering (exit {proc.returncode})")
                if self.restart_crashed and self.scenarios:
                    self._spawn_for_backlog(1)
        for wid in dead:
            print(f"server: worker {wid.hex()} died — "
                  f"{'requeueing piece, ' if wid in self.inflight else ''}"
                  f"removing from pool")
            self.workers.pop(wid, None)
            self.spawned.pop(wid, None)
            self.last_seen.pop(wid, None)
            if wid in self.avail_workers:
                self.avail_workers.remove(wid)
            self._requeue_lost_piece(wid)
            if self.restart_crashed and self.spawn_workers:
                self._spawn_for_backlog(1)
            while self.avail_workers and self.scenarios:
                self._send_pending_scenario()
        if dead:
            self._nodeschanged()

    def _handle_link(self, frames):
        """Events arriving over the upstream link (we are a client of
        the upstream server there)."""
        route, name, payload = split_envelope(frames)
        data = unpackb(payload) if payload else None
        if not route and name in (b"REGISTER", b"NODESCHANGED"):
            # upstream node table: mirror it to our clients with the
            # upstream as the routing hop (server.py:213-225)
            self.link_id = data["host_id"]
            self.remote_nodes = {bytes(nid): self.link_id
                                 for nid in data["nodes"]
                                 if bytes(nid) not in self.workers}
            self._nodeschanged()
        elif route:
            # reply/event for one of our endpoints: forward with the
            # upstream as the accumulated sender hop
            self._forward(self.link_id or b"", route, name, payload)

    # ------------------------------------------------------------ main loop
    def run(self):
        self.fe_event.bind(f"tcp://*:{self.ports['event']}")
        self.fe_stream.bind(f"tcp://*:{self.ports['stream']}")
        self.be_event.bind(f"tcp://*:{self.ports['wevent']}")
        self.be_stream.bind(f"tcp://*:{self.ports['wstream']}")
        poller = zmq.Poller()
        for sock in (self.fe_event, self.fe_stream, self.be_event,
                     self.be_stream):
            poller.register(sock, zmq.POLLIN)
        if self.discovery:
            poller.register(self.discovery.handle, zmq.POLLIN)
        if self.upstream:
            ctx = zmq.Context.instance()
            self.link = ctx.socket(zmq.DEALER)
            self.link.setsockopt(zmq.IDENTITY, self.server_id)
            self.link.setsockopt(zmq.LINGER, 0)
            self.link.connect(
                f"tcp://{self.upstream[0]}:{self.upstream[1]}")
            self.link.send_multipart([b"REGISTER", packb(None)])
            poller.register(self.link, zmq.POLLIN)
        self.running = not self._stop_requested
        if self.ha_role == "leader":
            # journal-fenced leadership: the lease record must precede
            # every sweep record this leader writes (resume included)
            self._ha_acquire()
        if self.resume_journal:
            self._replay_journal()
        if not self.headless:
            self.addnodes(1)
        while self.running:
            events = dict(poller.poll(100))
            now = time.monotonic()
            if self.ha_role:
                if self._ha_serving:
                    if now >= self._ha_next_renew:
                        self._ha_renew(now)
                    if self._ha_limbo and now >= self._ha_grace_until:
                        self._ha_release_limbo()
                elif now >= self._ha_next_poll:
                    self._ha_next_poll = now + self.ha_poll_dt
                    self._ha_standby_poll(now)
            if now >= self._next_hb:
                self._next_hb = now + self.hb_interval
                if self._ha_serving:
                    # a standby only WATCHES: reaping, hedging, SLO and
                    # mitigation resume on the new leader's first tick
                    self._reap_dead_workers()
                    self._check_stragglers(now)
                    self._check_perf_slo(now)
                    self.mitigator.tick(now)
                self.obs.gauge("server_queue_depth").set(
                    len(self.scenarios))
                if self.journal is not None:
                    self.obs.gauge("server_journal_bytes").set(
                        int(self.journal.size_bytes))
                self.obs.maybe_export()
            if self.link is not None and self.link in events:
                try:
                    self._handle_link(self.link.recv_multipart())
                except Exception as exc:
                    print(f"server: dropped malformed link message: "
                          f"{exc!r}")
            if self.be_stream in events:
                frames = self.be_stream.recv_multipart()
                try:
                    # NOBLOCK + XPUB_NODROP: a subscriber at its HWM
                    # (stalled GUI) surfaces as EAGAIN instead of a
                    # silent, uncountable per-peer drop
                    self.fe_stream.send_multipart(frames,
                                                  flags=zmq.NOBLOCK)
                except zmq.Again:
                    # count the drop, then re-send with the lossy flag
                    # temporarily restored: the saturated peer ALONE
                    # misses the frame — healthy subscribers must not
                    # go dark because one GUI stalled
                    self.stream_drops += 1
                    self.fe_stream.setsockopt(zmq.XPUB_NODROP, 0)
                    try:
                        self.fe_stream.send_multipart(
                            frames, flags=zmq.NOBLOCK)
                    except zmq.Again:
                        pass
                    finally:
                        self.fe_stream.setsockopt(zmq.XPUB_NODROP, 1)
            if self.fe_stream in events:    # subscription propagation
                self.be_stream.send_multipart(
                    self.fe_stream.recv_multipart())
            if self.discovery and (self.discovery.handle in events
                                   or self.discovery.handle.fileno()
                                   in events):
                kind, _ = self.discovery.recv_reqreply()
                if kind == "req":
                    if self.ha_role:
                        # HA arbitration: replies carry epoch + role so
                        # peers prefer the live leader over a deposed
                        # one (highest epoch) and skip warm standbys
                        self.discovery.send_reply(
                            self.ports["event"], self.ports["stream"],
                            epoch=self.ha_epoch,
                            role="leader" if self._ha_serving
                            else "standby",
                            # failed-over WORKERS must land on the
                            # worker-facing ROUTER, not the client one
                            wevent=self.ports["wevent"],
                            wstream=self.ports["wstream"])
                    else:
                        self.discovery.send_reply(self.ports["event"],
                                                  self.ports["stream"])
            for sock in (self.fe_event, self.be_event):
                if sock not in events:
                    continue
                frames = sock.recv_multipart()
                # a malformed message from one peer must not kill the broker
                try:
                    sender, rest = frames[0], frames[1:]
                    if sock is self.be_event:
                        self.last_seen[sender] = now   # any traffic counts
                    route, name, payload = split_envelope(rest)
                    if route:
                        self._forward(sender, route, name, payload)
                    else:
                        self._handle_server_event(sock, sender, name,
                                                  payload)
                except Exception as exc:
                    print(f"server: dropped malformed message: {exc!r}")
        # shutdown: tell workers to quit (covers stop() as well as the
        # client-QUIT path), then wait for them (server.py:311-317)
        for wid in self.workers:
            self.be_event.send_multipart([wid, b"QUIT", packb(None)])
        for proc in self.processes:
            try:
                proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                proc.kill()
        if self.journal:
            # clean-exit marker; queued-but-unfinished pieces stay
            # pending in the journal, so --resume-batch still works
            # after an orderly preemption shutdown
            self.journal.shutdown()
            self.journal.close()
        for sock in (self.fe_event, self.fe_stream, self.be_event,
                     self.be_stream):
            sock.close()
        if self.link is not None:
            self.link.close()
        if self.discovery:
            self.discovery.close()
