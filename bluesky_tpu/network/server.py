"""Broker + worker manager (parity: bluesky/network/server.py:26-317).

Four sockets: client-facing ROUTER (events) + XPUB (streams), worker-facing
ROUTER (events) + XSUB (streams).  Streams pass through XSUB->XPUB;
subscription messages flow back XPUB->XSUB.  Events are source-routed
multipart ``[*route, name, payload]`` (see node.split_envelope): on each
forward the server pops the first route frame as the next-hop destination
and appends the arrival sender id to the tail, so the frames a receiver
sees are exactly the return route for its reply.  ``b'*'`` fans out to all
workers.

Server-directed events (empty route): REGISTER, ADDNODES, BATCH, QUIT,
STATECHANGE, PONG.  BATCH splits a multi-SCEN scenario and farms the
pieces out to idle workers, spawning more (up to max_nnodes) as needed —
the reference's scenario-ensemble parallelism (§2.10), which on TPU pairs
with the device-side ensemble axis in parallel/sharding.py.

Hardening beyond the reference:
* **Worker liveness**: spawned workers get their id assigned
  (``--node-id``) so a dead child process maps straight back to its
  registration; external workers are probed with PING/PONG.  A dead
  worker's in-flight BATCH piece is requeued and a replacement is
  spawned — kill -9 a worker mid-batch and the batch still completes.
* **Durable BATCH sweeps** (docs/FAULT_TOLERANCE.md): every piece
  transition (queued/dispatched/completed/crashed/quarantined/
  preempted) is appended to a JSONL write-ahead journal
  (network/journal.py); ``--resume-batch <journal>`` replays it after a
  server crash or preemption to rebuild the queue with exactly-once
  completion semantics.  A ``PREEMPTED`` notice from a draining worker
  requeues its piece without a circuit-breaker strike, and
  ``BATCHQUARANTINE`` reports are replayed to late-joining clients.
* **Server-to-server chaining** (reference server.py:213-225): a server
  started with ``upstream=(host, port)`` registers at another server's
  client port, mirrors that server's node table to its own clients
  (NODESCHANGED merge), and routes events for remote nodes over the
  link.  Multi-hop replies work because reply routes are the REVERSED
  accumulated sender tail (single-hop routes are palindromes, so the
  flat fabric is unaffected).
"""
import os
import subprocess
import sys
import threading
import time

import zmq

from .common import DEFAULT_PORTS, make_id
from .discovery import Discovery
from .node import split_envelope
from .npcodec import packb, unpackb


def split_scenarios(scentime, scencmd):
    """Split a scenario command list into per-SCEN chunks
    (parity: server.py:26-32)."""
    starts = [i for i, cmd in enumerate(scencmd)
              if cmd.strip().upper().startswith("SCEN")]
    if not starts:
        return [(list(scentime), list(scencmd))] if scencmd else []
    # commands before the first SCEN are global setup: prepend to each piece
    pre_t, pre_c = scentime[:starts[0]], scencmd[:starts[0]]
    bounds = starts + [len(scencmd)]
    return [(pre_t + scentime[a:b], pre_c + scencmd[a:b])
            for a, b in zip(bounds[:-1], bounds[1:])]


class Server(threading.Thread):
    """Runs the broker loop in a thread (reference: Server(Thread))."""

    def __init__(self, headless=False, discoverable=False,
                 ports=None, max_nnodes=None, spawn_workers=True,
                 upstream=None, hb_interval=2.0, hb_timeout=30.0,
                 restart_crashed=True, max_piece_crashes=None,
                 journal_path=None, resume_journal=None):
        super().__init__(daemon=True)
        self.server_id = make_id()
        self.headless = headless
        self.ports = dict(DEFAULT_PORTS, **(ports or {}))
        self.max_nnodes = max_nnodes or min(os.cpu_count() or 1, 8)
        self.spawn_workers = spawn_workers
        self.running = False
        self._stop_requested = False
        self.clients = []                  # connected client ids
        self.workers = {}                  # worker_id -> state int
        self.avail_workers = []            # idle worker ids (for BATCH)
        self.scenarios = []                # pending BATCH pieces
        self.processes = []                # spawned worker Popen handles
        self._pending_spawns = 0           # spawned but not yet REGISTERed
        # ----- liveness / restart
        self.hb_interval = hb_interval
        self.hb_timeout = hb_timeout
        self.restart_crashed = restart_crashed
        self.spawned = {}                  # worker_id -> Popen
        self.inflight = {}                 # worker_id -> BATCH piece
        self.last_seen = {}                # worker_id -> monotonic stamp
        self._next_hb = 0.0
        # ----- per-scenario circuit breaker: a piece that loses its
        # worker K consecutive times is poison (NaN bomb, OOM bait,
        # FAULT KILL) — quarantine + report it instead of requeueing it
        # into a crash loop that eats the whole worker pool forever.
        from .. import settings as _settings
        self.max_piece_crashes = max_piece_crashes \
            if max_piece_crashes is not None \
            else getattr(_settings, "batch_max_crashes", 3)
        self.piece_crashes = {}            # piece key -> consecutive losses
        self.quarantined = []              # circuit-broken pieces
        self.quarantine_reports = []       # BATCHQUARANTINE payloads —
        #                                    replayed to late-joining
        #                                    clients on REGISTER
        # ----- durable BATCH state: append-only JSONL journal (WAL)
        # replayed on restart (--resume-batch).  journal_path=None ->
        # settings-derived default (<log_path>/batch-<serverid>.jsonl,
        # or the resume journal itself so chained resumes keep one
        # file); journal_path="" disables journaling.  The file is only
        # created when the first BATCH record is appended.
        from .journal import BatchJournal
        self.resume_journal = resume_journal or None
        if journal_path is None:
            journal_path = self.resume_journal or os.path.join(
                getattr(_settings, "log_path", "output"),
                f"batch-{self.server_id.hex()}.jsonl")
        self.journal = BatchJournal(
            journal_path,
            fsync=getattr(_settings, "batch_journal_fsync", True)) \
            if journal_path else None
        # ----- server-to-server chaining
        self.upstream = upstream           # (host, event_port) or None
        self.link = None                   # DEALER to the upstream server
        self.link_id = b""                 # upstream host id (after ack)
        self.remote_nodes = {}             # node_id -> upstream host id
        self.discovery = Discovery(self.server_id, is_client=False,
                                   port=self.ports["discovery"]) \
            if discoverable else None
        ctx = zmq.Context.instance()
        self.fe_event = ctx.socket(zmq.ROUTER)
        self.fe_stream = ctx.socket(zmq.XPUB)
        self.be_event = ctx.socket(zmq.ROUTER)
        self.be_stream = ctx.socket(zmq.XSUB)
        # event sockets get a short linger so final QUIT/NODESCHANGED sends
        # flush before close; stream sockets can drop in-flight data
        self.fe_event.setsockopt(zmq.LINGER, 500)
        self.be_event.setsockopt(zmq.LINGER, 500)
        self.fe_stream.setsockopt(zmq.LINGER, 0)
        self.be_stream.setsockopt(zmq.LINGER, 0)

    # ----------------------------------------------------------- lifecycle
    def addnodes(self, count=1):
        """Spawn sim worker processes (parity: server.py:62-67).

        The worker id is assigned HERE and passed down (--node-id) so a
        child that dies without a goodbye (kill -9, OOM) maps straight
        back to its registration for requeue + restart."""
        if not self.spawn_workers:
            return
        for _ in range(count):
            self._pending_spawns += 1
            wid = make_id()
            proc = subprocess.Popen(
                [sys.executable, "-m", "bluesky_tpu", "--sim",
                 "--event-port", str(self.ports["wevent"]),
                 "--stream-port", str(self.ports["wstream"]),
                 "--node-id", wid.hex()])
            self.processes.append(proc)
            self.spawned[wid] = proc

    def _spawn_for_backlog(self, count=None):
        """Spawn up to ``count`` workers (default: one per queued BATCH
        piece), capped by the max_nnodes headroom — the ONE place the
        headroom formula lives, so every requeue/replay/reap path
        spawns consistently."""
        headroom = self.max_nnodes - len(self.workers) \
            - self._pending_spawns
        n = max(0, min(len(self.scenarios) if count is None else count,
                       headroom))
        if n > 0:
            self.addnodes(n)

    def stop(self):
        self._stop_requested = True
        self.running = False

    # ------------------------------------------------------------- routing
    def _forward(self, sender, route, name, payload):
        """Pop next hop, append sender to the return tail, send."""
        if route and route[0] == b"*":
            # Fan out to every endpoint except the sender (stack.py's
            # b'*' semantics, server.py:302-307): workers AND clients.
            for wid in self.workers:
                if wid != sender:
                    self.be_event.send_multipart(
                        [wid, sender, name, payload])
            for cid in self.clients:
                if cid != sender:
                    self.fe_event.send_multipart(
                        [cid, sender, name, payload])
            return
        dest = route[0]
        tail = list(route[1:]) + [sender]
        if dest in self.workers:
            sock = self.be_event
        elif self.link is not None and (dest in self.remote_nodes
                                        or dest == self.link_id):
            # chained node: hop over the upstream link (the DEALER's own
            # identity is the implicit sender frame on the other side)
            self.link.send_multipart([dest] + tail + [name, payload])
            return
        else:
            sock = self.fe_event
        sock.send_multipart([dest] + tail + [name, payload])

    # --------------------------------------------------- circuit breaker
    @staticmethod
    def _piece_key(piece):
        scentime, scencmd = piece
        return (tuple(scentime), tuple(scencmd))

    @staticmethod
    def _piece_name(piece):
        for cmd in piece[1]:
            c = cmd.strip()
            if c.upper().startswith("SCEN"):
                parts = c.split(None, 1)
                return parts[1] if len(parts) > 1 else c
        return f"<{len(piece[1])}-command piece>"

    def _report_clients(self, text, name=b"ECHO", data=None):
        """Fan a server-originated event out to every connected client
        (ECHO payload format matches ScreenIO's)."""
        payload = packb(data if data is not None
                        else {"text": text, "flags": 0})
        for cid in self.clients:
            self.fe_event.send_multipart([cid, name, payload])

    def _requeue_lost_piece(self, wid):
        """A worker was lost with a BATCH piece in flight: requeue the
        piece — unless it has now taken down a worker
        ``max_piece_crashes`` consecutive times, in which case it is
        circuit-broken: quarantined server-side and reported to every
        client (ECHO + a machine-readable BATCHQUARANTINE event)
        instead of being requeued into an infinite crash loop."""
        piece = self.inflight.pop(wid, None)
        if piece is None:
            return
        key = self._piece_key(piece)
        count = self.piece_crashes.get(key, 0) + 1
        self.piece_crashes[key] = count
        if count >= self.max_piece_crashes:
            self.piece_crashes.pop(key, None)
            self.quarantined.append(piece)
            pname = self._piece_name(piece)
            if self.journal:
                self.journal.quarantined(piece, count)
            msg = (f"BATCH piece '{pname}' quarantined: lost its worker "
                   f"{count} consecutive times (circuit breaker)")
            print(f"server: {msg}")
            data = {"piece": pname, "crashes": count,
                    "scencmd": list(piece[1])}
            self.quarantine_reports.append(data)
            self._report_clients(msg)
            self._report_clients(msg, name=b"BATCHQUARANTINE", data=data)
        else:
            # requeue BEFORE the journal append: the fsync is a real
            # disk wait, and observers polling inflight/scenarios must
            # never see the piece in neither
            self.scenarios.insert(0, piece)
            if self.journal:
                self.journal.crashed(piece, count)

    def _nodeschanged(self):
        """Notify clients; chained remote nodes are merged in (reference
        server.py:213-225 route-prefixed server table)."""
        data = packb({"host_id": self.server_id,
                      "nodes": list(self.workers)
                      + list(self.remote_nodes)})
        for cid in self.clients:
            self.fe_event.send_multipart([cid, b"NODESCHANGED", data])

    def _handle_server_event(self, sock, sender, name, payload):
        from_worker = sock is self.be_event
        if name == b"REGISTER":
            if from_worker:
                if sender not in self.workers:
                    self.workers[sender] = 0
                    self._pending_spawns = max(0, self._pending_spawns - 1)
                # duplicated/late REGISTER frames (flaky transport) must
                # not double-book the worker: one mid-BATCH (in inflight
                # or state OP) stays unavailable, or piece B would
                # overwrite its in-flight piece A and silently drop A
                if sender not in self.avail_workers \
                        and sender not in self.inflight \
                        and self.workers[sender] < 2:
                    self.avail_workers.append(sender)
                self._send_pending_scenario()
                self._nodeschanged()
            new_client = False
            if not from_worker and sender not in self.clients:
                # backoff clients re-send REGISTER until acked — every
                # resend must ack, but only the first may register
                self.clients.append(sender)
                new_client = True
            sock.send_multipart(
                [sender, b"REGISTER",
                 packb({"host_id": self.server_id,
                        "nodes": list(self.workers)
                        + list(self.remote_nodes)})])
            if new_client:
                # replay circuit-breaker verdicts so a late-joining /
                # reattaching operator still sees what the sweep dropped
                for data in self.quarantine_reports:
                    sock.send_multipart(
                        [sender, b"BATCHQUARANTINE", packb(data)])
        elif name == b"ADDNODES":
            count = unpackb(payload) if payload else 1
            self.addnodes(int(count or 1))
        elif name == b"STATECHANGE":
            state = unpackb(payload)
            if state == -1:
                self.workers.pop(sender, None)
                self.spawned.pop(sender, None)
                self.last_seen.pop(sender, None)
                if sender in self.avail_workers:
                    self.avail_workers.remove(sender)
                # a worker that quit with a piece still running gives it
                # back to the queue — through the circuit breaker: a
                # poison pill that makes its worker abort cleanly loops
                # exactly like one that SIGKILLs it
                self._requeue_lost_piece(sender)
                self._nodeschanged()
                # keep the batch draining if pieces are still queued
                if self.scenarios:
                    self._spawn_for_backlog()
            else:
                self.workers[sender] = state
                # worker dropped out of OP -> available for the next piece;
                # busy workers must not receive BATCH pieces
                # (parity: server.py:234-247)
                if state < 2:
                    piece = self.inflight.pop(sender, None)
                    if piece is not None:   # piece completed cleanly:
                        # reset its consecutive-crash count
                        self.piece_crashes.pop(self._piece_key(piece),
                                               None)
                        if self.journal:    # exactly-once: a resumed
                            # server will never requeue this piece
                            self.journal.completed(piece, sender)
                    if sender not in self.avail_workers:
                        self.avail_workers.append(sender)
                        self._send_pending_scenario()
                elif sender in self.avail_workers:
                    self.avail_workers.remove(sender)
        elif name == b"PONG":
            pass                           # last_seen already stamped
        elif name == b"PREEMPTED" and from_worker:
            # a preempted worker drained its chunk, wrote a checkpoint
            # and is exiting: requeue its piece WITHOUT a circuit-
            # breaker strike (preemption is capacity churn, not a piece
            # fault) — the follow-up STATECHANGE(-1) then finds nothing
            # in flight, so no crash is counted either
            data = unpackb(payload) if payload else None
            piece = self.inflight.pop(sender, None)
            if piece is not None:
                self.scenarios.insert(0, piece)
                if self.journal:
                    self.journal.preempted(piece, sender)
                # hand the piece straight to an idle worker if one is
                # available — the preempted worker's own STATECHANGE(-1)
                # only spawns replacements, it does not dispatch
                while self.avail_workers and self.scenarios:
                    self._send_pending_scenario()
            ck = (data or {}).get("checkpoint", "")
            msg = (f"worker {sender.hex()} preempted"
                   + (f" (checkpoint: {ck})" if ck else "")
                   + (" — piece requeued" if piece is not None else ""))
            print(f"server: {msg}")
            self._report_clients(msg)
        elif name == b"BATCH":
            data = unpackb(payload)
            pieces = split_scenarios(data["scentime"], data["scencmd"])
            if self.journal:
                # one flush+fsync for the whole submission — per-piece
                # syncs would stall the poll loop on large sweeps
                self.journal.queued_many(pieces)
            self.scenarios.extend(pieces)
            while self.avail_workers and self.scenarios:
                self._send_pending_scenario()
            if self.scenarios:
                self._spawn_for_backlog()
        elif name == b"QUIT":
            for wid in self.workers:
                self.be_event.send_multipart([wid, b"QUIT", packb(None)])
            self.running = False
        elif from_worker:
            # unaddressed worker output (e.g. scenario-triggered ECHO with
            # no issuing client): fan out to every connected client
            for cid in self.clients:
                self.fe_event.send_multipart([cid, sender, name, payload])

    def _send_pending_scenario(self):
        if self.avail_workers and self.scenarios:
            wid = self.avail_workers.pop(0)
            piece = self.scenarios.pop(0)
            self.inflight[wid] = piece     # held until the worker leaves OP
            if self.journal:
                self.journal.dispatched(piece, wid)
            scentime, scencmd = piece
            self.be_event.send_multipart(
                [wid, b"BATCH", packb({"scentime": scentime,
                                       "scencmd": scencmd})])

    def _replay_journal(self):
        """--resume-batch: rebuild the sweep from the journal —
        completed pieces stay done (exactly-once), pieces in flight at
        crash time are requeued, quarantine decisions (and their
        client-visible reports) persist, crash counters carry over so
        a poison pill cannot reset its strikes by killing the server."""
        from .journal import BatchJournal
        try:
            state = BatchJournal.replay(self.resume_journal)
        except OSError as e:
            print(f"server: --resume-batch {self.resume_journal}: {e}")
            return
        for piece in state["quarantined"]:
            self.quarantined.append(piece)
            self.quarantine_reports.append(
                {"piece": self._piece_name(piece),
                 "crashes": state["quarantined_crashes"].get(
                     BatchJournal.piece_key(piece), 0),
                 "scencmd": list(piece[1]), "resumed": True})
        for piece in state["pending"]:
            jkey = BatchJournal.piece_key(piece)
            if jkey in state["crashes"]:
                self.piece_crashes[self._piece_key(piece)] = \
                    state["crashes"][jkey]
        self.scenarios.extend(state["pending"])
        if self.journal:
            self.journal.append("resumed",
                                pending=len(state["pending"]),
                                completed=len(state["completed"]),
                                quarantined=len(state["quarantined"]))
        print(f"server: resumed BATCH journal {self.resume_journal}: "
              f"{len(state['pending'])} piece(s) requeued, "
              f"{len(state['completed'])} already complete, "
              f"{len(state['quarantined'])} quarantined"
              + (f", {state['torn_lines']} torn line(s) skipped"
                 if state["torn_lines"] else ""))
        if self.scenarios and self.spawn_workers:
            self._spawn_for_backlog()

    # ------------------------------------------------- liveness / chaining
    def _reap_dead_workers(self):
        """PING registered workers and bury the dead: a spawned child
        whose process exited, or any worker silent past hb_timeout.
        The dead worker's in-flight piece is requeued and (for crashed
        children) a replacement is spawned."""
        now = time.monotonic()
        dead = []
        for wid in list(self.workers):
            proc = self.spawned.get(wid)
            # A worker mid-BATCH may be stuck in a long device chunk or
            # a first-step JIT compile (minutes at large N) without a
            # chance to pump events — give busy workers 10x the silence
            # budget before declaring a pong-based death (process exit
            # stays immediate for spawned children).
            budget = self.hb_timeout * (10.0 if wid in self.inflight
                                        or self.workers.get(wid, 0) >= 2
                                        else 1.0)
            if proc is not None and proc.poll() is not None:
                dead.append(wid)           # child exited without goodbye
            elif proc is None and now - self.last_seen.get(wid, now) \
                    > budget:
                dead.append(wid)           # external worker went silent
            else:
                self.be_event.send_multipart([wid, b"PING", packb(now)])
        # Spawned children that died BEFORE ever registering (startup
        # crash: import error, OOM) would otherwise leak their pending-
        # spawn slot and shrink the headroom forever.
        for wid, proc in list(self.spawned.items()):
            if wid not in self.workers and proc.poll() is not None:
                self.spawned.pop(wid, None)
                self._pending_spawns = max(0, self._pending_spawns - 1)
                print(f"server: spawned worker {wid.hex()} died before "
                      f"registering (exit {proc.returncode})")
                if self.restart_crashed and self.scenarios:
                    self._spawn_for_backlog(1)
        for wid in dead:
            print(f"server: worker {wid.hex()} died — "
                  f"{'requeueing piece, ' if wid in self.inflight else ''}"
                  f"removing from pool")
            self.workers.pop(wid, None)
            self.spawned.pop(wid, None)
            self.last_seen.pop(wid, None)
            if wid in self.avail_workers:
                self.avail_workers.remove(wid)
            self._requeue_lost_piece(wid)
            if self.restart_crashed and self.spawn_workers:
                self._spawn_for_backlog(1)
            while self.avail_workers and self.scenarios:
                self._send_pending_scenario()
        if dead:
            self._nodeschanged()

    def _handle_link(self, frames):
        """Events arriving over the upstream link (we are a client of
        the upstream server there)."""
        route, name, payload = split_envelope(frames)
        data = unpackb(payload) if payload else None
        if not route and name in (b"REGISTER", b"NODESCHANGED"):
            # upstream node table: mirror it to our clients with the
            # upstream as the routing hop (server.py:213-225)
            self.link_id = data["host_id"]
            self.remote_nodes = {bytes(nid): self.link_id
                                 for nid in data["nodes"]
                                 if bytes(nid) not in self.workers}
            self._nodeschanged()
        elif route:
            # reply/event for one of our endpoints: forward with the
            # upstream as the accumulated sender hop
            self._forward(self.link_id or b"", route, name, payload)

    # ------------------------------------------------------------ main loop
    def run(self):
        self.fe_event.bind(f"tcp://*:{self.ports['event']}")
        self.fe_stream.bind(f"tcp://*:{self.ports['stream']}")
        self.be_event.bind(f"tcp://*:{self.ports['wevent']}")
        self.be_stream.bind(f"tcp://*:{self.ports['wstream']}")
        poller = zmq.Poller()
        for sock in (self.fe_event, self.fe_stream, self.be_event,
                     self.be_stream):
            poller.register(sock, zmq.POLLIN)
        if self.discovery:
            poller.register(self.discovery.handle, zmq.POLLIN)
        if self.upstream:
            ctx = zmq.Context.instance()
            self.link = ctx.socket(zmq.DEALER)
            self.link.setsockopt(zmq.IDENTITY, self.server_id)
            self.link.setsockopt(zmq.LINGER, 0)
            self.link.connect(
                f"tcp://{self.upstream[0]}:{self.upstream[1]}")
            self.link.send_multipart([b"REGISTER", packb(None)])
            poller.register(self.link, zmq.POLLIN)
        self.running = not self._stop_requested
        if self.resume_journal:
            self._replay_journal()
        if not self.headless:
            self.addnodes(1)
        while self.running:
            events = dict(poller.poll(100))
            now = time.monotonic()
            if now >= self._next_hb:
                self._next_hb = now + self.hb_interval
                self._reap_dead_workers()
            if self.link is not None and self.link in events:
                try:
                    self._handle_link(self.link.recv_multipart())
                except Exception as exc:
                    print(f"server: dropped malformed link message: "
                          f"{exc!r}")
            if self.be_stream in events:
                self.fe_stream.send_multipart(
                    self.be_stream.recv_multipart())
            if self.fe_stream in events:    # subscription propagation
                self.be_stream.send_multipart(
                    self.fe_stream.recv_multipart())
            if self.discovery and (self.discovery.handle in events
                                   or self.discovery.handle.fileno()
                                   in events):
                kind, _ = self.discovery.recv_reqreply()
                if kind == "req":
                    self.discovery.send_reply(self.ports["event"],
                                              self.ports["stream"])
            for sock in (self.fe_event, self.be_event):
                if sock not in events:
                    continue
                frames = sock.recv_multipart()
                # a malformed message from one peer must not kill the broker
                try:
                    sender, rest = frames[0], frames[1:]
                    if sock is self.be_event:
                        self.last_seen[sender] = now   # any traffic counts
                    route, name, payload = split_envelope(rest)
                    if route:
                        self._forward(sender, route, name, payload)
                    else:
                        self._handle_server_event(sock, sender, name,
                                                  payload)
                except Exception as exc:
                    print(f"server: dropped malformed message: {exc!r}")
        # shutdown: tell workers to quit (covers stop() as well as the
        # client-QUIT path), then wait for them (server.py:311-317)
        for wid in self.workers:
            self.be_event.send_multipart([wid, b"QUIT", packb(None)])
        for proc in self.processes:
            try:
                proc.wait(timeout=5)
            except subprocess.TimeoutExpired:
                proc.kill()
        if self.journal:
            # clean-exit marker; queued-but-unfinished pieces stay
            # pending in the journal, so --resume-batch still works
            # after an orderly preemption shutdown
            self.journal.shutdown()
            self.journal.close()
        for sock in (self.fe_event, self.fe_stream, self.be_event,
                     self.be_stream):
            sock.close()
        if self.link is not None:
            self.link.close()
        if self.discovery:
            self.discovery.close()
