"""GUI-side client: the per-node data mirror the radar draws from.

Parity with the reference ``ui/qtgl/guiclient.py:19-296``: a ``Client``
subclass that subscribes to the ACDATA/ROUTEDATA/SIMINFO streams and
maintains a ``nodeData`` mirror per connected sim node — last aircraft
frame, accumulated trail segments, shape registry (SHAPE events), the
selected route, echo history, and sim info.  The reference's
RadarWidget consumes exactly this mirror; here ``render_svg`` draws it
through ``ui/radar.py`` so a connected client can save radar frames
without Qt.
"""
from collections import defaultdict

import numpy as np

from ..ui import radar
from .client import Client

STREAM_TOPICS = [b"ACDATA", b"ROUTEDATA", b"SIMINFO"]


class nodeData:
    """Mirror of one sim node's display state (guiclient.py:93-296)."""

    def __init__(self):
        self.acdata = {}
        self.routedata = {}
        self.siminfo = {}
        self.shapes = {}          # name -> (kind, coords)
        self.echo_text = []
        self.custwpts = {}        # DEFWPT mirror: name -> (lat, lon)
        self.flags = {}           # DISPLAYFLAG mirror: flag -> last args
        self.ssd_all = False      # SSD disc selection mirror
        self.ssd_conflicts = False   # (reference guiclient.py:138-140)
        self.ssd_ownship = set()
        self.nd_acid = None       # SHOWND selection mirror
        # Accumulated trail picture (ACDATA carries deltas)
        self.traillat0 = np.array([])
        self.traillon0 = np.array([])
        self.traillat1 = np.array([])
        self.traillon1 = np.array([])

    MAX_TRAIL_SEGMENTS = 20000

    def show_ssd(self, arg):
        """SSD selection update (reference guiclient.py:283-296)."""
        arg = {str(a).upper() for a in (arg or [])}
        if "ALL" in arg:
            self.ssd_all, self.ssd_conflicts = True, False
        elif "CONFLICTS" in arg:
            self.ssd_all, self.ssd_conflicts = False, True
        elif "OFF" in arg:
            self.ssd_all, self.ssd_conflicts = False, False
            self.ssd_ownship = set()
        else:
            remove = self.ssd_ownship.intersection(arg)
            self.ssd_ownship = self.ssd_ownship.union(arg) - remove

    def setacdata(self, data):
        self.acdata = data
        if len(np.atleast_1d(data.get("traillat0", []))):
            self.traillat0 = np.append(self.traillat0,
                                       data["traillat0"])
            self.traillon0 = np.append(self.traillon0,
                                       data["traillon0"])
            self.traillat1 = np.append(self.traillat1,
                                       data["traillat1"])
            self.traillon1 = np.append(self.traillon1,
                                       data["traillon1"])
            if len(self.traillat0) > self.MAX_TRAIL_SEGMENTS:
                keep = self.MAX_TRAIL_SEGMENTS
                self.traillat0 = self.traillat0[-keep:]
                self.traillon0 = self.traillon0[-keep:]
                self.traillat1 = self.traillat1[-keep:]
                self.traillon1 = self.traillon1[-keep:]
        if not data.get("swtrails", False):
            self.traillat0 = np.array([])
            self.traillon0 = np.array([])
            self.traillat1 = np.array([])
            self.traillon1 = np.array([])


class GuiClient(Client):
    """Client + nodeData bookkeeping (guiclient.py:19-92)."""

    def __init__(self):
        super().__init__()
        self.nodedata = defaultdict(nodeData)
        self.event_received.connect(self._on_event)
        self.stream_received.connect(self._on_stream)

    def connect(self, **kw):
        super().connect(**kw)
        for topic in STREAM_TOPICS:
            self.subscribe(topic)

    def get_nodedata(self, nodeid=None):
        nodeid = nodeid or self.actnode()
        return self.nodedata[nodeid]

    # ------------------------------------------------------------ intake
    def _on_event(self, name, data, sender):
        nd = self.nodedata[sender]
        if name == b"ECHO":
            nd.echo_text.append(data.get("text", ""))
        elif name == b"SHAPE":
            # Reference wire format (screenio.py:171 / guiclient.py:158):
            # coordinates=None deletes the named shape.
            if data.get("coordinates") is not None:
                nd.shapes[data["name"]] = (data.get("shape"),
                                           data.get("coordinates"))
            else:
                nd.shapes.pop(data.get("name"), None)
        elif name == b"DEFWPT":
            nd.custwpts[data["name"]] = (data.get("lat"), data.get("lon"))
        elif name == b"DISPLAYFLAG":
            nd.flags[data.get("flag")] = data.get("args")
            if data.get("flag") == "SSD":
                nd.show_ssd(data.get("args"))
            elif data.get("flag") == "SHOWND":
                nd.nd_acid = data.get("args")

    def _on_stream(self, name, data, sender):
        nd = self.nodedata[sender]
        if name == b"ACDATA":
            nd.setacdata(data)
        elif name == b"ROUTEDATA":
            nd.routedata = data if data.get("wplat") else {}
        elif name == b"SIMINFO":
            nd.siminfo = data

    # ------------------------------------------------------------ output
    def render_svg(self, fname=None, nodeid=None):
        """Draw the mirrored radar picture (RadarWidget stand-in)."""
        nd = self.get_nodedata(nodeid)
        acdata = dict(nd.acdata)
        acdata["traillat0"] = nd.traillat0
        acdata["traillon0"] = nd.traillon0
        acdata["traillat1"] = nd.traillat1
        acdata["traillon1"] = nd.traillon1
        info = nd.siminfo
        title = (f"simt {info.get('simt', 0):.1f} s — "
                 f"{info.get('ntraf', 0)} aircraft — "
                 f"{info.get('speed', 0):.1f}x") if info else ""
        svg = radar.render_svg(acdata, nd.shapes, nd.routedata, title,
                               ssd=radar.compute_ssd_discs_acdata(
                                   nd.acdata, nd.ssd_all,
                                   nd.ssd_conflicts, nd.ssd_ownship))
        if fname:
            with open(fname, "w") as f:
                f.write(svg)
        return svg
