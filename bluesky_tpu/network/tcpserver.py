"""Raw-TCP line bridge into the command stack (telnet-style).

Parity with the reference ``tools/network.py:151-184``
(TcpServer/StackTelnetServer): external programs (the reference's TCP
end-to-end tests, BlueBird-style REST adapters) connect a plain socket,
send stack command lines, and receive the echo output back on the same
connection.

Threading model: socket accept/read happens on daemon threads that only
ENQUEUE (line, connection) pairs; the simulation loop drains the queue at
its own cadence via ``pump()`` (wired into ``Simulation.step``), so all
stack/state access stays on the sim thread — the same discipline the
reference gets from its Qt event loop.
"""
import queue
import socket
import threading


class StackTelnetServer:
    def __init__(self, sim, host="127.0.0.1", port=8888):
        self.sim = sim
        self.host = host
        self.port = port
        self._sock = None
        self._conns = {}
        self._nextid = 0
        self._queue = queue.Queue()
        self._accept_thread = None
        self.running = False

    # ------------------------------------------------------------ control
    def start(self):
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((self.host, self.port))
        self.port = self._sock.getsockname()[1]   # resolve port 0
        self._sock.listen(5)
        self.running = True
        self._accept_thread = threading.Thread(target=self._accept_loop,
                                               daemon=True)
        self._accept_thread.start()
        return self.port

    def stop(self):
        self.running = False
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
        for conn in list(self._conns.values()):
            try:
                conn.close()
            except OSError:
                pass
        self._conns.clear()

    def numConnections(self):
        return len(self._conns)

    # ------------------------------------------------------- socket side
    def _accept_loop(self):
        while self.running:
            try:
                conn, _addr = self._sock.accept()
            except OSError:
                break
            cid = self._nextid
            self._nextid += 1
            # Bounded sends: a stalled client must not block the sim
            # thread in pump() (socket.timeout is an OSError there)
            conn.settimeout(2.0)
            self._conns[cid] = conn
            threading.Thread(target=self._read_loop, args=(cid, conn),
                             daemon=True).start()

    def _read_loop(self, cid, conn):
        buf = b""
        while self.running:
            try:
                data = conn.recv(4096)
            except socket.timeout:
                continue           # idle connection; keep listening
            except OSError:
                break
            if not data:
                break
            buf += data
            while b"\n" in buf:
                line, buf = buf.split(b"\n", 1)
                msg = line.decode("ascii", errors="ignore").strip()
                if msg:
                    self._queue.put((cid, msg))
        self._conns.pop(cid, None)
        try:
            conn.close()
        except OSError:
            pass

    # ---------------------------------------------------------- sim side
    def pump(self):
        """Drain pending lines on the SIM thread: stack, process, and
        send the echo output back to the issuing connection."""
        if self._queue.empty():
            return
        scr = self.sim.scr
        # Drain commands other clients queued first so their echoes
        # don't leak into a TCP reply.
        self.sim.stack.process()
        while True:
            try:
                cid, msg = self._queue.get_nowait()
            except queue.Empty:
                break
            # Capture echoes via a temporary tee (no echobuf indexing,
            # so the buffer stays boundable)
            collected = []
            orig_echo = scr.echo

            def tee(text="", flags=0, _c=collected, _o=orig_echo):
                _c.append(text)
                return _o(text, flags)

            scr.echo = tee
            try:
                self.sim.stack.stack(msg, sender=f"tcp{cid}")
                self.sim.stack.process()
            finally:
                scr.echo = orig_echo
            reply = "\n".join(collected)
            conn = self._conns.get(cid)
            if conn is not None and reply:
                try:
                    conn.sendall(reply.encode("ascii", errors="ignore")
                                 + b"\n")
                except OSError:
                    pass
