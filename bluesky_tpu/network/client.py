"""GUI/script-side network endpoint (parity: bluesky/network/client.py:16-196).

DEALER event socket + SUB stream socket.  ``connect()`` performs the
REGISTER handshake with a timeout; ``receive()`` pumps both sockets and
emits ``event_received(name, data, sender_id)`` /
``stream_received(name, data, sender_id)`` signals.  Tracks the set of sim
nodes (from NODESCHANGED) and an *active node* that untargeted events
(stack commands) are routed to.
"""
import time

import zmq

from ..utils.signalslot import Signal
from .common import DEFAULT_PORTS, make_id
from .discovery import Discovery
from .node import split_envelope
from .npcodec import packb, unpackb


class Client:
    def __init__(self):
        self.client_id = make_id()
        self.host_id = b""
        self.nodes = []            # known sim node ids
        self.act = b""             # active node id
        self.event_received = Signal("event")
        self.stream_received = Signal("stream")
        self.nodes_changed = Signal("nodes")
        self._pending = []         # node-bound events queued until a node registers
        self.last_rejection = None  # latest BATCHREJECTED payload (the
        #                             admission-control refusal carries
        #                             queue depth + a retry-after hint)
        self.last_health = None     # latest HEALTH reply payload
        self.last_metrics = None    # latest METRICS (telemetry) reply
        self.last_trace = None      # latest TRACE reply (dump path)
        self.last_ha = None         # latest HA (broker-HA) reply
        # broker HA (network/ha.py): lease terms learned from an HA
        # server's REGISTER ack — None epoch means a non-HA server and
        # failover() has nothing to arbitrate with
        self.host_pid = None
        self.host_epoch = None
        self.host_lease_ttl = 0.0
        self.host_disc_port = None
        self._endpoints = None      # (event, stream) currently connected
        self.opt_results = []       # BATCHOPT reports (OPT-piece
        #                             trajectory-optimization results:
        #                             offsets + objective trace)
        ctx = zmq.Context.instance()
        self.event_io = ctx.socket(zmq.DEALER)
        self.event_io.setsockopt(zmq.IDENTITY, self.client_id)
        self.event_io.setsockopt(zmq.LINGER, 0)
        self.stream_in = ctx.socket(zmq.SUB)
        self.stream_in.setsockopt(zmq.LINGER, 0)

    # ----------------------------------------------------------- connection
    def connect(self, host="127.0.0.1", event_port=DEFAULT_PORTS["event"],
                stream_port=DEFAULT_PORTS["stream"], timeout=5.0,
                backoff_base=None, backoff_cap=None):
        """REGISTER handshake with exponential backoff + jitter.

        A dropped or late server (not yet bound, restarting, a dropped
        REGISTER frame) is survived by re-sending REGISTER with the
        per-attempt wait growing ``backoff_base * 2^k`` up to
        ``backoff_cap``, plus 0-25% random jitter so a fleet of clients
        re-registering after a server restart does not stampede in sync.
        Total wall time stays bounded by ``timeout``; attempts are
        counted in ``self.connect_attempts``.
        """
        from .. import settings
        import random
        base = backoff_base if backoff_base is not None \
            else getattr(settings, "connect_backoff_base", 0.25)
        cap = backoff_cap if backoff_cap is not None \
            else getattr(settings, "connect_backoff_cap", 4.0)
        self._endpoints = (f"tcp://{host}:{event_port}",
                           f"tcp://{host}:{stream_port}")
        self.event_io.connect(self._endpoints[0])
        self.stream_in.connect(self._endpoints[1])
        deadline = time.perf_counter() + timeout
        delay = max(1e-3, float(base))
        self.connect_attempts = 0
        while time.perf_counter() < deadline:
            self.connect_attempts += 1
            self.send_event(b"REGISTER", target=b"")
            # wait one backoff interval (bounded by the deadline) for
            # the handshake ack before re-sending
            t_end = min(deadline,
                        time.perf_counter() + delay * (1.0
                                                       + 0.25 * random.random()))
            while time.perf_counter() < t_end:
                if self.event_io.poll(50):
                    route, name, payload = split_envelope(
                        self.event_io.recv_multipart())
                    if name == b"REGISTER":
                        data = unpackb(payload)
                        self.host_id = data["host_id"]
                        self._absorb_ha_ack(data)
                        self._set_nodes(data["nodes"])
                        return
                    self._dispatch(route, name, payload)
            delay = min(delay * 2.0, float(cap))
        raise TimeoutError(
            f"no REGISTER reply from server after "
            f"{self.connect_attempts} attempts in {timeout:.1f} s")

    def close(self):
        self.event_io.close()
        self.stream_in.close()

    def _absorb_ha_ack(self, data):
        """Fold an HA server's REGISTER-ack lease terms in (pid always
        rides the ack; epoch/ttl/discovery only from an HA server)."""
        if not isinstance(data, dict):
            return
        self.host_pid = data.get("pid", self.host_pid)
        if "epoch" in data:
            self.host_epoch = int(data["epoch"])
            self.host_lease_ttl = float(data.get("lease_ttl", 0.0)
                                        or 0.0)
            self.host_disc_port = data.get("discovery",
                                           self.host_disc_port)

    @staticmethod
    def arbitrate(replies):
        """Pick the server to talk to from a burst of discovery
        replies: standbys are skipped (not serving), the highest lease
        epoch wins (a deposed leader's stale reply advertises an older
        one), first-seen breaks ties.  Returns a discovery.Reply or
        None."""
        best = None
        for reply in replies:
            if reply is None or reply.role == "standby":
                continue
            if best is None or reply.epoch > best.epoch:
                best = reply
        return best

    @staticmethod
    def discover(timeout=3.0, settle=0.25, port=None):
        """Broadcast on the LAN and return the winning discovery.Reply.

        After the first reply lands, keep collecting for a short
        ``settle`` window so two-servers-one-leader setups (broker HA:
        a live leader plus a deposed one or a warm standby) arbitrate
        by epoch/role instead of by datagram race."""
        disc = Discovery(make_id(), is_client=True,
                         **({"port": port} if port else {}))
        replies = []
        try:
            disc.send_request()
            t_end = time.perf_counter() + timeout
            while time.perf_counter() < t_end:
                kind, reply = disc.recv_reqreply()
                if kind == "rep":
                    replies.append(reply)
                    t_end = min(t_end,
                                time.perf_counter() + max(0.0, settle))
        finally:
            disc.close()
        return Client.arbitrate(replies)

    def failover(self, timeout=3.0):
        """Broker-HA failover: re-run discovery, move the DEALER/SUB
        pair to the arbitration winner (a leader with a strictly higher
        epoch than the one we registered with) and re-REGISTER.  The
        DEALER identity is preserved, so the server sees the same
        client.  Returns True if a newer leader was adopted."""
        if self.host_epoch is None:
            return False           # non-HA server: nothing to fail to
        best = self.discover(timeout=timeout, port=self.host_disc_port)
        if best is None or best.epoch <= self.host_epoch:
            return False
        old = self._endpoints
        self._endpoints = (f"tcp://{best.ip}:{best.event_port}",
                           f"tcp://{best.ip}:{best.stream_port}")
        if old:
            for sock, ep in ((self.event_io, old[0]),
                             (self.stream_in, old[1])):
                try:
                    sock.disconnect(ep)
                except zmq.ZMQError:
                    pass
        self.event_io.connect(self._endpoints[0])
        self.stream_in.connect(self._endpoints[1])
        self.host_epoch = best.epoch
        self.send_event(b"REGISTER", target=b"")
        return True

    # ----------------------------------------------------------------- I/O
    def send_event(self, name: bytes, data=None, target=None):
        """target: None -> active node, b'' -> server, b'*' -> all nodes,
        or an explicit node id."""
        if target is None:
            if not self.nodes:
                # no sim node registered yet (worker still starting up):
                # queue instead of broadcasting into an empty worker set
                self._pending.append((name, data))
                return
            target = self.act or b"*"
        route = [target] if target else []
        self.event_io.send_multipart(route + [name, packb(data)])

    def stack(self, cmdline: str, target=None):
        self.send_event(b"STACKCMD", cmdline, target)

    def request_health(self):
        """Ask the server for its serving-fabric health snapshot; the
        reply arrives as a ``HEALTH`` event (also cached in
        ``self.last_health``)."""
        self.send_event(b"HEALTH", target=b"")

    def request_metrics(self):
        """Ask the server for its telemetry registries (broker + fleet
        aggregate); the reply arrives as a ``METRICS`` event (cached in
        ``self.last_metrics``)."""
        self.send_event(b"METRICS", target=b"")

    def subscribe(self, streamname: bytes, node_id: bytes = b""):
        self.stream_in.setsockopt(zmq.SUBSCRIBE, streamname + node_id)

    def unsubscribe(self, streamname: bytes, node_id: bytes = b""):
        self.stream_in.setsockopt(zmq.UNSUBSCRIBE, streamname + node_id)

    def actnode(self, node_id: bytes = None) -> bytes:
        if node_id is not None and node_id in self.nodes:
            self.act = node_id
        return self.act

    # ------------------------------------------------------------- receive
    def receive(self, timeout_ms: int = 0) -> int:
        """Pump both sockets; returns number of messages handled."""
        n = 0
        while self.event_io.poll(timeout_ms if n == 0 else 0):
            route, name, payload = split_envelope(
                self.event_io.recv_multipart())
            self._dispatch(route, name, payload)
            n += 1
        while self.stream_in.poll(0):
            topic, payload = self.stream_in.recv_multipart()
            name, sender = topic[:-5], topic[-5:]
            self.stream_received.emit(name, unpackb(payload), sender)
            n += 1
        return n

    def _dispatch(self, route, name, payload):
        data = unpackb(payload) if payload else None
        if name in (b"NODESCHANGED", b"REGISTER"):
            # REGISTER here is the late ack of a retried handshake
            # (backoff re-sends) or of a failover re-REGISTER: absorb
            # it as a node-table + HA-lease refresh instead of
            # surfacing a duplicate handshake event
            self.host_id = data["host_id"]
            if name == b"REGISTER":
                self._absorb_ha_ack(data)
            self._set_nodes(data["nodes"])
        else:
            if name == b"BATCHREJECTED":
                self.last_rejection = data   # retry logic reads this
            elif name == b"HEALTH":
                self.last_health = data
            elif name == b"METRICS":
                self.last_metrics = data
            elif name == b"TRACE":
                self.last_trace = data
            elif name == b"HA":
                self.last_ha = data
            elif name == b"BATCHOPT":
                self.opt_results.append(data)
            sender = route[0] if route else b""
            self.event_received.emit(name, data, sender)

    def _set_nodes(self, nodes):
        self.nodes = list(nodes)
        if (not self.act or self.act not in self.nodes) and self.nodes:
            self.act = self.nodes[0]
        self.nodes_changed.emit(self.nodes)
        if self.nodes and self._pending:
            pending, self._pending = self._pending, []
            for name, data in pending:
                self.send_event(name, data)
