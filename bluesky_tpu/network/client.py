"""GUI/script-side network endpoint (parity: bluesky/network/client.py:16-196).

DEALER event socket + SUB stream socket.  ``connect()`` performs the
REGISTER handshake with a timeout; ``receive()`` pumps both sockets and
emits ``event_received(name, data, sender_id)`` /
``stream_received(name, data, sender_id)`` signals.  Tracks the set of sim
nodes (from NODESCHANGED) and an *active node* that untargeted events
(stack commands) are routed to.
"""
import time

import zmq

from ..utils.signalslot import Signal
from .common import DEFAULT_PORTS, make_id
from .discovery import Discovery
from .node import split_envelope
from .npcodec import packb, unpackb


class Client:
    def __init__(self):
        self.client_id = make_id()
        self.host_id = b""
        self.nodes = []            # known sim node ids
        self.act = b""             # active node id
        self.event_received = Signal("event")
        self.stream_received = Signal("stream")
        self.nodes_changed = Signal("nodes")
        self._pending = []         # node-bound events queued until a node registers
        self.last_rejection = None  # latest BATCHREJECTED payload (the
        #                             admission-control refusal carries
        #                             queue depth + a retry-after hint)
        self.last_health = None     # latest HEALTH reply payload
        self.last_metrics = None    # latest METRICS (telemetry) reply
        self.last_trace = None      # latest TRACE reply (dump path)
        self.opt_results = []       # BATCHOPT reports (OPT-piece
        #                             trajectory-optimization results:
        #                             offsets + objective trace)
        ctx = zmq.Context.instance()
        self.event_io = ctx.socket(zmq.DEALER)
        self.event_io.setsockopt(zmq.IDENTITY, self.client_id)
        self.event_io.setsockopt(zmq.LINGER, 0)
        self.stream_in = ctx.socket(zmq.SUB)
        self.stream_in.setsockopt(zmq.LINGER, 0)

    # ----------------------------------------------------------- connection
    def connect(self, host="127.0.0.1", event_port=DEFAULT_PORTS["event"],
                stream_port=DEFAULT_PORTS["stream"], timeout=5.0,
                backoff_base=None, backoff_cap=None):
        """REGISTER handshake with exponential backoff + jitter.

        A dropped or late server (not yet bound, restarting, a dropped
        REGISTER frame) is survived by re-sending REGISTER with the
        per-attempt wait growing ``backoff_base * 2^k`` up to
        ``backoff_cap``, plus 0-25% random jitter so a fleet of clients
        re-registering after a server restart does not stampede in sync.
        Total wall time stays bounded by ``timeout``; attempts are
        counted in ``self.connect_attempts``.
        """
        from .. import settings
        import random
        base = backoff_base if backoff_base is not None \
            else getattr(settings, "connect_backoff_base", 0.25)
        cap = backoff_cap if backoff_cap is not None \
            else getattr(settings, "connect_backoff_cap", 4.0)
        self.event_io.connect(f"tcp://{host}:{event_port}")
        self.stream_in.connect(f"tcp://{host}:{stream_port}")
        deadline = time.perf_counter() + timeout
        delay = max(1e-3, float(base))
        self.connect_attempts = 0
        while time.perf_counter() < deadline:
            self.connect_attempts += 1
            self.send_event(b"REGISTER", target=b"")
            # wait one backoff interval (bounded by the deadline) for
            # the handshake ack before re-sending
            t_end = min(deadline,
                        time.perf_counter() + delay * (1.0
                                                       + 0.25 * random.random()))
            while time.perf_counter() < t_end:
                if self.event_io.poll(50):
                    route, name, payload = split_envelope(
                        self.event_io.recv_multipart())
                    if name == b"REGISTER":
                        data = unpackb(payload)
                        self.host_id = data["host_id"]
                        self._set_nodes(data["nodes"])
                        return
                    self._dispatch(route, name, payload)
            delay = min(delay * 2.0, float(cap))
        raise TimeoutError(
            f"no REGISTER reply from server after "
            f"{self.connect_attempts} attempts in {timeout:.1f} s")

    def close(self):
        self.event_io.close()
        self.stream_in.close()

    @staticmethod
    def discover(timeout=3.0):
        """Broadcast on the LAN and return the first discovery.Reply."""
        disc = Discovery(make_id(), is_client=True)
        try:
            disc.send_request()
            t0 = time.perf_counter()
            while time.perf_counter() - t0 < timeout:
                kind, reply = disc.recv_reqreply()
                if kind == "rep":
                    return reply
        finally:
            disc.close()
        return None

    # ----------------------------------------------------------------- I/O
    def send_event(self, name: bytes, data=None, target=None):
        """target: None -> active node, b'' -> server, b'*' -> all nodes,
        or an explicit node id."""
        if target is None:
            if not self.nodes:
                # no sim node registered yet (worker still starting up):
                # queue instead of broadcasting into an empty worker set
                self._pending.append((name, data))
                return
            target = self.act or b"*"
        route = [target] if target else []
        self.event_io.send_multipart(route + [name, packb(data)])

    def stack(self, cmdline: str, target=None):
        self.send_event(b"STACKCMD", cmdline, target)

    def request_health(self):
        """Ask the server for its serving-fabric health snapshot; the
        reply arrives as a ``HEALTH`` event (also cached in
        ``self.last_health``)."""
        self.send_event(b"HEALTH", target=b"")

    def request_metrics(self):
        """Ask the server for its telemetry registries (broker + fleet
        aggregate); the reply arrives as a ``METRICS`` event (cached in
        ``self.last_metrics``)."""
        self.send_event(b"METRICS", target=b"")

    def subscribe(self, streamname: bytes, node_id: bytes = b""):
        self.stream_in.setsockopt(zmq.SUBSCRIBE, streamname + node_id)

    def unsubscribe(self, streamname: bytes, node_id: bytes = b""):
        self.stream_in.setsockopt(zmq.UNSUBSCRIBE, streamname + node_id)

    def actnode(self, node_id: bytes = None) -> bytes:
        if node_id is not None and node_id in self.nodes:
            self.act = node_id
        return self.act

    # ------------------------------------------------------------- receive
    def receive(self, timeout_ms: int = 0) -> int:
        """Pump both sockets; returns number of messages handled."""
        n = 0
        while self.event_io.poll(timeout_ms if n == 0 else 0):
            route, name, payload = split_envelope(
                self.event_io.recv_multipart())
            self._dispatch(route, name, payload)
            n += 1
        while self.stream_in.poll(0):
            topic, payload = self.stream_in.recv_multipart()
            name, sender = topic[:-5], topic[-5:]
            self.stream_received.emit(name, unpackb(payload), sender)
            n += 1
        return n

    def _dispatch(self, route, name, payload):
        data = unpackb(payload) if payload else None
        if name in (b"NODESCHANGED", b"REGISTER"):
            # REGISTER here is the late ack of a retried handshake
            # (backoff re-sends): absorb it as a node-table refresh
            # instead of surfacing a duplicate handshake event
            self.host_id = data["host_id"]
            self._set_nodes(data["nodes"])
        else:
            if name == b"BATCHREJECTED":
                self.last_rejection = data   # retry logic reads this
            elif name == b"HEALTH":
                self.last_health = data
            elif name == b"METRICS":
                self.last_metrics = data
            elif name == b"TRACE":
                self.last_trace = data
            elif name == b"BATCHOPT":
                self.opt_results.append(data)
            sender = route[0] if route else b""
            self.event_received.emit(name, data, sender)

    def _set_nodes(self, nodes):
        self.nodes = list(nodes)
        if (not self.act or self.act not in self.nodes) and self.nodes:
            self.act = self.nodes[0]
        self.nodes_changed.emit(self.nodes)
        if self.nodes and self._pending:
            pending, self._pending = self._pending, []
            for name, data in pending:
                self.send_event(name, data)
