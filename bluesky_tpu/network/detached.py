"""Detached node: the Node interface with no networking
(parity: bluesky/network/detached.py:7-50).

For embedding the TPU sim in other Python programs (tests, notebooks,
batch scripts): events are delivered by direct calls, streams collected in
a buffer the host program may drain.
"""
from ..utils.timer import Timer
from .common import make_id


class Node:
    def __init__(self, *args, **kwargs):
        self.node_id = make_id()
        self.host_id = make_id()
        self.running = False
        self.streams = []         # [(name, data)] drained by the embedder

    def connect(self):
        pass

    def close(self):
        pass

    def quit(self):
        self.running = False

    def send_event(self, name: bytes, data=None, route=None):
        # loop server-bound events straight back into the handler
        self.event(name, data, [self.node_id])

    def send_stream(self, name: bytes, data):
        self.streams.append((name, data))

    def event(self, name: bytes, data, sender_route):
        pass

    def step(self):
        pass

    def process_events(self, timeout_ms: int = 0) -> int:
        return 0

    def run(self):
        self.running = True
        while self.running:
            self.step()
            Timer.update_timers()
