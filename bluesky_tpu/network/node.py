"""Sim-side network endpoint (parity: bluesky/network/node.py:13-96).

A Node owns a DEALER event socket and a PUB stream socket connected to the
Server's worker-facing ports.  Wire format for events is source-routed
multipart: ``[*route, name, payload]`` where route frames are 5-byte ids
(leading zero byte, common.make_id) or ``b'*'``; the first frame that is
neither is the event name.  Replies go back along the accumulated return
route (see server.py for the rotation rule).  Streams are PUB frames
``[name + node_id, payload]`` so SUB prefix-matching selects by stream name
(and optionally by node).
"""
import os
import threading
import time

import zmq

from ..utils.timer import Timer
from .common import DEFAULT_PORTS, make_id
from .npcodec import packb, unpackb


class EventLoopWatchdog(threading.Thread):
    """Detects a stalled worker event loop (GC pause, NFS hang, runaway
    host callback, FAULT STALL): the run loop ``beat()``s every
    iteration; if no beat lands for ``warn_after`` seconds the watchdog
    prints a warning and records the stall, and — when ``kill_after`` is
    set — exits the process with code 70 after that long, so the server
    reaps the silent worker, requeues its BATCH piece and respawns.

    ``kill_after`` defaults OFF: a first-compile of the big sharded
    programs can legitimately block the loop for minutes, and the
    server's busy-worker PING budget (10x hb_timeout, server.py) already
    covers pong-silence — the kill switch is for deployments that prefer
    fail-fast workers (settings.node_watchdog_kill).
    """

    def __init__(self, warn_after=30.0, kill_after=0.0, name=""):
        super().__init__(daemon=True)
        self.warn_after = float(warn_after)
        self.kill_after = float(kill_after)
        self.tag = name
        self.stalls = []             # [(stamp, silence_s)] observed stalls
        self._beat = time.monotonic()
        self._stop = threading.Event()
        self._warned = False

    def beat(self):
        self._beat = time.monotonic()
        self._warned = False

    def stop(self):
        self._stop.set()

    def run(self):
        ref = self.warn_after if self.warn_after > 0 else self.kill_after
        interval = max(0.1, min(1.0, ref / 4.0))
        while not self._stop.wait(interval):
            silence = time.monotonic() - self._beat
            if self.kill_after > 0 and silence > self.kill_after:
                print(f"watchdog{self.tag}: event loop silent "
                      f"{silence:.1f} s > kill_after="
                      f"{self.kill_after:.1f} s — exiting 70 so the "
                      "server respawns this worker", flush=True)
                os._exit(70)
            if self.warn_after > 0 and silence > self.warn_after \
                    and not self._warned:
                self._warned = True
                self.stalls.append((time.monotonic(), silence))
                print(f"watchdog{self.tag}: event loop stalled "
                      f"{silence:.1f} s (> {self.warn_after:.1f} s)",
                      flush=True)


def split_envelope(frames):
    """Split multipart frames into (route, name, payload)."""
    for i, frame in enumerate(frames):
        if not (frame == b"*" or (frame and frame[0:1] == b"\x00")):
            return frames[:i], frame, frames[i + 1] if i + 1 < len(frames) \
                else b""
    raise ValueError("malformed envelope: no name frame")


class Node:
    """Worker endpoint; subclass and override event()/step()."""

    def __init__(self, event_port: int = DEFAULT_PORTS["wevent"],
                 stream_port: int = DEFAULT_PORTS["wstream"],
                 host: str = "127.0.0.1", node_id: bytes = None,
                 watchdog_warn: float = None, watchdog_kill: float = None):
        # node_id may be assigned by the spawning server (so it can map
        # its child process to the registered worker for crash
        # detection); self-started nodes generate their own.
        self.node_id = node_id or make_id()
        self.host_id = b""        # filled by REGISTER reply
        self.running = False
        # broker HA (network/ha.py): learned from an HA server's
        # REGISTER ack — a lease epoch in the ack is what ARMS the
        # failover detector, so against a non-HA server every check
        # below is inert
        self.server_pid = None           # broker pid (FAULT KILLSERVER)
        self.server_epoch = None         # lease epoch, None = HA off
        self.server_lease_ttl = 0.0
        self.server_disc_port = None     # where to re-run discovery
        self._srv_last = time.monotonic()   # last traffic from server
        self._ha_next_probe = 0.0        # failover probe rate limit
        from .. import settings
        self._wd_warn = watchdog_warn if watchdog_warn is not None \
            else getattr(settings, "node_watchdog_warn", 30.0)
        self._wd_kill = watchdog_kill if watchdog_kill is not None \
            else getattr(settings, "node_watchdog_kill", 0.0)
        self.watchdog = None      # started by run()
        ctx = zmq.Context.instance()
        self.event_io = ctx.socket(zmq.DEALER)
        self.event_io.setsockopt(zmq.IDENTITY, self.node_id)
        # short linger so the final STATECHANGE(-1) flushes before close()
        self.event_io.setsockopt(zmq.LINGER, 500)
        self.stream_out = ctx.socket(zmq.PUB)
        self.stream_out.setsockopt(zmq.LINGER, 0)
        # bounded send buffer: a stalled broker/subscriber costs this
        # worker dropped stream frames (PUB drops at HWM), never a
        # blocked step loop (docs/FAULT_TOLERANCE.md row #11)
        self.stream_out.setsockopt(
            zmq.SNDHWM, int(getattr(settings, "stream_sndhwm", 1000)))
        self._endpoints = (f"tcp://{host}:{event_port}",
                           f"tcp://{host}:{stream_port}")

    # ------------------------------------------------------------ lifecycle
    def connect(self):
        self.event_io.connect(self._endpoints[0])
        self.stream_out.connect(self._endpoints[1])
        self.send_event(b"REGISTER", self.register_payload())

    def quit(self):
        self.running = False

    def close(self):
        self.event_io.close()
        self.stream_out.close()

    # ------------------------------------------------------------------ I/O
    def send_event(self, name: bytes, data=None, route=None):
        frames = list(route or []) + [name, packb(data)]
        self.event_io.send_multipart(frames)

    def send_stream(self, name: bytes, data):
        self.stream_out.send_multipart([name + self.node_id, packb(data)])

    # ------------------------------------------------------------- signals
    def _install_signal_handlers(self):
        """SIGTERM/SIGINT are treated as a preemption notice (cluster
        scheduler reclaiming the node, operator Ctrl-C): route them to
        ``on_preempt_signal`` so subclasses can drain the in-flight
        chunk and checkpoint instead of dying mid-scan.  Main-thread
        only (signal-module restriction); embedded/test nodes running
        in a worker thread use ``sim.request_preempt()`` directly —
        both paths converge on the same drain code."""
        import signal as _signal
        if threading.current_thread() is not threading.main_thread():
            return
        self._old_sig = {}
        for s in (_signal.SIGTERM, _signal.SIGINT):
            try:
                self._old_sig[s] = _signal.signal(
                    s, lambda signum, frame: self.on_preempt_signal(signum))
            except (ValueError, OSError):
                pass

    def _restore_signal_handlers(self):
        import signal as _signal
        for s, h in getattr(self, "_old_sig", {}).items():
            try:
                _signal.signal(s, h)
            except (ValueError, OSError, TypeError):
                pass

    def on_preempt_signal(self, signum):
        """Default preemption response: leave the loop (the teardown
        still sends STATECHANGE -1).  SimNode overrides this to drain
        the chunk and write a final checkpoint first."""
        self.quit()

    # ----------------------------------------------------------- watchdog
    def _watchdog_start(self):
        # either knob arms the thread: warn=0 + kill>0 is the
        # "fail-fast quietly" deployment and must still exit on a stall
        if (self._wd_warn > 0 or self._wd_kill > 0) \
                and self.watchdog is None:
            self.watchdog = EventLoopWatchdog(
                self._wd_warn, self._wd_kill,
                name=f"[{self.node_id.hex()[:8]}]")
            self.watchdog.start()

    def _watchdog_beat(self):
        if self.watchdog is not None:
            self.watchdog.beat()

    def _watchdog_stop(self):
        if self.watchdog is not None:
            self.watchdog.stop()

    # ------------------------------------------------------------ overrides
    def register_payload(self):
        """REGISTER payload.  The base node sends none; SimNode reports
        its in-flight BATCH piece so a re-REGISTER after broker
        failover lets the new leader ADOPT the running piece instead of
        requeueing it (server._ha_adopt)."""
        return None

    def heartbeat_payload(self, stamp):
        """PONG payload for a server PING.  The base node just echoes
        the stamp; SimNode returns a progress dict (simt, chunks done,
        state) so the server's straggler detector can distinguish a
        worker that is advancing slowly from one whose progress has
        stalled outright — and both from one that is silent (a long
        first-compile blocks this loop entirely, so NO heartbeat
        arrives and the busy-PING budget applies instead)."""
        return stamp

    def event(self, name: bytes, data, sender_route):
        """Handle one event; override in subclasses."""

    def step(self):
        """One host-loop iteration of work; override in subclasses."""

    # ------------------------------------------------------------ main loop
    def process_events(self, timeout_ms: int = 0) -> int:
        """Drain pending events; returns number handled."""
        n = 0
        while True:
            if not self.event_io.poll(timeout_ms if n == 0 else 0):
                return n
            route, name, payload = split_envelope(
                self.event_io.recv_multipart())
            n += 1
            self._srv_last = time.monotonic()  # any traffic counts
            data = unpackb(payload) if payload else None
            if name == b"REGISTER":
                # handshake ack: payload carries the server id, the
                # broker pid, and — from an HA server — the lease terms
                # that arm the failover detector
                self.host_id = data["host_id"]
                self.server_pid = data.get("pid", self.server_pid)
                if "epoch" in data:
                    self.server_epoch = int(data["epoch"])
                    self.server_lease_ttl = float(
                        data.get("lease_ttl", 0.0) or 0.0)
                    self.server_disc_port = data.get(
                        "discovery", self.server_disc_port)
            elif name == b"PING":
                # server liveness probe: echo the stamp back (the reply
                # is protocol-level so every Node flavor is covered).
                # Subclasses piggyback progress on the reply so the
                # server can tell a stalled worker from a busy one.
                self.send_event(b"PONG", self.heartbeat_payload(data))
            elif name == b"QUIT":
                self.quit()
            else:
                self.event(name, data, route)

    # ---------------------------------------------- broker-HA failover
    def _check_failover(self):
        """Broker-HA failover detector (network/ha.py): an HA server's
        REGISTER ack carried a lease epoch — once the event socket has
        been silent past 1.5x that lease ttl, re-run discovery and move
        to whichever server replies as LEADER with a strictly higher
        epoch (the promoted standby; a deposed leader's stale reply
        loses the arbitration).  Against a non-HA server no epoch was
        ever learned and this returns immediately."""
        if self.server_epoch is None or self.server_disc_port is None:
            return
        now = time.monotonic()
        ttl = self.server_lease_ttl or 10.0
        if now - self._srv_last <= 1.5 * ttl \
                or now < self._ha_next_probe:
            return
        self._ha_next_probe = now + max(0.5, ttl / 4.0)
        from .discovery import Discovery
        best = None
        try:
            disc = Discovery(self.node_id, is_client=True,
                             port=self.server_disc_port)
        except OSError:
            return
        try:
            disc.send_request()
            t_end = time.monotonic() + 0.5
            while time.monotonic() < t_end:
                kind, reply = disc.recv_reqreply()
                if kind != "rep" or reply.role != "leader":
                    continue
                if reply.epoch > self.server_epoch \
                        and (best is None or reply.epoch > best.epoch):
                    best = reply
        finally:
            disc.close()
        if best is None:
            return
        print(f"node {self.node_id.hex()[:8]}: server silent "
              f"{now - self._srv_last:.1f}s — failing over to "
              f"{best.ip}:{best.wevent or best.event_port} "
              f"(epoch {best.epoch})")
        self.server_epoch = best.epoch
        # a Node is a WORKER: reconnect to the new leader's worker-side
        # ROUTER pair, advertised separately in HA replies (the plain
        # event/stream ports are client-facing — a REGISTER there would
        # enrol us as a client and the in-flight report would be lost)
        self._reconnect(best.ip, best.wevent or best.event_port,
                        best.wstream or best.stream_port)

    def _reconnect(self, host, event_port, stream_port):
        """Move the DEALER/PUB pair to a new server.  The DEALER keeps
        its identity, so the re-REGISTER is idempotent server-side;
        frames queued to the dead endpoint are dropped with it — a lost
        completion was never journaled, so the piece stays owed and
        exactly-once holds."""
        old = self._endpoints
        self._endpoints = (f"tcp://{host}:{event_port}",
                           f"tcp://{host}:{stream_port}")
        for sock, ep in ((self.event_io, old[0]),
                         (self.stream_out, old[1])):
            try:
                sock.disconnect(ep)
            except zmq.ZMQError:
                pass
        self.event_io.connect(self._endpoints[0])
        self.stream_out.connect(self._endpoints[1])
        self.send_event(b"REGISTER", self.register_payload())
        self._srv_last = time.monotonic()

    def run(self):
        """Blocking loop: events -> step -> wall-clock timers (node.py:55-80).

        The loop beats the event-loop watchdog every iteration; a stall
        anywhere in events/step (FAULT STALL, a wedged host callback)
        is detected and reported — and, when node_watchdog_kill is set,
        turned into a clean exit(70) the server recovers from.
        """
        self.running = True
        self.connect()
        self._install_signal_handlers()
        self._watchdog_start()
        try:
            while self.running:
                self._watchdog_beat()
                self.process_events(timeout_ms=1)
                self._check_failover()
                self.step()
                Timer.update_timers()
        finally:
            # the watchdog must die with the loop even on an exception:
            # with kill_after armed, an orphaned watchdog would
            # os._exit(70) the process mid-traceback (or kill an
            # embedding host that had caught and recovered)
            self._watchdog_stop()
            self._restore_signal_handlers()
        # tell the server we are gone, then tear down
        self.send_event(b"STATECHANGE", -1)
        self.close()
