"""Sim-side network endpoint (parity: bluesky/network/node.py:13-96).

A Node owns a DEALER event socket and a PUB stream socket connected to the
Server's worker-facing ports.  Wire format for events is source-routed
multipart: ``[*route, name, payload]`` where route frames are 5-byte ids
(leading zero byte, common.make_id) or ``b'*'``; the first frame that is
neither is the event name.  Replies go back along the accumulated return
route (see server.py for the rotation rule).  Streams are PUB frames
``[name + node_id, payload]`` so SUB prefix-matching selects by stream name
(and optionally by node).
"""
import zmq

from ..utils.timer import Timer
from .common import DEFAULT_PORTS, make_id
from .npcodec import packb, unpackb


def split_envelope(frames):
    """Split multipart frames into (route, name, payload)."""
    for i, frame in enumerate(frames):
        if not (frame == b"*" or (frame and frame[0:1] == b"\x00")):
            return frames[:i], frame, frames[i + 1] if i + 1 < len(frames) \
                else b""
    raise ValueError("malformed envelope: no name frame")


class Node:
    """Worker endpoint; subclass and override event()/step()."""

    def __init__(self, event_port: int = DEFAULT_PORTS["wevent"],
                 stream_port: int = DEFAULT_PORTS["wstream"],
                 host: str = "127.0.0.1", node_id: bytes = None):
        # node_id may be assigned by the spawning server (so it can map
        # its child process to the registered worker for crash
        # detection); self-started nodes generate their own.
        self.node_id = node_id or make_id()
        self.host_id = b""        # filled by REGISTER reply
        self.running = False
        ctx = zmq.Context.instance()
        self.event_io = ctx.socket(zmq.DEALER)
        self.event_io.setsockopt(zmq.IDENTITY, self.node_id)
        # short linger so the final STATECHANGE(-1) flushes before close()
        self.event_io.setsockopt(zmq.LINGER, 500)
        self.stream_out = ctx.socket(zmq.PUB)
        self.stream_out.setsockopt(zmq.LINGER, 0)
        self._endpoints = (f"tcp://{host}:{event_port}",
                           f"tcp://{host}:{stream_port}")

    # ------------------------------------------------------------ lifecycle
    def connect(self):
        self.event_io.connect(self._endpoints[0])
        self.stream_out.connect(self._endpoints[1])
        self.send_event(b"REGISTER", None)

    def quit(self):
        self.running = False

    def close(self):
        self.event_io.close()
        self.stream_out.close()

    # ------------------------------------------------------------------ I/O
    def send_event(self, name: bytes, data=None, route=None):
        frames = list(route or []) + [name, packb(data)]
        self.event_io.send_multipart(frames)

    def send_stream(self, name: bytes, data):
        self.stream_out.send_multipart([name + self.node_id, packb(data)])

    # ------------------------------------------------------------ overrides
    def event(self, name: bytes, data, sender_route):
        """Handle one event; override in subclasses."""

    def step(self):
        """One host-loop iteration of work; override in subclasses."""

    # ------------------------------------------------------------ main loop
    def process_events(self, timeout_ms: int = 0) -> int:
        """Drain pending events; returns number handled."""
        n = 0
        while True:
            if not self.event_io.poll(timeout_ms if n == 0 else 0):
                return n
            route, name, payload = split_envelope(
                self.event_io.recv_multipart())
            n += 1
            data = unpackb(payload) if payload else None
            if name == b"REGISTER":
                # handshake ack: payload carries the server id
                self.host_id = data["host_id"]
            elif name == b"PING":
                # server liveness probe: echo the stamp back (the reply
                # is protocol-level so every Node flavor is covered)
                self.send_event(b"PONG", data)
            elif name == b"QUIT":
                self.quit()
            else:
                self.event(name, data, route)

    def run(self):
        """Blocking loop: events -> step -> wall-clock timers (node.py:55-80)."""
        self.running = True
        self.connect()
        while self.running:
            self.process_events(timeout_ms=1)
            self.step()
            Timer.update_timers()
        # tell the server we are gone, then tear down
        self.send_event(b"STATECHANGE", -1)
        self.close()
