"""Process fabric: ZMQ broker + sim nodes + clients (parity: bluesky/network/).

Architecture (same topology as the reference, reimplemented fresh):

  Client (DEALER+SUB) <-> Server broker (ROUTER:event_port / XPUB:stream_port
  client-facing; ROUTER:wevent_port / XSUB:wstream_port worker-facing)
  <-> Node workers (DEALER+PUB), each running one TPU Simulation.

Events are source-routed multipart messages ``[*route, name, payload]`` with
the route rotated one hop per forward; ``b'*'`` broadcasts.  Streams are
PUB/SUB topics ``name + node_id`` carried XSUB->XPUB through the broker.
This layer is deliberately host-side Python: per SURVEY.md §5.8 it is the
control plane; device-side communication is XLA collectives (parallel/).
"""
from .common import DEFAULT_PORTS, get_ownip, make_id
from .npcodec import packb, unpackb
