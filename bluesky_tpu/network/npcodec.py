"""msgpack codec with transparent numpy ndarray support.

Parity: bluesky/network/npcodec.py:3-16 — arrays travel as a tagged map of
``{dtype, shape, data}`` with raw ``tobytes()`` payload (no pickling, safe to
decode from untrusted peers).  JAX arrays are converted via ``np.asarray``
at the call site before packing (device->host copy happens exactly once,
at the stream boundary).
"""
import msgpack
import numpy as np

_ND = "__nd__"


def _encode(obj):
    if isinstance(obj, np.ndarray):
        return {_ND: True, "t": obj.dtype.str, "s": list(obj.shape),
                "d": obj.tobytes()}
    if isinstance(obj, (np.generic,)):
        return obj.item()
    raise TypeError(f"cannot serialize {type(obj)}")


def _decode(obj):
    if isinstance(obj, dict) and obj.get(_ND):
        arr = np.frombuffer(obj["d"], dtype=np.dtype(obj["t"]))
        return arr.reshape(obj["s"])
    return obj


def packb(data) -> bytes:
    return msgpack.packb(data, default=_encode, use_bin_type=True)


def unpackb(raw: bytes):
    return msgpack.unpackb(raw, object_hook=_decode, raw=False,
                           strict_map_key=False)
