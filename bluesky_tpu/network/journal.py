"""Crash-resumable BATCH journal: an append-only JSONL write-ahead log.

The server's sweep state (``scenarios``/``inflight``/``piece_crashes``/
``quarantined`` in server.py) is in-memory; without a WAL a server crash
or preemption loses a multi-hour sweep.  Every state transition of a
BATCH piece is journaled BEFORE/AS it happens, and ``--resume-batch
<journal>`` replays the log on restart to rebuild the queue with
exactly-once completion semantics: completed pieces are not re-run,
pieces in flight at crash time are requeued, quarantine decisions
persist.

Record types (one JSON object per line, ``rec`` selects the type):

  ``queued``      {key, scentime, scencmd}  piece entered the queue (the
                                            only record carrying the
                                            full piece, so the journal
                                            alone can rebuild it)
  ``dispatched``  {key, worker}             piece handed to a worker
  ``completed``   {key, worker}             piece finished cleanly
  ``crashed``     {key, crashes}            piece lost its worker (one
                                            circuit-breaker strike)
  ``quarantined`` {key, piece, crashes}     circuit-broken: never requeue
  ``preempted``   {key, worker}             worker preempted mid-piece:
                                            requeue WITHOUT a strike
  ``mesh_lost``   {key, worker, epoch, lost}  a device group of the
                                            worker's sharded mesh died
                                            mid-piece (audit; if the
                                            worker could not recover the
                                            piece is requeued WITHOUT a
                                            strike, PREEMPTED-style)
  ``resharded``   {key, worker, epoch, ndev, mode}  the worker re-formed
                                            a survivor mesh and resumed
                                            the SAME piece from its last
                                            checksummed snapshot — audit
                                            only, queue math ignores it
  ``hedged``      {key, worker, hedge_worker}  speculative straggler
                                            re-dispatch: a SECOND copy
                                            of an in-flight piece went
                                            to ``hedge_worker`` (first
                                            completion wins)
  ``dup_completed`` {key, worker}           the hedge LOSER also finished
                                            after the winner's
                                            ``completed``: recorded for
                                            audit, NOT counted as a
                                            completion (a repeat-trial
                                            sweep queueing identical
                                            content twice must not have
                                            its second copy consumed by
                                            a hedge duplicate)
  ``opt_result``  {key, worker, result}     trajectory-optimization
                                            output of an OPT piece
                                            (diff/optimize.py: offsets,
                                            objective trace, hard-LoS
                                            before/after, guard word) —
                                            audit only, queue math
                                            ignores it
  ``perf_regression`` {key, worker, rate, baseline, factor}  serving
                                            SLO watch (ISSUE-12): an
                                            in-flight piece's rolling
                                            steps/s fell below
                                            ``perf_slo_factor`` x the
                                            fleet median — audit only,
                                            queue math/exactly-once
                                            unaffected; surfaced by
                                            replay for inspection
  ``mitigation``  {cause, signal, action, target, outcome}  the
                                            mitigation engine
                                            (network/mitigate.py) acted
                                            on a sentinel signal —
                                            hedge escalation, load
                                            shed/unshed, re-pack,
                                            accept-degraded.  AUDIT
                                            only: queue math and
                                            exactly-once never see it;
                                            replay surfaces the history
                                            under ``mitigations``.  May
                                            carry a piece ``key`` when
                                            the action targets one
                                            piece; shed/repack actions
                                            have none.
  ``sdc_suspect``  {key, fps, via}          SDC defense (ISSUE-17): two
                                            executions of the same piece
                                            reported DIFFERENT state
                                            fingerprints (``via`` names
                                            the comparison — hedge_dup
                                            or audit).  AUDIT only:
                                            queue math and exactly-once
                                            never see it; replay
                                            surfaces it under ``sdc``.
  ``sdc_vote``     {key, fps, deviant}      the 2-of-3 tie-break
                                            re-execution resolved: the
                                            fingerprint map names the
                                            deviant worker (hex id, or
                                            null when all three
                                            disagreed).  AUDIT only,
                                            surfaced under ``sdc``; the
                                            quarantine that follows is
                                            its own gated ``mitigation``
                                            record (action
                                            ``quarantine_worker``).
  ``device_profile`` {worker, dir, chunks}  PROFILE DEVICE window: the
                                            XLA trace dir a worker
                                            captured (audit; links the
                                            journal to the Perfetto
                                            merge)
  ``lease``       {leader, epoch, ttl}      broker-HA leadership change
                                            (network/ha.py): ``leader``
                                            (server hex id) acquired
                                            lease ``epoch``.  Replay
                                            tracks the epoch in force
                                            positionally; records a
                                            writer appends after losing
                                            the lease are FENCED (see
                                            ``wepoch`` below).
  ``adopted``     {key, worker}             broker-HA failover: the new
                                            leader matched a replayed
                                            owed copy against a
                                            surviving worker's
                                            re-REGISTER in-flight
                                            report — the piece keeps
                                            running where it is (no
                                            requeue, no breaker strike;
                                            the PREEMPTED model
                                            generalized).  AUDIT only:
                                            the copy stays owed until
                                            its own ``completed``.
  ``resumed``     {pending, completed, quarantined}  replay marker
  ``shutdown``    {}                        clean server exit

Writer epochs (broker HA, network/ha.py): when a server holds an HA
lease it stamps every record it appends with ``wepoch`` (its lease
epoch — a distinct field from the MESH ``epoch`` that mesh_lost/
resharded already carry).  Replay folds the file positionally: a
``lease`` record raises the epoch in force, and any LATER
``dispatched``/``completed`` stamped with an older ``wepoch`` is a
deposed leader's late append — fenced off as audit-only (counted
under ``fenced``, never into the queue math) so a non-atomic
leadership handover cannot double-count or lose work.  Journals from
servers without HA carry no ``wepoch`` and replay exactly as before.

Packed world-batches (WORLDS packing, network/server.py): a pack of W
compatible pieces dispatches to ONE worker; its ``dispatched`` records
carry ``world`` (index in the pack) and ``pack`` (pack size), and each
per-world completion journals its OWN ``completed`` record (``world``
audit field) as the worker's BATCHWORLD events arrive.  Replay needs no
pack awareness: owed copies stay queued-minus-completed per content
key, so a crash mid-pack requeues exactly the worlds whose pieces never
completed.

Synthetic pieces (the ``FAULT LOADSPIKE`` chaos injector): their
``queued`` records carry ``synthetic: true`` and replay SKIPS them —
load-spike filler exercises admission/shedding but must never be owed
to a resumed sweep, so exactly-once accounting ignores the whole
lifecycle of a synthetic key (its dispatched/completed records fall
through the unknown-key filter).

Piece identity is content-addressed (sha256 over the canonical JSON of
``(scentime, scencmd)``), so keys are stable across restarts and across
servers.

Append atomicity: each record is ONE ``write()`` of a single line,
flushed (+ ``fsync`` unless ``batch_journal_fsync`` is off), so a crash
can only tear the final line — ``replay`` skips unparseable tails
instead of failing.  A whole BATCH submission's ``queued`` records
share one flush+fsync (``queued_many``): the WAL guarantee only needs
the batch durable before any dispatch.  A journal write failure (disk
full) disables the journal with a warning; it must never take the
broker down with it.
"""
import hashlib
import json
import os


class BatchJournal:
    def __init__(self, path: str, fsync: bool = True):
        self.path = path
        self.fsync = bool(fsync)
        self._f = None
        self._dead = False        # set after a write failure
        self._bytes = 0           # WAL size incl. pre-resume content
        # broker-HA writer epoch (network/ha.py): None = HA off, no
        # stamping — journals stay byte-identical to a non-HA server's
        self.epoch = None

    @property
    def size_bytes(self) -> int:
        """Current WAL size in bytes (existing file at open + every
        line appended since) — the ``journal_bytes`` gauge's source, so
        HEALTH can warn before an unbounded sweep fills the disk."""
        return self._bytes

    # ------------------------------------------------------------ identity
    @staticmethod
    def piece_key(piece) -> str:
        """Content-addressed piece id, stable across restarts."""
        scentime, scencmd = piece
        blob = json.dumps([[float(t) for t in scentime],
                           [str(c) for c in scencmd]],
                          separators=(",", ":"))
        return hashlib.sha256(blob.encode("utf-8")).hexdigest()[:16]

    # ------------------------------------------------------------- writing
    def _open(self):
        if self._f is None:
            d = os.path.dirname(self.path)
            if d:
                os.makedirs(d, exist_ok=True)
            # heal a crash-torn tail: if the existing file does not end
            # in a newline, the next append would glue onto the torn
            # line and be lost to replay — terminate it first so "a
            # crash can only tear the final line" stays true across
            # resumes
            try:
                with open(self.path, "rb") as f:
                    f.seek(-1, os.SEEK_END)
                    if f.read(1) != b"\n":
                        with open(self.path, "ab") as fa:
                            fa.write(b"\n")
            except (OSError, ValueError):
                pass                      # absent or empty file
            try:
                self._bytes = os.path.getsize(self.path)
            except OSError:
                self._bytes = 0
            self._f = open(self.path, "a", encoding="utf-8")
        return self._f

    def _write(self, records):
        if self._dead or not records:
            return
        from ..obs.trace import get_recorder
        try:
            with get_recorder().span("journal_append", cat="server",
                                     nrecords=len(records),
                                     rec=records[0].get("rec", "?"),
                                     fsync=self.fsync):
                f = self._open()
                for r in records:
                    if self.epoch is not None:
                        r.setdefault("wepoch", int(self.epoch))
                    line = json.dumps(r, separators=(",", ":")) + "\n"
                    f.write(line)
                    self._bytes += len(line.encode("utf-8"))
                f.flush()
                if self.fsync:
                    os.fsync(f.fileno())
        except OSError as e:
            self._dead = True
            print(f"batch journal: disabled after write failure "
                  f"({self.path}: {e})")

    def append(self, rec: str, **fields):
        self._write([dict(rec=rec, **fields)])

    @classmethod
    def _queued_rec(cls, piece, synthetic=False):
        scentime, scencmd = piece
        rec = dict(rec="queued", key=cls.piece_key(piece),
                   scentime=[float(t) for t in scentime],
                   scencmd=[str(c) for c in scencmd])
        if synthetic:
            # chaos filler (FAULT LOADSPIKE): replay must never owe it
            rec["synthetic"] = True
        return rec

    def queued(self, piece, synthetic=False):
        self._write([self._queued_rec(piece, synthetic)])

    def queued_many(self, pieces, synthetic=False):
        """Journal a whole BATCH submission with ONE flush+fsync — the
        WAL guarantee only needs the batch on disk before any dispatch,
        and per-piece fsyncs would stall the broker poll loop for large
        sweeps."""
        self._write([self._queued_rec(p, synthetic) for p in pieces])

    def dispatched(self, piece, worker: bytes = b"", world=None,
                   pack=None):
        """``world``/``pack`` mark a piece dispatched INSIDE a packed
        world-batch (world index, pack size) — audit detail only:
        replay folds packed pieces exactly like solo ones (queued minus
        completed per content key)."""
        rec = dict(key=self.piece_key(piece), worker=worker.hex())
        if world is not None:
            rec.update(world=int(world), pack=int(pack or 0))
        self.append("dispatched", **rec)

    def completed(self, piece, worker: bytes = b"", world=None):
        rec = dict(key=self.piece_key(piece), worker=worker.hex())
        if world is not None:
            rec["world"] = int(world)
        self.append("completed", **rec)

    def crashed(self, piece, crashes: int):
        self.append("crashed", key=self.piece_key(piece),
                    crashes=int(crashes))

    def quarantined(self, piece, crashes: int):
        self.append("quarantined", key=self.piece_key(piece),
                    crashes=int(crashes))

    def preempted(self, piece, worker: bytes = b"", world=None):
        rec = dict(key=self.piece_key(piece), worker=worker.hex())
        if world is not None:
            rec["world"] = int(world)
        self.append("preempted", **rec)

    def mesh_lost(self, piece, worker: bytes = b"", world=None,
                  epoch=None, lost=None):
        """A device group of the worker's sharded mesh died mid-piece.
        Audit record: queue math ignores it — an unrecovered loss also
        requeues the piece (push_front, no strike), and replay already
        counts that via queued - completed."""
        rec = dict(key=self.piece_key(piece), worker=worker.hex())
        if world is not None:
            rec["world"] = int(world)
        if epoch is not None:
            rec["epoch"] = int(epoch)
        if lost is not None:
            rec["lost"] = list(lost)
        self.append("mesh_lost", **rec)

    def resharded(self, piece, worker: bytes = b"", world=None,
                  epoch=None, ndev=None, mode=None):
        """The worker re-formed a survivor mesh (new epoch) and resumed
        the SAME piece from its last checksummed snapshot.  Audit only."""
        rec = dict(key=self.piece_key(piece), worker=worker.hex())
        if world is not None:
            rec["world"] = int(world)
        if epoch is not None:
            rec["epoch"] = int(epoch)
        if ndev is not None:
            rec["ndev"] = int(ndev)
        if mode is not None:
            rec["mode"] = str(mode)
        self.append("resharded", **rec)

    def hedged(self, piece, worker: bytes = b"",
               hedge_worker: bytes = b""):
        self.append("hedged", key=self.piece_key(piece),
                    worker=worker.hex(),
                    hedge_worker=hedge_worker.hex())

    def dup_completed(self, piece, worker: bytes = b""):
        self.append("dup_completed", key=self.piece_key(piece),
                    worker=worker.hex())

    def opt_result(self, piece, worker: bytes = b"", result=None):
        """Trajectory-optimization result of an OPT piece
        (diff/optimize.OptResult.to_payload: optimized offsets,
        objective trace, hard-LoS before/after, guard word).  AUDIT
        data: replay surfaces it under ``opt_results`` but the queue
        math ignores it (the piece's own ``completed`` record still
        governs exactly-once)."""
        self.append("opt_result", key=self.piece_key(piece),
                    worker=worker.hex(),
                    result=result if isinstance(result, dict) else None)

    def perf_regression(self, piece, worker: bytes = b"", rate=None,
                        baseline=None, factor=None):
        """Serving SLO watch (ISSUE-12): a worker's rolling per-piece
        progress rate dropped below ``perf_slo_factor`` x the fleet
        median.  AUDIT record — the piece stays in flight (hedging,
        not this record, is the mitigation) and replay's queue math
        ignores it; surfaced under ``perf_regressions``."""
        rec = dict(key=self.piece_key(piece), worker=worker.hex())
        if rate is not None:
            rec["rate"] = round(float(rate), 4)
        if baseline is not None:
            rec["baseline"] = round(float(baseline), 4)
        if factor is not None:
            rec["factor"] = float(factor)
        self.append("perf_regression", **rec)

    def mitigation(self, cause="", signal="", action="", target="",
                   outcome="", piece=None, worker: bytes = b""):
        """The mitigation engine (network/mitigate.py) took an action
        on a sentinel signal.  AUDIT record — replay surfaces the
        decision history under ``mitigations`` but the queue math and
        exactly-once accounting never see it.  ``piece`` (when the
        action targets one piece, e.g. a hedge escalation) adds the
        content key so the decision links to the piece's lifecycle."""
        rec = dict(cause=str(cause), signal=str(signal),
                   action=str(action), target=str(target),
                   outcome=str(outcome))
        if piece is not None:
            rec["key"] = self.piece_key(piece)
        if worker:
            rec["worker"] = worker.hex()
        self.append("mitigation", **rec)

    def sdc_suspect(self, piece, fps=None, via=""):
        """SDC defense (ISSUE-17): redundant executions of one piece
        disagreed on their state fingerprints.  ``fps`` maps worker hex
        id -> fingerprint hex word; ``via`` names the comparison that
        caught it (``hedge_dup`` — winner vs hedge loser — or ``audit``
        — original vs shadow re-execution).  AUDIT record: the piece's
        queue state is untouched (the winner's ``completed`` stands
        until a vote says otherwise); replay surfaces it under
        ``sdc``."""
        self.append("sdc_suspect", key=self.piece_key(piece),
                    fps=dict(fps or {}), via=str(via))

    def sdc_vote(self, piece, fps=None, deviant=""):
        """The 2-of-3 tie-break re-execution of a suspect piece
        resolved: ``fps`` holds all three fingerprints and ``deviant``
        the out-voted worker's hex id ('' when no majority formed —
        three distinct words name nobody).  AUDIT only, surfaced under
        ``sdc``; quarantine is the mitigation engine's own record."""
        self.append("sdc_vote", key=self.piece_key(piece),
                    fps=dict(fps or {}), deviant=str(deviant))

    def lease(self, leader="", epoch=0, ttl=0.0):
        """Broker-HA leadership acquisition (network/ha.py): ``leader``
        (server hex id) now holds lease ``epoch``.  The durable half of
        the lease file — replay uses it to fence a deposed leader's
        late appends (see the ``wepoch`` notes in the module
        docstring)."""
        self.append("lease", leader=str(leader), epoch=int(epoch),
                    ttl=float(ttl))

    def adopted(self, piece, worker: bytes = b""):
        """Broker-HA failover reconciliation: the new leader matched a
        replayed owed copy of this piece against ``worker``'s in-flight
        re-REGISTER report — the piece keeps running where it is.
        AUDIT record: no requeue, no strike, and the copy stays owed
        until its own ``completed`` lands."""
        self.append("adopted", key=self.piece_key(piece),
                    worker=worker.hex())

    def device_profile(self, worker: bytes = b"", dir="", chunks=None):
        """A worker opened a PROFILE DEVICE window: journal the XLA
        trace dir so the sweep's record links to the captured trace.
        Audit only (no piece key — the window is per-worker)."""
        rec = dict(worker=worker.hex(), dir=str(dir))
        if chunks is not None:
            rec["chunks"] = int(chunks)
        self.append("device_profile", **rec)

    def shutdown(self):
        # clean-exit marker — only if this run ever journaled anything
        # (a server that never saw a BATCH must not litter log_path
        # with marker-only files)
        if self._f is not None:
            self.append("shutdown")

    def close(self):
        if self._f is not None:
            try:
                self._f.close()
            except OSError:
                pass
            self._f = None

    # ------------------------------------------------------------- replay
    @staticmethod
    def replay(path: str, fence_strict: bool = True) -> dict:
        """Fold a journal into the queue state a restarted server needs.

        Returns a dict with ``pending`` (pieces to requeue, in original
        queue order — includes pieces that were dispatched/preempted/
        crashed but never completed), ``completed``, ``quarantined``
        piece lists, ``crashes``/``quarantined_crashes`` (journal key ->
        strike count) and ``torn_lines`` (unparseable records skipped —
        a crash mid-append can only tear the final line).  Raises
        ``OSError`` if the journal cannot be read at all.

        Keys are content-addressed, so a sweep that deliberately
        repeats an identical piece (repeat trials) shares one key
        across copies: replay uses MULTISET semantics — pending copies
        of a key = queued count - completed count — so N submissions
        still yield N runs.  Quarantine applies to the content (a
        poison piece is poison for every copy).

        Broker HA (network/ha.py): ``lease`` records raise the epoch in
        force positionally; a later ``dispatched``/``completed`` whose
        ``wepoch`` is older is a deposed leader's late append, counted
        under ``fenced`` and — with ``fence_strict`` (the default,
        settings.ha_fence_strict) — kept OUT of the queue math.
        ``fence_strict=False`` still surfaces the count but lets stale
        completions stand (forensic escape hatch: trust a deposed
        leader's work anyway).  The highest epoch/leader seen and the
        lease history come back under ``ha``.
        """
        pieces, order = {}, []
        n_queued, n_completed = {}, {}
        quarantined_keys = set()
        crashes, qcrashes = {}, {}
        opt_results = []
        perf_regressions = []
        mitigations = []
        sdc = dict(suspects=[], votes=[], quarantines=[])
        synthetic = 0
        torn = 0
        cur_epoch, leader = None, ""   # HA epoch in force (positional)
        leases = []
        fenced = 0
        # errors="replace": disk-level byte corruption must surface as
        # skipped torn lines, not a UnicodeDecodeError that escapes the
        # resume path's OSError handling
        with open(path, encoding="utf-8", errors="replace") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    r = json.loads(line)
                except json.JSONDecodeError:
                    torn += 1
                    continue
                rec, key = r.get("rec"), r.get("key")
                # a record stamped with a writer epoch older than the
                # lease in force at this POINT of the file is a deposed
                # leader's late append (see module docstring)
                wep = r.get("wepoch")
                stale = (cur_epoch is not None and isinstance(wep, int)
                         and wep < cur_epoch)
                if rec == "lease":
                    ep = r.get("epoch")
                    if isinstance(ep, int) and \
                            (cur_epoch is None or ep >= cur_epoch):
                        cur_epoch = ep
                        leader = str(r.get("leader", ""))
                    leases.append({"leader": str(r.get("leader", "")),
                                   "epoch": ep,
                                   "ttl": r.get("ttl")})
                elif rec == "queued" and key:
                    if r.get("synthetic"):
                        # LOADSPIKE chaos filler: never owed to a
                        # resumed sweep — skipping the queued record
                        # makes the key unknown, so the copy's later
                        # dispatched/completed records fall through
                        # the unknown-key filter below too
                        synthetic += 1
                        continue
                    if key not in pieces:
                        order.append(key)
                    pieces[key] = (list(r.get("scentime", [])),
                                   list(r.get("scencmd", [])))
                    n_queued[key] = n_queued.get(key, 0) + 1
                elif rec == "mitigation":
                    # mitigation-engine decision (audit; surfaced even
                    # keyless — shed/repack actions target no piece)
                    m = {"key": key, "cause": r.get("cause", ""),
                         "signal": r.get("signal", ""),
                         "action": r.get("action", ""),
                         "target": r.get("target", ""),
                         "outcome": r.get("outcome", "")}
                    mitigations.append(m)
                    if m["action"] == "quarantine_worker":
                        # the SDC defense's actuation — cross-listed
                        # under ``sdc`` next to the suspicion/vote
                        # records that led to it
                        sdc["quarantines"].append(m)
                elif rec == "sdc_suspect":
                    # fingerprint mismatch (audit; surfaced BEFORE the
                    # unknown-key filter like mitigation — a suspect
                    # raised by a synthetic shadow audit still matters
                    # to the auditor even though its key is unowed)
                    sdc["suspects"].append(
                        {"key": key, "fps": r.get("fps", {}),
                         "via": r.get("via", "")})
                elif rec == "sdc_vote":
                    sdc["votes"].append(
                        {"key": key, "fps": r.get("fps", {}),
                         "deviant": r.get("deviant", "")})
                elif key not in pieces:
                    continue              # marker records / unknown key
                elif stale and rec in ("dispatched", "completed"):
                    # FENCED: a deposed leader's late append — surfaced
                    # for audit, kept out of the queue math (unless the
                    # fence_strict escape hatch says to trust it)
                    fenced += 1
                    if rec == "completed" and not fence_strict:
                        n_completed[key] = n_completed.get(key, 0) + 1
                        crashes.pop(key, None)
                elif rec in ("dispatched", "preempted", "hedged",
                             "dup_completed", "mesh_lost", "resharded",
                             "adopted"):
                    # owed copies = queued - completed.  A hedge is a
                    # duplicate of an already-dispatched copy, and a
                    # dup_completed is the hedge loser finishing after
                    # the winner — counting either as a dispatch or a
                    # completion would break exactly-once for repeat-
                    # trial sweeps (identical content queued N times).
                    # mesh_lost/resharded likewise narrate one copy's
                    # mesh-epoch transitions, never its queue state;
                    # adopted narrates a failover reconciliation (the
                    # copy stays owed until its own completed lands).
                    pass
                elif rec == "crashed":
                    crashes[key] = int(r.get("crashes",
                                             crashes.get(key, 0) + 1))
                elif rec == "completed":
                    n_completed[key] = n_completed.get(key, 0) + 1
                    crashes.pop(key, None)
                elif rec == "quarantined":
                    quarantined_keys.add(key)
                    qcrashes[key] = int(r.get("crashes", 0))
                    crashes.pop(key, None)
                elif rec == "opt_result":
                    # audit record of an OPT piece's optimization output
                    # — surfaced for inspection, ignored by queue math
                    opt_results.append({"key": key,
                                        "result": r.get("result")})
                elif rec == "perf_regression":
                    # serving SLO-watch audit record (ISSUE-12) — the
                    # piece's queue state is untouched (exactly-once
                    # stays queued-minus-completed); surfaced so a
                    # resumed sweep can see which pieces ran slow
                    perf_regressions.append(
                        {"key": key, "worker": r.get("worker", ""),
                         "rate": r.get("rate"),
                         "baseline": r.get("baseline")})

        def owed(k):
            if k in quarantined_keys:
                return 0
            return max(0, n_queued.get(k, 0) - n_completed.get(k, 0))

        return dict(
            pending=[pieces[k] for k in order for _ in range(owed(k))],
            completed=[pieces[k] for k in order
                       for _ in range(min(n_queued.get(k, 0),
                                          n_completed.get(k, 0)))],
            quarantined=[pieces[k] for k in order
                         if k in quarantined_keys],
            crashes={k: c for k, c in crashes.items() if owed(k) > 0},
            quarantined_crashes=qcrashes,
            opt_results=opt_results,
            perf_regressions=perf_regressions,
            mitigations=mitigations,
            sdc=sdc,
            synthetic_skipped=synthetic,
            torn_lines=torn,
            fenced=fenced,
            ha=dict(epoch=cur_epoch, leader=leader, leases=leases),
        )
