"""Self-healing serving: the mitigation policy engine (ROADMAP item 5).

PRs 11/12/14 built the sensing stack — fleet metric registry, serving
SLO watch, straggler detector, mesh-epoch guard, memory watermarks —
but every actuator was still a human typing a stack command.  This
module closes the loop: a policy engine on the server's health tick
maps structured sentinel signals to the actuators the fabric already
has.

Signals -> actions (docs/FAULT_TOLERANCE.md has the recovery matrix):

  ``perf_regression``   SLO watch flagged an in-flight piece running
                        far below the fleet median  -> escalate a
                        speculative hedge for THAT piece
  ``straggler``         flat progress past straggler_timeout with
                        hedging disabled               -> hedge anyway
  ``mesh_degraded``     a worker re-formed a survivor mesh below its
                        full device count  -> accept the degraded
                        epoch (piece continues; no requeue churn)
  ``queue_pressure``    pending depth past mitigate_shed_hi x the
                        admission limit  -> shed load (tighten
                        batch_queue_max so floods get drain-rate-
                        informed BATCHREJECTED hints); restore only
                        below mitigate_shed_lo (hysteresis)
  ``mem_watermark``     fleet live-bytes watermark past mitigate_mem_hi
                        x the budget  -> re-pack (shrink
                        world_batch_max for the next packs); restore
                        below mitigate_mem_lo
  ``sdc_deviant``       the SDC 2-of-3 fingerprint vote (ISSUE-17,
                        server._finish_sdc_exec) out-voted a worker
                        whose silently-corrupting device produced the
                        minority state fingerprint  -> quarantine the
                        worker (drain it from assignment — every piece
                        it would run is suspect); MITIGATE OFF
                        releases quarantined workers back to the pool

Every DEGRADING action passes three gates before it fires:

  1. a global mitigation budget (``mitigate_budget`` actions per server
     lifetime — a runaway policy must exhaust itself, not the fleet),
  2. a per-action token bucket (``mitigate_rate`` tokens refilled over
     ``mitigate_rate_window`` seconds),
  3. exponential per-(action, target) backoff (``mitigate_backoff_base``
     doubling to ``mitigate_backoff_cap``) — repeated firings against
     the same target space out instead of hammering it.

Restores (``unshed``/``unrepack``) bypass the gates: undoing a
degradation must never be blocked by an exhausted budget.  Shed/unshed
and repack/unrepack additionally use split thresholds (hysteresis) so
the engine never flaps around one boundary.

Every decision — taken or restored — is journaled as an audit-only
``mitigation`` record ``{cause, signal, action, target, outcome}``
(replay surfaces the history, exactly-once queue math never sees it),
emitted on the flight recorder, and counted in the server registry.
Disabled (the default), the engine is completely inert: no journal
records, no HEALTH section, no counters — a server with
``mitigate_enabled=0`` is bit-identical to one without the engine.
"""
import collections
import time


#: action names that degrade service and therefore pass the full gate
DEGRADING = ("hedge_escalate", "shed", "repack", "accept_degraded",
             "quarantine_worker")
#: restore actions — journaled + counted, never gated
RESTORING = ("unshed", "unrepack", "release_worker")


class TokenBucket:
    """Per-action rate limit: ``capacity`` tokens refilled continuously
    over ``window`` seconds (refill rate = capacity / window)."""

    def __init__(self, capacity, window):
        self.capacity = max(1.0, float(capacity))
        self.window = max(1e-6, float(window))
        self.tokens = self.capacity
        self._t = None

    def take(self, now):
        if self._t is not None:
            self.tokens = min(
                self.capacity,
                self.tokens + (now - self._t) * self.capacity
                / self.window)
        self._t = now
        if self.tokens >= 1.0:
            self.tokens -= 1.0
            return True
        return False


class MitigationEngine:
    """Policy engine bound to one Server; driven by ``tick()`` on the
    server's heartbeat cadence plus direct signal hooks from the
    detectors (`_check_perf_slo`, `_check_stragglers`, MESHLOST)."""

    def __init__(self, server, enabled=None):
        from .. import settings as _s
        self.server = server
        self.enabled = bool(getattr(_s, "mitigate_enabled", False)) \
            if enabled is None else bool(enabled)
        self.budget_total = int(getattr(_s, "mitigate_budget", 64))
        self.rate = float(getattr(_s, "mitigate_rate", 4))
        self.rate_window = float(getattr(_s, "mitigate_rate_window",
                                         60.0))
        self.backoff_base = float(getattr(_s, "mitigate_backoff_base",
                                          5.0))
        self.backoff_cap = float(getattr(_s, "mitigate_backoff_cap",
                                         300.0))
        self.shed_hi = float(getattr(_s, "mitigate_shed_hi", 0.8))
        self.shed_lo = float(getattr(_s, "mitigate_shed_lo", 0.3))
        self.shed_factor = float(getattr(_s, "mitigate_shed_factor",
                                         0.5))
        self.mem_budget = int(getattr(_s, "mitigate_mem_budget", 0))
        self.mem_hi = float(getattr(_s, "mitigate_mem_hi", 0.9))
        self.mem_lo = float(getattr(_s, "mitigate_mem_lo", 0.6))
        self.repack_factor = float(getattr(_s, "mitigate_repack_factor",
                                           0.5))
        self.budget_used = 0
        self._buckets = {}          # action -> TokenBucket
        self._backoff = {}          # (action, target) -> (next_ok, delay)
        self.actions = collections.Counter()      # action -> fired
        self.suppressed = collections.Counter()   # gate -> suppressions
        self.recent = collections.deque(maxlen=16)
        # actuator baselines: what unshed/unrepack restore to.  Captured
        # when the action first fires, so operator WORLDS/queue changes
        # made BEFORE a shed are respected.
        self.shed_from = None       # batch_queue_max before shedding
        self.repack_from = None     # world_batch_max before re-packing
        self._seen_degraded = set()  # (wid, epoch) accept_degraded once

    # -------------------------------------------------------------- gating
    def _bucket(self, action):
        b = self._buckets.get(action)
        if b is None:
            b = self._buckets[action] = TokenBucket(self.rate,
                                                    self.rate_window)
        return b

    def _admit(self, action, target, now):
        """budget -> backoff -> token bucket; arms the exponential
        backoff on success.  Suppressions are counted per gate (the
        HEALTH section shows them) but never journaled — a suppressed
        decision changed nothing."""
        if self.budget_total and self.budget_used >= self.budget_total:
            self.suppressed["budget"] += 1
            return False
        key = (action, target)
        next_ok, delay = self._backoff.get(key, (0.0, 0.0))
        if now < next_ok:
            self.suppressed["backoff"] += 1
            return False
        if not self._bucket(action).take(now):
            self.suppressed["rate"] += 1
            return False
        delay = self.backoff_base if delay <= 0.0 \
            else min(delay * 2.0, self.backoff_cap)
        self._backoff[key] = (now + delay, delay)
        self.budget_used += 1
        return True

    # ----------------------------------------------------------- recording
    def _decide(self, cause, signal, action, target, outcome,
                piece=None, worker=b""):
        """Journal + trace + count one decision and tell the clients —
        the single funnel every action (and restore) goes through."""
        srv = self.server
        self.actions[action] += 1
        srv.obs.counter("server_mitigations",
                        help="mitigation-engine actions taken").inc()
        srv.obs.counter(f"server_mitigation_{action}",
                        help=f"mitigation '{action}' actions").inc()
        if srv.journal:
            srv.journal.mitigation(cause=cause, signal=signal,
                                   action=action, target=target,
                                   outcome=outcome, piece=piece,
                                   worker=worker)
        srv.recorder.instant("mitigation", cat="server", cause=cause,
                             signal=signal, action=action,
                             target=str(target), outcome=outcome)
        d = {"cause": cause, "signal": signal, "action": action,
             "target": str(target), "outcome": outcome}
        self.recent.append(d)
        msg = (f"MITIGATE: {signal} ({cause}) -> {action} on "
               f"{target or 'server'}: {outcome}")
        print(f"server: {msg}")
        srv._report_clients(msg)

    # -------------------------------------------------------- signal hooks
    def on_perf_regression(self, wid, piece, rate, median, now=None):
        """SLO watch flagged (wid, piece): escalate a hedge for the
        flagged piece — even with ``hedge_enabled`` off, mitigation IS
        the operator typing the hedge."""
        if not self.enabled:
            return
        srv = self.server
        now = time.monotonic() if now is None else now
        if wid in srv.hedge_by or wid in srv.hedge_of:
            return                  # one hedge per piece already placed
        if not srv.avail_workers:
            self.suppressed["no_idle_worker"] += 1
            return
        if not self._admit("hedge_escalate", wid.hex(), now):
            return
        srv._dispatch_hedge(wid, piece,
                            f"SLO regression (rate {rate:.2f} << "
                            f"median {median:.2f}) [mitigation]")
        self._decide(cause=f"rate {rate:.2f} < slo x median "
                           f"{median:.2f}",
                     signal="perf_regression", action="hedge_escalate",
                     target=wid.hex(),
                     outcome=f"hedged to {srv.hedge_by[wid].hex()}",
                     piece=piece, worker=wid)

    def on_straggler(self, wid, piece, why, now=None):
        """Flat-progress straggler with hedging DISABLED: the detector
        (``_check_stragglers``) found a stall it would normally hedge;
        mitigation places the hedge through its gates instead."""
        if not self.enabled:
            return
        srv = self.server
        now = time.monotonic() if now is None else now
        if not srv.avail_workers:
            self.suppressed["no_idle_worker"] += 1
            return
        if not self._admit("hedge_escalate", wid.hex(), now):
            return
        srv._dispatch_hedge(wid, piece, f"{why} [mitigation]")
        self._decide(cause=str(why), signal="straggler",
                     action="hedge_escalate", target=wid.hex(),
                     outcome=f"hedged to {srv.hedge_by[wid].hex()}",
                     piece=piece, worker=wid)

    def on_mesh_degraded(self, wid, piece, epoch, ndev, now=None):
        """A worker re-formed a DEGRADED survivor mesh and kept its
        piece.  The actuation — accept the epoch instead of requeueing
        — is the server's standing behavior; the engine's decision
        record makes the acceptance auditable and rate-limits the
        narration to once per (worker, epoch)."""
        if not self.enabled:
            return
        key = (wid, int(epoch or 0))
        if key in self._seen_degraded:
            return
        now = time.monotonic() if now is None else now
        # backoff target is epoch-qualified: each NEW epoch is a
        # distinct decision worth journaling (same-epoch repeats are
        # already deduped above); the token bucket still caps the
        # fleet-wide acceptance rate in a cascading failure
        if not self._admit("accept_degraded", f"{wid.hex()}#{epoch}",
                           now):
            return
        self._seen_degraded.add(key)
        self._decide(cause=f"mesh epoch {epoch} degraded to "
                           f"{ndev} device(s)",
                     signal="mesh_degraded", action="accept_degraded",
                     target=wid.hex(),
                     outcome="piece continues on survivor mesh",
                     piece=piece if not _is_pack(piece) else None,
                     worker=wid)

    def on_sdc_deviant(self, wid, piece, why="", now=None):
        """The SDC 2-of-3 fingerprint vote named ``wid`` the deviant:
        its device silently corrupts state, so every piece it would
        run is suspect — quarantine it (drain from assignment).  The
        ``sdc_vote`` audit record already names it; THIS record is the
        gated actuation (the closed loop's recovery step)."""
        if not self.enabled:
            return
        srv = self.server
        if wid in srv.sdc_quarantine:
            return                  # already quarantined
        now = time.monotonic() if now is None else now
        if not self._admit("quarantine_worker", wid.hex(), now):
            return
        srv.sdc_quarantine.add(wid)
        if wid in srv.avail_workers:
            srv.avail_workers.remove(wid)
        srv.sdc_quarantined_workers += 1
        self._decide(cause=str(why) or "fingerprint vote",
                     signal="sdc_deviant", action="quarantine_worker",
                     target=wid.hex(),
                     outcome="worker drained from assignment",
                     piece=piece if not _is_pack(piece) else None,
                     worker=wid)

    # ------------------------------------------------------------ the tick
    def tick(self, now=None):
        """Level-triggered checks on the server's heartbeat cadence:
        queue pressure (shed/unshed) and the fleet memory watermark
        (repack/unrepack)."""
        if not self.enabled:
            return
        now = time.monotonic() if now is None else now
        self._tick_queue(now)
        self._tick_mem(now)
        # bound the backoff map: entries idle past their cap expired
        for key, (next_ok, _d) in list(self._backoff.items()):
            if now > next_ok + self.backoff_cap:
                del self._backoff[key]

    def _tick_queue(self, now):
        srv = self.server
        limit = self.shed_from if self.shed_from is not None \
            else srv.batch_queue_max
        if not limit or limit <= 0:
            return                  # unbounded admission: nothing to shed
        depth = len(srv.scenarios)
        if self.shed_from is None:
            if depth >= self.shed_hi * limit \
                    and self._admit("shed", "admission", now):
                tightened = max(1, int(limit * self.shed_factor))
                self.shed_from = srv.batch_queue_max
                srv.batch_queue_max = tightened
                self._decide(
                    cause=f"queue depth {depth} >= "
                          f"{self.shed_hi:g} x limit {limit}",
                    signal="queue_pressure", action="shed",
                    target="admission",
                    outcome=f"batch_queue_max {self.shed_from} -> "
                            f"{tightened}")
        elif depth <= self.shed_lo * limit:
            restored, self.shed_from = self.shed_from, None
            tightened = srv.batch_queue_max
            srv.batch_queue_max = restored
            self._decide(
                cause=f"queue depth {depth} <= "
                      f"{self.shed_lo:g} x limit {limit}",
                signal="queue_pressure", action="unshed",
                target="admission",
                outcome=f"batch_queue_max {tightened} -> {restored}")

    def _tick_mem(self, now):
        srv = self.server
        if self.mem_budget <= 0:
            return
        g = srv.fleet.get("devprof_live_bytes_total")
        live = int(g.value) if g is not None else 0
        if self.repack_from is None:
            if live >= self.mem_hi * self.mem_budget \
                    and srv.world_batch_max > 1 \
                    and self._admit("repack", "worlds", now):
                shrunk = max(1, int(srv.world_batch_max
                                    * self.repack_factor))
                self.repack_from = srv.world_batch_max
                srv.world_batch_max = shrunk
                self._decide(
                    cause=f"fleet live bytes {live} >= "
                          f"{self.mem_hi:g} x budget {self.mem_budget}",
                    signal="mem_watermark", action="repack",
                    target="worlds",
                    outcome=f"world_batch_max {self.repack_from} -> "
                            f"{shrunk}")
        elif live <= self.mem_lo * self.mem_budget:
            restored, self.repack_from = self.repack_from, None
            shrunk = srv.world_batch_max
            srv.world_batch_max = restored
            self._decide(
                cause=f"fleet live bytes {live} <= "
                      f"{self.mem_lo:g} x budget {self.mem_budget}",
                signal="mem_watermark", action="unrepack",
                target="worlds",
                outcome=f"world_batch_max {shrunk} -> {restored}")

    # ------------------------------------------------------------- control
    def set_enabled(self, on):
        """MITIGATE ON/OFF.  Disabling first restores every actuator
        the engine has touched (journaled while still enabled) — an
        operator turning mitigation off must get the configured
        service levels back, not a silently-degraded server."""
        on = bool(on)
        if self.enabled and not on:
            if self.shed_from is not None:
                restored, self.shed_from = self.shed_from, None
                tightened = self.server.batch_queue_max
                self.server.batch_queue_max = restored
                self._decide(cause="MITIGATE OFF",
                             signal="operator", action="unshed",
                             target="admission",
                             outcome=f"batch_queue_max {tightened} -> "
                                     f"{restored}")
            if self.repack_from is not None:
                restored, self.repack_from = self.repack_from, None
                shrunk = self.server.world_batch_max
                self.server.world_batch_max = restored
                self._decide(cause="MITIGATE OFF",
                             signal="operator", action="unrepack",
                             target="worlds",
                             outcome=f"world_batch_max {shrunk} -> "
                                     f"{restored}")
            srv = self.server
            while srv.sdc_quarantine:
                # quarantine is this engine's actuation, so disabling
                # it releases the workers — the operator overriding the
                # vote gets the full pool back, journaled per worker
                wid = srv.sdc_quarantine.pop()
                self._decide(cause="MITIGATE OFF", signal="operator",
                             action="release_worker", target=wid.hex(),
                             outcome="worker returned to assignment",
                             worker=wid)
                if wid in srv.workers \
                        and wid not in srv.avail_workers \
                        and wid not in srv.inflight \
                        and srv.workers.get(wid, 0) < 2:
                    srv.avail_workers.append(wid)
                    srv._send_pending_scenario()
        self.enabled = on

    # ------------------------------------------------------------ readback
    def payload(self):
        """Machine-readable engine state (the ``MITIGATE`` command and
        the HEALTH ``mitigation`` section), with a human ``text``
        rendering — the HEALTH-style readback contract."""
        remaining = None if not self.budget_total \
            else max(0, self.budget_total - self.budget_used)
        d = {"enabled": bool(self.enabled),
             "budget": {"total": self.budget_total,
                        "used": self.budget_used,
                        "remaining": remaining},
             "actions": dict(self.actions),
             "suppressed": dict(self.suppressed),
             "shed_active": self.shed_from is not None,
             "repack_active": self.repack_from is not None,
             "queue_limit": self.server.batch_queue_max,
             "world_batch_max": self.server.world_batch_max,
             "quarantined_workers": sorted(
                 w.hex() for w in self.server.sdc_quarantine),
             "recent": list(self.recent)}
        taken = sum(self.actions.values())
        supp = sum(self.suppressed.values())
        supp_txt = ", ".join(f"{k}:{v}" for k, v in
                             sorted(self.suppressed.items())) or "-"
        act_txt = ", ".join(f"{k}:{v}" for k, v in
                            sorted(self.actions.items())) or "-"
        d["text"] = (
            f"MITIGATE {'ON' if self.enabled else 'OFF'}: {taken} "
            f"action(s) [{act_txt}], {supp} suppressed [{supp_txt}], "
            "budget "
            + (f"{remaining}/{self.budget_total} left"
               if self.budget_total else "unbounded")
            + (", SHEDDING (queue limit "
               f"{self.server.batch_queue_max})"
               if d["shed_active"] else "")
            + (", REPACKED (world max "
               f"{self.server.world_batch_max})"
               if d["repack_active"] else "")
            + (f", {len(d['quarantined_workers'])} worker(s) "
               "QUARANTINED"
               if d["quarantined_workers"] else ""))
        return d


def _is_pack(piece):
    from .server import WorldPack
    return isinstance(piece, WorldPack)
