"""Shared network helpers (parity: bluesky/network/common.py:4-15).

Endpoint ids are 5 random bytes with a leading zero byte so they can never
collide with single-character control tokens like ``b'*'``.
"""
import os
import socket

# Reference defaults (network/server.py:20-23): client event/stream ports,
# worker event/stream ports, UDP discovery port.
DEFAULT_PORTS = dict(event=9000, stream=9001,
                     wevent=10000, wstream=10001, discovery=11000)


def make_id() -> bytes:
    """A 5-byte endpoint id: zero byte + 4 random bytes (node.py:15)."""
    return b"\x00" + os.urandom(4)


def get_ownip() -> str:
    """Best-effort non-loopback IPv4 of this host."""
    try:
        s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        try:
            s.connect(("10.255.255.255", 1))
            return s.getsockname()[0]
        finally:
            s.close()
    except OSError:
        return "127.0.0.1"
