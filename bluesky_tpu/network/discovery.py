"""LAN server discovery via UDP broadcast (parity: network/discovery.py:14-73).

A client broadcasts a request datagram on the discovery port; every server
replies with its event/stream ports.  Datagrams are msgpack maps with a
magic tag so stray packets on the port are ignored.
"""
import socket
from dataclasses import dataclass

from .common import DEFAULT_PORTS, get_ownip
from .npcodec import packb, unpackb

_MAGIC = "bstpu-disc-1"


@dataclass
class Reply:
    ip: str
    event_port: int
    stream_port: int
    # broker HA (network/ha.py): servers advertise their lease epoch
    # and role so clients/workers can arbitrate between a deposed
    # leader's stale reply and the real one (highest epoch wins) and
    # skip warm standbys that are not serving yet.  Non-HA servers
    # advertise the defaults, so pre-HA wire peers keep working.
    epoch: int = 0
    role: str = "leader"
    # worker-side ports (HA replies only; 0 = not advertised): a
    # failed-over WORKER must re-REGISTER on the new leader's worker
    # ROUTER, not the client one — event/stream above are client-facing
    wevent: int = 0
    wstream: int = 0


class Discovery:
    def __init__(self, own_id: bytes, is_client: bool = True,
                 port: int = DEFAULT_PORTS["discovery"]):
        self.own_id = own_id
        self.is_client = is_client
        self.port = port
        self.sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self.sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self.sock.setsockopt(socket.SOL_SOCKET, socket.SO_BROADCAST, 1)
        self.sock.bind(("", port))
        self.sock.settimeout(0.2)

    @property
    def handle(self):
        return self.sock

    def close(self):
        self.sock.close()

    def send_request(self):
        msg = packb({"magic": _MAGIC, "kind": "req", "id": self.own_id})
        self.sock.sendto(msg, ("<broadcast>", self.port))

    def send_reply(self, event_port: int, stream_port: int,
                   epoch: int = None, role: str = None,
                   wevent: int = None, wstream: int = None):
        msg = {"magic": _MAGIC, "kind": "rep", "id": self.own_id,
               "ip": get_ownip(), "event": event_port,
               "stream": stream_port}
        if epoch is not None:      # broker HA: advertise lease epoch
            msg["epoch"] = int(epoch)
        if role is not None:       # ... and role (leader/standby)
            msg["role"] = str(role)
        if wevent is not None:     # ... and the worker-facing ports
            msg["wevent"] = int(wevent)
        if wstream is not None:
            msg["wstream"] = int(wstream)
        self.sock.sendto(packb(msg), ("<broadcast>", self.port))

    def recv_reqreply(self):
        """Receive one datagram; returns ('req', None) | ('rep', Reply) |
        (None, None) on timeout/foreign traffic/own echo."""
        try:
            raw, addr = self.sock.recvfrom(4096)
        except socket.timeout:
            return None, None
        try:
            msg = unpackb(raw)
        except Exception:
            return None, None
        if not isinstance(msg, dict) or msg.get("magic") != _MAGIC:
            return None, None
        if msg.get("id") == self.own_id:
            return None, None
        if msg.get("kind") == "req":
            return "req", None
        if msg.get("kind") == "rep":
            return "rep", Reply(msg.get("ip", addr[0]), msg["event"],
                                msg["stream"],
                                int(msg.get("epoch", 0) or 0),
                                str(msg.get("role", "leader")),
                                int(msg.get("wevent", 0) or 0),
                                int(msg.get("wstream", 0) or 0))
        return None, None
