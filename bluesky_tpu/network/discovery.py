"""LAN server discovery via UDP broadcast (parity: network/discovery.py:14-73).

A client broadcasts a request datagram on the discovery port; every server
replies with its event/stream ports.  Datagrams are msgpack maps with a
magic tag so stray packets on the port are ignored.
"""
import socket
from dataclasses import dataclass

from .common import DEFAULT_PORTS, get_ownip
from .npcodec import packb, unpackb

_MAGIC = "bstpu-disc-1"


@dataclass
class Reply:
    ip: str
    event_port: int
    stream_port: int


class Discovery:
    def __init__(self, own_id: bytes, is_client: bool = True,
                 port: int = DEFAULT_PORTS["discovery"]):
        self.own_id = own_id
        self.is_client = is_client
        self.port = port
        self.sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self.sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self.sock.setsockopt(socket.SOL_SOCKET, socket.SO_BROADCAST, 1)
        self.sock.bind(("", port))
        self.sock.settimeout(0.2)

    @property
    def handle(self):
        return self.sock

    def close(self):
        self.sock.close()

    def send_request(self):
        msg = packb({"magic": _MAGIC, "kind": "req", "id": self.own_id})
        self.sock.sendto(msg, ("<broadcast>", self.port))

    def send_reply(self, event_port: int, stream_port: int):
        msg = packb({"magic": _MAGIC, "kind": "rep", "id": self.own_id,
                     "ip": get_ownip(), "event": event_port,
                     "stream": stream_port})
        self.sock.sendto(msg, ("<broadcast>", self.port))

    def recv_reqreply(self):
        """Receive one datagram; returns ('req', None) | ('rep', Reply) |
        (None, None) on timeout/foreign traffic/own echo."""
        try:
            raw, addr = self.sock.recvfrom(4096)
        except socket.timeout:
            return None, None
        try:
            msg = unpackb(raw)
        except Exception:
            return None, None
        if not isinstance(msg, dict) or msg.get("magic") != _MAGIC:
            return None, None
        if msg.get("id") == self.own_id:
            return None, None
        if msg.get("kind") == "req":
            return "req", None
        if msg.get("kind") == "rep":
            return "rep", Reply(msg.get("ip", addr[0]), msg["event"],
                                msg["stream"])
        return None, None
