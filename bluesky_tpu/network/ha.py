"""Broker high availability: journal-fenced leadership + warm standby.

The BATCH journal (network/journal.py) is the single source of truth
for a sweep; this module adds the small amount of coordination state
needed for a *warm-standby* server to take over the sweep when the
leader dies, with no operator commands and no double-counted work
(docs/FAULT_TOLERANCE.md §broker HA):

- **lease file** — ``<journal>.lease``, an atomically-replaced JSON
  blob ``{leader, epoch, ttl, stamp}`` the leader rewrites every
  ``ha_poll_dt``.  The standby polls it cheaply; a stamp older than
  ``ttl`` (wall clock — the two servers are different processes, so
  monotonic clocks don't compare) means the leader has been silent
  for a full lease and the standby may take over.
- **lease journal record** — the durable half of the same fact: every
  leadership acquisition appends ``{"rec": "lease", leader, epoch,
  ttl}`` to the shared journal, so replay knows the epoch in force at
  every point of the file.  All records a leader writes after its
  lease carry ``wepoch`` (writer epoch, distinct from the mesh
  ``epoch`` field of mesh_lost/resharded records); replay fences a
  deposed leader's late ``dispatched``/``completed`` appends off as
  audit-only (``fenced``), which is what makes a non-atomic UNIX-file
  handover safe.
- **JournalTail** — the standby's warm view: an incremental reader
  that follows the growing journal between polls so takeover replay
  is a re-fold of an already-hot file, and HA STATUS can report how
  far behind the standby is.

The leader/standby *processes* are plain Servers (network/server.py
``ha_role=``); this module stays free of ZMQ so the lease protocol is
unit-testable in isolation.
"""
import json
import os
import time


def lease_path(journal_path):
    """The lease file that guards ``journal_path``."""
    return str(journal_path) + ".lease"


def write_lease(path, leader, epoch, ttl, stamp=None):
    """Atomically (tmp + rename) publish a lease: ``leader`` (hex id)
    holds ``epoch`` and promises a heartbeat within ``ttl`` seconds of
    ``stamp``.  Best-effort: a full disk degrades to the journal
    record being authoritative (takeover then keys off file age)."""
    blob = {"leader": str(leader), "epoch": int(epoch),
            "ttl": float(ttl),
            "stamp": float(time.time() if stamp is None else stamp)}
    tmp = path + ".tmp"
    try:
        with open(tmp, "w") as f:
            json.dump(blob, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    except OSError:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        return False
    return True


def read_lease(path):
    """The current lease blob, or None (absent/torn/unreadable —
    a torn read is impossible via os.replace, but a truncated disk
    copy still parses to None instead of raising)."""
    if not path:
        return None
    try:
        with open(path) as f:
            blob = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(blob, dict) or "epoch" not in blob:
        return None
    return blob


def lease_age(lease, now=None):
    """Seconds since the lease was last renewed (wall clock)."""
    now = time.time() if now is None else now
    return now - float(lease.get("stamp", 0.0))


def is_stale(lease, now=None, default_ttl=10.0):
    """Has the leader been silent past its own promised ttl?"""
    if lease is None:
        return True
    ttl = float(lease.get("ttl") or default_ttl)
    return lease_age(lease, now) > ttl


class JournalTail:
    """Incremental reader over the growing shared journal.

    ``poll()`` consumes newly-appended complete lines (a torn final
    line stays unconsumed until its newline lands, mirroring the
    replay torn-tail rule) and keeps running counters: total records
    seen, the highest lease epoch and its leader, lease-record count.
    This is the standby's warm state — cheap enough to run every
    ``ha_poll_dt`` — while the authoritative fold at takeover is a
    full ``BatchJournal.replay`` of the same file."""

    def __init__(self, path):
        self.path = str(path)
        self.pos = 0
        self.records = 0
        self.leases = 0
        self.epoch = 0
        self.leader = ""

    def poll(self):
        """Consume complete appended lines; return records consumed."""
        new = 0
        try:
            with open(self.path, "rb") as f:
                f.seek(self.pos)
                chunk = f.read()
        except OSError:
            return 0
        if not chunk:
            return 0
        # only whole lines: hold back a torn tail for the next poll
        cut = chunk.rfind(b"\n")
        if cut < 0:
            return 0
        self.pos += cut + 1
        for line in chunk[:cut + 1].splitlines():
            line = line.strip()
            if not line:
                continue
            try:
                r = json.loads(line)
            except ValueError:
                continue
            if not isinstance(r, dict):
                continue
            new += 1
            if r.get("rec") == "lease":
                self.leases += 1
                ep = r.get("epoch")
                if isinstance(ep, int) and ep >= self.epoch:
                    self.epoch = ep
                    self.leader = str(r.get("leader", ""))
        self.records += new
        return new


def reconcile(pending, reported):
    """Match journal-owed pieces against surviving workers' in-flight
    reports (pure function; the server applies the result).

    ``pending``: replayed owed pieces (the multiset of copies the old
    leader had queued-or-running), in journal order.  ``reported``:
    ``[(worker_hex, content_key), ...]`` from idempotent re-REGISTERs.
    Each report *adopts* one owed copy with a matching content key —
    the piece keeps running where it is, no requeue, no breaker
    strike.  Reports with no owed copy left are returned as ``extra``
    (a completion raced the failover, or a surviving hedge twin of an
    already-counted copy — the server cancels/dedupes those by key).
    Returns ``(adopted, requeue, extra)`` with ``adopted`` as
    ``[(worker_hex, piece)]`` and ``requeue`` the leftover pending
    copies in their original order."""
    from .journal import BatchJournal
    left = list(pending)
    keys = [BatchJournal.piece_key(p) for p in left]
    adopted, extra = [], []
    for worker, key in reported:
        try:
            i = keys.index(key)
        except ValueError:
            extra.append((worker, key))
            continue
        keys.pop(i)
        adopted.append((worker, left.pop(i)))
    return adopted, left, extra
