"""Config/flag system (parity: bluesky/settings.py:8-133, modernized).

Two-level scheme like the reference: a config file plus per-module
registered defaults (``set_variable_defaults``).  Divergence from the
reference (SURVEY.md §5.6 build note): the config file is a restricted
``key = value`` Python file evaluated with ``ast.literal_eval`` per line —
config is data, not arbitrary code — and unknown keys are kept so modules
registering defaults later still pick them up.

Data paths default to the read-only reference data mount when present so
navdata/performance coefficients load out of the box; everything degrades
gracefully when they are absent.
"""
import ast
import os
import sys

# BLUESKY_TPU_NO_REF=1 pretends the read-only reference mount is absent
# (standalone mode): navdata starts empty, performance falls back to the
# BUILTIN coefficients, and the scenario library is the local dir only.
# BLUESKY_TPU_DATA=/path points at a BlueSky data checkout (deployment
# hook used by the Dockerfile; takes precedence over the dev mount).
_NO_REF = os.environ.get("BLUESKY_TPU_NO_REF") == "1"
_REF_DATA = os.environ.get("BLUESKY_TPU_DATA") \
    or ("" if _NO_REF else "/root/reference/data")

# ----------------------------------------------------------------- defaults
simdt = 0.05
chunk_steps = 20                  # interactive device-chunk length in
                                  # steps (1 s sim time at simdt=0.05);
                                  # CHUNKSTEPS stack command at runtime.
                                  # FF/BATCH runs still use >=1000-step
                                  # chunks.  Off-ladder values compile
                                  # one extra scan program.
chunk_pipeline = True             # async chunk pipeline: dispatch chunk
                                  # k+1 before chunk k's edge work, edge
                                  # subsystems read the fused telemetry
                                  # pack, guard readback is deferred one
                                  # chunk (docs/PERF_ANALYSIS.md)
performance_model = "openap"
prefer_compiled = True            # use the C host extension when built
data_path = _REF_DATA if os.path.isdir(_REF_DATA) else "data"
cache_path = os.path.join(os.path.expanduser("~"), ".cache", "bluesky_tpu")
navdata_path = os.path.join(data_path, "navdata")
# `bluesky-tpu --import-navdata <dir>` copies a reference-format navdata
# tree here; it backs standalone deployments when no mount is configured
imported_navdata_path = os.path.join(cache_path, "navdata")
if not os.path.isdir(navdata_path) and os.path.isdir(imported_navdata_path):
    navdata_path = imported_navdata_path
perf_path = os.path.join(data_path, "performance")
log_path = "output"
scenario_path = "scenario"
# the reference's ~90-file scenario library, searched after the local
# dir (like the navdata/performance mounts above)
_REF_SCN = "" if _NO_REF else "/root/reference/scenario"
ref_scenario_path = _REF_SCN if os.path.isdir(_REF_SCN) else ""
plugin_path = "plugins"
enabled_plugins = ["datafeed"]
event_port = 9000
stream_port = 9001
wevent_port = 10000
wstream_port = 10001
discovery_port = 11000
max_nnodes = os.cpu_count() or 1
sim_detached = False
telnet_port = 8888

# ----- fault tolerance (docs/FAULT_TOLERANCE.md has the tuning guide)
guard_enabled = True              # in-scan isfinite integrity guard
guard_policy = "quarantine"       # "quarantine" | "rollback" | "halt"
snap_ring_depth = 4               # rollback horizon = depth * dt sim-sec
snap_ring_dt = 30.0               # [sim s] between ring captures (0 = off)
batch_max_crashes = 3             # consecutive worker losses before a
                                  # BATCH piece is circuit-broken
connect_backoff_base = 0.25       # [s] first client connect retry delay
connect_backoff_cap = 4.0         # [s] backoff ceiling (jitter on top)
node_watchdog_warn = 30.0         # [s] event-loop silence before warning
node_watchdog_kill = 0.0          # [s] silence before exit(70); 0 = never
fault_seed = 0                    # RNG seed for the FAULT injectors

# ----- overload / straggler serving layer (docs/FAULT_TOLERANCE.md
# rows #10/#11): progress heartbeats, speculative re-dispatch,
# admission control and bounded stream buffering
hb_busy_multiplier = 10.0         # [x hb_timeout] PING-silence budget for
                                  # a worker mid-BATCH / in OP (long device
                                  # chunks + first-compile legitimately
                                  # block the event loop for minutes)
straggler_timeout = 30.0          # [s] fresh heartbeats but no sim-time/
                                  # chunk advance on an in-flight piece
                                  # before it is hedged (0 = never)
hedge_enabled = True              # speculative straggler re-dispatch
hedge_rate_factor = 0.2           # also hedge when a worker's progress
                                  # rate < factor * fleet median
batch_queue_max = 4096            # pending BATCH pieces before a
                                  # submission gets BATCHREJECTED
                                  # (0 = unbounded, pre-PR3 behavior)
batch_retry_after = 5.0           # [s] BATCHREJECTED retry hint when no
                                  # drain-rate estimate exists yet
stream_sndhwm = 1000              # [msgs] send buffer bound on the stream
                                  # sockets; a stalled GUI client gets
                                  # drops (counted), never back-pressure
quarantine_report_cap = 64        # BATCHQUARANTINE replay history kept
                                  # for late-joining clients

# ----- multi-world serving (docs/PERF_ANALYSIS.md §multi-world)
world_pack = False                # pack compatible BATCH pieces into
                                  # world-batches: one worker steps W
                                  # scenarios per device dispatch
                                  # (vmapped world axis, core/step.py).
                                  # WORLDS stack command at runtime.
world_batch_max = 8               # max pieces per world-batch dispatch
                                  # (the per-bucket packing width; 1 =
                                  # packing effectively off).  Every
                                  # (nmax-bucket, chunk-length) pair
                                  # compiles one stacked scan program
                                  # per distinct W it sees.

# ----- multi-chip decomposition (docs/PERF_ANALYSIS.md §multi-chip)
shard_mode = "off"                # "off" | "replicate" (row-interleaved
                                  # kernels vs replicated O(N) columns) |
                                  # "spatial" (device-owned latitude
                                  # stripes + halo exchange; sparse
                                  # backend only) | "tiles" (2-D lat x
                                  # lon tiles + corner-halo exchange;
                                  # sparse backend only).  SHARD stack
                                  # command switches at runtime.
shard_devices = 0                 # mesh size (0 = every visible device)
shard_halo_blocks = 0             # spatial halo width in 256-slot blocks
                                  # per side (0 = one full neighbour
                                  # device; validated against the exact
                                  # reach bound + drift margin at every
                                  # refresh)
shard_tile_shape = ""             # tiles mode: "RxC" lat x lon grid
                                  # ("" = near-square factorization of
                                  # the device count, e.g. 8 -> "4x2");
                                  # per-offset halo slab budgets are
                                  # auto-pinned by the tile refresh

# ----- mesh-epoch recovery (docs/FAULT_TOLERANCE.md §mesh epochs):
# losing a device group ends the mesh epoch, not the run — survivors
# re-form a smaller mesh and resume from the last checksummed snapshot
mesh_guard_enabled = True         # MeshGuard dead-peer check at every
                                  # chunk dispatch of a sharded sim
mesh_dispatch_timeout = 0.0       # [wall s] collective-wait budget per
                                  # chunk edge; exceeding it with stale
                                  # peer heartbeats trips mesh_lost
                                  # (0 = block forever, single-host)
mesh_heartbeat_dir = ""           # shared dir for cross-process mesh
                                  # heartbeat stamps ("" = off; set for
                                  # multi-host meshes, e.g. an NFS path)
mesh_heartbeat_timeout = 10.0     # [wall s] peer stamp staleness before
                                  # the peer counts as dead

# ----- differentiable simulation (bluesky_tpu/diff/; OPT/GRAD stack
# commands; docs/PERF_ANALYSIS.md §differentiable).  The OPT driver
# descends on per-aircraft waypoint/time offsets with jax.value_and_grad
# over the smooth step scan; these are its defaults (stack-command
# arguments override per run).
opt_tend = 600.0                  # [sim s] optimization rollout horizon
opt_simdt = 1.0                   # [s] smooth-rollout step (coarser than
                                  # the serving 0.05 s; the hard-metric
                                  # verification runs at opt_verify_dt)
opt_chunk = 50                    # steps per jax.checkpoint chunk —
                                  # backward memory stays O(chunk)
opt_iters = 40                    # Adam iterations
opt_lr = 0.15                     # Adam LR (normalized offset units)
opt_temp0 = 0.3                   # soft-LoS temperature: anneal start
opt_temp1 = 0.05                  # ... and end (fractions of rpz/hpz)
opt_restarts = 1                  # multi-start particles batched on the
                                  # PR-6 world axis (best particle wins)
opt_los_margin = 1.2              # soft-zone inflation over the hard
                                  # rpz: buffer against the measured
                                  # <1 km smooth-vs-hard model mismatch
opt_verify_dt = 0.05              # [s] hard-metric verification step

# ----- durable runs (preemption-safe checkpoints + BATCH journal)
snapshot_autosave_dt = 0.0        # [sim s] between on-disk autosnapshots
                                  # of the newest ring entry (0 = off)
snapshot_autosave_path = ""       # "" -> <log_path>/autosave.snap
preempt_snapshot_dir = ""         # "" -> log_path; SIGTERM / FAULT
                                  # PREEMPT final checkpoints land here
batch_journal_fsync = True        # fsync each BATCH journal record (WAL
                                  # durability vs append latency)

# ----- broker HA (network/ha.py; docs/FAULT_TOLERANCE.md §broker HA).
# A warm-standby server tails the live journal and takes over when the
# leader dies: leadership is a lease (journal record + atomic lease
# file) with a monotonically-bumped epoch; every record an HA leader
# appends carries its writer epoch so replay fences a deposed leader's
# late appends off as audit-only.
ha_standby = False                # start this server as a warm standby
                                  # (tail the journal, serve nothing
                                  # until the lease is acquired)
ha_lease_ttl = 10.0               # [wall s] leader silence before the
                                  # standby may acquire the lease
ha_poll_dt = 1.0                  # [wall s] lease renewal (leader) /
                                  # lease+journal polling (standby)
ha_fence_strict = True            # replay drops a deposed leader's
                                  # stale-epoch completions from the
                                  # queue math (False surfaces them as
                                  # fenced but trusts them anyway)

# ----- observability (docs/OBSERVABILITY.md; bluesky_tpu/obs/)
trace_enabled = False             # flight recorder on at startup (the
                                  # TRACE stack command toggles at
                                  # runtime; PROFILE TRACE is a synonym)
trace_ring_size = 4096            # bounded event ring per process —
                                  # older spans fall off, dumps stay
                                  # incident-sized
trace_dir = ""                    # TRACE DUMP / auto-dump target dir
                                  # ("" -> log_path)
trace_autodump = True             # dump the ring on guard/mesh trips
                                  # (throttled to 1/s) so the spans
                                  # leading up to an incident survive it
metrics_export_path = ""          # Prometheus text-format dump file
                                  # ("" = off); rewritten atomically at
                                  # most every metrics_export_dt wall-s.
                                  # Set per process (sim and server
                                  # processes each export their own).
metrics_export_dt = 10.0          # [wall s] min interval between
                                  # metrics-export rewrites
scanstats = False                 # in-scan telemetry: fold per-step
                                  # device-side stats (conflict/LoS
                                  # histograms, clamp saturation, min
                                  # separation, stripe occupancy)
                                  # through the chunk scan carry and
                                  # drain them at each chunk edge.
                                  # SCANSTATS stack command toggles at
                                  # runtime; off traces identical HLO.
inscan_refresh = False            # in-scan sort refresh: fold the
                                  # stripe re-sort (+ spatial re-bucket)
                                  # into the compiled chunk scan instead
                                  # of a host call at chunk edges, so
                                  # short interactive chunks stop paying
                                  # a host refresh per chunk.  Sparse
                                  # backend only; SORTREFRESH stack
                                  # command toggles at runtime; off
                                  # traces identical HLO.

# ----- device observability + perf sentinel (obs/devprof.py)
devprof_compile_telemetry = True  # per-compile trace/lower/backend
                                  # duration histograms + cache hit/miss
                                  # counters keyed to the CHUNKSTEPS
                                  # ladder (host-side bookkeeping only)
devprof_mem_dt = 0.0              # [wall s] min interval between
                                  # live-bytes/peak watermark samples at
                                  # chunk edges (0 = off; sampling walks
                                  # jax.live_arrays(), so keep throttled)
devprof_donation_check = False    # after a donating dispatch, count
                                  # input buffers XLA failed to reuse
                                  # (forces a host sync — debug only)
perf_slo_factor = 0.0             # serving SLO watch: journal a
                                  # perf_regression audit record when a
                                  # worker's FF rate drops below
                                  # factor * fleet median (0 = off;
                                  # sensible values sit BELOW the
                                  # hedge_rate_factor so hedging fires
                                  # first and the journal explains why)
# ----- self-healing serving (network/mitigate.py; MITIGATE stack
# command; docs/FAULT_TOLERANCE.md §mitigation).  The mitigation engine
# maps sentinel signals (SLO perf_regression, straggler stall, degraded
# mesh epochs, admission-queue pressure, memory watermarks) to the
# actuators the fabric already has.  Every action passes a per-action
# token-bucket rate limit, exponential per-target backoff and a global
# budget; decisions are journaled as audit-only ``mitigation`` records.
# With mitigate_enabled off the engine is inert: journal and HEALTH
# output are bit-identical to a build without it.
mitigate_enabled = False          # closed-loop mitigation on the server
mitigate_budget = 64              # lifetime cap on degrading actions a
                                  # server may take (0 = unbounded);
                                  # restores (unshed/unrepack) are free
mitigate_rate = 4                 # token-bucket capacity per action ...
mitigate_rate_window = 60.0       # ... refilled over this window [s]
mitigate_backoff_base = 5.0       # [s] first per-(action,target) delay
mitigate_backoff_cap = 300.0      # [s] exponential-backoff ceiling
mitigate_shed_hi = 0.8            # shed load (tighten batch_queue_max)
                                  # when queue depth rises past this
                                  # fraction of the admission limit ...
mitigate_shed_lo = 0.3            # ... and restore it only once depth
                                  # falls below this fraction
                                  # (hysteresis: no shed/unshed flap)
mitigate_shed_factor = 0.5        # shed tightens batch_queue_max to
                                  # factor x the configured limit
mitigate_mem_budget = 0           # [bytes] fleet live-bytes watermark
                                  # budget (devprof_live_bytes_total
                                  # from worker heartbeats; 0 = off)
mitigate_mem_hi = 0.9             # re-pack (shrink world_batch_max)
                                  # when fleet live bytes rise past
                                  # this fraction of the budget ...
mitigate_mem_lo = 0.6             # ... and restore below this fraction
mitigate_repack_factor = 0.5      # re-pack shrinks world_batch_max to
                                  # factor x the configured width
# ----- silent-data-corruption defense (ISSUE-17; network/server.py,
# obs/fingerprint.py; SDC + FINGERPRINT stack commands;
# docs/FAULT_TOLERANCE.md §SDC).  Workers fold a cheap int32
# bit-pattern fingerprint of the sim state through the compiled chunk
# scan and ship it on completion; the server compares redundant
# executions (hedge duplicates, sampled shadow audits), journals
# audit-only sdc_suspect/sdc_vote records, and — with the mitigation
# engine on — quarantines the 2-of-3 out-voted deviant worker.
fingerprint = False               # worker-side: fold the state
                                  # fingerprint through the chunk scan
                                  # carry (jit-static; off traces
                                  # identical HLO, on adds no host
                                  # syncs or collectives).  FINGERPRINT
                                  # stack command toggles at runtime.
sdc_enabled = False               # server-side: compare fingerprints
                                  # of redundant executions, journal
                                  # suspects, place 2-of-3 votes.  Off
                                  # keeps journal and HEALTH output
                                  # bit-identical to a build without
                                  # the defense (audit-only contract).
sdc_audit_rate = 0.0              # fraction of completed fast-forward
                                  # pieces shadow re-executed for a
                                  # fingerprint comparison (0 = off;
                                  # deterministic accumulator sampling,
                                  # 1.0 = audit every FF piece)
journal_warn_bytes = 67108864     # [bytes] HEALTH warns when the BATCH
                                  # journal (WAL) grows past this
                                  # (64 MiB; 0 = never warn)
bench_history_path = "BENCH_HISTORY.jsonl"
                                  # append-only bench-row history every
                                  # write_bench_json() call extends
                                  # ("" = off); scripts/bench_history.py
                                  # compares newest rows vs baseline

_overrides = {}                   # file/CLI values for late-registered keys


def init(cfgfile: str = "") -> bool:
    """Load ``key = value`` lines from cfgfile into this module."""
    if not cfgfile or not os.path.isfile(cfgfile):
        return False
    mod = sys.modules[__name__]
    with open(cfgfile) as f:
        for line in f:
            line = line.strip()
            if not line or line.startswith("#") or "=" not in line:
                continue
            key, _, raw = line.partition("=")
            key = key.strip()
            try:
                val = ast.literal_eval(raw.strip())
            except (ValueError, SyntaxError):
                val = raw.strip()
            setattr(mod, key, val)
            _overrides[key] = val
    return True


def set_variable_defaults(**kwargs):
    """Per-module defaults registered at import time (settings.py:121-133):
    only set if neither a default nor a config override exists yet."""
    mod = sys.modules[__name__]
    for key, value in kwargs.items():
        if key in _overrides:
            setattr(mod, key, _overrides[key])
        elif not hasattr(mod, key):
            setattr(mod, key, value)
