"""Build the compiled host geodesy core:

    cd bluesky_tpu/src_cpp && python setup.py build_ext --inplace

Produces ``_cgeo`` next to this file; ``ops/hostgeo.py`` picks it up
automatically and falls back to NumPy when it is absent.
"""
import numpy as np
from setuptools import Extension, setup

setup(
    name="bluesky_tpu_cgeo",
    ext_modules=[
        Extension(
            "_cgeo",
            sources=["cgeo.cpp"],
            include_dirs=[np.get_include()],
            extra_compile_args=["-O3", "-std=c++17"],
        )
    ],
)
