// Host-side geodesy core (native twin of ops/geo.py).
//
// Role parity with the reference's compiled geodesy extension
// (bluesky/tools/src_cpp/cgeo.cpp): the DEVICE hot path in this framework
// is XLA (ops/geo.py jitted), but host-side consumers — navdb nearest
// queries, scenario tooling, landing checks, plugins — run NumPy at
// Python speed.  This extension provides the same formulas compiled.
//
// Design (deliberately different from the reference extension): the
// Python wrapper (ops/hostgeo.py) normalizes every call to flat,
// contiguous, equal-length float64 arrays (it owns broadcasting and the
// scalar/matrix conventions), so the C side is a handful of tight loops
// over raw pointers with zero per-element Python API traffic and no
// shape logic.  Formulas follow ops/geo.py, which documents the
// reference-parity quirks (hemisphere-aware mean radius; the matrix
// variant's radius-at-latitude-sum).
#define NPY_NO_DEPRECATED_API NPY_1_7_API_VERSION
#include <Python.h>
#include <numpy/arrayobject.h>
#include <cmath>

namespace {

constexpr double A = 6378137.0;              // WGS-84 semi-major axis [m]
constexpr double B = 6356752.314245;         // WGS-84 semi-minor axis [m]
constexpr double REARTH = 6371000.0;         // kwik* mean radius [m]
constexpr double NM = 1852.0;
constexpr double D2R = 0.017453292519943295;
constexpr double R2D = 57.29577951308232;

inline double rwgs84_rad(double coslat, double sinlat) {
    const double an = A * A * coslat, bn = B * B * sinlat;
    const double ad = A * coslat, bd = B * sinlat;
    return std::sqrt((an * an + bn * bn) / (ad * ad + bd * bd));
}

inline double rwgs84_deg(double latd) {
    const double lat = D2R * latd;
    return rwgs84_rad(std::cos(lat), std::sin(lat));
}

// Hemisphere-aware mean radius; mode 0 = scalar qdrdist semantics
// (radius at the average latitude), mode 1 = the matrix-variant quirks
// (radius at the SUM of latitudes; 1e-6 deg epsilon when lat1 == 0).
inline double mean_radius(double lat1, double lat2, int mode) {
    if (mode == 0) {
        if (lat1 * lat2 >= 0.0) return rwgs84_deg(0.5 * (lat1 + lat2));
        double denom = std::fabs(lat1) + std::fabs(lat2);
        if (denom < 1e-30) denom = 1e-30;
        return 0.5 * (std::fabs(lat1) * (rwgs84_deg(lat1) + A)
                      + std::fabs(lat2) * (rwgs84_deg(lat2) + A)) / denom;
    }
    if (lat1 * lat2 < 0.0) {
        const double denom = std::fabs(lat1) + std::fabs(lat2)
                             + (lat1 == 0.0 ? 1e-6 : 0.0);
        return 0.5 * (std::fabs(lat1) * (rwgs84_deg(lat1) + A)
                      + std::fabs(lat2) * (rwgs84_deg(lat2) + A)) / denom;
    }
    return rwgs84_deg(lat1 + lat2);
}

inline void haversine(double latd1, double lond1, double latd2,
                      double lond2, double r, double* qdr, double* dist) {
    const double lat1 = D2R * latd1, lon1 = D2R * lond1;
    const double lat2 = D2R * latd2, lon2 = D2R * lond2;
    const double s1 = std::sin(0.5 * (lat2 - lat1));
    const double s2 = std::sin(0.5 * (lon2 - lon1));
    const double c1 = std::cos(lat1), c2 = std::cos(lat2);
    const double root = s1 * s1 + c1 * c2 * s2 * s2;
    *dist = 2.0 * r * std::atan2(std::sqrt(root), std::sqrt(1.0 - root));
    *qdr = R2D * std::atan2(
        std::sin(lon2 - lon1) * c2,
        c1 * std::sin(lat2) - std::sin(lat1) * c2 * std::cos(lon2 - lon1));
}

// ---------------------------------------------------------------------
// Argument plumbing: every export takes flat float64 arrays of one
// common length (the wrapper guarantees it) and returns new arrays.
// ---------------------------------------------------------------------

struct Args {
    PyArrayObject* arr[4] = {nullptr, nullptr, nullptr, nullptr};
    const double* p[4] = {nullptr, nullptr, nullptr, nullptr};
    npy_intp n = 0;
    bool ok = false;

    Args(PyObject* args, int count, int extra_int = -1, int* mode = nullptr) {
        PyObject* o[4] = {nullptr, nullptr, nullptr, nullptr};
        static const char* fmts[] = {"O", "OO", "OOO", "OOOO", "OOOOi"};
        if (mode) {
            if (!PyArg_ParseTuple(args, fmts[4], &o[0], &o[1], &o[2], &o[3],
                                  mode))
                return;
        } else if (!PyArg_ParseTuple(args, fmts[count - 1],
                                     &o[0], &o[1], &o[2], &o[3])) {
            return;
        }
        (void)extra_int;
        for (int i = 0; i < count; ++i) {
            arr[i] = (PyArrayObject*)PyArray_FROM_OTF(
                o[i], NPY_DOUBLE, NPY_ARRAY_IN_ARRAY);
            if (!arr[i]) return;
            p[i] = (const double*)PyArray_DATA(arr[i]);
        }
        n = PyArray_SIZE(arr[0]);
        ok = true;
    }

    ~Args() {
        for (auto* a : arr) Py_XDECREF(a);
    }
};

PyObject* out_like(npy_intp n, double** data) {
    PyObject* o = PyArray_SimpleNew(1, &n, NPY_DOUBLE);
    *data = (double*)PyArray_DATA((PyArrayObject*)o);
    return o;
}

PyObject* py_rwgs84(PyObject*, PyObject* args) {
    Args a(args, 1);
    if (!a.ok) return nullptr;
    double* r;
    PyObject* out = out_like(a.n, &r);
    for (npy_intp i = 0; i < a.n; ++i) r[i] = rwgs84_deg(a.p[0][i]);
    return out;
}

PyObject* py_wgsg(PyObject*, PyObject* args) {
    Args a(args, 1);
    if (!a.ok) return nullptr;
    double* g;
    PyObject* out = out_like(a.n, &g);
    for (npy_intp i = 0; i < a.n; ++i) {
        const double s = std::sin(D2R * a.p[0][i]);
        g[i] = 9.7803 * (1.0 + 0.001932 * s * s)
               / std::sqrt(1.0 - 6.694e-3 * s * s);
    }
    return out;
}

// qdrdist(lat1, lon1, lat2, lon2, mode) -> (qdr_deg, dist_m)
PyObject* py_qdrdist(PyObject*, PyObject* args) {
    int mode = 0;
    Args a(args, 4, -1, &mode);
    if (!a.ok) return nullptr;
    double *q, *d;
    PyObject* qo = out_like(a.n, &q);
    PyObject* dn = out_like(a.n, &d);
    for (npy_intp i = 0; i < a.n; ++i) {
        const double r = mean_radius(a.p[0][i], a.p[2][i], mode);
        haversine(a.p[0][i], a.p[1][i], a.p[2][i], a.p[3][i], r,
                  &q[i], &d[i]);
    }
    return Py_BuildValue("(NN)", qo, dn);
}

// qdrpos(lat1, lon1, qdr_deg, dist_nm) -> (lat2, lon2) [deg]
PyObject* py_qdrpos(PyObject*, PyObject* args) {
    Args a(args, 4);
    if (!a.ok) return nullptr;
    double *la, *lo;
    PyObject* lao = out_like(a.n, &la);
    PyObject* loo = out_like(a.n, &lo);
    for (npy_intp i = 0; i < a.n; ++i) {
        const double R = rwgs84_deg(a.p[0][i]) / NM;
        const double lat1 = D2R * a.p[0][i], lon1 = D2R * a.p[1][i];
        const double dr = a.p[3][i] / R, qdrr = D2R * a.p[2][i];
        const double sl = std::sin(lat1), cl = std::cos(lat1);
        const double lat2 = std::asin(
            sl * std::cos(dr) + cl * std::sin(dr) * std::cos(qdrr));
        la[i] = R2D * lat2;
        lo[i] = R2D * (lon1 + std::atan2(
            std::sin(qdrr) * std::sin(dr) * cl,
            std::cos(dr) - sl * std::sin(lat2)));
    }
    return Py_BuildValue("(NN)", lao, loo);
}

// kwik(lat1, lon1, lat2, lon2) -> (qdr_deg in [0,360), dist_m)
PyObject* py_kwik(PyObject*, PyObject* args) {
    Args a(args, 4);
    if (!a.ok) return nullptr;
    double *q, *d;
    PyObject* qo = out_like(a.n, &q);
    PyObject* dn = out_like(a.n, &d);
    for (npy_intp i = 0; i < a.n; ++i) {
        const double dlat = D2R * (a.p[2][i] - a.p[0][i]);
        const double dlon = D2R * (a.p[3][i] - a.p[1][i]);
        const double cav = std::cos(D2R * 0.5 * (a.p[0][i] + a.p[2][i]));
        d[i] = REARTH * std::sqrt(dlat * dlat + dlon * dlon * cav * cav);
        q[i] = std::fmod(R2D * std::atan2(dlon * cav, dlat) + 360.0, 360.0);
    }
    return Py_BuildValue("(NN)", qo, dn);
}

PyMethodDef methods[] = {
    {"rwgs84", py_rwgs84, METH_VARARGS, "WGS-84 local radius [m]"},
    {"wgsg", py_wgsg, METH_VARARGS, "WGS-84 gravity [m/s2]"},
    {"qdrdist", py_qdrdist, METH_VARARGS,
     "(qdr deg, dist m); mode 0 scalar / 1 matrix radius semantics"},
    {"qdrpos", py_qdrpos, METH_VARARGS, "dead-reckoned (lat2, lon2) [deg]"},
    {"kwik", py_kwik, METH_VARARGS, "flat-earth (qdr deg, dist m)"},
    {nullptr, nullptr, 0, nullptr}};

PyModuleDef moduledef = {PyModuleDef_HEAD_INIT, "_cgeo",
                         "compiled host geodesy core", -1, methods,
                         nullptr, nullptr, nullptr, nullptr};

}  // namespace

PyMODINIT_FUNC PyInit__cgeo(void) {
    import_array();
    return PyModule_Create(&moduledef);
}
