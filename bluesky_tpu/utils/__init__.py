"""Cross-cutting utilities: data logging, area filters, plotting."""
