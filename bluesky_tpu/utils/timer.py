"""Wall-clock periodic triggers (parity: bluesky/tools/timer.py:6-42).

Timers fire callbacks from the host main loop — the reference calls
``Timer.update_timers()`` each Node.run() iteration (node.py:80); ours is
called from the network node loop the same way.  Device-side scheduling
(ASAS/FMS cadence) is *not* done with these: that lives inside the jitted
step (core/step.py) as sim-time gates.
"""
import time


class Timer:
    """Fires connected callbacks every ``interval`` wall-clock seconds."""

    timers = []

    def __init__(self, interval: float):
        self.interval = float(interval)
        self.tnext = time.perf_counter() + self.interval
        self.slots = []
        Timer.timers.append(self)

    def connect(self, slot):
        self.slots.append(slot)

    def disconnect(self, slot):
        try:
            self.slots.remove(slot)
        except ValueError:
            pass

    def remove(self):
        """Deregister this timer so it stops firing and can be collected."""
        try:
            Timer.timers.remove(self)
        except ValueError:
            pass

    @classmethod
    def update_timers(cls):
        now = time.perf_counter()
        for timer in cls.timers:
            if now >= timer.tnext:
                timer.tnext = now + timer.interval
                for slot in list(timer.slots):
                    slot()

    @classmethod
    def reset_all(cls):
        cls.timers.clear()
