"""Minimal pub-sub signal (parity: bluesky/tools/signal.py:4).

A Signal is a named list of callbacks; emit() fans an event out to every
connected slot.  Used by the network Client to deliver events/streams and by
the plugin/GUI layers.
"""


class Signal:
    """Named callback list with connect/disconnect/emit."""

    def __init__(self, name=""):
        self.name = name
        self.slots = []

    def connect(self, slot):
        self.slots.append(slot)

    def disconnect(self, slot):
        try:
            self.slots.remove(slot)
        except ValueError:
            pass

    def emit(self, *args, **kwargs):
        for slot in list(self.slots):
            slot(*args, **kwargs)
