"""Profiling hooks: JAX trace capture + per-kernel wall timings.

SURVEY §5.1's build note: the reference only has BENCHMARK wall totals
and the SIMINFO rate stream; here the PROFILE stack command adds
``jax.profiler`` trace capture (viewable in TensorBoard/Perfetto) and a
per-kernel timing report that times the pipeline pieces separately —
the scanned step chunk, the CD kernel, and the MVP resolution — so the
benchmark number can be decomposed.
"""
import time

import numpy as np


def start_trace(logdir="output/jax-trace"):
    import jax
    jax.profiler.start_trace(logdir)
    return logdir


def stop_trace():
    import jax
    jax.profiler.stop_trace()


def kernel_timings(sim, nsteps=50, reps=3):
    """Per-kernel wall timings [ms] at the current traffic state.

    Times: one scanned step chunk (nsteps), the CD kernel alone, and
    CD + MVP resolve — each best-of-reps with block_until_ready.
    """
    import jax
    import jax.numpy as jnp
    from ..core.step import run_steps
    from ..ops import cd as cdops, cr_mvp

    sim.traf.flush()
    state = sim.traf.state
    cfg = sim.cfg
    ac = state.ac
    acfg = cfg.asas

    timings = {}

    def best(fn, *args):
        out = fn(*args)                      # compile
        jax.block_until_ready(out)
        t = np.inf
        for _ in range(reps):
            t0 = time.perf_counter()
            out = fn(*args)
            jax.block_until_ready(out)
            t = min(t, time.perf_counter() - t0)
        return t * 1000.0, out

    # Full chunk (not donated here: state is reused below)
    stepfn = jax.jit(lambda s: run_steps(s, cfg, nsteps))
    ms, _ = best(stepfn, state)
    timings[f"step_chunk[{nsteps}]"] = ms
    timings["per_sim_step"] = ms / nsteps

    if cfg.cd_backend == "dense":
        detect = jax.jit(lambda a: cdops.detect(
            a.lat, a.lon, a.trk, a.gs, a.alt, a.vs, a.active,
            acfg.rpz, acfg.hpz, acfg.dtlookahead))
        ms, cdout = best(detect, ac)
        timings["cd_detect"] = ms

        mvpcfg = cr_mvp.MVPConfig(
            rpz_m=acfg.rpz_m, hpz_m=acfg.hpz_m,
            tlookahead=acfg.dtlookahead)
        resolve = jax.jit(lambda c, a, ap: cr_mvp.resolve(
            c, a.alt, a.gseast, a.gsnorth, a.vs, a.trk, a.gs,
            a.selalt, ap.vs, state.asas.alt,
            acfg.vmin, acfg.vmax, acfg.vsmin, acfg.vsmax, mvpcfg))
        ms, _ = best(resolve, cdout, ac, state.ap)
        timings["mvp_resolve"] = ms
    elif cfg.cd_backend == "tiled":
        from ..ops import cd_tiled
        mvpcfg = cr_mvp.MVPConfig(
            rpz_m=acfg.rpz_m, hpz_m=acfg.hpz_m,
            tlookahead=acfg.dtlookahead)
        tiled = jax.jit(lambda a, nr: cd_tiled.detect_resolve_tiled(
            a.lat, a.lon, a.trk, a.gs, a.alt, a.vs, a.gseast, a.gsnorth,
            a.active, nr, acfg.rpz, acfg.hpz, acfg.dtlookahead, mvpcfg,
            block=cfg.cd_block))
        ms, _ = best(tiled, ac, state.asas.noreso)
        timings["cd_tiled"] = ms
    else:
        from ..ops import cd_pallas
        mvpcfg = cr_mvp.MVPConfig(
            rpz_m=acfg.rpz_m, hpz_m=acfg.hpz_m,
            tlookahead=acfg.dtlookahead)
        pal = jax.jit(lambda a, nr: cd_pallas.detect_resolve_pallas(
            a.lat, a.lon, a.trk, a.gs, a.alt, a.vs, a.gseast, a.gsnorth,
            a.active, nr, acfg.rpz, acfg.hpz, acfg.dtlookahead, mvpcfg,
            block=cfg.cd_block))
        ms, _ = best(pal, ac, state.asas.noreso)
        timings["cd_pallas"] = ms

    return timings


def report(sim, nsteps=50):
    t = kernel_timings(sim, nsteps)
    n = sim.traf.ntraf
    lines = [f"Kernel timings at N={n} ({sim.cfg.cd_backend} backend):"]
    for name, ms in t.items():
        lines.append(f"  {name}: {ms:.3f} ms")
    if "per_sim_step" in t and t["per_sim_step"] > 0:
        rate = n * 1000.0 / t["per_sim_step"]
        lines.append(f"  -> {rate:,.0f} aircraft-steps/s")
    return "\n".join(lines)
