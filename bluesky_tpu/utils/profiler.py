"""Profiling hooks: JAX trace capture + per-kernel wall timings.

SURVEY §5.1's build note: the reference only has BENCHMARK wall totals
and the SIMINFO rate stream; here the PROFILE stack command adds
``jax.profiler`` trace capture (viewable in TensorBoard/Perfetto) and a
per-kernel timing report that times the pipeline pieces separately —
the scanned step chunk, the CD kernel, and the MVP resolution — so the
benchmark number can be decomposed.

``deep_timings`` (PROFILE DEEP) carries the round-3 profiling sweep
that used to live in scripts/profile_r3.py: the CD program-overhead
probe (all-inactive fleet — every tile skips, what remains is grid +
DMA overhead), the no-prefilter variant (pair-cost slope with the
reach skip defeated), the cached spatial-sort argsort, and the MVP
resolve-from-sums + partner-bookkeeping tail.  PROFILE TRACE drives
the ISSUE-11 flight recorder (obs/trace.py) instead of jax.profiler.
"""
import time

import numpy as np


def start_trace(logdir="output/jax-trace"):
    import jax
    jax.profiler.start_trace(logdir)
    return logdir


def stop_trace():
    import jax
    jax.profiler.stop_trace()


def kernel_timings(sim, nsteps=50, reps=3):
    """Per-kernel wall timings [ms] at the current traffic state.

    Times: one scanned step chunk (nsteps), the CD kernel alone, and
    CD + MVP resolve — each best-of-reps with block_until_ready.
    """
    import jax
    import jax.numpy as jnp
    from ..core.step import run_steps
    from ..ops import cd as cdops, cr_mvp

    sim.traf.flush()
    state = sim.traf.state
    cfg = sim.cfg
    ac = state.ac
    acfg = cfg.asas

    timings = {}

    def best(fn, *args):
        out = fn(*args)                      # compile
        jax.block_until_ready(out)
        t = np.inf
        for _ in range(reps):
            t0 = time.perf_counter()
            out = fn(*args)
            jax.block_until_ready(out)
            t = min(t, time.perf_counter() - t0)
        return t * 1000.0, out

    # Full chunk (not donated here: state is reused below)
    stepfn = jax.jit(lambda s: run_steps(s, cfg, nsteps))
    ms, _ = best(stepfn, state)
    timings[f"step_chunk[{nsteps}]"] = ms
    timings["per_sim_step"] = ms / nsteps

    if cfg.cd_backend == "dense":
        detect = jax.jit(lambda a: cdops.detect(
            a.lat, a.lon, a.trk, a.gs, a.alt, a.vs, a.active,
            acfg.rpz, acfg.hpz, acfg.dtlookahead))
        ms, cdout = best(detect, ac)
        timings["cd_detect"] = ms

        mvpcfg = cr_mvp.MVPConfig(
            rpz_m=acfg.rpz_m, hpz_m=acfg.hpz_m,
            tlookahead=acfg.dtlookahead)
        resolve = jax.jit(lambda c, a, ap: cr_mvp.resolve(
            c, a.alt, a.gseast, a.gsnorth, a.vs, a.trk, a.gs,
            a.selalt, ap.vs, state.asas.alt,
            acfg.vmin, acfg.vmax, acfg.vsmin, acfg.vsmax, mvpcfg))
        ms, _ = best(resolve, cdout, ac, state.ap)
        timings["mvp_resolve"] = ms
    elif cfg.cd_backend == "tiled":
        from ..ops import cd_tiled
        mvpcfg = cr_mvp.MVPConfig(
            rpz_m=acfg.rpz_m, hpz_m=acfg.hpz_m,
            tlookahead=acfg.dtlookahead)
        tiled = jax.jit(lambda a, nr: cd_tiled.detect_resolve_tiled(
            a.lat, a.lon, a.trk, a.gs, a.alt, a.vs, a.gseast, a.gsnorth,
            a.active, nr, acfg.rpz, acfg.hpz, acfg.dtlookahead, mvpcfg,
            block=cfg.cd_block))
        ms, _ = best(tiled, ac, state.asas.noreso)
        timings["cd_tiled"] = ms
    else:
        from ..ops import cd_pallas
        mvpcfg = cr_mvp.MVPConfig(
            rpz_m=acfg.rpz_m, hpz_m=acfg.hpz_m,
            tlookahead=acfg.dtlookahead)
        pal = jax.jit(lambda a, nr: cd_pallas.detect_resolve_pallas(
            a.lat, a.lon, a.trk, a.gs, a.alt, a.vs, a.gseast, a.gsnorth,
            a.active, nr, acfg.rpz, acfg.hpz, acfg.dtlookahead, mvpcfg,
            block=cfg.cd_block))
        ms, _ = best(pal, ac, state.asas.noreso)
        timings["cd_pallas"] = ms

    return timings


def report(sim, nsteps=50):
    t = kernel_timings(sim, nsteps)
    n = sim.traf.ntraf
    lines = [f"Kernel timings at N={n} ({sim.cfg.cd_backend} backend):"]
    for name, ms in t.items():
        lines.append(f"  {name}: {ms:.3f} ms")
    if "per_sim_step" in t and t["per_sim_step"] > 0:
        rate = n * 1000.0 / t["per_sim_step"]
        lines.append(f"  -> {rate:,.0f} aircraft-steps/s")
    return "\n".join(lines)


def deep_timings(sim, reps=3):
    """The round-3 decomposition sweep (ex scripts/profile_r3.py), run
    against the CURRENT traffic state: program-overhead and pair-cost
    probes for the tiled/pallas CD kernels, the spatial argsort, and
    the MVP tail.  Dense backend gets only the sort + tail (its kernel
    has no tile-skip structure to probe)."""
    import jax
    import jax.numpy as jnp
    from ..ops import cd_pallas, cd_tiled, cr_mvp

    sim.traf.flush()
    state = sim.traf.state
    ac = state.ac
    asas = state.asas
    acfg = sim.cfg.asas
    mcfg = cr_mvp.MVPConfig(rpz_m=acfg.rpz_m, hpz_m=acfg.hpz_m,
                            tlookahead=acfg.dtlookahead)

    def best(make):
        fn = jax.jit(make)
        jax.block_until_ready(fn())          # compile
        t = np.inf
        for _ in range(reps):
            t0 = time.perf_counter()
            jax.block_until_ready(fn())
            t = min(t, time.perf_counter() - t0)
        return t * 1000.0

    timings = {}

    # the cached Morton argsort (the sort_refresh cost the sim pays
    # every sort_every * dtasas sim seconds)
    timings["spatial_permutation"] = best(
        lambda: cd_tiled.spatial_permutation(ac.lat, ac.lon, ac.active))

    backend = sim.cfg.cd_backend
    if backend in ("tiled", "pallas"):
        mod = cd_pallas if backend == "pallas" else cd_tiled
        kern = (mod.detect_resolve_pallas if backend == "pallas"
                else mod.detect_resolve_tiled)
        perm = jax.block_until_ready(
            cd_tiled.spatial_permutation(ac.lat, ac.lon, ac.active)
            .astype(jnp.int32))
        args = (ac.lat, ac.lon, ac.trk, ac.gs, ac.alt, ac.vs,
                ac.gseast, ac.gsnorth)
        common = dict(block=sim.cfg.cd_block)

        timings["cd_sweep"] = best(
            lambda: kern(*args, ac.active, asas.noreso,
                         acfg.rpz, acfg.hpz, acfg.dtlookahead, mcfg,
                         perm=perm, **common).inconf)
        # all-inactive probe: every tile skips via the pair mask, so
        # what is left is pure grid + DMA program overhead
        inact = jnp.zeros_like(ac.active)
        timings["cd_all_inactive"] = best(
            lambda: kern(*args, inact, asas.noreso,
                         acfg.rpz, acfg.hpz, acfg.dtlookahead, mcfg,
                         perm=perm, **common).inconf)
        # no-prefilter variant: the reach skip defeated — the slope of
        # sweep-vs-this is the cost actually bought by sorting
        timings["cd_unsorted"] = best(
            lambda: kern(*args, ac.active, asas.noreso,
                         acfg.rpz, acfg.hpz, acfg.dtlookahead, mcfg,
                         perm=perm, spatial_sort=False, **common).inconf)

        # the ASAS tail: resolve-from-sums + partner bookkeeping
        rd = jax.block_until_ready(jax.jit(
            lambda: kern(*args, ac.active, asas.noreso,
                         acfg.rpz, acfg.hpz, acfg.dtlookahead, mcfg,
                         perm=perm, **common))())

        def tail():
            out = cr_mvp.resolve_from_sums(
                rd.sum_dve, rd.sum_dvn, rd.sum_dvv, rd.tsolv,
                ac.alt, ac.gseast, ac.gsnorth, ac.vs, ac.trk, ac.gs,
                ac.selalt, state.ap.vs, asas.alt,
                acfg.vmin, acfg.vmax, acfg.vsmin, acfg.vsmax, mcfg,
                resooff=asas.resooff)
            keep = cd_tiled.partner_keep(
                asas.partners, ac.lat, ac.lon, ac.gseast, ac.gsnorth,
                ac.trk, ac.active, acfg.rpz, acfg.rpz_m)
            merged = cd_tiled.merge_partners(
                cd_tiled.topk_partners(rd, 8), asas.partners, keep)
            return out[0], merged
        tailfn = jax.jit(tail)
        timings["mvp_tail"] = best(lambda: tailfn())
    return timings


def deep_report(sim):
    t = deep_timings(sim)
    lines = [f"Deep sweep at N={sim.traf.ntraf} "
             f"({sim.cfg.cd_backend} backend):"]
    for name, ms in t.items():
        lines.append(f"  {name}: {ms:.3f} ms")
    if "cd_sweep" in t:
        lines.append(
            f"  -> overhead floor {t['cd_all_inactive']:.3f} ms, "
            f"prefilter saves "
            f"{t['cd_unsorted'] - t['cd_sweep']:.3f} ms/sweep")
    # ISSUE-12: device-memory watermarks (live/peak bytes per device,
    # forced sample so the column appears even with devprof_mem_dt=0)
    dp = getattr(sim, "devprof", None)
    if dp is not None:
        try:
            dp.sample_memory(force=True)
            wm = dp.watermarks()
        except Exception:
            wm = {}
        if wm:
            lines.append("  device memory (live / peak):")
            for did in sorted(wm):
                live, peak = wm[did]
                lines.append(f"    dev{did}: {live / 1e6:8.2f} MB / "
                             f"{peak / 1e6:8.2f} MB")
    return "\n".join(lines)
