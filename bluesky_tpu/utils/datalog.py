"""CSV data logging with periodic scheduling.

Parity with reference ``bluesky/tools/datalog.py``: named loggers with a
header, an interval, and a selected-variable list; periodic loggers
(SNAPLOG/INSTLOG/SKYLOG, traffic.py:86-89) sample every dt of sim time into
``LOG_<name>_<scenario>_<timestamp>.log`` CSVs; every logger auto-registers a
stack command ``<NAME> ON/OFF [dt] / LISTVARS / SELECTVARS`` (datalog.py:
106-110, 216-242).

TPU-first: the reference intercepts ``__setattr__`` with a class swap to
capture variable groups (datalog.py:112-139).  Here variables are plain
named getters over the state pytree; sampling pulls one device->host
transfer per logged chunk edge (never inside the jitted step).

Registry scoping: loggers live in a ``LogRegistry``.  Historically the
registry was module-global (one set of loggers per process), which is a
singleton in the hot path once multiple Simulations share a process —
the multi-world serving path (simulation/worlds.py) runs W independent
scenario worlds per worker, and their datalog output must demux into
per-world files instead of interleaving in shared ones.  Every
``Simulation`` therefore owns a registry (``sim.datalog``); standalone
sims share the module default so the classic one-sim-per-process
behavior — and the module-level function API — is unchanged.
"""
import os
import time
from typing import Callable, Dict, List, Optional

import numpy as np

from .. import settings


def log_dir() -> str:
    """Output directory for logs — reads ``settings.log_path`` at call
    time so tests (and SETLOGPATH-style reconfiguration) can redirect
    all file output without touching module globals."""
    return settings.log_path


class LogRegistry:
    """One named-logger namespace: define/get loggers, sample the due
    ones at chunk edges, register their stack commands.

    ``tag`` is spliced into every log filename (``SNAPLOG_w03_...``) so
    W world registries sharing one output directory stay separable —
    the datalog leg of the multi-world demux.
    """

    def __init__(self, tag: str = ""):
        self.tag = str(tag)
        self._loggers: Dict[str, "CSVLogger"] = {}

    # ------------------------------------------------------------ loggers
    def getlogger(self, name: str) -> Optional["CSVLogger"]:
        return self._loggers.get(name.upper())

    def define_periodic(self, name: str, header: str,
                        dt: float) -> "CSVLogger":
        return CSVLogger(name, header, dt, _traf_getters(), registry=self)

    def define_event(self, name: str, header: str) -> "EventLogger":
        """Create-or-get an event logger (reference datalog.defineLogger)."""
        lg = self.getlogger(name)
        if lg is None:
            lg = EventLogger(name, header, registry=self)
        return lg

    def crelog(self, name: str, header: str, getters=None) -> "CSVLogger":
        return CSVLogger(name, header, 0.0, getters, registry=self)

    # ----------------------------------------------------------- sampling
    def postupdate(self, sim):
        """Sample due periodic loggers (called at chunk edges by the sim)."""
        simt = sim.simt
        for lg in self._loggers.values():
            if lg.active and lg.dt > 0 and simt >= lg.tlog:
                lg.tlog += lg.dt
                lg.log(sim)

    def any_due(self, simt: float) -> bool:
        """Any active periodic logger due at (or before) ``simt``?  The
        pipelined chunk loop asks this before dispatching: logger getters
        read live sim state, so a due sample forces a synchronous edge."""
        return any(lg.active and lg.dt > 0 and simt >= lg.tlog
                   for lg in self._loggers.values())

    def reset(self):
        for lg in self._loggers.values():
            lg.stop()

    def register_stack_commands(self, sim):
        """Give every logger its own stack command (datalog.py:106-110)."""
        cmds = {}
        for name, lg in self._loggers.items():
            cmds[name] = [
                f"{name} ON/OFF,[dt] or LISTVARS or SELECTVARS var1,...",
                "[txt,...]",
                (lambda l: lambda *args: l.stackio(sim, *args))(lg),
                lg.header]
        sim.stack.append_commands(cmds)


class CSVLogger:
    def __init__(self, name: str, header: str, dt: float = 0.0,
                 getters: Optional[Dict[str, Callable]] = None,
                 registry: Optional[LogRegistry] = None):
        self.name = name.upper()
        self.header = header
        self.dt = dt
        self.tlog = 0.0
        self.active = False
        self.file = None
        self.getters = getters or {}
        self.selvars = list(self.getters.keys())
        self.registry = registry if registry is not None else _default
        self.registry._loggers[self.name] = self

    # ----------------------------------------------------------- control
    def start(self, sim, dt: Optional[float] = None):
        if dt is not None:
            self.dt = dt
        os.makedirs(log_dir(), exist_ok=True)
        scen = sim.stack.scenname or "untitled"
        tag = f"{self.registry.tag}_" if self.registry.tag else ""
        stamp = time.strftime("%Y%m%d_%H-%M-%S")
        fname = os.path.join(log_dir(),
                             f"{self.name}_{tag}{scen}_{stamp}.log")
        # never truncate an existing log (two starts in the same
        # wall-clock second would share the timestamped name)
        k = 1
        while os.path.exists(fname):
            fname = os.path.join(
                log_dir(), f"{self.name}_{tag}{scen}_{stamp}_{k}.log")
            k += 1
        self.file = open(fname, "w")
        self.file.write(f"# {self.header}\n")
        self.file.write("# simt, " + ", ".join(self.selvars) + "\n")
        self.tlog = float(sim.simt)
        self.active = True
        return fname

    def stop(self):
        if self.file:
            self.file.close()
            self.file = None
        self.active = False

    def log(self, sim, *extra):
        """Write one sample row set (one line per aircraft for array vars)."""
        if not self.file:
            return
        simt = sim.simt
        cols = []
        for v in self.selvars:
            val = self.getters[v](sim)
            cols.append(np.atleast_1d(np.asarray(val)))
        if not cols:
            return
        nrows = max(c.shape[0] for c in cols)
        for r in range(nrows):
            vals = [f"{simt:.2f}"]
            for c in cols:
                x = c[min(r, c.shape[0] - 1)]
                vals.append(str(x))
            self.file.write(", ".join(vals) + "\n")

    # -------------------------------------------------------- stack cmd
    def stackio(self, sim, *args):
        """``NAME`` / ``NAME ON [dt]`` / ``NAME OFF`` / ``LISTVARS`` /
        ``SELECTVARS var1,...,varn`` (reference datalog.py:216-242)."""
        if not args:
            return True, (f"{self.name} is "
                          f"{'ON' if self.active else 'OFF'}\nUsage: "
                          f"{self.name} ON/OFF,[dt] or LISTVARS or "
                          f"SELECTVARS var1,...,varn")
        f = str(args[0]).upper()
        if f in ("ON", "TRUE", "1"):
            dt = None
            if len(args) > 1:
                try:
                    dt = float(args[1])
                except (TypeError, ValueError):
                    return False, (f"Turn {self.name} on with an "
                                   "optional numeric dt")
            if self.active:
                self.stop()           # ON while ON: rotate the file
            fname = self.start(sim, dt)
            return True, f"{self.name} logging to {fname}"
        if f in ("OFF", "FALSE", "0"):
            self.stop()
            return True
        if f == "LISTVARS":
            return True, "Variables: " + ", ".join(self.getters.keys())
        if f == "SELECTVARS":
            if not self.getters:
                return False, (f"{self.name}: event logger, columns "
                               "are fixed by its producer")
            if self.active and len(args) > 1:
                # the open file's column header is already written
                return False, (f"{self.name} is logging — OFF first, "
                               "then SELECTVARS (the header is fixed "
                               "per file)")
            if len(args) == 1:
                return True, (f"{self.name} selected: "
                              + ", ".join(self.selvars))
            bykey = {k.upper(): k for k in self.getters}
            want, unknown = [], []
            for a in args[1:]:
                k = bykey.get(str(a).upper())
                (want if k else unknown).append(k or str(a))
            if unknown:
                return False, (f"{self.name}: unknown variable(s) "
                               f"{', '.join(unknown)} (LISTVARS shows "
                               "the choices)")
            self.selvars = want
            return True, (f"{self.name} now logs: "
                          + ", ".join(self.selvars))
        return False, f"{self.name}: unknown argument {args[0]}"


class EventLogger(CSVLogger):
    """Event-driven logger: rows are passed explicitly to ``log`` instead
    of sampled through getters (the reference ``datalog.defineLogger``
    pattern used by the AREA plugin's FLST log, plugins/area.py:99,144)."""

    def __init__(self, name: str, header: str,
                 registry: Optional[LogRegistry] = None):
        super().__init__(name, header, dt=0.0, getters={},
                         registry=registry)

    def log(self, sim, *columns, simt=None):
        """Write one row per element; columns are arrays/lists of equal
        length (scalars broadcast).  ``simt`` overrides the timestamp:
        pipelined chunk edges pass their own edge clock so the row is
        stamped with the sampled state's time (and no device sync is
        forced while the next chunk is in flight)."""
        if not self.file or not columns:
            return
        if simt is None:
            simt = sim.simt
        cols = [np.atleast_1d(np.asarray(c)) for c in columns]
        nrows = max(c.shape[0] for c in cols)
        for c in cols:
            if c.shape[0] not in (1, nrows):
                raise ValueError(
                    f"{self.name}: column length {c.shape[0]} != {nrows} "
                    "(only scalars broadcast)")
        for r in range(nrows):
            vals = [f"{simt:.2f}"]
            for c in cols:
                vals.append(str(c[min(r, c.shape[0] - 1)]))
            self.file.write(", ".join(vals) + "\n")


def _traf_getters():
    """Default per-aircraft variable getters (SNAPLOG group,
    traffic.py:94-125)."""
    def arr(field):
        def get(sim):
            st = sim.traf.state
            live = np.asarray(st.ac.active)
            return np.asarray(getattr(st.ac, field))[live]
        return get

    def ids(sim):
        return np.asarray([i for i in sim.traf.ids if i is not None])

    g = {"id": ids}
    for f in ("lat", "lon", "alt", "hdg", "trk", "tas", "gs", "cas", "vs"):
        g[f] = arr(f)
    return g


# ------------------------------------------------- module-level default
# The process-wide default registry: standalone sims and the module
# function API below share it, preserving the classic behavior.  Multi-
# world sims pass their own LogRegistry to Simulation instead.
_default = LogRegistry()
_loggers = _default._loggers      # legacy alias (tests/introspection)


def default_registry() -> LogRegistry:
    return _default


def defineLogger(name: str, header: str) -> "EventLogger":
    return _default.define_event(name, header)


def definePeriodicLogger(name: str, header: str, dt: float) -> CSVLogger:
    return _default.define_periodic(name, header, dt)


def crelog(name: str, header: str, getters=None) -> CSVLogger:
    return _default.crelog(name, header, getters)


def getlogger(name: str) -> Optional[CSVLogger]:
    return _default.getlogger(name)


def postupdate(sim):
    return _default.postupdate(sim)


def any_due(simt: float) -> bool:
    return _default.any_due(simt)


def reset():
    _default.reset()


def register_stack_commands(sim):
    _default.register_stack_commands(sim)
