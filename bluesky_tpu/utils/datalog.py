"""CSV data logging with periodic scheduling.

Parity with reference ``bluesky/tools/datalog.py``: named loggers with a
header, an interval, and a selected-variable list; periodic loggers
(SNAPLOG/INSTLOG/SKYLOG, traffic.py:86-89) sample every dt of sim time into
``LOG_<name>_<scenario>_<timestamp>.log`` CSVs; every logger auto-registers a
stack command ``<NAME> ON/OFF [dt] / LISTVARS / SELECTVARS`` (datalog.py:
106-110, 216-242).

TPU-first: the reference intercepts ``__setattr__`` with a class swap to
capture variable groups (datalog.py:112-139).  Here variables are plain
named getters over the state pytree; sampling pulls one device->host
transfer per logged chunk edge (never inside the jitted step).
"""
import os
import time
from typing import Callable, Dict, List, Optional

import numpy as np

from .. import settings

_loggers: Dict[str, "CSVLogger"] = {}


def log_dir() -> str:
    """Output directory for logs — reads ``settings.log_path`` at call
    time so tests (and SETLOGPATH-style reconfiguration) can redirect
    all file output without touching module globals."""
    return settings.log_path


class CSVLogger:
    def __init__(self, name: str, header: str, dt: float = 0.0,
                 getters: Optional[Dict[str, Callable]] = None):
        self.name = name.upper()
        self.header = header
        self.dt = dt
        self.tlog = 0.0
        self.active = False
        self.file = None
        self.getters = getters or {}
        self.selvars = list(self.getters.keys())
        _loggers[self.name] = self

    # ----------------------------------------------------------- control
    def start(self, sim, dt: Optional[float] = None):
        if dt is not None:
            self.dt = dt
        os.makedirs(log_dir(), exist_ok=True)
        scen = sim.stack.scenname or "untitled"
        stamp = time.strftime("%Y%m%d_%H-%M-%S")
        fname = os.path.join(log_dir(), f"{self.name}_{scen}_{stamp}.log")
        # never truncate an existing log (two starts in the same
        # wall-clock second would share the timestamped name)
        k = 1
        while os.path.exists(fname):
            fname = os.path.join(
                log_dir(), f"{self.name}_{scen}_{stamp}_{k}.log")
            k += 1
        self.file = open(fname, "w")
        self.file.write(f"# {self.header}\n")
        self.file.write("# simt, " + ", ".join(self.selvars) + "\n")
        self.tlog = float(sim.simt)
        self.active = True
        return fname

    def stop(self):
        if self.file:
            self.file.close()
            self.file = None
        self.active = False

    def log(self, sim, *extra):
        """Write one sample row set (one line per aircraft for array vars)."""
        if not self.file:
            return
        simt = sim.simt
        cols = []
        for v in self.selvars:
            val = self.getters[v](sim)
            cols.append(np.atleast_1d(np.asarray(val)))
        if not cols:
            return
        nrows = max(c.shape[0] for c in cols)
        for r in range(nrows):
            vals = [f"{simt:.2f}"]
            for c in cols:
                x = c[min(r, c.shape[0] - 1)]
                vals.append(str(x))
            self.file.write(", ".join(vals) + "\n")

    # -------------------------------------------------------- stack cmd
    def stackio(self, sim, *args):
        """``NAME`` / ``NAME ON [dt]`` / ``NAME OFF`` / ``LISTVARS`` /
        ``SELECTVARS var1,...,varn`` (reference datalog.py:216-242)."""
        if not args:
            return True, (f"{self.name} is "
                          f"{'ON' if self.active else 'OFF'}\nUsage: "
                          f"{self.name} ON/OFF,[dt] or LISTVARS or "
                          f"SELECTVARS var1,...,varn")
        f = str(args[0]).upper()
        if f in ("ON", "TRUE", "1"):
            dt = None
            if len(args) > 1:
                try:
                    dt = float(args[1])
                except (TypeError, ValueError):
                    return False, (f"Turn {self.name} on with an "
                                   "optional numeric dt")
            if self.active:
                self.stop()           # ON while ON: rotate the file
            fname = self.start(sim, dt)
            return True, f"{self.name} logging to {fname}"
        if f in ("OFF", "FALSE", "0"):
            self.stop()
            return True
        if f == "LISTVARS":
            return True, "Variables: " + ", ".join(self.getters.keys())
        if f == "SELECTVARS":
            if not self.getters:
                return False, (f"{self.name}: event logger, columns "
                               "are fixed by its producer")
            if self.active and len(args) > 1:
                # the open file's column header is already written
                return False, (f"{self.name} is logging — OFF first, "
                               "then SELECTVARS (the header is fixed "
                               "per file)")
            if len(args) == 1:
                return True, (f"{self.name} selected: "
                              + ", ".join(self.selvars))
            bykey = {k.upper(): k for k in self.getters}
            want, unknown = [], []
            for a in args[1:]:
                k = bykey.get(str(a).upper())
                (want if k else unknown).append(k or str(a))
            if unknown:
                return False, (f"{self.name}: unknown variable(s) "
                               f"{', '.join(unknown)} (LISTVARS shows "
                               "the choices)")
            self.selvars = want
            return True, (f"{self.name} now logs: "
                          + ", ".join(self.selvars))
        return False, f"{self.name}: unknown argument {args[0]}"


class EventLogger(CSVLogger):
    """Event-driven logger: rows are passed explicitly to ``log`` instead
    of sampled through getters (the reference ``datalog.defineLogger``
    pattern used by the AREA plugin's FLST log, plugins/area.py:99,144)."""

    def __init__(self, name: str, header: str):
        super().__init__(name, header, dt=0.0, getters={})

    def log(self, sim, *columns, simt=None):
        """Write one row per element; columns are arrays/lists of equal
        length (scalars broadcast).  ``simt`` overrides the timestamp:
        pipelined chunk edges pass their own edge clock so the row is
        stamped with the sampled state's time (and no device sync is
        forced while the next chunk is in flight)."""
        if not self.file or not columns:
            return
        if simt is None:
            simt = sim.simt
        cols = [np.atleast_1d(np.asarray(c)) for c in columns]
        nrows = max(c.shape[0] for c in cols)
        for c in cols:
            if c.shape[0] not in (1, nrows):
                raise ValueError(
                    f"{self.name}: column length {c.shape[0]} != {nrows} "
                    "(only scalars broadcast)")
        for r in range(nrows):
            vals = [f"{simt:.2f}"]
            for c in cols:
                vals.append(str(c[min(r, c.shape[0] - 1)]))
            self.file.write(", ".join(vals) + "\n")


def defineLogger(name: str, header: str) -> "EventLogger":
    """Create-or-get an event logger (reference datalog.defineLogger)."""
    lg = getlogger(name)
    if lg is None:
        lg = EventLogger(name, header)
    return lg


def _traf_getters():
    """Default per-aircraft variable getters (SNAPLOG group,
    traffic.py:94-125)."""
    def arr(field):
        def get(sim):
            st = sim.traf.state
            live = np.asarray(st.ac.active)
            return np.asarray(getattr(st.ac, field))[live]
        return get

    def ids(sim):
        return np.asarray([i for i in sim.traf.ids if i is not None])

    g = {"id": ids}
    for f in ("lat", "lon", "alt", "hdg", "trk", "tas", "gs", "cas", "vs"):
        g[f] = arr(f)
    return g


def definePeriodicLogger(name: str, header: str, dt: float) -> CSVLogger:
    return CSVLogger(name, header, dt, _traf_getters())


def crelog(name: str, header: str, getters=None) -> CSVLogger:
    return CSVLogger(name, header, 0.0, getters)


def getlogger(name: str) -> Optional[CSVLogger]:
    return _loggers.get(name.upper())


def postupdate(sim):
    """Sample due periodic loggers (called at chunk edges by the sim)."""
    simt = sim.simt
    for lg in _loggers.values():
        if lg.active and lg.dt > 0 and simt >= lg.tlog:
            lg.tlog += lg.dt
            lg.log(sim)


def any_due(simt: float) -> bool:
    """Any active periodic logger due at (or before) ``simt``?  The
    pipelined chunk loop asks this before dispatching: logger getters
    read live sim state, so a due sample forces a synchronous edge."""
    return any(lg.active and lg.dt > 0 and simt >= lg.tlog
               for lg in _loggers.values())


def reset():
    for lg in _loggers.values():
        lg.stop()


def register_stack_commands(sim):
    """Give every logger its own stack command (datalog.py:106-110)."""
    cmds = {}
    for name, lg in _loggers.items():
        cmds[name] = [
            f"{name} ON/OFF,[dt] or LISTVARS or SELECTVARS var1,...",
            "[txt,...]",
            (lambda l: lambda *args: l.stackio(sim, *args))(lg),
            lg.header]
    sim.stack.append_commands(cmds)
