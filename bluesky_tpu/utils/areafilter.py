"""Named geometric areas with vectorized inside-tests.

Parity with the reference ``bluesky/tools/areafilter.py:15-104``: named
BOX / CIRCLE / POLY / LINE shapes with optional altitude bounds, a
vectorized ``checkInside(name, lat, lon, alt)`` over aircraft arrays, and
shape mirroring to the screen object for display.

TPU-first divergences:
* Shapes live in a registry object (no module-global mutable dict shared
  across sims) so parallel Simulation instances don't alias state; a
  module-level default registry keeps the reference's convenience API.
* Point-in-polygon is an explicit vectorized even-odd crossing test in
  NumPy (the reference leans on ``matplotlib.path.Path.contains_points``)
  — no plotting dependency, and the same math is expressible in jnp for a
  device-side mask when a consumer (e.g. GEOVECTOR) wants to stay on
  device: every shape exposes ``contains(lat, lon, alt, xp=np)`` where
  ``xp`` may be ``jax.numpy``.
* These tests run at chunk edges on host samples (area deletion and FLST
  logging are host bookkeeping anyway), so the hot step never pays for
  them.
"""
import numpy as np

from ..ops.geo import kwikdist_wrapped


class Shape:
    """Base: raw dict mirrors the reference Shape.raw for GUI streaming."""

    kind = "SHAPE"

    def __init__(self, name, coordinates, top=1e9, bottom=-1e9):
        self.name = name
        self.coordinates = list(coordinates)
        self.top = max(bottom, top)
        self.bottom = min(bottom, top)
        self.raw = dict(name=name, shape=self.kind.lower(),
                        coordinates=self.coordinates)

    def contains(self, lat, lon, alt, xp=np):
        raise NotImplementedError


class Line(Shape):
    """Display-only: never contains anything (areafilter.py:52-58)."""
    kind = "LINE"

    def __init__(self, name, coordinates):
        super().__init__(name, coordinates)

    def contains(self, lat, lon, alt, xp=np):
        return xp.zeros(xp.shape(lat), dtype=bool)


class Box(Shape):
    kind = "BOX"

    def __init__(self, name, coordinates, top=1e9, bottom=-1e9):
        super().__init__(name, coordinates, top, bottom)
        lat0, lon0, lat1, lon1 = coordinates[:4]
        self.lat0, self.lat1 = min(lat0, lat1), max(lat0, lat1)
        self.lon0, self.lon1 = min(lon0, lon1), max(lon0, lon1)

    def contains(self, lat, lon, alt, xp=np):
        return ((self.lat0 <= lat) & (lat <= self.lat1)
                & (self.lon0 <= lon) & (lon <= self.lon1)
                & (self.bottom <= alt) & (alt <= self.top))


class Circle(Shape):
    kind = "CIRCLE"

    def __init__(self, name, coordinates, top=1e9, bottom=-1e9):
        super().__init__(name, coordinates, top, bottom)
        self.clat, self.clon, self.r = coordinates[:3]   # radius in nm

    def contains(self, lat, lon, alt, xp=np):
        dist = kwikdist_wrapped(self.clat, self.clon, lat, lon, xp=xp)
        return (dist <= self.r) & (self.bottom <= alt) & (alt <= self.top)


class Poly(Shape):
    kind = "POLY"

    def __init__(self, name, coordinates, top=1e9, bottom=-1e9):
        super().__init__(name, coordinates, top, bottom)
        pts = np.reshape(np.asarray(coordinates, np.float64), (-1, 2))
        self.plat = pts[:, 0]
        self.plon = pts[:, 1]

    def contains(self, lat, lon, alt, xp=np):
        """Vectorized even-odd crossing test over all (point, edge) pairs.

        For V vertices and N points this is an [N, V] broadcast — tiny for
        realistic sector polygons, and pure elementwise math so the same
        expression runs on device with xp=jnp.
        """
        y = xp.asarray(lat)[..., None]            # [N,1] latitude  = "y"
        x = xp.asarray(lon)[..., None]            # [N,1] longitude = "x"
        y0, x0 = self.plat, self.plon             # [V]
        y1 = np.roll(self.plat, -1)
        x1 = np.roll(self.plon, -1)
        # Edge straddles the point's horizontal line...
        straddle = (y0 <= y) != (y1 <= y)
        # ...and the crossing is to the east of the point.
        with np.errstate(divide="ignore", invalid="ignore"):
            xcross = x0 + (y - y0) * (x1 - x0) / xp.where(
                y1 == y0, 1e-30, y1 - y0)
        crossings = xp.sum(straddle & (x < xcross), axis=-1)
        inside = (crossings % 2) == 1
        return inside & (self.bottom <= alt) & (alt <= self.top)


class AreaRegistry:
    """Named-shape registry (replaces the reference module-global dict)."""

    _KINDS = {"BOX": Box, "CIRCLE": Circle, "LINE": Line}

    def __init__(self, scr=None):
        self.areas = {}
        self.scr = scr

    def hasArea(self, name):
        return name in self.areas

    def defineArea(self, name, areatype, coordinates, top=1e9, bottom=-1e9):
        """BOX/CIRCLE/POLY*/LINE factory (areafilter.py:15-27)."""
        areatype = areatype.upper()
        if areatype.startswith("POLY"):
            shape = Poly(name, coordinates, top, bottom)
        elif areatype == "LINE":
            shape = Line(name, coordinates)
        elif areatype in self._KINDS:
            shape = self._KINDS[areatype](name, coordinates, top, bottom)
        else:
            return False, f"Unknown area type {areatype}"
        self.areas[name] = shape
        if self.scr is not None:
            self.scr.objappend(areatype, name, coordinates)
        return True

    def checkInside(self, name, lat, lon, alt, xp=np):
        """[N] bool: which points are inside the named area
        (areafilter.py:29-36).  Unknown name -> all-False."""
        area = self.areas.get(name)
        if area is None:
            return xp.zeros(xp.shape(lat), dtype=bool)
        return area.contains(lat, lon, alt, xp=xp)

    def deleteArea(self, name):
        if name in self.areas:
            self.areas.pop(name)
            if self.scr is not None:
                self.scr.objappend("", name, None)
            return True
        return False

    def reset(self):
        """Clear all areas, including their screen mirrors."""
        for name in list(self.areas):
            self.deleteArea(name)


# Module-level default registry: the reference-convenience API for code
# that doesn't carry a Simulation (plugins use sim.areas instead).
_default = AreaRegistry()
hasArea = _default.hasArea
defineArea = _default.defineArea
checkInside = _default.checkInside
deleteArea = _default.deleteArea
reset = _default.reset
areas = _default.areas
