"""EUROCONTROL SO6 flight-plan -> BlueSky scenario converter.

Role parity with the reference's scenario-creation tooling
(`/root/reference/utils/Scenario-creator/so6_to_scn.py`, a bit-rotted
Tk-era script): turn an SO6 "m1" trajectory file into a runnable `.scn`
— one timed `CRE` per flight at its first segment plus `ADDWPT` route
waypoints with altitude/speed constraints for the remaining segment
ends, so the FMS flies the profile.

SO6 m1 format (one segment per line, space-separated, 20 fields):

  seg_name origin destination actype t_begin t_end fl_begin fl_end
  status callsign date_begin date_end lat_begin lon_begin lat_end
  lon_end flightid sequence length [parity]

with latitudes/longitudes in MINUTES of arc (divide by 60), flight
levels in FL, times ``HHMMSS``, dates ``YYMMDD``, segment length in nm.

Usage:  python -m bluesky_tpu.utils.so6 flights.so6 [out.scn]
"""
import sys
from dataclasses import dataclass, field
from typing import Dict, List, Tuple


@dataclass
class _Flight:
    actype: str
    t0: int                      # [s] first segment start (absolute)
    segs: List[Tuple] = field(default_factory=list)
    # seg: (t_begin, t_end, fl0, fl1, lat0, lon0, lat1, lon1, len_nm)


def _hms(t: str) -> int:
    t = t.zfill(6)
    return int(t[0:2]) * 3600 + int(t[2:4]) * 60 + int(t[4:6])


def _fmt_t(sec: float) -> str:
    sec = max(0.0, sec)
    h, rem = divmod(int(sec), 3600)
    m, s = divmod(rem, 60)
    return f"{h:02d}:{m:02d}:{s:02d}.00"


def parse_so6(lines) -> Dict[str, _Flight]:
    """Parse SO6 text lines into per-flight segment lists.

    Key is ``callsign:flightid`` (SO6 repeats callsigns across days);
    malformed lines are skipped with a notice on stderr.
    """
    flights: Dict[str, _Flight] = {}
    for ln, line in enumerate(lines, 1):
        f = line.split()
        if not f or line.lstrip().startswith("#"):
            continue
        if len(f) < 19:
            print(f"so6: line {ln}: {len(f)} fields < 19 — skipped",
                  file=sys.stderr)
            continue
        try:
            actype = f[3]
            tb, te = _hms(f[4]), _hms(f[5])
            # date rollover: segments crossing midnight end "earlier"
            if te < tb:
                te += 86400
            fl0, fl1 = int(f[6]), int(f[7])
            callsign = f[9]
            lat0, lon0 = float(f[12]) / 60.0, float(f[13]) / 60.0
            lat1, lon1 = float(f[14]) / 60.0, float(f[15]) / 60.0
            fid = f[16]
            seq = int(f[17])
            length = float(f[18])
        except ValueError as e:
            print(f"so6: line {ln}: {e} — skipped", file=sys.stderr)
            continue
        key = f"{callsign}:{fid}"
        fl = flights.setdefault(key, _Flight(actype=actype, t0=tb))
        fl.segs.append((seq, tb, te, fl0, fl1, lat0, lon0, lat1, lon1,
                        length))
    for fl in flights.values():
        fl.segs.sort()
        # Midnight rollover ACROSS segments: walking the flight in
        # sequence order, a start time below the previous one means the
        # clock wrapped — shift the rest of the flight by whole days so
        # the timeline stays monotonic.
        off, prev_tb = 0, None
        segs = []
        for (seq, tb, te, *rest) in fl.segs:
            if prev_tb is not None and tb + off < prev_tb:
                off += 86400
            prev_tb = tb + off
            segs.append((seq, tb + off, te + off, *rest))
        fl.segs = segs
        fl.t0 = fl.segs[0][1]
    return flights


def convert(lines, rel_time: bool = True) -> List[str]:
    """SO6 lines -> scenario lines (``HH:MM:SS.00>CMD``).

    ``rel_time`` rebases the earliest segment start to scenario t=0
    (the usual replay case); False keeps absolute day times.
    """
    from ..ops import hostgeo
    flights = parse_so6(lines)
    if not flights:
        return []
    base = min(fl.t0 for fl in flights.values()) if rel_time else 0
    out: List[Tuple[float, str]] = []
    # SO6 repeats callsigns across flight ids (that is why flights are
    # keyed callsign:flightid) — but CRE needs a unique acid, or the
    # second flight's aircraft silently fails to spawn at replay time.
    # Repeated callsigns get a _2/_3... suffix, first occurrence keeps
    # the bare name; suffixes are checked against BOTH already-emitted
    # acids and every genuine callsign in the file, so a synthetic AB_2
    # can never collide with a real flight named AB_2.
    all_base = {k.split(":")[0] for k in flights}
    used: set = set()
    for key, fl in flights.items():
        cs = key.split(":")[0]
        acid = cs
        k = 2
        while acid in used or (acid != cs and acid in all_base):
            acid = f"{cs}_{k}"
            k += 1
        used.add(acid)
        if acid != cs:
            print(f"so6: duplicate callsign {cs!r} — emitting as "
                  f"{acid}", file=sys.stderr)
        _, tb, te, fl0, fl1, lat0, lon0, lat1, lon1, length = fl.segs[0]
        qdr, dist_nm = hostgeo.qdrdist(lat0, lon0, lat1, lon1)
        dur = max(te - tb, 1)
        gs_kts = (length if length > 0 else float(dist_nm)) * 3600.0 / dur
        t = fl.t0 - base
        out.append((t, f"CRE {acid} {fl.actype} {lat0:.6f} {lon0:.6f} "
                       f"{float(qdr) % 360.0:.1f} FL{fl0:03d} "
                       f"{min(gs_kts, 600.0):.0f}"))
        # route: every segment END becomes a waypoint with its FL (and
        # the segment speed), so VNAV/LNAV fly the profile
        for (_, tb, te, fl0, fl1, lat0, lon0, lat1, lon1,
             length) in fl.segs:
            dur = max(te - tb, 1)
            spd = (length * 3600.0 / dur) if length > 0 else 0.0
            spdarg = f" {min(spd, 600.0):.0f}" if spd > 0 else ""
            out.append((t + 0.01,
                        f"ADDWPT {acid} {lat1:.6f} {lon1:.6f} "
                        f"FL{fl1:03d}{spdarg}"))
        out.append((t + 0.02, f"LNAV {acid} ON"))
        out.append((t + 0.02, f"VNAV {acid} ON"))
    out.sort(key=lambda x: x[0])
    return [f"{_fmt_t(t)}>{cmd}" for t, cmd in out]


def main(argv=None):
    argv = argv if argv is not None else sys.argv[1:]
    if not argv:
        print(__doc__)
        return 1
    src = argv[0]
    dst = argv[1] if len(argv) > 1 else src.rsplit(".", 1)[0] + ".scn"
    with open(src) as f:
        scn = convert(f.readlines())
    with open(dst, "w") as f:
        f.write("\n".join(scn) + "\n")
    nfl = sum(1 for l in scn if ">CRE " in l)
    print(f"so6: {src} -> {dst} ({nfl} flights, {len(scn)} lines)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
