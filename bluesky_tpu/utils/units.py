"""Text-to-SI unit converters (reference tools/misc.py:18-150).

Shared by the stack argument parser AND the core route layer (AT
alt/spd constraint syntax) — pure text -> number helpers with no
dependency on either layer, so neither has to import the other.
"""
import re

from ..ops import aero


def txt2alt(txt: str) -> float:
    """Altitude text -> metres: 'FL200' -> 20000 ft; bare number = feet
    (tools/misc.py:18-38)."""
    t = txt.upper().strip()
    if t.startswith("FL"):
        return float(t[2:]) * 100.0 * aero.ft
    return float(t) * aero.ft


def txt2spd(txt: str) -> float:
    """Speed text -> CAS [m/s] or Mach: 'M.8'/'M08'/'.8' -> 0.8 Mach,
    else knots CAS (tools/misc.py:66-92)."""
    t = txt.upper().strip()
    if t.startswith("M"):
        t = t[1:]
        m = float(t) if "." in t else float("0." + t.lstrip("0") or "0")
        return m
    v = float(t)
    if 0.1 < v < 1.0:
        return v          # Mach
    return v * aero.kts   # knots -> m/s CAS


def txt2vspd(txt: str) -> float:
    """Vertical speed text [fpm] -> m/s."""
    return float(txt) * aero.fpm


def txt2hdg(txt: str) -> float:
    return float(txt) % 360.0


def txt2time(txt: str) -> float:
    """'[HH:]MM:SS[.hh]' or plain seconds -> seconds."""
    parts = txt.strip().split(":")
    if len(parts) == 1:
        return float(parts[0])
    sec = float(parts[-1])
    mins = int(parts[-2]) if len(parts) >= 2 else 0
    hrs = int(parts[-3]) if len(parts) >= 3 else 0
    return hrs * 3600.0 + mins * 60.0 + sec


def txt2lat(txt: str) -> float:
    """Latitude text: decimal or N/S prefix/suffix, DMS with ' " separators."""
    return _txt2deg(txt, "NS")


def txt2lon(txt: str) -> float:
    return _txt2deg(txt, "EW")


def _txt2deg(txt: str, hemis: str) -> float:
    t = txt.upper().strip()
    sign = 1.0
    if t and t[0] in hemis:
        sign = -1.0 if t[0] in "SW" else 1.0
        t = t[1:]
    elif t and t[-1] in hemis:
        sign = -1.0 if t[-1] in "SW" else 1.0
        t = t[:-1]
    if "'" in t or '"' in t or "°" in t:
        parts = re.split(r"[°'\"]+", t)
        parts = [p for p in parts if p]
        deg = float(parts[0])
        minutes = float(parts[1]) if len(parts) > 1 else 0.0
        seconds = float(parts[2]) if len(parts) > 2 else 0.0
        return sign * (deg + minutes / 60.0 + seconds / 3600.0)
    return sign * float(t)
