"""Sim-side plot registry: PLOT x,y,dt streams (x, y, color) series.

Parity with the reference ``tools/plotter.py:15-132``: dotted-name
variable lookup over registered parents, per-plot sample interval,
figure numbering, and a per-chunk update that collects due samples into
stream payloads (``PLOT*`` over ZMQ in node mode; buffered in headless
mode so scripts/tests can read the series directly).

Divergences: variables resolve against the Simulation object tree (no
global singletons) and device arrays are sampled as host copies at chunk
edges; ``sample buffers`` accumulate here instead of relying on a GUI
keeping history.
"""
import re
from collections import defaultdict
from numbers import Number

import numpy as np


def getvarsfromobj(obj):
    """Numeric attributes of an object (plotter.py:48-55)."""
    def is_num(o):
        return isinstance(o, Number) or \
            (isinstance(o, np.ndarray) and o.dtype.kind not in "OSUV")
    try:
        d = vars(obj)
    except TypeError:
        return (obj, [])
    names = []
    for name, val in d.items():
        if hasattr(val, "dtype") or isinstance(val, Number):
            names.append(name)
    return (obj, names)


class Variable:
    def __init__(self, parent, varname, index):
        self.parent = parent
        self.varname = varname
        try:
            self.index = [int(index)] if index else []
        except (ValueError, TypeError):
            self.index = []

    def get(self):
        val = getattr(self.parent, self.varname)
        val = np.asarray(val) if hasattr(val, "dtype") else val
        if self.index:
            return val[tuple(self.index)]
        return val


class Plot:
    """One registered plot (plotter.py:93-132)."""

    def __init__(self, plotter, varx="", vary="", dt=1.0, color=None,
                 fig=None):
        self.x = plotter.findvar(varx if vary else "simt")
        self.y = plotter.findvar(vary or varx)
        self.dt = float(dt)
        self.tnext = plotter.sim.simt
        self.color = color
        if fig is None:
            fig = plotter.maxfig
            plotter.maxfig += 1
        elif fig > plotter.maxfig:
            plotter.maxfig = fig
        self.fig = fig
        self.series = ([], [])          # headless sample history
        if None in (self.x, self.y):
            raise IndexError("Variable %s not found"
                             % (varx if self.x is None else vary))


class Plotter:
    """Per-Simulation plot registry + chunk-edge updater."""

    def __init__(self, sim):
        self.sim = sim
        self.plots = []
        self.maxfig = 0
        self.varlist = {}
        self._extra_parents = {}        # survive refresh_sources()
        self.stream_hook = None         # node mode: send_stream callable
        self.refresh_sources()

    def refresh_sources(self):
        """Register the default variable parents (plotter.py:15-23):
        the sim itself, the traffic facade, and the state arrays."""
        sim = self.sim
        st = sim.traf.state
        self.varlist = {
            "sim": (sim, ["simt", "simdt"]),
            "traf": getvarsfromobj(st.ac),
            "ac": getvarsfromobj(st.ac),
            "asas": getvarsfromobj(st.asas),
            "perf": getvarsfromobj(st.perf),
        }
        # re-resolve registered extra parents (metrics, plugins) so
        # their attribute lists stay current across state replacements
        for name, obj in self._extra_parents.items():
            self.varlist[name] = getvarsfromobj(obj)

    def register_data_parent(self, obj, name):
        self._extra_parents[name] = obj
        self.varlist[name] = getvarsfromobj(obj)

    def findvar(self, varname):
        """Resolve 'name' or 'parent.name[idx]' (plotter.py:57-88)."""
        try:
            varset = re.findall(r"(\w+)(?:\[(\w+)\])?", varname.lower())
            name, index = varset[-1]
            if len(varset) > 1:
                entry = self.varlist.get(varset[0][0])
                if entry is None:
                    return None
                obj = entry[0]
                for pair in varset[1:-1]:
                    if obj is None:
                        return None
                    obj = getattr(obj, pair[0], None)
                if obj is not None and hasattr(obj, name):
                    return Variable(obj, name, index)
            else:
                for el in self.varlist.values():
                    if name in el[1]:
                        return Variable(el[0], name, index)
                if hasattr(self.sim, name):
                    return Variable(self.sim, name, index)
        except (AttributeError, IndexError):
            pass
        return None

    # ------------------------------------------------------------ stack
    def plot(self, *args):
        """PLOT [x],y,[dt],[color] (plotter.py:26-34)."""
        try:
            # State arrays are replaced pytrees: re-resolve parents so
            # plots bind to the current arrays
            self.refresh_sources()
            self.plots.append(Plot(self, *args))
            return True
        except IndexError as e:
            return False, e.args[0]

    # ----------------------------------------------------------- update
    def update(self, simt):
        """Collect due samples; buffer and/or stream (plotter.py:36-45)."""
        if not self.plots:
            return
        self.refresh_sources()
        streamdata = defaultdict(dict)
        for p in self.plots:
            if p.tnext <= simt + 1e-9:
                p.tnext += p.dt
                # Re-bind to the live state arrays before sampling
                p.x.parent, p.y.parent = self._rebind(p.x), self._rebind(p.y)
                xval = np.asarray(p.x.get()).tolist()
                yval = np.asarray(p.y.get()).tolist()
                p.series[0].append(xval)
                p.series[1].append(yval)
                streamdata[b"PLOT"][p.fig] = (xval, yval, p.color)
        if self.stream_hook is not None:
            for streamname, data in streamdata.items():
                self.stream_hook(streamname, data)

    def _rebind(self, var):
        """State pytrees are replaced every chunk: find the same-named
        array on the current state if the old parent was one."""
        st = self.sim.traf.state
        for part in (st.ac, st.asas, st.perf):
            if hasattr(part, var.varname) and type(part) is type(var.parent):
                return part
        return var.parent

    def reset(self):
        self.plots = []
        self.maxfig = 0
