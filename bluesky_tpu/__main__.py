"""Entry point / mode dispatch (parity: BlueSky.py:28-119).

Modes:
  (default) / --headless   start a Server broker that spawns sim workers
  --sim                    run one sim worker node (spawned by the server)
  --detached               run an embedded sim with no networking
  --client                 interactive console client (text UI)

Example headless session:
  python -m bluesky_tpu --headless &
  python -m bluesky_tpu --client
  > CRE KL204 B744 52 4 90 FL200 250
  > OP
"""
import argparse
import os
import sys

from . import settings


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="bluesky_tpu", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    mode = parser.add_mutually_exclusive_group()
    mode.add_argument("--headless", action="store_true",
                      help="server + workers, no UI")
    mode.add_argument("--sim", action="store_true", help="one sim worker")
    mode.add_argument("--detached", action="store_true",
                      help="embedded sim, no networking")
    mode.add_argument("--client", action="store_true",
                      help="console client")
    mode.add_argument("--web", action="store_true",
                      help="embedded sim + live browser radar UI")
    parser.add_argument("--config-file", default="", help="settings file")
    parser.add_argument("--scenfile", default="", help="startup scenario")
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--event-port", type=int, default=None)
    parser.add_argument("--stream-port", type=int, default=None)
    parser.add_argument("--discoverable", action="store_true")
    parser.add_argument("--web-port", type=int, default=8080,
                        help="port for --web mode")
    parser.add_argument("--attach", action="store_true",
                        help="with --web: attach the browser UI to a "
                             "running server (GuiClient mirror) instead "
                             "of embedding a sim; --host/--event-port/"
                             "--stream-port select the server")
    parser.add_argument("--node-id", default="",
                        help="hex worker id assigned by the spawning "
                             "server (crash tracking)")
    parser.add_argument("--upstream", default="",
                        help="chain this server under another: host:port "
                             "of the upstream server's client event port")
    parser.add_argument("--standby", action="store_true",
                        help="broker HA: start the server as a warm "
                             "standby that tails the shared journal "
                             "(point --resume-batch at the leader's "
                             "journal) and takes over leadership "
                             "automatically when the leader's lease "
                             "goes stale")
    parser.add_argument("--resume-batch", default="", metavar="JOURNAL",
                        help="replay a BATCH journal (JSONL WAL) from a "
                             "crashed/preempted server: completed pieces "
                             "are not re-run, in-flight pieces are "
                             "requeued, quarantine decisions persist; "
                             "new records append to the same journal")
    parser.add_argument("--import-navdata", default="", metavar="DIR",
                        help="import a reference-format navdata directory "
                             "(fix.dat/nav.dat/airports.dat/awy.dat/fir/"
                             "apt.zip) into the local cache and exit; the "
                             "imported set is used automatically whenever "
                             "no navdata mount is configured")
    parser.add_argument("--dest", default="",
                        help="with --import-navdata: destination directory "
                             "(default: <cache>/navdata)")
    args = parser.parse_args(argv)
    if args.attach and not args.web:
        parser.error("--attach only applies to --web "
                     "(use: bluesky-tpu --web --attach [--host H])")

    settings.init(args.config_file)

    if args.import_navdata:
        return run_import_navdata(args)
    if args.sim:
        return run_sim(args)
    if args.detached:
        return run_detached(args)
    if args.client:
        return run_client(args)
    if args.web:
        return run_web(args)
    return run_server(args)


def run_import_navdata(args):
    """Import a reference-format navdata tree into the local cache
    (VERDICT r4 #9: one-command full-world data for standalone
    deployments; source format per the reference
    navdatabase/load_navdata_txt.py — see navdb/loaders.py).

    Copies the recognized sources to ``--dest`` (default
    settings.imported_navdata_path), parses them once to warm the
    pickle cache, and prints what was loaded.  settings picks the
    imported tree up automatically when no mount is configured."""
    import shutil
    from .navdb.loaders import load_navdata

    src = args.import_navdata
    if not os.path.isdir(src):
        print(f"--import-navdata: {src!r} is not a directory",
              file=sys.stderr)
        return 1
    names = ("fix.dat", "nav.dat", "airports.dat", "awy.dat",
             "icao-countries.dat", "apt.zip")
    present = [n for n in names if os.path.isfile(os.path.join(src, n))]
    has_fir = os.path.isdir(os.path.join(src, "fir"))
    if not present and not has_fir:
        print(f"--import-navdata: no recognized navdata files under "
              f"{src!r} (expected any of {', '.join(names)} or fir/)",
              file=sys.stderr)
        return 1

    dest = args.dest or settings.imported_navdata_path
    os.makedirs(dest, exist_ok=True)
    # A re-import REPLACES the previous one: recognized files/dirs the
    # new source does not provide are removed, so the destination is
    # always a faithful copy of ONE source (a silent A+B mix would make
    # the summary counts, and the sim's world, represent neither).
    for n in names:
        if n not in present and os.path.isfile(os.path.join(dest, n)):
            os.remove(os.path.join(dest, n))
            print(f"  removed stale {n}")
    if os.path.isdir(os.path.join(dest, "fir")):
        shutil.rmtree(os.path.join(dest, "fir"))
        if not has_fir:
            # match the per-file removal messages: a re-import from a
            # source without fir/ must say it dropped the old FIRs
            print("  removed stale fir/")
    for n in present:
        shutil.copy2(os.path.join(src, n), os.path.join(dest, n))
        print(f"  copied {n}")
    if has_fir:
        shutil.copytree(os.path.join(src, "fir"),
                        os.path.join(dest, "fir"))
        print("  copied fir/")

    data = load_navdata(dest, cache_path=settings.cache_path)
    print(f"imported navdata -> {dest}: "
          f"{len(data['wpid'])} waypoints, {len(data['aptid'])} airports, "
          f"{len(data['awid'])} airway legs, {len(data['firs'])} FIRs, "
          f"{len(data.get('rwythresholds', {}))} airports with runway "
          "thresholds (cache warmed)")
    if dest != settings.imported_navdata_path:
        print(f"note: set `navdata_path = {dest!r}` in your settings file "
              "to use a non-default destination")
    return 0


def run_server(args):
    import signal

    from .network.server import Server
    ports = {}
    if args.event_port:
        ports["event"] = args.event_port
    if args.stream_port:
        ports["stream"] = args.stream_port
    upstream = None
    if args.upstream:
        host, _, port = args.upstream.rpartition(":")
        upstream = (host or "127.0.0.1", int(port))
    server = Server(headless=True, discoverable=args.discoverable,
                    ports=ports, max_nnodes=settings.max_nnodes,
                    upstream=upstream,
                    resume_journal=args.resume_batch or None,
                    ha_role="standby" if args.standby else None)
    role = f" [{server.ha_role}]" if server.ha_role else ""
    print(f"bluesky_tpu server{role}: clients on "
          f"{server.ports['event']}/{server.ports['stream']}, workers on "
          f"{server.ports['wevent']}/{server.ports['wstream']}")
    if server.journal:
        print(f"bluesky_tpu server: BATCH journal at "
              f"{server.journal.path}")
    # preemption-safe shutdown: SIGTERM (scheduler reclaim) drains the
    # broker loop, QUITs the workers, journals the clean-exit marker
    # and leaves — the journal then resumes the sweep on the next start
    signal.signal(signal.SIGTERM, lambda signum, frame: server.stop())
    server.start()
    server.addnodes(1)
    try:
        # timed-join loop, not a bare join(): an unbounded join can sit
        # in an uninterruptible wait and starve the SIGTERM handler —
        # waking every second guarantees prompt preemption shutdown
        while server.is_alive():
            server.join(timeout=1.0)
    except KeyboardInterrupt:
        server.stop()
        server.join(timeout=5)
    return 0


def _start_telnet(sim):
    """Raw-TCP stack bridge on settings.telnet_port (the reference's
    StackTelnetServer, enabled for sim nodes; tools/network.py:151-184)."""
    if not settings.telnet_port:
        return
    from .network.tcpserver import StackTelnetServer
    try:
        sim.telnet = StackTelnetServer(sim, port=settings.telnet_port)
        sim.telnet.start()
        print(f"Telnet stack bridge on port {sim.telnet.port}")
    except OSError as e:
        print(f"Telnet bridge not started: {e}")
        sim.telnet = None


def run_sim(args):
    from .simulation.simnode import SimNode
    node = SimNode(event_port=args.event_port,
                   stream_port=args.stream_port,
                   node_id=bytes.fromhex(args.node_id)
                   if args.node_id else None)
    _start_telnet(node.sim)
    if args.scenfile:
        node.sim.stack.ic(args.scenfile)
    node.run()
    return 0


def run_detached(args):
    from .simulation.simnode import DetachedSimNode
    node = DetachedSimNode()
    _start_telnet(node.sim)
    if args.scenfile:
        node.sim.stack.ic(args.scenfile)
    node.run()
    return 0


def run_web(args):
    """Live browser radar (ui/web.py): embedded sim by default, or —
    with --attach — a GuiClient mirror of a running server (the same
    split as the reference's embedded pygame vs networked Qt radar)."""
    if args.attach:
        import time
        from .network.guiclient import GuiClient
        from .ui.web import ClientBackend, WebUI
        client = GuiClient()
        client.connect(host=args.host,
                       event_port=args.event_port or settings.event_port,
                       stream_port=args.stream_port
                       or settings.stream_port)
        backend = ClientBackend(client, pumped=True)
        backend.pump()           # seed the frame cache pre-serving
        ui = WebUI(backend, host="127.0.0.1",
                   port=args.web_port).start()
        print(f"bluesky_tpu web UI (attached to {args.host}) on "
              f"http://{ui.host}:{ui.port}/")
        try:
            while True:
                backend.pump()               # drain streams/events
                time.sleep(0.02)
        except KeyboardInterrupt:
            pass
        finally:
            ui.stop()
            client.close()
        return 0
    from .simulation.sim import Simulation
    from .ui.web import serve_sim
    sim = Simulation()
    _start_telnet(sim)
    if args.scenfile:
        sim.stack.ic(args.scenfile)
    serve_sim(sim, host=args.host, port=args.web_port)
    return 0


def run_client(args):
    """Minimal text console: lines -> STACKCMD, ECHO/SIMINFO printed."""
    from .network.client import Client
    client = Client()
    client.connect(host=args.host,
                   event_port=args.event_port or settings.event_port,
                   stream_port=args.stream_port or settings.stream_port)
    client.subscribe(b"SIMINFO")

    def on_event(name, data, sender):
        if name in (b"ECHO", b"HEALTH", b"HA"):
            print(data.get("text", data) if isinstance(data, dict)
                  else data)
        elif name == b"BATCHREJECTED":
            d = data or {}
            print(f"BATCH rejected: queue {d.get('queue_depth', '?')}/"
                  f"{d.get('limit', '?')} full — retry in "
                  f"{d.get('retry_after', '?')} s")
    client.event_received.connect(on_event)
    print(f"connected to {client.host_id.hex()}; "
          f"{len(client.nodes)} node(s). Ctrl-D to quit.")
    try:
        while True:
            client.receive(10)
            line = input("> ").strip()
            if not line:
                continue
            if line.upper() in ("QUIT", "EXIT", "BYE"):
                break
            if line.upper() == "HEALTH":
                # fabric-level introspection is answered by the SERVER,
                # not the active sim node
                client.request_health()
            else:
                client.stack(line)
            # give the reply a moment to arrive
            for _ in range(20):
                if client.receive(25):
                    break
    except (EOFError, KeyboardInterrupt):
        pass
    client.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
