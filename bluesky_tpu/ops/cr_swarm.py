"""Swarm conflict resolution: MVP avoidance + alignment + flock centering.

Parity with the reference ``traffic/asas/Swarm.py:23-103``: neighbors
within 7.5 nm / 1500 ft flying within 90 deg of the own track form the
swarm; the commanded velocity blends three parts with weights [10, 3, 1]:
Collision Avoidance (the MVP resolution, or the autopilot command when
not in conflict), Velocity Alignment (swarm-weighted averages of speed /
vertical speed / track difference), and Flock Centering (velocity toward
the swarm centroid).  All aircraft become ASAS-active (Swarm.py:101-102).

The reference is already matrix-formed NumPy; the port keeps the same
masked-average algebra in jnp.  The reference's stale ``asas.u``/
``asas.v`` diagonal terms (the attribute no longer exists upstream —
bit-rot noted in SURVEY §2.2) are taken as the ownship velocity
components, which is what the flock-centering geometry calls for.
"""
import jax.numpy as jnp

from . import aero

R_SWARM = 7.5 * aero.nm      # [m] swarm neighbourhood (Swarm.py start())
DH_SWARM = 1500.0 * aero.ft  # [m]
WEIGHTS = (10.0, 3.0, 1.0)   # CA / alignment / centering


def _wavg(x, w):
    """np.average(x, axis=1, weights=w) with all-zero-row guard."""
    den = jnp.sum(w, axis=1)
    den = jnp.where(den == 0.0, 1.0, den)
    return jnp.sum(x * w, axis=1) / den


def pair_weight(dx, dy, dalt, dtrk, pairok):
    """Swarm-neighbour weight for one pair (Swarm.py:47-58, 65-66):
    within 7.5 nm / 1500 ft, flying within 90 deg of the own track.
    ``dtrk`` must already be wrapped to (-180, 180].  Shape-agnostic —
    shared by the dense matrix path and the tiled backend."""
    close = (dx * dx + dy * dy < R_SWARM * R_SWARM) \
        & (jnp.abs(dalt) < DH_SWARM) & pairok
    return close & (jnp.abs(dtrk) < 90.0)


def resolve_from_sums(sw_w, sw_cas, sw_vs, sw_dtrk, sw_dx, sw_dy, sw_alt,
                      alt, trk, cas, vs, gseast, gsnorth, active,
                      mvp_trk, mvp_tas, mvp_vs, mvp_active,
                      ap_trk, selspd, selvs, vmin, vmax):
    """Swarm commands from per-ownship neighbour sums (the tiled backend
    accumulates them blockwise; the reference's diagonal self-terms —
    Swarm.py:53-58: w=1, dtrk=0, flock dx/dy = own velocity/100 — are
    folded in here so the kernels never special-case the diagonal)."""
    selfw = active.astype(cas.dtype)
    den = sw_w + selfw
    den = jnp.where(den == 0.0, 1.0, den)

    # Velocity alignment (Swarm.py:75-84); self terms: cas/vs own, dtrk 0
    va_cas = (sw_cas + selfw * cas) / den
    va_vs = (sw_vs + selfw * vs) / den
    va_trk = trk + sw_dtrk / den

    # Flock centering (Swarm.py:86-97); self terms: own velocity / 100
    fc_dx = (sw_dx + selfw * gseast / 100.0) / den
    fc_dy = (sw_dy + selfw * gsnorth / 100.0) / den
    fc_dz = (sw_alt + selfw * alt) / den - alt
    fc_trk = jnp.degrees(jnp.arctan2(fc_dx, fc_dy))
    fc_cas = cas
    cas_safe = jnp.where(cas == 0.0, 1.0, cas)
    ttoreach = jnp.sqrt(fc_dx * fc_dx + fc_dy * fc_dy) / cas_safe
    fc_vs = jnp.where(ttoreach == 0.0, 0.0,
                      fc_dz / jnp.where(ttoreach == 0.0, 1.0, ttoreach))

    # Collision avoidance part: MVP output where ASAS-active, else AP
    ca_trk = jnp.where(mvp_active, mvp_trk, ap_trk)
    ca_cas = jnp.where(mvp_active, mvp_tas, selspd)
    ca_vs = jnp.where(mvp_active, mvp_vs, selvs)

    # Blend the three parts in cartesian velocity space (Swarm.py:99-110)
    wsum = sum(WEIGHTS)

    def blend(a, b, c):
        return (WEIGHTS[0] * a + WEIGHTS[1] * b + WEIGHTS[2] * c) / wsum

    trks = [ca_trk, va_trk, fc_trk]
    cass = [ca_cas, va_cas, fc_cas]
    vxs = [c * jnp.sin(jnp.radians(t)) for t, c in zip(trks, cass)]
    vys = [c * jnp.cos(jnp.radians(t)) for t, c in zip(trks, cass)]
    newtrk = jnp.degrees(jnp.arctan2(blend(*vxs), blend(*vys))) % 360.0
    newcas = blend(ca_cas, va_cas, fc_cas)
    newvs = blend(ca_vs, va_vs, fc_vs)
    newtas = jnp.clip(newcas, vmin, vmax)
    newalt = jnp.sign(newvs) * 1e5
    return newtrk, newtas, newvs, newalt


def resolve(cd, lat, lon, alt, trk, gs, cas, vs, gseast, gsnorth,
            active,
            mvp_trk, mvp_tas, mvp_vs, mvp_active,
            ap_trk, selspd, selvs,
            vmin, vmax):
    """Swarm resolution commands.

    Args:
      cd:          ConflictData (for the qdr/dist matrices)
      lat..gsnorth: [N] state arrays; ``cas`` the calibrated speed
      active:      [N] live-aircraft mask (padding exclusion)
      mvp_*:       the MVP resolution output + its active flags (Swarm
                   runs MVP first, Swarm.py:68)
      ap_trk/selspd/selvs: autopilot commands for non-conflict aircraft
      vmin/vmax:   speed caps
    Returns (newtrk, newtas, newvs, newalt) for every aircraft.
    """
    n = lat.shape[0]
    eye = jnp.eye(n, dtype=bool)

    # Neighbour matrix (Swarm.py:47-58); the reference subtracts 1e9
    # from dy to kill the self-pair — here the eye mask does it.
    qdrrad = jnp.radians(cd.qdr)
    dx = cd.dist * jnp.sin(qdrrad)
    dy = cd.dist * jnp.cos(qdrrad)
    dalt = alt[:, None] - alt[None, :]
    pairok = (active[:, None] & active[None, :]) & ~eye
    trkdif = trk[None, :] - trk[:, None]
    dtrk = (trkdif + 180.0) % 360.0 - 180.0
    swarming = pair_weight(dx, dy, dalt, dtrk, pairok) \
        | (eye & active[:, None])
    w = swarming.astype(gs.dtype)

    # Collision avoidance part: MVP output where ASAS-active, else AP
    # (Swarm.py:70-73)
    ca_trk = jnp.where(mvp_active, mvp_trk, ap_trk)
    ca_cas = jnp.where(mvp_active, mvp_tas, selspd)
    ca_vs = jnp.where(mvp_active, mvp_vs, selvs)

    # Velocity alignment (Swarm.py:75-84)
    va_cas = _wavg(jnp.broadcast_to(cas[None, :], (n, n)), w)
    va_vs = _wavg(jnp.broadcast_to(vs[None, :], (n, n)), w)
    va_trk = trk + _wavg(dtrk, w)

    # Flock centering (Swarm.py:86-97): own velocity/100 on the diagonal
    dxflock = jnp.where(eye, gseast[:, None] / 100.0, dx)
    dyflock = jnp.where(eye, gsnorth[:, None] / 100.0, dy)
    fc_dx = _wavg(dxflock, w)
    fc_dy = _wavg(dyflock, w)
    fc_dz = _wavg(jnp.broadcast_to(alt[None, :], (n, n)), w) - alt
    fc_trk = jnp.degrees(jnp.arctan2(fc_dx, fc_dy))
    fc_cas = cas
    cas_safe = jnp.where(cas == 0.0, 1.0, cas)
    ttoreach = jnp.sqrt(fc_dx * fc_dx + fc_dy * fc_dy) / cas_safe
    fc_vs = jnp.where(ttoreach == 0.0, 0.0,
                      fc_dz / jnp.where(ttoreach == 0.0, 1.0, ttoreach))

    # Blend the three parts in cartesian velocity space (Swarm.py:99-110)
    wsum = sum(WEIGHTS)
    def blend(a, b, c):
        return (WEIGHTS[0] * a + WEIGHTS[1] * b + WEIGHTS[2] * c) / wsum
    trks = [ca_trk, va_trk, fc_trk]
    cass = [ca_cas, va_cas, fc_cas]
    vxs = [c * jnp.sin(jnp.radians(t)) for t, c in zip(trks, cass)]
    vys = [c * jnp.cos(jnp.radians(t)) for t, c in zip(trks, cass)]
    swarm_vx = blend(*vxs)
    swarm_vy = blend(*vys)
    newtrk = jnp.degrees(jnp.arctan2(swarm_vx, swarm_vy)) % 360.0
    newcas = blend(ca_cas, va_cas, fc_cas)
    newvs = blend(ca_vs, va_vs, fc_vs)

    newtas = jnp.clip(newcas, vmin, vmax)
    newalt = jnp.sign(newvs) * 1e5
    return newtrk, newtas, newvs, newalt
