"""Host-side geodesy: compiled core when built, NumPy otherwise.

The DEVICE hot path is ops/geo.py under XLA; this module serves the
HOST-side consumers (navdb nearest queries, landing checks, scenario
tooling, plugins) that the reference serves with its compiled cgeo
extension (bluesky/tools/src_cpp/cgeo.cpp, selected by
settings.prefer_compiled).  The public surface mirrors ops/geo.py's 12
functions; this wrapper owns all broadcasting and the scalar/matrix
conventions, handing the C core (src_cpp/cgeo.cpp) flat float64 arrays.

Build:  cd bluesky_tpu/src_cpp && python setup.py build_ext --inplace
"""
import glob
import importlib.util
import os

import numpy as np

nm = 1852.0
A_WGS84 = 6378137.0
B_WGS84 = 6356752.314245
REARTH = 6371000.0


def _load_ccore():
    """Load the built _cgeo extension by file path — no sys.path
    mutation (src_cpp also holds setup.py, which must never shadow a
    top-level ``setup`` import)."""
    src = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "src_cpp")
    for so in glob.glob(os.path.join(src, "_cgeo*.so")):
        try:
            spec = importlib.util.spec_from_file_location("_cgeo", so)
            mod = importlib.util.module_from_spec(spec)
            spec.loader.exec_module(mod)
            return mod
        except ImportError:
            continue
    return None


_ccore = _load_ccore()
compiled = _ccore is not None


def _flat(*args):
    """Broadcast args to one shape; return flat f64 arrays + shape +
    scalar-ness."""
    arrs = np.broadcast_arrays(*[np.asarray(a, np.float64) for a in args])
    shape = arrs[0].shape
    return [np.ascontiguousarray(a).ravel() for a in arrs], shape


def _unflat(flatval, shape):
    out = np.asarray(flatval).reshape(shape)
    return float(out) if shape == () else out


# ------------------------------------------------------------ NumPy core
def _np_rwgs84(latd):
    lat = np.radians(latd)
    coslat, sinlat = np.cos(lat), np.sin(lat)
    an = A_WGS84 * A_WGS84 * coslat
    bn = B_WGS84 * B_WGS84 * sinlat
    ad = A_WGS84 * coslat
    bd = B_WGS84 * sinlat
    return np.sqrt((an * an + bn * bn) / (ad * ad + bd * bd))


def _np_mean_radius(lat1, lat2, mode):
    r1, r2 = _np_rwgs84(lat1), _np_rwgs84(lat2)
    if mode == 0:
        res1 = _np_rwgs84(0.5 * (lat1 + lat2))
        denom = np.maximum(np.abs(lat1) + np.abs(lat2), 1e-30)
        res2 = 0.5 * (np.abs(lat1) * (r1 + A_WGS84)
                      + np.abs(lat2) * (r2 + A_WGS84)) / denom
        return np.where(lat1 * lat2 >= 0.0, res1, res2)
    res1 = _np_rwgs84(lat1 + lat2)
    denom = np.abs(lat1) + np.abs(lat2) + np.where(lat1 == 0.0, 1e-6, 0.0)
    res2 = 0.5 * (np.abs(lat1) * (r1 + A_WGS84)
                  + np.abs(lat2) * (r2 + A_WGS84)) / denom
    return np.where(lat1 * lat2 < 0.0, res2, res1)


def _np_qdrdist(lat1d, lon1d, lat2d, lon2d, mode):
    r = _np_mean_radius(lat1d, lat2d, mode)
    lat1, lon1 = np.radians(lat1d), np.radians(lon1d)
    lat2, lon2 = np.radians(lat2d), np.radians(lon2d)
    s1 = np.sin(0.5 * (lat2 - lat1))
    s2 = np.sin(0.5 * (lon2 - lon1))
    c1, c2 = np.cos(lat1), np.cos(lat2)
    root = s1 * s1 + c1 * c2 * s2 * s2
    d = 2.0 * r * np.arctan2(np.sqrt(root), np.sqrt(1.0 - root))
    qdr = np.degrees(np.arctan2(
        np.sin(lon2 - lon1) * c2,
        c1 * np.sin(lat2) - np.sin(lat1) * c2 * np.cos(lon2 - lon1)))
    return qdr, d


def _np_kwik(lat1, lon1, lat2, lon2):
    dlat = np.radians(lat2 - lat1)
    dlon = np.radians(lon2 - lon1)
    cav = np.cos(np.radians(lat1 + lat2) * 0.5)
    dist = REARTH * np.sqrt(dlat * dlat + dlon * dlon * cav * cav)
    qdr = np.degrees(np.arctan2(dlon * cav, dlat)) % 360.0
    return qdr, dist


# ------------------------------------------------------------- public API
def rwgs84(latd):
    flat, shape = _flat(latd)
    out = _ccore.rwgs84(flat[0]) if compiled else _np_rwgs84(flat[0])
    return _unflat(out, shape)


def wgsg(latd):
    flat, shape = _flat(latd)
    if compiled:
        out = _ccore.wgsg(flat[0])
    else:
        s = np.sin(np.radians(flat[0]))
        out = 9.7803 * (1.0 + 0.001932 * s * s) \
            / np.sqrt(1.0 - 6.694e-3 * s * s)
    return _unflat(out, shape)


def _qdrdist_core(lat1, lon1, lat2, lon2, mode):
    flat, shape = _flat(lat1, lon1, lat2, lon2)
    if compiled:
        q, d = _ccore.qdrdist(*flat, mode)
    else:
        q, d = _np_qdrdist(*flat, mode)
    return _unflat(q, shape), _unflat(d, shape)


def qdrdist(lat1, lon1, lat2, lon2):
    """Bearing [deg], distance [nm] (scalar mean-radius semantics)."""
    q, d = _qdrdist_core(lat1, lon1, lat2, lon2, 0)
    return q, d / nm


def latlondist(lat1, lon1, lat2, lon2):
    """Distance [m] (scalar semantics)."""
    return _qdrdist_core(lat1, lon1, lat2, lon2, 0)[1]


def qdrdist_matrix(lat1, lon1, lat2, lon2):
    """All-pairs bearing [deg] / distance [nm] (matrix radius quirk)."""
    q, d = _qdrdist_core(np.asarray(lat1)[:, None], np.asarray(lon1)[:, None],
                         np.asarray(lat2)[None, :], np.asarray(lon2)[None, :],
                         1)
    return q, d / nm


def latlondist_matrix(lat1, lon1, lat2, lon2):
    """All-pairs distance [nm] (reference returns nm here)."""
    return qdrdist_matrix(lat1, lon1, lat2, lon2)[1]


def qdrpos(lat1, lon1, qdr, dist):
    """Project position: bearing [deg] + distance [nm] -> lat2, lon2."""
    flat, shape = _flat(lat1, lon1, qdr, dist)
    if compiled:
        la, lo = _ccore.qdrpos(*flat)
    else:
        R = _np_rwgs84(flat[0]) / nm
        lat1r, lon1r = np.radians(flat[0]), np.radians(flat[1])
        dr, qdrr = flat[3] / R, np.radians(flat[2])
        lat2 = np.arcsin(np.sin(lat1r) * np.cos(dr)
                         + np.cos(lat1r) * np.sin(dr) * np.cos(qdrr))
        lon2 = lon1r + np.arctan2(
            np.sin(qdrr) * np.sin(dr) * np.cos(lat1r),
            np.cos(dr) - np.sin(lat1r) * np.sin(lat2))
        la, lo = np.degrees(lat2), np.degrees(lon2)
    return _unflat(la, shape), _unflat(lo, shape)


def _kwik_core(lat1, lon1, lat2, lon2):
    flat, shape = _flat(lat1, lon1, lat2, lon2)
    q, d = _ccore.kwik(*flat) if compiled else _np_kwik(*flat)
    return _unflat(q, shape), _unflat(d, shape)


def kwikdist(lat1, lon1, lat2, lon2):
    """Flat-earth distance [nm]."""
    return _kwik_core(lat1, lon1, lat2, lon2)[1] / nm


def kwikdist_matrix(lat1, lon1, lat2, lon2):
    return kwikdist(np.asarray(lat1)[:, None], np.asarray(lon1)[:, None],
                    np.asarray(lat2)[None, :], np.asarray(lon2)[None, :])


def kwikdist_wrapped(lat1, lon1, lat2, lon2):
    """Flat-earth distance [nm] with the longitude difference wrapped to
    [-180, 180) — the antimeridian-safe variant host consumers use
    (ops/geo.kwikdist_wrapped)."""
    lon1 = np.asarray(lon1, np.float64)
    lon2w = lon1 + (((np.asarray(lon2, np.float64) - lon1) + 180.0)
                    % 360.0 - 180.0)
    return kwikdist(lat1, lon1, lat2, lon2w)


def kwikqdrdist(lat1, lon1, lat2, lon2):
    """Flat-earth bearing [deg, 0..360) and distance [m] (NB: metres,
    like the reference)."""
    return _kwik_core(lat1, lon1, lat2, lon2)


def kwikqdrdist_matrix(lat1, lon1, lat2, lon2):
    return kwikqdrdist(np.asarray(lat1)[:, None], np.asarray(lon1)[:, None],
                       np.asarray(lat2)[None, :], np.asarray(lon2)[None, :])
