"""Pure jitted math ops: geodesy, atmosphere, conflict detection/resolution."""
