"""Legacy/BADA performance kernels: flight phases, energy-share factor,
envelope limits.

Elementwise jnp parity with the reference
``traffic/performance/legacy/performance.py`` (phases :45-144, esf
:155-211, calclimits :214-268), shared by the BS legacy model and BADA —
the reference imports the same three helpers in both
(``legacy/perfbs.py``, ``bada/perfbada.py``).

All functions are pure elementwise array math over the padded aircraft
axis — they fuse into the scanned step like the rest of the pipeline.
The reference's ``np.where(...)`` index assignments become masked
selects; outputs are bit-comparable against the reference on float64.
"""
import jax.numpy as jnp

from . import aero

# Phase codes (performance.py:25-33)
PHASE_NONE, PHASE_TO, PHASE_IC, PHASE_CR, PHASE_AP, PHASE_LD, PHASE_GD = \
    range(7)


def phases(alt, gs, delalt, cas, vmto, vmic, vmap, vmcr, vmld, bank,
           bphase, swhdgsel, bada=False):
    """Flight-phase classification + nominal bank angle per phase.

    Parity: performance.py:45-144.  ``bphase`` is the [6] per-phase bank
    table; returns (phase int32 [N], bank [N]).
    """
    ft, kts = aero.ft, aero.kts
    to = (alt < 400.0 * ft) & (gs > 30.0 * kts) & (delalt >= 0.0)
    ic = (alt >= 400.0 * ft) & (alt < 2000.0 * ft) & (delalt > 0.0)

    cra = (alt >= 2000.0 * ft) & (delalt >= 0.0)
    crb = alt > 8000.0 * ft
    crc = (alt <= 8000.0 * ft) & (delalt <= 0.0) \
        & (cas >= vmcr + 10.0 * kts)
    cr = cra | crb | crc

    apa = (alt > ft) & (alt <= 8000.0 * ft) & (cas < vmcr + 10.0 * kts) \
        & (delalt <= 0.0)
    if bada:
        abspd = (cas >= vmap + 10.0 * kts) & (cas < vmcr + 10.0 * kts)
    else:
        abspd = cas >= vmap + 10.0 * kts
    apb = (alt > ft) & (alt <= 3000.0 * ft) & abspd & (delalt <= 0.0)
    ap = apa | apb

    if bada:
        lspd = cas < vmap + 10.0 * kts
    else:
        lspd = gs >= 30.0 * kts
    ld = (alt <= 3000.0 * ft) & lspd & (delalt <= 0.0)

    gd = alt <= ft

    # maximum.reduce over the numbered phases (performance.py:122-124)
    phase = jnp.max(jnp.stack([
        to * PHASE_TO, ic * PHASE_IC, ap * PHASE_AP,
        ld * PHASE_LD, cr * PHASE_CR, gd * PHASE_GD]), axis=0)
    phase = phase.astype(jnp.int32)

    bank_tbl = jnp.asarray(bphase)
    bank = jnp.where(phase > 0, bank_tbl[jnp.maximum(phase - 1, 0)], bank)
    # non-turning aircraft: no bank (performance.py:140-142)
    noturn = jnp.where(swhdgsel, 100.0, 0.0)
    bank = jnp.minimum(noturn, bank)
    return phase, bank


def esf(abco, belco, alt, mach, climb, descent, delspd):
    """Energy-share factor (BADA 3.12 manual p.15; performance.py:155-211).

    abco/belco: above/below crossover altitude flags; climb/descent:
    vertical intent flags; delspd: commanded speed change.
    """
    gamma, gamma1, gamma2 = aero.gamma, aero.gamma1, aero.gamma2
    R, beta, g0 = aero.R, aero.beta, aero.g0
    m2 = mach * mach

    cspd = delspd == 0.0
    acc = delspd > 0.0
    dec = delspd < 0.0
    abtp = alt > 11000.0
    beltp = alt < 11000.0

    efa = 1.0 * (cspd & abco & abtp)
    efb = (1.0 / (1.0 + ((gamma * R * beta) / (2.0 * g0)) * m2)) \
        * (cspd & abco & beltp)
    efc = (1.0 / (1.0 + (((gamma * R * beta) / (2.0 * g0)) * m2)
                  + ((1.0 + gamma1 * m2) ** (-1.0 / (gamma - 1.0)))
                  * (((1.0 + gamma1 * m2) ** gamma2) - 1.0))) \
        * (cspd & belco & beltp)
    efd = (1.0 / (1.0 + ((1.0 + gamma1 * m2) ** (-1.0 / (gamma - 1.0)))
                  * (((1.0 + gamma1 * m2) ** gamma2) - 1.0))) \
        * (cspd & belco & abtp)
    efe = 0.3 * (acc & climb)
    eff = 0.3 * (dec & descent)
    efg = 1.7 * (dec & climb)
    efh = 1.7 * (acc & descent)

    out = jnp.max(jnp.stack([efa, efb, efc, efd, efe, eff, efg, efh]),
                  axis=0)
    return jnp.maximum(out, (out == 0.0) * 1.0)


def calclimits(desspd, gs, to_spd, vmin, vmo, mmo, mach, alt, hmaxact,
               desalt, desvs, maxthr, thr, drag, tas, mass, esf_, phase):
    """Envelope limit flags/values (performance.py:214-268).

    Returns (limspd, limspd_flag, limalt, limalt_flag, limvs, limvs_flag)
    with the reference's -999/-9999 sentinels.
    """
    g0 = aero.g0
    limspd = jnp.where(desspd < vmin, vmin, -999.0)
    limspd_flag = desspd < vmin
    limspd = jnp.where(desspd > vmo, vmo, limspd)
    limspd_flag = limspd_flag | (desspd > vmo)
    limspd = jnp.where(mach > mmo, aero.vmach2cas(mmo - 0.01, alt), limspd)
    limspd_flag = limspd_flag | (mach > mmo)
    limspd_flag = jnp.where(jnp.abs(desspd - limspd) < 0.1, False,
                            limspd_flag)
    limspd = jnp.where(~limspd_flag, -999.0, limspd)

    limalt = jnp.where(desalt > hmaxact, hmaxact - 1.0, -999.0)
    limalt_flag = desalt > hmaxact
    near = jnp.abs(desalt - hmaxact) < 0.1
    limalt = jnp.where(near, -999.0, limalt)
    limalt_flag = jnp.where(near, False, limalt_flag)

    thr_corr = jnp.where(thr > maxthr - 1.0, maxthr - 1.0, thr)
    limvs = jnp.where(thr > maxthr - 1.0,
                      ((thr_corr - drag) * tas) / (mass * g0) * esf_,
                      -9999.0)
    limvs_flag = limvs > -9999.0

    belowrot = (desvs > 0.0) & (gs < to_spd) & (phase == PHASE_GD)
    limvs = jnp.where(belowrot, 0.0, limvs)
    limvs_flag = limvs_flag | belowrot

    atrot = (jnp.abs(to_spd - gs) < 0.1) \
        & ((phase == PHASE_GD) | (phase == PHASE_TO))
    limvs = jnp.where(atrot, -9999.0, limvs)
    limvs_flag = limvs_flag | atrot

    # remove non-needed limits (performance.py:262-266); NB the reference
    # overwrites Thr before testing limvs, kept operation-for-operation
    thr2 = jnp.where(maxthr - thr < 2.0, -9999.0, thr)
    limvs = jnp.where(maxthr - thr2 < 2.0, -9999.0, limvs)
    limvs_flag = jnp.where(limvs < -999.0, False, limvs_flag)

    return limspd, limspd_flag, limalt, limalt_flag, limvs, limvs_flag
