"""Blockwise conflict detection + MVP accumulation for large N.

The dense kernel (``ops/cd.py``) materialises [N, N] matrices — fine to
~16k aircraft, impossible at the 100k north star (10^10 f32 entries).  This
module computes exactly the same per-ownship *reductions* without ever
holding an N x N array: the pair space is tiled into [Br, Bc] blocks that are
streamed through on-chip memory, flash-attention-style (SURVEY.md §5.7 calls
for precisely this blockwise decomposition of the CPA geometry).

Per ownship row the step needs only (see core/asas.py):
  * ``inconf``      — any conflict flag            (OR-reduction)
  * ``tcpamax``     — max of tcpa over conflicts   (MAX-reduction)
  * MVP sums        — sum of per-pair displacement (SUM-reduction; the tail
                      of the resolver, ``cr_mvp.resolve_from_sums``, is
                      per-aircraft and shared with the dense path)
  * ``tsolv``       — min vertical solve time      (MIN-reduction)
  * conflict/LoS counts                            (scalar SUMs)
  * partner candidates for resume-nav hysteresis (below).

Resume-nav (reference asas.py:409-471) keeps a *pair set* alive until past
CPA.  The dense path stores it as an [N, N] bool; here it becomes a fixed-K
**partner table** ``[N, K]`` of intruder indices: a running top-K (by
earliest conflict-entry time) is carried through the column-block scan, so
each CD interval yields the K genuinely most urgent conflicts per ownship;
these are merged with the surviving previous partners, and the resume
predicates are evaluated on gathered partner state (an [N, K] problem,
linear in N).  K defaults to 8: an ownship tracks at most K simultaneous
hysteresis partners — conflicts re-detect every interval, so this bounds only
how many *past* conflicts can hold ASAS engaged at once.  Empirical bound
(measured on the bench geometry): at N=10,000 inside the 230 nm regional
circle — already ~3x the density of the busiest real airspace — the
per-ownship simultaneous conflict-partner distribution is mean 2.5,
p50 2, p99 7, max 11; only 0.24% of ownships ever exceed 8, and for
those the table keeps the 8 *most urgent* (earliest entry time), so the
divergence is limited to the resume timing of their least-urgent past
partners.  Raise ``Traffic(k_partners=...)`` for denser studies.

Semantics match the reference StateBasedCD + MVP summation
(StateBasedCD.py:7-103, MVP.py:14-143) pair-for-pair; only the reduction
*order* differs (blockwise f32 reassociation), so golden tests compare to the
dense path at tolerance (tests/test_cd_tiled.py).
"""
import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp

from . import cr_mvp, geo, kmath


class RowConflictData(NamedTuple):
    """Per-ownship reductions of the pair space — no [N,N] anywhere."""
    inconf: jnp.ndarray     # [N] bool
    tcpamax: jnp.ndarray    # [N]
    sum_dve: jnp.ndarray    # [N]  sum over conflict pairs of MVP east term
    sum_dvn: jnp.ndarray    # [N]
    sum_dvv: jnp.ndarray    # [N]
    tsolv: jnp.ndarray      # [N]  min vertical solve time (1e9 = none)
    nconf: jnp.ndarray      # scalar int32 — directional conflict pairs
    nlos: jnp.ndarray       # scalar int32 — LoS pairs
    topk_idx: jnp.ndarray   # [N, K] int32 — K most urgent intruders,
    topk_tin: jnp.ndarray   # [N, K]         urgency order (1e9 = empty)


def _pad1(a, npad, value):
    return a if npad == 0 else jnp.concatenate(
        [a, jnp.full((npad,), value, a.dtype)])


# --------------------------------------------------------------------------
# Delta-polynomial pair geometry.
#
# The dense path evaluates the haversine + bearing with pairwise sin/cos/
# atan2 — ~a dozen transcendentals per PAIR.  Here the per-pair trig reduces
# to odd polynomials of the coordinate DELTAS plus products of per-AIRCRAFT
# sin/cos columns:
#   dlat, dlon are formed by direct subtraction (well-conditioned: the
#     cancellation happens on the raw degree values, keeping absolute error
#     at f32 eps of the coordinates — NOT on cos(delta) near 1, which would
#     lose all precision of close pairs),
#   sin(dlat/2) etc. come from a degree-7 odd Taylor evaluation (exact to
#     f32 for the |delta| < pi/2 range where precision matters; for far
#     pairs the small overshoot only pushes distances up, never creating
#     false conflicts),
#   the bearing uses sin(qdr) = qy/h, cos(qdr) = qx/h with
#     qy = sin(dlon)*cl_i,  qx = sin(dlat) + sl_o*cl_i*(2*sin^2(dlon/2))
#     so the angle itself is never formed,
#   rwgs84(lat_o+lat_i) (the reference matrix quirk, geo.py:117-128)
#     expands via the angle-sum identities from the per-aircraft columns.
# Per pair one atan2 (arc length) and a few sqrt survive.  The dense path
# keeps the literal reference formulas as the parity anchor.
# --------------------------------------------------------------------------

#: per-aircraft columns consumed by tile_geometry, in slab order
TRIG_FIELDS = ("lat", "lon", "sl", "cl", "rloc", "abslat")


def precompute_trig(lat, lon):
    """Per-aircraft trig/radius columns for the factored pair geometry."""
    rlat = jnp.radians(lat)
    return {
        "lat": lat, "lon": lon,
        "sl": jnp.sin(rlat), "cl": jnp.cos(rlat),
        "rloc": geo.rwgs84(lat),
        "abslat": jnp.abs(lat),
    }


def _rwgs84_from_trig(cosphi, sinphi):
    """geo.rwgs84 evaluated from cos/sin of the latitude angle.

    sqrt(num)*rsqrt(den) instead of sqrt(num/den): one fewer multi-cycle
    VPU op per pair, ~1 ulp difference."""
    an = geo.A_WGS84 * geo.A_WGS84 * cosphi
    bn = geo.B_WGS84 * geo.B_WGS84 * sinphi
    ad = geo.A_WGS84 * cosphi
    bd = geo.B_WGS84 * sinphi
    return jnp.sqrt(an * an + bn * bn) * jax.lax.rsqrt(ad * ad + bd * bd)


def _sin_poly(x):
    """sin(x) as a degree-7 odd Taylor evaluation, |x| <= pi.

    Error < 2e-4 at pi/2, < 1e-7 below 0.5 rad — and conflict geometry only
    needs precision for deltas far below that.
    """
    x2 = x * x
    return x * (1.0 - x2 / 6.0 * (1.0 - x2 / 20.0 * (1.0 - x2 / 42.0)))


def tile_geometry(own, intr, same_hemisphere=False):
    """Pair distance [m] + bearing sin/cos for one tile.

    ``same_hemisphere=True`` (static) asserts no pair in the tile can
    have lat_o * lat_i < 0, eliding the reference's cross-equator radius
    branch (geo.py:117-128 ``res2``) — bit-identical for such tiles
    because the per-pair ``where`` would always pick ``res1``.  Callers
    must only set it when the assertion provably holds (ops/cd_sched.py
    derives it from the active fleet's latitude signs).

    ``own``/``intr`` are dicts of TRIG_FIELDS columns, broadcast-shaped
    (ownship vs intruder axes).  Mirrors geo.qdrdist_matrix semantics
    (including the radius-at-sum-of-latitudes quirk and the 1e-6 epsilon,
    geo.py:117-128) via the delta-polynomial scheme above.  Returns
    (dist, sin_qdr, cos_qdr).

    VPU-lean transcendentals (shared verbatim by the lax and Pallas
    backends, so they cannot drift): the arc length uses the odd-Taylor
    arcsin (kmath.asin_taylor — f32-exact for every distance that can
    flip a conflict/LoS flag, conservative beyond) and the bearing
    normalization uses one rsqrt instead of sqrt + two divides.
    """
    sl_o, cl_o = own["sl"], own["cl"]
    sl_i, cl_i = intr["sl"], intr["cl"]

    # Mean radius (reference matrix quirk: evaluated at lat_o + lat_i)
    cos_sum = cl_o * cl_i - sl_o * sl_i
    sin_sum = sl_o * cl_i + cl_o * sl_i
    res1 = _rwgs84_from_trig(cos_sum, sin_sum)
    if same_hemisphere:
        r = res1
    else:
        denom = own["abslat"] + intr["abslat"] \
            + jnp.where(own["lat"] == 0.0, 1e-6, 0.0)
        res2 = 0.5 * (own["abslat"] * (own["rloc"] + geo.A_WGS84)
                      + intr["abslat"] * (intr["rloc"] + geo.A_WGS84)) / denom
        r = jnp.where(own["lat"] * intr["lat"] < 0.0, res2, res1)

    # Coordinate deltas; dlon wrapped into [-180, 180] (the reference's
    # pairwise sin/cos are periodic — the polynomial needs the wrap).
    dlat = jnp.radians(intr["lat"] - own["lat"])
    dlon_deg = intr["lon"] - own["lon"]
    dlon = jnp.radians(dlon_deg - 360.0 * jnp.round(dlon_deg * (1.0 / 360.0)))

    sh_lat = _sin_poly(0.5 * dlat)
    sh_lon = _sin_poly(0.5 * dlon)
    root = sh_lat * sh_lat + cl_o * cl_i * sh_lon * sh_lon
    root = jnp.clip(root, 0.0, 1.0)
    dist = 2.0 * r * kmath.asin_taylor(jnp.sqrt(root))

    # Bearing sin/cos as ratios — the angle is never formed.
    # qx = cl_o*sl_i - sl_o*cl_i*cos(dlon) = sin(dlat) + sl_o*cl_i*(1-cos
    # dlon), with 1-cos(dlon) = 2*sin^2(dlon/2): all well-conditioned terms.
    qy = _sin_poly(dlon) * cl_i
    qx = _sin_poly(dlat) + sl_o * cl_i * (2.0 * sh_lon * sh_lon)
    # Clamp must stay f32-NORMAL (1e-60 underflows to 0 -> rsqrt=inf ->
    # NaN bearings for co-located pairs, silently dropping their
    # conflicts); 1e-37 keeps rsqrt finite and 0*rsqrt = 0 like the
    # 0/h of the division form.
    rh = jax.lax.rsqrt(jnp.maximum(qx * qx + qy * qy, 1e-37))
    return dist, qy * rh, qx * rh


def spatial_permutation(lat, lon, active):
    """[N] permutation ordering aircraft along a Morton (Z-order) curve.

    Blocks of the tiled pair space are contiguous SLOT ranges; slots are
    assigned in creation order, so without sorting every block's
    bounding box spans the whole airspace and the reachability skip
    never fires.  Sorting by interleaved 16-bit quantized lat/lon makes
    blocks spatially tight, which is what turns the O(N^2) pair sweep
    into ~O(N * local density) for spread-out traffic.  Inactive slots
    sort last (their block is skipped entirely).
    """
    def spread16(x):
        # 16 -> 32 bit Morton spread (standard bit tricks)
        x = x.astype(jnp.uint32)
        x = (x | (x << 8)) & jnp.uint32(0x00FF00FF)
        x = (x | (x << 4)) & jnp.uint32(0x0F0F0F0F)
        x = (x | (x << 2)) & jnp.uint32(0x33333333)
        x = (x | (x << 1)) & jnp.uint32(0x55555555)
        return x

    # 15-bit quantization -> 30-bit code, so the inactive sentinel fits
    # in int32 without x64
    qlat = jnp.clip((lat + 90.0) / 180.0 * 32767.0, 0, 32767)
    qlon = jnp.clip((lon + 180.0) / 360.0 * 32767.0, 0, 32767)
    code = spread16(qlat.astype(jnp.uint32)) \
        | (spread16(qlon.astype(jnp.uint32)) << 1)
    # inactive last: force their code above every active one
    key = jnp.where(active, code.astype(jnp.int32),
                    jnp.int32(0x7FFFFFFF))
    return jnp.argsort(key)


def run_spatially_sorted(kernel, lat, lon, trk, gs, alt, vs, gseast,
                         gsnorth, active, noreso, *args, perm=None,
                         extra_cols=None, **kw):
    """Run a tiled CD&R kernel in Morton-sorted slot space and map the
    results back to the caller's slot order.

    Shared by the lax and Pallas backends: permutes every per-aircraft
    input, invokes ``kernel`` (which must accept the same leading
    arguments plus *args/**kw and return a RowConflictData), then
    inverse-permutes the row outputs and maps the partner indices
    through the permutation (they are sorted-space positions).

    ``perm`` lets the caller supply a (possibly stale) cached permutation
    — exact for ANY permutation, since block reachability is recomputed
    from the true positions; staleness only loosens the block bounding
    boxes (core/asas.py carries it in ``AsasArrays.sort_perm``).
    """
    if perm is None:
        perm = spatial_permutation(lat, lon, active)
    # Invert by scatter: an O(N) store instead of a second O(N log^2 N)
    # TPU sort (argsort of 100k keys costs more than the CD kernel).
    inv = jnp.zeros_like(perm).at[perm].set(
        jnp.arange(perm.shape[0], dtype=perm.dtype))
    g = lambda a: a[perm]
    if extra_cols:
        kw = dict(kw, extra_cols={k: g(v) for k, v in extra_cols.items()})
    rd = kernel(g(lat), g(lon), g(trk), g(gs), g(alt), g(vs),
                g(gseast), g(gsnorth), g(active), g(noreso),
                *args, **kw)
    extra = None
    if not isinstance(rd, RowConflictData):    # (rd, swarm_sums) pair
        rd, extra = rd
    back = lambda a: a[inv]
    topk_idx = jnp.where(
        rd.topk_idx >= 0,
        perm[jnp.maximum(rd.topk_idx, 0)].astype(jnp.int32), -1)
    rd = RowConflictData(
        inconf=back(rd.inconf), tcpamax=back(rd.tcpamax),
        sum_dve=back(rd.sum_dve), sum_dvn=back(rd.sum_dvn),
        sum_dvv=back(rd.sum_dvv), tsolv=back(rd.tsolv),
        nconf=rd.nconf, nlos=rd.nlos,
        topk_idx=back(topk_idx), topk_tin=back(rd.topk_tin))
    if extra is not None:
        return rd, tuple(back(a) for a in extra)
    return rd


def block_summaries(lat, lon, gs, active, nb, block, alt=None, vs=None):
    """Per-block active-aircraft summaries: the ONLY quantities the
    reachability bound reads.  Returns a dict of [nb] arrays
    (latmin/latmax/lonmin/lonmax/gsmax, plus altmin/altmax/vsmax when
    ``alt``/``vs`` are given).  Split out of ``block_reachability`` so
    the spatial domain-decomposition mode (ops/cd_sched.py) can compute
    summaries for its OWN blocks locally, all-gather the [nb]-sized
    summary vectors (O(N/block) metadata, never the O(N) columns), and
    evaluate reachability rows from them with bit-identical math."""
    shape = (nb, block)
    blat = lat.reshape(shape)
    blon = lon.reshape(shape)
    bgs = gs.reshape(shape)
    act = active.reshape(shape)
    inf = jnp.asarray(jnp.inf, lat.dtype)
    out = dict(
        latmin=jnp.min(jnp.where(act, blat, inf), axis=1),
        latmax=jnp.max(jnp.where(act, blat, -inf), axis=1),
        lonmin=jnp.min(jnp.where(act, blon, inf), axis=1),
        lonmax=jnp.max(jnp.where(act, blon, -inf), axis=1),
        gsmax=jnp.max(jnp.where(act, bgs, 0.0), axis=1))
    if alt is not None:
        balt = alt.reshape(shape)
        bvs = jnp.abs(vs.reshape(shape))
        out.update(
            altmin=jnp.min(jnp.where(act, balt, inf), axis=1),
            altmax=jnp.max(jnp.where(act, balt, -inf), axis=1),
            vsmax=jnp.max(jnp.where(act, bvs, 0.0), axis=1))
    return out


def reachability_from_summaries(row, col, rpz, tlookahead, hpz=None,
                                min_reach_m=0.0, min_vreach_m=0.0,
                                margin_m=0.0):
    """[nbr, nbc] bool reachability between two summary sets (the
    pairwise half of ``block_reachability``; ``row`` and ``col`` may be
    the same dict — the classic square case — or a device's own rows
    against the gathered global columns in the spatial mesh mode).
    ``margin_m`` widens the horizontal bound (the spatial refresh's
    drift allowance when validating halo coverage ahead of time)."""
    latmin_r, latmax_r = row["latmin"], row["latmax"]
    latmin_c, latmax_c = col["latmin"], col["latmax"]
    maxabslat_r = jnp.maximum(jnp.abs(latmin_r), jnp.abs(latmax_r))
    maxabslat_c = jnp.maximum(jnp.abs(latmin_c), jnp.abs(latmax_c))

    dlat_gap = jnp.maximum(0.0, jnp.maximum(
        latmin_r[:, None] - latmax_c[None, :],
        latmin_c[None, :] - latmax_r[:, None]))
    # Circular longitude gap between the two [min, max] intervals:
    # linear gap, or around the back of the sphere, whichever is smaller
    lin_gap = jnp.maximum(0.0, jnp.maximum(
        row["lonmin"][:, None] - col["lonmax"][None, :],
        col["lonmin"][None, :] - row["lonmax"][:, None]))
    wrap_gap = jnp.maximum(0.0, 360.0 - (
        jnp.maximum(row["lonmax"][:, None], col["lonmax"][None, :])
        - jnp.minimum(row["lonmin"][:, None], col["lonmin"][None, :])))
    dlon_gap = jnp.minimum(lin_gap, wrap_gap)

    cos_lb = jnp.cos(jnp.radians(jnp.minimum(
        90.0, jnp.maximum(maxabslat_r[:, None], maxabslat_c[None, :]))))
    r_min = 6335000.0
    zonal = 2.0 * r_min * jnp.arcsin(jnp.clip(
        cos_lb * jnp.sin(jnp.radians(0.5 * jnp.minimum(dlon_gap, 360.0))),
        0.0, 1.0))
    merid = dlat_gap * 110000.0
    dist_lb = jnp.maximum(merid, zonal)
    thresh = rpz + tlookahead * (row["gsmax"][:, None]
                                 + col["gsmax"][None, :])
    # min_reach_m widens the bound for reductions over pairs beyond the
    # conflict horizon (the Swarm 7.5 nm neighbourhood: with a short
    # DTLOOK the conflict bound alone could skip genuine neighbours)
    thresh = jnp.maximum(thresh, min_reach_m) + margin_m
    reach = dist_lb <= thresh * 1.05
    if hpz is not None and "altmin" in row:
        altgap = jnp.maximum(0.0, jnp.maximum(
            row["altmin"][:, None] - col["altmax"][None, :],
            col["altmin"][None, :] - row["altmax"][:, None]))
        vthresh = hpz + tlookahead * (row["vsmax"][:, None]
                                      + col["vsmax"][None, :])
        # min_vreach_m: vertical analogue of min_reach_m (the Swarm
        # 1500 ft neighbourhood exceeds hpz, so the conflict bound alone
        # would skip genuine co-cruising neighbours one band up)
        vthresh = jnp.maximum(vthresh, min_vreach_m)
        reach = reach & (altgap <= vthresh * 1.05)
    return reach


def block_reachability(lat, lon, gs, active, nb, block, rpz, tlookahead,
                       alt=None, vs=None, hpz=None, min_reach_m=0.0,
                       min_vreach_m=0.0):
    """[nb, nb] bool: which block pairs can possibly contain a conflict
    or LoS.

    EXACT skip predicate (shared by the lax and Pallas tiled backends):
    a pair farther apart than ``rpz + tlookahead * (gsmax_r + gsmax_c)``
    has horizontal conflict-entry time >= (dist - rpz)/vrel > tlookahead
    and dist > rpz, so neither swconfl nor swlos can hold.

    With ``alt``/``vs``/``hpz`` given, an analogous EXACT vertical skip
    is AND-ed in: blocks whose altitude ranges are separated by more
    than ``hpz + tlookahead * (vsmax_r + vsmax_c)`` have vertical entry
    time ``tinver >= (altgap - hpz)/dvs > tlookahead`` (so
    ``tinconf = max(tinver, tinhor)`` exceeds the lookahead) and
    ``|dalt| > hpz`` (no LoS).  This is what makes the altitude-layered
    sort of ``cd_sched.stripe_sort_dest`` pay off: cruise blocks only
    reach ~one flight-level band instead of the whole column.

    Distance lower bounds between the blocks' active-aircraft bounding
    boxes, valid on the whole sphere:
    * meridional: the central angle of any pair is >= its latitude
      difference, and the reference radius is >= 6,335 km, so
      ``dlat_gap * 110,000 m/deg`` under-estimates every pair distance;
    * zonal: the minimum distance between two meridians ``dlon`` apart
      for points with |lat| <= L is ``2 R asin(cos L * sin(dlon/2))``
      (attained at +/-L) — correct at the poles (cos L -> 0: no skip
      from longitude alone) unlike a naive ``dlon * cos L`` scaling;
    * the longitude gap is CIRCULAR: min of the linear gap and the
      wrap-around gap, so clusters on both sides of the antimeridian
      are never falsely skipped.
    Empty blocks get +/-inf bounds -> infinite gap -> always skipped.
    """
    summ = block_summaries(lat, lon, gs, active, nb, block, alt=alt, vs=vs)
    return reachability_from_summaries(summ, summ, rpz, tlookahead,
                                       hpz=hpz if alt is not None else None,
                                       min_reach_m=min_reach_m,
                                       min_vreach_m=min_vreach_m)


def detect_resolve_tiled(lat, lon, trk, gs, alt, vs, gseast, gsnorth,
                         active, noreso, rpz, hpz, tlookahead, mvpcfg,
                         block=512, k_partners=8, prefilter=True,
                         spatial_sort=True, perm=None, extra_cols=None,
                         reso="mvp"):
    """One fused pass over all aircraft pairs in [block, block] tiles.

    Args mirror ``ops.cd.detect`` plus the MVP inputs; ``mvpcfg`` is a
    ``cr_mvp.MVPConfig``.  Returns a ``RowConflictData``.

    ``prefilter=True`` adds an EXACT block-level reachability skip — the
    TPU analogue of the reference C++ prefilter (asas.hpp:24-27): a tile
    whose two blocks' bounding boxes are farther apart than
    ``rpz + tlookahead * (gsmax_r + gsmax_c)`` cannot contain a conflict
    (horizontal entry time >= (dist - rpz)/vrel > tlookahead) or LoS
    (dist > rpz), so the column scan skips its work entirely via
    ``lax.cond`` — sequential scan iterations on TPU really do elide the
    untaken branch.  Distance lower bounds are conservative
    (meridional/zonal components at <110 km/deg, cos at the highest
    |lat| of either block; antimeridian-spanning blocks degrade to
    "never skip").  Computed tiles are bit-identical with/without.
    """
    n = lat.shape[0]
    if spatial_sort and n > block:
        # Morton-order the slots so blocks are spatially tight (the
        # reachability skip is useless on creation-ordered slots)
        return run_spatially_sorted(
            functools.partial(detect_resolve_tiled, block=block,
                              k_partners=k_partners, prefilter=prefilter,
                              spatial_sort=False, reso=reso),
            lat, lon, trk, gs, alt, vs, gseast, gsnorth, active, noreso,
            rpz, hpz, tlookahead, mvpcfg, perm=perm, extra_cols=extra_cols)
    block = min(block, max(n, 1))
    kk = min(k_partners, block)   # per-tile candidates merged into the top-K
    nb = -(-n // block)
    # With a single tile the cap kk=block=n is exact (at most n-1 partners
    # exist); across multiple tiles a sub-K per-tile candidate list would
    # silently drop hysteresis partners beyond `block`.
    if nb > 1 and block < k_partners:
        raise ValueError(
            f"block ({block}) must be >= k_partners ({k_partners}) "
            "when the pair space spans multiple tiles")
    npad = nb * block - n
    dtype = lat.dtype

    packed = {
        "alt": _pad1(alt, npad, 0.0), "vs": _pad1(vs, npad, 0.0),
        "gse": _pad1(gseast, npad, 0.0), "gsn": _pad1(gsnorth, npad, 0.0),
    }
    # Per-aircraft trig columns for the rank-1-factored pair geometry
    packed.update(precompute_trig(_pad1(lat, npad, 0.0),
                                  _pad1(lon, npad, 0.0)))
    # East/north velocity components for the CPA math (StateBasedCD.py:31-40
    # uses trk/gs; gseast/gsnorth are the same numbers assembled in traffic).
    trkrad = jnp.radians(_pad1(trk, npad, 0.0))
    packed["u"] = _pad1(gs, npad, 0.0) * jnp.sin(trkrad)
    packed["v"] = _pad1(gs, npad, 0.0) * jnp.cos(trkrad)
    # tas/gs ratio: Eby's TAS velocity basis (ve = tr*u); 1.0 when no
    # tas column is supplied (MVP never reads it)
    tas = (extra_cols or {}).get("tas")
    packed["tr"] = _pad1(jnp.ones_like(gs) if tas is None
                         else tas / jnp.maximum(gs, 1e-6), npad, 1.0)
    if reso == "swarm":
        packed["trk"] = _pad1(trk, npad, 0.0)
        packed["cas"] = _pad1((extra_cols or {}).get("cas", gs), npad, 0.0)
    if reso == "eby":
        # Exact TAS velocity columns (the lax dict has no slab-row
        # budget, unlike the Pallas kernels' tas/gs-ratio encoding,
        # so the gs->0 hover-in-headwind corner is exact here)
        tas_col = _pad1(gs if tas is None else tas, npad, 0.0)
        packed["ute"] = tas_col * jnp.sin(trkrad)
        packed["utn"] = tas_col * jnp.cos(trkrad)
    packed = {k: v.reshape(nb, block) for k, v in packed.items()}
    act_b = _pad1(active, npad, False).reshape(nb, block)
    nor_b = _pad1(noreso, npad, False).reshape(nb, block)

    r2 = rpz * rpz
    bigval = jnp.asarray(1e9, dtype)
    col_ids = jnp.arange(nb * block, dtype=jnp.int32).reshape(nb, block)

    # Reachability flags for the exact tile skip (see docstring); the
    # Swarm mode widens the bound to its 7.5 nm neighbourhood so short
    # lookaheads cannot skip genuine swarm neighbours.
    if reso == "swarm":
        from . import cr_swarm
        min_reach = cr_swarm.R_SWARM
    else:
        min_reach = 0.0
    reach = block_reachability(_pad1(lat, npad, 0.0),
                               _pad1(lon, npad, 0.0),
                               _pad1(gs, npad, 0.0), act_b.reshape(-1),
                               nb, block, rpz, tlookahead,
                               min_reach_m=min_reach)

    def tile(ri, ci, rows_active, carry):
        """Compute one [block, block] tile and fold it into the row carry."""
        (inconf, tcpamax, sdve, sdvn, sdvv, tsolv, nconf, nlos,
         topk_tin, topk_idx) = carry[:10]
        r = {k: v[ri] for k, v in packed.items()}
        c = {k: v[ci] for k, v in packed.items()}
        cols_active = act_b[ci]
        cols_noreso = nor_b[ci]

        # Pair mask: both active, not the same aircraft (generalised
        # diagonal exclusion, StateBasedCD.py:11,22).
        same = (ri * block + jnp.arange(block, dtype=jnp.int32))[:, None] \
            == col_ids[ci][None, :]
        pairmask = (rows_active[:, None] & cols_active[None, :]) & ~same
        excl = jnp.where(pairmask, 0.0, bigval)

        # Horizontal geometry — factored haversine (tile_geometry docstring)
        rT = {k: r[k][:, None] for k in TRIG_FIELDS}
        cT = {k: c[k][None, :] for k in TRIG_FIELDS}
        dist0, sinqdr, cosqdr = tile_geometry(rT, cT)
        dist = dist0 + excl
        dx = dist * sinqdr
        dy = dist * cosqdr

        du = c["u"][None, :] - r["u"][:, None]
        dv = c["v"][None, :] - r["v"][:, None]
        dv2 = du * du + dv * dv
        dv2 = jnp.where(jnp.abs(dv2) < 1e-6, 1e-6, dv2)
        # One rsqrt replaces the sqrt + two divides of the reference
        # formulation (1/vrel and 1/dv2 both derive from it)
        rvrel = jax.lax.rsqrt(dv2)

        tcpa = -(du * dx + dv * dy) * (rvrel * rvrel) + excl
        dcpa2 = dist * dist - tcpa * tcpa * dv2
        swhorconf = dcpa2 < r2

        dtinhor = jnp.sqrt(jnp.maximum(0.0, r2 - dcpa2)) * rvrel
        tinhor = jnp.where(swhorconf, tcpa - dtinhor, 1e8)
        touthor = jnp.where(swhorconf, tcpa + dtinhor, -1e8)

        # Vertical geometry
        dalt = c["alt"][None, :] - r["alt"][:, None] + excl
        dvs = c["vs"][None, :] - r["vs"][:, None]
        dvs = jnp.where(jnp.abs(dvs) < 1e-6, 1e-6, dvs)
        nrdvs = -1.0 / dvs            # one divide for both crossings
        tcrosshi = (dalt + hpz) * nrdvs
        tcrosslo = (dalt - hpz) * nrdvs
        tinver = jnp.minimum(tcrosshi, tcrosslo)
        toutver = jnp.maximum(tcrosshi, tcrosslo)

        tinconf = jnp.maximum(tinver, tinhor)
        toutconf = jnp.minimum(toutver, touthor)
        swconfl = (swhorconf & (tinconf <= toutconf) & (toutconf > 0.0)
                   & (tinconf < tlookahead) & pairmask)
        swlos = (dist < rpz) & (jnp.abs(dalt) < hpz) & pairmask

        if reso == "eby":
            # Eby pair displacement (cr_eby.pair_contrib) on the exact
            # TAS velocity columns
            from . import cr_eby
            dve_p, dvn_p, dvv_p = cr_eby.pair_contrib(
                dx, dy, c["alt"][None, :] - r["alt"][:, None],
                c["ute"][None, :] - r["ute"][:, None],
                c["utn"][None, :] - r["utn"][:, None],
                c["vs"][None, :] - r["vs"][:, None], mvpcfg.rpz_m)
            tsolv_p = jnp.full_like(dve_p, 1e9)
            mvpmask = swconfl          # Eby has no noreso handling
        else:
            # MVP pair contributions (shared core, MVP.py:149-231)
            dve_p, dvn_p, dvv_p, tsolv_p = cr_mvp.pair_contrib_trig(
                sinqdr, cosqdr, dist, tcpa, tinconf,
                c["alt"][None, :] - r["alt"][:, None],
                c["gse"][None, :] - r["gse"][:, None],
                c["gsn"][None, :] - r["gsn"][:, None],
                c["vs"][None, :] - r["vs"][:, None],
                mvpcfg)
            mvpmask = swconfl & ~cols_noreso[None, :]
        maskf = mvpmask.astype(dtype)

        if reso == "swarm":
            # Swarm neighbour sums (Swarm.py:47-66 via cr_swarm.pair_weight)
            from . import cr_swarm
            dtrk = (c["trk"][None, :] - r["trk"][:, None]
                    + 180.0) % 360.0 - 180.0
            w = cr_swarm.pair_weight(
                dx, dy, c["alt"][None, :] - r["alt"][:, None], dtrk,
                pairmask).astype(dtype)
            sw = carry[-1]
            sw = (sw[0] + jnp.sum(w, axis=1),
                  sw[1] + jnp.sum(w * c["cas"][None, :], axis=1),
                  sw[2] + jnp.sum(w * c["vs"][None, :], axis=1),
                  sw[3] + jnp.sum(w * dtrk, axis=1),
                  sw[4] + jnp.sum(w * dx, axis=1),
                  sw[5] + jnp.sum(w * dy, axis=1),
                  sw[6] + jnp.sum(w * c["alt"][None, :], axis=1))

        # Fold tile reductions into the row carry
        inconf = inconf | jnp.any(swconfl, axis=1)
        tcpamax = jnp.maximum(tcpamax, jnp.max(tcpa * swconfl, axis=1))
        sdve = sdve + jnp.sum(dve_p * maskf, axis=1)
        sdvn = sdvn + jnp.sum(dvn_p * maskf, axis=1)
        sdvv = sdvv + jnp.sum(dvv_p * maskf, axis=1)
        tsolv = jnp.minimum(
            tsolv, jnp.min(jnp.where(mvpmask, tsolv_p, 1e9), axis=1))
        nconf = nconf + jnp.sum(swconfl, dtype=jnp.int32)
        nlos = nlos + jnp.sum(swlos, dtype=jnp.int32)

        # Partner candidates: the kk most urgent (earliest conflict entry)
        # in this block, merged into the running per-ownship top-K.
        urg = jnp.where(swconfl, tinconf, bigval)
        negv, jbest = jax.lax.top_k(-urg, kk)             # [block, kk]
        cand_tin = -negv
        cand_idx = (ci * block + jbest).astype(jnp.int32)
        cat_tin = jnp.concatenate([topk_tin, cand_tin], axis=1)
        cat_idx = jnp.concatenate([topk_idx, cand_idx], axis=1)
        negv, sel = jax.lax.top_k(-cat_tin, kk)
        topk_tin = -negv
        topk_idx = jnp.take_along_axis(cat_idx, sel, axis=1)
        out = (inconf, tcpamax, sdve, sdvn, sdvv, tsolv, nconf, nlos,
               topk_tin, topk_idx)
        if reso == "swarm":
            out = out + (sw,)
        return (out, None)

    def row_block(ri):
        rows_active = act_b[ri]
        z = jnp.zeros((block,), dtype)
        carry0 = (jnp.zeros((block,), bool),              # inconf
                  jnp.zeros((block,), dtype),             # tcpamax (>=0, see
                  z, z, z,                                #   cd.detect note)
                  jnp.full((block,), 1e9, dtype),         # tsolv
                  jnp.zeros((), jnp.int32), jnp.zeros((), jnp.int32),
                  jnp.full((block, kk), bigval, dtype),   # running top-K tin
                  jnp.full((block, kk), -1, jnp.int32))   # running top-K idx
        if reso == "swarm":
            carry0 = carry0 + ((z, z, z, z, z, z, z),)    # neighbour sums

        def colstep(carry, ci):
            if not prefilter:
                return tile(ri, ci, rows_active, carry)
            return jax.lax.cond(
                reach[ri, ci],
                lambda c: tile(ri, ci, rows_active, c)[0],
                lambda c: c, carry), None

        carry, _ = jax.lax.scan(colstep, carry0, jnp.arange(nb))
        return carry

    out = jax.lax.map(row_block, jnp.arange(nb))
    (inconf, tcpamax, sdve, sdvn, sdvv, tsolv, nconf, nlos,
     topk_tin, topk_idx) = out[:10]
    topk_idx = jnp.where(topk_tin < bigval, topk_idx, -1)

    unb = lambda a: a.reshape(nb * block, *a.shape[2:])[:n]
    rd = RowConflictData(
        inconf=unb(inconf), tcpamax=unb(tcpamax),
        sum_dve=unb(sdve), sum_dvn=unb(sdvn), sum_dvv=unb(sdvv),
        tsolv=unb(tsolv),
        nconf=jnp.sum(nconf, dtype=jnp.int32),
        nlos=jnp.sum(nlos, dtype=jnp.int32),
        topk_idx=unb(topk_idx), topk_tin=unb(topk_tin))
    if reso == "swarm":
        return rd, tuple(unb(a) for a in out[10])
    return rd


def topk_partners(rd, k):
    """The [N, K] partner candidates from a RowConflictData (-1 = empty).

    The running top-K merge in the scan already ordered them by urgency;
    this just pads/crops to the table width K.
    """
    idx = rd.topk_idx[:, :k]
    pad = k - idx.shape[1]
    if pad > 0:
        idx = jnp.pad(idx, ((0, 0), (0, pad)), constant_values=-1)
    return idx


def partner_keep(partners, lat, lon, gseast, gsnorth, trk, active,
                 rpz, rpz_m):
    """Resume-nav predicates on the partner table (reference asas.py:426-455).

    Same math as ``cr_mvp.resume_nav`` but on gathered [N, K] partner state
    instead of the [N, N] matrix.  Returns a bool [N, K] keep mask.
    """
    n = lat.shape[0]
    valid = partners >= 0
    j = jnp.clip(partners, 0, n - 1)

    latj, lonj = lat[j], lon[j]
    dist_e, dist_n = cr_mvp.resume_displacement(
        lat[:, None], lon[:, None], latj, lonj)
    vrel_e = gseast[j] - gseast[:, None]
    vrel_n = gsnorth[j] - gsnorth[:, None]

    alive = active[:, None] & active[j]
    keep = cr_mvp.resume_keep_core(dist_e, dist_n, vrel_e, vrel_n,
                                   trk[:, None], trk[j], alive, rpz, rpz_m)
    return keep & valid


def merge_partners(new_idx, old_idx, old_keep):
    """Merge fresh conflict partners with surviving previous partners.

    ``new_idx`` [N, K] (most urgent first, -1 empty) takes precedence; old
    partners surviving ``old_keep`` fill remaining slots, duplicates dropped.
    Returns the new [N, K] partner table.
    """
    k = new_idx.shape[1]
    old = jnp.where(old_keep, old_idx, -1)
    # Drop old entries that reappear among the new ones
    dup = jnp.any((old[:, :, None] == new_idx[:, None, :])
                  & (new_idx[:, None, :] >= 0), axis=2)
    old = jnp.where(dup, -1, old)

    cat = jnp.concatenate([new_idx, old], axis=1)        # [N, 2K]
    valid = cat >= 0
    pos = jnp.arange(2 * k, dtype=jnp.int32)[None, :]
    key = jnp.where(valid, pos, 2 * k + pos)             # valid first, stable
    order = jnp.argsort(key, axis=1)[:, :k]
    return jnp.take_along_axis(cat, order, axis=1)
