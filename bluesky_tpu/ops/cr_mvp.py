"""Modified Voltage Potential (MVP) conflict resolution, fully vectorized.

Semantic parity with the reference's ``bluesky/traffic/asas/MVP.py``: each
conflict pair contributes a displacement-at-CPA repulsion vector scaled by the
intrusion depth; contributions are summed per ownship; the combined velocity
change is direction-limited and capped.

TPU-first redesign: the reference loops over a Python list of conflict pairs
calling a scalar ``MVP()`` per pair (MVP.py:33-61).  Here the per-pair
displacement is computed for *all* N x N pairs as one masked broadcast, and
the per-ownship accumulation (``dv[id1] -= dv_mvp``) becomes a masked row-sum
— mathematically identical because contributions are additive.  Pair order
never matters (addition is commutative up to float reassociation; golden tests
compare at tolerance, see tests/test_cr_mvp.py).

The priority rulesets (FF1-3/LAY1-2, MVP.py:235-300) act per pair on the sign
and vertical component of each contribution; they are implemented as masks on
the same pair matrices.  NORESO/RESOOFF lists arrive as boolean per-aircraft
masks from the host.
"""
from typing import NamedTuple

import jax.numpy as jnp

from . import geo


class MVPConfig(NamedTuple):
    """Static-ish resolver configuration (device scalars / small arrays)."""
    rpz_m: float          # protected zone radius with margin Rm [m]
    hpz_m: float          # protected zone half-height with margin dhm [m]
    tlookahead: float     # [s]
    swresohoriz: bool = False   # resolve horizontally only
    swresospd: bool = False     # ... with speed changes only
    swresohdg: bool = False     # ... with heading changes only
    swresovert: bool = False    # resolve vertically only
    swprio: bool = False        # priority rules on (PRIORULES cmd)
    priocode: str = "FF1"       # FF1/FF2/FF3/LAY1/LAY2 (MVP.py:235-300)


def pair_contributions(cd, alt, gseast, gsnorth, vs, cfg):
    """Per-pair MVP displacement vectors for all pairs.

    Mirrors the scalar ``MVP()`` body (MVP.py:149-231) on [N,N] operands.
    Returns (dve, dvn, dvv, tsolv): east/north/vertical velocity-change
    contribution of pair (i,j) *to ownship i*, and the vertical solve time.
    Entries where ``cd.swconfl`` is False are garbage; callers mask.
    """
    return pair_contrib_core(
        cd.qdr, cd.dist, cd.tcpa, cd.tinconf,
        alt[None, :] - alt[:, None],
        gseast[None, :] - gseast[:, None],
        gsnorth[None, :] - gsnorth[:, None],
        vs[None, :] - vs[:, None],
        cfg)


def pair_contrib_core(qdr_deg, dist, tcpa, tlos,
                      drel_v, vrel_e, vrel_n, vrel_v, cfg, arcsin=None):
    """Shape-agnostic MVP pair math (MVP.py:149-231).

    Operands may be full [N,N] matrices (dense path) or [Br,Bc] tiles
    (ops/cd_tiled.py) — any broadcast-compatible shapes.  ``arcsin`` is
    injectable for the Pallas kernel (Mosaic has no asin lowering; it passes
    ``kmath.asin``).
    """
    arcsin = arcsin or jnp.arcsin
    qdr = jnp.radians(qdr_deg)
    return pair_contrib_trig(jnp.sin(qdr), jnp.cos(qdr), dist, tcpa, tlos,
                             drel_v, vrel_e, vrel_n, vrel_v, cfg,
                             arcsin=arcsin)


def pair_contrib_trig(sin_qdr, cos_qdr, dist, tcpa, tlos,
                      drel_v, vrel_e, vrel_n, vrel_v, cfg, arcsin=None):
    """MVP pair math taking the bearing as (sin, cos) directly.

    The tiled backends produce sin/cos of the bearing without ever forming
    the angle (they come out of the haversine as ratios), so this entry
    skips the radians/sin/cos round-trip.  With ``arcsin=None`` the
    non-grazing erratum factor cos(asin r1 - asin r2) is evaluated via the
    algebraic identity sqrt(1-r1^2)*sqrt(1-r2^2) + r1*r2 — mathematically
    identical, transcendental-free (the reference formula is MVP.py:190-193;
    the dense path passes a real arcsin to keep bit-parity with the oracle).
    """
    # Relative position of intruder j w.r.t. ownship i (MVP.py:157-159)
    drel_e = sin_qdr * dist
    drel_n = cos_qdr * dist

    # Horizontal displacement at CPA (MVP.py:170-171)
    dcpa_e = drel_e + vrel_e * tcpa
    dcpa_n = drel_n + vrel_n * tcpa
    dabsh = jnp.sqrt(dcpa_e * dcpa_e + dcpa_n * dcpa_n)

    # Horizontal intrusion w.r.t. the margin-scaled zone radius (MVP.py:174)
    ih = cfg.rpz_m - dabsh

    # Head-on degenerate geometry: rotate drel 90 degrees (MVP.py:178-181)
    headon = dabsh <= 10.0
    safe_dist = jnp.maximum(dist, 1e-9)
    dcpa_e = jnp.where(headon, drel_n / safe_dist * 10.0, dcpa_e)
    dcpa_n = jnp.where(headon, -drel_e / safe_dist * 10.0, dcpa_n)
    dabsh = jnp.where(headon, 10.0, dabsh)

    abstcpa = jnp.maximum(jnp.abs(tcpa), 1e-9)
    dve = (ih * dcpa_e) / (abstcpa * dabsh)
    dvn = (ih * dcpa_n) / (abstcpa * dabsh)

    # Non-grazing correction factor when intruder outside own PZ
    # (MVP.py:190-193).  Guard the arcsin args; the branch condition already
    # implies they are < 1 for pairs where it applies.
    apply_err = (cfg.rpz_m < dist) & (dabsh < dist)
    ratio1 = jnp.clip(cfg.rpz_m / safe_dist, -1.0, 1.0)
    ratio2 = jnp.clip(dabsh / safe_dist, -1.0, 1.0)
    if arcsin is not None:
        erratum = jnp.cos(arcsin(ratio1) - arcsin(ratio2))
    else:
        # cos(asin r1 - asin r2) for r in [-1, 1]
        erratum = (jnp.sqrt(jnp.maximum(0.0, 1.0 - ratio1 * ratio1))
                   * jnp.sqrt(jnp.maximum(0.0, 1.0 - ratio2 * ratio2))
                   + ratio1 * ratio2)
    erratum = jnp.where(apply_err, erratum, 1.0)
    # erratum can be ~0 for extreme geometry; reference divides unguarded, we
    # clamp to keep the kernel NaN-free under padding garbage.
    erratum = jnp.where(jnp.abs(erratum) < 1e-9, 1e-9, erratum)
    dve = dve / erratum
    dvn = dvn / erratum

    # Vertical resolution (MVP.py:198-215)
    has_dvs = jnp.abs(vrel_v) > 0.0
    iv = jnp.where(has_dvs, cfg.hpz_m, cfg.hpz_m - jnp.abs(drel_v))
    tsolv = jnp.where(has_dvs,
                      jnp.abs(drel_v / jnp.where(has_dvs, vrel_v, 1.0)),
                      tlos)
    # Too slow to solve vertically within lookahead: solve within tLOS
    slow = tsolv > cfg.tlookahead
    tsolv = jnp.where(slow, tlos, tsolv)
    iv = jnp.where(slow, cfg.hpz_m, iv)
    tsolv_safe = jnp.where(jnp.abs(tsolv) < 1e-9, 1e-9, tsolv)
    dvv = jnp.where(has_dvs,
                    (iv / tsolv_safe) * (-jnp.sign(vrel_v)),
                    iv / tsolv_safe)
    return dve, dvn, dvv, tsolv


def resolve(cd, alt, gseast, gsnorth, vs, trk, gs,
            selalt, ap_vs, prev_alt,
            vmin, vmax, vsmin, vsmax, cfg,
            noreso=None, resooff=None, wconf=None, smooth=None):
    """Compute per-aircraft resolution commands from the conflict matrix.

    Args mirror the data the reference resolver reads from ``traf``/``asas``:
      cd:           ConflictData from ops.cd.detect
      alt..gs:      [N] current state
      selalt:       [N] autopilot selected altitude [m]
      ap_vs:        [N] autopilot commanded vertical speed [m/s]
      prev_alt:     [N] previous ASAS altitude command (persistent state)
      vmin..vsmax:  ASAS velocity caps (scalars or [N])
      noreso:       [N] bool — aircraft nobody needs to avoid (MVP.py:52-56)
      resooff:      [N] bool — aircraft that do not resolve (MVP.py:58-61)
      wconf:        [N,N] float in [0,1] or None — differentiable-mode
                    SIGMOID conflict weights (diff/smooth.py) replacing
                    the hard ``cd.swconfl`` mask on the contribution
                    sums: a pair approaching conflict contributes a
                    smoothly growing repulsion.  None (default) is the
                    exact boolean path.
      smooth:       diff.smooth.SmoothConfig or None — softmin for the
                    per-ownship vertical solve time (the resolver's
                    hard min reduction) and straight-through velocity
                    caps in ``resolve_from_sums``.

    Returns (newtrk, newgs, newvs, newalt, asase, asasn): the ASAS command
    arrays (reference stores these on the asas object, MVP.py:103-143).
    """
    dve_p, dvn_p, dvv_p, tsolv_p = pair_contributions(
        cd, alt, gseast, gsnorth, vs, cfg)

    mask = cd.swconfl
    # Nobody avoids a noreso intruder: drop contributions where j is noreso
    # (reference adds the term back, MVP.py:52-56 — same net effect).
    if noreso is not None:
        mask = mask & ~noreso[None, :]

    if wconf is not None:
        # sigmoid weights; excluded/diagonal pairs carry the detect
        # kernel's 1e9 offsets, which drive their weight to exactly 0
        # (the pair fields there are finite masked garbage, so 0 * x
        # stays 0 — no NaN leakage)
        maskf = wconf if noreso is None \
            else wconf * (~noreso[None, :]).astype(dve_p.dtype)
    else:
        maskf = mask.astype(dve_p.dtype)
    vmaskf = maskf
    if cfg.swprio and cfg.priocode != "FF1":
        # Priority rules (MVP.py:235-300), as per-directional-pair apply
        # masks: the reference updates dv1/dv2 per unique pair; with the
        # antisymmetric pair function, "aircraft k solves" means row k
        # keeps its contribution.  Cruising = |vs| < 0.1 m/s.
        cruise = jnp.abs(vs) < 0.1
        ci = cruise[:, None]
        cj = cruise[None, :]
        mixed = ci ^ cj
        if cfg.priocode == "FF2":
            # cruiser has priority: the climbing/descending one solves
            apply = jnp.where(mixed, ~ci, True)
            vapply = apply
        elif cfg.priocode == "FF3":
            # climber/descender has priority: cruiser solves, and in
            # mixed pairs horizontally only (dv_mvp[2] = 0)
            apply = jnp.where(mixed, ci, True)
            vapply = apply & ~mixed
        elif cfg.priocode == "LAY1":
            # all horizontal; climbing/descending solves in mixed pairs
            apply = jnp.where(mixed, ~ci, True)
            vapply = jnp.zeros_like(mixed)
        elif cfg.priocode == "LAY2":
            # all horizontal; cruiser solves in mixed pairs
            apply = jnp.where(mixed, ci, True)
            vapply = jnp.zeros_like(mixed)
        else:
            raise ValueError(
                f"Unknown priocode {cfg.priocode!r}; expected "
                "FF1/FF2/FF3/LAY1/LAY2")
        maskf = maskf * apply
        vmaskf = maskf * vapply

    # Raw pair sums; sign flip + cooperative halving happen in
    # ``resolve_from_sums`` (shared with the tiled large-N path).
    sum_dve = jnp.sum(dve_p * maskf, axis=1)
    sum_dvn = jnp.sum(dvn_p * maskf, axis=1)
    sum_dvv = jnp.sum(dvv_p * vmaskf, axis=1)

    # Vertical solve time: min over this ownship's conflicts (MVP.py:41-42)
    # — the resolver's hard min reduction; softmin in differentiable
    # mode (the documented resolver min/max relaxation, diff/smooth.py)
    if wconf is not None and smooth is not None:
        from ..diff.smooth import softmin_weighted
        tsolv = softmin_weighted(tsolv_p, maskf,
                                 smooth.temp_min * cfg.tlookahead)
    else:
        tsolv = jnp.min(jnp.where(mask, tsolv_p, 1e9), axis=1)

    return resolve_from_sums(
        sum_dve, sum_dvn, sum_dvv, tsolv,
        alt, gseast, gsnorth, vs, trk, gs,
        selalt, ap_vs, prev_alt, vmin, vmax, vsmin, vsmax, cfg,
        resooff=resooff, smooth=smooth)


def resolve_from_sums(sum_dve, sum_dvn, sum_dvv, tsolv,
                      alt, gseast, gsnorth, vs, trk, gs,
                      selalt, ap_vs, prev_alt,
                      vmin, vmax, vsmin, vsmax, cfg,
                      resooff=None, smooth=None):
    """Per-aircraft command synthesis from accumulated pair contributions.

    ``sum_dv*`` are the plain sums over conflict pairs of the per-pair MVP
    displacement (un-negated); ``tsolv`` the per-ownship min vertical solve
    time.  Shared tail of the dense ``resolve`` and the tiled large-N path
    (ops/cd_tiled.py), which produce the same sums without the [N,N] matrices.
    """
    # dv[i] -= sum_j dv_mvp(i,j); vertical component halved because the
    # resolution is cooperative (both aircraft manoeuvre, MVP.py:48-50).
    dve = -sum_dve
    dvn = -sum_dvn
    dvv = -0.5 * sum_dvv

    # Resooff aircraft do no resolutions at all (MVP.py:58-61)
    if resooff is not None:
        keep = ~resooff
        dve = jnp.where(keep, dve, 0.0)
        dvn = jnp.where(keep, dvn, 0.0)
        dvv = jnp.where(keep, dvv, 0.0)

    # New velocity vector (MVP.py:67-76)
    newv_e = dve + gseast
    newv_n = dvn + gsnorth
    newv_v = dvv + vs
    has_reso = dve * dve + dvn * dvn > 0.0

    # Direction limiting (MVP.py:81-101)
    full_trk = jnp.degrees(jnp.arctan2(newv_e, newv_n)) % 360.0
    full_gs = jnp.sqrt(newv_e * newv_e + newv_n * newv_n)
    if cfg.swresohoriz:
        if cfg.swresospd and not cfg.swresohdg:
            newtrk, newgs_, newvs = trk, full_gs, vs
        elif cfg.swresohdg and not cfg.swresospd:
            newtrk, newgs_, newvs = full_trk, gs, vs
        else:
            newtrk, newgs_, newvs = full_trk, full_gs, vs
    elif cfg.swresovert:
        newtrk, newgs_, newvs = trk, gs, newv_v
    else:
        newtrk, newgs_, newvs = full_trk, full_gs, newv_v

    # Velocity caps (MVP.py:106-109) — straight-through in
    # differentiable mode (exact forward, identity backward: the
    # documented clamp STE, diff/smooth.py)
    if smooth is not None and smooth.ste_caps:
        from ..diff.smooth import ste_clip
        newgs_ = ste_clip(newgs_, vmin, vmax)
        newvs = ste_clip(newvs, vsmin, vsmax)
    else:
        newgs_ = jnp.clip(newgs_, vmin, vmax)
        newvs = jnp.clip(newvs, vsmin, vsmax)

    # Resolution vector for display/streams (MVP.py:117-118)
    asase = jnp.where(has_reso, newgs_ * jnp.sin(jnp.radians(newtrk)), 0.0)
    asasn = jnp.where(has_reso, newgs_ * jnp.cos(jnp.radians(newtrk)), 0.0)

    # ASAS altitude command (MVP.py:123-143): follow the AP level-off
    # altitude when it also resolves the conflict...
    signdvs = jnp.sign(newvs - ap_vs * jnp.sign(selalt - alt))
    signalt = jnp.sign(prev_alt - selalt)
    newalt = jnp.where((signdvs == 0) | (signdvs == signalt), prev_alt, selalt)
    # ...else aim at the altitude reached after the vertical solve time
    altcond = (tsolv < cfg.tlookahead) & (jnp.abs(dvv) > 0.0)
    newalt = jnp.where(altcond, newvs * tsolv + alt, newalt)
    if cfg.swresohoriz:
        newalt = selalt
    return newtrk, newgs_, newvs, newalt, asase, asasn


def resume_displacement(lat_own, lon_own, lat_other, lon_other):
    """Flat-earth east/north displacement [m] used by the resume predicates
    (reference asas.py:426-432).  Shared by the [N,N] matrix path and the
    gathered [N,K] partner-table path so the geometry cannot diverge."""
    dist_e = geo.REARTH * (jnp.radians(lon_other - lon_own)
                           * jnp.cos(0.5 * jnp.radians(lat_other + lat_own)))
    dist_n = geo.REARTH * jnp.radians(lat_other - lat_own)
    return dist_e, dist_n


def resume_keep_core(dist_e, dist_n, vrel_e, vrel_n, trk_i, trk_j,
                     alive, rpz, rpz_m):
    """Shape-agnostic resume-nav keep predicate (reference asas.py:426-455).

    A pair stays engaged while not yet past CPA, in horizontal LoS, or in a
    near-parallel "bouncing" encounter.  Shared by the dense [N,N] path
    (``resume_nav``) and the gathered [N,K] partner table
    (``cd_tiled.partner_keep``).
    """
    past_cpa = dist_e * vrel_e + dist_n * vrel_n > 0.0
    hdist = jnp.sqrt(dist_e * dist_e + dist_n * dist_n)
    hor_los = hdist < rpz
    is_bouncing = (jnp.abs(trk_i - trk_j) < 30.0) & (hdist < rpz_m)
    return (~past_cpa | hor_los | is_bouncing) & alive


def resume_nav(resopairs, swlos_unused, lat, lon, gseast, gsnorth, trk,
               active_ac, rpz, rpz_m):
    """Vectorized ResumeNav (reference asas.py:409-471).

    Decides per surviving resolution pair whether ASAS stays engaged: a pair
    is kept while the aircraft have not yet passed their CPA, are in
    horizontal LOS, or are in a "bouncing" near-parallel encounter.  The
    reference iterates a Python set of pairs; here ``resopairs`` is an [N,N]
    bool matrix and the same predicates are evaluated for all pairs at once.

    Returns (new_resopairs, asas_active):
      asas_active[i] = any pair (i, j) still demanding resolution.
    """
    dist_e, dist_n = resume_displacement(lat[:, None], lon[:, None],
                                         lat[None, :], lon[None, :])

    vrel_e = gseast[None, :] - gseast[:, None]
    vrel_n = gsnorth[None, :] - gsnorth[:, None]

    # Drop pairs whose intruder was deleted (reference asas.py:419-421)
    alive = active_ac[:, None] & active_ac[None, :]
    keep = resume_keep_core(dist_e, dist_n, vrel_e, vrel_n,
                            trk[:, None], trk[None, :], alive, rpz, rpz_m)
    new_resopairs = resopairs & keep
    asas_active = jnp.any(new_resopairs, axis=1)
    return new_resopairs, asas_active
