"""Inverse-trig built from VPU-supported primitives, for Pallas kernels.

Mosaic's TPU lowering has no atan/atan2/asin (only sin/cos/sqrt/exp/log —
probed on hardware), but the conflict-detection geometry needs bearing
(atan2) and the MVP erratum term (arcsin).  These are classic Cephes-style
float32 evaluations: an odd minimax polynomial on |z| <= tan(pi/8) with the
two standard range reductions (reciprocal for |z| > 1, the tan(pi/8)
rotation otherwise), accurate to ~1 ulp f32 — well inside the f32 noise of
the surrounding haversine math.

The shared geometry cores (``geo._haversine_qdr_dist``,
``cr_mvp.pair_contrib_core``) take these as injectable parameters defaulting
to the exact jnp versions, so only the Pallas kernel pays the approximation.
"""
import jax.numpy as jnp

_PI = 3.14159265358979323846
_PI_2 = 1.57079632679489661923
_PI_4 = 0.78539816339744830962
_TAN_PI_8 = 0.41421356237309503


def _atan_pos(z):
    """arctan for z >= 0 (Cephes atanf reduction + degree-7 odd poly)."""
    big = z > 1.0
    zr = jnp.where(big, 1.0 / jnp.maximum(z, 1e-30), z)
    red = zr > _TAN_PI_8
    z2 = jnp.where(red, (zr - 1.0) / (zr + 1.0), zr)
    zz = z2 * z2
    p = ((8.05374449538e-2 * zz - 1.38776856032e-1) * zz
         + 1.99777106478e-1) * zz - 3.33329491539e-1
    y = z2 + z2 * zz * p
    y = jnp.where(red, y + _PI_4, y)
    return jnp.where(big, _PI_2 - y, y)


def atan(x):
    return jnp.sign(x) * _atan_pos(jnp.abs(x))


def atan2(y, x):
    """Four-quadrant arctangent; matches jnp.arctan2 on finite inputs
    (including the axes: atan2(0, x>0)=0, atan2(0, x<0)=pi, atan2(0,0)=0)."""
    ax = jnp.abs(x)
    ay = jnp.abs(y)
    base = _atan_pos(ay / jnp.maximum(ax, 1e-30))
    ang = jnp.where(x >= 0.0, base, _PI - base)
    return jnp.where(y >= 0.0, ang, -ang)


def asin(x):
    """arcsin on [-1, 1] via atan2(x, sqrt(1-x^2))."""
    x = jnp.clip(x, -1.0, 1.0)
    return atan2(x, jnp.sqrt(jnp.maximum(0.0, 1.0 - x * x)))


def asin_taylor(s):
    """Odd Taylor arcsin for the haversine arc length, |s| <= 1.

    Error bounds that matter for conflict detection (s = sin(d/2R)):
    < 1e-9 relative for d <= 400 km — and a pair beyond ~400 km can
    neither be in LoS (d >> rpz) nor enter conflict within the 300 s
    lookahead (closing speed would have to exceed 1.3 km/s), so every
    distance that can flip a conflict/LoS flag is evaluated to full f32
    precision.  For far pairs the polynomial *under*-estimates the arc
    (up to 16% at the antipode), which cannot create a false conflict:
    dcpa scales with dist, so shrinking a >400 km pair still leaves
    dcpa orders of magnitude above the protected zone.
    """
    s2 = s * s
    return s * (1.0 + s2 * (1.0 / 6.0 + s2 * (3.0 / 40.0 + s2 * (
        15.0 / 336.0 + s2 * (105.0 / 3456.0)))))
