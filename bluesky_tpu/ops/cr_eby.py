"""Eby conflict resolution, vectorized.

Parity with the reference ``traffic/asas/Eby.py:15-138`` (Eby-method
geometric resolution assuming straight-line motion): for each conflict
pair, find the time ``tstar`` maximizing intrusion-over-time via the
quadratic formula, evaluate the relative position there, and displace
the velocity vector by ``intrusion * drelstar / (dstarabs * tstar)``.

TPU-first redesign: the reference solves each pair in a Python loop over
the conflict list (Eby.py:26-38); here every [N, N] pair solves in one
broadcast and the per-aircraft displacement is the masked row sum —
the same segment-sum treatment as the MVP kernel.  The reference applies
``dv[id1] -= dv_eby; dv[id2] += dv_eby`` per unique pair; with the
directional conflict matrix, ``dv_pair(j, i) == -dv_pair(i, j)``, so
``dv[i] = -sum_j swconfl[i,j] * dv_pair(i,j)`` reproduces both updates.

NB the reference's final assignment stores capped EAS in ``asas.tas``
(Eby.py:55-61) — a reference quirk kept for parity.
"""
import jax.numpy as jnp

from . import aero


def pair_contrib(dx, dy, dz, vx, vy, vz, rpz_m):
    """Per-pair Eby displacement (Eby.py:73-138), shape-agnostic.

    ``dx/dy/dz``: relative position of the intruder w.r.t. the ownship;
    ``vx/vy/vz``: relative TAS-based velocity (v_j - v_i).  Returns
    (dve_p, dvn_p, dvv_p); callers sum over conflict pairs and NEGATE
    (the reference applies ``dv[id1] -= dv_eby`` per pair).  Shared by
    the dense matrix path and the tiled/pallas/sparse kernels so the
    math cannot drift.

    Evaluated in protected-zone-radius units: in meters the quadratic's
    ``b*b`` overflows float32 for pairs a few hundred km apart
    (b ~ dist^2 * vrel * 2 ~ 1e19), and the inf - inf NaN then leaks
    through the masked conflict-pair sums (NaN * 0 = NaN).  Scaling
    positions AND velocities by 1/rpz_m keeps every intermediate in
    range for any airspace-scale separation; tstar is scale-invariant
    and the output displacement just unscales.
    """
    eps = 1e-12
    s = 1.0 / rpz_m
    dx, dy, dz = dx * s, dy * s, dz * s
    vx, vy, vz = vx * s, vy * s, vz * s
    rpz_m = 1.0
    r2 = rpz_m * rpz_m
    d2 = dx * dx + dy * dy + dz * dz
    v2 = vx * vx + vy * vy + vz * vz
    dv = dx * vx + dy * vy + dz * vz

    # Quadratic for tstar (Eby.py:104-117)
    a = r2 * v2 - dv * dv
    b = 2.0 * dv * (r2 - d2)
    c = r2 * d2 - d2 * d2
    discrim = jnp.maximum(b * b - 4.0 * a * c, 0.0)
    a_safe = jnp.where(jnp.abs(a) < eps, eps, a)
    sq = jnp.sqrt(discrim)
    time1 = (-b + sq) / (2.0 * a_safe)
    time2 = (-b - sq) / (2.0 * a_safe)
    tstar = jnp.minimum(jnp.abs(time1), jnp.abs(time2))

    # Relative position at tstar (Eby.py:120-122)
    dsx = dx + vx * tstar
    dsy = dy + vy * tstar
    dsz = dz + vz * tstar
    dstarabs = jnp.sqrt(dsx * dsx + dsy * dsy + dsz * dsz)

    # Exact-collision-course fix (Eby.py:125-131): if passing within
    # 10 m, push drelstar out sideways to 10 m
    dif = 10.0 * s - dstarabs
    vperp_norm = jnp.sqrt(vy * vy + vx * vx)
    vp_safe = jnp.where(vperp_norm < eps, eps, vperp_norm)
    fixmask = dif > 0.0
    dsx = dsx + fixmask * dif * (-vy) / vp_safe
    dsy = dsy + fixmask * dif * vx / vp_safe
    dstarabs = jnp.sqrt(dsx * dsx + dsy * dsy + dsz * dsz)

    # Intrusion and displacement (Eby.py:134-138); the 1/s restores the
    # velocity scale (dsx and intr both carry one factor of s)
    intr = rpz_m - dstarabs
    denom = dstarabs * tstar
    denom = jnp.where(jnp.abs(denom) < eps, eps, denom)
    scale = intr / (denom * s)
    return scale * dsx, scale * dsy, scale * dsz


def resolve_from_sums(sum_dve, sum_dvn, sum_dvv, alt, vs, trk, tas,
                      vmin, vmax):
    """Eby commands from the per-ownship conflict-pair sums (the tiled/
    sparse backends accumulate them blockwise; the negation of the
    reference's ``dv[id1] -= dv_eby`` is applied here).  Eby.py:42-61."""
    trkrad = jnp.radians(trk)
    ve = tas * jnp.sin(trkrad)
    vn = tas * jnp.cos(trkrad)
    newv_e = -sum_dve + ve
    newv_n = -sum_dvn + vn
    newv_v = -sum_dvv + vs
    newtrk = jnp.degrees(jnp.arctan2(newv_e, newv_n)) % 360.0
    newgs = jnp.sqrt(newv_e * newv_e + newv_n * newv_n)
    neweas = aero.vtas2eas(newgs, alt)
    newtas = jnp.clip(neweas, vmin, vmax)
    newalt = jnp.sign(newv_v) * 1e5
    return newtrk, newtas, newv_v, newalt


def resolve(cd, alt, vs, trk, tas, rpz_m, vmin, vmax):
    """Eby resolution commands.

    Args:
      cd:       ConflictData (ops/cd.py) — swconfl/qdr/dist matrices
      alt/vs:   [N] state arrays
      trk/tas:  [N] track + TRUE AIRSPEED — the reference builds its
                velocity vectors from tas, not groundspeed (Eby.py:44-46,
                84-87), so the EAS cap stays wind-independent
      rpz_m:    resolution zone radius Rm [m] (asas.Rm)
      vmin/vmax: EAS caps [m/s]
    Returns (newtrk, newtas, newvs, newalt) per aircraft.
    """
    maskf = cd.swconfl.astype(tas.dtype)
    trkrad = jnp.radians(trk)
    ve = tas * jnp.sin(trkrad)
    vn = tas * jnp.cos(trkrad)

    # Pairwise relative position (Eby.py:73-78)
    qdrrad = jnp.radians(cd.qdr)
    dx = cd.dist * jnp.sin(qdrrad)
    dy = cd.dist * jnp.cos(qdrrad)
    dz = alt[None, :] - alt[:, None]

    # Relative velocity v = v_j - v_i (Eby.py:85-87)
    vx = ve[None, :] - ve[:, None]
    vy = vn[None, :] - vn[:, None]
    vz = vs[None, :] - vs[:, None]

    dve_p, dvn_p, dvv_p = pair_contrib(dx, dy, dz, vx, vy, vz, rpz_m)

    # dv[i] = -sum_j over conflict pairs (see module docstring); the
    # negation lives in resolve_from_sums.
    return resolve_from_sums(jnp.sum(dve_p * maskf, axis=1),
                             jnp.sum(dvn_p * maskf, axis=1),
                             jnp.sum(dvv_p * maskf, axis=1),
                             alt, vs, trk, tas, vmin, vmax)
