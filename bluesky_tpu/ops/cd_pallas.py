"""Pallas TPU kernel for the fused blockwise CD&R pass.

Same computation as ``ops/cd_tiled.py`` (which is the portable lax.scan
formulation and the golden-test oracle for this kernel): the N x N pair space
of the state-based conflict detection (reference
``bluesky/traffic/asas/StateBasedCD.py``) plus the MVP displacement sums
(reference ``MVP.py:14-143``) is computed in [block, block] tiles and reduced
per ownship, never materialising an N² array.

Here the tile loop is a real TPU kernel: the grid is (ownship blocks,
intruder blocks), each program reads two [_NF, block] slabs of packed
aircraft state from VMEM, evaluates the CPA geometry + MVP contribution on a
[block, block] tile with the VPU, and accumulates the per-ownship reductions
in-place in the output blocks (revisited across the intruder grid dimension
— the standard Pallas accumulation pattern).  The pair math is the *same
code* as the lax backend — ``cd_tiled.tile_geometry`` (rank-1-factored
haversine, VPU-lean: rsqrt bearings + odd-Taylor arcsin arc length from
``kmath``) and ``cr_mvp.pair_contrib_trig`` are shape-agnostic jnp and trace
straight into the kernel — so the tiled backends cannot drift apart.

Layout note: the tile is oriented **intruder-major**: intruders vary along
sublanes (axis 0), ownships along lanes (axis 1).  Per-ownship reductions
are then axis-0 reduces that land in the natural (1, block) lane layout of
the accumulator blocks; only the intruder-side operands need a
(1, block) -> (block, 1) relayout.

Partner candidates for resume-nav hysteresis: a running top-K (by earliest
conflict-entry time) is accumulated in the candidate output refs across the
intruder-block grid dimension — K-pass masked index-min extraction per tile,
skipped entirely for conflict-free tiles — so the kernel yields exactly the
K most urgent intruders per ownship, same as ``cd_tiled``'s carry-based
top-K merge.
"""
import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import cd_tiled, cr_mvp
from .cd_tiled import RowConflictData, TRIG_FIELDS, block_reachability, \
    precompute_trig, tile_geometry

# Packed state row order for the [nb, 16, block] slabs: 6 trig/geometry
# columns (cd_tiled.TRIG_FIELDS), the gs velocity components + altitude
# columns, the track angle (resume-nav "bouncing" predicate), the
# tas/gs ratio (Eby builds its velocity from TAS: ve = tr*u), then the
# active and noreso masks.
#
# The "tr" row is OVERLOADED per resolver: Eby reads it as the tas/gs
# ratio, Swarm reads it as the calibrated airspeed (its alignment term,
# Swarm.py:75-84) — the two resolvers never combine, and reusing the
# slot keeps the slab at 16 rows (a 17th would break the whole-vreg
# alignment of the sched kernel's Element-indexed slabs and cost ~25%
# more slab DMA in every mode).
_FIELDS = TRIG_FIELDS + ("u", "v", "alt", "vs", "gse", "gsn", "trk",
                         "tr", "active", "noreso")
_NF = len(_FIELDS)
_IDX = {k: i for i, k in enumerate(_FIELDS)}
_BIG = 1e9

#: number of per-ownship Swarm neighbour-sum accumulators appended to
#: the kernel outputs when reso == "swarm": w, w*cas, w*vs, w*dtrk,
#: w*dx, w*dy, w*alt (cr_swarm.resolve_from_sums input order).
_N_SWARM = 7

#: Identity elements of the 10 accumulator outputs, in output-tuple order:
#: inconf, tcpamax, sdve, sdvn, sdvv, tsolv, ncnt, lcnt, ctin, cidx.
#: Single source of truth for every kernel's accumulator-init block.
_ACC_NEUTRAL = (0.0, 0.0, 0.0, 0.0, 0.0, _BIG, 0.0, 0.0, _BIG, 2**30)


def shard_map_compat(body, mesh, in_specs, out_specs):
    """``jax.shard_map`` across JAX generations: the top-level API with
    ``check_vma`` (>= 0.6), else the experimental module with its older
    ``check_rep`` spelling (0.4.x) — replication checking off in both
    (the bodies use collectives the checker cannot see through)."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(body, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=False)
    from jax.experimental.shard_map import shard_map
    return shard_map(body, mesh=mesh, in_specs=in_specs,
                     out_specs=out_specs, check_rep=False)


def _init_accumulators(refs, block, kk):
    """Write the identity element into each accumulator ref (10 refs in
    output order)."""
    for ref, v in zip(refs[:8], _ACC_NEUTRAL[:8]):
        ref[0] = jnp.full((1, block), v, jnp.float32)
    refs[8][0] = jnp.full((kk, block), _ACC_NEUTRAL[8], jnp.float32)
    refs[9][0] = jnp.full((kk, block), _ACC_NEUTRAL[9], jnp.int32)


def _kernel(reach_ref, row0_ref, own_ref, intr_ref,
            inconf_ref, tcpamax_ref, sdve_ref, sdvn_ref, sdvv_ref,
            tsolv_ref, ncnt_ref, lcnt_ref, ctin_ref, cidx_ref,
            *swarm_refs, block, kk, cpp, rpz, hpz, tlookahead, mvpcfg,
            same_hemi=False, reso="mvp", rstride=1):
    ib = pl.program_id(0)
    jp = pl.program_id(1)      # program handles cpp column tiles
    # Global row id of local row i is row0 + i*rstride (0/1 except
    # under shard_map, where each device owns a strided row subset of
    # the global grid but column/partner ids stay global; the stride
    # interleaves rows across devices for load balance).  col0 offsets
    # intruder ids the same way when the COLUMN slabs are a local halo
    # window rather than the full grid (the domain-decomposition mesh
    # mode of ops/cd_sched.py): DMA/reach indices stay local, global
    # ids = (col0 + local block) * block + lane.
    row0 = row0_ref[0, 0]
    col0 = row0_ref[0, 1]

    # Initialise the accumulators on the first intruder program; the
    # tile compute below is skipped entirely for unreachable tiles, so
    # the init must not depend on it.  Accumulating t >= 0 maxima into
    # 0 / minima into BIG reproduces the former set-at-jb==0 semantics.
    @pl.when(jp == 0)
    def _():
        _init_accumulators((inconf_ref, tcpamax_ref, sdve_ref, sdvn_ref,
                            sdvv_ref, tsolv_ref, ncnt_ref, lcnt_ref,
                            ctin_ref, cidx_ref), block, kk)
        for ref in swarm_refs:
            ref[0] = jnp.zeros((1, block), jnp.float32)

    # Exact block-level reachability skip (cd_tiled.block_reachability):
    # a scalar-predicated branch in Mosaic, so unreachable tiles cost no
    # VPU work.  The cpp sub-tiles run sequentially in one program,
    # amortizing grid/DMA overhead (skipped sub-tiles still skip).
    # reach_ref holds a BIT-PACKED 8-row SMEM window around the current
    # row (the whole [nb, nb] matrix is 61 MB of SMEM at N=1M, and even
    # one unpacked row breaks the SMEM budget there; 8-row granularity
    # because SMEM block rows must be 8-divisible).
    for k in range(cpp):
        jb = jp * cpp + k

        @pl.when(((reach_ref[ib % 8, jb // 32] >> (jb % 32)) & 1) > 0)
        def _compute(k=k, jb=jb):
            _tile_body(ib, col0 + jb, k, own_ref, intr_ref, inconf_ref,
                       tcpamax_ref, sdve_ref, sdvn_ref, sdvv_ref,
                       tsolv_ref, ncnt_ref, lcnt_ref, ctin_ref,
                       cidx_ref, block=block, kk=kk, rpz=rpz, hpz=hpz,
                       tlookahead=tlookahead, mvpcfg=mvpcfg,
                       same_hemi=same_hemi, reso=reso, row_off=row0,
                       row_stride=rstride, swarm_refs=swarm_refs or None)


def _tile_body(ib, jb, ksub, own_ref, intr_ref,
               inconf_ref, tcpamax_ref, sdve_ref, sdvn_ref, sdvv_ref,
               tsolv_ref, ncnt_ref, lcnt_ref, ctin_ref, cidx_ref,
               *, block, kk, rpz, hpz, tlookahead, mvpcfg,
               same_hemi=False, resume_refs=None, rpz_m=None, reso="mvp",
               row_off=0, row_stride=1, swarm_refs=None):
    oslab = own_ref[0]                                    # (_NF, block)
    islab_t = intr_ref[ksub].T                            # (block, _NF): ONE
    # lane->sublane relayout shared by all intruder columns

    def own(k):            # ownship operand, varies along lanes: (1, block)
        return oslab[_IDX[k]:_IDX[k] + 1, :]

    def intr(k):           # intruder operand, varies along sublanes
        return islab_t[:, _IDX[k]:_IDX[k] + 1]            # (block, 1)

    gid_own = (row_off + ib * row_stride) * block \
        + jax.lax.broadcasted_iota(
            jnp.int32, (1, block), 1)                     # ownships on lanes
    gid_int = jb * block + jax.lax.broadcasted_iota(
        jnp.int32, (block, 1), 0)                         # intruders sublanes
    act_o = own("active") > 0.5                           # (1, block)
    act_i = intr("active") > 0.5                          # (block, 1)
    pairmask = (act_o & act_i) & (gid_own != gid_int)

    # All-inactive tiles (sentinel/padding worklist entries, empty blocks)
    # contribute nothing — skip the whole geometry for the cost of one
    # OR-reduce.
    @pl.when(jnp.any(pairmask))
    def _live_tile():
        _tile_pairs(pairmask, gid_int, own, intr, inconf_ref, tcpamax_ref,
                    sdve_ref, sdvn_ref, sdvv_ref, tsolv_ref, ncnt_ref,
                    lcnt_ref, ctin_ref, cidx_ref, kk=kk, rpz=rpz, hpz=hpz,
                    tlookahead=tlookahead, mvpcfg=mvpcfg,
                    same_hemi=same_hemi, jb=jb, resume_refs=resume_refs,
                    rpz_m=rpz_m, reso=reso, swarm_refs=swarm_refs)


def _tile_pairs(pairmask, gid_int, own, intr,
                inconf_ref, tcpamax_ref, sdve_ref, sdvn_ref, sdvv_ref,
                tsolv_ref, ncnt_ref, lcnt_ref, ctin_ref, cidx_ref,
                *, kk, rpz, hpz, tlookahead, mvpcfg, same_hemi=False,
                jb=None, resume_refs=None, rpz_m=None, reso="mvp",
                swarm_refs=None):
    block = pairmask.shape[1]
    excl = jnp.where(pairmask, 0.0, _BIG)

    # Horizontal geometry — the factored haversine (cd_tiled.tile_geometry),
    # evaluated [intruder, ownship] so per-ownship reductions are axis 0.
    trig_o = {k: own(k) for k in TRIG_FIELDS}
    trig_i = {k: intr(k) for k in TRIG_FIELDS}
    dist0, sinqdr, cosqdr = tile_geometry(trig_o, trig_i,
                                          same_hemisphere=same_hemi)
    dist = dist0 + excl
    dx = dist * sinqdr
    dy = dist * cosqdr

    du = intr("u") - own("u")
    dv = intr("v") - own("v")
    dv2 = du * du + dv * dv
    dv2 = jnp.where(jnp.abs(dv2) < 1e-6, 1e-6, dv2)
    # Same rsqrt-based CPA math as cd_tiled.tile — kept in lockstep
    rvrel = jax.lax.rsqrt(dv2)

    tcpa = -(du * dx + dv * dy) * (rvrel * rvrel) + excl
    dcpa2 = dist * dist - tcpa * tcpa * dv2
    r2 = rpz * rpz
    swhorconf = dcpa2 < r2

    dtinhor = jnp.sqrt(jnp.maximum(0.0, r2 - dcpa2)) * rvrel
    tinhor = jnp.where(swhorconf, tcpa - dtinhor, 1e8)
    touthor = jnp.where(swhorconf, tcpa + dtinhor, -1e8)

    dalt = intr("alt") - own("alt") + excl
    dvs = intr("vs") - own("vs")
    dvs = jnp.where(jnp.abs(dvs) < 1e-6, 1e-6, dvs)
    nrdvs = -1.0 / dvs
    tcrosshi = (dalt + hpz) * nrdvs
    tcrosslo = (dalt - hpz) * nrdvs
    tinver = jnp.minimum(tcrosshi, tcrosslo)
    toutver = jnp.maximum(tcrosshi, tcrosslo)

    tinconf = jnp.maximum(tinver, tinhor)
    toutconf = jnp.minimum(toutver, touthor)
    swconfl = (swhorconf & (tinconf <= toutconf) & (toutconf > 0.0)
               & (tinconf < tlookahead) & pairmask)
    swlos = (dist < rpz) & (jnp.abs(dalt) < hpz) & pairmask

    # Everything past the flags only matters when the tile has at least one
    # conflict or LoS pair: every accumulator update below is then a no-op
    # (max with 0, sum with 0, min with BIG).  Conflicts are rare even in
    # *reachable* tiles, so predicating the whole MVP + reduction tail on a
    # single any-hit flag cuts the common tile to the core CPA geometry.
    @pl.when(jnp.any(swconfl | swlos))
    def _accumulate():
        if reso == "eby":
            # Eby pair displacement (cr_eby.pair_contrib — same code as
            # the dense matrix path) built on TAS velocities via the
            # per-aircraft tas/gs ratio column: ve = tr*u.
            from . import cr_eby
            dve_p, dvn_p, dvv_p = cr_eby.pair_contrib(
                dx, dy, intr("alt") - own("alt"),
                intr("tr") * intr("u") - own("tr") * own("u"),
                intr("tr") * intr("v") - own("tr") * own("v"),
                intr("vs") - own("vs"), mvpcfg.rpz_m)
            tsolv_p = jnp.full_like(dve_p, _BIG)
            mvpmask = swconfl           # Eby has no noreso handling
        else:
            dve_p, dvn_p, dvv_p, tsolv_p = cr_mvp.pair_contrib_trig(
                sinqdr, cosqdr, dist, tcpa, tinconf,
                intr("alt") - own("alt"), intr("gse") - own("gse"),
                intr("gsn") - own("gsn"), intr("vs") - own("vs"), mvpcfg)
            nor_i = intr("noreso") > 0.5
            mvpmask = swconfl & ~nor_i
        maskf = mvpmask.astype(dist.dtype)

        conff = swconfl.astype(dist.dtype)
        t_inconf = jnp.max(conff, axis=0, keepdims=True)
        t_tcpamax = jnp.max(tcpa * conff, axis=0, keepdims=True)
        t_sdve = jnp.sum(dve_p * maskf, axis=0, keepdims=True)
        t_sdvn = jnp.sum(dvn_p * maskf, axis=0, keepdims=True)
        t_sdvv = jnp.sum(dvv_p * maskf, axis=0, keepdims=True)
        t_tsolv = jnp.min(jnp.where(mvpmask, tsolv_p, _BIG),
                          axis=0, keepdims=True)
        t_ncnt = jnp.sum(conff, axis=0, keepdims=True)
        t_lcnt = jnp.sum(swlos.astype(dist.dtype), axis=0, keepdims=True)

        inconf_ref[0] = jnp.maximum(inconf_ref[0], t_inconf)
        tcpamax_ref[0] = jnp.maximum(tcpamax_ref[0], t_tcpamax)
        sdve_ref[0] = sdve_ref[0] + t_sdve
        sdvn_ref[0] = sdvn_ref[0] + t_sdvn
        sdvv_ref[0] = sdvv_ref[0] + t_sdvv
        tsolv_ref[0] = jnp.minimum(tsolv_ref[0], t_tsolv)
        ncnt_ref[0] = ncnt_ref[0] + t_ncnt
        lcnt_ref[0] = lcnt_ref[0] + t_lcnt

    if reso == "swarm":
        # Swarm neighbour sums (reference Swarm.py:47-66 via
        # cr_swarm.pair_weight — the same predicate the lax tiled and
        # dense paths use, so the three backends cannot drift).  The
        # neighbourhood (7.5 nm / 1500 ft / <90 deg track) is far rarer
        # than reachability, so the whole accumulation is predicated on
        # one any-neighbour flag.  The "tr" slab row carries cas in
        # swarm mode (see the _FIELDS note).
        from . import cr_swarm
        dtrk = (intr("trk") - own("trk") + 180.0) % 360.0 - 180.0
        dalt_raw = intr("alt") - own("alt")
        w_mask = cr_swarm.pair_weight(dx, dy, dalt_raw, dtrk, pairmask)

        @pl.when(jnp.any(w_mask))
        def _swarm_sums():
            wf = w_mask.astype(dist.dtype)
            terms = (wf, wf * intr("tr"), wf * intr("vs"), wf * dtrk,
                     wf * dx, wf * dy, wf * intr("alt"))
            for ref, t in zip(swarm_refs, terms):
                ref[0] = ref[0] + jnp.sum(t, axis=0, keepdims=True)

    # In-kernel resume-nav: evaluate the keep predicate for every OLD
    # partner pair this tile visits (reference asas.py:426-455 — the
    # same cr_mvp.resume_keep_core the host paths use, so the math
    # cannot drift).  The tile already holds all required pair state, so
    # this replaces the [N,K] gather storm of the host-side
    # ``cd_tiled.partner_keep`` (measured ~60 ms/interval at N=100k with
    # TPU gathers serializing at ~30 ns/element).  Pairs OUTSIDE the
    # visited windows are provably non-conflicting within the lookahead
    # AND out of LoS; the kernel path releases them (no keep bit) — a
    # documented, bounded divergence from the dense path, which can hold
    # a far-but-approaching pair engaged until CPA (such pairs re-engage
    # on their next detection).
    def _extract_merge(cand_mask):
        """Fold this tile's candidate conflicts (cand_mask) into the
        running per-ownship top-kk held in the candidate refs.
        Extraction is masked index-min passes (argmin has no stable
        Mosaic lowering); the pass count is bounded by the tile's MAX
        per-ownship candidate count (usually 1-3 ≪ kk) — passes beyond
        it would only extract the BIG sentinel, which is exactly what
        the unrun passes' slots hold."""
        urg0 = jnp.where(cand_mask, tinconf, _BIG)
        cmax = jnp.max(jnp.sum(cand_mask.astype(jnp.int32), axis=0))
        pio = jax.lax.broadcasted_iota(jnp.int32, (kk, block), 0)
        carry0 = (urg0,
                  jnp.full((kk, block), _BIG, urg0.dtype),
                  jnp.full((kk, block), 2**30, jnp.int32))

        def extract(p, carry):
            urg, tins, idxs = carry
            minv = jnp.min(urg, axis=0, keepdims=True)    # (1, block)
            jloc = jnp.min(jnp.where(urg == minv, gid_int, jnp.int32(2**30)),
                           axis=0, keepdims=True)
            tins = jnp.where(pio == p, minv, tins)
            idxs = jnp.where(pio == p, jloc, idxs)
            urg = jnp.where(gid_int == jloc, _BIG, urg)
            return urg, tins, idxs

        _, tins, idxs = jax.lax.fori_loop(
            0, jnp.minimum(cmax, kk), extract, carry0)
        cat_t = jnp.concatenate([ctin_ref[0], tins], axis=0)    # (2kk, block)
        cat_i = jnp.concatenate([cidx_ref[0], idxs], axis=0)
        rio = jax.lax.broadcasted_iota(jnp.int32, (2 * kk, block), 0)
        new_t, new_i = [], []
        for _s in range(kk):
            minv = jnp.min(cat_t, axis=0, keepdims=True)
            rloc = jnp.min(jnp.where(cat_t == minv, rio, jnp.int32(2**30)),
                           axis=0, keepdims=True)
            sel = jnp.min(jnp.where(rio == rloc, cat_i, jnp.int32(2**30)),
                          axis=0, keepdims=True)
            new_t.append(minv)
            new_i.append(sel)
            cat_t = jnp.where(rio == rloc, _BIG, cat_t)
        ctin_ref[0] = jnp.concatenate(new_t, axis=0)
        cidx_ref[0] = jnp.concatenate(new_i, axis=0)

    if resume_refs is None:
        # Partner candidates only; conflict-free tiles skip entirely.
        @pl.when(jnp.any(swconfl))
        def _():
            _extract_merge(swconfl)
    else:
        # In-kernel resume-nav (reference asas.py:409-471, the same
        # cr_mvp.resume_keep_core the host paths use so the math cannot
        # drift): evaluate the keep predicate for every visited pair,
        # (a) OR it into the keep bits of OLD partner pairs present in
        # this tile, and (b) filter the FRESH candidates with it — the
        # dense path prunes the union (old | swconfl) through resume_nav
        # each interval, so a fresh conflict already past CPA must not
        # enter the table either.  Pairs OUTSIDE the visited windows are
        # provably non-conflicting within the lookahead AND out of LoS;
        # the kernel path releases them — a documented, bounded
        # divergence from the dense path, which can hold a
        # far-but-approaching pair engaged until CPA (such pairs
        # re-engage on their next detection).
        pold_ref, keep_ref = resume_refs
        pold = pold_ref[0]                        # (kk, block) sorted ids
        in_rng = (pold >= jb * block) & (pold < (jb + 1) * block)

        @pl.when(jnp.any(in_rng) | jnp.any(swconfl))
        def _resume_and_candidates():
            # Flat-earth displacement of cr_mvp.resume_displacement from
            # per-aircraft trig: cos(0.5*(lat_o+lat_i)) =
            # sqrt((1+cos(lat_o+lat_i))/2), exact for |lat sum| <= 180.
            cos_sum = own("cl") * intr("cl") - own("sl") * intr("sl")
            cos_half = jnp.sqrt(jnp.maximum(0.5 + 0.5 * cos_sum, 0.0))
            from . import geo
            dist_e = geo.REARTH * jnp.radians(intr("lon") - own("lon")) \
                * cos_half
            dist_n = geo.REARTH * jnp.radians(intr("lat") - own("lat"))
            vrel_e = intr("gse") - own("gse")
            vrel_n = intr("gsn") - own("gsn")
            keep_pair = cr_mvp.resume_keep_core(
                dist_e, dist_n, vrel_e, vrel_n, own("trk"), intr("trk"),
                pairmask, rpz, rpz_m)

            @pl.when(jnp.any(in_rng))
            def _keep_old():
                for k in range(kk):
                    match = (gid_int == pold[k:k + 1, :]) & keep_pair
                    hit = jnp.max(match.astype(jnp.float32), axis=0,
                                  keepdims=True)
                    keep_ref[0, k:k + 1] = jnp.maximum(
                        keep_ref[0, k:k + 1], hit)

            @pl.when(jnp.any(swconfl))
            def _fresh():
                _extract_merge(swconfl & keep_pair)


def _merge_partners_block(pold_ref, keep_ref, ctin_ref, cidx_ref,
                          pnew_ref, pact_ref, kk):
    """In-kernel partner merge for one ownship block (kernel-space
    equivalent of ``cd_tiled.merge_partners`` + the active flag).

    Fresh conflict candidates (already urgency-ordered in the ctin/cidx
    refs) take the leading slots; old partners surviving their keep bit
    fill the rest in original slot order; duplicates are dropped.  The
    compaction is ``kk`` masked-min selection passes over the (2kk,
    block) concatenation — pure VPU, no sort."""
    big_i = jnp.int32(2 ** 30)
    new_ids = jnp.where(ctin_ref[0] < _BIG, cidx_ref[0], -1)   # (kk, block)
    old_ids = jnp.where(keep_ref[0] > 0.5, pold_ref[0], -1)
    dup = jnp.zeros_like(old_ids, dtype=bool)
    for m in range(kk):
        nm = new_ids[m:m + 1, :]
        dup = dup | ((old_ids == nm) & (nm >= 0))
    old_ids = jnp.where(dup, -1, old_ids)

    cat = jnp.concatenate([new_ids, old_ids], axis=0)          # (2kk, block)
    rio = jax.lax.broadcasted_iota(jnp.int32, cat.shape, 0)
    key = jnp.where(cat >= 0, rio, big_i)
    outs = []
    for _s in range(kk):
        m = jnp.min(key, axis=0, keepdims=True)
        val = jnp.min(jnp.where(key == m, cat, big_i), axis=0,
                      keepdims=True)
        outs.append(jnp.where(m < big_i, val, -1))
        key = jnp.where(key == m, big_i, key)
    pnew = jnp.concatenate(outs, axis=0)
    pnew_ref[0] = pnew
    pact_ref[0] = jnp.max((pnew >= 0).astype(jnp.float32), axis=0,
                          keepdims=True)


def _kernel_resume(reach_ref, row0_ref, own_ref, intr_ref, pold_ref,
                   inconf_ref, tcpamax_ref, sdve_ref, sdvn_ref, sdvv_ref,
                   tsolv_ref, ncnt_ref, lcnt_ref, ctin_ref, cidx_ref,
                   keep_ref, pnew_ref, pact_ref,
                   *swarm_refs, block, kk, cpp, rpz, hpz, tlookahead,
                   mvpcfg, rpz_m, same_hemi=False, reso="mvp", rstride=1):
    """Full-grid kernel with in-kernel resume-nav (the sparse scheduler's
    overflow fallback): same tile sweep as ``_kernel`` plus the keep
    evaluation per visited tile and the partner merge on the last
    intruder program."""
    ib = pl.program_id(0)
    jp = pl.program_id(1)
    row0 = row0_ref[0, 0]
    col0 = row0_ref[0, 1]

    @pl.when(jp == 0)
    def _():
        _init_accumulators((inconf_ref, tcpamax_ref, sdve_ref, sdvn_ref,
                            sdvv_ref, tsolv_ref, ncnt_ref, lcnt_ref,
                            ctin_ref, cidx_ref), block, kk)
        keep_ref[0] = jnp.zeros((kk, block), jnp.float32)
        for ref in swarm_refs:
            ref[0] = jnp.zeros((1, block), jnp.float32)

    for k in range(cpp):
        jb = jp * cpp + k

        @pl.when(((reach_ref[ib % 8, jb // 32] >> (jb % 32)) & 1) > 0)
        def _compute(k=k, jb=jb):
            _tile_body(ib, col0 + jb, k, own_ref, intr_ref, inconf_ref,
                       tcpamax_ref, sdve_ref, sdvn_ref, sdvv_ref,
                       tsolv_ref, ncnt_ref, lcnt_ref, ctin_ref,
                       cidx_ref, block=block, kk=kk, rpz=rpz, hpz=hpz,
                       tlookahead=tlookahead, mvpcfg=mvpcfg,
                       same_hemi=same_hemi,
                       resume_refs=(pold_ref, keep_ref), rpz_m=rpz_m,
                       reso=reso, row_off=row0, row_stride=rstride,
                       swarm_refs=swarm_refs or None)

    @pl.when(jp == pl.num_programs(1) - 1)
    def _finish():
        _merge_partners_block(pold_ref, keep_ref, ctin_ref, cidx_ref,
                              pnew_ref, pact_ref, kk)


def _kernel_cand(own_ref, cand_ref, cgid_ref,
                 inconf_ref, tcpamax_ref, sdve_ref, sdvn_ref, sdvv_ref,
                 tsolv_ref, ncnt_ref, lcnt_ref, ctin_ref, cidx_ref,
                 *, block, kk, rpz, hpz, tlookahead, mvpcfg, reso="mvp"):
    """Candidate-list variant: ownship block i vs its GATHERED candidate
    aircraft (sub-chunk j of the per-block candidate table).

    Tiles are (candidate, ownship)-shaped exactly like the block kernels,
    but the intruder axis holds only aircraft that passed the exact
    point-to-bounding-box reachability bound (_build_candidates) — the
    pair count approaches the physics floor (aircraft within
    rpz + tlookahead * closing speed) instead of the block-granular
    superset.  Candidate global ids ride along in ``cgid_ref`` (sentinel
    entries point at the all-inactive padding row and mask out).
    """
    i = pl.program_id(0)
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _():
        _init_accumulators((inconf_ref, tcpamax_ref, sdve_ref, sdvn_ref,
                            sdvv_ref, tsolv_ref, ncnt_ref, lcnt_ref,
                            ctin_ref, cidx_ref), block, kk)

    oslab = own_ref[0]                                    # (_NF, block)
    cslab_t = cand_ref[0].T                               # (block, _NF)

    def own(k):
        return oslab[_IDX[k]:_IDX[k] + 1, :]

    def intr(k):
        return cslab_t[:, _IDX[k]:_IDX[k] + 1]

    gid_own = i * block + jax.lax.broadcasted_iota(
        jnp.int32, (1, block), 1)
    gid_int = cgid_ref[0].T                               # (block, 1)
    act_o = own("active") > 0.5
    act_i = intr("active") > 0.5
    pairmask = (act_o & act_i) & (gid_own != gid_int)

    @pl.when(jnp.any(pairmask))
    def _live_tile():
        _tile_pairs(pairmask, gid_int, own, intr, inconf_ref, tcpamax_ref,
                    sdve_ref, sdvn_ref, sdvv_ref, tsolv_ref, ncnt_ref,
                    lcnt_ref, ctin_ref, cidx_ref, kk=kk, rpz=rpz, hpz=hpz,
                    tlookahead=tlookahead, mvpcfg=mvpcfg, reso=reso)


def _build_candidates(lat, lon, gs, active, nb, block, c_cap, rpz,
                      tlookahead, sub=32):
    """Per-ownship-block candidate aircraft: exact bbox-to-bbox bound at
    ``sub``-aircraft granularity.

    For each ownship block's active bounding box, a sub-block of ``sub``
    consecutive (Morton-sorted) aircraft is a candidate iff the
    conservative distance lower bound between the boxes is within
    ``rpz + tlookahead * (gsmax_row + gsmax_sub)`` — the same exact skip
    predicate as ``block_reachability`` evaluated 8x finer.  Candidate
    sub-block ids are compacted per row with a SORT (ascending id keys),
    not a scatter — TPU scatters over the [nb, n] domain serialize into
    hundreds of ms, while a batched [nb, nb*block/sub] sort is
    milliseconds — then expanded to aircraft ids.

    Returns ``(cand [nb, c_cap] int32, row_over [nb] bool)``; entries
    beyond a row's count hold the sentinel id ``n`` (the all-inactive
    padding column).  Rows whose candidate count exceeds c_cap are
    OVERFLOW rows: their table is forced all-sentinel (so the candidate
    kernel skips them for free) and the caller must cover them with a
    row-masked full-grid pass — the straddle blocks of the Morton curve
    (bounding boxes spanning Z-order jumps) make a handful of such rows
    unavoidable at any practical capacity.
    """
    n = lat.shape[0]                       # nb*block, padded sorted space
    nsb = n // sub                         # number of sub-blocks
    c_sub = c_cap // sub

    def boxes(shape):
        inf = jnp.asarray(jnp.inf, lat.dtype)
        blat, blon = lat.reshape(shape), lon.reshape(shape)
        act = active.reshape(shape)
        return (jnp.min(jnp.where(act, blat, inf), axis=1),
                jnp.max(jnp.where(act, blat, -inf), axis=1),
                jnp.min(jnp.where(act, blon, inf), axis=1),
                jnp.max(jnp.where(act, blon, -inf), axis=1),
                jnp.max(jnp.where(act, gs.reshape(shape), 0.0), axis=1),
                jnp.any(act, axis=1))

    rlatmin, rlatmax, rlonmin, rlonmax, rgsmax, _ = boxes((nb, block))
    slatmin, slatmax, slonmin, slonmax, sgsmax, s_any = boxes((nsb, sub))
    r_abslat = jnp.maximum(jnp.abs(rlatmin), jnp.abs(rlatmax))
    s_abslat = jnp.maximum(jnp.abs(slatmin), jnp.abs(slatmax))

    # [nb, nsb] box-to-box gaps — same conservative bound family as
    # block_reachability (meridional <110 km/deg; zonal via the min
    # meridian distance at the highest |lat|; circular longitude gap)
    dlat_gap = jnp.maximum(0.0, jnp.maximum(
        rlatmin[:, None] - slatmax[None, :],
        slatmin[None, :] - rlatmax[:, None]))
    lin_gap = jnp.maximum(0.0, jnp.maximum(
        rlonmin[:, None] - slonmax[None, :],
        slonmin[None, :] - rlonmax[:, None]))
    wrap_gap = jnp.maximum(0.0, 360.0 - (
        jnp.maximum(rlonmax[:, None], slonmax[None, :])
        - jnp.minimum(rlonmin[:, None], slonmin[None, :])))
    dlon_gap = jnp.minimum(lin_gap, wrap_gap)
    cos_lb = jnp.cos(jnp.radians(jnp.minimum(
        90.0, jnp.maximum(r_abslat[:, None], s_abslat[None, :]))))
    zonal = 2.0 * 6335000.0 * jnp.arcsin(jnp.clip(
        cos_lb * jnp.sin(jnp.radians(0.5 * jnp.minimum(dlon_gap, 360.0))),
        0.0, 1.0))
    dist_lb = jnp.maximum(dlat_gap * 110000.0, zonal)
    thresh = rpz + tlookahead * (rgsmax[:, None] + sgsmax[None, :])
    mask = (dist_lb <= thresh * 1.05) & s_any[None, :]

    count = jnp.sum(mask, axis=1, dtype=jnp.int32)
    row_over = count > c_sub
    # Sort-based compaction: candidate ids ascend, non-candidates sink
    key = jnp.where(mask, jnp.arange(nsb, dtype=jnp.int32)[None, :],
                    jnp.int32(2**30))
    cand_sub = jnp.sort(key, axis=1)[:, :c_sub]          # [nb, c_sub]
    valid = (cand_sub < 2**30) & ~row_over[:, None]
    cand = jnp.where(valid, cand_sub, 0)[:, :, None] * sub \
        + jnp.arange(sub, dtype=jnp.int32)[None, None, :]
    cand = jnp.where(valid[:, :, None], cand, n).reshape(nb, c_sub * sub)
    return cand, row_over


def interleave_rows(nb, ndev):
    """Device-major row interleave for the shard_map row split (device
    d owns global rows d, d+D, 2D+d, ... — measured to cut the
    contiguous split's 1.2-1.5x row-density imbalance to ~1.0-1.1x,
    scripts/scaling_table.py).  Returns ``(rows_l, nbrp, rperm, rinv)``:
    rows per device, the padded row count, the permutation placing
    global row j*D+d at new index d*rows_l+j, and its inverse.  Shared
    by cd_pallas.run_full_sharded and cd_sched's shard branch so the
    two kernels' row<->device mapping can never drift apart."""
    import numpy as onp
    nbrp = -(-nb // ndev) * ndev
    rows_l = nbrp // ndev
    rperm = onp.arange(nbrp).reshape(rows_l, ndev).T.reshape(-1)
    return rows_l, nbrp, rperm, onp.argsort(rperm)


def full_grid_pass(packed, reach, *, block, kk, cpp, kern_kw,
                   interpret=False, pold=None, rpz_m=None,
                   packed_own=None, row0=None, rstride=1, col0=None):
    """Grid over ALL tile pairs; unreachable ones branch past the body.

    Several column tiles per grid program amortize the per-program
    overhead (grid steps + slab DMA) across the skipped tiles.  ``reach``
    [nbr, nbc] restricts the pass to a tile subset (prefilter skip and
    the mixed-mode / sparse-scheduler overflow rows — ops/cd_sched.py
    reuses this as its exact fallback).  ``packed`` is the
    [nbc, _NF, block] intruder slab array; returns the 10 accumulator
    outputs in standard order.

    ``packed_own``/``row0``/``rstride`` support a ROW SUBSET of the grid
    (the per-device share under ``shard_map``): the ownship side reads
    ``packed_own`` [nbr, _NF, block] whose local row i is GLOBAL row
    ``row0 + i*rstride`` (``row0`` a traced int32 scalar, ``rstride``
    static) — so pair exclusion and partner ids stay in the global slot
    space, and an interleaved (strided) row assignment balances load
    across devices.  Default (None/1): square grid over ``packed``
    itself with identity row ids — the single-chip path, bit-identical
    to before.

    With ``pold`` ([nbr, kk, block] int32 partner table in the global
    slot space) the kernel also evaluates in-kernel resume-nav and
    appends 3 outputs: keep [nbr, kk, block] f32, merged partners
    [nbr, kk, block] int32, active [nbr, 1, block] f32.
    """
    nbc = packed.shape[0]
    own_arr = packed if packed_own is None else packed_own
    nbr = own_arr.shape[0]
    assert reach.shape == (nbr, nbc), (reach.shape, nbr, nbc)
    dtype = packed.dtype
    cpp = min(cpp, nbc)
    nbp = -(-nbc // cpp) * cpp
    nb8 = -(-nbr // 8) * 8
    nw = -(-nbp // 32)
    bits = jnp.zeros((nb8, nw * 32), jnp.uint32).at[:nbr, :nbc].set(
        reach.astype(jnp.uint32))
    reach_i = jnp.sum(
        bits.reshape(nb8, nw, 32)
        << jnp.arange(32, dtype=jnp.uint32)[None, None, :],
        axis=2, dtype=jnp.uint32).astype(jnp.int32)
    # [row0, col0] ride one SMEM scalar pair; col0 offsets intruder ids
    # when ``packed`` is a local halo window of the global grid (the
    # cd_sched domain-decomposition mode) instead of the whole grid.
    row0_arr = jnp.stack([
        jnp.asarray(0 if row0 is None else row0, jnp.int32),
        jnp.asarray(0 if col0 is None else col0, jnp.int32)]).reshape(1, 2)
    packed_f = packed
    if nbp != nbc:
        # Padded intruder buffer; the padded columns' reach bits are 0,
        # so their tiles are never computed.
        packed_f = jnp.concatenate(
            [packed, jnp.zeros((nbp - nbc, _NF, block), dtype)], axis=0)

    acc_spec = lambda: pl.BlockSpec(
        (1, 1, block), lambda i, j: (i, 0, 0), memory_space=pltpu.VMEM)
    cand_spec = lambda: pl.BlockSpec(
        (1, kk, block), lambda i, j: (i, 0, 0), memory_space=pltpu.VMEM)
    acc = [jax.ShapeDtypeStruct((nbr, 1, block), dtype)] * 8 + [
        jax.ShapeDtypeStruct((nbr, kk, block), dtype),       # ctin
        jax.ShapeDtypeStruct((nbr, kk, block), jnp.int32)]   # cidx
    in_specs = [
        pl.BlockSpec((8, nw), lambda i, j: (i // 8, 0),
                     memory_space=pltpu.SMEM),       # reach window
        pl.BlockSpec((1, 2), lambda i, j: (0, 0),
                     memory_space=pltpu.SMEM),       # global row/col offsets
        pl.BlockSpec((1, _NF, block), lambda i, j: (i, 0, 0),
                     memory_space=pltpu.VMEM),       # ownship slab
        pl.BlockSpec((cpp, _NF, block), lambda i, j: (j, 0, 0),
                     memory_space=pltpu.VMEM),       # intruder slabs
    ]
    out_specs = [acc_spec() for _ in range(8)] + [cand_spec(), cand_spec()]
    args = [reach_i, row0_arr, own_arr, packed_f]
    if pold is None:
        kern = functools.partial(_kernel, cpp=cpp, rstride=rstride,
                                 **kern_kw)
    else:
        kern = functools.partial(_kernel_resume, cpp=cpp, rstride=rstride,
                                 rpz_m=float(rpz_m), **kern_kw)
        in_specs.append(cand_spec())                 # pold
        args.append(pold)
        out_specs += [cand_spec(), cand_spec(), acc_spec()]
        acc += [jax.ShapeDtypeStruct((nbr, kk, block), dtype),      # keep
                jax.ShapeDtypeStruct((nbr, kk, block), jnp.int32),  # merged
                jax.ShapeDtypeStruct((nbr, 1, block), dtype)]       # active
    if kern_kw.get("reso") == "swarm":
        # Swarm neighbour-sum accumulators ride as trailing outputs
        out_specs += [acc_spec() for _ in range(_N_SWARM)]
        acc += [jax.ShapeDtypeStruct((nbr, 1, block), dtype)] * _N_SWARM
    return list(pl.pallas_call(
        kern,
        grid=(nbr, nbp // cpp),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=acc,
        interpret=interpret,
    )(*args))


def interpret_default(interpret):
    """Resolve ``interpret=None`` to the platform default: the Pallas
    interpreter (loop-based, jit-friendly) off-TPU, the Mosaic compiler
    on TPU — so the same SimConfig runs everywhere (CPU tests, the
    virtual-mesh dryrun, the real chip)."""
    if interpret is None:
        return jax.default_backend() == "cpu"
    return interpret


def detect_resolve_pallas(lat, lon, trk, gs, alt, vs, gseast, gsnorth,
                          active, noreso, rpz, hpz, tlookahead, mvpcfg,
                          block=256, k_partners=8, interpret=None,
                          spatial_sort=True, cols_per_prog=4,
                          cand_cap=0, perm=None, extra_cols=None,
                          reso="mvp", mesh=None, mesh_axis="ac"):
    """Pallas-backed equivalent of ``cd_tiled.detect_resolve_tiled``.

    Returns a ``RowConflictData``; reductions match the lax formulation to
    float tolerance (identical per-tile math, same block iteration order).
    Always computes in float32 (the TPU-native dtype for this kernel).

    ``cand_cap`` > 0 enables the mixed-mode candidate scheduler: a
    per-ownship-block table of sub-block-granular candidate aircraft
    (exact bound), with overflow rows covered by a row-masked full-grid
    pass.  Measured on v5e at N=100k it is at best ~10% ahead of the
    default block grid (the reach annulus is dominated by the
    rpz + tlookahead*vrel physics radius, not by block granularity), so
    it stays off by default; it is exact at any capacity and may win for
    much sparser or larger-N fleets.

    With ``mesh`` the full-grid pass runs under ``shard_map`` on the
    ``mesh_axis`` dimension: each device owns a contiguous slice of row
    blocks (one per-device Pallas program over its rows), the intruder
    slab array replicates (the GSPMD all-gather over ICI), and row ids
    are offset to the global slot space — SURVEY §5.7/5.8's
    block-distributed CD for the Pallas backend.
    """
    interpret = interpret_default(interpret)
    n = lat.shape[0]
    if spatial_sort and n > block:
        # Morton-order the slots (cd_tiled.run_spatially_sorted) so the
        # in-kernel reachability skip has tight blocks to work with.
        return cd_tiled.run_spatially_sorted(
            functools.partial(detect_resolve_pallas, block=block,
                              k_partners=k_partners, interpret=interpret,
                              spatial_sort=False,
                              cols_per_prog=cols_per_prog,
                              cand_cap=cand_cap, reso=reso,
                              mesh=mesh, mesh_axis=mesh_axis),
            lat, lon, trk, gs, alt, vs, gseast, gsnorth, active, noreso,
            rpz, hpz, tlookahead, mvpcfg, perm=perm, extra_cols=extra_cols)
    dtype = jnp.float32
    # Scoped-VMEM budget: the tile temporaries exceed the 16 MiB stack
    # limit above block=256 on v5e (measured 18-21 MiB at block=512).
    block = min(block, 256)
    if n <= 128:
        block = 128
    else:
        block = min(block, 1 << (n - 1).bit_length())
    nb = -(-n // block)
    npad = nb * block - n

    def pad(a):
        a = a.astype(dtype)
        return a if npad == 0 else jnp.concatenate(
            [a, jnp.zeros((npad,), dtype)])

    trkrad = jnp.radians(trk.astype(dtype))
    fields = precompute_trig(pad(lat), pad(lon))
    fields.update({
        "u": pad(gs.astype(dtype) * jnp.sin(trkrad)),
        "v": pad(gs.astype(dtype) * jnp.cos(trkrad)),
        "alt": pad(alt), "vs": pad(vs), "gse": pad(gseast),
        "gsn": pad(gsnorth), "trk": pad(trk),
        # tas/gs ratio: Eby's velocity basis (ve = tr*u = tas*sin(trk));
        # 1.0 when no tas given (MVP never reads it; no-wind tas == gs).
        # In swarm mode the slot carries cas instead (see _FIELDS note).
        "tr": pad((extra_cols or {}).get("cas", gs).astype(dtype)
                  if reso == "swarm"
                  else jnp.ones_like(gs.astype(dtype))
                  if not extra_cols or "tas" not in extra_cols
                  else extra_cols["tas"].astype(dtype)
                  / jnp.maximum(gs.astype(dtype), 0.5)),
        "active": pad(active.astype(dtype)),
        "noreso": pad(noreso.astype(dtype)),
    })
    # [nb, _NF, block]: per-block slabs of the per-aircraft columns
    packed = jnp.stack([fields[k] for k in _FIELDS]).reshape(
        _NF, nb, block).transpose(1, 0, 2)

    # Exact tile-skip flags (shared bound with the lax backend); swarm
    # widens the bound to its 7.5 nm neighbourhood (short lookaheads
    # must not skip genuine non-conflicting swarm neighbours)
    if reso == "swarm":
        from . import cr_swarm
        min_reach = cr_swarm.R_SWARM
    else:
        min_reach = 0.0
    reach = block_reachability(
        pad(lat), pad(lon), pad(gs), fields["active"] > 0.5,
        nb, block, float(rpz), float(tlookahead), min_reach_m=min_reach)

    kk = k_partners
    kern_kw = dict(block=block, kk=kk, rpz=float(rpz), hpz=float(hpz),
                   tlookahead=float(tlookahead), mvpcfg=mvpcfg, reso=reso)

    acc = lambda m: [jax.ShapeDtypeStruct((m, 1, block), dtype)] * 8 + [
        jax.ShapeDtypeStruct((m, kk, block), dtype),       # ctin
        jax.ShapeDtypeStruct((m, kk, block), jnp.int32)]   # cidx

    def run_full(reach_in=None):
        return full_grid_pass(packed, reach if reach_in is None else reach_in,
                              block=block, kk=kk, cpp=cols_per_prog,
                              kern_kw=kern_kw, interpret=interpret)

    def run_full_sharded():
        """Row blocks INTERLEAVED over the mesh (device d owns global
        rows d, d+D, d+2D, ... — measured to cut the contiguous split's
        1.2-1.5x row-density imbalance to ~1.0-1.1x); each device sweeps
        its rows against the replicated intruder slabs with GLOBAL row
        ids via the row0 + i*rstride mapping."""
        from jax.sharding import PartitionSpec as P
        ndev = mesh.shape[mesh_axis]
        rows_l, nbrp, rperm, inv = interleave_rows(nb, ndev)
        own_p, reach_p = packed, reach
        if nbrp != nb:
            own_p = jnp.concatenate(
                [packed, jnp.zeros((nbrp - nb, _NF, block), dtype)])
            reach_p = jnp.concatenate(
                [reach, jnp.zeros((nbrp - nb, nb), bool)])
        own_p, reach_p = own_p[rperm], reach_p[rperm]

        def body(own_l, reach_l, packed_g):
            row0 = jax.lax.axis_index(mesh_axis)
            return tuple(full_grid_pass(
                packed_g, reach_l, block=block, kk=kk, cpp=cols_per_prog,
                kern_kw=kern_kw, interpret=interpret,
                packed_own=own_l, row0=row0, rstride=ndev))

        outs = shard_map_compat(
            body, mesh,
            (P(mesh_axis), P(mesh_axis), P()),
            P(mesh_axis))(own_p, reach_p, packed)
        return [o[inv][:nb] for o in outs]

    def run_cand(cand):
        """Grid over (ownship block, candidate sub-chunk): the intruder
        axis holds only aircraft that can possibly conflict with the
        block (exact bound, _build_candidates), so the pair count
        approaches the physics floor instead of the block-granular
        superset — the win that makes spread-out 100k-aircraft
        geometries pair-math-bound rather than tile-granularity-bound."""
        nsub = cand.shape[1] // block
        # Gather candidate columns; sentinel id n selects the appended
        # all-zero (inactive) column.
        allf = jnp.stack([fields[k] for k in _FIELDS])     # [_NF, n]
        allf = jnp.concatenate(
            [allf, jnp.zeros((_NF, 1), dtype)], axis=1)
        csl = allf[:, cand]                                # [_NF, nb, c_cap]
        csl = csl.transpose(1, 0, 2).reshape(nb, _NF, nsub, block) \
            .transpose(0, 2, 1, 3).reshape(nb * nsub, _NF, block)
        cgid = cand.reshape(nb * nsub, 1, block)

        kern = functools.partial(_kernel_cand, **kern_kw)
        own_map = lambda i, j: (i, 0, 0)
        sub_map = lambda i, j: (i * nsub + j, 0, 0)
        acc_spec = lambda: pl.BlockSpec((1, 1, block), own_map,
                                        memory_space=pltpu.VMEM)
        cand_spec = lambda: pl.BlockSpec((1, kk, block), own_map,
                                         memory_space=pltpu.VMEM)
        return list(pl.pallas_call(
            kern,
            grid=(nb, nsub),
            in_specs=[
                pl.BlockSpec((1, _NF, block), own_map,
                             memory_space=pltpu.VMEM),     # ownship slab
                pl.BlockSpec((1, _NF, block), sub_map,
                             memory_space=pltpu.VMEM),     # candidate slab
                pl.BlockSpec((1, 1, block), sub_map,
                             memory_space=pltpu.VMEM),     # candidate ids
            ],
            out_specs=[acc_spec() for _ in range(8)]
            + [cand_spec(), cand_spec()],
            out_shape=acc(nb),
            interpret=interpret,
        )(packed, csl, cgid))

    # Mixed-mode dispatch: the candidate pass covers rows whose table
    # fits the static capacity; the handful of overflow rows (Morton
    # straddle blocks, or every row when the whole fleet is mutually
    # reachable — dense regional traffic) are covered by a row-masked
    # full-grid pass and the row-disjoint outputs merged.  Identical
    # results either way — the split is purely a scheduling optimization.
    c_cap = -(-cand_cap // block) * block if cand_cap else 0
    if reso == "swarm" and c_cap:
        raise ValueError("cand_cap mixed mode does not carry the swarm "
                         "neighbour sums; use cand_cap=0 with RESO SWARM")
    if mesh is not None and mesh.shape[mesh_axis] > 1:
        outs = run_full_sharded()
    elif nb >= 8 and 0 < c_cap < nb * block:
        cand, row_over = _build_candidates(
            pad(lat), pad(lon), pad(gs), fields["active"] > 0.5,
            nb, block, c_cap, float(rpz), float(tlookahead))
        outs_c = run_cand(cand)
        reach_f = reach & row_over[:, None]

        def neutral(_):
            return [jnp.full(o.shape, v, o.dtype)
                    for o, v in zip(outs_c, _ACC_NEUTRAL)]

        outs_f = jax.lax.cond(jnp.any(row_over), run_full, neutral, reach_f)
        rsel = row_over[:, None, None]
        outs = [jnp.where(rsel, f, c) for f, c in zip(outs_f, outs_c)]
    else:
        outs = run_full()

    (inconf, tcpamax, sdve, sdvn, sdvv, tsolv, ncnt, lcnt,
     ctin, cidx) = outs[:10]

    unb = lambda a: a.reshape(nb * block)[:n]
    # Candidates: [nb, kk, block] -> [N, kk], already urgency-sorted
    topk_tin = ctin.transpose(0, 2, 1).reshape(nb * block, kk)[:n]
    topk_idx = cidx.transpose(0, 2, 1).reshape(nb * block, kk)[:n]
    topk_idx = jnp.where(topk_tin < _BIG, topk_idx, -1)

    rd = RowConflictData(
        inconf=unb(inconf) > 0.5,
        tcpamax=unb(tcpamax),
        sum_dve=unb(sdve), sum_dvn=unb(sdvn), sum_dvv=unb(sdvv),
        tsolv=unb(tsolv),
        # Cast per-block float counts to int32 BEFORE summing: a float32
        # total silently loses exactness past 2^24 pairs (plausible at 100k).
        nconf=jnp.sum(ncnt.astype(jnp.int32), dtype=jnp.int32),
        nlos=jnp.sum(lcnt.astype(jnp.int32), dtype=jnp.int32),
        topk_idx=topk_idx, topk_tin=topk_tin)
    if reso == "swarm":
        return rd, tuple(unb(a) for a in outs[10:10 + _N_SWARM])
    return rd
