"""Pallas TPU kernel for the fused blockwise CD&R pass.

Same computation as ``ops/cd_tiled.py`` (which is the portable lax.scan
formulation and the golden-test oracle for this kernel): the N x N pair space
of the state-based conflict detection (reference
``bluesky/traffic/asas/StateBasedCD.py``) plus the MVP displacement sums
(reference ``MVP.py:14-143``) is computed in [block, block] tiles and reduced
per ownship, never materialising an N² array.

Here the tile loop is a real TPU kernel: the grid is (ownship blocks,
intruder blocks), each program reads two [_NF, block] slabs of packed
aircraft state from VMEM, evaluates the CPA geometry + MVP contribution on a
[block, block] tile with the VPU, and accumulates the per-ownship reductions
in-place in the output blocks (revisited across the intruder grid dimension
— the standard Pallas accumulation pattern).  The pair math is the *same
code* as the lax backend — ``cd_tiled.tile_geometry`` (rank-1-factored
haversine, VPU-lean: rsqrt bearings + odd-Taylor arcsin arc length from
``kmath``) and ``cr_mvp.pair_contrib_trig`` are shape-agnostic jnp and trace
straight into the kernel — so the tiled backends cannot drift apart.

Layout note: the tile is oriented **intruder-major**: intruders vary along
sublanes (axis 0), ownships along lanes (axis 1).  Per-ownship reductions
are then axis-0 reduces that land in the natural (1, block) lane layout of
the accumulator blocks; only the intruder-side operands need a
(1, block) -> (block, 1) relayout.

Partner candidates for resume-nav hysteresis: a running top-K (by earliest
conflict-entry time) is accumulated in the candidate output refs across the
intruder-block grid dimension — K-pass masked index-min extraction per tile,
skipped entirely for conflict-free tiles — so the kernel yields exactly the
K most urgent intruders per ownship, same as ``cd_tiled``'s carry-based
top-K merge.
"""
import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import cd_tiled, cr_mvp
from .cd_tiled import RowConflictData, TRIG_FIELDS, block_reachability, \
    precompute_trig, tile_geometry

# Packed state row order for the [nb, 13, block] slabs: 7 trig/geometry
# columns (cd_tiled.TRIG_FIELDS), 4 velocity/altitude columns, then the
# active and noreso masks.
_FIELDS = TRIG_FIELDS + ("u", "v", "alt", "vs", "gse", "gsn",
                         "active", "noreso")
_NF = len(_FIELDS)
_IDX = {k: i for i, k in enumerate(_FIELDS)}
_BIG = 1e9

#: Identity elements of the 10 accumulator outputs, in output-tuple order:
#: inconf, tcpamax, sdve, sdvn, sdvv, tsolv, ncnt, lcnt, ctin, cidx.
#: Single source of truth for both kernels' init blocks and the
#: never-visited-row neutralisation in run_compact.
_ACC_NEUTRAL = (0.0, 0.0, 0.0, 0.0, 0.0, _BIG, 0.0, 0.0, _BIG, 2**30)


def _init_accumulators(refs, block, kk):
    """Write the identity element into each accumulator ref (10 refs in
    output order)."""
    for ref, v in zip(refs[:8], _ACC_NEUTRAL[:8]):
        ref[0] = jnp.full((1, block), v, jnp.float32)
    refs[8][0] = jnp.full((kk, block), _ACC_NEUTRAL[8], jnp.float32)
    refs[9][0] = jnp.full((kk, block), _ACC_NEUTRAL[9], jnp.int32)


def _kernel(reach_ref, own_ref, intr_ref,
            inconf_ref, tcpamax_ref, sdve_ref, sdvn_ref, sdvv_ref,
            tsolv_ref, ncnt_ref, lcnt_ref, ctin_ref, cidx_ref,
            *, block, kk, cpp, rpz, hpz, tlookahead, mvpcfg):
    ib = pl.program_id(0)
    jp = pl.program_id(1)      # program handles cpp column tiles

    # Initialise the accumulators on the first intruder program; the
    # tile compute below is skipped entirely for unreachable tiles, so
    # the init must not depend on it.  Accumulating t >= 0 maxima into
    # 0 / minima into BIG reproduces the former set-at-jb==0 semantics.
    @pl.when(jp == 0)
    def _():
        _init_accumulators((inconf_ref, tcpamax_ref, sdve_ref, sdvn_ref,
                            sdvv_ref, tsolv_ref, ncnt_ref, lcnt_ref,
                            ctin_ref, cidx_ref), block, kk)

    # Exact block-level reachability skip (cd_tiled.block_reachability):
    # a scalar-predicated branch in Mosaic, so unreachable tiles cost no
    # VPU work.  The cpp sub-tiles run sequentially in one program,
    # amortizing grid/DMA overhead (skipped sub-tiles still skip).
    for k in range(cpp):
        jb = jp * cpp + k

        @pl.when(reach_ref[ib, jb] > 0)
        def _compute(k=k, jb=jb):
            _tile_body(ib, jb, k, own_ref, intr_ref, inconf_ref,
                       tcpamax_ref, sdve_ref, sdvn_ref, sdvv_ref,
                       tsolv_ref, ncnt_ref, lcnt_ref, ctin_ref,
                       cidx_ref, block=block, kk=kk, rpz=rpz, hpz=hpz,
                       tlookahead=tlookahead, mvpcfg=mvpcfg)


def _tile_body(ib, jb, ksub, own_ref, intr_ref,
               inconf_ref, tcpamax_ref, sdve_ref, sdvn_ref, sdvv_ref,
               tsolv_ref, ncnt_ref, lcnt_ref, ctin_ref, cidx_ref,
               *, block, kk, rpz, hpz, tlookahead, mvpcfg):
    oslab = own_ref[0]                                    # (_NF, block)
    islab_t = intr_ref[ksub].T                            # (block, _NF): ONE
    # lane->sublane relayout shared by all intruder columns

    def own(k):            # ownship operand, varies along lanes: (1, block)
        return oslab[_IDX[k]:_IDX[k] + 1, :]

    def intr(k):           # intruder operand, varies along sublanes
        return islab_t[:, _IDX[k]:_IDX[k] + 1]            # (block, 1)

    gid_own = ib * block + jax.lax.broadcasted_iota(
        jnp.int32, (block, block), 1)
    gid_int = jb * block + jax.lax.broadcasted_iota(
        jnp.int32, (block, block), 0)
    act_o = own("active") > 0.5                           # (1, block)
    act_i = intr("active") > 0.5                          # (block, 1)
    pairmask = (act_o & act_i) & (gid_own != gid_int)

    # All-inactive tiles (sentinel/padding worklist entries, empty blocks)
    # contribute nothing — skip the whole geometry for the cost of one
    # OR-reduce.
    @pl.when(jnp.any(pairmask))
    def _live_tile():
        _tile_pairs(pairmask, gid_int, own, intr, inconf_ref, tcpamax_ref,
                    sdve_ref, sdvn_ref, sdvv_ref, tsolv_ref, ncnt_ref,
                    lcnt_ref, ctin_ref, cidx_ref, kk=kk, rpz=rpz, hpz=hpz,
                    tlookahead=tlookahead, mvpcfg=mvpcfg)


def _tile_pairs(pairmask, gid_int, own, intr,
                inconf_ref, tcpamax_ref, sdve_ref, sdvn_ref, sdvv_ref,
                tsolv_ref, ncnt_ref, lcnt_ref, ctin_ref, cidx_ref,
                *, kk, rpz, hpz, tlookahead, mvpcfg):
    block = pairmask.shape[1]
    excl = jnp.where(pairmask, 0.0, _BIG)

    # Horizontal geometry — the factored haversine (cd_tiled.tile_geometry),
    # evaluated [intruder, ownship] so per-ownship reductions are axis 0.
    trig_o = {k: own(k) for k in TRIG_FIELDS}
    trig_i = {k: intr(k) for k in TRIG_FIELDS}
    dist0, sinqdr, cosqdr = tile_geometry(trig_o, trig_i)
    dist = dist0 + excl
    dx = dist * sinqdr
    dy = dist * cosqdr

    du = intr("u") - own("u")
    dv = intr("v") - own("v")
    dv2 = du * du + dv * dv
    dv2 = jnp.where(jnp.abs(dv2) < 1e-6, 1e-6, dv2)
    # Same rsqrt-based CPA math as cd_tiled.tile — kept in lockstep
    rvrel = jax.lax.rsqrt(dv2)

    tcpa = -(du * dx + dv * dy) * (rvrel * rvrel) + excl
    dcpa2 = dist * dist - tcpa * tcpa * dv2
    r2 = rpz * rpz
    swhorconf = dcpa2 < r2

    dtinhor = jnp.sqrt(jnp.maximum(0.0, r2 - dcpa2)) * rvrel
    tinhor = jnp.where(swhorconf, tcpa - dtinhor, 1e8)
    touthor = jnp.where(swhorconf, tcpa + dtinhor, -1e8)

    dalt = intr("alt") - own("alt") + excl
    dvs = intr("vs") - own("vs")
    dvs = jnp.where(jnp.abs(dvs) < 1e-6, 1e-6, dvs)
    nrdvs = -1.0 / dvs
    tcrosshi = (dalt + hpz) * nrdvs
    tcrosslo = (dalt - hpz) * nrdvs
    tinver = jnp.minimum(tcrosshi, tcrosslo)
    toutver = jnp.maximum(tcrosshi, tcrosslo)

    tinconf = jnp.maximum(tinver, tinhor)
    toutconf = jnp.minimum(toutver, touthor)
    swconfl = (swhorconf & (tinconf <= toutconf) & (toutconf > 0.0)
               & (tinconf < tlookahead) & pairmask)
    swlos = (dist < rpz) & (jnp.abs(dalt) < hpz) & pairmask

    # Everything past the flags only matters when the tile has at least one
    # conflict or LoS pair: every accumulator update below is then a no-op
    # (max with 0, sum with 0, min with BIG).  Conflicts are rare even in
    # *reachable* tiles, so predicating the whole MVP + reduction tail on a
    # single any-hit flag cuts the common tile to the core CPA geometry.
    @pl.when(jnp.any(swconfl | swlos))
    def _accumulate():
        dve_p, dvn_p, dvv_p, tsolv_p = cr_mvp.pair_contrib_trig(
            sinqdr, cosqdr, dist, tcpa, tinconf,
            intr("alt") - own("alt"), intr("gse") - own("gse"),
            intr("gsn") - own("gsn"), intr("vs") - own("vs"), mvpcfg)
        nor_i = intr("noreso") > 0.5
        mvpmask = swconfl & ~nor_i
        maskf = mvpmask.astype(dist.dtype)

        conff = swconfl.astype(dist.dtype)
        t_inconf = jnp.max(conff, axis=0, keepdims=True)
        t_tcpamax = jnp.max(tcpa * conff, axis=0, keepdims=True)
        t_sdve = jnp.sum(dve_p * maskf, axis=0, keepdims=True)
        t_sdvn = jnp.sum(dvn_p * maskf, axis=0, keepdims=True)
        t_sdvv = jnp.sum(dvv_p * maskf, axis=0, keepdims=True)
        t_tsolv = jnp.min(jnp.where(mvpmask, tsolv_p, _BIG),
                          axis=0, keepdims=True)
        t_ncnt = jnp.sum(conff, axis=0, keepdims=True)
        t_lcnt = jnp.sum(swlos.astype(dist.dtype), axis=0, keepdims=True)

        inconf_ref[0] = jnp.maximum(inconf_ref[0], t_inconf)
        tcpamax_ref[0] = jnp.maximum(tcpamax_ref[0], t_tcpamax)
        sdve_ref[0] = sdve_ref[0] + t_sdve
        sdvn_ref[0] = sdvn_ref[0] + t_sdvn
        sdvv_ref[0] = sdvv_ref[0] + t_sdvv
        tsolv_ref[0] = jnp.minimum(tsolv_ref[0], t_tsolv)
        ncnt_ref[0] = ncnt_ref[0] + t_ncnt
        lcnt_ref[0] = lcnt_ref[0] + t_lcnt

    # Partner candidates: merge this tile's top-kk most urgent conflicts
    # into the running per-ownship top-kk held in the candidate refs.
    # Extraction is kk passes of masked index-min (argmin has no stable
    # Mosaic lowering); conflict-free tiles skip the whole thing.
    @pl.when(jnp.any(swconfl))
    def _():
        urg = jnp.where(swconfl, tinconf, _BIG)
        tins, idxs = [], []
        for _s in range(kk):
            minv = jnp.min(urg, axis=0, keepdims=True)    # (1, block)
            jloc = jnp.min(jnp.where(urg == minv, gid_int, jnp.int32(2**30)),
                           axis=0, keepdims=True)
            tins.append(minv)
            idxs.append(jloc)
            urg = jnp.where(gid_int == jloc, _BIG, urg)
        cat_t = jnp.concatenate([ctin_ref[0]] + tins, axis=0)   # (2kk, block)
        cat_i = jnp.concatenate([cidx_ref[0]] + idxs, axis=0)
        rio = jax.lax.broadcasted_iota(jnp.int32, (2 * kk, block), 0)
        new_t, new_i = [], []
        for _s in range(kk):
            minv = jnp.min(cat_t, axis=0, keepdims=True)
            rloc = jnp.min(jnp.where(cat_t == minv, rio, jnp.int32(2**30)),
                           axis=0, keepdims=True)
            sel = jnp.min(jnp.where(rio == rloc, cat_i, jnp.int32(2**30)),
                          axis=0, keepdims=True)
            new_t.append(minv)
            new_i.append(sel)
            cat_t = jnp.where(rio == rloc, _BIG, cat_t)
        ctin_ref[0] = jnp.concatenate(new_t, axis=0)
        cidx_ref[0] = jnp.concatenate(new_i, axis=0)


def _kernel_compact(ilist_ref, jlist_ref, own_ref, intr_ref,
                    inconf_ref, tcpamax_ref, sdve_ref, sdvn_ref, sdvv_ref,
                    tsolv_ref, ncnt_ref, lcnt_ref, ctin_ref, cidx_ref,
                    *, block, kk, rpz, hpz, tlookahead, mvpcfg):
    """Tile worklist variant: program t computes reachable tile
    (ilist[t], jlist[t]) — no grid step is ever spent on a skipped tile.

    The worklist is row-major sorted, so all programs of one ownship block
    are consecutive: accumulators are initialised on the first program of
    each ownship block (detected by comparing with the previous list entry)
    and stay VMEM-resident until the block changes.  Padding entries beyond
    the real worklist point both slabs at the all-inactive sentinel block,
    whose pair mask is empty — they accumulate nothing.
    """
    t = pl.program_id(0)
    ib = ilist_ref[t]
    prev = ilist_ref[jnp.maximum(t - 1, 0)]

    @pl.when((t == 0) | (ib != prev))
    def _():
        _init_accumulators((inconf_ref, tcpamax_ref, sdve_ref, sdvn_ref,
                            sdvv_ref, tsolv_ref, ncnt_ref, lcnt_ref,
                            ctin_ref, cidx_ref), block, kk)

    _tile_body(ib, jlist_ref[t], 0, own_ref, intr_ref, inconf_ref,
               tcpamax_ref, sdve_ref, sdvn_ref, sdvv_ref, tsolv_ref,
               ncnt_ref, lcnt_ref, ctin_ref, cidx_ref, block=block, kk=kk,
               rpz=rpz, hpz=hpz, tlookahead=tlookahead, mvpcfg=mvpcfg)


def detect_resolve_pallas(lat, lon, trk, gs, alt, vs, gseast, gsnorth,
                          active, noreso, rpz, hpz, tlookahead, mvpcfg,
                          block=256, k_partners=8, interpret=False,
                          spatial_sort=True, cols_per_prog=4,
                          compact_cap=None, perm=None):
    """Pallas-backed equivalent of ``cd_tiled.detect_resolve_tiled``.

    Returns a ``RowConflictData``; reductions match the lax formulation to
    float tolerance (identical per-tile math, same block iteration order).
    Always computes in float32 (the TPU-native dtype for this kernel).
    """
    n = lat.shape[0]
    if spatial_sort and n > block:
        # Morton-order the slots (cd_tiled.run_spatially_sorted) so the
        # in-kernel reachability skip has tight blocks to work with.
        return cd_tiled.run_spatially_sorted(
            functools.partial(detect_resolve_pallas, block=block,
                              k_partners=k_partners, interpret=interpret,
                              spatial_sort=False,
                              cols_per_prog=cols_per_prog,
                              compact_cap=compact_cap),
            lat, lon, trk, gs, alt, vs, gseast, gsnorth, active, noreso,
            rpz, hpz, tlookahead, mvpcfg, perm=perm)
    dtype = jnp.float32
    # Scoped-VMEM budget: the tile temporaries exceed the 16 MiB stack
    # limit above block=256 on v5e (measured 18-21 MiB at block=512).
    block = min(block, 256)
    if n <= 128:
        block = 128
    else:
        block = min(block, 1 << (n - 1).bit_length())
    nb = -(-n // block)
    npad = nb * block - n

    def pad(a):
        a = a.astype(dtype)
        return a if npad == 0 else jnp.concatenate(
            [a, jnp.zeros((npad,), dtype)])

    trkrad = jnp.radians(trk.astype(dtype))
    fields = precompute_trig(pad(lat), pad(lon))
    fields.update({
        "u": pad(gs.astype(dtype) * jnp.sin(trkrad)),
        "v": pad(gs.astype(dtype) * jnp.cos(trkrad)),
        "alt": pad(alt), "vs": pad(vs), "gse": pad(gseast),
        "gsn": pad(gsnorth),
        "active": pad(active.astype(dtype)),
        "noreso": pad(noreso.astype(dtype)),
    })
    # [nb, _NF, block]: per-block slabs of the per-aircraft columns
    packed = jnp.stack([fields[k] for k in _FIELDS]).reshape(
        _NF, nb, block).transpose(1, 0, 2)

    # Exact tile-skip flags (shared bound with the lax backend)
    reach = block_reachability(
        pad(lat), pad(lon), pad(gs), fields["active"] > 0.5,
        nb, block, float(rpz), float(tlookahead))

    kk = k_partners
    kern_kw = dict(block=block, kk=kk, rpz=float(rpz), hpz=float(hpz),
                   tlookahead=float(tlookahead), mvpcfg=mvpcfg)

    acc = lambda m: [jax.ShapeDtypeStruct((m, 1, block), dtype)] * 8 + [
        jax.ShapeDtypeStruct((m, kk, block), dtype),       # ctin
        jax.ShapeDtypeStruct((m, kk, block), jnp.int32)]   # cidx

    def run_full(_):
        """Grid over ALL tile pairs; unreachable ones branch past the body.

        Several column tiles per grid program amortize the per-program
        overhead (grid steps + slab DMA) across the skipped tiles."""
        cpp = min(cols_per_prog, nb)
        nbp = -(-nb // cpp) * cpp
        reach_i = reach.astype(jnp.int32)
        packed_f = packed
        if nbp != nb:
            # One padded buffer serves BOTH inputs (the ownship grid
            # dimension stays nb, so its padded rows are never read)
            packed_f = jnp.concatenate(
                [packed, jnp.zeros((nbp - nb, _NF, block), dtype)], axis=0)
            reach_i = jnp.concatenate(
                [reach_i, jnp.zeros((nb, nbp - nb), jnp.int32)], axis=1)

        kern = functools.partial(_kernel, cpp=cpp, **kern_kw)
        acc_spec = lambda: pl.BlockSpec(
            (1, 1, block), lambda i, j: (i, 0, 0), memory_space=pltpu.VMEM)
        cand_spec = lambda: pl.BlockSpec(
            (1, kk, block), lambda i, j: (i, 0, 0), memory_space=pltpu.VMEM)
        return list(pl.pallas_call(
            kern,
            grid=(nb, nbp // cpp),
            in_specs=[
                pl.BlockSpec(memory_space=pltpu.SMEM),       # reach flags
                pl.BlockSpec((1, _NF, block), lambda i, j: (i, 0, 0),
                             memory_space=pltpu.VMEM),       # ownship slab
                pl.BlockSpec((cpp, _NF, block), lambda i, j: (j, 0, 0),
                             memory_space=pltpu.VMEM),       # intruder slabs
            ],
            out_specs=[acc_spec() for _ in range(8)]
            + [cand_spec(), cand_spec()],
            out_shape=acc(nb),
            interpret=interpret,
        )(reach_i, packed_f, packed_f))

    def run_compact(operand):
        """Grid over the compacted worklist of reachable tiles only.

        Per-program cost is all real work, so the grid shrinks from nb^2
        tile visits to ~(reachable fraction) * nb^2 — the win that makes
        spread-out 100k-aircraft geometries CD-bound rather than
        grid-overhead-bound.  Ownship blocks with no reachable tile are
        never visited; their (uninitialised) output rows are neutralised
        after the call."""
        ilist, jlist = operand
        # Sentinel slab nb: all-inactive (zeros) — padding worklist entries
        # and never-visited output rows both resolve to it.
        packed_c = jnp.concatenate(
            [packed, jnp.zeros((1, _NF, block), dtype)], axis=0)
        kern = functools.partial(_kernel_compact, **kern_kw)
        own_map = lambda t, il, jl: (il[t], 0, 0)
        intr_map = lambda t, il, jl: (jl[t], 0, 0)
        acc_spec = lambda: pl.BlockSpec((1, 1, block), own_map,
                                        memory_space=pltpu.VMEM)
        cand_spec = lambda: pl.BlockSpec((1, kk, block), own_map,
                                         memory_space=pltpu.VMEM)
        outs = pl.pallas_call(
            kern,
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=2,
                grid=(ilist.shape[0],),
                in_specs=[
                    pl.BlockSpec((1, _NF, block), own_map,
                                 memory_space=pltpu.VMEM),   # ownship slab
                    pl.BlockSpec((1, _NF, block), intr_map,
                                 memory_space=pltpu.VMEM),   # intruder slab
                ],
                out_specs=[acc_spec() for _ in range(8)]
                + [cand_spec(), cand_spec()],
            ),
            out_shape=acc(nb + 1),
            interpret=interpret,
        )(ilist, jlist, packed_c, packed_c)
        # Neutralise rows whose ownship block was never visited (no
        # reachable tiles -> uninitialised memory), and drop the sentinel.
        visited = jnp.any(reach, axis=1)[:, None, None]
        return [jnp.where(visited, o[:nb], jnp.asarray(v, o.dtype))
                for o, v in zip(outs, _ACC_NEUTRAL)]

    # Worklist capacity: static. Geometries whose reachable set overflows it
    # (dense regional traffic) take the full-grid path — bit-identical
    # results, the worklist is purely a scheduling optimization.
    if compact_cap is None:
        compact_cap = max(512, (nb * nb) // 8)
    compact_cap = min(compact_cap, nb * nb)
    if nb >= 8 and compact_cap > 0:
        flat = reach.reshape(-1)
        count = jnp.sum(flat.astype(jnp.int32))
        # Stable argsort keeps the reachable tiles in row-major order, so
        # each ownship block's programs are consecutive in the worklist.
        order = jnp.argsort(jnp.where(flat, jnp.int32(0), jnp.int32(1)),
                            stable=True)[:compact_cap]
        valid = jnp.arange(compact_cap, dtype=jnp.int32) < count
        ilist = jnp.where(valid, (order // nb).astype(jnp.int32), nb)
        jlist = jnp.where(valid, (order % nb).astype(jnp.int32), nb)
        outs = jax.lax.cond(count <= compact_cap, run_compact, run_full,
                            (ilist, jlist))
    else:
        outs = run_full(None)

    (inconf, tcpamax, sdve, sdvn, sdvv, tsolv, ncnt, lcnt,
     ctin, cidx) = outs

    unb = lambda a: a.reshape(nb * block)[:n]
    # Candidates: [nb, kk, block] -> [N, kk], already urgency-sorted
    topk_tin = ctin.transpose(0, 2, 1).reshape(nb * block, kk)[:n]
    topk_idx = cidx.transpose(0, 2, 1).reshape(nb * block, kk)[:n]
    topk_idx = jnp.where(topk_tin < _BIG, topk_idx, -1)

    return RowConflictData(
        inconf=unb(inconf) > 0.5,
        tcpamax=unb(tcpamax),
        sum_dve=unb(sdve), sum_dvn=unb(sdvn), sum_dvv=unb(sdvv),
        tsolv=unb(tsolv),
        # Cast per-block float counts to int32 BEFORE summing: a float32
        # total silently loses exactness past 2^24 pairs (plausible at 100k).
        nconf=jnp.sum(ncnt.astype(jnp.int32), dtype=jnp.int32),
        nlos=jnp.sum(lcnt.astype(jnp.int32), dtype=jnp.int32),
        topk_idx=topk_idx, topk_tin=topk_tin)
