"""Pallas TPU kernel for the fused blockwise CD&R pass.

Same computation as ``ops/cd_tiled.py`` (which is the portable lax.scan
formulation and the golden-test oracle for this kernel): the N x N pair space
of the state-based conflict detection (reference
``bluesky/traffic/asas/StateBasedCD.py``) plus the MVP displacement sums
(reference ``MVP.py:14-143``) is computed in [block, block] tiles and reduced
per ownship, never materialising an N² array.

Here the tile loop is a real TPU kernel: the grid is (ownship blocks,
intruder blocks), each program reads two [_NF, block] slabs of packed
aircraft state from VMEM, evaluates the CPA geometry + MVP contribution on a
[block, block] tile with the VPU, and accumulates the per-ownship reductions
in-place in the output blocks (revisited across the intruder grid dimension
— the standard Pallas accumulation pattern).  The pair math is the *same
code* as the lax backend — ``cd_tiled.tile_geometry`` (rank-1-factored
haversine) and ``cr_mvp.pair_contrib_trig`` are shape-agnostic jnp and trace
straight into the kernel — so the tiled backends cannot drift apart.  The
one transcendental Mosaic lacks (atan2, for the arc length) comes from
``kmath`` (f32 Cephes-style polynomial).

Layout note: the tile is oriented **intruder-major**: intruders vary along
sublanes (axis 0), ownships along lanes (axis 1).  Per-ownship reductions
are then axis-0 reduces that land in the natural (1, block) lane layout of
the accumulator blocks; only the intruder-side operands need a
(1, block) -> (block, 1) relayout.

Partner candidates for resume-nav hysteresis: a running top-K (by earliest
conflict-entry time) is accumulated in the candidate output refs across the
intruder-block grid dimension — K-pass masked index-min extraction per tile,
skipped entirely for conflict-free tiles — so the kernel yields exactly the
K most urgent intruders per ownship, same as ``cd_tiled``'s carry-based
top-K merge.
"""
import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import cd_tiled, cr_mvp, kmath
from .cd_tiled import RowConflictData, TRIG_FIELDS, block_reachability, \
    precompute_trig, tile_geometry

# Packed state row order for the [nb, 13, block] slabs: 7 trig/geometry
# columns (cd_tiled.TRIG_FIELDS), 4 velocity/altitude columns, then the
# active and noreso masks.
_FIELDS = TRIG_FIELDS + ("u", "v", "alt", "vs", "gse", "gsn",
                         "active", "noreso")
_NF = len(_FIELDS)
_IDX = {k: i for i, k in enumerate(_FIELDS)}
_BIG = 1e9


def _kernel(reach_ref, own_ref, intr_ref,
            inconf_ref, tcpamax_ref, sdve_ref, sdvn_ref, sdvv_ref,
            tsolv_ref, ncnt_ref, lcnt_ref, ctin_ref, cidx_ref,
            *, block, kk, cpp, rpz, hpz, tlookahead, mvpcfg):
    ib = pl.program_id(0)
    jp = pl.program_id(1)      # program handles cpp column tiles

    # Initialise the accumulators on the first intruder program; the
    # tile compute below is skipped entirely for unreachable tiles, so
    # the init must not depend on it.  Accumulating t >= 0 maxima into
    # 0 / minima into BIG reproduces the former set-at-jb==0 semantics.
    @pl.when(jp == 0)
    def _():
        zero = jnp.zeros((1, block), jnp.float32)
        inconf_ref[0] = zero
        tcpamax_ref[0] = zero
        sdve_ref[0] = zero
        sdvn_ref[0] = zero
        sdvv_ref[0] = zero
        tsolv_ref[0] = jnp.full((1, block), _BIG, jnp.float32)
        ncnt_ref[0] = zero
        lcnt_ref[0] = zero
        ctin_ref[0] = jnp.full((kk, block), _BIG, jnp.float32)
        cidx_ref[0] = jnp.full((kk, block), 2**30, jnp.int32)

    # Exact block-level reachability skip (cd_tiled.block_reachability):
    # a scalar-predicated branch in Mosaic, so unreachable tiles cost no
    # VPU work.  The cpp sub-tiles run sequentially in one program,
    # amortizing grid/DMA overhead (skipped sub-tiles still skip).
    for k in range(cpp):
        jb = jp * cpp + k

        @pl.when(reach_ref[ib, jb] > 0)
        def _compute(k=k, jb=jb):
            _tile_body(ib, jb, k, own_ref, intr_ref, inconf_ref,
                       tcpamax_ref, sdve_ref, sdvn_ref, sdvv_ref,
                       tsolv_ref, ncnt_ref, lcnt_ref, ctin_ref,
                       cidx_ref, block=block, kk=kk, rpz=rpz, hpz=hpz,
                       tlookahead=tlookahead, mvpcfg=mvpcfg)


def _tile_body(ib, jb, ksub, own_ref, intr_ref,
               inconf_ref, tcpamax_ref, sdve_ref, sdvn_ref, sdvv_ref,
               tsolv_ref, ncnt_ref, lcnt_ref, ctin_ref, cidx_ref,
               *, block, kk, rpz, hpz, tlookahead, mvpcfg):
    oslab = own_ref[0]                                    # (_NF, block)
    islab = intr_ref[ksub]

    def own(k):            # ownship operand, varies along lanes: (1, block)
        return oslab[_IDX[k]:_IDX[k] + 1, :]

    def intr(k):           # intruder operand, varies along sublanes
        return islab[_IDX[k]:_IDX[k] + 1, :].T            # (block, 1)

    gid_own = ib * block + jax.lax.broadcasted_iota(
        jnp.int32, (block, block), 1)
    gid_int = jb * block + jax.lax.broadcasted_iota(
        jnp.int32, (block, block), 0)
    act_o = own("active") > 0.5                           # (1, block)
    act_i = intr("active") > 0.5                          # (block, 1)
    pairmask = (act_o & act_i) & (gid_own != gid_int)
    excl = jnp.where(pairmask, 0.0, _BIG)

    # Horizontal geometry — the factored haversine (cd_tiled.tile_geometry),
    # evaluated [intruder, ownship] so per-ownship reductions are axis 0.
    trig_o = {k: own(k) for k in TRIG_FIELDS}
    trig_i = {k: intr(k) for k in TRIG_FIELDS}
    dist0, sinqdr, cosqdr = tile_geometry(trig_o, trig_i, atan2=kmath.atan2)
    dist = dist0 + excl
    dx = dist * sinqdr
    dy = dist * cosqdr

    du = intr("u") - own("u")
    dv = intr("v") - own("v")
    dv2 = du * du + dv * dv
    dv2 = jnp.where(jnp.abs(dv2) < 1e-6, 1e-6, dv2)
    vrel = jnp.sqrt(dv2)

    tcpa = -(du * dx + dv * dy) / dv2 + excl
    dcpa2 = dist * dist - tcpa * tcpa * dv2
    r2 = rpz * rpz
    swhorconf = dcpa2 < r2

    dtinhor = jnp.sqrt(jnp.maximum(0.0, r2 - dcpa2)) / vrel
    tinhor = jnp.where(swhorconf, tcpa - dtinhor, 1e8)
    touthor = jnp.where(swhorconf, tcpa + dtinhor, -1e8)

    dalt = intr("alt") - own("alt") + excl
    dvs = intr("vs") - own("vs")
    dvs = jnp.where(jnp.abs(dvs) < 1e-6, 1e-6, dvs)
    tcrosshi = (dalt + hpz) / -dvs
    tcrosslo = (dalt - hpz) / -dvs
    tinver = jnp.minimum(tcrosshi, tcrosslo)
    toutver = jnp.maximum(tcrosshi, tcrosslo)

    tinconf = jnp.maximum(tinver, tinhor)
    toutconf = jnp.minimum(toutver, touthor)
    swconfl = (swhorconf & (tinconf <= toutconf) & (toutconf > 0.0)
               & (tinconf < tlookahead) & pairmask)
    swlos = (dist < rpz) & (jnp.abs(dalt) < hpz) & pairmask

    dve_p, dvn_p, dvv_p, tsolv_p = cr_mvp.pair_contrib_trig(
        sinqdr, cosqdr, dist, tcpa, tinconf,
        intr("alt") - own("alt"), intr("gse") - own("gse"),
        intr("gsn") - own("gsn"), intr("vs") - own("vs"), mvpcfg)
    nor_i = intr("noreso") > 0.5
    mvpmask = swconfl & ~nor_i
    maskf = mvpmask.astype(dist.dtype)

    conff = swconfl.astype(dist.dtype)
    t_inconf = jnp.max(conff, axis=0, keepdims=True)
    t_tcpamax = jnp.max(tcpa * conff, axis=0, keepdims=True)
    t_sdve = jnp.sum(dve_p * maskf, axis=0, keepdims=True)
    t_sdvn = jnp.sum(dvn_p * maskf, axis=0, keepdims=True)
    t_sdvv = jnp.sum(dvv_p * maskf, axis=0, keepdims=True)
    t_tsolv = jnp.min(jnp.where(mvpmask, tsolv_p, _BIG),
                      axis=0, keepdims=True)
    t_ncnt = jnp.sum(conff, axis=0, keepdims=True)
    t_lcnt = jnp.sum(swlos.astype(dist.dtype), axis=0, keepdims=True)

    inconf_ref[0] = jnp.maximum(inconf_ref[0], t_inconf)
    tcpamax_ref[0] = jnp.maximum(tcpamax_ref[0], t_tcpamax)
    sdve_ref[0] = sdve_ref[0] + t_sdve
    sdvn_ref[0] = sdvn_ref[0] + t_sdvn
    sdvv_ref[0] = sdvv_ref[0] + t_sdvv
    tsolv_ref[0] = jnp.minimum(tsolv_ref[0], t_tsolv)
    ncnt_ref[0] = ncnt_ref[0] + t_ncnt
    lcnt_ref[0] = lcnt_ref[0] + t_lcnt

    # Partner candidates: merge this tile's top-kk most urgent conflicts
    # into the running per-ownship top-kk held in the candidate refs.
    # Extraction is kk passes of masked index-min (argmin has no stable
    # Mosaic lowering); conflict-free tiles skip the whole thing.
    @pl.when(jnp.any(swconfl))
    def _():
        urg = jnp.where(swconfl, tinconf, _BIG)
        tins, idxs = [], []
        for _s in range(kk):
            minv = jnp.min(urg, axis=0, keepdims=True)    # (1, block)
            jloc = jnp.min(jnp.where(urg == minv, gid_int, jnp.int32(2**30)),
                           axis=0, keepdims=True)
            tins.append(minv)
            idxs.append(jloc)
            urg = jnp.where(gid_int == jloc, _BIG, urg)
        cat_t = jnp.concatenate([ctin_ref[0]] + tins, axis=0)   # (2kk, block)
        cat_i = jnp.concatenate([cidx_ref[0]] + idxs, axis=0)
        rio = jax.lax.broadcasted_iota(jnp.int32, (2 * kk, block), 0)
        new_t, new_i = [], []
        for _s in range(kk):
            minv = jnp.min(cat_t, axis=0, keepdims=True)
            rloc = jnp.min(jnp.where(cat_t == minv, rio, jnp.int32(2**30)),
                           axis=0, keepdims=True)
            sel = jnp.min(jnp.where(rio == rloc, cat_i, jnp.int32(2**30)),
                          axis=0, keepdims=True)
            new_t.append(minv)
            new_i.append(sel)
            cat_t = jnp.where(rio == rloc, _BIG, cat_t)
        ctin_ref[0] = jnp.concatenate(new_t, axis=0)
        cidx_ref[0] = jnp.concatenate(new_i, axis=0)


def detect_resolve_pallas(lat, lon, trk, gs, alt, vs, gseast, gsnorth,
                          active, noreso, rpz, hpz, tlookahead, mvpcfg,
                          block=256, k_partners=8, interpret=False,
                          spatial_sort=True, cols_per_prog=4):
    """Pallas-backed equivalent of ``cd_tiled.detect_resolve_tiled``.

    Returns a ``RowConflictData``; reductions match the lax formulation to
    float tolerance (identical per-tile math, same block iteration order).
    Always computes in float32 (the TPU-native dtype for this kernel).
    """
    n = lat.shape[0]
    if spatial_sort and n > block:
        # Morton-order the slots (cd_tiled.run_spatially_sorted) so the
        # in-kernel reachability skip has tight blocks to work with.
        return cd_tiled.run_spatially_sorted(
            functools.partial(detect_resolve_pallas, block=block,
                              k_partners=k_partners, interpret=interpret,
                              spatial_sort=False,
                              cols_per_prog=cols_per_prog),
            lat, lon, trk, gs, alt, vs, gseast, gsnorth, active, noreso,
            rpz, hpz, tlookahead, mvpcfg)
    dtype = jnp.float32
    # Scoped-VMEM budget: the tile temporaries exceed the 16 MiB stack
    # limit above block=256 on v5e (measured 18-21 MiB at block=512).
    block = min(block, 256)
    if n <= 128:
        block = 128
    else:
        block = min(block, 1 << (n - 1).bit_length())
    nb = -(-n // block)
    npad = nb * block - n

    def pad(a):
        a = a.astype(dtype)
        return a if npad == 0 else jnp.concatenate(
            [a, jnp.zeros((npad,), dtype)])

    trkrad = jnp.radians(trk.astype(dtype))
    fields = precompute_trig(pad(lat), pad(lon))
    fields.update({
        "u": pad(gs.astype(dtype) * jnp.sin(trkrad)),
        "v": pad(gs.astype(dtype) * jnp.cos(trkrad)),
        "alt": pad(alt), "vs": pad(vs), "gse": pad(gseast),
        "gsn": pad(gsnorth),
        "active": pad(active.astype(dtype)),
        "noreso": pad(noreso.astype(dtype)),
    })
    # [nb, _NF, block]: per-block slabs of the per-aircraft columns
    packed = jnp.stack([fields[k] for k in _FIELDS]).reshape(
        _NF, nb, block).transpose(1, 0, 2)

    # Exact tile-skip flags (shared bound with the lax backend)
    reach = block_reachability(
        pad(lat), pad(lon), pad(gs), fields["active"] > 0.5,
        nb, block, float(rpz), float(tlookahead)).astype(jnp.int32)

    kk = k_partners
    # Several column tiles per grid program amortize the per-program
    # overhead (grid steps + slab DMA), which dominates once the
    # reachability skip elides most tiles' compute at large nb.
    cpp = min(cols_per_prog, nb)
    nbp = -(-nb // cpp) * cpp
    if nbp != nb:
        padslabs = jnp.zeros((nbp - nb, _NF, block), dtype)
        # One padded buffer serves BOTH inputs (the ownship grid
        # dimension stays nb, so its padded rows are never read)
        packed = jnp.concatenate([packed, padslabs], axis=0)
        reach = jnp.concatenate(
            [reach, jnp.zeros((nb, nbp - nb), jnp.int32)], axis=1)
    packed_cols = packed

    kern = functools.partial(
        _kernel, block=block, kk=kk, cpp=cpp, rpz=float(rpz),
        hpz=float(hpz), tlookahead=float(tlookahead), mvpcfg=mvpcfg)

    acc = lambda: jax.ShapeDtypeStruct((nb, 1, block), dtype)
    out_shapes = [acc(), acc(), acc(), acc(), acc(), acc(), acc(), acc(),
                  jax.ShapeDtypeStruct((nb, kk, block), dtype),      # ctin
                  jax.ShapeDtypeStruct((nb, kk, block), jnp.int32)]  # cidx

    acc_spec = lambda: pl.BlockSpec((1, 1, block), lambda i, j: (i, 0, 0),
                                    memory_space=pltpu.VMEM)
    cand_spec = lambda: pl.BlockSpec(
        (1, kk, block), lambda i, j: (i, 0, 0),
        memory_space=pltpu.VMEM)

    outs = pl.pallas_call(
        kern,
        grid=(nb, nbp // cpp),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),       # reach flags
            pl.BlockSpec((1, _NF, block), lambda i, j: (i, 0, 0),
                         memory_space=pltpu.VMEM),       # ownship slab
            pl.BlockSpec((cpp, _NF, block), lambda i, j: (j, 0, 0),
                         memory_space=pltpu.VMEM),       # intruder slabs
        ],
        out_specs=[acc_spec() for _ in range(8)] + [cand_spec(), cand_spec()],
        out_shape=out_shapes,
        interpret=interpret,
    )(reach, packed, packed_cols)

    (inconf, tcpamax, sdve, sdvn, sdvv, tsolv, ncnt, lcnt,
     ctin, cidx) = outs

    unb = lambda a: a.reshape(nb * block)[:n]
    # Candidates: [nb, kk, block] -> [N, kk], already urgency-sorted
    topk_tin = ctin.transpose(0, 2, 1).reshape(nb * block, kk)[:n]
    topk_idx = cidx.transpose(0, 2, 1).reshape(nb * block, kk)[:n]
    topk_idx = jnp.where(topk_tin < _BIG, topk_idx, -1)

    return RowConflictData(
        inconf=unb(inconf) > 0.5,
        tcpamax=unb(tcpamax),
        sum_dve=unb(sdve), sum_dvn=unb(sdvn), sum_dvv=unb(sdvv),
        tsolv=unb(tsolv),
        # Cast per-block float counts to int32 BEFORE summing: a float32
        # total silently loses exactness past 2^24 pairs (plausible at 100k).
        nconf=jnp.sum(ncnt.astype(jnp.int32), dtype=jnp.int32),
        nlos=jnp.sum(lcnt.astype(jnp.int32), dtype=jnp.int32),
        topk_idx=topk_idx, topk_tin=topk_tin)
