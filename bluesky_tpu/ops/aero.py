"""ISA atmosphere + airspeed conversions as jitted JAX functions.

Parity with reference ``bluesky/tools/aero.py`` (vectorized ``v*`` family,
aero.py:62-172): two-layer ISA (troposphere + isothermal stratosphere up to
22 km), CAS/TAS/EAS/Mach conversions, and the crossover-aware ``vcasormach``.
Everything is elementwise math — ideal XLA fusion food — and works for both
scalars and arrays in any float dtype.  The scalar 8-layer ``atmos`` of the
reference (aero.py:178-260) is only used for ground-level utilities; the
vectorized 2-layer model is what the simulation loop uses, and that is what
we provide.
"""
import math

import jax.numpy as jnp

# Constants (reference aero.py:11-29)
kts = 0.514444          # m/s per knot
ft = 0.3048             # m per foot
fpm = ft / 60.0         # m/s per foot-per-minute
inch = 0.0254
sqft = 0.09290304
nm = 1852.0             # m per nautical mile
lbs = 0.453592          # kg per pound
g0 = 9.80665            # m/s2
R = 287.05287           # J/kg/K specific gas constant of air
p0 = 101325.0           # Pa sea-level ISA pressure
rho0 = 1.225            # kg/m3 sea-level ISA density
T0 = 288.15             # K sea-level ISA temperature
Tstrat = 216.65         # K stratosphere temperature
gamma = 1.40
gamma1 = 0.2            # (gamma-1)/2
gamma2 = 3.5            # gamma/(gamma-1)
beta = -0.0065          # K/m tropospheric lapse rate
Rearth = 6371000.0      # m mean earth radius
# Host-side math.sqrt, NOT jnp: a module-scope device op would initialise the
# JAX backend at import time and pin the platform before the caller (tests,
# multi-chip dryrun) can choose one.
a0 = math.sqrt(gamma * R * T0)  # sea-level speed of sound


def vtemp(h):
    """ISA temperature [K] at altitude h [m] (reference aero.py:77-79)."""
    return jnp.maximum(T0 + beta * h, Tstrat)


def vatmos(h):
    """ISA pressure [Pa], density [kg/m3], temperature [K] at h [m].

    Troposphere: rho ~ T^(g/(beta R) - 1); stratosphere: exponential decay.
    Constants match reference aero.py:62-74 digit for digit.
    """
    T = vtemp(h)
    rhotrop = rho0 * (T / T0) ** 4.256848030018761
    dhstrat = jnp.maximum(0.0, h - 11000.0)
    rho = rhotrop * jnp.exp(-dhstrat / 6341.552161)  # = g0/(R*Tstrat)
    p = rho * R * T
    return p, rho, T


def vpressure(h):
    return vatmos(h)[0]


def vdensity(h):
    return vatmos(h)[1]


def vvsound(h):
    """Speed of sound [m/s] at altitude h [m]."""
    return jnp.sqrt(gamma * R * vtemp(h))


def vtas2mach(tas, h):
    return tas / vvsound(h)


def vmach2tas(M, h):
    return M * vvsound(h)


def veas2tas(eas, h):
    return eas * jnp.sqrt(rho0 / vdensity(h))


def vtas2eas(tas, h):
    return tas * jnp.sqrt(vdensity(h) / rho0)


def vcas2tas(cas, h):
    """CAS -> TAS [m/s] via compressible-flow dynamic pressure (aero.py:128-136)."""
    p, rho, _ = vatmos(h)
    qdyn = p0 * ((1.0 + rho0 * cas * cas / (7.0 * p0)) ** 3.5 - 1.0)
    tas = jnp.sqrt(7.0 * p / rho * ((1.0 + qdyn / p) ** (2.0 / 7.0) - 1.0))
    return jnp.where(cas < 0, -tas, tas)


def vtas2cas(tas, h):
    """TAS -> CAS [m/s] (aero.py:139-147)."""
    p, rho, _ = vatmos(h)
    qdyn = p * ((1.0 + rho * tas * tas / (7.0 * p)) ** 3.5 - 1.0)
    cas = jnp.sqrt(7.0 * p0 / rho0 * ((qdyn / p0 + 1.0) ** (2.0 / 7.0) - 1.0))
    return jnp.where(tas < 0, -cas, cas)


def vmach2cas(M, h):
    return vtas2cas(vmach2tas(M, h), h)


def vcas2mach(cas, h):
    return vtas2mach(vcas2tas(cas, h), h)


def vcasormach(spd, h):
    """Interpret spd as Mach if 0.1 < spd < 1 else as CAS [m/s].

    Returns (tas, cas, mach) — reference aero.py:163-168.
    """
    ismach = jnp.logical_and(0.1 < spd, spd < 1.0)
    tas = jnp.where(ismach, vmach2tas(spd, h), vcas2tas(spd, h))
    cas = jnp.where(ismach, vtas2cas(tas, h), spd)
    m = jnp.where(ismach, spd, vtas2mach(tas, h))
    return tas, cas, m


def vcasormach2tas(spd, h):
    """TAS from a CAS-or-Mach command value (|spd|<1 => Mach), aero.py:170-172."""
    return jnp.where(jnp.abs(spd) < 1.0, vmach2tas(spd, h), vcas2tas(spd, h))


def crossoveralt(cas, mach):
    """Crossover altitude [m] where given CAS and Mach coincide.

    Standard ISA relation; used for above/below-crossover speed-hold logic
    (reference traffic keeps ``abco``/``belco`` flags, traffic.py:137-140).
    """
    # Impact pressure ratio at sea level for the CAS
    dp = (1.0 + gamma1 * (cas / a0) ** 2) ** gamma2 - 1.0
    # Pressure ratio at which the same impact pressure gives the target Mach
    pratio = dp / ((1.0 + gamma1 * mach * mach) ** gamma2 - 1.0)
    # Invert the tropospheric pressure law p/p0 = (T/T0)^(-g/(beta R))
    texp = -beta * R / g0  # ~ 0.19026
    return T0 / beta * (pratio ** texp - 1.0)


# Aliases matching the reference's scalar names (same vectorized code — JAX
# functions are shape-polymorphic, so no separate scalar implementations).
atmos = vatmos
temp = vtemp
pressure = vpressure
density = vdensity
vsound = vvsound
tas2mach = vtas2mach
mach2tas = vmach2tas
eas2tas = veas2tas
tas2eas = vtas2eas
cas2tas = vcas2tas
tas2cas = vtas2cas
mach2cas = vmach2cas
cas2mach = vcas2mach
casormach = vcasormach
casormach2tas = vcasormach2tas
