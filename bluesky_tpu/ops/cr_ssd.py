"""SSD conflict resolution: solution-space diagram on a velocity grid.

Capability parity with the reference ``traffic/asas/SSD.py:99-625``,
which builds velocity-obstacle polygons with pyclipper and picks the
resolution velocity per priority rule RS1-RS9.  That construction is
inherently sequential host geometry; this is a ground-up TPU redesign:

* The solution space is DISCRETIZED: candidate velocities sample a polar
  grid (``ntrk`` tracks x ``nspd`` speeds spanning [vmin, vmax] —
  matching the reference's SSD bounded by the speed envelope ring,
  SSD.py:131-141), plus two per-aircraft specials: the CURRENT velocity
  (whose freedom is the reference's ``inconf2`` test, SSD.py:304-307)
  and the AP velocity (the ``ap_free`` test, SSD.py:308-310).
* Each candidate is tested against every intruder with the same CPA
  predicate as conflict detection (a candidate lies inside the velocity
  obstacle of intruder j iff flying it would come within ``rpz_m`` of j
  inside the lookahead) — elementwise masks instead of polygon clipping,
  which is exactly the shape the VPU eats.  The intruder axis is
  CHUNKED (``lax.map`` over slices), so peak memory is [N, C, chunk]
  instead of [N, C, N] — the former ~500-aircraft ceiling is gone.
* The reference's nine priority codes (SSD.py:369-399, 429-558) become
  masks/objectives over the same free-velocity set:
    RS1  shortest way out: free candidate closest to current velocity.
    RS2  clockwise:  restrict to the half-plane RIGHT of own heading
         (the right-turn box of SSD.py:373-387).
    RS3  heading-only: restrict to the AP-speed ring (SSD.py:388-391).
    RS4  speed-only: restrict to the own-heading wedge (SSD.py:392-398).
    RS5  closest to the AP velocity; the AP velocity itself wins when
         free (SSD.py:446-453).
    RS6  rules-of-the-air: ignore VOs of intruders the ownship has
         priority over (bearing gates of SSD.py:296-302), with the RS2
         right-turn preference.
    RS7  sequential RS1: a second layer built from intruders within
         HALF the ADS-B range (SSD.py:113-114); when the current
         velocity conflicts in that near layer and the near solution
         differs from the full one, prefer the near-layer candidate
         (choice tie-broken by latest earliest-LoS, the grid analogue
         of minTLOS, SSD.py:515-558).
    RS8  sequential RS5: as RS7 with the AP-velocity objective.
    RS9  counter-clockwise: the LEFT half-plane (SSD.py:377-381).
  Restricted sets fall back to the unrestricted free set when empty,
  and to max earliest-conflict-time delay when nothing is free at all.

SSD remains a dense-backend tool (it consumes the [N,N] qdr/dist
matrices of ``ops/cd.py``), but the chunking lifts the memory ceiling to
what the dense CD itself allows (~16k aircraft).

Quantization bound (exact-certified in ``tests/test_cr_ssd_cert.py``
against an independent float64 closed-interval VO formulation, since
pyclipper is unavailable): the chosen velocity is (a) exactly
conflict-free whenever any grid candidate is, (b) the free-set optimum
of its grid, and (c) within the polar grid's covering radius
``h = hypot(vmax * 2pi/ntrk, (vmax - vmin)/(nspd - 1))`` of the exact
continuous optimum on a closed-form single-intruder cone — i.e. the
discretization error is bounded by the grid pitch (defaults: ~100 kts;
raise ntrk/nspd for finer resolutions, cost is linear).
"""
from typing import NamedTuple

import jax
import jax.numpy as jnp

ADSB_MAX = 65.0 * 1852.0     # [m] SSD.py:110 adsbmax


class SSDConfig(NamedTuple):
    ntrk: int = 24        # track samples (15 deg, SSD.py N_angle analogue)
    nspd: int = 6         # speed ring samples between vmin and vmax
    rpz_m: float = 9260.0  # resolution zone [m]
    tlookahead: float = 300.0
    priocode: str = "RS1"
    chunk: int = 512      # intruder-axis slab (memory: N*C*chunk floats)


def _wrap180(a):
    return (a + 180.0) % 360.0 - 180.0


def _vo_masks(cve, cvn, dxm, dym, gseast, gsnorth, pairok, cfg):
    """Chunked candidate-vs-intruder conflict reduction.

    cve/cvn: [N, C] candidate velocities.  Returns (anyconf [N, C],
    min_tin [N, C]) reduced over the intruder axis, never materialising
    [N, C, N]: ``lax.map`` walks intruder slabs of cfg.chunk.
    """
    n = dxm.shape[0]
    dtype = cve.dtype
    r2 = cfg.rpz_m * cfg.rpz_m
    big = jnp.asarray(1e18, dtype)
    nch = -(-n // cfg.chunk)
    npad = nch * cfg.chunk - n

    pad2 = lambda a: jnp.pad(a, ((0, 0), (0, npad)))
    dxp = pad2(dxm)
    dyp = pad2(dym)
    okp = jnp.pad(pairok, ((0, 0), (0, npad)))
    gep = jnp.pad(gseast, (0, npad))
    gnp_ = jnp.pad(gsnorth, (0, npad))

    def slab(c):
        s = c * cfg.chunk
        dx = jax.lax.dynamic_slice_in_dim(dxp, s, cfg.chunk, 1)[:, None, :]
        dy = jax.lax.dynamic_slice_in_dim(dyp, s, cfg.chunk, 1)[:, None, :]
        ok = jax.lax.dynamic_slice_in_dim(okp, s, cfg.chunk, 1)[:, None, :]
        ge = jax.lax.dynamic_slice_in_dim(gep, s, cfg.chunk, 0)
        gn = jax.lax.dynamic_slice_in_dim(gnp_, s, cfg.chunk, 0)
        # w = v_j - u_c (StateBasedCD.py:39-40 convention)
        wve = ge[None, None, :] - cve[:, :, None]      # [N, C, chunk]
        wvn = gn[None, None, :] - cvn[:, :, None]
        dv2 = wve * wve + wvn * wvn
        dv2 = jnp.where(dv2 < 1e-6, 1e-6, dv2)
        tcpa = -(wve * dx + wvn * dy) / dv2
        dcpa2 = dx * dx + dy * dy - tcpa * tcpa * dv2
        dtinhor = jnp.sqrt(jnp.maximum(0.0, r2 - dcpa2) / dv2)
        tin = tcpa - dtinhor
        conf = (dcpa2 < r2) & (tcpa + dtinhor > 0.0) \
            & (tin < cfg.tlookahead) & ok
        return (jnp.any(conf, axis=2),
                jnp.min(jnp.where(conf, jnp.maximum(tin, 0.0), big),
                        axis=2))

    anyc, mint = jax.lax.map(slab, jnp.arange(nch))
    return jnp.any(anyc, axis=0), jnp.min(mint, axis=0)


def _pick(free, allowed, dist2, min_tin):
    """Free candidate minimising dist2, preferring the ``allowed``
    restriction (fall back to any free candidate when the restricted set
    is empty — reference SSD.py:317-333 intersects and falls back), and
    to max earliest-conflict delay when nothing is free at all."""
    big = jnp.asarray(1e18, dist2.dtype)
    free_r = free & allowed
    has_r = jnp.any(free_r, axis=1)
    has_f = jnp.any(free, axis=1)
    sel = jnp.where(has_r[:, None], free_r, free)
    best_free = jnp.argmin(jnp.where(sel, dist2, big), axis=1)
    best_delay = jnp.argmax(jnp.where(jnp.isfinite(min_tin), min_tin, 0.0),
                            axis=1)
    return jnp.where(has_f, best_free, best_delay), has_f


def _candidate_grid(n, rule, cfg, dtype, hdg, ap_tas, ap_ve, ap_vn,
                    gseast, gsnorth, vmin, vmax):
    """[N, C] candidate velocities: polar product + the two specials
    ([C-2] = current velocity, [C-1] = AP velocity).  Shared by the
    dense and partner-table paths so the grids cannot drift."""
    if rule == "RS3":
        # heading-only: every track at the AP speed (SSD.py:388-391 ring)
        ctrk = jnp.linspace(0.0, 360.0, cfg.ntrk, endpoint=False,
                            dtype=dtype)[None, :].repeat(n, 0)
        cspd = jnp.clip(ap_tas, vmin, vmax)[:, None].repeat(cfg.ntrk, 1)
    elif rule == "RS4":
        # speed-only: the own-heading wedge (SSD.py:392-398)
        cspd = jnp.linspace(vmin, vmax, cfg.nspd,
                            dtype=dtype)[None, :].repeat(n, 0)
        ctrk = hdg[:, None].repeat(cfg.nspd, 1)
    else:
        trks = jnp.linspace(0.0, 360.0, cfg.ntrk, endpoint=False,
                            dtype=dtype)
        spds = jnp.linspace(vmin, vmax, cfg.nspd, dtype=dtype)
        ctrk = jnp.repeat(trks, cfg.nspd)[None, :].repeat(n, 0)
        cspd = jnp.tile(spds, cfg.ntrk)[None, :].repeat(n, 0)
    cve = cspd * jnp.sin(jnp.radians(ctrk))
    cvn = cspd * jnp.cos(jnp.radians(ctrk))
    cve = jnp.concatenate([cve, gseast[:, None], ap_ve[:, None]], axis=1)
    cvn = jnp.concatenate([cvn, gsnorth[:, None], ap_vn[:, None]], axis=1)
    return cve, cvn, ctrk


def _select_best(rule, cve, cvn, ctrk, hdg, free, min_tin, masks_near,
                 ap_ve, ap_vn, gseast, gsnorth):
    """Rule-restricted pick + the sequential (RS7/RS8) near layer + the
    RS5 AP override — the decision tail shared by both VO-mask sources.
    ``masks_near`` is a thunk returning (anyconf, min_tin) for the
    half-ADS-B-range layer, only called for RS7/RS8."""
    n = cve.shape[0]
    i_cur = cve.shape[1] - 2
    i_ap = cve.shape[1] - 1

    if rule in ("RS5", "RS8"):
        ref_e, ref_n = ap_ve, ap_vn
    else:
        ref_e, ref_n = gseast, gsnorth
    dist2 = (cve - ref_e[:, None]) ** 2 + (cvn - ref_n[:, None]) ** 2

    allowed = jnp.ones(cve.shape, bool)
    if rule in ("RS2", "RS6"):
        rel = _wrap180(ctrk - hdg[:, None])
        allowed = allowed.at[:, :-2].set(rel >= 0.0)   # right half-plane
    elif rule == "RS9":
        rel = _wrap180(ctrk - hdg[:, None])
        allowed = allowed.at[:, :-2].set(rel <= 0.0)   # left half-plane
    # the specials only participate where the reference consults them
    allowed = allowed.at[:, i_cur].set(False)
    allowed = allowed.at[:, i_ap].set(rule in ("RS5", "RS8"))

    best, has_f = _pick(free, allowed, dist2, min_tin)

    if rule in ("RS7", "RS8"):
        # Second, nearer layer: intruders within HALF the ADS-B range
        # (SSD.py:113-114); inconf2 = current velocity inside a near VO.
        anyc2, mint2 = masks_near()
        free2 = ~anyc2
        inconf2 = anyc2[:, i_cur]
        best2, has_f2 = _pick(free2, allowed, dist2, mint2)
        # Prefer the near-layer solution when the current velocity
        # conflicts nearby and the two solutions genuinely differ
        # (SSD.py:515-545; the <1 m/s^2 sameness test), tie-broken
        # toward the later earliest-LoS via _pick's dist2 objective.
        d12 = (cve[jnp.arange(n), best] - cve[jnp.arange(n), best2]) ** 2 \
            + (cvn[jnp.arange(n), best] - cvn[jnp.arange(n), best2]) ** 2
        use2 = inconf2 & has_f2 & (d12 >= 1.0)
        best = jnp.where(use2, best2, best)

    if rule == "RS5":
        # AP setting wins when it is conflict-free (SSD.py:446-453)
        best = jnp.where(free[:, i_ap], i_ap, best)

    btrk = jnp.degrees(jnp.arctan2(
        jnp.take_along_axis(cve, best[:, None], 1)[:, 0],
        jnp.take_along_axis(cvn, best[:, None], 1)[:, 0])) % 360.0
    bspd = jnp.sqrt(
        jnp.take_along_axis(cve, best[:, None], 1)[:, 0] ** 2
        + jnp.take_along_axis(cvn, best[:, None], 1)[:, 0] ** 2)
    return btrk, bspd


def resolve(cd, lat, lon, alt, trk, gs, vs, gseast, gsnorth, active,
            vmin, vmax, cfg: SSDConfig, hdg=None, ap_trk=None,
            ap_tas=None):
    """Priority-rule resolution velocities for in-conflict aircraft.

    Returns (newtrk, newgs): per-aircraft track/speed of the chosen free
    velocity (aircraft not in conflict keep their current trk/gs).
    ``hdg``/``ap_trk``/``ap_tas`` feed the heading- and AP-referenced
    rules; they default to trk/gs when omitted (RS1 needs neither).
    """
    n = lat.shape[0]
    dtype = gs.dtype
    rule = cfg.priocode.upper()
    hdg = trk if hdg is None else hdg
    ap_trk = trk if ap_trk is None else ap_trk
    ap_tas = gs if ap_tas is None else ap_tas
    ap_ve = ap_tas * jnp.sin(jnp.radians(ap_trk))
    ap_vn = ap_tas * jnp.cos(jnp.radians(ap_trk))

    cve, cvn, ctrk = _candidate_grid(n, rule, cfg, dtype, hdg, ap_tas,
                                     ap_ve, ap_vn, gseast, gsnorth,
                                     vmin, vmax)

    # ---- Pair geometry from the CD output ----
    qdrrad = jnp.radians(cd.qdr)
    dxm = cd.dist * jnp.sin(qdrrad)                # [N,N] i->j east
    dym = cd.dist * jnp.cos(qdrrad)
    eye = jnp.eye(n, dtype=bool)
    pairok = (active[:, None] & active[None, :]) & ~eye
    # The reference only sees intruders within ADS-B range (SSD.py:110)
    pairok = pairok & (cd.dist < ADSB_MAX)

    if rule == "RS6":
        # Rules of the air (SSD.py:296-302): the VO of intruder j binds
        # only when own must give way — head-on / converging from the
        # right (bearing from own view in [-20, 110]) or own overtaking
        # (bearing from j's view beyond +-110).
        brg_own = _wrap180(cd.qdr - hdg[:, None])
        brg_oth = _wrap180(cd.qdr + 180.0 - hdg[None, :])
        must_avoid = ((brg_own >= -20.0) & (brg_own <= 110.0)) \
            | (brg_oth <= -110.0) | (brg_oth >= 110.0)
        pairok = pairok & must_avoid

    anyconf, min_tin = _vo_masks(cve, cvn, dxm, dym, gseast, gsnorth,
                                 pairok, cfg)

    def masks_near():
        return _vo_masks(cve, cvn, dxm, dym, gseast, gsnorth,
                         pairok & (cd.dist < ADSB_MAX / 2.0), cfg)

    btrk, bspd = _select_best(rule, cve, cvn, ctrk, hdg, ~anyconf,
                              min_tin, masks_near, ap_ve, ap_vn,
                              gseast, gsnorth)
    newtrk = jnp.where(cd.inconf, btrk, trk)
    newgs = jnp.where(cd.inconf, bspd, gs)
    return newtrk, newgs


def _vo_masks_pairs(cve, cvn, dx, dy, vje, vjn, ok, cfg, chunk=16):
    """VO-mask reduction over a GATHERED [N, P] partner set.

    Same CPA predicate as ``_vo_masks`` but the intruder axis is the
    per-ownship partner table, not the whole fleet; the candidate axis
    is chunked (``lax.map``) so peak memory is [N, chunk, P] instead of
    [N, C, P].  Returns (anyconf [N, C], min_tin [N, C])."""
    n, c = cve.shape
    p = dx.shape[1]
    dtype = cve.dtype
    r2 = cfg.rpz_m * cfg.rpz_m
    big = jnp.asarray(1e18, dtype)
    nch = -(-c // chunk)
    cpad = nch * chunk - c

    cvep = jnp.pad(cve, ((0, 0), (0, cpad)))
    cvnp = jnp.pad(cvn, ((0, 0), (0, cpad)))

    def slab(ci):
        s = ci * chunk
        ce = jax.lax.dynamic_slice_in_dim(cvep, s, chunk, 1)[:, :, None]
        cn = jax.lax.dynamic_slice_in_dim(cvnp, s, chunk, 1)[:, :, None]
        # w = v_j - u_c (StateBasedCD.py:39-40 convention)
        wve = vje[:, None, :] - ce                       # [N, chunk, P]
        wvn = vjn[:, None, :] - cn
        dv2 = wve * wve + wvn * wvn
        dv2 = jnp.where(dv2 < 1e-6, 1e-6, dv2)
        dxc = dx[:, None, :]
        dyc = dy[:, None, :]
        tcpa = -(wve * dxc + wvn * dyc) / dv2
        dcpa2 = dxc * dxc + dyc * dyc - tcpa * tcpa * dv2
        dtinhor = jnp.sqrt(jnp.maximum(0.0, r2 - dcpa2) / dv2)
        tin = tcpa - dtinhor
        conf = (dcpa2 < r2) & (tcpa + dtinhor > 0.0) \
            & (tin < cfg.tlookahead) & ok[:, None, :]
        return (jnp.any(conf, axis=2),
                jnp.min(jnp.where(conf, jnp.maximum(tin, 0.0), big),
                        axis=2))

    anyc, mint = jax.lax.map(slab, jnp.arange(nch))
    anyc = anyc.transpose(1, 0, 2).reshape(n, nch * chunk)[:, :c]
    mint = mint.transpose(1, 0, 2).reshape(n, nch * chunk)[:, :c]
    return anyc, mint


def resolve_from_partners(partners, inconf, lat, lon, alt, trk, gs, vs,
                          gseast, gsnorth, active, vmin, vmax,
                          cfg: SSDConfig, hdg=None, ap_trk=None,
                          ap_tas=None):
    """SSD resolution from an [N, P] partner table — the large-N path.

    The blockwise CD backends never materialise [N, N] matrices; what
    they do produce is the per-ownship partner table: the K most urgent
    currently-conflicting intruders merged with the still-engaged
    partners of previous intervals (``cd_tiled.topk_partners`` /
    the sparse backend's in-kernel merge).  This resolver builds the
    velocity obstacles from exactly that set.

    **K-truncation semantics** (the documented delta vs the dense path,
    reference SSD.py:110-141 which draws a VO for EVERY intruder within
    ADS-B range): only the tabled intruders contribute VOs, so the
    chosen velocity is guaranteed conflict-free against the K most
    urgent threats (and all held partners), but may conflict with an
    untabled neighbour — such a pair is surfaced by the very next CD
    interval (it becomes a most-urgent conflict itself) and resolved
    then.  Scenes whose per-ownship conflict count stays within K are
    bit-equivalent to the dense path.

    ``partners`` holds caller-space intruder indices, -1 = empty.
    Returns (newtrk, newgs); non-conflicting aircraft keep trk/gs.
    """
    n = lat.shape[0]
    dtype = gs.dtype
    rule = cfg.priocode.upper()
    hdg = trk if hdg is None else hdg
    ap_trk = trk if ap_trk is None else ap_trk
    ap_tas = gs if ap_tas is None else ap_tas
    ap_ve = ap_tas * jnp.sin(jnp.radians(ap_trk))
    ap_vn = ap_tas * jnp.cos(jnp.radians(ap_trk))

    cve, cvn, ctrk = _candidate_grid(n, rule, cfg, dtype, hdg, ap_tas,
                                     ap_ve, ap_vn, gseast, gsnorth,
                                     vmin, vmax)

    # ---- Gathered pair geometry (own -> partner), [N, P] ----
    from . import cd_tiled
    valid = partners >= 0
    j = jnp.clip(partners, 0, n - 1)
    trig = cd_tiled.precompute_trig(lat, lon)
    own_t = {k: v[:, None] for k, v in trig.items()}
    intr_t = {k: v[j] for k, v in trig.items()}
    dist, sinqdr, cosqdr = cd_tiled.tile_geometry(own_t, intr_t)
    dx = dist * sinqdr
    dy = dist * cosqdr
    vje = gseast[j]
    vjn = gsnorth[j]
    ok = valid & active[:, None] & active[j] & (dist < ADSB_MAX)

    if rule == "RS6":
        # Rules-of-the-air gates on the gathered bearings (SSD.py:296-302)
        qdr = jnp.degrees(jnp.arctan2(sinqdr, cosqdr))
        brg_own = _wrap180(qdr - hdg[:, None])
        brg_oth = _wrap180(qdr + 180.0 - hdg[j])
        must_avoid = ((brg_own >= -20.0) & (brg_own <= 110.0)) \
            | (brg_oth <= -110.0) | (brg_oth >= 110.0)
        ok = ok & must_avoid

    anyconf, min_tin = _vo_masks_pairs(cve, cvn, dx, dy, vje, vjn, ok, cfg)

    def masks_near():
        return _vo_masks_pairs(cve, cvn, dx, dy, vje, vjn,
                               ok & (dist < ADSB_MAX / 2.0), cfg)

    btrk, bspd = _select_best(rule, cve, cvn, ctrk, hdg, ~anyconf,
                              min_tin, masks_near, ap_ve, ap_vn,
                              gseast, gsnorth)
    newtrk = jnp.where(inconf, btrk, trk)
    newgs = jnp.where(inconf, bspd, gs)
    return newtrk, newgs
