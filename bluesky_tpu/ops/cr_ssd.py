"""SSD conflict resolution: solution-space diagram on a velocity grid.

Capability parity with the reference ``traffic/asas/SSD.py:99-625``,
which builds velocity-obstacle polygons with pyclipper and picks the
resolution velocity per priority rule.  That construction is inherently
sequential host geometry; this is a ground-up TPU redesign:

* The solution space is DISCRETIZED: candidate velocities sample a polar
  grid (``ntrk`` tracks x ``nspd`` speeds spanning [vmin, vmax] —
  matching the reference's SSD bounded by the speed envelope ring,
  SSD.py:131-141).
* Each candidate is tested against every intruder with the same
  CPA predicate as conflict detection (a candidate lies inside the
  velocity obstacle of intruder j iff flying it would come within
  ``rpz_m`` of j inside the lookahead) — an [N, C, N] elementwise mask
  instead of polygon clipping, which is exactly the shape the VPU eats.
* Resolution rule RS1 "shortest way out" (the reference default,
  SSD.py:429-500): among free candidates, take the one closest to the
  current velocity.  If the whole grid is forbidden, fall back to the
  candidate whose earliest conflict is farthest away (max min-tin).

Memory: N * C * N floats with C = ntrk*nspd.  With the default 24x6
grid and N=500 that is ~2 GB transient — SSD is a small-N study tool in
the reference too (pyclipper per pair per step); for big-N use MVP.
"""
from typing import NamedTuple

import jax.numpy as jnp


class SSDConfig(NamedTuple):
    ntrk: int = 24        # track samples (15 deg, SSD.py N_angle analogue)
    nspd: int = 6         # speed ring samples between vmin and vmax
    rpz_m: float = 9260.0  # resolution zone [m]
    tlookahead: float = 300.0


def resolve(cd, lat, lon, alt, trk, gs, vs, gseast, gsnorth, active,
            vmin, vmax, cfg: SSDConfig):
    """RS1 resolution velocities for in-conflict aircraft.

    Returns (newtrk, newgs): per-aircraft track/speed of the chosen free
    velocity (aircraft not in conflict get their current trk/gs back).
    """
    n = lat.shape[0]
    dtype = gs.dtype

    # Candidate velocity grid [C]: polar product of tracks and speeds
    trks = jnp.linspace(0.0, 360.0, cfg.ntrk, endpoint=False, dtype=dtype)
    spds = jnp.linspace(vmin, vmax, cfg.nspd, dtype=dtype)
    ctrk = jnp.repeat(trks, cfg.nspd)              # [C]
    cspd = jnp.tile(spds, cfg.ntrk)                # [C]
    cve = cspd * jnp.sin(jnp.radians(ctrk))        # [C] east
    cvn = cspd * jnp.cos(jnp.radians(ctrk))        # [C] north

    # Pairwise geometry from the CD output (relative position i->j)
    qdrrad = jnp.radians(cd.qdr)
    dxm = cd.dist * jnp.sin(qdrrad)                # [N,N]
    dym = cd.dist * jnp.cos(qdrrad)
    eye = jnp.eye(n, dtype=bool)
    pairok = (active[:, None] & active[None, :]) & ~eye

    # Relative velocity for candidate c of ownship i vs intruder j, in
    # the CD convention (StateBasedCD.py:39-40 via its (1,N)/(N,1)
    # broadcast): w = v_j - u_c.  [1,C,N] against [N,1,N] geometry.
    wve = gseast[None, None, :] - cve[None, :, None]    # [1,C,N]
    wvn = gsnorth[None, None, :] - cvn[None, :, None]
    dx = dxm[:, None, :]                                # [N,1,N]
    dy = dym[:, None, :]

    dv2 = wve * wve + wvn * wvn
    dv2 = jnp.where(dv2 < 1e-6, 1e-6, dv2)
    tcpa = -(wve * dx + wvn * dy) / dv2                 # [N,C,N]
    dcpa2 = dx * dx + dy * dy - tcpa * tcpa * dv2
    r2 = cfg.rpz_m * cfg.rpz_m
    # Horizontal-only VO test (the reference SSD is a horizontal method,
    # SSD.py:99-104): conflict if CPA inside rpz within the lookahead
    dxinhor = jnp.sqrt(jnp.maximum(0.0, r2 - dcpa2))
    dtinhor = dxinhor / jnp.sqrt(dv2)
    tin = tcpa - dtinhor
    conflict = (dcpa2 < r2) & (tcpa + dtinhor > 0.0) \
        & (tin < cfg.tlookahead)
    conflict = conflict & pairok[:, None, :]

    free = ~jnp.any(conflict, axis=2)                   # [N,C]

    # RS1: free candidate closest to the current velocity (SSD.py:429+)
    dist2 = (cve[None, :] - gseast[:, None]) ** 2 \
        + (cvn[None, :] - gsnorth[:, None]) ** 2       # [N,C]
    big = jnp.asarray(1e18, dtype)
    best_free = jnp.argmin(jnp.where(free, dist2, big), axis=1)

    # Fallback when nothing is free: max earliest-conflict time
    tin_masked = jnp.where(conflict, jnp.maximum(tin, 0.0), big)
    min_tin = jnp.min(tin_masked, axis=2)               # [N,C]
    best_delay = jnp.argmax(jnp.where(jnp.isfinite(min_tin), min_tin,
                                      0.0), axis=1)
    any_free = jnp.any(free, axis=1)
    best = jnp.where(any_free, best_free, best_delay)

    newtrk = jnp.where(cd.inconf, ctrk[best], trk)
    newgs = jnp.where(cd.inconf, cspd[best], gs)
    return newtrk, newgs
