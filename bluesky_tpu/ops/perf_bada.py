"""BADA 3 thrust and fuel-flow kernels.

Elementwise jnp parity with the physics block of the reference
``traffic/performance/bada/perfbada.py:390-520`` (BADA User Manual 3.12):
max-climb thrust by engine type (jet / turboprop / piston), level and
phase-dependent descent thrust, reduced-climb-power correction, and
thrust-specific fuel consumption with nominal / minimal / cruise /
approach regimes.

Inputs are per-aircraft coefficient columns (from models/coeff_bada.py)
and state arrays; everything is masked select over the padded axis, so
the whole block fuses into the scanned step.
"""
import jax.numpy as jnp

from . import aero
from .perf_legacy import PHASE_CR, PHASE_AP, PHASE_LD, PHASE_GD


def max_climb_thrust(alt, tas, jet, turbo, piston, ctcth1, ctcth2, ctcth3):
    """Max climb (= max available) thrust in ISA [N]
    (perfbada.py:404-429; BADA 3.12 p.32)."""
    h_ft = alt / aero.ft
    tas_kt = jnp.maximum(1.0, tas / aero.kts)
    tj = ctcth1 * (1.0 - h_ft / ctcth2 + ctcth3 * h_ft * h_ft)
    tt = ctcth1 / tas_kt * (1.0 - h_ft / ctcth2) + ctcth3
    tp = ctcth1 * (1.0 - h_ft / ctcth2) + ctcth3 / tas_kt
    return jnp.where(jet, tj, jnp.where(turbo, tt, tp * piston))


def thrust(phase, climb, descent, lvl, alt, tas, drag, jet, turbo, piston,
           ctcth1, ctcth2, ctcth3, ctdesl, ctdesh, ctdesa, ctdesld,
           hpdes):
    """Thrust by flight condition (perfbada.py:404-458).

    Returns (thr, maxthr).  ``lvl`` = level flight mask.
    """
    h_ft = alt / aero.ft
    tas_kt = jnp.maximum(1.0, tas / aero.kts)
    tj = ctcth1 * (1.0 - h_ft / ctcth2 + ctcth3 * h_ft * h_ft)
    tt = ctcth1 / tas_kt * (1.0 - h_ft / ctcth2) + ctcth3
    tp = ctcth1 * (1.0 - h_ft / ctcth2) + ctcth3 / tas_kt
    tjc = (climb & jet) * tj
    ttc = (climb & turbo) * tt
    tpc = (climb & piston) * tp
    maxthr = tj * jet + tt * turbo + tp * piston

    tlvl = lvl * drag

    delh = alt - hpdes
    high = delh > 0.0
    low = delh < 0.0
    tdesh = maxthr * ctdesh * (descent & high)
    tdeslc = maxthr * ctdesl * (descent & low & (phase == PHASE_CR))
    tdesla = maxthr * ctdesa * (descent & low & (phase == PHASE_AP))
    tdesll = maxthr * ctdesld * (descent & low & (phase == PHASE_LD))
    tgd = jnp.minimum(tdesh, tdeslc) * (phase == PHASE_GD)

    thr = jnp.max(jnp.stack([tjc, ttc, tpc, tlvl, tdesh, tdeslc,
                             tdesla, tdesll, tgd]), axis=0)
    return thr, maxthr


def reduced_climb_power(alt, hmaxact, climb, cred, mass, mmin, mmax):
    """Reduced-climb-power factor cpred (perfbada.py:462-469)."""
    clh = (alt < hmaxact * 0.8) & climb
    c = cred * clh
    return 1.0 - c * ((mmax - mass) / (mmax - mmin))


def fuelflow(phase, alt, tas, thr, jet, turbo, piston, cf1, cf2, cf3, cf4,
             cf_cruise):
    """Fuel flow by regime (perfbada.py:483-520).

    Returns (fnom, fmin, fcr, fal): nominal, minimal, cruise, and
    approach/landing fuel flows [kg/s equivalent of the reference's
    units]; the caller selects per phase like perfbada.py:523-535.
    """
    tas_kt = tas / aero.kts
    h_ft = alt / aero.ft
    etaj = cf1 * (1.0 + tas_kt / cf2)
    etat = cf1 * (1.0 - tas_kt / cf2) * (tas_kt / 1000.0)
    eta = jnp.maximum(etaj * jet, etat * turbo) / 1000.0

    jt = jet | turbo
    fnom = eta * thr * jt + cf1 * piston
    fmin = cf3 * (1.0 - h_ft / cf4) * jt + cf3 * piston
    fcr = eta * thr * cf_cruise * jt + cf1 * cf_cruise * piston
    fal = jnp.maximum(fnom, fmin)
    return fnom, fmin, fcr, fal
