"""State-based conflict detection as a batched all-pairs kernel.

Semantic parity with the reference's ``bluesky/traffic/asas/StateBasedCD.py``
(StateBasedCD.py:7-103) and its C++ twin ``casas.cpp``: pairwise
bearing/distance on the WGS-84 mean-radius sphere, closest-point-of-approach
(CPA) from the relative velocity, horizontal entry/exit times, vertical
protected-disk crossing times, and the combined conflict predicate within the
lookahead horizon.

TPU-first redesign:
* The reference materialises a dozen N x N float64 matrices in NumPy and
  returns *Python lists* of conflict pairs.  Here the whole computation is one
  fused jnp broadcast over ``[N, 1]`` vs ``[1, N]`` operands, stays on device,
  and returns fixed-shape arrays (the ``[N, N]`` conflict mask + per-pair
  geometry) so the resolver can consume them without host sync.
* Inactive padding slots are excluded the same way the reference excludes the
  diagonal: a 1e9 offset on distance/tcpa plus a hard mask on the flags, so
  numerics of real pairs are untouched.
* Pair *lists* (for stack commands / logging) are extracted lazily on the
  host from the returned mask — see ``core/asas.py``.

For N beyond ~16k the N^2 f32 matrices stop fitting in HBM comfortably; the
tiled Pallas variant in ``ops/cd_pallas.py`` streams tiles through VMEM
instead.  This reference version is the golden-test anchor.
"""
from typing import NamedTuple

import jax.numpy as jnp

from . import geo


class ConflictData(NamedTuple):
    """Fixed-shape device-side conflict-detection output.

    All pairwise matrices are indexed [ownship i, intruder j]; entries where
    ``swconfl`` is False are garbage (masked large values), matching how the
    reference only reads matrix entries at conflict indices
    (StateBasedCD.py:98-101).
    """
    swconfl: jnp.ndarray   # [N,N] bool  conflict pair flag (directional)
    swlos: jnp.ndarray     # [N,N] bool  loss-of-separation flag
    inconf: jnp.ndarray    # [N]   bool  ownship-in-conflict flag
    tcpamax: jnp.ndarray   # [N]         max tcpa over this ownship's conflicts
    qdr: jnp.ndarray       # [N,N] deg   bearing i->j
    dist: jnp.ndarray      # [N,N] m     distance i->j (diagonal/masked +1e9)
    dcpa2: jnp.ndarray     # [N,N] m2    min separation squared at CPA
    tcpa: jnp.ndarray      # [N,N] s     time to CPA (diagonal/masked +1e9)
    tinconf: jnp.ndarray   # [N,N] s     time of conflict entry (tLOS)
    toutconf: jnp.ndarray  # [N,N] s     time of conflict exit


def detect(lat, lon, trk, gs, alt, vs, active, rpz, hpz, tlookahead):
    """All-pairs state-based conflict detection.

    Args:
      lat, lon:  [N] position [deg]
      trk:       [N] ground track [deg]
      gs:        [N] ground speed [m/s]
      alt:       [N] altitude [m]
      vs:        [N] vertical speed [m/s]
      active:    [N] bool mask of live (non-padding) aircraft
      rpz:       protected-zone radius [m]
      hpz:       protected-zone half-height [m]
      tlookahead: detection horizon [s]

    Returns a ``ConflictData``; numerics of active off-diagonal pairs match
    the NumPy reference elementwise (same operations, same order).
    """
    n = lat.shape[0]
    # Diagonal + padding exclusion, generalising the reference's
    # ``1e9 * I`` trick (StateBasedCD.py:11,22) to inactive slots.
    eye = jnp.eye(n, dtype=bool)
    pairmask = (active[:, None] & active[None, :]) & ~eye
    bigval = jnp.asarray(1e9, dtype=lat.dtype)
    excl = jnp.where(pairmask, 0.0, bigval)

    # Horizontal geometry ---------------------------------------------------
    qdr, distnm = geo.qdrdist_matrix(lat, lon, lat, lon)
    dist = distnm * geo.nm + excl

    qdrrad = jnp.radians(qdr)
    dx = dist * jnp.sin(qdrrad)   # east offset of j relative to i
    dy = dist * jnp.cos(qdrrad)   # north offset of j relative to i

    trkrad = jnp.radians(trk)
    u = gs * jnp.sin(trkrad)      # [N] east ground-speed component
    v = gs * jnp.cos(trkrad)      # [N] north ground-speed component

    # du[i,j] = u[j] - u[i]: relative velocity of j as seen from i
    # (reference builds the same matrix via ownu - intu.T,
    #  StateBasedCD.py:31-40).
    du = u[None, :] - u[:, None]
    dv = v[None, :] - v[:, None]

    dv2 = du * du + dv * dv
    dv2 = jnp.where(jnp.abs(dv2) < 1e-6, 1e-6, dv2)
    vrel = jnp.sqrt(dv2)

    tcpa = -(du * dx + dv * dy) / dv2 + excl

    # Minimum (squared) horizontal separation at CPA
    dcpa2 = dist * dist - tcpa * tcpa * dv2

    r2 = rpz * rpz
    swhorconf = dcpa2 < r2

    dxinhor = jnp.sqrt(jnp.maximum(0.0, r2 - dcpa2))
    dtinhor = dxinhor / vrel
    tinhor = jnp.where(swhorconf, tcpa - dtinhor, 1e8)
    touthor = jnp.where(swhorconf, tcpa + dtinhor, -1e8)

    # Vertical geometry -----------------------------------------------------
    # dalt[i,j] = alt[j] - alt[i] (+ exclusion offset), matching
    # StateBasedCD.py:65-66 where ownship row j minus intruder column i.
    dalt = alt[None, :] - alt[:, None] + excl
    dvs = vs[None, :] - vs[:, None]
    dvs = jnp.where(jnp.abs(dvs) < 1e-6, 1e-6, dvs)

    tcrosshi = (dalt + hpz) / -dvs
    tcrosslo = (dalt - hpz) / -dvs
    tinver = jnp.minimum(tcrosshi, tcrosslo)
    toutver = jnp.maximum(tcrosshi, tcrosslo)

    # Combined --------------------------------------------------------------
    tinconf = jnp.maximum(tinver, tinhor)
    toutconf = jnp.minimum(toutver, touthor)

    swconfl = (swhorconf
               & (tinconf <= toutconf)
               & (toutconf > 0.0)
               & (tinconf < tlookahead)
               & pairmask)

    inconf = jnp.any(swconfl, axis=1)
    tcpamax = jnp.max(tcpa * swconfl, axis=1)

    swlos = (dist < rpz) & (jnp.abs(dalt) < hpz) & pairmask

    return ConflictData(swconfl=swconfl, swlos=swlos, inconf=inconf,
                        tcpamax=tcpamax, qdr=qdr, dist=dist, dcpa2=dcpa2,
                        tcpa=tcpa, tinconf=tinconf, toutconf=toutconf)


def pairs_from_mask(mask, ids):
    """Host helper: extract [(id_i, id_j), ...] from a boolean pair matrix.

    Row-major order matches the reference's ``zip(*np.where(swconfl))``
    (StateBasedCD.py:93-95).  ``ids`` is the host-side list of callsigns.
    """
    import numpy as np
    rows, cols = np.where(np.asarray(mask))
    return [(ids[i], ids[j]) for i, j in zip(rows, cols)]
