"""Geodesy ops on the WGS-84 ellipsoid, as jitted JAX functions.

Functional parity with the reference's ``bluesky/tools/geo.py`` (and its C++
twin ``bluesky/tools/src_cpp/cgeo.cpp``): WGS-84 local earth radius, haversine
bearing/distance (scalar + all-pairs), dead-reckoning position projection, and
the fast flat-earth ``kwik*`` approximations.  All functions are pure,
dtype-polymorphic (float32 on TPU for speed, float64 on CPU for golden tests)
and shape-polymorphic under broadcasting, so the "matrix" variants are the
same code evaluated on ``[N,1]`` vs ``[1,M]`` operands — XLA fuses the whole
chain into one kernel instead of materialising intermediates like the NumPy
reference does.

Reference semantics notes (kept for behavioural parity, see docstrings):
* reference ``geo.py:57-107``  (qdrdist: hemisphere-aware mean radius)
* reference ``geo.py:110-162`` (qdrdist_matrix: radius evaluated at the SUM of
  the two latitudes — a reference quirk we reproduce in the ``*_matrix``
  variants because conflict detection numerics depend on it)
* reference ``geo.py:263-285`` (qdrpos), ``geo.py:288-382`` (kwik*)
"""
import jax
import jax.numpy as jnp

# 1 nautical mile in metres (reference geo.py:7)
nm = 1852.0

# WGS-84 semi-axes [m]
A_WGS84 = 6378137.0
B_WGS84 = 6356752.314245

# Mean earth radius used by the kwik* flat-earth approximations [m]
REARTH = 6371000.0


def rwgs84(latd):
    """Local WGS-84 ellipsoid radius [m] at geodetic latitude latd [deg].

    Same formula as reference geo.py:10-28 (geometric mean of the radius of
    curvature components).
    """
    lat = jnp.radians(latd)
    coslat = jnp.cos(lat)
    sinlat = jnp.sin(lat)
    an = A_WGS84 * A_WGS84 * coslat
    bn = B_WGS84 * B_WGS84 * sinlat
    ad = A_WGS84 * coslat
    bd = B_WGS84 * sinlat
    return jnp.sqrt((an * an + bn * bn) / (ad * ad + bd * bd))


def _mean_radius_scalar(latd1, latd2):
    """Hemisphere-aware mean earth radius (reference geo.py:65-83).

    Same hemisphere: radius at the average latitude.  Different hemispheres:
    latitude-weighted average of the local radii blended with the equatorial
    semi-axis.
    """
    res1 = rwgs84(0.5 * (latd1 + latd2))
    r1 = rwgs84(latd1)
    r2 = rwgs84(latd2)
    denom = jnp.abs(latd1) + jnp.abs(latd2)
    # Guard denom==0 (both on the equator -> same-hemisphere branch is taken).
    res2 = 0.5 * (jnp.abs(latd1) * (r1 + A_WGS84)
                  + jnp.abs(latd2) * (r2 + A_WGS84)) / jnp.maximum(denom, 1e-30)
    return jnp.where(latd1 * latd2 >= 0.0, res1, res2)


def _mean_radius_matrix(latd1, latd2):
    """Hemisphere-aware radius with the reference *matrix* quirks.

    Reference geo.py:117-128 evaluates the same-hemisphere radius at
    ``lat1 + lat2`` (NOT the average — a long-standing BlueSky quirk) and adds
    a 1e-6 deg epsilon to the denominator where lat1 == 0.  Conflict-detection
    distances inherit these numerics, so the all-pairs path reproduces them
    exactly for golden-test parity.
    """
    res1 = rwgs84(latd1 + latd2)
    r1 = rwgs84(latd1)
    r2 = rwgs84(latd2)
    denom = jnp.abs(latd1) + jnp.abs(latd2) + jnp.where(latd1 == 0.0, 1e-6, 0.0)
    res2 = 0.5 * (jnp.abs(latd1) * (r1 + A_WGS84)
                  + jnp.abs(latd2) * (r2 + A_WGS84)) / denom
    return jnp.where(latd1 * latd2 < 0.0, res2, res1)


def _haversine_qdr_dist(latd1, lond1, latd2, lond2, r, atan2=None):
    """Shared haversine core: bearing [deg] and distance [m] given radius r.

    ``atan2`` is injectable because Mosaic has no atan2 lowering — the
    Pallas CD kernel passes ``kmath.atan2`` (f32 Cephes evaluation); every
    other caller gets the exact jnp primitive.
    """
    atan2 = atan2 or jnp.arctan2
    lat1 = jnp.radians(latd1)
    lon1 = jnp.radians(lond1)
    lat2 = jnp.radians(latd2)
    lon2 = jnp.radians(lond2)

    sin1 = jnp.sin(0.5 * (lat2 - lat1))
    sin2 = jnp.sin(0.5 * (lon2 - lon1))
    coslat1 = jnp.cos(lat1)
    coslat2 = jnp.cos(lat2)

    root = sin1 * sin1 + coslat1 * coslat2 * sin2 * sin2
    # arctan2 form (not arcsin) matches the reference and is stable near
    # antipodes.
    d = 2.0 * r * atan2(jnp.sqrt(root), jnp.sqrt(1.0 - root))

    qdr = jnp.degrees(atan2(
        jnp.sin(lon2 - lon1) * coslat2,
        coslat1 * jnp.sin(lat2) - jnp.sin(lat1) * coslat2 * jnp.cos(lon2 - lon1)))
    return qdr, d


def qdrdist(latd1, lond1, latd2, lond2):
    """Bearing [deg] and distance [nm] from pos1 to pos2 (reference geo.py:57-107)."""
    r = _mean_radius_scalar(latd1, latd2)
    qdr, d = _haversine_qdr_dist(latd1, lond1, latd2, lond2, r)
    return qdr, d / nm


def latlondist(latd1, lond1, latd2, lond2):
    """Distance [m] between two positions (reference geo.py:165-208)."""
    r = _mean_radius_scalar(latd1, latd2)
    _, d = _haversine_qdr_dist(latd1, lond1, latd2, lond2, r)
    return d


def qdrdist_matrix(latd1, lond1, latd2, lond2):
    """All-pairs bearing [deg] / distance [nm]: row i = from pos1[i], col j = to pos2[j].

    Broadcasting replacement for reference geo.py:110-162 (np.mat based),
    including its radius-at-sum-of-latitudes quirk.  Inputs are 1-D vectors;
    output is [len(pos1), len(pos2)].
    """
    latd1 = jnp.asarray(latd1)[:, None]
    lond1 = jnp.asarray(lond1)[:, None]
    latd2 = jnp.asarray(latd2)[None, :]
    lond2 = jnp.asarray(lond2)[None, :]
    r = _mean_radius_matrix(latd1, latd2)
    # The reference matrix haversine (geo.py:153-158) takes |sin(dlat/2)|,
    # |sin(dlon/2)| — absolute values don't change the squares, so the shared
    # core is numerically identical.
    qdr, d = _haversine_qdr_dist(latd1, lond1, latd2, lond2, r)
    return qdr, d / nm


def latlondist_matrix(latd1, lond1, latd2, lond2):
    """All-pairs distance [nm] (reference geo.py:211-248; NB reference doc
    says metres but the code returns nm — we match the code)."""
    _, d = qdrdist_matrix(latd1, lond1, latd2, lond2)
    return d


def wgsg(latd):
    """WGS-84 gravity [m/s2] at latitude latd [deg] (reference geo.py:251-260)."""
    geq = 9.7803
    e2 = 6.694e-3
    k = 0.001932
    sinlat = jnp.sin(jnp.radians(latd))
    return geq * (1.0 + k * sinlat * sinlat) / jnp.sqrt(1.0 - e2 * sinlat * sinlat)


def qdrpos(latd1, lond1, qdr, dist):
    """Project position: start [deg], bearing [deg], distance [nm] -> lat2, lon2 [deg].

    Great-circle dead reckoning on the local WGS-84 sphere (reference
    geo.py:263-285).
    """
    R = rwgs84(latd1) / nm
    lat1 = jnp.radians(latd1)
    lon1 = jnp.radians(lond1)
    dr = dist / R
    qdrr = jnp.radians(qdr)
    lat2 = jnp.arcsin(jnp.sin(lat1) * jnp.cos(dr)
                      + jnp.cos(lat1) * jnp.sin(dr) * jnp.cos(qdrr))
    lon2 = lon1 + jnp.arctan2(jnp.sin(qdrr) * jnp.sin(dr) * jnp.cos(lat1),
                              jnp.cos(dr) - jnp.sin(lat1) * jnp.sin(lat2))
    return jnp.degrees(lat2), jnp.degrees(lon2)


def kwikdist(lata, lona, latb, lonb):
    """Fast flat-earth distance [nm] (reference geo.py:288-305)."""
    dlat = jnp.radians(latb - lata)
    dlon = jnp.radians(lonb - lona)
    cavelat = jnp.cos(jnp.radians(lata + latb) * 0.5)
    dangle = jnp.sqrt(dlat * dlat + dlon * dlon * cavelat * cavelat)
    return REARTH * dangle / nm


def kwikdist_matrix(lata, lona, latb, lonb):
    """All-pairs fast distance [nm]: row i = from a[i], col j = to b[j]."""
    return kwikdist(jnp.asarray(lata)[:, None], jnp.asarray(lona)[:, None],
                    jnp.asarray(latb)[None, :], jnp.asarray(lonb)[None, :])


def kwikdist_wrapped(lata, lona, latb, lonb, xp=jnp):
    """Flat-earth distance [nm] with the longitude difference wrapped to
    [-180, 180).

    Deliberate divergence from the reference ``kwikdist`` (geo.py:288-305),
    which returns nonsense across the antimeridian; the shared host-side
    consumers (navdb nearest-waypoint lookup, areafilter circles) use this
    with ``xp=np``.  ``kwikdist`` above stays reference-exact for kernel
    parity.
    """
    dlat = xp.radians(latb - lata)
    dlon = xp.radians(((lonb - lona) + 180.0) % 360.0 - 180.0)
    cavelat = xp.cos(xp.radians(lata + latb) * 0.5)
    dangle = xp.sqrt(dlat * dlat + dlon * dlon * cavelat * cavelat)
    return REARTH * dangle / nm


def kwikqdrdist(lata, lona, latb, lonb):
    """Fast flat-earth bearing [deg, 0..360) and distance [m]!

    NB: unlike kwikdist, the reference returns metres here (geo.py:330-344).
    """
    dlat = jnp.radians(latb - lata)
    dlon = jnp.radians(lonb - lona)
    cavelat = jnp.cos(jnp.radians(lata + latb) * 0.5)
    dangle = jnp.sqrt(dlat * dlat + dlon * dlon * cavelat * cavelat)
    dist = REARTH * dangle
    qdr = jnp.degrees(jnp.arctan2(dlon * cavelat, dlat)) % 360.0
    return qdr, dist


def kwikqdrdist_matrix(lata, lona, latb, lonb):
    """All-pairs fast bearing [deg] / distance [m]."""
    return kwikqdrdist(jnp.asarray(lata)[:, None], jnp.asarray(lona)[:, None],
                       jnp.asarray(latb)[None, :], jnp.asarray(lonb)[None, :])


def kwikpos(latd1, lond1, qdr, dist):
    """Fast flat-earth position projection, dist in [nm] (reference geo.py:365-382)."""
    dx = dist * jnp.sin(jnp.radians(qdr))
    dy = dist * jnp.cos(jnp.radians(qdr))
    dlat = dy / 60.0
    dlon = dx / jnp.maximum(0.01, 60.0 * jnp.cos(jnp.radians(latd1)))
    return latd1 + dlat, lond1 + dlon


# jitted entry points for direct use from host code; inside larger jitted
# steps call the plain functions so XLA fuses across op boundaries.
qdrdist_jit = jax.jit(qdrdist)
qdrpos_jit = jax.jit(qdrpos)
qdrdist_matrix_jit = jax.jit(qdrdist_matrix)
