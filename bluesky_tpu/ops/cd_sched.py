"""Sparse segment-scheduled CD&R: near-physics-floor pair enumeration.

The full-grid Pallas kernel (``ops/cd_pallas.py``) visits every
[block, block] tile of the N x N pair space and skips unreachable ones.
Round-3 profiling on the v5e showed that at N=100k continental this costs
~120 ms per CD interval: ~82 ms of pair math over 7.6e8 block-granular
pairs and ~38 ms of pure grid+DMA overhead across 38k grid programs,
while the *physics floor* — pairs within ``rpz + tlookahead*(gs_i+gs_j)``
of each other, the exact conservative bound of the reference C++
prefilter idea (``bluesky/traffic/asas/src_cpp/asas.hpp:24-27``) — is
only ~5.5e7 pairs.  This module restructures the schedule so both costs
approach their floors:

* **Stripe sort** (``stripe_sort_dest``): aircraft are ordered by
  latitude stripe (stripe height >= the reach radius), longitude within
  the stripe, and each stripe is padded to a block boundary.  Unlike the
  Morton curve, this guarantees the reachable columns of any row block
  form at most ONE contiguous run per lat-reachable stripe (the lon
  window in a lon-sorted stripe is an interval), i.e. ~3 runs instead of
  Morton's fragmented ~7-21.

* **Segment schedule** (``build_windows``): from the exact block
  reachability matrix (``cd_tiled.block_reachability`` — unchanged
  bound, so the skip stays exact), each row's reachable columns are
  covered by at most ``S_cap`` contiguous segments of at most ``Wmax``
  blocks.  Rows needing more (dense geometries where everyone reaches
  everyone — e.g. the regional benchmark circle) are OVERFLOW rows,
  covered exactly by the old full-grid kernel restricted to those rows
  (``cd_pallas.full_grid_pass``), and the row-disjoint outputs merged.

* **Segment kernel** (``_sched_kernel``): ONE grid program per ownship
  block (grid = (nb,), not (nb, nb/cpp)): the program loops over its
  prefetched (start, len) segments, each an ``pl.Element``-indexed
  contiguous [Wmax, 16, block] slab DMA — no per-tile grid step, no
  gathers.  Tile math is byte-identical to the other backends
  (``cd_pallas._tile_pairs`` traced into this kernel), so results match
  the dense oracle exactly like the tiled/pallas paths do.

Semantics: identical reductions to ``cd_tiled.detect_resolve_tiled`` —
the schedule only changes WHICH provably-conflict-free tiles are
skipped, never the computed pairs' math.
"""
import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import cd_pallas, cd_tiled
from .cd_pallas import _ACC_NEUTRAL, _FIELDS, _IDX, _init_accumulators
from .cd_tiled import RowConflictData, block_reachability, precompute_trig

#: slab rows padded 13 -> 16 so a dynamic leading-index of a
#: [Wmax, _NFP, block] VMEM ref lands on a whole-vreg boundary
#: (16*block is a multiple of the (8, 128) vreg for block >= 128)
_NFP = 16


def _element_spec(shape, imap):
    """Element-indexed BlockSpec across JAX generations: ``pl.Element``
    dims where available (>= 0.5), the whole-spec
    ``indexing_mode=pl.Unblocked()`` form otherwise (0.4.x) — both give
    the index map element (slab-row) granularity for the dynamic
    ``(start, len)`` window DMAs."""
    if hasattr(pl, "Element"):
        return pl.BlockSpec(tuple(pl.Element(s) for s in shape), imap,
                            memory_space=pltpu.VMEM)
    return pl.BlockSpec(shape, imap, memory_space=pltpu.VMEM,
                        indexing_mode=pl.Unblocked())

#: max grid rows per pallas_call — the TPU compiler dies without
#: diagnostics somewhere above ~1700 rows (see the row-split note in
#: detect_resolve_sched); 1408 rows = 360k aircraft stays well inside
#: the measured-good range.
_MAX_ROWS = 1408

#: above this many rows, skip the cross-equator kernel specialization
#: (one variant instead of two halves compile time; huge fleets
#: usually straddle the equator anyway)
_ONE_VARIANT_ROWS = 1024


def padded_size(n, block=256, extra=32):
    """Total slots of the padded stripe-sorted layout for n aircraft."""
    block = min(block, 256)
    return (-(-n // block) + extra) * block


def spatial_layout(n, block=256, ndev=1, extra=32):
    """Padded-layout parameters for the spatial domain-decomposition
    mode: pick the extra-block count (<= ``extra``, >= 2) so the padded
    block count divides evenly into ``ndev`` contiguous device stripes.
    Returns ``(extra_eff, nb, nb_local, n_tot)``.  Shrinking ``extra``
    only makes the latitude stripes taller (stripe height is
    ``max(reach, span/(extra-1))``), never incorrect — reachability is
    recomputed from true positions every interval."""
    block = min(block, 256)
    nb0 = -(-n // block)
    extra_eff = extra - ((nb0 + extra) % ndev)
    if extra_eff < 2:
        extra_eff += ndev
    nb = nb0 + extra_eff
    return extra_eff, nb, nb // ndev, nb * block


def slot_inverse(perm, n, n_tot, fill=-1):
    """[n_tot + 1] int32 lookup: padded-slot id -> caller index
    (``fill`` for empty slots).  ``perm`` is the ``stripe_sort_dest``
    destination table (caller i -> slot perm[i]); the +1 row makes
    clipped sentinel lookups safe.  Single source of truth for the
    sorted-space -> caller-space translation (partner-table remaps in
    core/asas)."""
    return jnp.full((n_tot + 1,), fill, jnp.int32).at[
        jnp.clip(perm, 0, n_tot)].set(jnp.arange(n, dtype=jnp.int32))


def partners_to_caller(perm, partners_s, n, n_tot):
    """Translate a sorted-space partner table ``partners_s``
    [n_tot, K] into a caller-space [n, K] table (-1 = empty), the
    composition the sparse SSD-resolve branch performs: partner slot
    ids map through ``slot_inverse`` and each caller row i reads the
    row of its own slot ``perm[i]``.  Shared by core/asas (resolver
    partner plumbing) and obs/scanstats (min-separation fold)."""
    inv = slot_inverse(perm, n, n_tot)
    pc = jnp.where(partners_s >= 0,
                   inv[jnp.clip(partners_s, 0, n_tot)], -1)
    return pc[jnp.clip(perm, 0, n_tot - 1), :]


def reach_threshold_m(gs, active, tlookahead, rpz):
    """Worst-case reach radius [m]: the exact conservative CD bound at
    fleet-max closing speed (used to size stripes; per-block thresholds
    in the reachability matrix stay per-block)."""
    gsmax = jnp.max(jnp.where(active, gs, 0.0))
    return rpz + tlookahead * 2.0 * gsmax


#: per-stripe altitude layering (cruise bands + one "climber" bucket
#: collecting |vs| > _CLIMB_VS aircraft so they cannot poison a cruise
#: block's vsmax in the vertical reachability bound).  Measured at
#: N=100k CONTINENTAL the layering INCREASES scheduled pairs (5.4e8 vs
#: 3.4e8: thinning the lat-lon buckets makes blocks longitude-fat and
#: the +block-span dilation outweighs the vertical selectivity) — but
#: in DENSE geometries (the reference's 230 nm circle) the horizontal
#: windows are saturated anyway, so altitude-homogeneous blocks let the
#: exact vertical term of block_reachability prune the tile set by the
#: cruise-band fraction.  The caller (core/asas.refresh_spatial_sort)
#: therefore passes ``n_layers > 0`` only when its density estimate
#: says horizontal windows can no longer discriminate.
_CLIMB_VS = 1.0     # [m/s]


def stripe_sort_dest(lat, lon, gs, active, thresh_m, block, extra,
                     alt=None, vs=None, n_layers=0, spread_pad=False):
    """See module docstring; ``n_layers`` may be an int, or "auto" to
    gate the per-stripe altitude layering ON DEVICE from the density
    estimate (no host sync — the tunnel costs ~80 ms per pull).

    ``spread_pad`` (the SPATIAL layout): distribute the layout's free
    padding blocks between stripes proportionally to cumulative active
    count instead of leaving them all at the end — the map from
    aircraft fraction to block position becomes ~affine, so a
    contiguous equal-block device split gets ~equal aircraft counts
    (without it, low-occupancy layouts put every occupied block at the
    front and the first devices overflow their caller shards).  The
    single-chip schedule is indifferent to WHERE padding sits (empty
    blocks are skipped exactly), so this only shapes device balance."""
    return _stripe_sort_dest_impl(lat, lon, gs, active, thresh_m, block,
                                  extra, alt, vs, n_layers,
                                  spread_pad=spread_pad)


def _auto_layers(lat, lon, alt, active, thresh_m):
    """Traced layering decision: mean reachable-neighbor count over the
    active bounding box; dense (>3000 — horizontal windows saturated,
    e.g. the 230 nm circle at 100k) -> ~500 m bands, else 0."""
    act = active
    big = jnp.asarray(1e9, lat.dtype)
    n_act = jnp.sum(act)
    lat_a = jnp.where(act, lat, jnp.nan)
    lon_a = jnp.where(act, lon, jnp.nan)
    alt_a = jnp.where(act, alt, jnp.nan)
    ptp = lambda a: jnp.nanmax(a) - jnp.nanmin(a)
    dlat_km = jnp.maximum(ptp(lat_a), 0.3) * 111.0
    coslat = jnp.maximum(jnp.cos(jnp.radians(
        jnp.nanmax(jnp.abs(lat_a)))), 0.05)
    dlon_km = jnp.maximum(ptp(lon_a), 0.3) * 111.0 * coslat
    reach_km = thresh_m / 1000.0
    nbrs = n_act * jnp.pi * reach_km ** 2 / (dlat_km * dlon_km)
    # ~500 m bands: above the cruise-block vertical reach (~340 m),
    # thin enough that own+-1-band coverage prunes hard (measured 2.3x
    # fewer scheduled pairs on the 230 nm circle at N=100k)
    l0 = jnp.clip(ptp(alt_a) / 500.0, 0, 16).astype(jnp.int32)
    use = (nbrs > 3000.0) & (l0 >= 2) & (n_act > 0)
    return jnp.where(use, l0, 0)


def _stripe_sort_dest_impl(lat, lon, gs, active, thresh_m, block, extra,
                           alt=None, vs=None, n_layers=0,
                           spread_pad=False):
    """Padded stripe-major sort: per-aircraft destination slots.

    Returns ``dest`` [n] int32: aircraft i occupies padded slot dest[i]
    in a layout of ``n + extra*block`` slots where each latitude stripe
    starts on a block boundary (so no row block straddles two stripes —
    straddle blocks have airspace-wide bounding boxes that blow up their
    column windows).  Stripe height is the larger of the reach radius
    and what caps the stripe count at ``extra - 1`` (so the padding
    always fits); inactive aircraft sort into the last stripe.

    With ``alt``/``vs``, aircraft are sub-ordered inside each stripe by
    altitude band (cruisers) with climbers/descenders in a separate
    bucket, then longitude — so blocks are homogeneous in altitude and
    the vertical term of ``block_reachability`` can skip whole
    flight-level bands.  Bucket boundaries are soft: they only shape
    block contents, never correctness (the reachability bound reads the
    true per-block ranges every interval).

    Like the Morton permutation this is refreshed only every
    ``sort_every`` CD intervals — ANY staleness is exact because block
    reachability is recomputed from true positions each interval;
    staleness only loosens the windows.
    """
    n = lat.shape[0]
    act = active
    big = jnp.asarray(1e9, lat.dtype)
    latmin = jnp.min(jnp.where(act, lat, big))
    latmax = jnp.max(jnp.where(act, lat, -big))
    any_act = jnp.any(act)
    latmin = jnp.where(any_act, latmin, 0.0)
    latmax = jnp.where(any_act, latmax, 1.0)
    span = jnp.maximum(latmax - latmin, 1e-6)
    # [m] -> [deg]: 1 deg of great-circle is >= 110 km everywhere, so
    # thresh/110000 over-estimates the needed stripe height -> safe.
    h = jnp.maximum(jnp.maximum(thresh_m * 1.05 / 110000.0,
                                span / (extra - 1)), 0.05)
    s = jnp.clip(jnp.floor((lat - latmin) / h), 0, extra - 2).astype(jnp.int32)
    s = jnp.where(act, s, extra - 1)

    if alt is None or (n_layers != "auto" and int(n_layers) == 0):
        nl = jnp.int32(0)
        layer = jnp.zeros((n,), jnp.int32)
    else:
        nl = _auto_layers(lat, lon, alt, active, thresh_m) \
            if n_layers == "auto" else jnp.int32(n_layers)
        amin = jnp.where(any_act, jnp.min(jnp.where(act, alt, big)), 0.0)
        amax = jnp.where(any_act, jnp.max(jnp.where(act, alt, -big)), 1.0)
        lh = jnp.maximum((amax - amin) / jnp.maximum(nl, 1), 1.0)
        layer = jnp.clip(jnp.floor((alt - amin) / lh), 0,
                         jnp.maximum(nl - 1, 0)).astype(jnp.int32)
        layer = jnp.where(jnp.abs(vs) > _CLIMB_VS, nl, layer)
        layer = jnp.where(nl > 0, layer, 0)

    qlon = jnp.clip((lon + 180.0) * (2 ** 19 / 360.0), 0, 2 ** 19 - 1)
    key = (s * (nl + 1) + layer) * (2 ** 19) + qlon.astype(jnp.int32)
    order = jnp.argsort(key)                       # sorted -> original
    ss = s[order]

    onehot = ss[:, None] == jnp.arange(extra, dtype=jnp.int32)[None, :]
    counts = jnp.sum(onehot, axis=0, dtype=jnp.int32)          # [extra]
    nblocks = -(-counts // block)
    base_b = jnp.concatenate([jnp.zeros((1,), jnp.int32),
                              jnp.cumsum(nblocks)[:-1]])
    if spread_pad:
        # Count-proportional dilution of the free padding blocks (see
        # the stripe_sort_dest docstring); the inactive stripe
        # (extra - 1) stays pinned at the very end of the layout.
        nb_tot = -(-n // block) + extra
        free = nb_tot - jnp.sum(nblocks)
        act_counts = counts.at[extra - 1].set(0)
        cc = jnp.concatenate([jnp.zeros((1,), jnp.int32),
                              jnp.cumsum(act_counts)[:-1]])
        n_act = jnp.maximum(jnp.sum(act_counts), 1)
        pad_before = (free * cc // n_act).astype(jnp.int32)
        pad_before = pad_before.at[extra - 1].set(free)
        base_b = base_b + pad_before
    base = base_b * block
    first = jnp.concatenate([jnp.zeros((1,), jnp.int32),
                             jnp.cumsum(counts)[:-1]])
    rank = jnp.arange(n, dtype=jnp.int32) - first[ss]
    dest_sorted = base[ss] + rank
    return jnp.zeros((n,), jnp.int32).at[order].set(dest_sorted)


def scatter_padded(arrs, dest, n_tot, neutral=0.0):
    """Place per-aircraft columns into the padded sorted layout.

    Unfilled slots get ``neutral`` (0 -> inactive for the mask columns).
    One shared index computation; each array costs one O(n) scatter.
    """
    return [jnp.full((n_tot,), neutral, a.dtype).at[dest].set(a)
            for a in arrs]


def build_windows(reach, s_cap, wmax, pad_start):
    """Cover each row's reachable columns with <= s_cap segments of
    <= wmax blocks.

    ``reach`` [nbr, nbc] bool (square [nb, nb] on the single-grid
    paths; rectangular when the rows are a subset of the columns, e.g.
    a device's own rows against its halo window in the
    domain-decomposition mesh mode).  Returns ``(start, ln, overflow)``:
    ``start``/``ln`` [nbr, s_cap] int32 (unused slots: start=pad_start,
    ln=0), ``overflow`` [nbr] bool marking rows whose reachable set
    needs more segments than s_cap — the caller covers those with the
    full-grid fallback.  Covering a SUPERSET of reachable columns is
    always exact (extra tiles just compute provably-empty pairs), so the
    segmentation never needs to be tight, only sufficient.
    """
    nb = reach.shape[1]
    col = jnp.arange(nb, dtype=jnp.int32)
    prev = jnp.pad(reach[:, :-1], ((0, 0), (1, 0)))
    nxt = jnp.pad(reach[:, 1:], ((0, 0), (0, 1)))
    starts = reach & ~prev
    # run start id per column (within its run), then split runs at wmax
    rs = jax.lax.cummax(jnp.where(starts, col, -1), axis=1)
    off = col - rs
    newseg = reach & (starts | (off % wmax == 0))
    # a segment ENDS at a run end or just before the next wmax split
    segend = reach & (~nxt | (off % wmax == wmax - 1))
    nseg = jnp.sum(newseg, axis=1)
    overflow = nseg > s_cap

    # Extract the s-th start/end per row with a searchsorted on the
    # running flag counts — O(nb log nb) and graph-size O(1), unlike the
    # former [nb, s_cap, nb] one-hot reduction whose window-build graph
    # broke the TPU compiler around nb ~ 4000 (N = 1M).
    want = jnp.arange(1, s_cap + 1, dtype=jnp.int32)
    find = jax.vmap(lambda cnt: jnp.searchsorted(cnt, want, side="left"))
    st = find(jnp.cumsum(newseg, axis=1)).astype(jnp.int32)    # [nb, S]
    en = find(jnp.cumsum(segend, axis=1)).astype(jnp.int32)
    valid = want[None, :] <= nseg[:, None]
    ln = jnp.where(valid, en - st + 1, 0)
    use = valid & ~overflow[:, None]
    st = jnp.where(use, st, pad_start).astype(jnp.int32)
    ln = jnp.where(use, ln, 0).astype(jnp.int32)
    return st, ln, overflow


def tile_offsets(tiles, hr=1, hc=1):
    """Canonical neighbour offsets of the R x C tile mesh.

    Offsets are ``(dr, dc)`` tile steps (edge AND corner neighbours of
    the ``(2*hr+1) x (2*hc+1)`` block minus self).  Longitude wraps
    (``dc`` mod C) and latitude does not, so offsets that alias under
    the wrap are DEDUPED to one canonical ``(dr, dc mod C)`` entry —
    e.g. a 4x2 mesh has 5 canonical offsets, not 8: (0,1) covers both
    east and west, and each diagonal pair collapses likewise.  One
    ppermute pair-set per canonical offset is the whole exchange."""
    R, C = int(tiles[0]), int(tiles[1])
    offs, seen = [], set()
    for dr in range(-hr, hr + 1):
        if abs(dr) >= R and dr != 0:
            continue                     # no (src, dst) pair exists
        for dc in range(-hc, hc + 1):
            key = (dr, dc % C)
            if key == (0, 0) or key in seen:
                continue                 # self (incl. wrap-to-self)
            seen.add(key)
            offs.append(key)
    return tuple(offs)


def _offset_pairs(tiles, off):
    """ppermute (src, dst) pairs for one canonical offset over the
    flattened row-major (lat, lon) device space.  Longitude wraps,
    latitude clips (edge tiles simply have no partner and receive the
    collective's zero fill = invalid columns)."""
    R, C = int(tiles[0]), int(tiles[1])
    dr, dcm = off
    return [(r * C + c, (r + dr) * C + (c + dcm) % C)
            for r in range(R) for c in range(C) if 0 <= r + dr < R]


def tile_wire_blocks(tiles, budgets=None, nb_t=0):
    """Worst-case RECEIVED halo blocks per device for the canonical
    offset set: sum of the per-offset budgets (or nb_t each when
    unpinned).  Diagnostic/bench helper — the actual per-interval
    wire is the reach-selected subset."""
    offs = tile_offsets(tiles)
    if budgets:
        return int(sum(min(int(b), nb_t) if nb_t else int(b)
                       for b in budgets))
    return int(len(offs) * nb_t)


def tile_sort_dest(lat, lon, gs, active, thresh_m, block, extra, tiles,
                   alt=None, vs=None):
    """Tile-major sort destinations for the 2-D lat x lon decomposition.

    Tile ``t = r*C + c`` owns the contiguous slot range
    ``[t*S_t, (t+1)*S_t)`` of the padded layout (``S_t = (nb/(R*C)) *
    block``) — the direct 2-D analogue of the stripe layout's
    device-contiguous ranges, so the spatial re-bucketing bijection and
    partner-table remap apply unchanged.  Assignment is
    count-proportional but GRANULARITY-LIMITED:

    * latitude: the geometric reach-height stripes of
      ``stripe_sort_dest`` are grouped into R bands by cumulative
      active count — a stripe never splits across bands;
    * longitude: fine fixed cells (0.35 deg) within each band are
      grouped into C chunks by cumulative count — a cell never splits.

    Equal-block tiles therefore hold ~equal aircraft on any smooth
    density, but one over-dense stripe/cell CAN overflow its tile —
    that is exactly what the refresh's tile-occupancy guard bit
    detects (refuse / fall back, never silently spill).  Within a tile
    aircraft pack contiguously ordered by (stripe, lon); the free
    padding sits at each tile's tail (empty blocks are skipped exactly
    by the reachability bound).  Inactive aircraft return the last
    slot — callers only ever use ACTIVE rows' destinations (inactive
    rows carry the sentinel via ``dest_sent``)."""
    R, C = int(tiles[0]), int(tiles[1])
    D = R * C
    n = lat.shape[0]
    nb = -(-n // block) + extra
    n_tot = nb * block
    S_t = (nb // D) * block
    act = active
    big = jnp.asarray(1e9, lat.dtype)
    any_act = jnp.any(act)
    latmin = jnp.where(any_act, jnp.min(jnp.where(act, lat, big)), 0.0)
    latmax = jnp.where(any_act, jnp.max(jnp.where(act, lat, -big)), 1.0)
    span = jnp.maximum(latmax - latmin, 1e-6)
    h = jnp.maximum(jnp.maximum(thresh_m * 1.05 / 110000.0,
                                span / (extra - 1)), 0.05)
    s = jnp.clip(jnp.floor((lat - latmin) / h), 0,
                 extra - 2).astype(jnp.int32)
    s = jnp.where(act, s, extra - 1)
    acti = act.astype(jnp.int32)

    # stripe -> band: count-proportional over whole stripes
    sc = jnp.zeros((extra,), jnp.int32).at[s].add(acti)
    csum = jnp.cumsum(sc) - sc
    n_act = jnp.maximum(jnp.sum(acti), 1)
    band_of = jnp.clip(((csum + sc // 2) * R) // n_act, 0, R - 1)
    band = band_of[s]

    # (band, cell) -> lon chunk: count-proportional over whole cells
    ncell = 1024
    cell = jnp.clip(((lon + 180.0) * (ncell / 360.0)).astype(jnp.int32),
                    0, ncell - 1)
    bc = jnp.zeros((R, ncell), jnp.int32).at[band, cell].add(acti)
    ccsum = jnp.cumsum(bc, axis=1) - bc
    btot = jnp.maximum(jnp.sum(bc, axis=1), 1)
    chunk_of = jnp.clip(((ccsum + bc // 2) * C) // btot[:, None],
                        0, C - 1)
    tile = band * C + chunk_of[band, cell]

    # pack actives contiguously per tile, ordered (stripe, lon) within
    qlon = jnp.clip((lon + 180.0) * (2 ** 19 / 360.0),
                    0, 2 ** 19 - 1).astype(jnp.int32)
    key = s * jnp.int32(2 ** 19) + qlon
    tile_a = jnp.where(act, tile, D)
    order1 = jnp.argsort(key)
    order = order1[jnp.argsort(tile_a[order1], stable=True)]
    ta_o = tile_a[order]
    start = jnp.searchsorted(ta_o, jnp.arange(D + 1, dtype=jnp.int32),
                             side="left").astype(jnp.int32)
    rank_o = jnp.arange(n, dtype=jnp.int32) - start[jnp.clip(ta_o, 0, D)]
    dest_o = jnp.where(ta_o < D,
                       jnp.clip(ta_o * S_t + rank_o, 0, n_tot - 1),
                       n_tot - 1)
    return jnp.zeros((n,), jnp.int32).at[order].set(dest_o)


def _tile_select(reach_any, budget, nb_t):
    """Budget-capped export selection: the (ascending) local block ids
    of the sender's blocks any receiver row can reach.  Returns
    ``(sidx [budget] clipped ids, valid [budget])`` — deterministic, so
    the mesh sender and the single-chip reference agree bit-for-bit."""
    selkey = jnp.where(reach_any, jnp.arange(nb_t, dtype=jnp.int32),
                       nb_t)
    sidx = jnp.sort(selkey)[:budget]
    valid = sidx < nb_t
    return jnp.clip(sidx, 0, nb_t - 1), valid


def _tile_windows(reach_rows, gkey, nb, s_cap_t, wmax):
    """Sort the present (own + received) column slabs by global block
    id and build this tile's segment windows over them — shared
    VERBATIM by the per-device tiles shard_map body and the single-chip
    tiles reference, so both visit IDENTICAL column sets (the tiles
    bit-parity contract).  Overflow rows get a synthetic full-present
    coverage (disjoint <= wmax segments over all present slabs) instead
    of the 1-D full-grid fallback: the superset visit is exact (extra
    tiles compute provably-empty pairs / invalid slabs are inactive),
    and because both paths take this same construction, even the
    resume-keep bits cannot diverge.

    ``gkey`` [ncols]: candidate columns' global block ids, invalid
    entries = ``nb``.  Returns ``(order, gid_tab, wl)``: the slab
    reorder, the per-slab global-id table (invalid = nb) and the
    bit-packed windows."""
    ncols = gkey.shape[0]
    order = jnp.argsort(gkey)                     # stable
    gid_tab = gkey[order]
    vcol = gid_tab < nb
    reach_h = reach_rows[:, jnp.clip(gid_tab, 0, nb - 1)] & vcol[None, :]
    st, ln, overflow = build_windows(reach_h, s_cap_t, wmax,
                                     pad_start=ncols)
    ist = jnp.arange(s_cap_t, dtype=jnp.int32) * wmax
    fln = jnp.clip(ncols - ist, 0, wmax)
    st = jnp.where(overflow[:, None], jnp.minimum(ist, ncols),
                   jnp.clip(st, 0, ncols))
    ln = jnp.where(overflow[:, None], fln, ln)
    return order, gid_tab, (st | (ln << 20)).astype(jnp.int32)


def _sched_kernel(wl_ref, *refs, block, kk, s_cap, wmax, rpz, hpz,
                  tlookahead, mvpcfg, same_hemi=False, rpz_m=None,
                  reso="mvp", rstride=1, gid_mode=False):
    resume = rpz_m is not None
    if gid_mode:
        # tiles mode: the column slabs are the tile's PRESENT set (own +
        # reach-selected halo imports) ranked by global block id, which
        # is NOT an affine window of the grid — a second scalar-prefetch
        # table maps local slab index -> global block id (SMEM scalar
        # reads, same budget class as the worklist itself).
        gid_ref, own_ref = refs[0], refs[1]
        rest = refs[2:]
    else:
        gid_ref, own_ref = None, refs[0]
        rest = refs[1:]
    intr_refs = rest[:s_cap]
    rest = rest[s_cap:]
    if resume:
        pold_ref = rest[0]
        out_refs = rest[1:11]
        keep_ref, pnew_ref, pact_ref = rest[11:14]
        rest = rest[14:]
    else:
        pold_ref = keep_ref = pnew_ref = pact_ref = None
        out_refs = rest[:10]
        rest = rest[10:]
    swarm_refs = rest if reso == "swarm" else None
    i = pl.program_id(0)
    _init_accumulators(out_refs, block, kk)
    if resume:
        keep_ref[0] = jnp.zeros((kk, block), jnp.float32)
    if swarm_refs:
        for ref in swarm_refs:
            ref[0] = jnp.zeros((1, block), jnp.float32)

    oslab = own_ref[0]                                     # (_NFP, block)

    def own(k):
        return oslab[_IDX[k]:_IDX[k] + 1, :]

    # wl's trailing columns carry the global row-block base and the
    # global id of the column slab array's block 0: local row i is
    # GLOBAL row row0 + i*rstride (0/1 except under shard_map, where
    # each device owns a row subset but column and partner ids stay
    # global), and local column block j is GLOBAL block col0 + j.
    # col0 != 0 only in the spatial domain-decomposition mode, where the
    # column slabs are the device's local halo window of the global
    # grid instead of the full replicated slab array — DMA/window
    # indices stay halo-local, pair ids lift back to the global slot
    # space (the cd_pallas col0 contract, tests/test_cd_pallas_col0.py).
    row0 = wl_ref[i, s_cap]
    col0 = wl_ref[i, s_cap + 1]
    gid_own = (row0 + i * rstride) * block + jax.lax.broadcasted_iota(
        jnp.int32, (1, block), 1)
    act_o = own("active") > 0.5

    # Whole-row skip: a row block of padding/inactive slots has no work
    # in any segment.
    @pl.when(jnp.any(act_o))
    def _row():
        for s in range(s_cap):
            # (start, len) are bit-packed into one scalar-prefetch array
            # (start low 20 bits, len high 12): the scalar-prefetch SMEM
            # budget overflows with two [nb, s_cap] int32 tables around
            # nb ~ 1600 (the TPU compiler crashes ungracefully there).
            w = wl_ref[i, s]
            base = w & 0xFFFFF
            ln = w >> 20
            slab_ref = intr_refs[s]

            def body(k, _, base=base, slab_ref=slab_ref):
                islab_t = slab_ref[k].T                    # (block, _NFP)
                # (a pre-transposed slab layout was measured SLOWER:
                # per-field column reads of a (block, _NFP) VMEM slab
                # stride across lanes; one .T per tile wins)

                def intr(f):
                    return islab_t[:, _IDX[f]:_IDX[f] + 1]

                if gid_mode:
                    jb = gid_ref[base + k]                 # GLOBAL block id
                else:
                    jb = col0 + base + k                   # GLOBAL block id
                gid_int = jb * block + jax.lax.broadcasted_iota(
                    jnp.int32, (block, 1), 0)
                act_i = intr("active") > 0.5
                pairmask = (act_o & act_i) & (gid_own != gid_int)

                @pl.when(jnp.any(pairmask))
                def _tile():
                    cd_pallas._tile_pairs(
                        pairmask, gid_int, own, intr, *out_refs,
                        kk=kk, rpz=rpz, hpz=hpz, tlookahead=tlookahead,
                        mvpcfg=mvpcfg, same_hemi=same_hemi, jb=jb,
                        resume_refs=(pold_ref, keep_ref) if resume
                        else None, rpz_m=rpz_m, reso=reso,
                        swarm_refs=swarm_refs)
                return 0

            jax.lax.fori_loop(0, jnp.minimum(ln, wmax), body, 0)

    if resume:
        # ctin/cidx refs hold the finished per-ownship candidates after
        # the segment loops; fold in the surviving old partners.
        cd_pallas._merge_partners_block(
            pold_ref, keep_ref, out_refs[8], out_refs[9],
            pnew_ref, pact_ref, kk)


def detect_resolve_sched(lat, lon, trk, gs, alt, vs, gseast, gsnorth,
                         active, noreso, rpz, hpz, tlookahead, mvpcfg,
                         block=256, k_partners=8, s_cap=6, wmax=16,
                         extra_blocks=32, interpret=None, perm=None,
                         cols_per_prog=4, partners=None, resume_rpz_m=None,
                         tas=None, cas=None, reso="mvp", mesh=None,
                         mesh_axis="ac", shard_mode="replicate",
                         halo_blocks=0, tile_shape=None, tile_budgets=()):
    """Sparse-scheduled equivalent of ``cd_pallas.detect_resolve_pallas``.

    ``perm`` is the cached ``stripe_sort_dest`` destination table (NOT a
    Morton permutation); recomputed when None.  Results match the other
    backends' reductions (same tile math, superset tile coverage).

    With ``mesh``, the segment kernel and its overflow fallback run
    under ``shard_map``: each device owns an interleaved subset of row
    blocks (its own worklist, partner-table rows, and Pallas program),
    the packed column slabs replicate over the mesh, and row ids carry a
    global offset — so results are bit-identical to the single-device
    schedule (asserted bit-for-bit in tests/test_sharding.py, and across
    a real 2-process jax.distributed boundary in tests/test_multihost.py).
    Communication structure per interval, verified on the compiled HLO
    (tests/test_hlo_collectives.py): GSPMD all-gathers the RAW O(N)
    per-aircraft columns (~90 B/aircraft total over ICI) and every
    device recomputes the padded layout/trig/reachability/windows
    locally — cheaper than shipping the [nb, 16, block] slab — plus one
    O(N*K) all-reduce for the partner back-permute; no all-to-alls, no
    per-tile collectives.  The pair math — the dominant cost — scales
    ~linearly with devices.

    With ``shard_mode='spatial'`` (and a real mesh) the decomposition
    changes from row-interleave-vs-replicated-columns to device-OWNED
    latitude stripes: each device holds the caller shard of exactly the
    aircraft whose sorted stripe slots it owns (the spatial refresh's
    re-bucketing invariant, core/asas.refresh_spatial_shard), builds
    its padded columns/trig/windows locally over its own O(N/D) rows,
    and the per-interval communication is ONLY the halo boundary-slab
    collective-permutes + one O(N/block) summary all-gather + scalar
    psums — zero O(N) column all-gathers (asserted on the HLO in
    tests/test_hlo_collectives.py).  ``halo_blocks`` sets the window
    half-width (0 = one full neighbour device; the exchange hops
    several neighbours when stripes are narrower than the reach).
    Results are bit-identical to the same call without a mesh — the
    single-chip reference on the identical stripe-bucketed layout
    (tests/test_spatial.py).  Without a mesh, ``shard_mode='spatial'``
    only switches the back-map to its sentinel-masked form (inactive
    rows carry the sentinel slot in spatial layouts).

    With ``shard_mode='tiles'`` the decomposition generalises to 2-D
    lat x lon tiles on a ``('lat', 'lon')`` device mesh of shape
    ``tile_shape = (R, C)``: device (r, c) owns tile ``t = r*C + c``'s
    contiguous block range of the tile-major layout
    (``tile_sort_dest``), and the per-interval exchange ships only the
    reach-SELECTED boundary slabs to the edge+corner neighbours — one
    ``ppermute`` pair per canonical offset (``tile_offsets``; wrapped
    lon offsets dedupe) with a per-offset block budget
    (``tile_budgets``, pinned by the tile refresh at 1.25x measured
    need), plus the same O(N/block) summary all-gather and scalar
    psums as the stripe mode.  The halo wire therefore scales with
    tile PERIMETER instead of stripe width.  Each device's kernel runs
    over its PRESENT columns (own + imports, ranked by global block
    id) with a scalar-prefetch gid table lifting pair/partner ids back
    to global slots; window construction (incl. the synthetic
    full-present coverage for overflow rows) is shared verbatim with
    the single-chip ``shard_mode='tiles'`` reference, which makes the
    mesh results bit-identical to it by construction
    (tests/test_spatial.py).  The refresh contract
    (core/asas.refresh_tile_shard) guarantees reachability never
    escapes the canonical neighbourhood or the budgets until the next
    refresh — violations refuse / fall back to replicate, never
    silently miss conflicts.

    With ``partners`` ([n_tot, K] int32, SORTED-space ids, -1 empty) the
    kernels also run in-kernel resume-nav (keep evaluation on every
    visited partner pair + the candidate/old merge — reference
    asas.py:409-471 without any [N,K] host gathers), and the return
    value becomes ``(rd, partners_new, active)`` where ``partners_new``
    [n_tot, K] stays in sorted space (the caller keeps the table there
    between intervals; ``rd.topk_*`` are then also sorted-space and
    mainly diagnostic) and ``active`` [n] is the caller-space ASAS
    engagement flag.
    ``resume_rpz_m`` is the margin-scaled resume radius (rpz*resofach).
    """
    n = lat.shape[0]
    dtype = jnp.float32
    block = min(block, 256)
    interpret = cd_pallas.interpret_default(interpret)
    if partners is None and n <= 2 * block:
        # Too small to schedule — the plain kernel is already one tile.
        extra = None
        if tas is not None:
            extra = {"tas": tas}
        if reso == "swarm":
            extra = {"cas": gs if cas is None else cas}
        return cd_pallas.detect_resolve_pallas(
            lat, lon, trk, gs, alt, vs, gseast, gsnorth, active, noreso,
            rpz, hpz, tlookahead, mvpcfg, block=block,
            k_partners=k_partners, interpret=interpret, reso=reso,
            extra_cols=extra)
    resume = partners is not None

    thresh = reach_threshold_m(gs.astype(dtype), active,
                               float(tlookahead), float(rpz))
    if perm is None:
        if shard_mode == "tiles" and tile_shape:
            perm = tile_sort_dest(lat.astype(dtype), lon.astype(dtype),
                                  gs.astype(dtype), active, thresh,
                                  block, extra_blocks,
                                  tuple(tile_shape),
                                  alt=alt.astype(dtype),
                                  vs=vs.astype(dtype))
        else:
            perm = stripe_sort_dest(lat.astype(dtype),
                                    lon.astype(dtype),
                                    gs.astype(dtype), active, thresh,
                                    block, extra_blocks,
                                    alt=alt.astype(dtype),
                                    vs=vs.astype(dtype))
    nb = -(-n // block) + extra_blocks
    n_tot = nb * block

    cols = {
        "lat": lat, "lon": lon, "trk": trk, "gs": gs, "alt": alt,
        "vs": vs, "gse": gseast, "gsn": gsnorth,
        # tas/gs ratio: Eby's velocity basis (ve = tr*u); 1.0 when no
        # tas given (MVP never reads it).  Swarm overloads the slot
        # with cas (see cd_pallas._FIELDS note).
        "tr": ((gs if cas is None else cas).astype(dtype)
               if reso == "swarm"
               else jnp.ones_like(gs.astype(dtype)) if tas is None
               else tas.astype(dtype)
               / jnp.maximum(gs.astype(dtype), 0.5)),
        "active": active.astype(dtype), "noreso": noreso.astype(dtype),
    }
    if reso == "swarm":
        from . import cr_swarm
        min_reach, min_vreach = cr_swarm.R_SWARM, cr_swarm.DH_SWARM
    else:
        min_reach = min_vreach = 0.0
    if nb >= 2 ** 20 or wmax >= 2 ** 11:
        raise ValueError(
            f"worklist bit-pack overflow: nb={nb} must be < 2^20 and "
            f"wmax={wmax} < 2^11 (start|len share one int32; a silent "
            "overflow would drop conflict windows)")

    ndev_sp = mesh.shape[mesh_axis] if (
        shard_mode == "spatial" and mesh is not None
        and mesh_axis in mesh.shape) else 0
    spatial = ndev_sp > 1
    if shard_mode == "spatial" and not resume:
        raise ValueError(
            "spatial shard mode requires the resume/partner-table path "
            "(the production sparse backend always passes `partners`)")
    if spatial and nb % ndev_sp != 0:
        raise ValueError(
            f"spatial shard mode: padded block count nb={nb} must divide "
            f"into {ndev_sp} devices — build the layout with "
            f"cd_sched.spatial_layout (extra_blocks={extra_blocks})")
    if spatial and n % ndev_sp != 0:
        raise ValueError(
            f"spatial shard mode: nmax={n} must be divisible by the "
            f"{ndev_sp}-device mesh")

    tiles_on = shard_mode == "tiles"
    mesh_tiles = False
    if tiles_on:
        if not tile_shape or len(tuple(tile_shape)) != 2:
            raise ValueError(
                "tiles shard mode needs tile_shape=(R, C) — set "
                "SimConfig.cd_tile_shape / SHARD TILE RxC")
        tR, tC = int(tile_shape[0]), int(tile_shape[1])
        tD = tR * tC
        if not resume:
            raise ValueError(
                "tiles shard mode requires the resume/partner-table "
                "path (the production sparse backend always passes "
                "`partners`)")
        if nb % tD:
            raise ValueError(
                f"tiles shard mode: padded block count nb={nb} must "
                f"divide into {tR}x{tC}={tD} tiles — build the layout "
                f"with cd_sched.spatial_layout (extra_blocks="
                f"{extra_blocks})")
        mshape = dict(mesh.shape) if mesh is not None else {}
        mesh_tiles = tD > 1 and mshape.get("lat") == tR \
            and mshape.get("lon") == tC
        if mesh is not None and not mesh_tiles and tD > 1:
            raise ValueError(
                f"tiles shard mode needs a ('lat', 'lon') mesh of "
                f"shape {tR}x{tC}; got axes {mshape} — build it with "
                "parallel.sharding.make_tile_mesh")
        if mesh_tiles and n % tD:
            raise ValueError(
                f"tiles shard mode: nmax={n} must be divisible by the "
                f"{tD}-device tile mesh")
        offs = tile_offsets((tR, tC))
        nb_t = nb // tD
        if tile_budgets:
            if len(tile_budgets) != len(offs):
                raise ValueError(
                    f"tile_budgets must carry one entry per canonical "
                    f"offset ({len(offs)} for {tR}x{tC}); got "
                    f"{len(tile_budgets)}")
            budgets = tuple(max(1, min(int(b), nb_t))
                            for b in tile_budgets)
        else:
            budgets = tuple(nb_t for _ in offs)
        ncols_t = nb_t + sum(budgets)
        s_cap_t = max(s_cap, -(-ncols_t // wmax))

    def make_fields(padded_cols):
        """Per-slot trig/velocity columns of the padded layout — shared
        verbatim by the single-chip prep and the per-device spatial
        shard so the two can never drift (bit-parity contract)."""
        flds = precompute_trig(padded_cols["lat"], padded_cols["lon"])
        trkrad = jnp.radians(padded_cols["trk"])
        flds.update({
            "u": padded_cols["gs"] * jnp.sin(trkrad),
            "v": padded_cols["gs"] * jnp.cos(trkrad),
            "alt": padded_cols["alt"], "vs": padded_cols["vs"],
            "gse": padded_cols["gse"], "gsn": padded_cols["gsn"],
            "tr": padded_cols["tr"],
            "active": padded_cols["active"],
            "noreso": padded_cols["noreso"],
        })
        flds["trk"] = padded_cols["trk"]
        return flds

    kk = k_partners
    pold = None
    if resume:
        pold = partners.reshape(nb, block, kk).transpose(0, 2, 1) \
            .astype(jnp.int32)                             # [nb, kk, block]
    neutral_vals = _ACC_NEUTRAL + ((0.0, -1, 0.0) if resume else ()) \
        + ((0.0,) * cd_pallas._N_SWARM if reso == "swarm" else ())
    #: per-BACKED-row neutral values for caller rows whose sort slot is
    #: the sentinel (inactive rows in spatial mode): exactly the
    #: accumulator identities a never-touched slot holds, so masked
    #: gathers and real gathers of empty slots cannot differ.
    backed_neutral = [0.0, 0.0, 0.0, 0.0, 0.0, cd_pallas._BIG]
    if resume:
        backed_neutral.append(0.0)                         # active flag
    if reso == "swarm":
        backed_neutral.extend([0.0] * cd_pallas._N_SWARM)

    if not spatial and not mesh_tiles:
        padded = dict(zip(cols, scatter_padded(
            [v.astype(dtype) for v in cols.values()], perm, n_tot)))
        fields = make_fields(padded)
        packed = jnp.stack([fields[k] for k in _FIELDS]).reshape(
            len(_FIELDS), nb, block).transpose(1, 0, 2)    # [nb, _NF, block]

        act_b = padded["active"] > 0.5
        reach = block_reachability(
            padded["lat"], padded["lon"], padded["gs"], act_b, nb, block,
            float(rpz), float(tlookahead), alt=padded["alt"],
            vs=padded["vs"], hpz=float(hpz), min_reach_m=min_reach,
            min_vreach_m=min_vreach)

        if not tiles_on:
            # Segment windows + the Wmax-block pad region the sentinel
            # slots point at (slots are clamped so every DMA stays in
            # bounds); start and len ride one bit-packed scalar-prefetch
            # array (SMEM budget, see _sched_kernel).  Tiles mode builds
            # its windows PER TILE over the present sets instead
            # (_tile_windows, below).
            st, ln, overflow = build_windows(reach, s_cap, wmax,
                                             pad_start=nb)
            st = jnp.clip(st, 0, nb)
            wl = st | (ln << 20)
            reach_f = reach & overflow[:, None]
        packed16 = jnp.concatenate([
            jnp.concatenate(                       # len(_FIELDS) -> _NFP
                [packed,                           # (zero-width at 16)
                 jnp.zeros((nb, _NFP - len(_FIELDS), block), dtype)],
                axis=1),
            jnp.zeros((wmax, _NFP, block), dtype)], axis=0)  # DMA pad

    def run_rows(wl_r, own16_r, packedown_r, pold_r, reachf_r, overflow_r,
                 row0, same_hemi, intr16, intr, rstride=1, col0=0,
                 gid_tab=None, fallback=True, s_cap_r=None):
        """Sched kernel + overflow fallback over one row subset.

        ``wl_r`` [rows, s_cap+2] carries (start|len) plus the global
        row-block base and the columns' global block-0 id in its last
        two columns (local row i = global row row0 + i*rstride, local
        column block j = global block col0 + j); ``own16_r``/
        ``packedown_r`` are the subset's ownship slabs; ``intr16``/
        ``intr`` are the column slab arrays — the FULL grid (col0 == 0)
        on the single-chip and column-replicated paths, the device's
        local halo window in the spatial mode.

        ``gid_tab`` (tiles mode) replaces the affine col0 lift with a
        per-slab global-block-id table riding a SECOND scalar-prefetch
        array (column slabs are the present set ranked by gid, not a
        contiguous window); ``fallback=False`` skips the full-grid
        overflow cond entirely (tiles overflow rows already carry the
        synthetic full-present windows, see _tile_windows);
        ``s_cap_r`` overrides the segment cap (tiles rows straddle up
        to 9 neighbour tiles, so their run count exceeds the 1-D
        default)."""
        rows = wl_r.shape[0]
        sc = s_cap if s_cap_r is None else s_cap_r
        gidm = gid_tab is not None
        imap_i = lambda i, *pf: (i, 0, 0)

        def imap_w(s):
            return lambda i, wl, *pf: (wl[i, s] & 0xFFFFF, 0, 0)

        own_spec = pl.BlockSpec((1, _NFP, block), imap_i,
                                memory_space=pltpu.VMEM)
        intr_specs = [_element_spec((wmax, _NFP, block), imap_w(s))
                      for s in range(sc)]
        acc_spec = lambda: pl.BlockSpec((1, 1, block), imap_i,
                                        memory_space=pltpu.VMEM)
        cand_spec = lambda: pl.BlockSpec((1, kk, block), imap_i,
                                         memory_space=pltpu.VMEM)
        out_shape = [jax.ShapeDtypeStruct((rows, 1, block), dtype)] * 8 + [
            jax.ShapeDtypeStruct((rows, kk, block), dtype),
            jax.ShapeDtypeStruct((rows, kk, block), jnp.int32)]
        if resume:
            out_shape = out_shape + [
                jax.ShapeDtypeStruct((rows, kk, block), dtype),     # keep
                jax.ShapeDtypeStruct((rows, kk, block), jnp.int32),  # merged
                jax.ShapeDtypeStruct((rows, 1, block), dtype)]      # active
        if reso == "swarm":
            out_shape = out_shape + [
                jax.ShapeDtypeStruct((rows, 1, block), dtype)
            ] * cd_pallas._N_SWARM
        kern = functools.partial(
            _sched_kernel, block=block, kk=kk, s_cap=sc, wmax=wmax,
            rpz=float(rpz), hpz=float(hpz), tlookahead=float(tlookahead),
            mvpcfg=mvpcfg, same_hemi=same_hemi, rstride=rstride,
            rpz_m=float(resume_rpz_m) if resume else None, reso=reso,
            gid_mode=gidm)
        in_specs = [own_spec] + [intr_specs[s] for s in range(sc)]
        out_specs = [acc_spec() for _ in range(8)] \
            + [cand_spec(), cand_spec()]
        args = [wl_r] + ([gid_tab] if gidm else []) \
            + [own16_r] + [intr16] * sc
        if resume:
            in_specs.append(cand_spec())               # pold
            args.append(pold_r)
            out_specs += [cand_spec(), cand_spec(), acc_spec()]
        if reso == "swarm":
            out_specs += [acc_spec() for _ in range(cd_pallas._N_SWARM)]
        outs_s = list(pl.pallas_call(
            kern,
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=2 if gidm else 1,
                grid=(rows,),
                in_specs=in_specs,
                out_specs=out_specs,
            ),
            out_shape=out_shape,
            interpret=interpret,
        )(*args))
        if not fallback:
            return tuple(outs_s)

        # Overflow rows (dense geometries): exact full-grid fallback on
        # the row-restricted reachability, merged row-disjointly.
        kern_kw = dict(block=block, kk=kk, rpz=float(rpz), hpz=float(hpz),
                       tlookahead=float(tlookahead), mvpcfg=mvpcfg,
                       same_hemi=same_hemi, reso=reso)

        def fallback(rf):
            return cd_pallas.full_grid_pass(
                intr, rf, block=block, kk=kk, cpp=cols_per_prog,
                kern_kw=kern_kw, interpret=interpret, pold=pold_r,
                rpz_m=resume_rpz_m, packed_own=packedown_r, row0=row0,
                rstride=rstride, col0=col0)

        def neutral(_):
            return [jnp.full(o.shape, v, o.dtype)
                    for o, v in zip(outs_s, neutral_vals)]

        outs_f = jax.lax.cond(jnp.any(overflow_r), fallback, neutral,
                              reachf_r)
        rsel = overflow_r[:, None, None]
        return tuple(jnp.where(rsel, f, s) for f, s in zip(outs_f, outs_s))

    row0_col = lambda w, r0, c0=0: jnp.concatenate(
        [w,
         jnp.full((w.shape[0], 1), r0, jnp.int32),
         jnp.full((w.shape[0], 1), c0, jnp.int32)], axis=1)

    if spatial:
        # ------------------------------------------------------------
        # Spatial domain decomposition: device d OWNS the contiguous
        # latitude-stripe block range [d*nb_l, (d+1)*nb_l) of the
        # sorted layout — O(N/D) scatter, trig, reachability, window
        # build and kernel rows per device — and exchanges only the
        # `halo`-block boundary stripes with its lat-neighbours over
        # ICI (collective-permute), plus one O(N/block) all-gather of
        # the per-block summary vectors the exact reachability bound
        # reads.  No O(N) per-aircraft column is ever gathered
        # (asserted mechanically in tests/test_hlo_collectives.py).
        # The caller guarantees (and the spatial refresh verifies with
        # a drift margin, core/asas.refresh_spatial_shard) that the
        # halo window covers every reachable column until the next
        # refresh, and that each aircraft's caller slot lives on the
        # device owning its sorted slot — which makes the per-interval
        # scatter and result back-map DEVICE-LOCAL.
        # ------------------------------------------------------------
        from jax.sharding import PartitionSpec as P
        ndev = ndev_sp
        nb_l = nb // ndev
        S_l = nb_l * block
        halo = int(halo_blocks) if halo_blocks else nb_l
        # the halo may span several neighbour devices (narrow stripes
        # at large D): the exchange below hops ceil(halo/nb_l) devices
        # per side, wire still ~2*halo blocks per device
        halo = min(halo, (ndev - 1) * nb_l)
        n_hops = -(-halo // nb_l)
        nbh = nb_l + 2 * halo
        cols_f = {k: v.astype(dtype) for k, v in cols.items()}

        def body(cols_l, perm_l, pold_l):
            d = jax.lax.axis_index(mesh_axis)
            base = d * jnp.int32(S_l)
            in_dev = (perm_l >= base) & (perm_l < base + S_l)
            # sentinel (inactive) and off-device slots drop out of the
            # scatter; the spatial refresh guarantees the latter set is
            # empty, so dropping is exact, never lossy
            dest_loc = jnp.where(in_dev, perm_l - base, S_l)
            padded_l = {
                k: jnp.zeros((S_l,), dtype).at[dest_loc].set(
                    v, mode="drop")
                for k, v in cols_l.items()}
            fields_l = make_fields(padded_l)
            packed_l = jnp.stack(
                [fields_l[k] for k in _FIELDS]).reshape(
                    len(_FIELDS), nb_l, block).transpose(1, 0, 2)
            act_l = padded_l["active"] > 0.5

            # Exact reachability of OWN rows vs the whole grid from the
            # gathered per-block summaries (identical per-block math to
            # the single-chip block_reachability — bit-parity contract)
            summ_l = cd_tiled.block_summaries(
                padded_l["lat"], padded_l["lon"], padded_l["gs"], act_l,
                nb_l, block, alt=padded_l["alt"], vs=padded_l["vs"])
            summ_g = {k: jax.lax.all_gather(v, mesh_axis, tiled=True)
                      for k, v in summ_l.items()}
            reach_rows = cd_tiled.reachability_from_summaries(
                summ_l, summ_g, float(rpz), float(tlookahead),
                hpz=float(hpz), min_reach_m=min_reach,
                min_vreach_m=min_vreach)                   # [nb_l, nb]

            # Restrict to the halo window; out-of-grid columns (mesh
            # edges) are masked, never visited
            cg = base // block - halo + jnp.arange(nbh, dtype=jnp.int32)
            vcol = (cg >= 0) & (cg < nb)
            reach_h = reach_rows[:, jnp.clip(cg, 0, nb - 1)] \
                & vcol[None, :]
            st_l, ln_l, overflow_l = build_windows(
                reach_h, s_cap, wmax, pad_start=nbh)
            wl_l = jnp.clip(st_l, 0, nbh) | (ln_l << 20)

            # Halo exchange: ship only the boundary slabs to the
            # lat-neighbours, hopping as many devices as the halo spans
            # (h-th hop carries the h-th-nearest neighbour's share;
            # edge devices receive zeros = inactive, and their
            # out-of-grid columns are reach-masked anyway).  Wire per
            # device ~ 2 * halo * _NF * block * 4 B regardless of hops.
            parts_lo, parts_hi = [], []
            for h in range(1, n_hops + 1):
                take = halo - (h - 1) * nb_l if h == n_hops else nb_l
                lo_h = jax.lax.ppermute(
                    packed_l[nb_l - take:], mesh_axis,
                    [(i, i + h) for i in range(ndev - h)])
                hi_h = jax.lax.ppermute(
                    packed_l[:take], mesh_axis,
                    [(i, i - h) for i in range(h, ndev)])
                # ascending global order: farthest-left part first
                parts_lo.insert(0, lo_h)
                parts_hi.append(hi_h)
            halo13 = jnp.concatenate(
                parts_lo + [packed_l] + parts_hi, axis=0)
            halo16 = jnp.concatenate([
                jnp.concatenate(
                    [halo13, jnp.zeros(
                        (nbh, _NFP - len(_FIELDS), block), dtype)],
                    axis=1),
                jnp.zeros((wmax, _NFP, block), dtype)], axis=0)
            own16 = halo16[halo:halo + nb_l]

            row0 = base // block
            col0 = row0 - halo
            outs_l = run_rows(
                row0_col(wl_l, row0, col0), own16, packed_l, pold_l,
                reach_h & overflow_l[:, None], overflow_l, row0, False,
                halo16, halo13, rstride=1, col0=col0)

            # Back-map to THIS device's caller shard (device-local
            # gather; sentinel rows read the accumulator identities)
            (inconf_l, tcpamax_l, sdve_l, sdvn_l, sdvv_l, tsolv_l,
             ncnt_l, lcnt_l, ctin_l, cidx_l) = outs_l[:10]
            rows_l = [inconf_l, tcpamax_l, sdve_l, sdvn_l, sdvv_l,
                      tsolv_l, outs_l[12]]                 # + active
            if reso == "swarm":
                rows_l.extend(outs_l[13:13 + cd_pallas._N_SWARM])
            stacked_l = jnp.stack([o.reshape(S_l) for o in rows_l])
            gsl = jnp.clip(dest_loc, 0, S_l - 1)
            backed_l = jnp.where(
                in_dev[None, :], stacked_l[:, gsl],
                jnp.asarray(backed_neutral, dtype)[:, None])
            tt_l = ctin_l.transpose(0, 2, 1).reshape(S_l, kk)[gsl]
            ti_l = cidx_l.transpose(0, 2, 1).reshape(S_l, kk)[gsl]
            tt_l = jnp.where(in_dev[:, None], tt_l, cd_pallas._BIG)
            ti_l = jnp.where(in_dev[:, None], ti_l, jnp.int32(2 ** 30))
            nconf_l = jax.lax.psum(
                jnp.sum(ncnt_l.astype(jnp.int32), dtype=jnp.int32),
                mesh_axis)
            nlos_l = jax.lax.psum(
                jnp.sum(lcnt_l.astype(jnp.int32), dtype=jnp.int32),
                mesh_axis)
            return backed_l, tt_l, ti_l, outs_l[11], nconf_l, nlos_l

        col_specs = {k: P(mesh_axis) for k in cols_f}
        backed, topk_tin, ti_raw, pmerged, nconf, nlos = \
            cd_pallas.shard_map_compat(
                body, mesh,
                (col_specs, P(mesh_axis), P(mesh_axis)),
                (P(None, mesh_axis), P(mesh_axis), P(mesh_axis),
                 P(mesh_axis), P(), P()))(cols_f, perm, pold)

        topk_idx = jnp.where(
            (topk_tin < cd_pallas._BIG) & (ti_raw < n_tot), ti_raw, -1)
        rd = RowConflictData(
            inconf=backed[0] > 0.5,
            tcpamax=backed[1],
            sum_dve=backed[2], sum_dvn=backed[3], sum_dvv=backed[4],
            tsolv=backed[5],
            nconf=nconf, nlos=nlos,
            topk_idx=topk_idx, topk_tin=topk_tin)
        partners_new = pmerged.transpose(0, 2, 1).reshape(n_tot, kk)
        active_caller = backed[6] > 0.5
        if reso == "swarm":
            return rd, partners_new, active_caller, \
                tuple(backed[7:7 + cd_pallas._N_SWARM])
        return rd, partners_new, active_caller

    if mesh_tiles:
        # ------------------------------------------------------------
        # 2-D tile decomposition: device (r, c) OWNS tile t = r*C + c's
        # contiguous block range of the tile-major layout — O(N/D)
        # scatter/trig/reachability/windows/kernel rows per device —
        # and exchanges only the reach-SELECTED boundary slabs with its
        # edge+corner neighbours: ONE ppermute pair per canonical
        # offset (wrapped lon offsets deduped), each budget-capped, so
        # the halo wire scales with tile PERIMETER instead of stripe
        # width.  The summary all-gather/psum structure matches the
        # stripe mode (O(N/block) metadata, zero O(N) column
        # collectives — asserted in tests/test_hlo_collectives.py).
        # The tile refresh (core/asas.refresh_tile_shard) guarantees
        # margin-widened reachability stays inside the canonical
        # neighbourhood AND the per-offset budgets until the next
        # refresh, and that each aircraft's caller slot lives on the
        # device owning its sorted slot — scatter and back-map stay
        # device-local.
        # ------------------------------------------------------------
        from jax.sharding import PartitionSpec as P
        axes = ("lat", "lon")
        S_t = nb_t * block
        cols_f = {k: v.astype(dtype) for k, v in cols.items()}
        pairs_o = [_offset_pairs((tR, tC), off) for off in offs]

        def body(cols_l, perm_l, pold_l):
            r_i = jax.lax.axis_index("lat")
            c_i = jax.lax.axis_index("lon")
            t = r_i * tC + c_i
            base = t * jnp.int32(S_t)
            in_dev = (perm_l >= base) & (perm_l < base + S_t)
            dest_loc = jnp.where(in_dev, perm_l - base, S_t)
            padded_l = {
                k: jnp.zeros((S_t,), dtype).at[dest_loc].set(
                    v, mode="drop")
                for k, v in cols_l.items()}
            fields_l = make_fields(padded_l)
            packed_l = jnp.stack(
                [fields_l[k] for k in _FIELDS]).reshape(
                    len(_FIELDS), nb_t, block).transpose(1, 0, 2)
            act_l = padded_l["active"] > 0.5

            summ_l = cd_tiled.block_summaries(
                padded_l["lat"], padded_l["lon"], padded_l["gs"], act_l,
                nb_t, block, alt=padded_l["alt"], vs=padded_l["vs"])
            summ_g = {k: jax.lax.all_gather(v, axes, tiled=True)
                      for k, v in summ_l.items()}
            reach_rows = cd_tiled.reachability_from_summaries(
                summ_l, summ_g, float(rpz), float(tlookahead),
                hpz=float(hpz), min_reach_m=min_reach,
                min_vreach_m=min_vreach)                   # [nb_t, nb]

            # Per-offset export: ship only the own blocks the RECEIVER
            # tile's rows can reach.  Sender and the single-chip
            # reference derive the selection from the SAME gathered
            # summaries, so the shipped sets agree bit-for-bit; gids
            # ride a parallel +1-coded int permute (0 = invalid — edge
            # tiles without a partner receive the collective's zeros).
            own_gid0 = t * jnp.int32(nb_t)
            gparts = [own_gid0 + jnp.arange(nb_t, dtype=jnp.int32)]
            sparts = [packed_l]
            for off, E, prs in zip(offs, budgets, pairs_o):
                dr, dcm = off
                tdst = jnp.clip(r_i + dr, 0, tR - 1) * tC \
                    + (c_i + dcm) % tC
                summ_dst = {
                    k: jax.lax.dynamic_slice(v, (tdst * nb_t,), (nb_t,))
                    for k, v in summ_g.items()}
                reach_out = cd_tiled.reachability_from_summaries(
                    summ_dst, summ_l, float(rpz), float(tlookahead),
                    hpz=float(hpz), min_reach_m=min_reach,
                    min_vreach_m=min_vreach)       # [dst rows, own cols]
                sidx, valid = _tile_select(
                    jnp.any(reach_out, axis=0), E, nb_t)
                buf = jnp.where(valid[:, None, None],
                                packed_l[sidx], 0.0)
                gidp = jnp.where(valid, own_gid0 + sidx + 1,
                                 0).astype(jnp.int32)
                rbuf = jax.lax.ppermute(buf, axes, prs)
                rgid = jax.lax.ppermute(gidp, axes, prs)
                gparts.append(jnp.where(rgid > 0, rgid - 1, nb))
                sparts.append(rbuf)

            gkey = jnp.concatenate(gparts)
            order, gid_tab, wl_l = _tile_windows(
                reach_rows, gkey, nb, s_cap_t, wmax)
            halo13 = jnp.concatenate(sparts, axis=0)[order]
            halo16 = jnp.concatenate([
                jnp.concatenate(
                    [halo13, jnp.zeros(
                        (ncols_t, _NFP - len(_FIELDS), block), dtype)],
                    axis=1),
                jnp.zeros((wmax, _NFP, block), dtype)], axis=0)
            own16 = jnp.concatenate(
                [packed_l,
                 jnp.zeros((nb_t, _NFP - len(_FIELDS), block), dtype)],
                axis=1)
            gid_pad = jnp.concatenate(
                [gid_tab, jnp.full((wmax,), nb, jnp.int32)])

            row0 = t * jnp.int32(nb_t)
            outs_l = run_rows(
                row0_col(wl_l, row0, 0), own16, packed_l, pold_l,
                None, None, row0, False, halo16, halo13,
                rstride=1, col0=0, gid_tab=gid_pad, fallback=False,
                s_cap_r=s_cap_t)

            # Back-map to THIS device's caller shard (device-local
            # gather; sentinel rows read the accumulator identities)
            (inconf_l, tcpamax_l, sdve_l, sdvn_l, sdvv_l, tsolv_l,
             ncnt_l, lcnt_l, ctin_l, cidx_l) = outs_l[:10]
            rows_l = [inconf_l, tcpamax_l, sdve_l, sdvn_l, sdvv_l,
                      tsolv_l, outs_l[12]]                 # + active
            if reso == "swarm":
                rows_l.extend(outs_l[13:13 + cd_pallas._N_SWARM])
            stacked_l = jnp.stack([o.reshape(S_t) for o in rows_l])
            gsl = jnp.clip(dest_loc, 0, S_t - 1)
            backed_l = jnp.where(
                in_dev[None, :], stacked_l[:, gsl],
                jnp.asarray(backed_neutral, dtype)[:, None])
            tt_l = ctin_l.transpose(0, 2, 1).reshape(S_t, kk)[gsl]
            ti_l = cidx_l.transpose(0, 2, 1).reshape(S_t, kk)[gsl]
            tt_l = jnp.where(in_dev[:, None], tt_l, cd_pallas._BIG)
            ti_l = jnp.where(in_dev[:, None], ti_l, jnp.int32(2 ** 30))
            nconf_l = jax.lax.psum(
                jnp.sum(ncnt_l.astype(jnp.int32), dtype=jnp.int32),
                axes)
            nlos_l = jax.lax.psum(
                jnp.sum(lcnt_l.astype(jnp.int32), dtype=jnp.int32),
                axes)
            return backed_l, tt_l, ti_l, outs_l[11], nconf_l, nlos_l

        col_specs = {k: P(axes) for k in cols_f}
        backed, topk_tin, ti_raw, pmerged, nconf, nlos = \
            cd_pallas.shard_map_compat(
                body, mesh,
                (col_specs, P(axes), P(axes)),
                (P(None, axes), P(axes), P(axes),
                 P(axes), P(), P()))(cols_f, perm, pold)

        topk_idx = jnp.where(
            (topk_tin < cd_pallas._BIG) & (ti_raw < n_tot), ti_raw, -1)
        rd = RowConflictData(
            inconf=backed[0] > 0.5,
            tcpamax=backed[1],
            sum_dve=backed[2], sum_dvn=backed[3], sum_dvv=backed[4],
            tsolv=backed[5],
            nconf=nconf, nlos=nlos,
            topk_idx=topk_idx, topk_tin=topk_tin)
        partners_new = pmerged.transpose(0, 2, 1).reshape(n_tot, kk)
        active_caller = backed[6] > 0.5
        if reso == "swarm":
            return rd, partners_new, active_caller, \
                tuple(backed[7:7 + cd_pallas._N_SWARM])
        return rd, partners_new, active_caller

    if tiles_on:
        # Single-chip tiles reference: the SAME per-tile present-set
        # construction and windows as the mesh body (shared helpers),
        # run as one kernel call per tile over the global slab array —
        # a parity/debug path, not a perf path (it re-gathers each
        # tile's imports from the replicated grid).  Bit-parity with
        # the mesh is by construction: identical selection, identical
        # present ranking, identical windows, identical gid lift.
        chunks = []
        for t in range(tD):
            r0t, c0t = divmod(t, tC)
            rr = reach[t * nb_t:(t + 1) * nb_t]            # [nb_t, nb]
            reach_any = jnp.any(rr, axis=0)
            gparts = [t * nb_t + jnp.arange(nb_t, dtype=jnp.int32)]
            sparts = [packed[t * nb_t:(t + 1) * nb_t]]
            for off, E in zip(offs, budgets):
                dr, dcm = off
                ru, cu = r0t - dr, (c0t - dcm) % tC
                if 0 <= ru < tR:
                    u = ru * tC + cu
                    sidx, valid = _tile_select(
                        reach_any[u * nb_t:(u + 1) * nb_t], E, nb_t)
                    gparts.append(jnp.where(valid, u * nb_t + sidx, nb))
                    sparts.append(jnp.where(valid[:, None, None],
                                            packed[u * nb_t + sidx],
                                            0.0))
                else:
                    gparts.append(jnp.full((E,), nb, jnp.int32))
                    sparts.append(jnp.zeros((E, len(_FIELDS), block),
                                            dtype))
            gkey = jnp.concatenate(gparts)
            order, gid_tab, wl_t = _tile_windows(rr, gkey, nb,
                                                 s_cap_t, wmax)
            halo13_t = jnp.concatenate(sparts, axis=0)[order]
            halo16_t = jnp.concatenate([
                jnp.concatenate(
                    [halo13_t, jnp.zeros(
                        (ncols_t, _NFP - len(_FIELDS), block), dtype)],
                    axis=1),
                jnp.zeros((wmax, _NFP, block), dtype)], axis=0)
            gid_pad = jnp.concatenate(
                [gid_tab, jnp.full((wmax,), nb, jnp.int32)])
            chunks.append(run_rows(
                row0_col(wl_t, t * nb_t, 0),
                packed16[t * nb_t:(t + 1) * nb_t],
                packed[t * nb_t:(t + 1) * nb_t],
                None if pold is None else pold[t * nb_t:(t + 1) * nb_t],
                None, None, t * nb_t, False, halo16_t, halo13_t,
                rstride=1, col0=0, gid_tab=gid_pad, fallback=False,
                s_cap_r=s_cap_t))
        outs = [parts[0] if tD == 1 else jnp.concatenate(parts)
                for parts in zip(*chunks)]
    elif mesh is not None and mesh.shape[mesh_axis] > 1:
        # shard_map over the row blocks: each device schedules and
        # sweeps its own rows against the replicated column slabs (the
        # all-gather rides ICI); row/partner ids stay global via the
        # row0 + i*ndev mapping.  Rows are INTERLEAVED across devices
        # (device d owns global rows d, d+D, ...) — measured to cut the
        # contiguous split's 1.2-1.5x stripe-density imbalance to
        # ~1.0-1.1x (scripts/scaling_table.py).  SURVEY §5.7/5.8
        # block-distributed CD.
        from jax.sharding import PartitionSpec as P
        ndev = mesh.shape[mesh_axis]
        rows_l, nbrp, rperm, rinv = cd_pallas.interleave_rows(nb, ndev)
        pad_r = nbrp - nb

        def prep(a, fill):
            if pad_r:
                a = jnp.concatenate(
                    [a, jnp.full((pad_r,) + a.shape[1:], fill, a.dtype)])
            return a[rperm]

        # Padding rows: empty windows (start=sentinel, len=0) + inactive
        # own slabs -> the kernel's whole-row skip; overflow=False.
        wl_p = prep(wl, nb)                       # start=nb, ln=0
        own16_p = prep(packed16[:nb], 0)
        packedown_p = prep(packed, 0)
        pold_p = prep(pold, -1) if resume else None
        reachf_p = prep(reach_f, False)
        overflow_p = prep(overflow, False)

        def body(wl_l, own16_l, packedown_l, pold_l, reachf_l,
                 overflow_l, intr16_g, intr_g):
            row0 = jax.lax.axis_index(mesh_axis)
            return run_rows(row0_col(wl_l, row0), own16_l, packedown_l,
                            pold_l, reachf_l, overflow_l, row0,
                            False, intr16_g, intr_g, rstride=ndev)

        specs_in = (P(mesh_axis), P(mesh_axis), P(mesh_axis),
                    P(mesh_axis) if resume else P(),
                    P(mesh_axis), P(mesh_axis), P(), P())
        outs = cd_pallas.shard_map_compat(
            body, mesh, specs_in, P(mesh_axis))(
                wl_p, own16_p, packedown_p,
                pold_p if resume else jnp.zeros((ndev,), jnp.int32),
                reachf_p, overflow_p, packed16, packed)
        outs = [o[rinv][:nb] for o in outs]
    elif nb > _ONE_VARIANT_ROWS:
        # Large-N: compile a single kernel variant (both equator-branch
        # variants double compile time for a ~10% saving that huge
        # fleets, which usually straddle the equator, rarely get).
        # ROW SPLIT: the TPU compiler crashes (tpu_compile_helper exit
        # 1, no diagnostics) on this kernel somewhere above ~1700 grid
        # rows (N ~ 450-500k) — measured OK at 400k, dead at 700k, and
        # neither scalar-prefetch bytes, Element-dim size nor grid
        # shape proved to be the variable.  Rows are independent, so
        # slicing the grid into <=_MAX_ROWS-row pallas_call invocations
        # keeps every compiled program inside the proven range while
        # the concatenated outputs stay bit-identical; this is what
        # lifts the sparse backend past the old 400k ceiling to 1M+.
        chunks = []
        for r0 in range(0, nb, _MAX_ROWS):
            r1 = min(r0 + _MAX_ROWS, nb)
            chunks.append(run_rows(
                row0_col(wl[r0:r1], r0), packed16[r0:r1], packed[r0:r1],
                None if pold is None else pold[r0:r1],
                reach_f[r0:r1], overflow[r0:r1], r0, False,
                packed16, packed))
        outs = [parts[0] if len(chunks) == 1 else jnp.concatenate(parts)
                for parts in zip(*chunks)]
    else:
        lat_a = jnp.where(act_b, padded["lat"], 0.0)
        cross = (jnp.min(lat_a) < 0.0) & (jnp.max(lat_a) > 0.0)
        run = lambda sh: functools.partial(
            run_rows, row0_col(wl, 0), packed16, packed, pold,
            reach_f, overflow, 0, sh, packed16, packed)
        outs = jax.lax.cond(cross,
                            lambda: run(False)(),
                            lambda: run(True)())

    (inconf, tcpamax, sdve, sdvn, sdvv, tsolv, ncnt, lcnt,
     ctin, cidx) = outs[:10]

    # Map padded-sorted rows back to caller slots with ONE fused gather
    # (aircraft i lives at padded slot perm[i]; separate per-array
    # gathers serialize on TPU at ~30 ns/element).
    rows = [inconf, tcpamax, sdve, sdvn, sdvv, tsolv]
    if resume:
        rows.append(outs[12])                              # active
    sw_start = 13 if resume else 10
    if reso == "swarm":
        rows.extend(outs[sw_start:sw_start + cd_pallas._N_SWARM])
    stacked = jnp.stack([o.reshape(n_tot) for o in rows])
    if shard_mode in ("spatial", "tiles"):
        # A spatial/tiles-mode refresh stores the SENTINEL slot n_tot
        # for inactive rows (they are dropped from the padded scatter);
        # mask their gathers to the accumulator identities so this
        # single-chip reference stays bit-identical to the mesh
        # decomposition's masked device-local back-map.
        pvalid = perm < n_tot
        pc = jnp.clip(perm, 0, n_tot - 1)
        backed = jnp.where(pvalid[None, :], stacked[:, pc],
                           jnp.asarray(backed_neutral, dtype)[:, None])
        topk_tin = jnp.where(
            pvalid[:, None],
            ctin.transpose(0, 2, 1).reshape(n_tot, kk)[pc],
            cd_pallas._BIG)
        topk_idx = jnp.where(
            pvalid[:, None],
            cidx.transpose(0, 2, 1).reshape(n_tot, kk)[pc],
            jnp.int32(2 ** 30))
    else:
        backed = stacked[:, perm]                          # [6|7|+7, n]
        topk_tin = ctin.transpose(0, 2, 1).reshape(n_tot, kk)[perm]
        topk_idx = cidx.transpose(0, 2, 1).reshape(n_tot, kk)[perm]
    if not resume:
        # Translate sorted-space partner ids to caller slots via the
        # inverse scatter (sentinel-filled with n -> invalid -> -1).
        inv = slot_inverse(perm, n, n_tot, fill=n)
        topk_idx = inv[jnp.clip(topk_idx, 0, n_tot)]
    topk_idx = jnp.where((topk_tin < cd_pallas._BIG) & (topk_idx < n_tot),
                         topk_idx, -1)

    rd = RowConflictData(
        inconf=backed[0] > 0.5,
        tcpamax=backed[1],
        sum_dve=backed[2], sum_dvn=backed[3], sum_dvv=backed[4],
        tsolv=backed[5],
        nconf=jnp.sum(ncnt.astype(jnp.int32), dtype=jnp.int32),
        nlos=jnp.sum(lcnt.astype(jnp.int32), dtype=jnp.int32),
        topk_idx=topk_idx, topk_tin=topk_tin)
    nfix = 7 if resume else 6
    sw = tuple(backed[nfix:nfix + cd_pallas._N_SWARM]) \
        if reso == "swarm" else None
    if not resume:
        return (rd, sw) if sw is not None else rd
    pmerged = outs[11]
    partners_new = pmerged.transpose(0, 2, 1).reshape(n_tot, kk)
    active_caller = backed[6] > 0.5
    if sw is not None:
        return rd, partners_new, active_caller, sw
    return rd, partners_new, active_caller
