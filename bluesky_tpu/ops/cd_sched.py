"""Sparse segment-scheduled CD&R: near-physics-floor pair enumeration.

The full-grid Pallas kernel (``ops/cd_pallas.py``) visits every
[block, block] tile of the N x N pair space and skips unreachable ones.
Round-3 profiling on the v5e showed that at N=100k continental this costs
~120 ms per CD interval: ~82 ms of pair math over 7.6e8 block-granular
pairs and ~38 ms of pure grid+DMA overhead across 38k grid programs,
while the *physics floor* — pairs within ``rpz + tlookahead*(gs_i+gs_j)``
of each other, the exact conservative bound of the reference C++
prefilter idea (``bluesky/traffic/asas/src_cpp/asas.hpp:24-27``) — is
only ~5.5e7 pairs.  This module restructures the schedule so both costs
approach their floors:

* **Stripe sort** (``stripe_sort_dest``): aircraft are ordered by
  latitude stripe (stripe height >= the reach radius), longitude within
  the stripe, and each stripe is padded to a block boundary.  Unlike the
  Morton curve, this guarantees the reachable columns of any row block
  form at most ONE contiguous run per lat-reachable stripe (the lon
  window in a lon-sorted stripe is an interval), i.e. ~3 runs instead of
  Morton's fragmented ~7-21.

* **Segment schedule** (``build_windows``): from the exact block
  reachability matrix (``cd_tiled.block_reachability`` — unchanged
  bound, so the skip stays exact), each row's reachable columns are
  covered by at most ``S_cap`` contiguous segments of at most ``Wmax``
  blocks.  Rows needing more (dense geometries where everyone reaches
  everyone — e.g. the regional benchmark circle) are OVERFLOW rows,
  covered exactly by the old full-grid kernel restricted to those rows
  (``cd_pallas.full_grid_pass``), and the row-disjoint outputs merged.

* **Segment kernel** (``_sched_kernel``): ONE grid program per ownship
  block (grid = (nb,), not (nb, nb/cpp)): the program loops over its
  prefetched (start, len) segments, each an ``pl.Element``-indexed
  contiguous [Wmax, 16, block] slab DMA — no per-tile grid step, no
  gathers.  Tile math is byte-identical to the other backends
  (``cd_pallas._tile_pairs`` traced into this kernel), so results match
  the dense oracle exactly like the tiled/pallas paths do.

Semantics: identical reductions to ``cd_tiled.detect_resolve_tiled`` —
the schedule only changes WHICH provably-conflict-free tiles are
skipped, never the computed pairs' math.
"""
import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import cd_pallas, cd_tiled
from .cd_pallas import _ACC_NEUTRAL, _FIELDS, _IDX, _init_accumulators
from .cd_tiled import RowConflictData, block_reachability, precompute_trig

#: slab rows padded 13 -> 16 so a dynamic leading-index of a
#: [Wmax, _NFP, block] VMEM ref lands on a whole-vreg boundary
#: (16*block is a multiple of the (8, 128) vreg for block >= 128)
_NFP = 16


def padded_size(n, block=256, extra=32):
    """Total slots of the padded stripe-sorted layout for n aircraft."""
    block = min(block, 256)
    return (-(-n // block) + extra) * block


def reach_threshold_m(gs, active, tlookahead, rpz):
    """Worst-case reach radius [m]: the exact conservative CD bound at
    fleet-max closing speed (used to size stripes; per-block thresholds
    in the reachability matrix stay per-block)."""
    gsmax = jnp.max(jnp.where(active, gs, 0.0))
    return rpz + tlookahead * 2.0 * gsmax


#: altitude layers per stripe (cruise bands); one extra "climber" bucket
#: collects |vs| > _CLIMB_VS aircraft so they cannot poison a cruise
#: block's vsmax in the vertical reachability bound.  Measured at N=100k
#: continental the layering INCREASES scheduled pairs (5.4e8 vs 3.4e8:
#: thinning the lat-lon buckets makes blocks longitude-fat, and the
#: +block-span dilation outweighs the vertical selectivity), so it is
#: disabled; the vertical term of block_reachability stays on — it can
#: only remove tiles, and fleets with genuinely spatially-banded
#: altitudes get the skip for free.
_N_LAYERS = 0
_CLIMB_VS = 1.0     # [m/s]


def stripe_sort_dest(lat, lon, gs, active, thresh_m, block, extra,
                     alt=None, vs=None):
    """Padded stripe-major sort: per-aircraft destination slots.

    Returns ``dest`` [n] int32: aircraft i occupies padded slot dest[i]
    in a layout of ``n + extra*block`` slots where each latitude stripe
    starts on a block boundary (so no row block straddles two stripes —
    straddle blocks have airspace-wide bounding boxes that blow up their
    column windows).  Stripe height is the larger of the reach radius
    and what caps the stripe count at ``extra - 1`` (so the padding
    always fits); inactive aircraft sort into the last stripe.

    With ``alt``/``vs``, aircraft are sub-ordered inside each stripe by
    altitude band (cruisers) with climbers/descenders in a separate
    bucket, then longitude — so blocks are homogeneous in altitude and
    the vertical term of ``block_reachability`` can skip whole
    flight-level bands.  Bucket boundaries are soft: they only shape
    block contents, never correctness (the reachability bound reads the
    true per-block ranges every interval).

    Like the Morton permutation this is refreshed only every
    ``sort_every`` CD intervals — ANY staleness is exact because block
    reachability is recomputed from true positions each interval;
    staleness only loosens the windows.
    """
    n = lat.shape[0]
    act = active
    big = jnp.asarray(1e9, lat.dtype)
    latmin = jnp.min(jnp.where(act, lat, big))
    latmax = jnp.max(jnp.where(act, lat, -big))
    any_act = jnp.any(act)
    latmin = jnp.where(any_act, latmin, 0.0)
    latmax = jnp.where(any_act, latmax, 1.0)
    span = jnp.maximum(latmax - latmin, 1e-6)
    # [m] -> [deg]: 1 deg of great-circle is >= 110 km everywhere, so
    # thresh/110000 over-estimates the needed stripe height -> safe.
    h = jnp.maximum(jnp.maximum(thresh_m * 1.05 / 110000.0,
                                span / (extra - 1)), 0.05)
    s = jnp.clip(jnp.floor((lat - latmin) / h), 0, extra - 2).astype(jnp.int32)
    s = jnp.where(act, s, extra - 1)

    if alt is None or _N_LAYERS == 0:
        layer = jnp.zeros((n,), jnp.int32)
    else:
        amin = jnp.where(any_act, jnp.min(jnp.where(act, alt, big)), 0.0)
        amax = jnp.where(any_act, jnp.max(jnp.where(act, alt, -big)), 1.0)
        lh = jnp.maximum((amax - amin) / _N_LAYERS, 1.0)
        layer = jnp.clip(jnp.floor((alt - amin) / lh), 0,
                         _N_LAYERS - 1).astype(jnp.int32)
        layer = jnp.where(jnp.abs(vs) > _CLIMB_VS, _N_LAYERS, layer)

    qlon = jnp.clip((lon + 180.0) * (2 ** 19 / 360.0), 0, 2 ** 19 - 1)
    key = (s * (_N_LAYERS + 1) + layer) * (2 ** 19) + qlon.astype(jnp.int32)
    order = jnp.argsort(key)                       # sorted -> original
    ss = s[order]

    onehot = ss[:, None] == jnp.arange(extra, dtype=jnp.int32)[None, :]
    counts = jnp.sum(onehot, axis=0, dtype=jnp.int32)          # [extra]
    nblocks = -(-counts // block)
    base = jnp.concatenate([jnp.zeros((1,), jnp.int32),
                            jnp.cumsum(nblocks)[:-1]]) * block
    first = jnp.concatenate([jnp.zeros((1,), jnp.int32),
                             jnp.cumsum(counts)[:-1]])
    rank = jnp.arange(n, dtype=jnp.int32) - first[ss]
    dest_sorted = base[ss] + rank
    return jnp.zeros((n,), jnp.int32).at[order].set(dest_sorted)


def scatter_padded(arrs, dest, n_tot, neutral=0.0):
    """Place per-aircraft columns into the padded sorted layout.

    Unfilled slots get ``neutral`` (0 -> inactive for the mask columns).
    One shared index computation; each array costs one O(n) scatter.
    """
    return [jnp.full((n_tot,), neutral, a.dtype).at[dest].set(a)
            for a in arrs]


def build_windows(reach, s_cap, wmax, pad_start):
    """Cover each row's reachable columns with <= s_cap segments of
    <= wmax blocks.

    ``reach`` [nb, nb] bool.  Returns ``(start, ln, overflow)``:
    ``start``/``ln`` [nb, s_cap] int32 (unused slots: start=pad_start,
    ln=0), ``overflow`` [nb] bool marking rows whose reachable set needs
    more segments than s_cap — the caller covers those with the
    full-grid fallback.  Covering a SUPERSET of reachable columns is
    always exact (extra tiles just compute provably-empty pairs), so the
    segmentation never needs to be tight, only sufficient.
    """
    nb = reach.shape[0]
    col = jnp.arange(nb, dtype=jnp.int32)
    prev = jnp.pad(reach[:, :-1], ((0, 0), (1, 0)))
    nxt = jnp.pad(reach[:, 1:], ((0, 0), (0, 1)))
    starts = reach & ~prev
    # run start id per column (within its run), then split runs at wmax
    rs = jax.lax.cummax(jnp.where(starts, col, -1), axis=1)
    off = col - rs
    newseg = reach & (starts | (off % wmax == 0))
    # a segment ENDS at a run end or just before the next wmax split
    segend = reach & (~nxt | (off % wmax == wmax - 1))
    nseg = jnp.sum(newseg, axis=1)
    overflow = nseg > s_cap

    # Extract the s-th start/end per row with a searchsorted on the
    # running flag counts — O(nb log nb) and graph-size O(1), unlike the
    # former [nb, s_cap, nb] one-hot reduction whose window-build graph
    # broke the TPU compiler around nb ~ 4000 (N = 1M).
    want = jnp.arange(1, s_cap + 1, dtype=jnp.int32)
    find = jax.vmap(lambda cnt: jnp.searchsorted(cnt, want, side="left"))
    st = find(jnp.cumsum(newseg, axis=1)).astype(jnp.int32)    # [nb, S]
    en = find(jnp.cumsum(segend, axis=1)).astype(jnp.int32)
    valid = want[None, :] <= nseg[:, None]
    ln = jnp.where(valid, en - st + 1, 0)
    use = valid & ~overflow[:, None]
    st = jnp.where(use, st, pad_start).astype(jnp.int32)
    ln = jnp.where(use, ln, 0).astype(jnp.int32)
    return st, ln, overflow


def _sched_kernel(wl_ref, own_ref, *rest,
                  block, kk, s_cap, wmax, rpz, hpz, tlookahead, mvpcfg,
                  same_hemi=False, rpz_m=None, reso="mvp"):
    resume = rpz_m is not None
    intr_refs = rest[:s_cap]
    rest = rest[s_cap:]
    if resume:
        pold_ref = rest[0]
        out_refs = rest[1:11]
        keep_ref, pnew_ref, pact_ref = rest[11:]
    else:
        pold_ref = keep_ref = pnew_ref = pact_ref = None
        out_refs = rest
    i = pl.program_id(0)
    _init_accumulators(out_refs, block, kk)
    if resume:
        keep_ref[0] = jnp.zeros((kk, block), jnp.float32)

    oslab = own_ref[0]                                     # (_NFP, block)

    def own(k):
        return oslab[_IDX[k]:_IDX[k] + 1, :]

    gid_own = i * block + jax.lax.broadcasted_iota(
        jnp.int32, (1, block), 1)
    act_o = own("active") > 0.5

    # Whole-row skip: a row block of padding/inactive slots has no work
    # in any segment.
    @pl.when(jnp.any(act_o))
    def _row():
        for s in range(s_cap):
            # (start, len) are bit-packed into one scalar-prefetch array
            # (start low 20 bits, len high 12): the scalar-prefetch SMEM
            # budget overflows with two [nb, s_cap] int32 tables around
            # nb ~ 1600 (the TPU compiler crashes ungracefully there).
            w = wl_ref[i, s]
            base = w & 0xFFFFF
            ln = w >> 20
            slab_ref = intr_refs[s]

            def body(k, _, base=base, slab_ref=slab_ref):
                islab_t = slab_ref[k].T                    # (block, _NFP)
                # (a pre-transposed slab layout was measured SLOWER:
                # per-field column reads of a (block, _NFP) VMEM slab
                # stride across lanes; one .T per tile wins)

                def intr(f):
                    return islab_t[:, _IDX[f]:_IDX[f] + 1]

                jb = base + k
                gid_int = jb * block + jax.lax.broadcasted_iota(
                    jnp.int32, (block, 1), 0)
                act_i = intr("active") > 0.5
                pairmask = (act_o & act_i) & (gid_own != gid_int)

                @pl.when(jnp.any(pairmask))
                def _tile():
                    cd_pallas._tile_pairs(
                        pairmask, gid_int, own, intr, *out_refs,
                        kk=kk, rpz=rpz, hpz=hpz, tlookahead=tlookahead,
                        mvpcfg=mvpcfg, same_hemi=same_hemi, jb=jb,
                        resume_refs=(pold_ref, keep_ref) if resume
                        else None, rpz_m=rpz_m, reso=reso)
                return 0

            jax.lax.fori_loop(0, jnp.minimum(ln, wmax), body, 0)

    if resume:
        # ctin/cidx refs hold the finished per-ownship candidates after
        # the segment loops; fold in the surviving old partners.
        cd_pallas._merge_partners_block(
            pold_ref, keep_ref, out_refs[8], out_refs[9],
            pnew_ref, pact_ref, kk)


def detect_resolve_sched(lat, lon, trk, gs, alt, vs, gseast, gsnorth,
                         active, noreso, rpz, hpz, tlookahead, mvpcfg,
                         block=256, k_partners=8, s_cap=6, wmax=16,
                         extra_blocks=32, interpret=False, perm=None,
                         cols_per_prog=4, partners=None, resume_rpz_m=None,
                         tas=None, reso="mvp"):
    """Sparse-scheduled equivalent of ``cd_pallas.detect_resolve_pallas``.

    ``perm`` is the cached ``stripe_sort_dest`` destination table (NOT a
    Morton permutation); recomputed when None.  Results match the other
    backends' reductions (same tile math, superset tile coverage).

    With ``partners`` ([n_tot, K] int32, SORTED-space ids, -1 empty) the
    kernels also run in-kernel resume-nav (keep evaluation on every
    visited partner pair + the candidate/old merge — reference
    asas.py:409-471 without any [N,K] host gathers), and the return
    value becomes ``(rd, partners_new, active)`` where ``partners_new``
    [n_tot, K] stays in sorted space (the caller keeps the table there
    between intervals; ``rd.topk_*`` are then also sorted-space and
    mainly diagnostic) and ``active`` [n] is the caller-space ASAS
    engagement flag.
    ``resume_rpz_m`` is the margin-scaled resume radius (rpz*resofach).
    """
    n = lat.shape[0]
    dtype = jnp.float32
    block = min(block, 256)
    if n > 400_000:
        # The TPU compiler crashes (tpu_compile_helper exit 1, no
        # diagnostics) on this kernel somewhere above ~500k aircraft —
        # measured OK at 400k, failing at 700k; neither scalar-prefetch
        # size, Element-dim size nor grid shape proved to be the
        # variable.  The plain pallas grid covers the 1M scale
        # (bench._pick_backend routes there); shrinking s_cap extends
        # the sparse range a little.
        s_cap = min(s_cap, 4)
    if partners is None and n <= 2 * block:
        # Too small to schedule — the plain kernel is already one tile.
        return cd_pallas.detect_resolve_pallas(
            lat, lon, trk, gs, alt, vs, gseast, gsnorth, active, noreso,
            rpz, hpz, tlookahead, mvpcfg, block=block,
            k_partners=k_partners, interpret=interpret, reso=reso,
            extra_cols=None if tas is None else {"tas": tas})
    resume = partners is not None

    thresh = reach_threshold_m(gs.astype(dtype), active,
                               float(tlookahead), float(rpz))
    if perm is None:
        perm = stripe_sort_dest(lat.astype(dtype), lon.astype(dtype),
                                gs.astype(dtype), active, thresh, block,
                                extra_blocks, alt=alt.astype(dtype),
                                vs=vs.astype(dtype))
    nb = -(-n // block) + extra_blocks
    n_tot = nb * block

    cols = {
        "lat": lat, "lon": lon, "trk": trk, "gs": gs, "alt": alt,
        "vs": vs, "gse": gseast, "gsn": gsnorth,
        # tas/gs ratio: Eby's velocity basis (ve = tr*u); 1.0 when no
        # tas given (MVP never reads it)
        "tr": (jnp.ones_like(gs.astype(dtype)) if tas is None
               else tas.astype(dtype)
               / jnp.maximum(gs.astype(dtype), 0.5)),
        "active": active.astype(dtype), "noreso": noreso.astype(dtype),
    }
    padded = dict(zip(cols, scatter_padded(
        [v.astype(dtype) for v in cols.values()], perm, n_tot)))

    fields = precompute_trig(padded["lat"], padded["lon"])
    trkrad = jnp.radians(padded["trk"])
    fields.update({
        "u": padded["gs"] * jnp.sin(trkrad),
        "v": padded["gs"] * jnp.cos(trkrad),
        "alt": padded["alt"], "vs": padded["vs"],
        "gse": padded["gse"], "gsn": padded["gsn"], "tr": padded["tr"],
        "active": padded["active"], "noreso": padded["noreso"],
    })
    fields["trk"] = padded["trk"]
    packed = jnp.stack([fields[k] for k in _FIELDS]).reshape(
        len(_FIELDS), nb, block).transpose(1, 0, 2)        # [nb, _NF, block]

    act_b = padded["active"] > 0.5
    reach = block_reachability(padded["lat"], padded["lon"], padded["gs"],
                               act_b, nb, block, float(rpz),
                               float(tlookahead), alt=padded["alt"],
                               vs=padded["vs"], hpz=float(hpz))

    # Segment windows + the Wmax-block pad region the sentinel slots
    # point at (slots are clamped so every DMA stays in bounds); start
    # and len ride one bit-packed scalar-prefetch array (SMEM budget,
    # see _sched_kernel).
    if nb >= 2 ** 20 or wmax >= 2 ** 11:
        raise ValueError(
            f"worklist bit-pack overflow: nb={nb} must be < 2^20 and "
            f"wmax={wmax} < 2^11 (start|len share one int32; a silent "
            "overflow would drop conflict windows)")
    st, ln, overflow = build_windows(reach, s_cap, wmax, pad_start=nb)
    st = jnp.clip(st, 0, nb)
    wl = st | (ln << 20)
    packed16 = jnp.concatenate([
        jnp.concatenate(                                   # 13 -> 16 rows
            [packed, jnp.zeros((nb, _NFP - len(_FIELDS), block), dtype)],
            axis=1),
        jnp.zeros((wmax, _NFP, block), dtype)], axis=0)    # DMA pad region

    kk = k_partners
    own_spec = pl.BlockSpec((1, _NFP, block), lambda i, wl: (i, 0, 0),
                            memory_space=pltpu.VMEM)
    intr_specs = [
        pl.BlockSpec((pl.Element(wmax), pl.Element(_NFP),
                      pl.Element(block)),
                     functools.partial(
                         lambda i, wl, s=0: (wl[i, s] & 0xFFFFF, 0, 0),
                         s=s),
                     memory_space=pltpu.VMEM)
        for s in range(s_cap)]
    acc_spec = lambda: pl.BlockSpec((1, 1, block),
                                    lambda i, wl: (i, 0, 0),
                                    memory_space=pltpu.VMEM)
    cand_spec = lambda: pl.BlockSpec((1, kk, block),
                                     lambda i, wl: (i, 0, 0),
                                     memory_space=pltpu.VMEM)
    out_shape = [jax.ShapeDtypeStruct((nb, 1, block), dtype)] * 8 + [
        jax.ShapeDtypeStruct((nb, kk, block), dtype),
        jax.ShapeDtypeStruct((nb, kk, block), jnp.int32)]
    pold = None
    if resume:
        pold = partners.reshape(nb, block, kk).transpose(0, 2, 1) \
            .astype(jnp.int32)                             # [nb, kk, block]
        out_shape = out_shape + [
            jax.ShapeDtypeStruct((nb, kk, block), dtype),       # keep
            jax.ShapeDtypeStruct((nb, kk, block), jnp.int32),   # merged
            jax.ShapeDtypeStruct((nb, 1, block), dtype)]        # active
    reach_f = reach & overflow[:, None]
    rsel = overflow[:, None, None]
    neutral_vals = _ACC_NEUTRAL + ((0.0, -1, 0.0) if resume else ())

    def run(same_hemi):
        """Sched kernel + overflow fallback, specialised on the static
        cross-equator-radius-branch elision (exact: only taken when no
        active pair can straddle the equator)."""
        kern = functools.partial(
            _sched_kernel, block=block, kk=kk, s_cap=s_cap, wmax=wmax,
            rpz=float(rpz), hpz=float(hpz), tlookahead=float(tlookahead),
            mvpcfg=mvpcfg, same_hemi=same_hemi,
            rpz_m=float(resume_rpz_m) if resume else None, reso=reso)
        in_specs = [own_spec] + [intr_specs[s] for s in range(s_cap)]
        out_specs = [acc_spec() for _ in range(8)] \
            + [cand_spec(), cand_spec()]
        args = [wl, packed16] + [packed16] * s_cap
        if resume:
            in_specs.append(cand_spec())               # pold
            args.append(pold)
            out_specs += [cand_spec(), cand_spec(), acc_spec()]
        outs_s = list(pl.pallas_call(
            kern,
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=1,
                grid=(nb,),
                in_specs=in_specs,
                out_specs=out_specs,
            ),
            out_shape=out_shape,
            interpret=interpret,
        )(*args))

        # Overflow rows (dense geometries): exact full-grid fallback on
        # the row-restricted reachability, merged row-disjointly.
        kern_kw = dict(block=block, kk=kk, rpz=float(rpz), hpz=float(hpz),
                       tlookahead=float(tlookahead), mvpcfg=mvpcfg,
                       same_hemi=same_hemi, reso=reso)

        def fallback(rf):
            return cd_pallas.full_grid_pass(
                packed, rf, block=block, kk=kk, cpp=cols_per_prog,
                kern_kw=kern_kw, interpret=interpret, pold=pold,
                rpz_m=resume_rpz_m)

        def neutral(_):
            return [jnp.full(o.shape, v, o.dtype)
                    for o, v in zip(outs_s, neutral_vals)]

        outs_f = jax.lax.cond(jnp.any(overflow), fallback, neutral, reach_f)
        return [jnp.where(rsel, f, s) for f, s in zip(outs_f, outs_s)]

    if nb > 1024:
        # Large-N: compile a single kernel variant (both equator-branch
        # variants double compile time for a ~10% saving that huge
        # fleets, which usually straddle the equator, rarely get).
        outs = run(False)
    else:
        lat_a = jnp.where(act_b, padded["lat"], 0.0)
        cross = (jnp.min(lat_a) < 0.0) & (jnp.max(lat_a) > 0.0)
        outs = jax.lax.cond(cross,
                            functools.partial(run, False),
                            functools.partial(run, True))

    (inconf, tcpamax, sdve, sdvn, sdvv, tsolv, ncnt, lcnt,
     ctin, cidx) = outs[:10]

    # Map padded-sorted rows back to caller slots with ONE fused gather
    # (aircraft i lives at padded slot perm[i]; separate per-array
    # gathers serialize on TPU at ~30 ns/element).
    rows = [inconf, tcpamax, sdve, sdvn, sdvv, tsolv]
    if resume:
        rows.append(outs[12])                              # active
    stacked = jnp.stack([o.reshape(n_tot) for o in rows])
    backed = stacked[:, perm]                              # [6|7, n]
    topk_tin = ctin.transpose(0, 2, 1).reshape(n_tot, kk)[perm]
    topk_idx = cidx.transpose(0, 2, 1).reshape(n_tot, kk)[perm]
    if not resume:
        # Translate sorted-space partner ids to caller slots via the
        # inverse scatter (sentinel-filled with n -> invalid -> -1).
        inv = jnp.full((n_tot + 1,), n, jnp.int32).at[perm].set(
            jnp.arange(n, dtype=jnp.int32))
        topk_idx = inv[jnp.clip(topk_idx, 0, n_tot)]
    topk_idx = jnp.where((topk_tin < cd_pallas._BIG) & (topk_idx < n_tot),
                         topk_idx, -1)

    rd = RowConflictData(
        inconf=backed[0] > 0.5,
        tcpamax=backed[1],
        sum_dve=backed[2], sum_dvn=backed[3], sum_dvv=backed[4],
        tsolv=backed[5],
        nconf=jnp.sum(ncnt.astype(jnp.int32), dtype=jnp.int32),
        nlos=jnp.sum(lcnt.astype(jnp.int32), dtype=jnp.int32),
        topk_idx=topk_idx, topk_tin=topk_tin)
    if not resume:
        return rd
    pmerged = outs[11]
    partners_new = pmerged.transpose(0, 2, 1).reshape(n_tot, kk)
    active_caller = backed[6] > 0.5
    return rd, partners_new, active_caller
