"""Shard the simulation over a device mesh.

The reference scales two ways (SURVEY.md §2.10): NumPy vectorization within a
process, and a process farm for *independent* scenarios.  Neither helps one
big traffic scene.  Here the aircraft axis itself is sharded over a
``jax.sharding.Mesh``:

* every per-aircraft array ``[N]`` is split along axis 0 ('ac'),
* the O(N^2) pair matrices ``[N, N]`` are split along rows — each device owns
  the conflict rows of its aircraft block and all-gathers the column side
  (position/velocity of all aircraft) over ICI, which is exactly the
  block-distributed CD with halo exchange called for in SURVEY.md §5.7,
* waypoint tables ``[N, W]`` split along rows; scalars/PRNG keys replicate.

We annotate shardings and let GSPMD insert the collectives (all-gather of the
broadcast operands of ``ops/cd.py``'s [N,1] x [1,N] math) rather than
hand-writing shard_map — the step stays one jitted program on any mesh size,
and the same code runs single-chip when the mesh has one device.

A second mesh axis ('ens') replicates whole scenarios for Monte-Carlo
ensembles (BASELINE config #4): see ``ensemble_step``.

Three decompositions for the sparse backend's shard_map kernels
(SimConfig.cd_shard_mode / the SHARD stack command):

* ``replicate`` — interleaved row blocks per device against the
  replicated O(N) column state (round 4; ~200x ceiling as D grows,
  docs/PERF_ANALYSIS.md §multi-chip);
* ``spatial`` — device-OWNED latitude stripes with conservative halo
  exchange (``prepare_spatial``): the spatial sort refresh re-buckets
  each aircraft into the caller shard of the device owning its sorted
  stripe slot, so per-interval scatter/trig/reachability/windows are
  O(N/D) device-local and only boundary slabs + per-block summaries
  ride ICI.  Bit-identical to the single-chip sparse schedule
  (tests/test_spatial.py) with zero O(N) column all-gathers on the
  compiled HLO (tests/test_hlo_collectives.py);
* ``tiles`` — 2-D lat x lon tiles on a ``('lat', 'lon')`` device mesh
  (``make_tile_mesh`` + ``prepare_tiles``): stripes cut only latitude,
  so on a global scene a D-device stripe still spans 360 degrees of
  longitude and its halo slab scales with the full stripe WIDTH; tiles
  cut both axes, halo wire scales with the tile PERIMETER (edge + 4
  corner slabs, multi-hop ppermute along both mesh axes), and the
  per-tile occupancy bound follows the 2-D population split.  Same
  refusal contract: the tile refresh validates corner-halo coverage
  per re-bucketing and REFUSES geometries it cannot cover.
"""
import threading
import time
from functools import partial

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..core.state import SimState
from ..core.step import SimConfig, step


def init_multihost(coordinator_address=None, num_processes=None,
                   process_id=None):
    """Join a multi-host mesh (the reference's MPI/NCCL scale-out role,
    SURVEY §5.8, as jax.distributed over DCN).

    Call ONCE per host process before any other JAX use; afterwards
    ``jax.devices()`` lists every chip in the job, so ``make_mesh()``
    and the sharded step below span hosts with no further changes —
    GSPMD routes intra-host collectives over ICI and cross-host ones
    over DCN.  On Cloud TPU pods the arguments default from the
    environment (``jax.distributed.initialize()`` with none needed).
    Single-host (and this repo's one-chip CI) skips this entirely.
    """
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id)


def make_mesh(n_devices=None, devices=None):
    """1-D mesh over the aircraft axis (all JOB devices after
    ``init_multihost`` — i.e. every chip on every host)."""
    devices = devices if devices is not None else jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    return Mesh(np.asarray(devices), ("ac",))


def make_tile_mesh(tiles, devices=None):
    """2-D ``('lat', 'lon')`` mesh for the tiles decomposition: device
    (r, c) owns tile ``t = r*C + c`` of the R x C lat x lon grid.  The
    flattened row-major device order matches the tile-major sorted
    layout of ``ops/cd_sched.tile_sort_dest``, so ``P(('lat', 'lon'))``
    on the aircraft axis IS the tile ownership map."""
    tR, tC = int(tiles[0]), int(tiles[1])
    if tR < 1 or tC < 1:
        raise ValueError(f"tile mesh shape must be positive, got "
                         f"{tR}x{tC}")
    devices = devices if devices is not None else jax.devices()
    if len(devices) < tR * tC:
        raise ValueError(f"tile mesh {tR}x{tC} needs {tR * tC} devices, "
                         f"have {len(devices)}")
    return Mesh(np.asarray(devices[:tR * tC]).reshape(tR, tC),
                ("lat", "lon"))


def _ac_axes(mesh: Mesh):
    """The mesh axis (or axis tuple) the aircraft dimension shards on:
    'ac' on the 1-D mesh, the flattened ('lat', 'lon') product on a
    tile mesh."""
    if "ac" in mesh.shape:
        return "ac"
    if "lat" in mesh.shape and "lon" in mesh.shape:
        return ("lat", "lon")
    raise ValueError(f"mesh has neither an 'ac' nor a ('lat', 'lon') "
                     f"axis set: {dict(mesh.shape)}")


def state_shardings(state: SimState, mesh: Mesh):
    """NamedSharding pytree for a SimState: rank>=1 arrays with a leading
    aircraft axis shard on 'ac' (or the flattened ('lat', 'lon') tile
    axes); scalars and the PRNG key replicate."""
    nmax = state.nmax
    ax = _ac_axes(mesh)

    def spec(leaf):
        if hasattr(leaf, "ndim") and leaf.ndim >= 1 and leaf.shape[0] == nmax:
            return NamedSharding(mesh, P(ax, *([None] * (leaf.ndim - 1))))
        return NamedSharding(mesh, P())

    return jax.tree.map(spec, state)


def shard_state(state: SimState, mesh: Mesh) -> SimState:
    """Place a host-built state onto the mesh with the canonical shardings."""
    return jax.tree.map(lambda x, s: jax.device_put(x, s), state,
                        state_shardings(state, mesh))


def spatial_state_shardings(state: SimState, mesh: Mesh):
    """Spatial-mode shardings: the canonical per-aircraft split plus
    the sorted-space partner table sharded over its (device-divisible)
    padded rows — it must never re-enter an interval replicated, or the
    shard_map boundary would reshard O(N*K) every interval."""
    sh = state_shardings(state, mesh)
    return sh.replace(asas=sh.asas.replace(
        partners_s=NamedSharding(mesh, P(_ac_axes(mesh), None))))


def prepare_spatial(state: SimState, mesh: Mesh, acfg, block: int = 256,
                    halo_blocks: int = 0, put: bool = True):
    """Enter the spatial domain-decomposition mode: size the
    sorted-space partner table to the device-divisible padded layout,
    run the spatial refresh (stripe sort + caller-slot re-bucketing +
    halo-coverage check), and place the state on the mesh with the
    canonical shardings (the re-bucketed caller axis IS the stripe
    ownership map: device d's shard holds the aircraft of its latitude
    stripes).

    Returns ``(state, newslot, info)`` — ``newslot`` the old->new
    caller slot map the host applies to its id/route bookkeeping
    (``Traffic.apply_slot_permutation``), ``info`` the refresh stats
    (occupancy, halo need, layout) for SHARD readback.

    Entering the mode RESETS engagement hysteresis (the partner table
    is rebuilt empty in the new layout): conservative — engaged pairs
    re-detect on the next CD interval.
    """
    import jax.numpy as jnp
    from ..core import asas as asasmod
    ndev = mesh.shape["ac"]
    n = state.nmax
    if n % ndev:
        raise ValueError(f"spatial mode: nmax={n} must divide into the "
                         f"{ndev}-device mesh")
    n_tot = asasmod.spatial_table_size(n, block, ndev)
    kk = state.asas.partners_s.shape[1]
    state = state.replace(asas=state.asas.replace(
        partners_s=jnp.full((n_tot, kk), -1, jnp.int32)))
    state, newslot, info = asasmod.refresh_spatial_shard(
        state, acfg, ndev, block=block, halo_blocks=halo_blocks)
    if put:
        # single-host placement; a multi-host job places the shards
        # itself (jax.make_array_from_callback over
        # spatial_state_shardings — see tests/multihost_worker.py)
        state = jax.tree.map(lambda x, s: jax.device_put(x, s), state,
                             spatial_state_shardings(state, mesh))
    return state, newslot, info


def prepare_tiles(state: SimState, mesh: Mesh, acfg, tiles=None,
                  block: int = 256, budgets=(), put: bool = True):
    """Enter the 2-D tiles decomposition: size the sorted-space partner
    table to the device-divisible padded layout of the R*C-device tile
    grid, run the tile refresh (tile-major sort + caller-slot
    re-bucketing + corner-halo coverage check, auto-pinning the
    per-offset halo slab budgets at 1.25x the measured need when
    ``budgets`` is empty), and place the state on the mesh.

    ``tiles`` defaults to the mesh's own ('lat', 'lon') shape.  Returns
    ``(state, newslot, info)`` like ``prepare_spatial``; pin
    ``info['budgets']`` into ``SimConfig.cd_tile_budgets`` (and
    ``info['tile_shape']`` into ``cd_tile_shape``) so the compiled
    interval and every later refresh validate the SAME static window.
    """
    import jax.numpy as jnp
    from ..core import asas as asasmod
    if tiles is None:
        try:
            tiles = (mesh.shape["lat"], mesh.shape["lon"])
        except KeyError:
            raise ValueError(
                "prepare_tiles needs a ('lat', 'lon') mesh (build it "
                "with make_tile_mesh) or an explicit tiles=(R, C)")
    tR, tC = int(tiles[0]), int(tiles[1])
    ndev = tR * tC
    n = state.nmax
    if n % ndev:
        raise ValueError(f"tiles mode: nmax={n} must divide into the "
                         f"{tR}x{tC}={ndev}-tile grid")
    n_tot = asasmod.spatial_table_size(n, block, ndev)
    kk = state.asas.partners_s.shape[1]
    state = state.replace(asas=state.asas.replace(
        partners_s=jnp.full((n_tot, kk), -1, jnp.int32)))
    state, newslot, info = asasmod.refresh_tile_shard(
        state, acfg, (tR, tC), block=block, budgets=tuple(budgets))
    if put:
        state = jax.tree.map(lambda x, s: jax.device_put(x, s), state,
                             spatial_state_shardings(state, mesh))
    return state, newslot, info


def unprepare_spatial(state: SimState):
    """Leave spatial/tiles mode: restore the default-size sorted tables
    (hysteresis resets, like entering — conservative either way).
    Caller slots keep their last bucketing (valid, just no longer
    maintained)."""
    import jax.numpy as jnp
    from ..core.state import SORT_PAD
    n = state.nmax
    kk = state.asas.partners_s.shape[1]
    return state.replace(asas=state.asas.replace(
        partners_s=jnp.full((n + SORT_PAD, kk), -1, jnp.int32),
        sort_perm=jnp.arange(n, dtype=jnp.int32)))


def sharded_step_fn(mesh: Mesh, cfg: SimConfig, nsteps: int = 1):
    """Compile the (scanned) step with explicit in/out shardings on mesh.

    The dense/tiled backends shard purely via GSPMD from the state
    shardings; the Pallas backends ('pallas', 'sparse') additionally
    need the mesh itself for their shard_map row split, so it is filled
    into the config here (see ``ops/cd_sched.detect_resolve_sched``).

    With ``cfg.scanstats`` the compiled program returns ``(state,
    ScanStats)`` instead of bare state: the in-scan accumulators ride
    the same scan carry (obs/scanstats.py) with their per-aircraft
    folds kept as [ndev] per-device partials — GSPMD keeps the
    row-split reductions shard-local, so the stats add ZERO in-scan
    collectives (tests/test_hlo_collectives.py pins ON vs OFF equal).
    With ``cfg.inscan_refresh`` the RefreshPack joins the outputs the
    same way (after stats), its due gate seeded from the optional
    ``sort_t0`` call argument (None = cold: sort_t = -1, so the first
    due step refreshes).
    """
    if cfg.cd_backend in ("pallas", "sparse") and cfg.cd_mesh is None:
        if "ac" in mesh.shape:
            cfg = cfg._replace(cd_mesh=mesh, cd_mesh_axis="ac")
        elif "lat" in mesh.shape and "lon" in mesh.shape:
            # tile mesh: the shard_map body splits over both axes; the
            # 1-D mesh_axis name is unused on that path
            cfg = cfg._replace(cd_mesh=mesh)

    def run(state, sort_t0=None):
        from ..core.step import _scan_steps
        out, _, stats, refresh, fp = _scan_steps(state, cfg, nsteps,
                                                 checked=False,
                                                 sort_t0=sort_t0)
        ret = (out,)
        if stats is not None:
            ret = ret + (stats,)
        if refresh is not None:
            ret = ret + (refresh,)
        if fp is not None:
            ret = ret + (fp,)
        return ret[0] if len(ret) == 1 else ret

    return jax.jit(run, donate_argnums=0)


# --------------------------------------------------------------------------
# Monte-Carlo ensembles: vmap over a replica axis, sharded over devices.
# Replaces the reference's BATCH process farm (server.py:269-287) with a
# single SPMD program: each device owns whole replicas, no cross-device
# traffic at all (embarrassingly parallel, DCN-friendly across slices).
# --------------------------------------------------------------------------

def make_ensemble_mesh(n_devices=None, devices=None):
    devices = devices if devices is not None else jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    return Mesh(np.asarray(devices), ("ens",))


def ensemble_step_fn(mesh: Mesh, cfg: SimConfig, nsteps: int = 1):
    """vmapped step over a leading replica axis, replicas sharded on 'ens'.

    Input: a SimState pytree whose every leaf has a leading replica axis
    (build with ``stack_replicas``).
    """
    def run_one(state):
        def body(s, _):
            return step(s, cfg), None
        out, _ = jax.lax.scan(body, state, None, length=nsteps)
        return out

    vrun = jax.vmap(run_one)

    def espec(leaf):
        return NamedSharding(mesh, P("ens", *([None] * (leaf.ndim - 1))))

    def run(states):
        states = jax.lax.with_sharding_constraint(
            states, jax.tree.map(espec, states))
        return vrun(states)

    return jax.jit(run, donate_argnums=0)


def stack_replicas(states):
    """Stack a list of equal-shape SimStates into one leading replica axis."""
    import jax.numpy as jnp
    return jax.tree.map(lambda *xs: jnp.stack(xs, axis=0), *states)


# --------------------------------------------------------------------------
# Mesh-epoch recovery (ISSUE-10): losing a device group ends the EPOCH,
# not the run.  MeshGuard is the liveness sentinel a sharded sim consults
# at every chunk dispatch; on a trip the sim tears the epoch down,
# reloads the last checksummed snapshot onto the survivor mesh and steps
# on degraded (simulation/sim._handle_mesh_lost).
# --------------------------------------------------------------------------

class MeshLostError(RuntimeError):
    """A device group of the active mesh is dead or unreachable.

    Carries the lost group indices and the surviving device list so the
    recovery layer can re-form a smaller mesh without re-deriving the
    topology from a wedged runtime.
    """

    def __init__(self, msg, lost_groups=(), survivors=None):
        super().__init__(msg)
        self.lost_groups = tuple(lost_groups)
        self.survivors = list(survivors) if survivors is not None else []


class MeshGuard:
    """Liveness sentinel for one mesh epoch.

    Device groups model the unit of correlated failure: on a real
    multi-process mesh they are the per-process device partitions (a
    host dying takes its whole group); on a single-process (virtual)
    mesh the device list splits into two contiguous halves so chaos
    tests can kill "host 1" of the 8-device CPU mesh (``FAULT MESHKILL
    1`` -> devices 4-7 dead, survivors 0-3).

    Detection is two-pronged:

    * ``check()`` — cheap dispatch-time precheck: raises
      ``MeshLostError`` for any group marked dead (the ``FAULT
      MESHKILL`` injector, or a stale peer heartbeat observed earlier).
    * ``guarded_ready(x)`` — heartbeat-stamped collective timeout
      wrapper around a device sync: ``jax.block_until_ready`` runs in a
      side thread while this process keeps stamping its own heartbeat
      file; if the wait exceeds ``timeout`` (a collective blocked on a
      dead peer never returns) the peer stamps decide who died.
    """

    def __init__(self, mesh=None, heartbeat_dir=None, timeout=0.0,
                 hb_timeout=10.0):
        self.timeout = float(timeout)        # collective wait budget [s]
        self.hb_timeout = float(hb_timeout)  # peer stamp staleness [s]
        self.heartbeat_dir = heartbeat_dir
        self.epoch = 0
        self._killed = set()
        self.groups = []
        self.mesh = None
        self.set_mesh(mesh)

    # ------------------------------------------------------------ topology
    def set_mesh(self, mesh):
        """Bind a (new) mesh: recompute device groups, clear kill marks
        — a re-formed survivor mesh starts its epoch healthy."""
        self.mesh = mesh
        self._killed = set()
        devs = list(mesh.devices.flat) if mesh is not None else []
        self.groups = self._partition(devs)

    @staticmethod
    def _partition(devs):
        if not devs:
            return []
        try:
            nproc = jax.process_count()
        except RuntimeError:
            nproc = 1
        if nproc > 1:
            by_proc = {}
            for d in devs:
                by_proc.setdefault(getattr(d, "process_index", 0),
                                   []).append(d)
            return [by_proc[k] for k in sorted(by_proc)]
        if len(devs) < 2:
            return [devs]
        half = (len(devs) + 1) // 2
        return [devs[:half], devs[half:]]

    @property
    def survivors(self):
        """Devices of every still-live group, in mesh order."""
        return [d for k, g in enumerate(self.groups)
                if k not in self._killed for d in g]

    # ---------------------------------------------------------- injection
    def kill_group(self, k):
        """Mark device group ``k`` dead (the FAULT MESHKILL injector).
        The fault surfaces at the next ``check()``/``guarded_ready()``,
        i.e. the next chunk dispatch — like a real host loss, nothing
        happens until the fabric next touches the mesh."""
        k = int(k)
        if not 0 <= k < len(self.groups):
            raise ValueError(f"no device group {k} "
                             f"(mesh has {len(self.groups)})")
        if len(self.groups) - len(self._killed | {k}) < 1:
            raise ValueError("cannot kill the last live device group")
        self._killed.add(k)
        return self.groups[k]

    # ---------------------------------------------------------- detection
    def check(self):
        """Dispatch-time precheck: raise MeshLostError if any group of
        the bound mesh is marked dead."""
        if self.mesh is None or not self._killed:
            return
        lost = sorted(self._killed)
        raise MeshLostError(
            f"mesh epoch {self.epoch}: device group(s) "
            f"{','.join(map(str, lost))} dead "
            f"({len(self.survivors)} device(s) survive)",
            lost_groups=lost, survivors=self.survivors)

    # ------------------------------------------------- cross-process pulse
    def _hb_path(self, pid=None):
        import os
        if not self.heartbeat_dir:
            return None
        if pid is None:
            try:
                pid = jax.process_index()
            except RuntimeError:
                pid = 0
        return os.path.join(self.heartbeat_dir, f"meshhb-{pid}")

    def stamp(self):
        """Refresh this process's heartbeat file (mtime is the pulse)."""
        import os
        path = self._hb_path()
        if path is None:
            return
        os.makedirs(self.heartbeat_dir, exist_ok=True)
        with open(path, "w") as f:
            f.write(f"{time.time():.3f}\n")

    def stale_peers(self, hb_timeout=None):
        """Process indices whose heartbeat stamp is older than
        ``hb_timeout`` (missing stamps are NOT stale: a peer that never
        stamped may simply not have started)."""
        import os
        if not self.heartbeat_dir or not os.path.isdir(self.heartbeat_dir):
            return []
        budget = self.hb_timeout if hb_timeout is None else float(hb_timeout)
        try:
            me = jax.process_index()
        except RuntimeError:
            me = 0
        now = time.time()
        stale = []
        for name in sorted(os.listdir(self.heartbeat_dir)):
            if not name.startswith("meshhb-"):
                continue
            try:
                pid = int(name.split("-", 1)[1])
            except ValueError:
                continue
            if pid == me:
                continue
            try:
                age = now - os.path.getmtime(
                    os.path.join(self.heartbeat_dir, name))
            except OSError:
                continue
            if age > budget:
                stale.append(pid)
        return stale

    def guarded_ready(self, x):
        """``jax.block_until_ready(x)`` under the heartbeat-stamped
        collective timeout: the wait runs in a daemon thread while this
        process keeps stamping; past ``timeout`` seconds (0 = block
        forever) — or if the wait errors out with a peer already stale —
        the epoch is declared lost."""
        self.check()
        if self.timeout <= 0:
            self.stamp()
            return jax.block_until_ready(x)
        box = {}

        def _wait():
            try:
                box["out"] = jax.block_until_ready(x)
            except Exception as e:          # noqa: BLE001 — the backend
                box["err"] = e              # aborts in its own way
        t = threading.Thread(target=_wait, daemon=True)
        t.start()
        deadline = time.monotonic() + self.timeout
        beat = max(0.05, min(1.0, self.timeout / 4.0))
        while True:
            t.join(beat)
            self.stamp()
            if not t.is_alive():
                break
            stale = self.stale_peers()
            if stale or time.monotonic() > deadline:
                raise MeshLostError(
                    f"mesh epoch {self.epoch}: collective wait exceeded "
                    f"{self.timeout:.1f}s"
                    + (f", peer process(es) {stale} silent "
                       f"> {self.hb_timeout:.1f}s" if stale else ""),
                    lost_groups=stale, survivors=self.survivors)
        if "err" in box:
            stale = self.stale_peers()
            if stale:
                raise MeshLostError(
                    f"mesh epoch {self.epoch}: collective failed "
                    f"({box['err']}) with peer process(es) {stale} "
                    "silent", lost_groups=stale,
                    survivors=self.survivors) from box["err"]
            raise box["err"]
        return box["out"]
