"""Shard the simulation over a device mesh.

The reference scales two ways (SURVEY.md §2.10): NumPy vectorization within a
process, and a process farm for *independent* scenarios.  Neither helps one
big traffic scene.  Here the aircraft axis itself is sharded over a
``jax.sharding.Mesh``:

* every per-aircraft array ``[N]`` is split along axis 0 ('ac'),
* the O(N^2) pair matrices ``[N, N]`` are split along rows — each device owns
  the conflict rows of its aircraft block and all-gathers the column side
  (position/velocity of all aircraft) over ICI, which is exactly the
  block-distributed CD with halo exchange called for in SURVEY.md §5.7,
* waypoint tables ``[N, W]`` split along rows; scalars/PRNG keys replicate.

We annotate shardings and let GSPMD insert the collectives (all-gather of the
broadcast operands of ``ops/cd.py``'s [N,1] x [1,N] math) rather than
hand-writing shard_map — the step stays one jitted program on any mesh size,
and the same code runs single-chip when the mesh has one device.

A second mesh axis ('ens') replicates whole scenarios for Monte-Carlo
ensembles (BASELINE config #4): see ``ensemble_step``.
"""
from functools import partial

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..core.state import SimState
from ..core.step import SimConfig, step


def init_multihost(coordinator_address=None, num_processes=None,
                   process_id=None):
    """Join a multi-host mesh (the reference's MPI/NCCL scale-out role,
    SURVEY §5.8, as jax.distributed over DCN).

    Call ONCE per host process before any other JAX use; afterwards
    ``jax.devices()`` lists every chip in the job, so ``make_mesh()``
    and the sharded step below span hosts with no further changes —
    GSPMD routes intra-host collectives over ICI and cross-host ones
    over DCN.  On Cloud TPU pods the arguments default from the
    environment (``jax.distributed.initialize()`` with none needed).
    Single-host (and this repo's one-chip CI) skips this entirely.
    """
    jax.distributed.initialize(coordinator_address=coordinator_address,
                               num_processes=num_processes,
                               process_id=process_id)


def make_mesh(n_devices=None, devices=None):
    """1-D mesh over the aircraft axis (all JOB devices after
    ``init_multihost`` — i.e. every chip on every host)."""
    devices = devices if devices is not None else jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    return Mesh(np.asarray(devices), ("ac",))


def state_shardings(state: SimState, mesh: Mesh):
    """NamedSharding pytree for a SimState: rank>=1 arrays with a leading
    aircraft axis shard on 'ac'; scalars and the PRNG key replicate."""
    nmax = state.nmax

    def spec(leaf):
        if hasattr(leaf, "ndim") and leaf.ndim >= 1 and leaf.shape[0] == nmax:
            return NamedSharding(mesh, P("ac", *([None] * (leaf.ndim - 1))))
        return NamedSharding(mesh, P())

    return jax.tree.map(spec, state)


def shard_state(state: SimState, mesh: Mesh) -> SimState:
    """Place a host-built state onto the mesh with the canonical shardings."""
    return jax.tree.map(lambda x, s: jax.device_put(x, s), state,
                        state_shardings(state, mesh))


def sharded_step_fn(mesh: Mesh, cfg: SimConfig, nsteps: int = 1):
    """Compile the (scanned) step with explicit in/out shardings on mesh.

    The dense/tiled backends shard purely via GSPMD from the state
    shardings; the Pallas backends ('pallas', 'sparse') additionally
    need the mesh itself for their shard_map row split, so it is filled
    into the config here (see ``ops/cd_sched.detect_resolve_sched``).
    """
    if cfg.cd_backend in ("pallas", "sparse") and cfg.cd_mesh is None \
            and "ac" in mesh.shape:
        cfg = cfg._replace(cd_mesh=mesh, cd_mesh_axis="ac")

    def run(state):
        def body(s, _):
            return step(s, cfg), None
        out, _ = jax.lax.scan(body, state, None, length=nsteps)
        return out

    return jax.jit(run, donate_argnums=0)


# --------------------------------------------------------------------------
# Monte-Carlo ensembles: vmap over a replica axis, sharded over devices.
# Replaces the reference's BATCH process farm (server.py:269-287) with a
# single SPMD program: each device owns whole replicas, no cross-device
# traffic at all (embarrassingly parallel, DCN-friendly across slices).
# --------------------------------------------------------------------------

def make_ensemble_mesh(n_devices=None, devices=None):
    devices = devices if devices is not None else jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    return Mesh(np.asarray(devices), ("ens",))


def ensemble_step_fn(mesh: Mesh, cfg: SimConfig, nsteps: int = 1):
    """vmapped step over a leading replica axis, replicas sharded on 'ens'.

    Input: a SimState pytree whose every leaf has a leading replica axis
    (build with ``stack_replicas``).
    """
    def run_one(state):
        def body(s, _):
            return step(s, cfg), None
        out, _ = jax.lax.scan(body, state, None, length=nsteps)
        return out

    vrun = jax.vmap(run_one)

    def espec(leaf):
        return NamedSharding(mesh, P("ens", *([None] * (leaf.ndim - 1))))

    def run(states):
        states = jax.lax.with_sharding_constraint(
            states, jax.tree.map(espec, states))
        return vrun(states)

    return jax.jit(run, donate_argnums=0)


def stack_replicas(states):
    """Stack a list of equal-shape SimStates into one leading replica axis."""
    import jax.numpy as jnp
    return jax.tree.map(lambda *xs: jnp.stack(xs, axis=0), *states)
