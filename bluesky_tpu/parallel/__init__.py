"""Device-mesh parallelism: aircraft-axis sharding, ensemble replication."""
