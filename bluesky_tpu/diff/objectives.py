"""Differentiable objective library for trajectory optimization.

Three cost families (arXiv:2412.16750's shapes), all accumulated INSIDE
the rollout scan so memory stays O(state), never O(trajectory):

* **Soft LoS count** — the loss-of-separation predicate ``(dist < rpz)
  & (|dalt| < hpz)`` relaxed to a product of sigmoids
  (diff/smooth.soft_los_weight) with a DYNAMIC temperature the
  optimizer anneals without recompiling; summed over unique live pairs
  and steps.  ``temp -> 0`` recovers the hard per-step pair count.
* **Fuel burn** — the per-step integral of the performance model's
  ``fuelflow`` column over live aircraft: already smooth (core/perf.py
  computes it from the drag polar / thrust ratio every step).
* **Waypoint-deviation penalty** — quadratic regularizer on the
  optimized offsets in natural units (lateral in protected-zone radii,
  time shifts in ``TSHIFT_SCALE`` seconds), keeping optimized plans
  close to the filed ones.

The HARD metrics (``hard_los_count`` / the rollout trace in
diff/optimize.py) evaluate the exact serving predicate — optimized
plans are verified against the hard metric, never the relaxation.
"""
from typing import NamedTuple

import jax.numpy as jnp

from ..ops import geo
from .smooth import soft_los_weight


#: natural scale of the per-aircraft departure-time offsets [s]
TSHIFT_SCALE = 60.0


class ObjectiveWeights(NamedTuple):
    """Objective mix (hashable -> jit-static)."""
    w_los: float = 1.0       # soft LoS count (the safety term)
    w_fuel: float = 1e-6     # [1/kg] fuel burn
    w_dev: float = 1e-3      # waypoint/time deviation regularizer


def _pair_geometry(ac, eps_m2=1.0):
    """Flat-earth pairwise horizontal distance [m] + altitude gap [m].

    Same small-angle geometry as the resume-nav predicates
    (ops/cr_mvp.resume_displacement); ``eps_m2`` regularizes the sqrt
    at the (masked) diagonal so gradients stay finite.
    """
    lat, lon = ac.lat, ac.lon
    dist_e = geo.REARTH * (jnp.radians(lon[None, :] - lon[:, None])
                           * jnp.cos(0.5 * jnp.radians(lat[None, :]
                                                       + lat[:, None])))
    dist_n = geo.REARTH * jnp.radians(lat[None, :] - lat[:, None])
    dist = jnp.sqrt(dist_e * dist_e + dist_n * dist_n + eps_m2)
    dalt = ac.alt[None, :] - ac.alt[:, None]
    return dist, dalt


def _pairmask(ac):
    n = ac.lat.shape[0]
    eye = jnp.eye(n, dtype=bool)
    return (ac.active[:, None] & ac.active[None, :]) & ~eye


def soft_los_cost(state, rpz, hpz, temp):
    """Soft (sigmoid) LoS count of one state: sum over unique live
    pairs of ``soft_los_weight`` — the annealable safety objective.
    ``temp`` is traced (annealed without recompiling)."""
    dist, dalt = _pair_geometry(state.ac)
    w = soft_los_weight(dist, dalt, rpz, hpz, temp)
    mask = _pairmask(state.ac)
    return 0.5 * jnp.sum(jnp.where(mask, w, 0.0))


def fuel_cost(state, simdt):
    """Fuel burned this step [kg]: fuelflow integral over live rows."""
    live = state.ac.active
    return jnp.sum(jnp.where(live, state.perf.fuelflow, 0.0)) * simdt


def step_cost(state, rpz, hpz, weights: ObjectiveWeights, temp, simdt):
    """Per-step objective increment, accumulated in the rollout carry.

    ``rpz``/``hpz`` are the SOFT zone sizes — the driver inflates them
    by ``los_margin`` over the verification zone so plans carry a
    buffer against the smooth-vs-hard model mismatch (measured < 1 km
    over a 400 s rollout; diff/optimize.hard_los_trace)."""
    c = weights.w_los * soft_los_cost(state, rpz, hpz, temp)
    if weights.w_fuel:
        c = c + weights.w_fuel * fuel_cost(state, simdt)
    return c


def deviation_penalty(lateral_m, tshift_s, rpz,
                      weights: ObjectiveWeights):
    """Quadratic waypoint/time-deviation regularizer in natural units
    (lateral in protected-zone radii, time in TSHIFT_SCALE seconds)."""
    return weights.w_dev * (jnp.sum((lateral_m / rpz) ** 2)
                            + jnp.sum((tshift_s / TSHIFT_SCALE) ** 2))


# ----------------------------------------------------------- hard metrics
def hard_los_matrix(state, rpz, hpz):
    """The EXACT serving LoS predicate (ops/cd.detect's ``swlos``:
    great-circle pair distance, hard comparisons) — the verification
    metric for optimized plans."""
    ac = state.ac
    _, distnm = geo.qdrdist_matrix(ac.lat, ac.lon, ac.lat, ac.lon)
    dist = distnm * geo.nm
    dalt = ac.alt[None, :] - ac.alt[:, None]
    return (dist < rpz) & (jnp.abs(dalt) < hpz) & _pairmask(state.ac)


def hard_los_count(state, rpz, hpz):
    """Directional hard-LoS pair count of one state (int32) — matches
    ``nlos_cur``'s counting convention (core/asas.py)."""
    return jnp.sum(hard_los_matrix(state, rpz, hpz), dtype=jnp.int32)


def anneal_schedule(temp0, temp1, iters):
    """Geometric temperature annealing schedule (host-side list)."""
    import numpy as np
    if iters <= 1:
        return [float(temp1)]
    r = (float(temp1) / float(temp0)) ** (1.0 / (iters - 1))
    return [float(temp0) * r ** k for k in range(iters)]
