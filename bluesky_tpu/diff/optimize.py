"""Trajectory optimization: Adam descent through the chunked step scan.

The driver descends on per-aircraft **lateral waypoint offsets**
(meters perpendicular to the initial track, applied to every route
waypoint + the cached active waypoint) and **departure-time offsets**
(seconds, applied as an along-track shift of the initial position) via
``jax.value_and_grad`` over the smooth rollout:

* the rollout is the REAL step scan (core/step.step) with
  ``SimConfig.smooth`` set — the documented relaxations of
  diff/smooth.py — chunked and wrapped in ``jax.checkpoint`` across
  chunk boundaries, so backward-pass memory stays O(chunk·state +
  nchunks·state) instead of O(nsteps·state);
* the objective (diff/objectives.py) accumulates in the scan carry:
  soft LoS (annealed temperature, traced so annealing never
  recompiles) + fuel + deviation penalty;
* the integrity-guard word of ``run_steps_checked`` is EXTENDED over
  the backward pass (``GUARD_BAD_*``): >= 0 pins the first non-finite
  forward step exactly like the serving guard, -2 flags a non-finite
  objective, -3 non-finite gradients — the optimizer halts on any trip
  and the host routes it through the existing guard machinery
  (fault/guard.py trip records);
* multi-start batching rides the PR-6 world axis: ``restarts > 1``
  stacks R perturbed offset particles on a leading world axis and
  steps them with ``core/step.step_worlds`` in ONE scan (the
  many-scenarios-per-device shape of arXiv:2406.08496), returning the
  best particle.

Optimized plans are verified against the HARD metric: a plain
(smooth=None) scan of the offset-applied state counting exact LoS
pairs per step.  The headline demo (tests/test_diff.py,
scripts/grad_smoke.py) optimizes a 50-aircraft conflict scene to zero
hard-metric LoS.
"""
from functools import partial
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core.step import (SimConfig, state_finite, step, step_worlds,
                         stack_worlds, world_slice)
from ..ops import aero
from . import objectives
from .objectives import ObjectiveWeights, TSHIFT_SCALE
from .smooth import SmoothConfig

#: guard word extensions over run_steps_checked's contract
#: (>= 0 = first bad forward step, -1 = clean):
GUARD_BAD_VALUE = -2     # non-finite objective out of the forward pass
GUARD_BAD_GRADS = -3     # non-finite gradients out of the backward pass


class OffsetParams(NamedTuple):
    """The optimized decision variables, one row per aircraft slot.
    Normalized units (lateral in protected-zone radii, time shifts
    tanh-bounded to a ±TSHIFT_SCALE-second departure slot) keep Adam's
    step size geometry-free."""
    lateral: jnp.ndarray    # [*, N] lateral waypoint offset [rpz units]
    tshift: jnp.ndarray     # [*, N] departure-time offset [tanh units]


def tshift_seconds(tshift_param):
    """Effective departure-time offset [s]: tanh-squashed so the
    optimizer can never 'teleport' an aircraft past its whole conflict
    (an unbounded time shift trivially zeroes the objective by moving
    the crossing outside the horizon — a degenerate optimum, not a
    plan).  The ±TSHIFT_SCALE bound models a realistic departure slot."""
    return TSHIFT_SCALE * jnp.tanh(tshift_param)


def apply_offsets(state, params: OffsetParams, rpz):
    """Apply the decision variables to a base state, differentiably.

    * lateral: every route waypoint and the cached active waypoint
      shift ``lateral * rpz`` meters perpendicular to the aircraft's
      current track;
    * tshift: the initial position shifts ``tshift_seconds(tshift)``
      BACKWARD along the current ground velocity (a positive shift
      delays the crossing like a later departure would).

    Padding rows are frozen (offsets masked by ``active``).
    """
    ac = state.ac
    live = ac.active
    lat_m = jnp.where(live, params.lateral * rpz, 0.0)
    dt_s = jnp.where(live, tshift_seconds(params.tshift), 0.0)

    trkrad = jnp.radians(ac.trk)
    tn, te = jnp.cos(trkrad), jnp.sin(trkrad)
    # perpendicular (left of track) unit vector
    pn, pe = -te, tn
    coslat = jnp.maximum(jnp.abs(ac.coslat), 1e-6)
    dlat_wp = jnp.degrees(pn * lat_m / aero.Rearth)
    dlon_wp = jnp.degrees(pe * lat_m / aero.Rearth / coslat)

    route = state.route.replace(
        wplat=state.route.wplat + dlat_wp[:, None],
        wplon=state.route.wplon + dlon_wp[:, None])
    actwp = state.actwp.replace(lat=state.actwp.lat + dlat_wp,
                                lon=state.actwp.lon + dlon_wp)
    dlat_t = jnp.degrees(-dt_s * ac.gsnorth / aero.Rearth)
    dlon_t = jnp.degrees(-dt_s * ac.gseast / aero.Rearth / coslat)
    ac = ac.replace(lat=ac.lat + dlat_t, lon=ac.lon + dlon_t)
    return state.replace(ac=ac, route=route, actwp=actwp)


# ------------------------------------------------------------- rollouts
def _rollout(state, cfg: SimConfig, nsteps: int, chunk: int,
             weights: ObjectiveWeights, temp, worlds: bool,
             los_margin: float = 1.0):
    """The chunked, checkpointed objective rollout.

    Returns ``(cost, final_state, bad)`` where ``cost`` is the
    accumulated step objective (scalar, or [W] with a world axis),
    and ``bad`` the per-rollout first-bad-step guard word (as
    run_steps_checked; [W] when batched).  ``jax.checkpoint`` wraps the
    chunk body: the forward stores only chunk-boundary states and the
    backward recomputes within each chunk — O(chunk) live activations.
    """
    nchunks = max(1, -(-nsteps // chunk))
    stepfn = (lambda s: step_worlds(s, cfg)) if worlds \
        else (lambda s: step(s, cfg))
    rpz_s = cfg.asas.rpz * los_margin    # margin-inflated SOFT zone
    hpz_s = cfg.asas.hpz
    costfn = objectives.step_cost
    if worlds:
        costfn = jax.vmap(objectives.step_cost,
                          in_axes=(0, None, None, None, None, None))
    finitefn = jax.vmap(state_finite) if worlds else state_finite

    def chunk_body(carry, i0):
        def body(c, i):
            s, acc, bad = c
            s = stepfn(s)
            acc = acc + costfn(s, rpz_s, hpz_s, weights, temp, cfg.simdt)
            bad = jnp.where(bad >= 0, bad,
                            jnp.where(finitefn(s), -1, i))
            return (s, acc, bad), None
        return jax.lax.scan(body, carry,
                            i0 + jnp.arange(chunk, dtype=jnp.int32))

    chunk_body = jax.checkpoint(chunk_body)
    zero = jnp.zeros((state.simt.shape[0],) if worlds else (),
                     state.simt.dtype)
    badw = jnp.full(zero.shape, -1, jnp.int32)
    (state, acc, bad), _ = jax.lax.scan(
        chunk_body, (state, zero, badw),
        jnp.arange(nchunks, dtype=jnp.int32) * chunk)
    return acc, state, bad


@partial(jax.jit, static_argnames=("cfg", "nsteps"))
def _hard_los_scan(state, cfg: SimConfig, nsteps: int):
    """Module-level jitted verification scan (cfg/nsteps static, so
    repeated before/after verifications of one OPT — and every OPT
    piece of a sweep — hit the same compiled program)."""
    rpz, hpz = cfg.asas.rpz, cfg.asas.hpz

    def body(c, _):
        s, mx, tot = c
        s = step(s, cfg)
        n = objectives.hard_los_count(s, rpz, hpz)
        return (s, jnp.maximum(mx, n),
                tot + (n > 0).astype(jnp.int32)), None

    (s, mx, tot), _ = jax.lax.scan(
        body, (state, jnp.zeros((), jnp.int32),
               jnp.zeros((), jnp.int32)),
        None, length=nsteps)
    return mx, tot, s


def hard_los_trace(state, cfg: SimConfig, nsteps: int,
                   simdt: Optional[float] = None):
    """HARD-metric verification scan: step the EXACT (smooth=None) scan
    and return ``(max_los, total_los_steps, final_state)`` — the peak
    directional LoS pair count over every step and the number of steps
    with any LoS.  This is the metric optimized plans are judged by.

    ``simdt`` re-times the scan (default: keep cfg's): the driver
    verifies at the SERVING resolution (0.05 s), where the bang-bang
    dead-bands are tight — measured < 1 km of a 400 s smooth-dt=1 plan
    — rather than at the coarse optimization dt, whose 2°-wide heading
    dead-band is an artifact of the step size, not of the plant."""
    if simdt is not None:
        nsteps = max(1, int(round(nsteps * cfg.simdt / float(simdt))))
        cfg = cfg._replace(simdt=float(simdt))
    cfg = cfg._replace(smooth=None)
    mx, tot, s = _hard_los_scan(state, cfg, nsteps)
    return int(mx), int(tot), s


# ------------------------------------------------- checked value_and_grad
def checked_value_and_grad(fn):
    """``jax.value_and_grad(fn, has_aux=True)`` with the integrity-guard
    word extended over the backward pass.

    ``fn(params, ...) -> (cost, aux)`` where ``aux`` carries the
    forward guard word under key ``"bad"``.  Returns
    ``(value, aux, grads, bad)`` with ``bad``:

    * ``>= 0``             — first non-finite FORWARD step (the
                             run_steps_checked contract, unchanged),
    * ``GUARD_BAD_VALUE``  — the objective itself came back non-finite,
    * ``GUARD_BAD_GRADS``  — the BACKWARD pass produced a non-finite
                             gradient leaf,
    * ``-1``               — clean.
    """
    vg = jax.value_and_grad(fn, has_aux=True)

    def checked(*args, **kwargs):
        (value, aux), grads = vg(*args, **kwargs)
        gfinite = jnp.array(True)
        for leaf in jax.tree_util.tree_leaves(grads):
            gfinite &= jnp.all(jnp.isfinite(leaf))
        fwd_bad = jnp.max(jnp.asarray(aux["bad"]))
        bad = jnp.where(
            fwd_bad >= 0, fwd_bad,
            jnp.where(~jnp.all(jnp.isfinite(jnp.asarray(value))),
                      GUARD_BAD_VALUE,
                      jnp.where(~gfinite, GUARD_BAD_GRADS, -1)))
        return value, aux, grads, bad.astype(jnp.int32)

    return checked


# ------------------------------------------------------------ the driver
class OptResult(NamedTuple):
    lateral_m: np.ndarray       # [N] optimized lateral offsets [m]
    tshift_s: np.ndarray        # [N] optimized time offsets [s]
    objective: list             # per-iteration total objective
    grad_norm: list             # per-iteration gradient 2-norm
    temps: list                 # annealing schedule actually used
    hard_los_before: int        # peak hard LoS pairs, zero offsets
    hard_los_after: int         # peak hard LoS pairs, optimized
    bad: int                    # final guard word (-1 clean)
    iters: int
    nsteps: int
    restarts: int
    best_restart: int

    def to_payload(self, traf_ids=None, slots=None):
        """JSON-able summary for the OPT journal record / client echo."""
        sl = list(slots) if slots is not None else \
            list(range(len(self.lateral_m)))
        d = {
            "iters": self.iters, "nsteps": self.nsteps,
            "restarts": self.restarts, "best_restart": self.best_restart,
            "objective_first": float(self.objective[0]),
            "objective_last": float(self.objective[-1]),
            "objective_trace": [round(float(v), 6)
                                for v in self.objective],
            "hard_los_before": self.hard_los_before,
            "hard_los_after": self.hard_los_after,
            "bad": self.bad,
            "lateral_m": [round(float(self.lateral_m[s]), 2)
                          for s in sl],
            "tshift_s": [round(float(self.tshift_s[s]), 3) for s in sl],
        }
        if traf_ids is not None:
            d["acid"] = [traf_ids[s] for s in sl]
        return d


def _adam(params, grads, m, v, t, lr, b1=0.9, b2=0.999, eps=1e-8):
    m = jax.tree_util.tree_map(lambda m_, g: b1 * m_ + (1 - b1) * g,
                               m, grads)
    v = jax.tree_util.tree_map(lambda v_, g: b2 * v_ + (1 - b2) * g * g,
                               v, grads)
    mh = jax.tree_util.tree_map(lambda m_: m_ / (1 - b1 ** t), m)
    vh = jax.tree_util.tree_map(lambda v_: v_ / (1 - b2 ** t), v)
    params = jax.tree_util.tree_map(
        lambda p, m_, v_: p - lr * m_ / (jnp.sqrt(v_) + eps),
        params, mh, vh)
    return params, m, v


def optimize(state, asas_cfg=None, *, tend: float = 600.0,
             simdt: float = 1.0, chunk: int = 50, iters: int = 60,
             lr: float = 0.15, temp0: float = 0.3, temp1: float = 0.05,
             weights: Optional[ObjectiveWeights] = None,
             smooth: Optional[SmoothConfig] = None,
             with_asas: bool = False, restarts: int = 1, seed: int = 0,
             opt_tshift: bool = True, init_noise: float = 0.1,
             los_margin: float = 1.2, verify_simdt: float = 0.05,
             verbose=None) -> OptResult:
    """Descend on waypoint/time offsets until the (annealed) soft-LoS
    objective is minimized; verify against the hard metric.

    ``state`` is a plain single-world SimState (e.g. ``sim.traf.state``
    at OPT-command time).  The optimization rollout runs the smooth
    scan at ``simdt`` (coarser than the serving 0.05 s — guidance and
    the objective are what matter, and the hard verification runs at
    the same dt); ASAS stays OUT of the optimization loop by default
    (strategic deconfliction of the open-loop plans — set
    ``with_asas=True`` to optimize THROUGH the smooth MVP resolver).

    ``restarts > 1`` runs R perturbed starts batched on the world axis
    in one scan (PR-6 ``step_worlds``) and returns the best particle.
    """
    from ..core.asas import AsasConfig
    asas_cfg = asas_cfg if asas_cfg is not None else AsasConfig()
    weights = weights or ObjectiveWeights()
    smooth = smooth or SmoothConfig()
    rpz, hpz = float(asas_cfg.rpz), float(asas_cfg.hpz)
    opt_asas = asas_cfg if with_asas \
        else asas_cfg._replace(swasas=False)
    cfg = SimConfig(simdt=float(simdt), asas=opt_asas,
                    cd_backend="dense", smooth=smooth)
    nsteps = max(1, int(round(float(tend) / float(simdt))))
    chunk = max(1, min(int(chunk), nsteps))
    nsteps = -(-nsteps // chunk) * chunk     # whole chunks (scan shape)
    iters = max(1, int(iters))               # 0 iters has no iterate to
    #                                          return; run one
    nmax = state.ac.lat.shape[0]
    worlds = restarts > 1

    base = state
    if worlds:
        base = stack_worlds([state] * restarts)

    def cost_fn(params, bstate, temp):
        pl = params.lateral
        pt = params.tshift if opt_tshift \
            else jax.lax.stop_gradient(params.tshift)
        if worlds:
            s = jax.vmap(apply_offsets, in_axes=(0, 0, None))(
                bstate, OffsetParams(pl, pt), rpz)
            dev = jax.vmap(objectives.deviation_penalty,
                           in_axes=(0, 0, None, None))(
                pl * rpz, tshift_seconds(pt), rpz, weights)
        else:
            s = apply_offsets(bstate, OffsetParams(pl, pt), rpz)
            dev = objectives.deviation_penalty(
                pl * rpz, tshift_seconds(pt), rpz, weights)
        acc, final, bad = _rollout(s, cfg, nsteps, chunk, weights,
                                   temp, worlds, los_margin=los_margin)
        per = acc + dev                      # scalar or [W]
        return jnp.sum(per), {"per_restart": per, "bad": bad}

    vgc = checked_value_and_grad(cost_fn)

    @jax.jit
    def opt_iter(params, m, v, t, temp):
        value, aux, grads, bad = vgc(params, base, temp)
        gnorm = jnp.sqrt(sum(jnp.sum(g * g) for g in
                             jax.tree_util.tree_leaves(grads)))
        params, m, v = _adam(params, grads, m, v, t, lr)
        return params, m, v, value, aux["per_restart"], gnorm, bad

    shape = (restarts, nmax) if worlds else (nmax,)
    dtype = state.ac.lat.dtype
    key = jax.random.PRNGKey(seed)
    # Jittered initialization is REQUIRED, not cosmetic: an exactly
    # head-on pair sits on a symmetry saddle of the soft-LoS objective
    # (the lateral derivative of the pair distance is dy/dist = 0 on
    # the aligned ridge), so zero offsets have zero deconfliction
    # gradient.  ~init_noise·rpz of seeded noise breaks every such tie;
    # multi-start particles get progressively wider draws.
    lat0 = init_noise * jax.random.normal(key, shape, dtype)
    if worlds:
        widen = jnp.linspace(1.0, 3.0, restarts, dtype=dtype)
        lat0 = lat0 * widen[:, None]
    params = OffsetParams(lateral=lat0, tshift=jnp.zeros(shape, dtype))
    m = jax.tree_util.tree_map(jnp.zeros_like, params)
    v = jax.tree_util.tree_map(jnp.zeros_like, params)

    temps = objectives.anneal_schedule(temp0, temp1, iters)
    # opt_step spans (ISSUE-12 satellite): one per descent iteration —
    # the optimize driver was missing from the PR-11 span vocabulary
    from ..obs.trace import get_recorder
    rec = get_recorder()
    trace, gnorms = [], []
    bad_word = -1
    for it in range(iters):
        # keep the pre-update iterate: on a guard trip the Adam update
        # has already folded the non-finite gradients into the NEW
        # params, and "halt at the last finite iterate" must mean it
        params_prev = params
        with rec.span("opt_step", cat="opt", it=it,
                      restarts=restarts, nsteps=nsteps):
            params, m, v, value, per, gnorm, bad = opt_iter(
                params, m, v, it + 1, jnp.asarray(temps[it], dtype))
            bad_word = int(bad)
        trace.append(float(value))
        gnorms.append(float(gnorm))
        if verbose:
            verbose(it, float(value), float(gnorm), bad_word)
        if bad_word != -1:
            params = params_prev       # guard trip: halt the descent
            break

    per = np.asarray(per)
    best = int(np.argmin(per)) if worlds else 0
    bp = OffsetParams(*[np.asarray(world_slice(p, best) if worlds else p)
                        for p in params])
    lateral_m = np.where(np.asarray(state.ac.active),
                         bp.lateral * rpz, 0.0)
    tshift_s = np.where(np.asarray(state.ac.active) & opt_tshift,
                        TSHIFT_SCALE * np.tanh(bp.tshift), 0.0)

    # hard-metric verification of the zero-offset and optimized plans
    zerop = OffsetParams(jnp.zeros((nmax,), dtype),
                         jnp.zeros((nmax,), dtype))
    los_before, _, _ = hard_los_trace(
        apply_offsets(state, zerop, rpz), cfg, nsteps,
        simdt=verify_simdt)
    optp = OffsetParams(
        jnp.asarray(lateral_m / rpz, dtype),
        jnp.asarray(np.arctanh(np.clip(tshift_s / TSHIFT_SCALE,
                                       -0.999999, 0.999999)), dtype))
    los_after, _, _ = hard_los_trace(
        apply_offsets(state, optp, rpz), cfg, nsteps,
        simdt=verify_simdt)

    return OptResult(
        lateral_m=lateral_m, tshift_s=tshift_s, objective=trace,
        grad_norm=gnorms, temps=temps[:len(trace)],
        hard_los_before=los_before, hard_los_after=los_after,
        bad=bad_word, iters=len(trace), nsteps=nsteps,
        restarts=restarts, best_restart=best)


def grad_once(state, asas_cfg=None, *, tend: float = 600.0,
              simdt: float = 1.0, chunk: int = 50, temp: float = 1.0,
              weights: Optional[ObjectiveWeights] = None,
              smooth: Optional[SmoothConfig] = None,
              with_asas: bool = False, los_margin: float = 1.2):
    """One checked value_and_grad evaluation at zero offsets (the GRAD
    stack command): returns ``(objective, grad_norm, bad)``."""
    from ..core.asas import AsasConfig
    asas_cfg = asas_cfg if asas_cfg is not None else AsasConfig()
    weights = weights or ObjectiveWeights()
    smooth = smooth or SmoothConfig()
    rpz = float(asas_cfg.rpz)
    opt_asas = asas_cfg if with_asas else asas_cfg._replace(swasas=False)
    cfg = SimConfig(simdt=float(simdt), asas=opt_asas,
                    cd_backend="dense", smooth=smooth)
    nsteps = max(1, int(round(float(tend) / float(simdt))))
    chunk = max(1, min(int(chunk), nsteps))

    def cost_fn(params, bstate, t):
        s = apply_offsets(bstate, params, rpz)
        acc, _, bad = _rollout(s, cfg, nsteps, chunk, weights, t, False,
                               los_margin=los_margin)
        return acc, {"bad": bad}

    nmax = state.ac.lat.shape[0]
    dtype = state.ac.lat.dtype
    params = OffsetParams(jnp.zeros((nmax,), dtype),
                          jnp.zeros((nmax,), dtype))
    value, _aux, grads, bad = checked_value_and_grad(cost_fn)(
        params, state, jnp.asarray(temp, dtype))
    gnorm = float(jnp.sqrt(sum(jnp.sum(g * g) for g in
                               jax.tree_util.tree_leaves(grads))))
    return float(value), gnorm, int(bad)


# --------------------------------------------------------------- scenes
def conflict_scene(n_ac: int = 50, *, leg_km: float = 60.0,
                   pair_spacing_km: float = 80.0, alt_m: float = 8000.0,
                   spd_ms: float = 240.0, lat0: float = 48.0,
                   lon0: float = 4.0, nmax: Optional[int] = None,
                   dtype=None, wmax: int = 8):
    """A guaranteed-conflict scene: ``n_ac // 2`` head-on pairs on an
    east-west axis, pairs stacked north-south far enough apart that
    only partners conflict.  Every aircraft files a single waypoint at
    its partner's start (LNAV direct), so with zero offsets each pair
    meets nose-to-nose at its midpoint — the 50-aircraft demo scene
    gradient descent must deconflict to zero hard LoS.

    Returns ``(traf, cfg_asas)`` — a host Traffic facade whose state is
    ready to roll out.
    """
    from ..core.asas import AsasConfig
    from ..core.traffic import Traffic

    n_pairs = max(1, n_ac // 2)
    n = 2 * n_pairs
    dlat_pair = pair_spacing_km / 111.0
    dlon_leg = leg_km / 111.0     # deliberately ~cos-uncorrected: scene
    #                               scale only needs to be approximate
    lats, lons, hdgs = [], [], []
    for k in range(n_pairs):
        plat = lat0 + k * dlat_pair
        lats += [plat, plat]
        lons += [lon0 - dlon_leg, lon0 + dlon_leg]
        hdgs += [90.0, 270.0]
    traf = Traffic(nmax=nmax or n, wmax=wmax,
                   dtype=dtype or jnp.float32, pair_matrix=True)
    traf.create(n, "B744", alt_m, spd_ms, None,
                np.asarray(lats), np.asarray(lons), np.asarray(hdgs),
                acid=[f"OPT{i:03d}" for i in range(n)])
    traf.flush()

    st = traf.state
    # single-waypoint LNAV-direct routes: each aircraft aims at its
    # partner's start point (functional table writes; route edits at
    # stack cadence go through core/route.py — this is a scene builder)
    nmax_eff = st.ac.lat.shape[0]
    partner = np.arange(n) ^ 1
    wplat = np.array(st.route.wplat)
    wplon = np.array(st.route.wplon)
    wplat[:n, 0] = np.asarray(lats)[partner]
    wplon[:n, 0] = np.asarray(lons)[partner]
    nwp = np.array(st.route.nwp)
    nwp[:n] = 1
    aw_lat = np.array(st.actwp.lat)
    aw_lon = np.array(st.actwp.lon)
    aw_lat[:n] = np.asarray(lats)[partner]
    aw_lon[:n] = np.asarray(lons)[partner]
    lnav = np.zeros(nmax_eff, bool)
    lnav[:n] = True
    st = st.replace(
        route=st.route.replace(
            wplat=jnp.asarray(wplat, st.route.wplat.dtype),
            wplon=jnp.asarray(wplon, st.route.wplon.dtype),
            nwp=jnp.asarray(nwp, jnp.int32),
            iactwp=jnp.where(jnp.asarray(lnav), 0, st.route.iactwp)),
        actwp=st.actwp.replace(
            lat=jnp.asarray(aw_lat, st.actwp.lat.dtype),
            lon=jnp.asarray(aw_lon, st.actwp.lon.dtype)),
        ac=st.ac.replace(
            swlnav=jnp.asarray(lnav),
            swvnav=jnp.zeros((nmax_eff,), bool)))
    traf.state = st
    return traf, AsasConfig()
