"""Differentiable simulation: gradients through the step scan.

The whole hot path (core/step.py) is pure JAX, so the simulator is one
``jax.grad`` away from gradient-based trajectory optimization and ML
research — the parallelized differentiable traffic-simulation shape of
arXiv:2412.16750, served on the same fabric as every other workload
(an ``OPT`` BATCH piece whose journal-logged result is the optimized
offsets + objective trace, network/server.py).

Three modules:

* ``smooth``     — the documented relaxations that make the step scan
                   usefully differentiable (``SmoothConfig`` rides on
                   ``SimConfig.smooth``; ``smooth=None`` — the default
                   everywhere — is bit-identical to the hard step).
* ``objectives`` — the differentiable objective library: fuel burn,
                   soft (sigmoid) LoS count with an annealable
                   temperature, waypoint-deviation penalties, plus the
                   HARD LoS trace used to verify optimized plans.
* ``optimize``   — the trajectory-optimization driver: Adam descent on
                   per-aircraft lateral-waypoint/time offsets via
                   ``jax.value_and_grad`` over the chunked scan
                   (``jax.checkpoint`` across chunk boundaries keeps
                   memory O(chunk)), with the integrity-guard word
                   extended over the backward pass and optional
                   multi-start batching on the PR-6 world axis.

docs/PERF_ANALYSIS.md §differentiable documents the relaxation choices
and the checkpointing memory model; docs/commands.md the ``OPT`` /
``GRAD`` stack commands and journal record.
"""
from .smooth import SmoothConfig                      # noqa: F401
from .objectives import ObjectiveWeights              # noqa: F401
