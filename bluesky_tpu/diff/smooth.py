"""Documented relaxations of the step scan's hard gates.

``SimConfig.smooth`` (core/step.py) carries a ``SmoothConfig`` — or
``None``, the default, in which case every call site takes its original
code path at TRACE time, so the serving scan is bit-identical to the
pre-relaxation step (tests/test_diff.py pins this; the relaxations can
never leak into the serving path).

Relaxation inventory (each one is a *documented choice*, not a silent
approximation — docs/PERF_ANALYSIS.md §differentiable):

1. **Conflict indicator → sigmoid with temperature.**  The hard pair
   predicate ``swconfl`` (ops/cd.detect: four chained comparisons on
   CPA geometry) becomes a product of sigmoids on the same margins
   (``soft_conflict_weight``), so a pair approaching conflict
   contributes a smoothly growing repulsion instead of a step.  The
   per-aircraft engagement *selection* stays hard-forward (both
   branches of the ``jnp.where`` are differentiable); the gradient
   signal rides the contribution weights.
2. **Resolver min/max → softmin/softmax.**  MVP's per-ownship vertical
   solve time (``min`` over conflict pairs) becomes a weighted softmin
   (``softmin_weighted``); the velocity caps in
   ``cr_mvp.resolve_from_sums`` become straight-through clips.
3. **Hard performance-limit clamps → straight-through estimators.**
   ``perf.limits`` / the resolver caps keep their exact forward values
   (``ste_clip``: forward = ``jnp.clip``, backward = identity), so the
   envelope is enforced bit-exactly while gradients keep flowing when
   an intent is pinned against a limit.
4. **Bang-bang kinematic captures → clipped proportional steps.**  The
   turn / TAS / VS dynamics (core/kinematics.update_airspeed) advance
   by ``sign(error) * rate`` under a dead-band — zero gradient
   everywhere.  Smooth mode advances by ``ste_clip(error, ±rate·dt)``:
   outside the dead-band the forward value is identical (full-rate
   step toward the target), inside it the state captures exactly
   instead of chattering, and the clip's straight-through backward
   carries d(state)/d(target) ≈ 1 through the saturation.
5. **RNG noise stop-gradiented.**  Turbulence/ADS-B draws are wrapped
   in ``lax.stop_gradient`` (core/noise.py): the draws are
   parameter-independent by construction, and pinning them keeps the
   backward pass from ever differentiating through ``jax.random``
   internals.

Temperatures are *static* (part of the hashable config — they change at
optimizer-schedule cadence, and the soft-LoS objective anneals its OWN
dynamic temperature; see diff/objectives.py).
"""
from typing import NamedTuple

import jax
import jax.numpy as jnp


class SmoothConfig(NamedTuple):
    """Relaxation temperatures (hashable → jit-static on SimConfig).

    ``temp_conf`` scales the conflict-indicator sigmoids in units of
    the natural margin (rpz² for the CPA distance, lookahead for the
    times); ``temp_min`` is the softmin sharpness for resolver
    reductions in units of the reduced quantity's scale.
    """
    temp_conf: float = 0.1     # conflict sigmoid temperature [x margin]
    temp_min: float = 0.05     # softmin temperature [x tlookahead]
    ste_caps: bool = True      # straight-through resolver/perf clamps
    stop_grad_noise: bool = True  # lax.stop_gradient on RNG draws


def sigmoid(x):
    return jax.nn.sigmoid(x)


def ste_clip(x, lo, hi):
    """Straight-through clip: forward ``jnp.clip(x, lo, hi)``, backward
    identity — the documented STE for hard performance/velocity caps."""
    return x + jax.lax.stop_gradient(jnp.clip(x, lo, hi) - x)


def softmin_weighted(x, w, temp, big=1e9):
    """Weighted softmin over the last axis: smooth stand-in for
    ``min(where(mask, x, big))``.

    ``w`` in [0, 1] are the (sigmoid) pair weights; entries with w≈0
    drop out exactly like masked entries of the hard min.  ``temp`` is
    the softmin temperature in x's units.  Returns the hard masked min
    as ``temp -> 0``.
    """
    xe = jnp.where(w > 0.0, x, big)
    xmin = jnp.min(xe, axis=-1, keepdims=True)
    # log-sum-exp softmin, weight-scaled; fully masked rows return big
    e = w * jnp.exp(-(xe - xmin) / temp)
    den = jnp.sum(e, axis=-1)
    num = jnp.sum(e * xe, axis=-1)
    return jnp.where(den > 1e-30, num / jnp.maximum(den, 1e-30),
                     jnp.squeeze(xmin, -1))


def softmax_weighted(x, w, temp, big=1e9):
    """Weighted softmax reduction — the dual of ``softmin_weighted``
    (the documented resolver min/max relaxation family; the MVP path
    only reduces with min today, so this is the library's max side)."""
    return -softmin_weighted(-x, w, temp, big=big)


def soft_conflict_weight(cd, rpz, tlookahead, smooth: SmoothConfig):
    """Sigmoid relaxation of the hard conflict predicate
    (ops/cd.detect: ``swconfl = swhorconf & (tin <= tout) & (tout > 0)
    & (tin < tlookahead) & pairmask``) on the SAME CPA geometry.

    Each comparison margin becomes a sigmoid at its natural scale:
    the CPA miss distance against rpz² (scale ``temp_conf * rpz²``)
    and the window times against the lookahead (scale ``temp_conf *
    tlookahead``).  Masked/diagonal pairs carry the detect kernel's
    1e9 exclusion offsets, which drive every sigmoid to 0 exactly.
    Returns a [N, N] weight in [0, 1]; ``temp_conf -> 0`` recovers the
    boolean predicate a.e.
    """
    r2 = rpz * rpz
    th = smooth.temp_conf * r2
    tt = smooth.temp_conf * tlookahead
    w = sigmoid((r2 - cd.dcpa2) / th)
    w = w * sigmoid((cd.toutconf - cd.tinconf) / tt)
    w = w * sigmoid(cd.toutconf / tt)
    w = w * sigmoid((tlookahead - cd.tinconf) / tt)
    return w


def soft_los_weight(dist, dalt, rpz, hpz, temp):
    """Sigmoid relaxation of the LoS predicate ``(dist < rpz) &
    (|dalt| < hpz)`` — the soft-LoS objective kernel
    (diff/objectives.py).  ``temp`` is DYNAMIC (annealed by the
    optimizer without recompiling): a fraction of the zone size.
    """
    wh = sigmoid((rpz - dist) / (temp * rpz))
    wv = sigmoid((hpz - jnp.abs(dalt)) / (temp * hpz))
    return wh * wv


def capture_step(error, max_step):
    """Relaxed bang-bang capture: advance toward the target by the
    full-rate step, saturating exactly at the error (no overshoot /
    chatter), with a straight-through backward (see module docstring
    item 4).  ``max_step`` = rate * dt >= 0."""
    return ste_clip(error, -max_step, max_step)
