"""Built-in stack commands: the user/API surface of the simulator.

Mirrors the reference command dictionary (stack/stack.py:180-796) and
synonym table (stack.py:44-115).  Each entry is
``NAME: [usage, argtypes, function, helptext]``; functions return
True/False/None or (ok, echotext) exactly like the reference contract.

Traffic-state mutation happens through small per-slot device writes — these
run at command cadence (human/scenario rate), not step rate, so .at[].set
dispatch cost is irrelevant; bulk creation goes through the batched
``Traffic.flush`` path instead.
"""
import numpy as np
import jax.numpy as jnp

from ..ops import aero
from ..core import wind as windmod
from ..core.asas import AsasConfig
from ..core.noise import NoiseConfig
from . import synthetic
from .argparser import txt2alt, txt2spd


def register_all(stack):
    sim = stack.sim
    traf = sim.traf

    # ------------------------------------------------------------ helpers
    def st():
        return traf.state

    def setac(**updates):
        traf.state = traf.state.replace(ac=traf.state.ac.replace(**updates))

    def setslot(field, idx, value):
        arr = getattr(traf.state.ac, field)
        setac(**{field: arr.at[idx].set(value)})

    def acname(idx):
        return traf.ids[idx] or f"#{idx}"

    # ------------------------------------------------------- a/c commands
    def cre(acid, actype, pos, hdg=None, alt=None, spd=None):
        """CRE acid,type,latlon,hdg,alt,spd (traffic.py:192)."""
        lat, lon = pos
        ok, msg = traf.create(1, actype or "B744", alt, spd, None,
                              lat, lon, hdg, acid)
        if not ok:
            return False, msg
        traf.flush()
        return True

    def mcre(n, actype=None, alt=None, spd=None, dest=None):
        """MCRE n,[type,alt,spd,dest]: n random aircraft."""
        traf.area = sim.scr.getviewbounds()
        ok, msg = traf.create(n, actype or "B744", alt, spd, dest)
        traf.flush()
        return ok, msg

    def delete(idx):
        name = acname(idx)
        traf.delete(idx)
        return True, f"Deleted {name}"

    def delall():
        idxs = [i for i, v in enumerate(traf.ids) if v is not None]
        if idxs:
            traf.delete(idxs)
        return True

    def move(idx, pos, alt=None, hdg=None, spd=None, vspd=None):
        """MOVE acid,latlon,[alt,hdg,spd,vspd] (traffic.py:517)."""
        lat, lon = pos
        setslot("lat", idx, lat)
        setslot("lon", idx, lon)
        setslot("coslat", idx, float(np.cos(np.radians(lat))))
        if alt is not None:
            setslot("alt", idx, alt)
            setslot("selalt", idx, alt)
        if hdg is not None:
            setslot("hdg", idx, hdg)
            setslot("trk", idx, hdg)
        if spd is not None:
            setslot("selspd", idx, spd)
        if vspd is not None:
            setslot("selvs", idx, vspd)
        return True

    def selalt(idx, alt, vspd=None):
        """ALT acid,alt,[vspd] (autopilot.py:306-322)."""
        setslot("selalt", idx, alt)
        setslot("swvnav", idx, False)
        if vspd is not None:
            setslot("selvs", idx, vspd)
        else:
            delalt = alt - float(st().ac.alt[idx])
            cur = float(st().ac.selvs[idx])
            if cur * delalt < 0 and abs(cur) > 0.01:
                setslot("selvs", idx, 0.0)
        return True

    def selvspd(idx, vspd):
        """VS acid,vspd (autopilot.py:324-328)."""
        setslot("selvs", idx, vspd)
        setslot("swvnav", idx, False)
        return True

    def selhdg(idx, hdg):
        """HDG acid,hdg: heading select, LNAV off (autopilot.py:330-346)."""
        # Wind-corrected track happens continuously in the pilot module;
        # here we set the AP track like the reference's no-wind path.
        ap = st().ap
        traf.state = st().replace(ap=ap.replace(trk=ap.trk.at[idx].set(hdg)))
        setslot("swlnav", idx, False)
        return True

    def selspd(idx, spd):
        """SPD acid,spd(CASkt/Mach) (autopilot.py:348-358)."""
        setslot("selspd", idx, spd)
        setslot("swvnav", idx, False)
        return True

    def setvs_direct(idx, vspd):
        setslot("vs", idx, vspd)
        return True

    def pos(idx):
        """POS acid: info text (traffic.py poscommand)."""
        s = st()
        i = idx
        txt = (f"Info on {acname(i)} {traf.types[i]}\n"
               f"Pos: {float(s.ac.lat[i]):.4f}, {float(s.ac.lon[i]):.4f}\n"
               f"Hdg: {float(s.ac.hdg[i]):.0f}   Trk: {float(s.ac.trk[i]):.0f}\n"
               f"Alt: {float(s.ac.alt[i]) / aero.ft:.0f} ft\n"
               f"CAS: {float(s.ac.cas[i]) / aero.kts:.0f} kts   "
               f"TAS: {float(s.ac.tas[i]) / aero.kts:.0f} kts   "
               f"GS: {float(s.ac.gs[i]) / aero.kts:.0f} kts\n"
               f"VS: {float(s.ac.vs[i]) / aero.fpm:.0f} fpm")
        # POS also selects this aircraft's route for the ROUTEDATA
        # stream (reference traffic.py:587 poscommand -> scr.showroute)
        sim.scr.showroute(acname(i))
        return True, txt

    def defwpt(name, pos, wptype=None):
        """DEFWPT wpname,lat,lon[,type] (navdatabase.py defwpt)."""
        sim.navdb.defwpt(name, pos[0], pos[1], wptype or "DEF")
        # GUI mirror (reference navdatabase.py:136 -> scr.addnavwpt)
        sim.scr.addnavwpt(name.upper(), pos[0], pos[1])
        return True, f"Waypoint {name.upper()} defined at " \
                     f"{pos[0]:.4f}, {pos[1]:.4f}"

    def navdbinfo(txt):
        """WPTINFO name: resolve a named position via the navdb."""
        ndb = sim.navdb
        i = ndb.getaptidx(txt)
        if i >= 0:
            return True, (f"{txt.upper()}: airport {ndb.aptname[i]} at "
                          f"{ndb.aptlat[i]:.4f}, {ndb.aptlon[i]:.4f}, "
                          f"elev {ndb.aptelev[i]:.0f} m")
        i = ndb.getwpidx(txt)
        if i >= 0:
            return True, (f"{txt.upper()}: {ndb.wptype[i]} at "
                          f"{ndb.wplat[i]:.4f}, {ndb.wplon[i]:.4f}")
        return False, f"{txt}: not found in navdb"

    def dist(pos1, pos2):
        from ..core.route import _host_qdrdist_nm
        d = _host_qdrdist_nm(pos1[0], pos1[1], pos2[0], pos2[1])
        return True, f"Dist = {d:.3f} nm"

    def calc(*expr):
        try:
            allowed = {"__builtins__": {}, "abs": abs, "min": min, "max": max}
            value = eval(" ".join(str(e) for e in expr if e is not None),
                         allowed, {})
            return True, f"Ans = {value}"
        except Exception as e:
            return False, f"CALC error: {e}"

    # --------------------------------------------------------------- route
    def setlnav(idx, flag=None):
        """LNAV acid,[on/off] (autopilot.py:444-461)."""
        if flag is None:
            on = bool(st().ac.swlnav[idx])
            return True, f"{acname(idx)}: LNAV is {'ON' if on else 'OFF'}"
        if flag:
            r = sim.routes.route(idx)
            if r.nwp <= 0:
                return False, f"LNAV {acname(idx)}: no waypoints"
            if not bool(st().ac.swlnav[idx]):
                setslot("swlnav", idx, True)
                iact = sim.routes.findact(idx)
                if iact >= 0:
                    sim.routes.direct(idx, sim.routes.route(idx).name[iact])
        else:
            setslot("swlnav", idx, False)
        return True

    def setvnav(idx, flag=None):
        """VNAV acid,[on/off] (autopilot.py:463-485)."""
        if flag is None:
            on = bool(st().ac.swvnav[idx])
            return True, f"{acname(idx)}: VNAV is {'ON' if on else 'OFF'}"
        if flag:
            if not bool(st().ac.swlnav[idx]):
                return False, f"{acname(idx)}: VNAV ON requires LNAV ON"
            if sim.routes.route(idx).nwp <= 0:
                return False, f"VNAV {acname(idx)}: no waypoints"
            setslot("swvnav", idx, True)
            sim.routes.sync(idx, point_active=True)
        else:
            setslot("swvnav", idx, False)
        return True

    def addwpt(idx, pos, alt=None, spd=None, afterwp=None):
        """ADDWPT acid,(wpt/lat,lon),[alt,spd,afterwp] (route.py:472)."""
        from ..core.route import WPT_LATLON, WPT_RWY
        # FLYBY/FLYOVER are turn-mode KEYWORDS, not waypoints
        # (reference route.py:77-92; the wppos argtype preserves them)
        if _turnmode_kw(idx, pos):
            return True
        lat, lon = pos
        # navdb-resolved positions carry their name (NamedPos)
        name = getattr(pos, "name", None) \
            or f"WP{sim.routes.route(idx).nwp + 1:03d}"
        # APT/RWNN threshold waypoints are runway-typed (route.py:472
        # runway branch) so the landing chain can engage
        wtype = WPT_RWY if "/" in name else WPT_LATLON
        wpidx = sim.routes.addwpt(idx, name, lat, lon,
                                  alt if alt is not None else -999.0,
                                  spd if spd is not None else -999.0,
                                  wtype, None, afterwp)
        if wpidx < 0:
            return False, "ADDWPT: afterwp not found"
        # First waypoint: engage LNAV and aim at it (route.py addwpt behavior)
        r = sim.routes.route(idx)
        if r.nwp == 1 or not bool(st().ac.swlnav[idx]):
            sim.routes.direct(idx, r.name[r.iactwp if r.iactwp >= 0 else 0])
        return True

    def dest_orig(cmd, idx, pos=None):
        """DEST/ORIG acid,[apt[/rwy]/lat,lon] (autopilot.py:360-442)."""
        from ..core.route import WPT_DEST, WPT_ORIG, WPT_RWY
        r = sim.routes.route(idx)
        if pos is None:
            return True, f"{cmd} {acname(idx)}: (not set)"
        lat, lon = pos
        wtype = WPT_DEST if cmd == "DEST" else WPT_ORIG
        name = getattr(pos, "name", None) or cmd
        if cmd == "DEST" and "/" in name:
            # Runway destination (autopilot.py setdestorig runway branch):
            # the final waypoint is the displaced threshold, typed RWY so
            # the landing chain (sim._check_runway_landings) engages.
            wtype = WPT_RWY
        sim.routes.addwpt(idx, name if wtype == WPT_RWY else cmd,
                          lat, lon, 0.0,
                          float(st().ac.cas[idx]), wtype,
                          as_dest=(cmd == "DEST"))
        if cmd == "DEST":
            r = sim.routes.route(idx)
            if r.nwp == 1 or (r.nwp == 2 and r.wtype[0] == WPT_ORIG):
                setslot("swlnav", idx, True)
                setslot("swvnav", idx, True)
                # the new final waypoint may be named DEST or APT/RWNN
                sim.routes.direct(idx, r.name[-1])
        return True

    def delwpt(idx, name):
        ok = sim.routes.delwpt(idx, name)
        return (True,) if ok else (False, f"Waypoint {name} not found")

    def direct(idx, name):
        ok = sim.routes.direct(idx, name)
        return (True,) if ok else (False, f"Waypoint {name} not in route")

    def listrte(idx):
        r = sim.routes.route(idx)
        if r.nwp == 0:
            return True, f"{acname(idx)}: route is empty"
        lines = []
        for w in range(r.nwp):
            mark = "*" if w == r.iactwp else " "
            alttxt = f" FL{r.alt[w] / aero.ft / 100:.0f}" if r.alt[w] >= 0 else ""
            spdtxt = f" {r.spd[w] / aero.kts:.0f}kt" if r.spd[w] >= 0 else ""
            lines.append(f"{mark}{r.name[w]} ({r.lat[w]:.4f}, {r.lon[w]:.4f})"
                         f"{alttxt}{spdtxt}")
        return True, "\n".join(lines)

    # ---------------------------------------------------------------- ASAS
    def _setasas(**kw):
        sim.cfg = sim.cfg._replace(asas=sim.cfg.asas._replace(**kw))

    def asas_onoff(flag=None):
        if flag is None:
            return True, f"ASAS is {'ON' if sim.cfg.asas.swasas else 'OFF'}"
        _setasas(swasas=bool(flag))
        return True

    def reso(method=None):
        """RESO [method]: MVP/EBY/SWARM/SSD/OFF/ON (asas.py CRmethods
        registry, asas.py:41-55)."""
        if method is None:
            cfg = sim.cfg.asas
            return True, f"RESO {cfg.reso_method if cfg.reso_on else 'OFF'}"
        m = method.upper()
        if m == "ON":
            _setasas(reso_on=True)
            return True
        if m in ("MVP", "EBY", "SWARM", "SSD"):
            # Every resolver runs on every CD backend (reference
            # asas.py:41-55 keeps CD and CR orthogonal): MVP/EBY via
            # pair sums, SWARM via in-kernel neighbour sums, SSD from
            # the partner table (cr_ssd.resolve_from_partners).
            _setasas(reso_on=True, reso_method=m)
            return True
        if m in ("OFF", "NONE", "DONOTHING"):
            _setasas(reso_on=False)
            return True
        return False, (f"RESO method {method} not available "
                       "(have: MVP, EBY, SWARM, SSD, OFF)")

    def zoner(r=None):
        if r is None:
            return True, f"ZONER = {sim.cfg.asas.rpz / aero.nm:.2f} nm"
        _setasas(rpz=float(r) * aero.nm)
        return True

    def zonedh(h=None):
        if h is None:
            return True, f"ZONEDH = {sim.cfg.asas.hpz / aero.ft:.0f} ft"
        _setasas(hpz=float(h) * aero.ft)
        return True

    def rszoner(r=None):
        if r is None:
            return True, f"RSZONER = {sim.cfg.asas.rpz * sim.cfg.asas.resofach / aero.nm:.2f} nm"
        _setasas(resofach=float(r) * aero.nm / sim.cfg.asas.rpz)
        return True

    def rszonedh(h=None):
        if h is None:
            return True, "RSZONEDH"
        _setasas(resofacv=float(h) * aero.ft / sim.cfg.asas.hpz)
        return True

    def dtlook(t=None):
        if t is None:
            return True, f"DTLOOK = {sim.cfg.asas.dtlookahead:.0f} s"
        _setasas(dtlookahead=float(t))
        return True

    def dtnolook(t=None):
        if t is None:
            return True, f"DTNOLOOK = {sim.cfg.asas.dtasas:.2f} s"
        _setasas(dtasas=float(t))
        return True

    def rmethh(method=None):
        """RMETHH [SPD/HDG/BOTH/OFF]: horizontal resolution limiting."""
        if method is None:
            return True, "RMETHH"
        m = method.upper()
        if m in ("BOTH", "ON"):
            _setasas(swresohoriz=True, swresospd=True, swresohdg=True,
                     swresovert=False)
        elif m == "SPD":
            _setasas(swresohoriz=True, swresospd=True, swresohdg=False,
                     swresovert=False)
        elif m == "HDG":
            _setasas(swresohoriz=True, swresospd=False, swresohdg=True,
                     swresovert=False)
        elif m in ("OFF", "NONE"):
            _setasas(swresohoriz=False, swresospd=False, swresohdg=False)
        return True

    def rmethv(method=None):
        if method is None:
            return True, "RMETHV"
        m = method.upper()
        _setasas(swresovert=m in ("V/S", "VS", "ON", "BOTH"),
                 swresohoriz=False if m in ("V/S", "VS", "ON", "BOTH")
                 else sim.cfg.asas.swresohoriz)
        return True

    def noreso(acids=None):
        """NORESO acid,...: toggle no-avoidance list (asas.py:360-376)."""
        s = st()
        if acids is None:
            traf.state = s.replace(asas=s.asas.replace(
                noreso=jnp.zeros_like(s.asas.noreso)))
            return True
        idx = traf.id2idx(acids)
        if idx < 0:
            return False, f"{acids} not found"
        cur = bool(s.asas.noreso[idx])
        traf.state = s.replace(asas=s.asas.replace(
            noreso=s.asas.noreso.at[idx].set(not cur)))
        return True

    def resooff(acids=None):
        s = st()
        if acids is None:
            traf.state = s.replace(asas=s.asas.replace(
                resooff=jnp.zeros_like(s.asas.resooff)))
            return True
        idx = traf.id2idx(acids)
        if idx < 0:
            return False, f"{acids} not found"
        cur = bool(s.asas.resooff[idx])
        traf.state = s.replace(asas=s.asas.replace(
            resooff=s.asas.resooff.at[idx].set(not cur)))
        return True

    def vlimits(flag=None, spd=None):
        if flag is None:
            return True, (f"ASAS limits [{sim.cfg.asas.vmin / aero.kts:.0f};"
                          f"{sim.cfg.asas.vmax / aero.kts:.0f}] kts")
        if flag.upper() == "MAX":
            _setasas(vmax=spd * aero.nm / 3600.0 if spd else sim.cfg.asas.vmax)
        else:
            _setasas(vmin=spd * aero.nm / 3600.0 if spd else sim.cfg.asas.vmin)
        return True

    def confinfo():
        s = st()
        nconf = int(s.asas.nconf_cur)
        nlos = int(s.asas.nlos_cur)
        from ..ops.cd import pairs_from_mask
        # inconf flags are device-side; pair extraction on demand
        return True, f"Current conflicts: {nconf} (LoS: {nlos})"

    # ----------------------------------------------------- sim-control cmds
    def op():
        sim.op()
        return True

    def hold():
        sim.pause()
        return True

    def ff(t=None):
        sim.fastforward(t)
        return True

    def setdt(dt=None):
        if dt is None:
            return True, f"DT = {sim.cfg.simdt}"
        sim.setdt(dt)
        return True

    def setdtmult(m=None):
        if m is None:
            return True, f"DTMULT = {sim.dtmult}"
        sim.setdtmult(m)
        return True

    def reset():
        sim.reset()
        return True

    def quitsim():
        sim.stop()
        return True

    def echo(*txt):
        return True, " ".join(str(t) for t in txt if t is not None)

    def seed(value):
        traf._rng = np.random.default_rng(int(value))
        s = st()
        import jax
        traf.state = s.replace(rng=jax.random.PRNGKey(int(value)))
        return True

    def noise(flag=None):
        if flag is None:
            on = sim.cfg.noise.turb_active
            return True, f"Noise is {'ON' if on else 'OFF'}"
        sim.cfg = sim.cfg._replace(noise=sim.cfg.noise._replace(
            turb_active=bool(flag), adsb_transnoise=bool(flag),
            adsb_truncated=bool(flag)))
        return True

    def wind(pos, *args):
        """WIND lat,lon,dir,spd[,alt,dir,spd...] (windsim.py:8-53).

        Without altitude triples: a constant-profile point.  With them: an
        altitude-dependent profile point.
        """
        lat, lon = pos
        vals = [a for a in args if a is not None]
        try:
            if len(vals) == 2:
                newwind = windmod.add_point(st().wind, lat, lon,
                                            float(vals[0]), float(vals[1]) * aero.kts)
            elif len(vals) >= 3 and len(vals) % 3 == 0:
                alts, dirs, spds = [], [], []
                for k in range(0, len(vals), 3):
                    alts.append(float(vals[k]))
                    dirs.append(float(vals[k + 1]))
                    spds.append(float(vals[k + 2]) * aero.kts)
                newwind = windmod.add_point(st().wind, lat, lon, dirs, spds,
                                            windalt=alts)
            else:
                return False, "WIND: expected dir,spd or alt,dir,spd triples"
        except ValueError as e:
            return False, f"WIND: {e}"
        traf.state = st().replace(wind=newwind)
        sim.cfg = sim.cfg._replace(use_wind=True)
        return True

    def creconfs(acid, actype, targetidx, dpsi, cpa, tlosh, dh=None,
                 tlosv=None, spd=None):
        traf.creconfs(acid, actype, targetidx, dpsi, cpa, tlosh, dh, tlosv,
                      spd, pzr_nm=sim.cfg.asas.rpz / aero.nm,
                      pzh_ft=sim.cfg.asas.hpz / aero.ft)
        return True

    def benchmark(fname=None, t=None):
        return sim.benchmark(fname or "IC", t or 60.0)

    def scen(name):
        return stack.scen(name)

    def pcall(fname, *pargs):
        args = [str(a) for a in pargs if a is not None]
        rel = bool(args and args[0].upper() == "REL")
        if rel:
            args = args[1:]
        return stack.openfile(fname, args, mergeWithExisting=True,
                              t_offset=sim.simt if rel else 0.0)

    def schedule(t, *cmdwords):
        return stack.sched_cmd(
            t, " ".join(str(c) for c in cmdwords if c is not None),
            relative=False)

    def delay(dt, *cmdwords):
        return stack.sched_cmd(
            dt, " ".join(str(c) for c in cmdwords if c is not None),
            relative=True)

    def ic(fname=None):
        return stack.ic(fname or "")

    def saveic(fname=None):
        return stack.saveic(fname)

    def bank(idx, angle=None):
        if angle is None:
            return True, f"BANK {acname(idx)}: {np.degrees(float(st().ac.bank[idx])):.0f} deg"
        setslot("bank", idx, float(np.radians(angle)))
        setslot("aphi", idx, float(np.radians(angle)))
        return True

    def syn(subcmd=None, *args):
        return synthetic.process(sim, subcmd, [a for a in args if a is not None])

    # ----------------------------------- areas / conditionals / trails
    def _flat(*vals):
        """Flatten (lat, lon) tuples + scalars into the reference's flat
        coordinate list, dropping empty optionals."""
        out = []
        for v in vals:
            if v is None:
                continue
            if isinstance(v, tuple):
                out.extend(v)
            else:
                out.append(v)
        return out

    def boxcmd(name, p0, p1, top=None, bottom=None):
        """BOX name,lat,lon,lat,lon,[top,bottom] (stack.py:266-269)."""
        return sim.areas.defineArea(
            name, "BOX", _flat(p0, p1),
            top if top is not None else 1e9,
            bottom if bottom is not None else -1e9)

    def circlecmd(name, p, radius, top=None, bottom=None):
        """CIRCLE name,lat,lon,radius[nm],[top,bottom] (stack.py:290-293)."""
        return sim.areas.defineArea(
            name, "CIRCLE", _flat(p, radius),
            top if top is not None else 1e9,
            bottom if bottom is not None else -1e9)

    def polycmd(name, *pts):
        """POLY name,lat,lon,lat,lon,... (stack.py:577-580)."""
        coords = _flat(*pts)
        if len(coords) < 6:
            return False, "POLY needs at least 3 points"
        return sim.areas.defineArea(name, "POLY", coords)

    def polyaltcmd(name, top, bottom, *pts):
        """POLYALT name,top,bottom,lat,lon,... (stack.py:583-586)."""
        coords = _flat(*pts)
        if len(coords) < 6:
            return False, "POLYALT needs at least 3 points"
        return sim.areas.defineArea(name, "POLY", coords, top, bottom)

    def linecmd(name, *pts):
        """LINE/POLYLINE name,lat,lon,lat,lon[,...] (stack.py:469-472,
        589-592 — POLYLINE is a LINE shape with more points)."""
        coords = _flat(*pts)
        if len(coords) < 4:
            return False, "LINE needs at least 2 points"
        return sim.areas.defineArea(name, "LINE", coords)

    def delcmd(name):
        """DEL acid/ALL/WIND/shape (stack.py:321-327)."""
        u = str(name).upper()
        if u == "ALL":
            return delall()
        if u == "WIND":
            traf.state = st().replace(wind=windmod.make_windstate(
                dtype=traf.dtype))
            return True, "Wind field cleared"
        i = traf.id2idx(u)
        if isinstance(i, int) and i >= 0:
            return delete(i)
        for nm_ in (name, u):
            if sim.areas.hasArea(nm_):
                sim.areas.deleteArea(nm_)
                return True, f"Deleted area {nm_}"
        return False, f"{name}: no such aircraft or area"

    def atalt(idx, targalt, cmdtxt):
        sim.cond.ataltcmd(idx, targalt, cmdtxt)
        return True, f"ATALT armed for {acname(idx)}"

    def atspd(idx, targspd, cmdtxt):
        sim.cond.atspdcmd(idx, targspd, cmdtxt)
        return True, f"ATSPD armed for {acname(idx)}"

    def trailcmd(a0=None, a1=None):
        """TRAIL ON/OFF [dt] or TRAIL acid color (stack.py:734-739)."""
        tr = traf.trails
        if a0 is None:
            return tr.setTrails()
        u = str(a0).upper()
        if u in ("ON", "TRUE", "YES", "1"):
            return tr.setTrails(True, a1)
        if u in ("OFF", "FALSE", "NO", "0"):
            return tr.setTrails(False)
        if u == "CLEAR":
            return tr.setTrails("CLEAR")
        idx = traf.id2idx(u)
        if isinstance(idx, int) and idx >= 0:
            return tr.setTrails(idx, a1)
        return False, "Usage: TRAIL ON/OFF,[dt] or TRAIL acid,color"

    # -------------------------------------------- route editing (FMS)
    _TURNMODE = ("FLYBY", "FLY-BY", "FLYOVER", "FLY-OVER")

    def _turnmode_kw(idx, pos):
        """FLYBY/FLYOVER keyword via any route-editing command toggles
        the route turn mode (reference routes all ADDWPT forms through
        addwptStack, route.py:77-92).  Returns True when handled."""
        if getattr(pos, "name", "") in _TURNMODE:
            sim.routes.route(idx).swflyby = \
                getattr(pos, "name", "") in ("FLYBY", "FLY-BY")
            return True
        return False

    def _resolve_wpt(token, idx):
        """wpt token -> (name, lat, lon): the 'latlon' argtype always
        yields a tuple — plain for numeric pairs, NamedPos (carrying the
        waypoint name) for navdb-resolved positions."""
        lat, lon = token
        name = getattr(token, "name", None) \
            or f"WP{sim.routes.route(idx).nwp + 1:03d}"
        return name, lat, lon

    def after(idx, afterwp, sub, wpt, alt=None, spd=None):
        """acid AFTER afterwp ADDWPT wpt,[alt,spd] (route.py
        afteraddwptStack)."""
        if str(sub).upper() != "ADDWPT":
            return False, "Syntax: acid AFTER wpname ADDWPT wpname"
        from ..core.route import WPT_LATLON
        if _turnmode_kw(idx, wpt):
            return True
        name, lat, lon = _resolve_wpt(wpt, idx)
        wpidx = sim.routes.addwpt(idx, name, lat, lon,
                                  alt if alt is not None else -999.0,
                                  spd if spd is not None else -999.0,
                                  WPT_LATLON, None, afterwp)
        if wpidx < 0:
            return False, f"AFTER: {afterwp} not in route"
        return True

    def before(idx, beforewp, sub, wpt, alt=None, spd=None):
        """acid BEFORE beforewp ADDWPT wpt,[alt,spd] (route.py
        beforeaddwptStack)."""
        if str(sub).upper() != "ADDWPT":
            return False, "Syntax: acid BEFORE wpname ADDWPT wpname"
        if _turnmode_kw(idx, wpt):
            return True
        name, lat, lon = _resolve_wpt(wpt, idx)
        wpidx = sim.routes.addwpt_before(
            idx, beforewp, name, lat, lon,
            alt if alt is not None else -999.0,
            spd if spd is not None else -999.0)
        if wpidx < 0:
            return False, f"BEFORE: {beforewp} not in route"
        return True

    def atwpt(idx, wpname, what=None, value=None):
        """acid AT wpname [DEL] SPD/ALT [val] (route.py atwptStack)."""
        if what is not None and str(what).upper() == "ALT" \
                and value is not None:
            value = txt2alt(str(value))
        elif what is not None and str(what).upper() == "SPD" \
                and value is not None:
            value = txt2spd(str(value))
        return sim.routes.atwpt(idx, wpname, what, value)

    def delrte(idx):
        sim.routes.delrte(idx)
        setslot("swlnav", idx, False)
        setslot("swvnav", idx, False)
        return True

    def dumprte(idx):
        fname = sim.routes.dumproute(idx, acname(idx))
        return True, f"Route written to {fname}"

    # ---------------------------------------------------- info / misc
    def airway(wp):
        """AIRWAY wp/airway (traffic.py airwaycmd)."""
        navdb = sim.navdb
        awid = wp.upper()
        segs = navdb.listairway(awid)
        if segs:
            txt = f"Airway {awid}: " + " - ".join(
                " ".join(leg) for leg in segs)
            return True, txt
        conns = navdb.listconnections(awid)
        if conns:
            return True, f"Connections of {awid}: " + ", ".join(
                f"{aw}>{wpto}" for aw, wpto in conns)
        return False, f"{wp}: no airway or connections found"

    def listac():
        ids = [i for i in traf.ids if i is not None]
        return True, "Aircraft: " + (", ".join(ids) if ids else "(none)")

    def getwind(pos, alt=None):
        lat, lon = pos
        vn, ve = windmod.getdata(st().wind, jnp.asarray([lat]),
                                 jnp.asarray([lon]),
                                 jnp.asarray([alt or 0.0]))
        vn, ve = float(vn[0]), float(ve[0])
        spd = float(np.hypot(vn, ve))
        direc = float(np.degrees(np.arctan2(ve, vn)) % 360.0)
        # wind FROM direction (meteo convention, windsim.py get)
        return True, (f"Wind at ({lat:.4f}, {lon:.4f}): "
                      f"{(direc + 180.0) % 360.0:03.0f} deg, "
                      f"{spd / aero.kts:.1f} kts")

    def engcmd(idx, engid=None):
        """ENG acid,[engine_id] (perfbase engchange contract)."""
        actype = traf.types[idx] or "NA"
        avail = traf.coeffdb.get(actype).get("engines_avail", {})
        if engid is None:
            names = ", ".join(avail) if avail else "(no data)"
            return True, f"{acname(idx)} ({actype}) engines: {names}"
        e = avail.get(engid.upper())
        if e is None:
            return False, f"{engid}: not an engine of {actype}"
        from ..models.perf_coeffs import _ff_quadratic
        ffa, ffb, ffc = _ff_quadratic(e["ff_idl"], e["ff_app"],
                                      e["ff_co"], e["ff_to"])
        perf = st().perf
        traf.state = st().replace(perf=perf.replace(
            engthrust=perf.engthrust.at[idx].set(e["thr"]),
            engbpr=perf.engbpr.at[idx].set(e["bpr"]),
            ff_a=perf.ff_a.at[idx].set(ffa),
            ff_b=perf.ff_b.at[idx].set(ffb),
            ff_c=perf.ff_c.at[idx].set(ffc)))
        return True, f"{acname(idx)}: engine set to {engid.upper()}"

    def nom(idx):
        """NOM acid: reset to nominal performance accel (traffic.nom)."""
        setslot("ax", idx, aero.kts)
        return True

    def cdcmd(path=None):
        """CD [path]: change the scenario folder (stack.py setscenpath)."""
        if path is None:
            return True, f"Scenario path: {stack.scenario_path}"
        import os as _os
        if not _os.path.isdir(path):
            return False, f"{path}: not a directory"
        stack.scenario_path = path
        return True

    def cdmethod(method=None):
        """CDMETHOD [method] (asas.SetCDmethod); detection backends map
        to SimConfig.cd_backend."""
        if method is None:
            return True, f"CDMETHOD {sim.cfg.cd_backend.upper()}"
        m = method.upper()
        table = {"STATEBASED": "dense", "DENSE": "dense",
                 "TILED": "tiled", "PALLAS": "pallas", "SPARSE": "sparse"}
        if m not in table:
            return False, (f"CDMETHOD {method} not available "
                           "(have: STATEBASED/DENSE, TILED, PALLAS, "
                           "SPARSE)")
        if table[m] != sim.cfg.cd_backend:
            # sort_perm semantics differ per backend (Morton permutation
            # vs stripe destinations); the identity layout is valid for
            # both, and Simulation.update force-refreshes on backend
            # change.  The partner tables are cleared too: caller-space
            # ids (partners) and sorted-space ids (partners_s) are not
            # interchangeable, and a later refresh would remap stale
            # sorted-space rows onto the wrong aircraft.  Hysteresis
            # re-establishes within one CD interval.
            st = sim.traf.state
            sim.traf.state = st.replace(asas=st.asas.replace(
                sort_perm=jnp.arange(st.asas.sort_perm.shape[0],
                                     dtype=jnp.int32),
                partners=jnp.full_like(st.asas.partners, -1),
                partners_s=jnp.full_like(st.asas.partners_s, -1)))
        sim.cfg = sim.cfg._replace(cd_backend=table[m])
        return True

    def asasv(minmax=None, spd=None):
        """ASASV MAX/MIN SPD (asas.SetVLimits; TAS in kts)."""
        if minmax is None:
            c = sim.cfg.asas
            return True, (f"ASAS speed limits: {c.vmin / aero.kts:.0f}"
                          f"-{c.vmax / aero.kts:.0f} kts")
        mm = minmax.upper()
        if spd is None or mm not in ("MIN", "MAX"):
            return False, "Usage: ASASV MAX/MIN spd (kts)"
        if mm == "MIN":
            _setasas(vmin=float(spd) * aero.kts)
        else:
            _setasas(vmax=float(spd) * aero.kts)
        return True

    def priorules(flag=None, priocode=None):
        """PRIORULES [ON/OFF PRIOCODE] (asas.SetPrio + MVP.py:235-300)."""
        if flag is None:
            c = sim.cfg.asas
            return True, (f"PRIORULES {'ON' if c.swprio else 'OFF'} "
                          f"{c.priocode}")
        if sim.cfg.cd_backend != "dense" and flag:
            return False, ("PRIORULES needs the dense CD backend "
                           "(per-pair priority masks)")
        kw = dict(swprio=bool(flag))
        if priocode is not None:
            pc = priocode.upper()
            # FF*/LAY* feed the MVP priority masks (MVP.py:235-300);
            # RS1-RS9 select the SSD ruleset (SSD.py:429-558)
            if pc not in ("FF1", "FF2", "FF3", "LAY1", "LAY2",
                          "RS1", "RS2", "RS3", "RS4", "RS5", "RS6",
                          "RS7", "RS8", "RS9"):
                return False, (f"Priority code {priocode} not understood;"
                               " use FF1/FF2/FF3/LAY1/LAY2 (MVP) or "
                               "RS1..RS9 (SSD)")
            kw["priocode"] = pc
        _setasas(**kw)
        return True

    def rfach(factor=None):
        if factor is None:
            return True, f"RFACH {sim.cfg.asas.resofach}"
        _setasas(resofach=float(factor))
        return True

    def rfacv(factor=None):
        if factor is None:
            return True, f"RFACV {sim.cfg.asas.resofacv}"
        _setasas(resofacv=float(factor))
        return True

    # ------------------------------------------------- time / sim ctrl
    def timecmd(arg=None):
        return sim.setutc(arg) if arg is not None else (
            True, f"Simulation time: {sim.utc.isoformat(' ')}")

    def datecmd(*args):
        args = [a for a in args if a is not None]
        if not args:
            return True, f"Date: {sim.utc.date().isoformat()}"
        return sim.setutc(*args)

    def fixdt(flag, tend=None):
        return sim.setFixdt(flag, tend)

    def addnodes(n):
        """ADDNODES n (server worker spawn; sim.addnodes on nodes)."""
        fn = getattr(sim, "addnodes", None)
        if fn is None:
            # informative no-op, not a syntax error
            return True, "ADDNODES: no server attached (headless sim)"
        fn(int(n))
        return True

    def batchcmd(fname):
        """BATCH scenario (sim.batch on nodes; server farm-out)."""
        fn = getattr(sim, "batch", None)
        if fn is None:
            return True, "BATCH: no server attached (headless sim)"
        return fn(fname)

    # ------------------------------------------------- display state
    def pan(arg, lon=None):
        """PAN lat lon / acid / waypoint / LEFT/RIGHT/UP/DOWN
        (scr.pan; raw tokens, resolved here like the reference's
        pandir/latlon union)."""
        a = str(arg).upper()
        if lon is not None:
            try:
                return sim.scr.pan(float(a), float(lon))
            except ValueError:
                pass
        step = 0.5
        moves = {"LEFT": (0.0, -step), "RIGHT": (0.0, step),
                 "UP": (step, 0.0), "ABOVE": (step, 0.0),
                 "DOWN": (-step, 0.0)}
        if a in moves:
            dlat, dlon = moves[a]
            return sim.scr.pan(sim.scr.ctrlat + dlat,
                               sim.scr.ctrlon + dlon)
        i = traf.id2idx(a)
        if isinstance(i, int) and i >= 0:
            return sim.scr.pan(float(st().ac.lat[i]),
                               float(st().ac.lon[i]))
        pos = sim.navdb.txt2pos(a, sim.scr.ctrlat, sim.scr.ctrlon)
        if pos is not None:
            return sim.scr.pan(pos[0], pos[1])
        return False, f"PAN: {arg} not found"

    def zoom(factor):
        f = str(factor).upper()
        if f == "IN":
            return sim.scr.zoom(1.4142135623730951)
        if f == "OUT":
            return sim.scr.zoom(0.7071067811865475)
        try:
            return sim.scr.zoom(float(factor), True)
        except (TypeError, ValueError):
            return False, "Usage: ZOOM IN/OUT or factor"

    def swrad(sw, dt=None):
        return sim.scr.feature(sw, dt)

    def filteralt(flag, bottom=None, top=None):
        return sim.scr.filteralt(flag, bottom, top)

    def insedit(txt=""):
        return sim.scr.cmdline(txt)

    def nd(acid_txt=None):
        return sim.scr.shownd(acid_txt)

    def symbol():
        return sim.scr.symbol()

    def tmx():
        return True, "TMX command not (yet?) implemented."

    def screenshot(fname=None):
        """SCREENSHOT [fname]: SVG radar render of the current state
        (ui/radar.py — the headless RadarWidget)."""
        import os as _os
        from .. import settings as _settings
        from ..ui import radar
        if fname is None:
            _os.makedirs(_settings.log_path, exist_ok=True)
            fname = _os.path.join(_settings.log_path,
                                  f"radar_{sim.simt:08.1f}.svg")
        radar.render_sim(sim, fname)
        return True, f"Radar snapshot written to {fname}"

    def metricscmd(flag=None, dt=None):
        """Bare/OFF/1/2 keep the reference sector-metrics behavior;
        METRICS DUMP reads the ISSUE-11 telemetry registry — the local
        sim's series, plus (networked) the server's broker + fleet
        aggregate, which arrives as a METRICS event."""
        if flag is not None and str(flag).upper() == "DUMP":
            node = getattr(sim, "node", None)
            if node is not None and getattr(node, "event_io", None) \
                    is not None:
                node.send_event(b"METRICS", None)  # -> server registries
                return True, ("sim registry:\n" + sim.obs.text()
                              + "\n(server+fleet registries requested "
                                "— echoed when the reply arrives)")
            return True, "sim registry:\n" + sim.obs.text()
        return sim.metrics.toggle(flag, dt)

    def tracecmd(sub=None):
        """TRACE [ON/OFF/DUMP]: the flight recorder (obs/trace.py) —
        bounded span ring dumped as Chrome/Perfetto trace-event JSON;
        merge multi-process dumps with scripts/trace_report.py."""
        rec = sim.recorder
        if sub is None:
            return True, (f"TRACE {'ON' if rec.enabled else 'OFF'} "
                          f"({len(rec)}/{rec.maxlen} events buffered)")
        s = str(sub).upper()
        if s in ("ON", "1", "TRUE"):
            rec.enable()
            return True, "Flight recorder ON"
        if s in ("OFF", "0", "FALSE"):
            rec.disable()
            return True, (f"Flight recorder OFF "
                          f"({len(rec)} buffered events kept)")
        if s == "DUMP":
            path = rec.dump(reason="manual", proc="sim")
            node = getattr(sim, "node", None)
            if node is not None and getattr(node, "event_io", None) \
                    is not None:
                node.send_event(b"TRACE", None)  # server dumps its ring
            if path is None:
                return True, "TRACE DUMP: ring is empty, nothing written"
            return True, f"Trace written to {path}"
        return False, "TRACE [ON/OFF/DUMP]"

    def profile(sub=None, arg=None, arg2=None):
        """PROFILE START [dir] / STOP / KERNELS [nsteps] / DEVICE ...
        (jax.profiler trace + per-kernel timing report; TRACE is a
        synonym for the flight-recorder command)."""
        from ..utils import profiler
        s = (sub or "KERNELS").upper()
        if s == "START":
            logdir = profiler.start_trace(arg or "output/jax-trace")
            return True, f"JAX trace capturing to {logdir}"
        if s == "STOP":
            profiler.stop_trace()
            return True, "JAX trace stopped"
        if s == "TRACE":
            return tracecmd(arg)
        if s == "DEVICE":
            # ISSUE-12 device-trace window (obs/devprof.py): bracket the
            # next n chunk dispatches with a jax.profiler trace and
            # per-chunk compute/halo/edge attribution; the window is a
            # device_profile recorder span tagged with the trace dir so
            # scripts/devprof_report.py merges host + XLA timelines.
            if sim.devprof.window_active:
                return False, ("PROFILE DEVICE: a window is already "
                               "active")
            try:
                n = int(float(arg)) if arg else 1
            except (TypeError, ValueError):
                return False, "PROFILE DEVICE [n_chunks] [dir]"
            if n < 1:
                return False, f"PROFILE DEVICE: need n >= 1, got {n}"
            logdir = sim.devprof.request_window(n, arg2)
            node = getattr(sim, "node", None)
            if node is not None and getattr(node, "event_io", None) \
                    is not None:
                # journal the window server-side (audit record, ignored
                # by replay's queue math)
                node.send_event(b"DEVPROF", {"dir": logdir,
                                             "chunks": n})
            return True, (f"PROFILE DEVICE: tracing the next {n} "
                          f"chunk(s) to {logdir}")
        if s == "KERNELS":
            if traf.ntraf == 0:
                return False, "PROFILE KERNELS: no traffic"
            nsteps = int(float(arg)) if arg else 50
            return True, profiler.report(sim, nsteps)
        if s == "DEEP":
            # the round-3 decomposition sweep (ex scripts/profile_r3.py)
            if traf.ntraf == 0:
                return False, "PROFILE DEEP: no traffic"
            return True, profiler.deep_report(sim)
        return False, ("PROFILE START [dir] / STOP / KERNELS [nsteps] "
                       "/ DEEP / DEVICE [n] [dir] / TRACE [ON/OFF/DUMP]")

    def faultcmd(*args):
        """FAULT: chaos-injection harness (fault/harness.py) — poison
        state with NaN/Inf, flip guard policy, degrade the event
        transport, stall/kill/straggle the worker, truncate
        snapshots."""
        from ..fault import harness
        return harness.fault_command(sim, *args)

    def chunksteps(arg=None, onoff=None):
        """CHUNKSTEPS [n | PIPELINE ON/OFF]: interactive device-chunk
        length + async-pipeline toggle, with HEALTH-style readback."""
        if arg is None:
            ps = sim.pipe_stats
            reasons = ", ".join(
                f"{k}:{v}" for k, v in sorted(
                    ps["sync_reasons"].items())) or "-"
            return True, (
                f"CHUNKSTEPS {sim.chunk_steps} "
                f"(={sim.chunk_steps * sim.simdt:.2f} s sim/chunk, "
                f"pipeline {'ON' if sim.pipeline_enabled else 'OFF'}; "
                f"chunks: {ps['pipelined_chunks']} pipelined, "
                f"{ps['sync_chunks']} sync, "
                f"{ps['deferred_trips']} deferred guard trips; "
                f"sync fallbacks: {reasons})")
        if str(arg).upper() == "PIPELINE":
            if onoff is None:
                return True, (f"CHUNKSTEPS PIPELINE is "
                              f"{'ON' if sim.pipeline_enabled else 'OFF'}")
            sw = str(onoff).upper()
            if sw not in ("ON", "OFF", "TRUE", "FALSE", "1", "0"):
                return False, "CHUNKSTEPS PIPELINE ON/OFF"
            sim.pipeline_enabled = sw in ("ON", "TRUE", "1")
            if not sim.pipeline_enabled:
                sim.drain_pipeline()
            return True, (f"Chunk pipeline "
                          f"{'ON' if sim.pipeline_enabled else 'OFF'}")
        try:
            n = int(float(arg))
        except (TypeError, ValueError):
            return False, "CHUNKSTEPS [n | PIPELINE ON/OFF]"
        if n < 1:
            return False, f"CHUNKSTEPS: need n >= 1, got {n}"
        sim.chunk_steps = n
        note = "" if n in sim.CHUNK_LADDER else \
            " (off-ladder: compiles one extra scan program)"
        return True, (f"Chunk set to {n} steps "
                      f"(={n * sim.simdt:.2f} s sim){note}")

    def shardcmd(mode=None, ndev=None, halo=None):
        """SHARD [OFF | REPLICATE [n] | SPATIAL [n [halo]] | TILE RxC]:
        multi-chip decomposition, with HEALTH-style readback when
        called bare."""
        import jax as _jax
        usage = ("SHARD [OFF | REPLICATE [n] | SPATIAL [n [halo]] | "
                 "TILE RxC]")
        if mode is None:
            if sim.shard_mode == "off":
                return True, (f"SHARD OFF ({len(_jax.devices())} "
                              f"device(s) visible; modes: REPLICATE, "
                              "SPATIAL, TILE [sparse backend])")
            nd = sim._shard_ndev()
            msg = (f"SHARD {sim.shard_mode.upper()}: {nd} devices, "
                   f"backend {sim.cfg.cd_backend}")
            st = sim.shard_stats
            if sim.shard_mode == "spatial" and st:
                cnt = st.get("counts")
                imb = (float(cnt.max()) / max(float(cnt.mean()), 1e-9)
                       if cnt is not None and cnt.size else 0.0)
                msg += (
                    f"; stripes {st['nb_local']} blocks/device "
                    f"(nb={st['nb']}, extra={st['extra_blocks']}), "
                    f"occupancy {st['occupancy']:.0%} of shard cap, "
                    f"last-refresh imbalance {imb:.2f}x, "
                    f"halo {st['halo_blocks']} blocks/side "
                    f"(need {st['halo_need']}) = "
                    f"{st['halo_rows']} exchanged rows/interval, "
                    f"gsmax {st['gsmax']:.0f} m/s")
            elif sim.shard_mode == "tiles" and st:
                cnt = st.get("counts")
                imb = (float(cnt.max()) / max(float(cnt.mean()), 1e-9)
                       if cnt is not None and cnt.size else 0.0)
                tr, tc = st["tile_shape"]
                msg += (
                    f"; tiles {tr}x{tc} lat x lon "
                    f"({st['nb_local']} blocks/tile, nb={st['nb']}, "
                    f"extra={st['extra_blocks']}), "
                    f"occupancy {st['occupancy']:.0%} of shard cap, "
                    f"last-refresh imbalance {imb:.2f}x, "
                    f"halo budgets {tuple(st['budgets'])} blocks/offset "
                    f"(need {tuple(st['needs'])}) = "
                    f"{st['halo_rows']} exchanged rows/interval, "
                    f"gsmax {st['gsmax']:.0f} m/s")
            return True, msg
        m = str(mode).upper()
        if m in ("TILE", "TILES"):
            tiles, nd = None, 0
            if ndev is not None:
                ts = str(ndev).lower()
                if "x" in ts:
                    try:
                        r, c = ts.split("x", 1)
                        tiles = (int(r), int(c))
                        nd = tiles[0] * tiles[1]
                    except ValueError:
                        return False, usage
                else:
                    try:
                        nd = int(float(ndev))
                    except ValueError:
                        return False, usage
            try:
                sim.set_shard("tiles", nd, tiles=tiles)
            except (ValueError, RuntimeError) as e:
                return False, f"SHARD TILE: {e}"
            return shardcmd()
        if m not in ("OFF", "REPLICATE", "SPATIAL"):
            return False, usage
        try:
            nd = int(float(ndev)) if ndev is not None else 0
            hb = int(float(halo)) if halo is not None else 0
            sim.set_shard(m.lower(), nd, halo_blocks=hb)
        except (ValueError, RuntimeError) as e:
            return False, f"SHARD {m}: {e}"
        return shardcmd()

    def healthcmd():
        """HEALTH: serving-fabric introspection.  On a networked
        worker the server is queried (queue depth + per-client split,
        per-worker in-flight piece age / heartbeat staleness /
        progress rate, hedge + admission + stream-drop counters) and
        the reply is echoed when it arrives; a detached sim reports
        its local state."""
        node = getattr(sim, "node", None)
        if node is not None and getattr(node, "event_io", None) \
                is not None:
            node.send_event(b"HEALTH", None)   # empty route -> server
            return True, "HEALTH requested from the server"
        ps = sim.pipe_stats
        mh = sim.mesh_health()
        mesh_line = ""
        if mh["mode"] != "off" or mh["epoch"] > 0:
            mesh_line = (f"\nmesh: epoch {mh['epoch']}, "
                         f"{mh['devices']} device(s), mode {mh['mode']}"
                         + (f" {mh['tiles']}" if mh.get("tiles") else "")
                         + f", last refresh {mh['last_refresh_ms']:g} ms"
                         + (" [DEGRADED]" if mh["degraded"] else ""))
        sh = sim.scan_health()
        sim_line = ""
        if sh.get("scanstats"):
            if sh.get("steps"):
                ms = sh.get("min_sep_m")
                sim_line = (
                    f"\nsim: last chunk {sh['steps']} steps, conflicts "
                    f"peak {sh['conf_peak']}/mean {sh['conf_mean']:g}, "
                    f"LoS peak {sh['los_peak']}, min sep "
                    + (f"{ms:g} m" if ms is not None else "n/a")
                    + f", clamp-sat {sh['clamp_sat_ratio']:.1%}"
                    + f", occ peak {sh['occ_peak']}"
                    + (f" (imbalance {sh['occ_imbalance']:g}x)"
                       if sh.get("occ_imbalance", 1.0) != 1.0 else ""))
            else:
                sim_line = "\nsim: scanstats ON (no chunk drained yet)"
        return True, (f"detached sim: state {sim.state_flag}, simt "
                      f"{sim.simt_planned:.1f} s, {traf.ntraf} aircraft, "
                      f"{sim._step_count} steps done, chunks "
                      f"{ps['pipelined_chunks']} pipelined/"
                      f"{ps['sync_chunks']} sync"
                      + (", straggle STALLED"
                         if getattr(sim, 'straggle_stall', False)
                         else "") + mesh_line + sim_line
                      + f"\ncompiles: {sim.devprof.compile_summary()}")

    def scanstatscmd(flag=None):
        """SCANSTATS [ON/OFF]: in-scan telemetry — per-step device-side
        stats (conflict/LoS histograms, resolver engagement, envelope
        clamp saturation, min separation, stripe occupancy) folded
        through the chunk scan and drained at every edge.  Bare call
        reads back state + the newest chunk summary."""
        if flag is None:
            sh = sim.scan_health()
            if not sh.get("scanstats"):
                return True, "SCANSTATS OFF"
            if not sh.get("steps"):
                return True, "SCANSTATS ON (no chunk drained yet)"
            ms = sh.get("min_sep_m")
            hr = sh.get("alt_headroom_min_m")
            return True, (
                f"SCANSTATS ON: last chunk {sh['steps']} steps, "
                f"conflicts peak {sh['conf_peak']}/mean "
                f"{sh['conf_mean']:g}, LoS peak {sh['los_peak']}, "
                f"engaged peak {sh['engaged_peak']}, min sep "
                + (f"{ms:g} m" if ms is not None else "n/a")
                + ", headroom "
                + (f"{hr:g} m" if hr is not None else "n/a")
                + f", clamp-sat {sh['clamp_sat_ratio']:.1%}, occ peak "
                  f"{sh['occ_peak']}")
        on = str(flag).upper() in ("ON", "TRUE", "1", "YES")
        changed = sim.set_scanstats(on)
        state = "ON" if on else "OFF"
        return True, (f"SCANSTATS {state}"
                      + ("" if changed else " (unchanged)")
                      + (": next dispatch compiles the stats-carrying "
                         "chunk program" if changed and on else ""))

    def sortrefreshcmd(flag=None):
        """SORTREFRESH [ON/OFF]: in-scan sort refresh — the stripe
        re-sort (+ spatial re-bucket) folded into the compiled chunk
        instead of a host call at chunk edges.  Sparse backend only
        (tiled/pallas stays host-called).  Bare call reads back mode +
        retired refresh counters."""
        if flag is None:
            rh = sim.refresh_health()
            if not rh["inscan"]:
                return True, "SORTREFRESH OFF (host refresh at chunk edges)"
            mode = "active" if rh["active"] else \
                "armed (inactive: needs sparse backend)"
            t = rh["last_refresh_simt"]
            return True, (
                f"SORTREFRESH ON ({mode}): {rh['inscan_refreshes']} "
                f"in-scan refreshes retired, last at simt "
                + (f"{t:.1f} s" if t >= 0 else "n/a")
                + f", guard trips {rh['guard_trips']}")
        on = str(flag).upper() in ("ON", "TRUE", "1", "YES")
        changed = sim.set_inscan_refresh(on)
        state = "ON" if on else "OFF"
        return True, (f"SORTREFRESH {state}"
                      + ("" if changed else " (unchanged)")
                      + (": next dispatch compiles the refresh-carrying "
                         "chunk program" if changed and on else ""))

    def optcmd(tend=None, iters=None, lr=None, restarts=None):
        """OPT [tend,iters,lr,restarts]: gradient-based trajectory
        optimization of the current fleet (bluesky_tpu/diff/) — Adam
        descent on per-aircraft lateral-waypoint/time offsets via
        jax.value_and_grad over the checkpointed smooth step scan,
        verified against the hard LoS metric.  On a networked worker
        the result (optimized offsets + objective trace) is reported
        upstream as an OPTRESULT event the server journals against the
        in-flight BATCH piece; the sim then HOLDs, completing the
        piece.  Defaults from settings.opt_* knobs."""
        if traf.ntraf == 0:
            return False, "OPT: no traffic to optimize"
        try:
            res = sim.optimize_trajectories(tend, iters, lr, restarts)
        except (ValueError, RuntimeError) as e:
            return False, f"OPT: {e}"
        slots = np.nonzero(np.asarray(st().ac.active))[0].tolist()
        payload = res.to_payload(traf.ids, slots)
        node = getattr(sim, "node", None)
        if node is not None and getattr(node, "event_io", None) \
                is not None:
            node.send_event(b"OPTRESULT", payload)
        sim.pause()      # leave OP: a BATCH piece completes here
        ok = res.bad == -1
        return ok, (
            f"OPT: objective {res.objective[0]:.3f} -> "
            f"{res.objective[-1]:.3f} in {res.iters} iters "
            f"({res.restarts} restart(s), best {res.best_restart}); "
            f"hard LoS {res.hard_los_before} -> {res.hard_los_after}; "
            f"max |lateral| {float(np.abs(res.lateral_m).max()):.0f} m, "
            f"max |tshift| {float(np.abs(res.tshift_s).max()):.1f} s"
            + ("" if ok else f"; GUARD TRIP word {res.bad}"))

    def gradcmd(tend=None):
        """GRAD [tend]: one checked value_and_grad evaluation of the
        soft-LoS+fuel objective at zero offsets — reports the
        objective, gradient norm and the (backward-extended) guard
        word without descending."""
        if traf.ntraf == 0:
            return False, "GRAD: no traffic"
        from .. import settings as _settings
        from ..diff import optimize as diffopt
        sim.drain_pipeline()
        traf.flush()
        try:
            v, gnorm, bad = diffopt.grad_once(
                st(), sim.cfg.asas,
                tend=float(tend) if tend is not None
                else getattr(_settings, "opt_tend", 600.0),
                simdt=getattr(_settings, "opt_simdt", 1.0),
                chunk=getattr(_settings, "opt_chunk", 50))
        except (ValueError, RuntimeError) as e:
            return False, f"GRAD: {e}"
        return bad == -1, (
            f"GRAD: objective {v:.4f}, |grad| {gnorm:.4g}, guard "
            + ("clean" if bad == -1 else f"TRIPPED (word {bad})"))

    def worldscmd(arg=None, val=None):
        """WORLDS [ON/OFF | max n]: multi-world BATCH packing — pack
        compatible pieces into world-batches stepped as one stacked
        device program per worker (docs/PERF_ANALYSIS.md §multi-world).
        Bare WORLDS reads the server's packing state and counters back
        HEALTH-style; on a detached sim it reports the local settings
        defaults a future server would inherit."""
        from .. import settings as _settings
        node = getattr(sim, "node", None)
        networked = node is not None \
            and getattr(node, "event_io", None) is not None
        if arg is None:
            if networked:
                node.send_event(b"WORLDS", None)  # empty route -> server
                return True, "WORLDS requested from the server"
            return True, (
                f"detached sim: WORLDS packing "
                f"{'ON' if getattr(_settings, 'world_pack', False) else 'OFF'}"
                f", max {getattr(_settings, 'world_batch_max', 8)} "
                "pieces/dispatch (settings.world_pack / "
                "settings.world_batch_max; a server inherits these)")
        a = str(arg).upper()
        if a in ("ON", "OFF", "TRUE", "FALSE", "1", "0"):
            on = a in ("ON", "TRUE", "1")
            _settings.world_pack = on
            if networked:
                node.send_event(b"WORLDS", {"pack": on})
                return True, f"WORLDS packing {'ON' if on else 'OFF'} sent"
            return True, f"WORLDS packing {'ON' if on else 'OFF'}"
        if a == "MAX":
            try:
                n = int(float(val))
            except (TypeError, ValueError):
                return False, "WORLDS MAX n: need an integer n >= 1"
            if n < 1:
                return False, f"WORLDS MAX: need n >= 1, got {n}"
            _settings.world_batch_max = n
            if networked:
                node.send_event(b"WORLDS", {"max": n})
                return True, f"WORLDS max {n} pieces/dispatch sent"
            return True, f"WORLDS max {n} pieces/dispatch"
        return False, "WORLDS [ON/OFF | MAX n]"

    def mitigatecmd(arg=None):
        """MITIGATE [ON/OFF/STATUS]: the server's self-healing policy
        engine (network/mitigate.py) — structured health signals (SLO
        regressions, stragglers, degraded meshes, queue floods, memory
        watermarks) mapped to the existing actuators (hedge, shed,
        re-pack, accept-degraded) behind rate limits, backoff and a
        global budget.  Bare MITIGATE / MITIGATE STATUS reads the
        engine state back HEALTH-style; on a detached sim it reports
        the local settings default a future server would inherit."""
        from .. import settings as _settings
        node = getattr(sim, "node", None)
        networked = node is not None \
            and getattr(node, "event_io", None) is not None
        a = str(arg).upper() if arg is not None else ""
        if a in ("", "STATUS"):
            if networked:
                node.send_event(b"MITIGATE", None)  # empty route -> server
                return True, "MITIGATE status requested from the server"
            return True, (
                f"detached sim: mitigation "
                f"{'ON' if getattr(_settings, 'mitigate_enabled', False) else 'OFF'}"
                " (settings.mitigate_enabled; a server inherits this)")
        if a in ("ON", "OFF", "TRUE", "FALSE", "1", "0"):
            on = a in ("ON", "TRUE", "1")
            _settings.mitigate_enabled = on
            if networked:
                node.send_event(b"MITIGATE", {"enabled": on})
                return True, f"MITIGATE {'ON' if on else 'OFF'} sent"
            return True, f"MITIGATE {'ON' if on else 'OFF'}"
        return False, "MITIGATE [ON/OFF/STATUS]"

    def fingerprintcmd(flag=None):
        """FINGERPRINT [ON/OFF]: device-side SDC state fingerprint — a
        cheap int32 bit-pattern fold over the guarded state leaves,
        threaded through the chunk-scan carry (jit-static: OFF traces
        identical HLO, ON adds no host syncs or collectives) and
        chained per piece.  The completion word ships to the server
        for redundant-execution comparison (SDC defense).  Bare call
        reads back state + the running chain."""
        if flag is None:
            if not sim.cfg.fingerprint:
                return True, "FINGERPRINT OFF"
            fp = sim.fp_summary()
            if fp is None:
                return True, "FINGERPRINT ON (no chunk drained yet)"
            return True, (f"FINGERPRINT ON: chain {fp['fp']} over "
                          f"{fp['chunks']} chunk(s) / {fp['steps']} "
                          f"step(s)")
        on = str(flag).upper() in ("ON", "TRUE", "1", "YES")
        changed = sim.set_fingerprint(on)
        state = "ON" if on else "OFF"
        return True, (f"FINGERPRINT {state}"
                      + ("" if changed else " (unchanged)")
                      + (": next dispatch compiles the fingerprint-"
                         "carrying chunk program"
                         if changed and on else ""))

    def sdccmd(arg=None, val=None):
        """SDC [ON/OFF/STATUS | AUDIT rate]: the server's silent-data-
        corruption defense — fingerprints of redundant executions
        (hedge duplicates, sampled shadow audits) compared on
        completion; mismatches journal audit-only sdc_suspect records,
        a 2-of-3 re-execution vote names the deviant worker and the
        mitigation engine quarantines it.  Bare SDC / SDC STATUS reads
        the defense state back HEALTH-style; on a detached sim it
        reports the local settings defaults a future server would
        inherit."""
        from .. import settings as _settings
        node = getattr(sim, "node", None)
        networked = node is not None \
            and getattr(node, "event_io", None) is not None
        a = str(arg).upper() if arg is not None else ""
        if a in ("", "STATUS"):
            if networked:
                node.send_event(b"SDC", None)  # empty route -> server
                return True, "SDC status requested from the server"
            return True, (
                f"detached sim: SDC "
                f"{'ON' if getattr(_settings, 'sdc_enabled', False) else 'OFF'}"
                f", audit rate "
                f"{getattr(_settings, 'sdc_audit_rate', 0.0):g} "
                "(settings.sdc_enabled / settings.sdc_audit_rate; a "
                "server inherits these)")
        if a in ("ON", "OFF", "TRUE", "FALSE", "1", "0"):
            on = a in ("ON", "TRUE", "1")
            _settings.sdc_enabled = on
            if networked:
                node.send_event(b"SDC", {"enabled": on})
                return True, f"SDC {'ON' if on else 'OFF'} sent"
            return True, f"SDC {'ON' if on else 'OFF'}"
        if a == "AUDIT":
            try:
                rate = max(0.0, float(val))
            except (TypeError, ValueError):
                return False, "SDC AUDIT rate: need a fraction 0..1"
            _settings.sdc_audit_rate = rate
            if networked:
                node.send_event(b"SDC", {"audit_rate": rate})
                return True, f"SDC audit rate {rate:g} sent"
            return True, f"SDC audit rate {rate:g}"
        return False, "SDC [ON/OFF/STATUS | AUDIT rate]"

    def hacmd(arg=None):
        """HA [STATUS]: broker high availability — a warm-standby
        server tails the live journal and takes over leadership (lease
        epoch bump + journal-fenced writes from the deposed leader)
        when the leader's lease goes stale.  Bare HA / HA STATUS reads
        the lease state back HEALTH-style: role, epoch, lease age,
        takeover/adoption counters; on a detached sim it reports the
        local settings a future server would inherit."""
        from .. import settings as _settings
        node = getattr(sim, "node", None)
        networked = node is not None \
            and getattr(node, "event_io", None) is not None
        a = str(arg).upper() if arg is not None else ""
        if a in ("", "STATUS"):
            if networked:
                node.send_event(b"HA", None)  # empty route -> server
                return True, "HA status requested from the server"
            return True, (
                f"detached sim: HA standby "
                f"{'ON' if getattr(_settings, 'ha_standby', False) else 'OFF'}"
                f", lease ttl "
                f"{getattr(_settings, 'ha_lease_ttl', 10.0):g} s "
                "(settings.ha_standby / settings.ha_lease_ttl; a "
                "server inherits these)")
        return False, "HA [STATUS]"

    def snapshot(sub, fname=None):
        """SNAPSHOT SAVE/LOAD fname: binary pytree state checkpoint
        (device-state snapshot the reference lacks, SURVEY 5.4)."""
        from ..simulation import snapshot as snap
        s = str(sub).upper()
        if fname is None:
            return False, "SNAPSHOT SAVE/LOAD filename"
        if not fname.lower().endswith(".snap"):
            fname += ".snap"
        if s == "SAVE":
            # disk-full / bad path degrades to a command error instead
            # of raising out of the stack, symmetric with LOAD; the
            # atomic writer guarantees any previous good file survives
            try:
                out = snap.save(sim, fname)
            except OSError as e:
                return False, f"SNAPSHOT SAVE {fname}: {e}"
            return True, f"Snapshot written to {out}"
        if s == "LOAD":
            import os as _os
            if not _os.path.isfile(fname):
                return False, f"{fname}: not found"
            return snap.load(sim, fname)
        return False, "SNAPSHOT SAVE/LOAD filename"

    def ssdcmd(*args):
        """SSD ALL/CONFLICTS/OFF or SSD acid0,acid1,...: select which
        aircraft draw their solution-space disc on the radar (reference
        stack.py:697-700 -> scr.feature('SSD', args) -> the
        radarwidget.py:290-302 SSD view; here ui/radar.py renders the
        same velocity-obstacle annulus into the SVG/web frame).  A
        single named aircraft additionally gets a textual occupancy
        report, so the view also works headless."""
        if not args:
            return True, "SSD ALL/CONFLICTS/OFF or SSD acid0,acid1,..."
        words = [str(a).upper() for a in args]
        # validate callsigns before toggling (keywords pass through);
        # a callsign already holding a disc may always be toggled OFF,
        # even after the aircraft was deleted — otherwise only SSD OFF
        # could ever clear its stale disc.
        acids = [w for w in words
                 if w not in ("ALL", "CONFLICTS", "OFF")]
        selected = getattr(sim.scr, "ssd_ownship", set())
        for a in acids:
            i = traf.id2idx(a)
            if (not isinstance(i, int) or i < 0) and a not in selected:
                return False, f"{a}: aircraft not found"
        sim.scr.show_ssd(*words)
        if len(acids) == 1 and len(words) == 1:
            a = acids[0]
            if a not in getattr(sim.scr, "ssd_ownship", set()):
                # toggle DEselected the disc: no occupancy report (it
                # would imply the disc is still active)
                return True, f"{a}: SSD disc deselected"
            from ..ui import radar
            ac = st().ac
            c = sim.cfg.asas
            i = traf.id2idx(a)
            conf = radar.ssd_disc(
                i, np.asarray(ac.lat), np.asarray(ac.lon),
                np.asarray(ac.gseast), np.asarray(ac.gsnorth),
                np.asarray(ac.active), c.vmin, c.vmax, c.rpz_m,
                c.dtlookahead)
        else:
            return True, f"SSD: {' '.join(words)}"
        occ = 100.0 * float(np.mean(conf))
        inconf = bool(np.asarray(st().asas.inconf)[i])
        return True, (f"{acname(i)}: SSD disc selected; "
                      f"{'IN CONFLICT' if inconf else 'clear'}; "
                      f"{occ:.0f}% of the velocity envelope blocked")

    def doccmd(cmd=None):
        """DOC [command]: extended help (scr.show_cmd_doc)."""
        return helpcmd(cmd)

    def makedoc():
        """MAKEDOC: write command reference markdown (stack.py makedoc)."""
        import os as _os
        from .. import settings as _settings
        _os.makedirs(_settings.log_path, exist_ok=True)
        fname = _os.path.join(_settings.log_path, "commands.md")
        with open(fname, "w") as f:
            f.write("# Stack command reference\n\n")
            for name in sorted(stack.cmddict):
                usage, _, _, helptxt = stack.cmddict[name]
                f.write(f"## {name}\n\n    {usage}\n\n{helptxt}\n\n")
        return True, f"Command reference written to {fname}"

    def helpcmd(cmd=None):
        if cmd is None:
            names = ", ".join(sorted(stack.cmddict.keys()))
            return True, f"Commands: {names}"
        c = stack.synonyms.get(cmd.upper(), cmd.upper())
        if c in stack.cmddict:
            e = stack.cmddict[c]
            return True, f"{e[0]}\n{e[3]}"
        return False, f"Unknown command {cmd}"

    # ----------------------------------------------------------- dictionary
    stack.append_commands({
        "ADDWPT": ["ADDWPT acid,(wpname/FLYBY/FLYOVER/lat,lon),"
                   "[alt,spd,afterwp]",
                   "acid,wppos,[alt,spd,wpinroute]", addwpt,
                   "Add a waypoint to the route of an aircraft"],
        "ALT": ["ALT acid,alt,[vspd]", "acid,alt,[vspd]", selalt,
                "Altitude select command"],
        "ASAS": ["ASAS [ON/OFF]", "[onoff]", asas_onoff,
                 "Airborne separation assurance on/off"],
        "BANK": ["BANK acid,[angle deg]", "acid,[float]", bank,
                 "Set bank angle limit"],
        "BENCHMARK": ["BENCHMARK [scenfile,time]", "[word,time]", benchmark,
                      "Load a scenario and time a fast-forward run"],
        "CALC": ["CALC expression", "[string,...]", calc,
                 "Evaluate a simple expression"],
        "CRE": ["CRE acid,type,latlon,hdg,alt,spd",
                "txt,txt,latlon,[hdg,alt,spd]", cre, "Create an aircraft"],
        "CRECONFS": ["CRECONFS acid,type,targetacid,dpsi,cpa,tlosh,[dH,tlosv,spd]",
                     "txt,txt,acid,float,float,time,[alt,time,spd]", creconfs,
                     "Create an aircraft in conflict with target"],
        "ATALT": ["acid ATALT alt cmd", "acid,alt,string", atalt,
                  "When a/c passes given altitude, execute a command"],
        "ATSPD": ["acid ATSPD spd cmd", "acid,spd,string", atspd,
                  "When a/c reaches given speed, execute a command"],
        "BOX": ["BOX name,lat,lon,lat,lon,[top,bottom]",
                "txt,latlon,latlon,[alt,alt]", boxcmd,
                "Define a box-shaped area"],
        "CIRCLE": ["CIRCLE name,lat,lon,radius,[top,bottom]",
                   "txt,latlon,float,[alt,alt]", circlecmd,
                   "Define a circle-shaped area"],
        "POLY": ["POLY name,lat,lon,lat,lon, ...", "txt,latlon,...",
                 polycmd, "Define a polygon-shaped area"],
        "POLYALT": ["POLYALT name,top,bottom,lat,lon, ...",
                    "txt,alt,alt,latlon,...", polyaltcmd,
                    "Define a polygon-shaped area in 3D"],
        "LINE": ["LINE name,lat,lon,lat,lon", "txt,latlon,latlon,...",
                 linecmd, "Draw a (poly)line between points"],
        "TRAIL": ["TRAIL ON/OFF,[dt] OR TRAIL acid color",
                  "[txt],[txt]", trailcmd, "Toggle aircraft trails on/off"],
        "DEL": ["DEL acid/ALL/WIND/shape", "txt", delcmd,
                "Delete an aircraft, wind field or area"],
        "DELALL": ["DELALL", "", delall, "Delete all aircraft"],
        "DELAY": ["DELAY dt,COMMAND+ARGS", "time,string,...", delay,
                  "Schedule a command in dt seconds"],
        "DEFWPT": ["DEFWPT wpname,lat,lon,[type]", "txt,latlon,[txt]",
                   defwpt, "Define a user waypoint"],
        "WPTINFO": ["WPTINFO wpname", "txt", navdbinfo,
                    "Look up a waypoint/airport in the navdb"],
        "DELWPT": ["DELWPT acid,wpname", "acid,wpinroute", delwpt,
                   "Delete a waypoint from the route"],
        "DEST": ["DEST acid,latlon", "acid,[latlon]",
                 lambda idx, pos=None: dest_orig("DEST", idx, pos),
                 "Set destination"],
        "DIRECT": ["DIRECT acid,wpname", "acid,wpinroute", direct,
                   "Go direct to a waypoint in the route"],
        "DIST": ["DIST lat1,lon1,lat2,lon2", "latlon,latlon", dist,
                 "Distance between positions"],
        "DT": ["DT [dt]", "[float]", setdt, "Set simulation timestep"],
        "DTLOOK": ["DTLOOK [time]", "[time]", dtlook,
                   "Conflict detection lookahead time"],
        "DTMULT": ["DTMULT [mult]", "[float]", setdtmult,
                   "Sim speed multiplier"],
        "DTNOLOOK": ["DTNOLOOK [time]", "[time]", dtnolook,
                     "Conflict detection interval"],
        "ECHO": ["ECHO txt", "[string,...]", echo, "Echo text"],
        "FF": ["FF [time]", "[time]", ff, "Fast-forward [for time]"],
        "HDG": ["HDG acid,hdg", "acid,hdg", selhdg, "Heading select command"],
        "HELP": ["HELP [cmd]", "[txt]", helpcmd, "Command help"],
        "HOLD": ["HOLD", "", hold, "Pause the simulation"],
        "IC": ["IC [scenfile]", "[word]", ic, "Load/reload a scenario"],
        "LISTRTE": ["LISTRTE acid", "acid", listrte, "Show route"],
        "LNAV": ["LNAV acid,[ON/OFF]", "acid,[onoff]", setlnav,
                 "Lateral navigation on/off"],
        "MCRE": ["MCRE n,[type,alt,spd,dest]", "int,[txt,alt,spd,txt]", mcre,
                 "Create n random aircraft"],
        "MOVE": ["MOVE acid,latlon,[alt,hdg,spd,vspd]",
                 "acid,latlon,[alt,hdg,spd,vspd]", move,
                 "Instantly move an aircraft"],
        "NOISE": ["NOISE [ON/OFF]", "[onoff]", noise,
                  "Turbulence/ADS-B noise on/off"],
        "NORESO": ["NORESO [acid]", "[txt]", noreso,
                   "Toggle no-avoidance for an aircraft"],
        "OP": ["OP", "", op, "Start/resume the simulation"],
        "OPT": ["OPT [tend,iters,lr,restarts]",
                "[float,int,float,int]", optcmd,
                "Gradient-based trajectory optimization: descend on "
                "per-aircraft waypoint/time offsets to zero LoS "
                "(bluesky_tpu/diff/; result journaled as an OPT BATCH "
                "piece record)"],
        "GRAD": ["GRAD [tend]", "[float]", gradcmd,
                 "One checked value_and_grad of the soft-LoS+fuel "
                 "objective (reports objective, |grad|, guard word)"],
        "ORIG": ["ORIG acid,latlon", "acid,[latlon]",
                 lambda idx, pos=None: dest_orig("ORIG", idx, pos),
                 "Set origin"],
        "PCALL": ["PCALL scenfile,[REL,args]", "word,[string,...]", pcall,
                  "Merge a scenario file [with %0-%n substitution]"],
        "POS": ["POS acid", "acid", pos, "Aircraft info"],
        "QUIT": ["QUIT", "", quitsim, "Stop the simulation"],
        "RESET": ["RESET", "", reset, "Reset the simulation"],
        "RESO": ["RESO [method]", "[txt]", reso,
                 "Conflict resolution method (MVP/OFF)"],
        "RESOOFF": ["RESOOFF [acid]", "[txt]", resooff,
                    "Toggle resolution off for an aircraft"],
        "RMETHH": ["RMETHH [SPD/HDG/BOTH/OFF]", "[txt]", rmethh,
                   "Horizontal resolution method limiting"],
        "RMETHV": ["RMETHV [V/S / OFF]", "[txt]", rmethv,
                   "Vertical resolution method limiting"],
        "RSZONER": ["RSZONER [radius nm]", "[float]", rszoner,
                    "Resolution zone radius"],
        "RSZONEDH": ["RSZONEDH [height ft]", "[float]", rszonedh,
                     "Resolution zone half-height"],
        "SAVEIC": ["SAVEIC filename", "[word]", saveic,
                   "Record scenario from current state"],
        "SCEN": ["SCEN name", "word", scen, "Name the current scenario"],
        "SCHEDULE": ["SCHEDULE time,COMMAND+ARGS", "time,string,...", schedule,
                     "Schedule a command at a sim time"],
        "SEED": ["SEED value", "int", seed, "Set random seed"],
        "SPD": ["SPD acid,spd", "acid,spd", selspd, "Speed select command"],
        "SSD": ["SSD ALL/CONFLICTS/OFF or SSD acid0,acid1,...",
                "[txt,...]", ssdcmd,
                "Show solution space diagram"],
        "SYN": ["SYN subcmd,args", "[txt,string,...]", syn,
                "Synthetic conflict geometries (SUPER/WALL/MATRIX/...)"],
        "VNAV": ["VNAV acid,[ON/OFF]", "acid,[onoff]", setvnav,
                 "Vertical navigation on/off"],
        "VS": ["VS acid,vspd", "acid,vspd", selvspd,
               "Vertical speed select command"],
        "WIND": ["WIND lat,lon,dir,spd[,alt,dir,spd...]",
                 "latlon,float,float,[float,...]", wind,
                 "Define a wind vector/profile at a position"],
        "ZONEDH": ["ZONEDH [height ft]", "[float]", zonedh,
                   "Protected zone half-height"],
        "ZONER": ["ZONER [radius nm]", "[float]", zoner,
                  "Protected zone radius"],
        "CHUNKSTEPS": ["CHUNKSTEPS [n | PIPELINE ON/OFF]", "[txt,txt]",
                       chunksteps,
                       "Interactive device-chunk length / async-pipeline "
                       "toggle (readback without args)"],
        "CONFINFO": ["CONFINFO", "", confinfo, "Current conflict counts"],
        "PLUGINS": ["PLUGINS LIST or PLUGINS LOAD/REMOVE plugin",
                    "[txt,txt]",
                    lambda cmd=None, name=None: sim.plugins.manage(
                        cmd or "LIST", name or ""),
                    "List, load or remove plugins"],
        "ADDNODES": ["ADDNODES number", "int", addnodes,
                     "Add a simulation instance/node"],
        "AFTER": ["acid AFTER afterwp ADDWPT (wpname/lat,lon),[alt,spd]",
                  "acid,wpinroute,txt,wppos,[alt,spd]", after,
                  "After waypoint, add a waypoint to route of aircraft"],
        "AIRWAY": ["AIRWAY wp/airway", "txt", airway,
                   "Get info on airway or connections of a waypoint"],
        "ASASV": ["ASASV MAX/MIN SPD (TAS in kts)", "[txt,float]", asasv,
                  "Airborne Separation Assurance System Speed limits"],
        "AT": ["acid AT wpname [DEL] SPD/ALT [spd/alt]",
               "acid,wpinroute,[txt,txt]", atwpt,
               "Edit, delete or show spd/alt constraints at a waypoint"],
        "BATCH": ["BATCH filename", "string", batchcmd,
                  "Start a scenario file as batch simulation"],
        "BEFORE": ["acid BEFORE beforewp ADDWPT (wpname/lat,lon),[alt,spd]",
                   "acid,wpinroute,txt,wppos,[alt,spd]", before,
                   "Before waypoint, add a waypoint to route of aircraft"],
        "CD": ["CD [path]", "[txt]", cdcmd,
               "Change to a different scenario folder"],
        "CDMETHOD": ["CDMETHOD [method]", "[txt]", cdmethod,
                     "Set conflict detection method"],
        "DATE": ["DATE [day,month,year,HH:MM:SS.hh]", "[int,int,int,txt]",
                 datecmd, "Set simulation date"],
        "DELRTE": ["DELRTE acid", "acid", delrte,
                   "Delete the complete route/dest/orig of an aircraft"],
        "DOC": ["DOC [command]", "[txt]", doccmd,
                "Show extended help for a command"],
        "DUMPRTE": ["DUMPRTE acid", "acid", dumprte,
                    "Write route to output/routelog.txt"],
        "ENG": ["ENG acid,[engine_id]", "acid,[txt]", engcmd,
                "Specify a different engine type"],
        "FILTERALT": ["FILTERALT ON/OFF,[bottom,top]", "onoff,[alt,alt]",
                      filteralt,
                      "Display aircraft only in an altitude range"],
        "FIXDT": ["FIXDT ON/OFF [tend]", "onoff,[time]", fixdt,
                  "Fix the time step"],
        "GETWIND": ["GETWIND lat,lon,[alt]", "latlon,[alt]", getwind,
                    "Get wind at a specified position"],
        "INSEDIT": ["INSEDIT txt", "string", insedit,
                    "Insert text on the edit line in command window"],
        "LISTAC": ["LISTAC", "", listac,
                   "List all aircraft identifiers in the simulation"],
        "MAKEDOC": ["MAKEDOC", "", makedoc,
                    "Write the stack command reference to output/"],
        "ND": ["ND acid", "[txt]", nd,
               "Show navigation display with CDTI"],
        "NOM": ["NOM acid", "acid", nom,
                "Set nominal acceleration for this aircraft"],
        "PAN": ["PAN latlon/acid/airport/waypoint/LEFT/RIGHT/UP/DOWN",
                "txt,[txt]", pan,
                "Pan screen (move view) to a position or aircraft"],
        "PRIORULES": ["PRIORULES [ON/OFF PRIOCODE]", "[onoff,txt]",
                      priorules,
                      "Define priority rules (right of way) for "
                      "conflict resolution"],
        "RFACH": ["RFACH [factor]", "[float]", rfach,
                  "Set resolution factor horizontal (margin)"],
        "RFACV": ["RFACV [factor]", "[float]", rfacv,
                  "Set resolution factor vertical (margin)"],
        "SWRAD": ["SWRAD GEO/GRID/APT/VOR/WPT/LABEL/TRAIL/POLY [value]",
                  "txt,[float]", swrad,
                  "Switch on/off elements of the radar view"],
        "SYMBOL": ["SYMBOL", "", symbol, "Toggle aircraft symbol"],
        "TIME": ["TIME RUN(default)/HH:MM:SS.hh/REAL/UTC", "[txt]",
                 timecmd, "Set simulated clock time"],
        "TMX": ["TMX", "", tmx, "Stub for not-implemented TMX commands"],
        "PLOT": ["PLOT [x],y,[dt],[color]", "[txt,txt,float,txt]",
                 sim.plotter.plot,
                 "Create a plot of variables x versus y"],
        "METRICS": ["METRICS OFF/1/2 [dt] | DUMP", "[txt,float]",
                    metricscmd,
                    "Sector metrics: 1=CoCa cell occupancy, "
                    "2=HB conflict-geometry complexity; DUMP reads "
                    "the telemetry registry (sim + server + fleet)"],
        "PROFILE": ["PROFILE START [dir]/STOP/KERNELS [nsteps]/DEEP/"
                    "DEVICE [n] [dir]/TRACE [ON/OFF/DUMP]",
                    "[txt,word,word]", profile,
                    "JAX trace capture, per-kernel timings, device-"
                    "trace windows and the flight recorder"],
        "TRACE": ["TRACE [ON/OFF/DUMP]", "[txt]", tracecmd,
                  "Flight recorder: bounded span ring dumped as "
                  "Perfetto trace JSON (readback bare)"],
        "FAULT": ["FAULT NAN/INF [acid] | BITFLIP [STATE|PAYLOAD] | "
                  "GUARD ../RING .. | DROP/DUP/"
                  "DELAY p | NETOFF | STALL s | STRAGGLE f/STALL/OFF | "
                  "KILL | KILLSERVER [s] | PREEMPT [s] | MESHKILL [g] "
                  "| PARTITION [OFF] "
                  "| LOADSPIKE n [rate] | SNAPTRUNC f | LIST",
                  "[word,...]", faultcmd,
                  "Fault-injection harness (chaos testing)"],
        "HEALTH": ["HEALTH", "", healthcmd,
                   "Serving-fabric health: queue depth, worker "
                   "progress, hedges, drops"],
        "SHARD": ["SHARD [OFF | REPLICATE [n] | SPATIAL [n [halo]] | "
                  "TILE RxC]",
                  "[txt,txt,txt]", shardcmd,
                  "Multi-chip mode: replicated columns, spatial "
                  "latitude stripes, or 2-D lat x lon tiles with "
                  "corner-halo exchange (readback bare)"],
        "SCANSTATS": ["SCANSTATS [ON/OFF]", "[txt]", scanstatscmd,
                      "In-scan telemetry: per-step device-side stats "
                      "folded through the chunk scan (readback bare)"],
        "SORTREFRESH": ["SORTREFRESH [ON/OFF]", "[txt]", sortrefreshcmd,
                        "In-scan sort refresh: stripe re-sort folded "
                        "into the compiled chunk (readback bare)"],
        "SNAPSHOT": ["SNAPSHOT SAVE/LOAD fname", "txt,[word]", snapshot,
                     "Save/restore a binary state snapshot"],
        "MITIGATE": ["MITIGATE [ON/OFF/STATUS]", "[txt]", mitigatecmd,
                     "Self-healing serving: signal->actuator policy "
                     "engine behind rate limits, backoff and a budget "
                     "(readback bare)"],
        "FINGERPRINT": ["FINGERPRINT [ON/OFF]", "[txt]", fingerprintcmd,
                        "Device-side SDC state fingerprint folded "
                        "through the compiled chunk scan "
                        "(readback bare)"],
        "SDC": ["SDC [ON/OFF/STATUS | AUDIT rate]", "[txt,txt]", sdccmd,
                "Silent-data-corruption defense: redundant-execution "
                "fingerprint voting + worker quarantine "
                "(readback bare)"],
        "HA": ["HA [STATUS]", "[txt]", hacmd,
               "Broker high availability: warm-standby lease state, "
               "epoch, takeover/adoption counters (readback bare)"],
        "WORLDS": ["WORLDS [ON/OFF | MAX n]", "[txt,txt]", worldscmd,
                   "Multi-world BATCH packing: world-batch size + "
                   "per-bucket packing on/off (readback bare)"],
        "SCREENSHOT": ["SCREENSHOT [fname.svg]", "[word]", screenshot,
                       "Render the radar picture to an SVG file"],
        "ZOOM": ["ZOOM IN/OUT or factor", "txt", zoom,
                 "Zoom display in/out"],
    })

    # Synonyms (reference stack.py:44-115 subset)
    stack.append_synonyms({
        "CREATE": "CRE", "DELETE": "DEL", "DIRECTTO": "DIRECT",
        "DIRTO": "DIRECT", "DISP": "SWRAD", "END": "QUIT", "EXIT": "QUIT",
        "FWD": "FF", "PAUSE": "HOLD", "STOP": "QUIT", "RUN": "OP",
        "RESUME": "OP", "START": "OP", "TURN": "HDG", "?": "HELP",
        "CONTINUE": "OP", "SAVE": "SAVEIC", "CLOSE": "QUIT",
        "DELROUTE": "DELRTE", "LOAD": "IC", "OPEN": "IC",
        "TRAILS": "TRAIL", "POLYGON": "POLY", "POLYLINE": "LINE",
        "POLYLINES": "LINE", "LINES": "LINE", "PLUGIN": "PLUGINS",
        "PLUG-INS": "PLUGINS", "PLUG-IN": "PLUGINS",
        # Full reference synonym table (stack.py:44-115)
        "AWY": "POS", "AIRPORT": "POS", "AIRWAYS": "AIRWAY",
        "CALL": "PCALL", "CHDIR": "CD", "DEBUG": "CALC",
        "DELWP": "DELWPT", "HEADING": "HDG", "HMETH": "RMETHH",
        "HRESOM": "RMETHH", "HRESOMETH": "RMETHH", "PRINT": "ECHO",
        "Q": "QUIT", "RTF": "DTMULT", "RUNWAYS": "POS",
        "RESOFACH": "RFACH", "RESOFACV": "RFACV", "SPEED": "SPD",
        "VMETH": "RMETHV", "VRESOM": "RMETHV", "VRESOMETH": "RMETHV",
        # Unimplemented TMX commands route to the TMX stub
        "BGPASAS": "TMX", "DFFLEVEL": "TMX", "FFLEVEL": "TMX",
        "FILTCONF": "TMX", "FILTTRED": "TMX", "FILTTAMB": "TMX",
        "GRAB": "TMX", "HDGREF": "TMX", "MOVIE": "TMX",
        "NAVDB": "TMX", "PREDASAS": "TMX", "RENAME": "TMX",
        "RETYPE": "TMX", "SWNLRPASAS": "TMX", "TRAFRECDT": "TMX",
        "TRAFLOGDT": "TMX", "TREACT": "TMX", "WINDGRID": "TMX",
        "METRIC": "METRICS",
    })
