"""The text command stack — the universal user/API surface."""
